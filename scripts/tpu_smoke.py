"""On-chip smoke sweep of XLA-level surfaces that have never touched the
real TPU (VERDICT-r4 item 2; lesson source: BENCH_r02's interpret-mode
blind spot — CPU-green is not TPU-green).

Runs fwd (+bwd where differentiable) ON THE TPU for:
  weight_only_linear int8/int4, varlen flash attention, fused
  MHA/FFN/EcMoE, grid_sample, sparse.nn conv, ring attention (shard_map
  over however many devices exist), blockwise fused CE — and then
  pre-tunes the flash block sizes for the bench shape, committing the
  winners to the repo autotune cache (autotune_cache.json) that bench.py
  reads in its never-measure "cached" mode.

Emits TPU_SMOKE.json: {"skipped": reason} when the tunnel is down
(probed first — a dead relay must not hang this script), else
{"results": {case: "ok" | "FAIL: ..."}, ...}. Exit code 0 when skipped
or all green, 1 when any case failed.
"""
import json
import os
import socket
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
OUT = os.path.join(REPO, "TPU_SMOKE.json")

from bench import _RELAY_PORTS  # noqa: E402  single source for the port set
DEADLINE_S = float(os.environ.get("SMOKE_DEADLINE_S", "1500"))
_T0 = time.monotonic()


def _watchdog():
    while True:
        time.sleep(2)
        if time.monotonic() - _T0 > DEADLINE_S:
            _emit({"skipped": None, "error":
                   f"smoke sweep exceeded {DEADLINE_S}s; killed by its "
                   "own watchdog"})
            os._exit(2)


def _emit(payload):
    payload["elapsed_s"] = round(time.monotonic() - _T0, 1)
    with open(OUT, "w") as f:
        json.dump(payload, f, indent=1)
    print(json.dumps(payload))


def _relay_alive():
    for port in _RELAY_PORTS:
        try:
            socket.create_connection(("127.0.0.1", port), timeout=2).close()
            return True
        except OSError:
            continue
    return False


def main():
    if os.environ.get("SMOKE_ALLOW_CPU") != "1" and \
            os.environ.get("PALLAS_AXON_POOL_IPS") and not _relay_alive():
        _emit({"skipped": "tpu tunnel relay dead (no relay port open)"})
        return 0
    threading.Thread(target=_watchdog, daemon=True).start()

    import jax
    import jax.numpy as jnp
    import numpy as np

    devs = jax.devices()
    on_tpu = devs[0].platform in ("tpu", "axon") or \
        "TPU" in (devs[0].device_kind or "")
    if not on_tpu and os.environ.get("SMOKE_ALLOW_CPU") != "1":
        _emit({"skipped": f"first device is {devs[0].platform}, not TPU"})
        return 0
    os.environ.setdefault("TPU_ACCELERATOR_TYPE", "v5litepod-1")

    import paddle_tpu as paddle

    results = {}

    def case(name):
        def deco(fn):
            t0 = time.monotonic()
            try:
                fn()
                results[name] = "ok"
            except Exception as e:
                results[name] = f"FAIL: {type(e).__name__}: {e}"[:400]
            print(f"[{time.monotonic() - t0:6.1f}s] {name}: "
                  f"{results[name][:120]}", file=sys.stderr)
            return fn
        return deco

    rng = np.random.default_rng(0)

    @case("weight_only_linear_int8")
    def _():
        from paddle_tpu.nn.quant import weight_only_linear, weight_quantize
        x = paddle.to_tensor(rng.normal(size=(8, 256)).astype("float32"))
        w = paddle.to_tensor(rng.normal(size=(256, 128)).astype("float32"))
        qw, scale = weight_quantize(w, algo="weight_only_int8")
        out = weight_only_linear(x, qw, weight_scale=scale,
                                 weight_dtype="int8")
        float(out.sum().numpy())

    @case("weight_only_linear_int4")
    def _():
        from paddle_tpu.nn.quant import weight_only_linear, weight_quantize
        x = paddle.to_tensor(rng.normal(size=(8, 256)).astype("float32"))
        w = paddle.to_tensor(rng.normal(size=(256, 128)).astype("float32"))
        qw, scale = weight_quantize(w, algo="weight_only_int4")
        out = weight_only_linear(x, qw, weight_scale=scale,
                                 weight_dtype="int4")
        float(out.sum().numpy())

    @case("varlen_flash_attention")
    def _():
        import paddle_tpu.nn.functional as F
        q = paddle.to_tensor(
            rng.normal(size=(6, 4, 64)).astype("float32"),
            stop_gradient=False)
        cu = paddle.to_tensor(np.array([0, 2, 6], "int32"))
        out, _sm = F.flash_attn_unpadded(q, q, q, cu, cu, 4, 4)
        out.sum().backward()
        float(q.grad.sum().numpy())

    @case("fused_mha_ffn_ecmoe")
    def _():
        import paddle_tpu.incubate.nn.functional as IF
        d, nh = 64, 4
        x = paddle.to_tensor(rng.normal(size=(2, 8, d)).astype("float32"),
                             stop_gradient=False)
        qkvw = paddle.to_tensor(
            rng.normal(size=(3, nh, d // nh, d)).astype("float32") * 0.05)
        lw = paddle.to_tensor(rng.normal(size=(d, d)).astype("float32")
                              * 0.05)
        out = IF.fused_multi_head_attention(x, qkvw, lw, num_heads=nh)
        l1 = paddle.to_tensor(rng.normal(size=(d, 128)).astype("float32")
                              * 0.05)
        l2 = paddle.to_tensor(rng.normal(size=(128, d)).astype("float32")
                              * 0.05)
        out = IF.fused_feedforward(out, l1, l2)
        ne, dh = 4, 128
        gw = paddle.to_tensor(rng.normal(size=(d, ne)).astype("float32"))
        ew1 = paddle.to_tensor(
            rng.normal(size=(ne, d, dh)).astype("float32") * 0.05)
        eb1 = paddle.to_tensor(np.zeros((ne, dh), "float32"))
        ew2 = paddle.to_tensor(
            rng.normal(size=(ne, dh, d)).astype("float32") * 0.05)
        eb2 = paddle.to_tensor(np.zeros((ne, d), "float32"))
        out = IF.fused_ec_moe(out, gw, ew1, eb1, ew2, eb2)
        out.sum().backward()
        float(x.grad.sum().numpy())

    @case("grid_sample_grad")
    def _():
        import paddle_tpu.nn.functional as F
        x = paddle.to_tensor(
            rng.normal(size=(1, 2, 8, 8)).astype("float32"),
            stop_gradient=False)
        grid = paddle.to_tensor(
            rng.uniform(-1, 1, size=(1, 4, 4, 2)).astype("float32"))
        out = F.grid_sample(x, grid)
        out.sum().backward()
        float(x.grad.sum().numpy())

    @case("sparse_conv")
    def _():
        import paddle_tpu.sparse as sparse
        dense = np.zeros((1, 8, 8, 3), "float32")
        dense[0, 2, 3, :] = 1.0
        st = sparse.sparse_coo_tensor_from_dense(paddle.to_tensor(dense))
        conv = sparse.nn.Conv2D(3, 4, 3, padding=1)
        out = conv(st)
        float(out.to_dense().sum().numpy())

    @case("ring_attention_shard_map")
    def _():
        from jax.sharding import Mesh

        from paddle_tpu.kernels import ring_attention
        n = len(jax.devices())
        mesh = Mesh(np.array(jax.devices()).reshape(n), ("sp",))
        q = jnp.asarray(rng.normal(size=(2, 16 * n, 2, 32)), jnp.float32)
        out = ring_attention(q, q, q, mesh, causal=True)
        float(jnp.sum(out).astype(jnp.float32))

    @case("fused_cross_entropy_grad")
    def _():
        from paddle_tpu.kernels import fused_cross_entropy
        x = jnp.asarray(rng.normal(size=(4, 16, 64)), jnp.bfloat16)
        head = jnp.asarray(rng.normal(size=(1000, 64)) * 0.1, jnp.bfloat16)
        labels = jnp.asarray(rng.integers(0, 1000, (4, 16)), jnp.int32)
        loss, grads = jax.value_and_grad(
            lambda x, h: fused_cross_entropy(x, h, labels,
                                             vocab_chunk=256),
            argnums=(0, 1))(x, head)
        float(loss)

    @case("moe_capacity_dispatch_train")
    def _():
        # the bench MoE rung's dispatch mode, at toy shapes: capacity
        # gather + expert matmuls + drop path must compile AND grad
        from paddle_tpu.models import llama as L
        from paddle_tpu.models import moe as M
        cfg = M.moe_tiny(dispatch_mode="capacity", dtype=jnp.bfloat16,
                         capacity_factor=1.0)
        params = M.init_params(cfg, jax.random.PRNGKey(0))
        opt = L.adamw_init(params)
        # guard=False: this stage measures MoE dispatch, not the
        # sentinel gate (nan_skip_resume covers the guarded step) —
        # and must keep its 3-in/3-out shape under chaos-run flags
        step = M.make_train_step(cfg, lr=1e-3, guard=False)
        ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 17)),
                          jnp.int32)
        _, _, loss = step(params, opt, ids)
        assert np.isfinite(float(loss))

    @case("kv_cache_decode")
    def _():
        # the bench decode rung's path at toy shapes: prefill + jitted
        # generate scan over decode steps
        from paddle_tpu.models import llama as L
        cfg = L.llama_tiny(num_hidden_layers=2, dtype=jnp.bfloat16)
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 8)),
                          jnp.int32)
        toks = jax.jit(lambda p, i: L.generate(
            p, i, cfg, max_new_tokens=4))(params, ids)
        t = np.asarray(toks)
        assert t.shape == (2, 4) and (t >= 0).all()

    @case("paged_decode")
    def _():
        # the serving engine's full lifecycle on the real chip: prefill,
        # a request JOINING mid-stream (continuous batching), EOS/max-len
        # retirement, and the page pool draining back to empty
        from paddle_tpu.inference import Request, ServingEngine
        from paddle_tpu.models import llama as L
        cfg = L.llama_tiny(num_hidden_layers=2, dtype=jnp.bfloat16)
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        # page_size 16 = the bf16 sublane tile, so on-chip this drives
        # the pallas kernel through the engine (8 would jnp-fallback)
        eng = ServingEngine(L, params, cfg, num_slots=2, max_len=32,
                            page_size=16, decode_chunk=2)
        eng.submit(Request(rid=0, prompt=rng.integers(
            0, cfg.vocab_size, (6,)).astype(np.int32), max_new_tokens=8))
        eng.step()                      # rid 0 prefilled + decoding
        assert eng.stats.admitted == 1
        eng.submit(Request(rid=1, prompt=rng.integers(
            0, cfg.vocab_size, (4,)).astype(np.int32), max_new_tokens=4))
        eng.step()                      # rid 1 joins mid-stream
        assert eng.stats.admitted == 2
        outs = eng.run()                # decode to retirement
        assert sorted(outs) == [0, 1]
        assert len(outs[0].tokens) == 8 and len(outs[1].tokens) == 4
        # retirement freed every page
        assert eng.cache.alloc.used_pages == 0, \
            f"leaked pages: {eng.cache.alloc.used_pages}"
        eng.cache.alloc.check_invariants()

    @case("kv_quant_decode")
    def _():
        # quantized memory plane (FLAGS_serving_kv_quant) on the real
        # chip: the same trace served from int8 page pools must emit
        # the full-precision pools' greedy tokens and drain the pool.
        # page_size 32 = the int8 sublane tile, so on-chip this drives
        # the quantized pallas kernel arm (not the jnp fallback)
        from paddle_tpu.inference import Request, ServingEngine
        from paddle_tpu.models import llama as L
        # f32 like the prefix_cache stage: a random tiny model's logit
        # gaps sit inside bf16 cross-program rounding noise. int8 KV
        # quantization is additionally LOSSY, so even in f32 a greedy
        # argmax whose top-2 gap is inside the quantization noise can
        # legitimately flip — the assert below tolerates exactly that
        # (runner-up token at a tiny fp gap) and nothing else.
        cfg = L.llama_tiny(num_hidden_layers=2)
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        prompts = [rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)
                   for n in (6, 9)]

        def serve(kv_quant):
            eng = ServingEngine(L, params, cfg, num_slots=2, max_len=64,
                                page_size=32, decode_chunk=2,
                                kv_quant=kv_quant)
            outs = eng.run([Request(rid=i, prompt=p, max_new_tokens=6)
                            for i, p in enumerate(prompts)])
            assert eng.cache.alloc.used_pages == 0, \
                f"leaked pages: {eng.cache.alloc.used_pages}"
            eng.cache.alloc.check_invariants()
            return {i: np.asarray(o.tokens) for i, o in outs.items()}, eng

        want, _ = serve(kv_quant=False)
        got, qeng = serve(kv_quant=True)
        assert isinstance(qeng.cache.pool["k"], dict), "pool not quantized"
        for i in want:
            eq = want[i] == got[i]
            if eq.all():
                continue
            # benign near-tie flip: the quant run may take the greedy
            # runner-up when the fp top-2 gap is inside the int8 noise
            # floor; anything else (wrong rank, fat gap) is a real bug
            k = int(np.argmin(eq))
            ctx = np.concatenate([prompts[i], want[i][:k]])
            lg = np.asarray(
                L.forward(params, jnp.asarray(ctx)[None, :], cfg)[0, -1],
                np.float64)
            order = np.argsort(lg)[::-1]
            gap = float(lg[order[0]] - lg[order[1]])
            assert int(order[1]) == int(got[i][k]) and gap < 1e-2, (
                f"rid {i} diverged at token {k}: fp={want[i][k]} "
                f"quant={got[i][k]}, fp top-2 gap {gap:.3e} — not a "
                f"near-tie flip")

    @case("operator_scrape")
    def _():
        # the operator plane against the real chip: start the telemetry
        # server, run a serving chunk, scrape /metrics + /healthz, and
        # assert the text parses with the key gauges nonzero — the
        # end-to-end proof an external Prometheus would see real numbers
        import json as _json
        import urllib.request
        from paddle_tpu.inference import Request, ServingEngine
        from paddle_tpu.models import llama as L
        from paddle_tpu.monitor import server as mon_server
        paddle.set_flags({"FLAGS_enable_monitor": True,
                          "FLAGS_enable_monitor_server": True})
        try:
            cfg = L.llama_tiny(num_hidden_layers=2, dtype=jnp.bfloat16)
            params = L.init_params(cfg, jax.random.PRNGKey(0))
            eng = ServingEngine(L, params, cfg, num_slots=2, max_len=32,
                                page_size=16, decode_chunk=2)
            srv = mon_server.get_server()
            assert srv is not None, "engine did not start the server"
            outs = eng.run([Request(
                rid=i, prompt=rng.integers(0, cfg.vocab_size, (6,))
                .astype(np.int32), max_new_tokens=6) for i in range(3)])
            assert len(outs) == 3
            txt = urllib.request.urlopen(
                f"{srv.url}/metrics", timeout=10).read().decode()
            # parseable: every non-comment line is "name[{labels}] value"
            samples = {}
            for line in txt.splitlines():
                if not line or line.startswith("#"):
                    continue
                name, val = line.rsplit(" ", 1)
                samples[name.split("{")[0]] = float(val)
            for gauge in ("serving_tokens_generated",
                          "serving_pages_total",
                          "serving_latency_ttft_ms_count",
                          "jit_program_flops"):
                assert samples.get(gauge, 0) > 0, \
                    f"{gauge} missing/zero in /metrics: " \
                    f"{sorted(samples)[:40]}"
            hz = urllib.request.urlopen(f"{srv.url}/healthz", timeout=10)
            payload = _json.load(hz)
            assert hz.status == 200 and payload["status"] == "ok"
            assert any(k.startswith("serving:")
                       for k in payload["providers"])
            mem = _json.load(urllib.request.urlopen(
                f"{srv.url}/memory", timeout=10))
            if on_tpu:   # the TPU PJRT client reports memory_stats
                assert mem["hbm"]["totals"].get("bytes_in_use", 0) > 0
        finally:
            mon_server.stop_server()
            paddle.set_flags({"FLAGS_enable_monitor": False,
                              "FLAGS_enable_monitor_server": False})
            from paddle_tpu import monitor as _mon
            _mon.reset()

    @case("roofline_scrape")
    def _():
        # comm/roofline observability on the real chip: a guarded train
        # step + an engine run populate the program registry, then
        # /roofline must classify both (nonzero FLOPs + bytes-accessed,
        # non-null boundedness verdict) and /sharding must report
        # per-leaf layouts. On TPU the HBM-bandwidth denominator must
        # come from the real generation table, not a fallback.
        import json as _json
        import urllib.request
        from paddle_tpu.inference import Request, ServingEngine
        from paddle_tpu.models import llama as L
        from paddle_tpu.monitor import server as mon_server
        paddle.set_flags({"FLAGS_enable_monitor": True,
                          "FLAGS_enable_monitor_server": True})
        from paddle_tpu.monitor import exectime as mon_exectime
        mon_exectime.set_sample_rate(1)   # every dispatch measured
        try:
            cfg = L.llama_tiny(num_hidden_layers=2, dtype=jnp.bfloat16)
            params = L.init_params(cfg, jax.random.PRNGKey(0))
            # guarded train step through the to_static-equivalent
            # registration path: the registry must see a training
            # program, not just serving
            from paddle_tpu.monitor import programs as mon_programs
            step = L.make_train_step(cfg, lr=1e-3, donate=False,
                                     guard=False)
            opt = L.adamw_init(params)
            ids = jnp.asarray(rng.integers(
                0, cfg.vocab_size, (2, 32)).astype(np.int32))
            params, opt, _loss = step(params, opt, ids)
            mon_programs.record_jit_call(
                ("smoke.train_step",), "llama.train_step", step,
                (params, opt, ids))
            # measured side: an explicitly timed execution so the
            # train-step record carries exec stats for calibration
            mon_exectime.time_call(("smoke.train_step",), step,
                                   params, opt, ids)
            eng = ServingEngine(L, params, cfg, num_slots=2, max_len=32,
                                page_size=16, decode_chunk=2)
            eng.run([Request(
                rid=i, prompt=rng.integers(0, cfg.vocab_size, (6,))
                .astype(np.int32), max_new_tokens=4) for i in range(2)])
            srv = mon_server.get_server()
            assert srv is not None, "engine did not start the server"
            rl = _json.load(urllib.request.urlopen(
                f"{srv.url}/roofline", timeout=30))
            progs = {p["name"]: p for p in rl["programs"]}
            assert "llama.train_step" in progs, sorted(progs)
            assert any(n.startswith("serving.decode_chunk")
                       for n in progs), sorted(progs)
            for name, p in progs.items():
                if name == "llama.train_step" or \
                        name.startswith("serving.decode_chunk"):
                    assert p["flops"] and p["flops"] > 0, (name, p)
                    assert p["bytes_accessed"] and \
                        p["bytes_accessed"] > 0, (name, p)
                    assert p["verdict"] in ("compute-bound",
                                            "hbm-bound",
                                            "comm-bound"), (name, p)
                    # comm accounting ran (counts may be 0 on one chip,
                    # but the scan itself must have happened)
                    assert p["comms_analyzed"], (name, p)
                    assert isinstance(p["collective_ops"], int)
            if on_tpu:
                assert rl["peaks"]["hbm_source"] == "table", rl["peaks"]
            # roofline CALIBRATION: at least one registered program
            # must report a measured/modeled error ratio (non-null,
            # never fabricated) — the acceptance gate of the measured
            # performance plane
            measured = [p for p in rl["programs"]
                        if p.get("model_error_ratio") is not None]
            assert measured, \
                "no program reported model_error_ratio at /roofline"
            assert rl["calibration"]["measured_programs"] >= 1, \
                rl["calibration"]
            assert rl["calibration"]["max_error_ratio"] > 0
            sh = _json.load(urllib.request.urlopen(
                f"{srv.url}/sharding", timeout=10))
            assert any(k.endswith(".params") for k in sh["trees"]), \
                sorted(sh["trees"])
            tree = next(v for k, v in sh["trees"].items()
                        if k.endswith(".params"))
            assert tree["num_arrays"] > 0 and tree["leaves"]
            leaf = tree["leaves"][0]
            assert leaf["shard_bytes"] > 0 and leaf["dtype"]
            assert any(p["name"].startswith("serving.")
                       for p in sh["programs"])
        finally:
            mon_exectime.set_sample_rate(None)
            mon_server.stop_server()
            paddle.set_flags({"FLAGS_enable_monitor": False,
                              "FLAGS_enable_monitor_server": False})
            from paddle_tpu import monitor as _mon
            _mon.reset()

    @case("profile_capture")
    def _():
        # on-demand device profiler capture end to end: flags on, a
        # short engine run DURING the /profile?seconds=1 window, then a
        # parseable trace directory. TPU asserts device events landed
        # in the xplane (CPU accepts host-only traces).
        import json as _json
        import tempfile
        import urllib.request
        from paddle_tpu.inference import Request, ServingEngine
        from paddle_tpu.models import llama as L
        from paddle_tpu.monitor import profile_capture as pcap
        from paddle_tpu.monitor import server as mon_server
        paddle.set_flags({"FLAGS_enable_monitor": True,
                          "FLAGS_enable_monitor_server": True})
        prof_dir = tempfile.mkdtemp(prefix="smoke_prof_")
        os.environ["PADDLE_TPU_PROFILE_DIR"] = prof_dir
        try:
            cfg = L.llama_tiny(num_hidden_layers=2, dtype=jnp.bfloat16)
            params = L.init_params(cfg, jax.random.PRNGKey(0))
            eng = ServingEngine(L, params, cfg, num_slots=2, max_len=32,
                                page_size=16, decode_chunk=2)
            eng.run([Request(       # compile OUTSIDE the window
                rid=0, prompt=rng.integers(0, cfg.vocab_size, (6,))
                .astype(np.int32), max_new_tokens=4)])
            srv = mon_server.get_server()
            assert srv is not None

            stop = threading.Event()

            def churn():
                # throttled: the point is device events DURING the
                # window, not maximum op volume — an unthrottled tiny-
                # model loop floods the host tracer and stop_trace
                # then spends a minute serializing it on CPU
                rid = 100
                while not stop.is_set():
                    eng.run([Request(
                        rid=rid, prompt=rng.integers(
                            0, cfg.vocab_size, (6,)).astype(np.int32),
                        max_new_tokens=4)])
                    rid += 1
                    stop.wait(0.25)

            t = threading.Thread(target=churn, daemon=True)
            t.start()
            try:
                # generous timeout: the window is 1s but stop_trace
                # serialization scales with traced op volume
                info = _json.load(urllib.request.urlopen(
                    f"{srv.url}/profile?seconds=1", timeout=240))
            finally:
                stop.set()
                t.join(timeout=60)
            assert info["files"], f"empty capture: {info}"
            xplanes = [f for f in info["files"]
                       if f["path"].endswith(".xplane.pb")
                       and (f["bytes"] or 0) > 0]
            assert xplanes, f"no xplane in capture: {info['files']}"
            assert os.path.isdir(info["dir"])
            if on_tpu:
                blob = b""
                for f in xplanes:
                    with open(os.path.join(info["dir"], f["path"]),
                              "rb") as fh:
                        blob += fh.read()
                assert b"TPU" in blob, \
                    "no device events in the TPU capture"
        finally:
            mon_server.stop_server()
            os.environ.pop("PADDLE_TPU_PROFILE_DIR", None)
            paddle.set_flags({"FLAGS_enable_monitor": False,
                              "FLAGS_enable_monitor_server": False})
            from paddle_tpu import monitor as _mon
            _mon.reset()

    @case("drift_detect")
    def _():
        # step-time drift detection end to end through the StepTimer
        # seam: a synthetic slowdown (sleep-padded compute phases) must
        # trip train.step.drift_ratio and the /timeseries drift report
        from paddle_tpu import monitor as _mon
        from paddle_tpu.monitor import timeseries as ts
        paddle.set_flags({"FLAGS_enable_monitor": True})
        try:
            _mon.reset()
            st = _mon.StepTimer("smoke.drift")
            for i in range(16):          # baseline: fast steps
                with st.compute():
                    time.sleep(0.004)
                st.end_step()
            for i in range(8):           # recent: 4x slower
                with st.compute():
                    time.sleep(0.016)
                st.end_step()
            status = ts.drift_status()
            assert status["ratio"] and status["ratio"] > 1.25, status
            assert status["drifting"], status
            g = _mon.snapshot()["gauges"].get("train.step.drift_ratio")
            assert g and g > 1.25, f"drift gauge did not trip: {g}"
        finally:
            paddle.set_flags({"FLAGS_enable_monitor": False})
            _mon.reset()

    @case("numerics_scrape")
    def _():
        # the numerics plane end to end: numerics-enabled guarded
        # steps + engine churn with KV sampling, then /numerics must
        # serve per-layer grad stats, a worst-layer attribution, a
        # finite nonzero int8 SQNR audit, and KV-page absmax samples
        import json as _json
        import urllib.request
        from paddle_tpu.inference import Request, ServingEngine
        from paddle_tpu.models import llama as L
        from paddle_tpu.monitor import numerics as mon_numerics
        from paddle_tpu.monitor import server as mon_server
        from paddle_tpu.training.sentinel import (AnomalySentinel,
                                                  SentinelConfig,
                                                  SentinelLoop)
        paddle.set_flags({"FLAGS_enable_monitor": True,
                          "FLAGS_enable_monitor_server": True,
                          "FLAGS_enable_numerics": True})
        mon_numerics.set_kv_sample_rate(1)
        try:
            cfg = L.llama_tiny(num_hidden_layers=2, vocab_size=64)
            params = L.init_params(cfg, jax.random.PRNGKey(0))
            opt = L.adamw_init(params)
            step = L.make_train_step(cfg, lr=1e-3, guard=True,
                                     donate=False)

            def batches():
                for i in range(4):
                    r = np.random.default_rng(2000 + i)
                    ids = r.integers(0, 64, (2, 33)).astype(np.int32)
                    yield ids[:, :-1], ids[:, 1:]

            loop = SentinelLoop(step, params, opt, batches,
                                sentinel=AnomalySentinel(
                                    SentinelConfig(agree=False)))
            out = loop.run(4)
            assert out["applied"] == 4, out
            # int8 audit through the shared seam contract
            mon_numerics.audit_quantized_tree(
                params, L.quantize_weights(params),
                serving_dtype=jnp.bfloat16)
            eng = ServingEngine(L, params, cfg, num_slots=2,
                                max_len=32, page_size=16,
                                decode_chunk=2)
            eng.run([Request(
                rid=i, prompt=rng.integers(0, 64, (6,))
                .astype(np.int32), max_new_tokens=6)
                for i in range(2)])
            srv = mon_server.get_server()
            assert srv is not None, "loop did not start the server"
            p = _json.load(urllib.request.urlopen(
                f"{srv.url}/numerics", timeout=10))
            assert p["total_steps"] == 4, p["total_steps"]
            assert any(k.startswith("layers.") for k in p["tensors"]), \
                sorted(p["tensors"])[:10]
            wq0 = p["tensors"]["layers.wq[0]"]
            assert wq0["gnorm"] and wq0["gnorm"] > 0
            assert wq0["absmax_ema"] and wq0["absmax_ema"] > 0
            assert p["worst_layer"]["name"] and \
                p["worst_layer"]["finite"]
            for name, ent in p["quant"]["tensors"].items():
                assert ent["sqnr_db"] and ent["sqnr_db"] > 0, \
                    (name, ent)
            assert p["quant"]["min_sqnr_db"] > 0
            assert p["kv"]["samples"] > 0 and p["kv"]["max"] > 0
            # sentinel health report names a layer
            hz = _json.load(urllib.request.urlopen(
                f"{srv.url}/healthz", timeout=10))
            sent = next(v for k, v in hz["providers"].items()
                        if k.startswith("sentinel:"))
            assert sent["worst_layer"], sent
        finally:
            mon_numerics.set_kv_sample_rate(None)
            mon_server.stop_server()
            paddle.set_flags({"FLAGS_enable_monitor": False,
                              "FLAGS_enable_monitor_server": False,
                              "FLAGS_enable_numerics": False})
            from paddle_tpu import monitor as _mon
            _mon.reset()

    @case("slo_scrape")
    def _():
        # the SLO accounting plane end to end: a mixed-tenant engine
        # run with one forced preemption (tiny page pool), scraped
        # mid-run (autoscale demand nonzero) and after drain — /slo
        # must serve finite burn rates + per-tenant cost aggregates,
        # /metrics must carry the tenant-labeled series (hostile
        # tenant names escaped, not corrupting), and a malformed
        # submission must land in the availability window
        import json as _json
        import urllib.request
        from paddle_tpu.inference import (Request, RequestRejected,
                                          ServingEngine)
        from paddle_tpu.models import llama as L
        from paddle_tpu.monitor import server as mon_server
        from paddle_tpu.monitor import slo as mon_slo
        paddle.set_flags({"FLAGS_enable_monitor": True,
                          "FLAGS_enable_monitor_server": True})
        try:
            cfg = L.llama_tiny(num_hidden_layers=2)
            params = L.init_params(cfg, jax.random.PRNGKey(0))
            # 5-page pool, 2 slots: three 12-token sequences cannot
            # coexist -> at least one recompute preemption (the
            # test_trace token-invariant shape)
            eng = ServingEngine(L, params, cfg, num_slots=2,
                                max_len=16, page_size=4, num_pages=5,
                                decode_chunk=2)
            tenants = ["alpha", "beta", 'evil"\n\\tenant']
            # 15 requests: 5 per tenant, clearing the per-tenant
            # min-sample floor (5) so tenant_compliance can answer
            reqs = [Request(rid=i,
                            prompt=rng.integers(0, cfg.vocab_size, (4,))
                            .astype(np.int32),
                            max_new_tokens=8 if i < 3 else 3,
                            tenant=tenants[i % 3], priority=i % 2)
                    for i in range(15)]
            for r in reqs:
                eng.submit(r)
            for _i in range(3):                # mid-run: backlog live
                eng.step()
            srv = mon_server.get_server()
            assert srv is not None, "engine did not start the server"
            mid = _json.load(urllib.request.urlopen(
                f"{srv.url}/slo", timeout=30))
            asc = mid["autoscale"]
            assert asc["available"] and not asc["drain_safe"], asc
            assert asc["demand_estimate"] > 0, asc
            assert asc["desired_capacity_hint"] >= 1, asc
            eng.run()                          # drain
            assert eng.stats.preempted >= 1, eng.stats.as_dict()
            try:
                # malformed AFTER alpha earned its label slot: the
                # rejection attributes to the claimed tenant and
                # enters the availability window
                eng.submit(Request(rid=99, prompt=reqs[0].prompt,
                                   max_new_tokens=3, tenant="alpha",
                                   priority=1.5))      # not integral
                raise AssertionError("bad priority was not rejected")
            except RequestRejected:
                pass
            pre = [o for o in eng.outputs.values()
                   if o.cost and o.cost.preemptions >= 1]
            assert pre, "no output carries a preempted cost record"
            assert pre[0].cost.queue_wait_ms > 0
            p = _json.load(urllib.request.urlopen(
                f"{srv.url}/slo", timeout=30))
            comp = p["compliance"]["objectives"]
            for obj in ("availability", "ttft_p99_ms", "e2e_p99_ms"):
                st = comp[obj]
                assert st["compliance"] is not None, (obj, st)
                for k in ("burn_fast", "burn_slow", "budget_remaining"):
                    assert st[k] is not None and \
                        np.isfinite(st[k]), (obj, k, st)
            # the rejected submission entered the availability window
            assert comp["availability"]["compliance"] < 1.0, comp
            tl = p["tenants"]["tenants"]
            for t in tenants:
                assert t in tl, sorted(tl)
                assert tl[t]["decode_tokens"] > 0, (t, tl[t])
                assert tl[t]["page_seconds"] > 0, (t, tl[t])
            tc = p["tenant_compliance"]
            assert tc["alpha"]["availability"] is not None, tc
            assert tl["alpha"]["rejected"] >= 1, tl["alpha"]
            assert p["autoscale"]["drain_safe"], p["autoscale"]
            text = urllib.request.urlopen(
                f"{srv.url}/metrics", timeout=30).read().decode()
            assert 'slo_tenant_requests{tenant="alpha"}' in text
            # hostile tenant name rides label ESCAPING, never raw bytes
            assert 'tenant="evil\\"\\n\\\\tenant"' in text, \
                [ln for ln in text.splitlines() if "slo_tenant" in ln][:3]
            assert "serving_autoscale_drain_safe 1" in text
            assert "slo_window_requests" in text
        finally:
            mon_server.stop_server()
            paddle.set_flags({"FLAGS_enable_monitor": False,
                              "FLAGS_enable_monitor_server": False})
            from paddle_tpu import monitor as _mon
            _mon.reset()

    @case("overload_drain")
    def _():
        # the acting control plane end to end on the real backend:
        # submit -> shed -> drain. A bounded-queue priority-admission
        # engine under a burst must shed low-priority work with a
        # typed EngineOverloaded + demand-model retry hint, displace
        # for high priority, expire a deadline, finish everything
        # admitted, then drain clean (drain_safe flips, queue shed
        # with hints, live decodes retired) — every submit accounted
        # in exactly one terminal state
        from paddle_tpu.inference import (EngineOverloaded, Request,
                                          ServingEngine)
        from paddle_tpu.models import llama as L

        cfg = L.llama_tiny(num_hidden_layers=2)
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        eng = ServingEngine(L, params, cfg, num_slots=2, max_len=16,
                            page_size=4, decode_chunk=2,
                            priority_admission=True, max_queue=3,
                            slo_preemption=True)

        def mk(rid, **kw):
            return Request(rid=rid,
                           prompt=rng.integers(0, cfg.vocab_size, (5,))
                           .astype(np.int32),
                           max_new_tokens=6, **kw)
        shed_rids, submitted = [], []
        for i in range(8):                      # burst > slots + queue
            try:
                eng.submit(mk(i, priority=0))
                submitted.append(i)
            except EngineOverloaded as e:
                assert e.retry_after_s >= 1.0, e.retry_after_s
                shed_rids.append(i)
        assert shed_rids, "burst did not shed over the bounded queue"
        eng.submit(mk(100, priority=5))          # displaces a low
        submitted.append(100)
        displaced = [r for r, o in eng.outputs.items()
                     if o.finish_reason == "shed"]
        assert len(displaced) == 1, displaced
        eng.submit(mk(101, priority=5, deadline_s=1e-4))
        submitted.append(101)
        time.sleep(0.01)                        # deadline burns out
        for _ in range(3):
            eng.step()
        eng.begin_drain()                        # shed queue, finish live
        try:
            eng.submit(mk(200))
            raise AssertionError("draining engine accepted a submit")
        except EngineOverloaded:
            shed_rids.append(200)                # drain refusal counts
        eng.run()
        assert eng.drain_complete
        assert eng.autoscale_payload()["drain_safe"]
        states = {r: o.finish_reason for r, o in eng.outputs.items()}
        assert sorted(states) == sorted(submitted), (states, submitted)
        assert states[100] == "completed", states
        assert states[101] == "expired", states
        assert eng.stats.completed + eng.stats.expired \
            + eng.stats.shed == len(submitted) + len(shed_rids)
        emitted = sum(len(o.tokens) for o in eng.outputs.values())
        assert eng.stats.tokens_generated \
            - eng.stats.tokens_discarded == emitted
        eng.cache.alloc.check_invariants()
        assert eng.cache.alloc.free_pages == eng.cache.num_pages

    @case("request_forensics")
    def _():
        # the forensics plane end to end on the real backend: a
        # mixed-priority overload run with forced preemption (tiny
        # page pool), then scrape /forensics and /requests/<rid> —
        # the preempted request's timeline must show the preemption
        # with its victim-selection inputs, every terminal request
        # exactly one terminal event, and phases summing to e2e
        import json as _json
        import urllib.request
        from paddle_tpu.inference import (EngineOverloaded, Request,
                                          ServingEngine)
        from paddle_tpu.models import llama as L
        from paddle_tpu.monitor import forensics as mon_forensics
        from paddle_tpu.monitor import server as mon_server
        paddle.set_flags({"FLAGS_enable_monitor": True,
                          "FLAGS_enable_monitor_server": True})
        try:
            cfg = L.llama_tiny(num_hidden_layers=2)
            params = L.init_params(cfg, jax.random.PRNGKey(0))
            # 5-page pool, 2 slots: three 12-token sequences cannot
            # coexist -> at least one recompute preemption
            eng = ServingEngine(L, params, cfg, num_slots=2,
                                max_len=16, page_size=4, num_pages=5,
                                decode_chunk=2, max_queue=3)

            def mk(rid, **kw):
                return Request(rid=rid, prompt=rng.integers(
                    0, cfg.vocab_size, (4,)).astype(np.int32),
                    max_new_tokens=8, **kw)
            shed = []
            for i in range(6):                  # burst > slots + queue
                try:
                    eng.submit(mk(i, priority=i % 2,
                                  tenant=f"t{i % 2}"))
                except EngineOverloaded:
                    shed.append(i)
            assert shed, "burst did not shed over the bounded queue"
            eng.run()
            assert eng.stats.preempted >= 1, eng.stats.as_dict()
            srv = mon_server.get_server()
            assert srv is not None, "engine did not start the server"
            p = _json.load(urllib.request.urlopen(
                f"{srv.url}/forensics", timeout=30))
            assert p["kind"] == "paddle_tpu.forensics"
            by_state = p["terminal_by_state"]
            assert by_state.get("completed") and by_state.get("shed")
            assert p["decisions"]["by_kind"].get("preempt"), \
                p["decisions"]["by_kind"]
            term = set(mon_forensics._TERMINAL_KIND.values())
            preempted = None
            for rid_s in p["requests"]:
                tl = _json.load(urllib.request.urlopen(
                    f"{srv.url}/requests/{rid_s}", timeout=30))
                assert tl["state"] is not None, tl
                kinds = [e["kind"] for e in tl["events"]]
                assert sum(k in term for k in kinds) == 1, tl
                if tl["e2e_ms"] is not None:
                    assert abs(tl["phase_sum_ms"] - tl["e2e_ms"]) \
                        <= 1.0, tl
                if "preempt" in kinds:
                    preempted = tl
            assert preempted is not None, "no timeline saw preemption"
            ev = next(e for e in preempted["events"]
                      if e["kind"] == "preempt")
            for k in ("policy", "slot", "prior_preemptions", "work",
                      "discarded"):
                assert k in ev, (k, ev)
            assert preempted["phases"]["preempted_out"] > 0, preempted
            # a shed rid answers on /requests/<rid> too (terminal-only)
            tl = _json.load(urllib.request.urlopen(
                f"{srv.url}/requests/{shed[0]}", timeout=30))
            assert tl["state"] == "shed", tl
        finally:
            mon_server.stop_server()
            paddle.set_flags({"FLAGS_enable_monitor": False,
                              "FLAGS_enable_monitor_server": False})
            from paddle_tpu import monitor as _mon
            _mon.reset()

    @case("prefix_cache")
    def _():
        # radix shared-prefix KV cache on the real backend: two
        # requests opening with the same 16-token system prefix run
        # serially (the first's retirement seeds the radix), the
        # second must fork cached pages — its prefill token count
        # shrinks by the page-aligned prefix — and every emitted token
        # must match the cache-off run byte for byte. A spec-decode
        # engine then replays one request and must also match.
        from paddle_tpu.inference import Request, ServingEngine
        from paddle_tpu.models import llama as L

        # f32: the parity asserts compare tokens across differently
        # shaped programs (full vs shared prefill, turbo chunk vs
        # verify window) — identical math, but this random model's
        # logit gaps sit inside bf16 cross-program rounding noise, so
        # bf16 argmax ties could flip on the real chip
        cfg = L.llama_tiny(num_hidden_layers=2, dtype=jnp.float32)
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        prefix = rng.integers(0, cfg.vocab_size, (16,)).astype(np.int32)
        prompts = [np.concatenate([prefix, rng.integers(
            0, cfg.vocab_size, (n,)).astype(np.int32)]) for n in (5, 3)]

        def serve(**kw):
            eng = ServingEngine(L, params, cfg, num_slots=2, max_len=48,
                                page_size=4, decode_chunk=2, **kw)
            outs = {}
            for i, p in enumerate(prompts):     # serial: retire seeds
                outs.update(eng.run([Request(
                    rid=i, prompt=p, max_new_tokens=5)]))
            eng.cache.alloc.check_invariants()
            return eng, outs

        eng_off, outs_off = serve()
        eng_on, outs_on = serve(prefix_cache=True)
        for i in range(len(prompts)):
            np.testing.assert_array_equal(outs_on[i].tokens,
                                          outs_off[i].tokens)
        assert eng_on.stats.prefix_hits >= 1, eng_on.stats.as_dict()
        saved = eng_on.stats.prefix_tokens_saved
        assert saved >= 16, saved               # the full aligned prefix
        assert eng_on.stats.tokens_prefilled \
            == eng_off.stats.tokens_prefilled - saved
        # cache holds outlive retirement: the radix pins pages the
        # free-pool no longer counts (the off engine drained to empty)
        assert eng_off.cache.alloc.free_pages == eng_off.cache.num_pages
        assert eng_on.cache.alloc.free_pages < eng_on.cache.num_pages
        # spec decode: greedy token identity through the verify window
        eng_sp = ServingEngine(L, params, cfg, num_slots=1, max_len=64,
                               page_size=4, decode_chunk=2,
                               spec_decode=True)
        outs_sp = eng_sp.run([Request(rid=0, prompt=prompts[0],
                                      max_new_tokens=16)])
        eng_ref = ServingEngine(L, params, cfg, num_slots=1, max_len=64,
                                page_size=4, decode_chunk=2)
        outs_ref = eng_ref.run([Request(rid=0, prompt=prompts[0],
                                        max_new_tokens=16)])
        np.testing.assert_array_equal(outs_sp[0].tokens,
                                      outs_ref[0].tokens)
        assert eng_sp.stats.spec_rounds > 0, eng_sp.stats.as_dict()
        eng_sp.cache.alloc.check_invariants()

    @case("fleet_federation")
    def _():
        # fleet SLO federation end to end on the real backend: two
        # in-process engines publish telemetry frames through the
        # name-keyed heartbeat transport; the elastic controller
        # (FLAGS_serving_fleet_burn_scaling on) scales OUT on an
        # injected fast-burn at flat demand and refuses scale-in
        # while it alerts; /fleet/serving names the burning replica
        # on attribution line 1; beat files are swept on retirement
        import json as _json
        import tempfile
        import threading
        import urllib.request
        from paddle_tpu.distributed import heartbeat as hb
        from paddle_tpu.distributed.fleet.elastic import (
            AdaptiveElasticManager)
        from paddle_tpu.inference import Request, ServingEngine
        from paddle_tpu.models import llama as L
        from paddle_tpu.monitor import federation as fed
        from paddle_tpu.monitor import server as mon_server
        paddle.set_flags({"FLAGS_enable_monitor": True,
                          "FLAGS_enable_monitor_server": True})
        fed.reset()
        hb_dir = tempfile.mkdtemp(prefix="smoke_fed_")
        cfg = L.llama_tiny(num_hidden_layers=1)
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        burning = [True]

        def burn_report():
            # injected per-replica report: replica0 fast-burns while
            # `burning` holds (the in-process engines share the global
            # slo ring, so per-replica burns are injected here)
            hot = burning[0]
            return {"objectives": {"ttft_p99_ms": {
                "compliance": 0.5 if hot else 1.0,
                "burn_fast": 40.0 if hot else 0.0,
                "burn_slow": 30.0 if hot else 0.0,
                "samples_slow": 64, "samples_fast": 32,
                "target_ratio": 0.99}},
                "alerting": ["ttft_p99_ms"] if hot else []}

        def healthy_report():
            return {"objectives": {"ttft_p99_ms": {
                "compliance": 1.0, "burn_fast": 0.0, "burn_slow": 0.0,
                "samples_slow": 64, "samples_fast": 32,
                "target_ratio": 0.99}}, "alerting": []}

        engines = {}
        stoppers = {}        # name -> (run_stop event, churn thread)
        stopped = []

        def spawn(name):
            eng = ServingEngine(L, params, cfg, num_slots=2,
                                max_len=16, page_size=4,
                                decode_chunk=2)
            eng.publish_frames(
                name, hb_dir, min_interval_s=0.0,
                slo_fn=burn_report if name == "replica0"
                else healthy_report)
            engines[name] = eng
            run_stop = threading.Event()

            def churn():
                # a short real burst, then idle stepping: demand
                # settles to ~0 (FLAT — the scale-out below must be
                # attributable to the injected burn, not to load),
                # while the per-step hook keeps publishing frames
                for rid in range(3):
                    try:
                        eng.submit(Request(
                            rid=rid,
                            prompt=rng.integers(
                                0, cfg.vocab_size, (3,))
                            .astype(np.int32),
                            max_new_tokens=2))
                    except Exception:
                        pass
                while not run_stop.is_set():
                    eng.step()
                    time.sleep(0.002)

            churn_th = threading.Thread(target=churn, daemon=True)
            churn_th.start()
            stoppers[name] = (run_stop, churn_th)
            return eng

        def stop(name, h):
            # a real stop: halt the replica's loop BEFORE returning,
            # so it cannot republish a frame after the controller's
            # beat-file sweep
            ev_th = stoppers.get(name)
            if ev_th is not None:
                ev_th[0].set()
                ev_th[1].join(timeout=10)
            stopped.append(name)

        view = fed.FleetSLOView(hb_dir, staleness_s=10.0)
        mgr = AdaptiveElasticManager()
        done = threading.Event()

        def run_ctl():
            mgr.run_serving(spawn, stop, min_replicas=1,
                            max_replicas=2, poll_interval=0.02,
                            heartbeat_dir=hb_dir, federation=view,
                            fleet_burn_scaling=True,
                            max_ticks=100_000, stop_event=done)

        th = threading.Thread(target=run_ctl, daemon=True)
        th.start()
        try:
            # injected fast-burn at flat demand -> scale-out to 2
            deadline = time.monotonic() + 30
            while len(engines) < 2 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert len(engines) == 2, mgr.events
            assert not stopped          # scale-in refused while hot
            srv = mon_server.get_server()
            assert srv is not None
            deadline = time.monotonic() + 30
            while True:
                p = _json.load(urllib.request.urlopen(
                    f"{srv.url}/fleet/serving", timeout=30))
                if sorted(p["frames"]) == ["replica0", "replica1"] \
                        or time.monotonic() >= deadline:
                    break
                time.sleep(0.05)
            assert p["source"] == "controller", p["source"]
            assert sorted(p["frames"]) == ["replica0", "replica1"], \
                sorted(p["frames"])
            att = p["report"]["attribution"]
            assert att[0]["replica"] == "replica0", att
            assert att[0]["alerting"] is True, att
            assert p["report"]["alerting"] == ["ttft_p99_ms"]
            reasons = [d.get("reason") for _, _s, d in mgr.events]
            assert "burn-pressure" in reasons, reasons
            # burn clears -> demand (~0) wants 1 replica -> newest
            # drained, stopped, beat file swept
            burning[0] = False
            deadline = time.monotonic() + 30
            while "replica1" not in stopped \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            assert stopped == ["replica1"], (stopped, mgr.events)
            deadline = time.monotonic() + 10
            beat = os.path.join(hb_dir, "replica1.alive")
            while os.path.exists(beat) \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            assert not os.path.exists(beat)
        finally:
            done.set()
            th.join(timeout=10)
            for ev, _th in stoppers.values():
                ev.set()
            mon_server.stop_server()
            paddle.set_flags({"FLAGS_enable_monitor": False,
                              "FLAGS_enable_monitor_server": False})
            from paddle_tpu import monitor as _mon
            _mon.reset()
            import shutil
            shutil.rmtree(hb_dir, ignore_errors=True)

    @case("trace_replay")
    def _():
        # the loadgen harness end to end on the real backend: a small
        # seeded multi-tenant trace with one scripted overload burst
        # replays open-loop through a live bounded-queue engine; the
        # scorecard must JSON-parse, every submission must sit in
        # exactly one typed terminal state, and every shed must carry
        # a retry-after hint
        import json as _json
        from paddle_tpu.inference import ServingEngine
        from paddle_tpu.loadgen import (Episode, TenantSpec,
                                        build_scorecard, generate_trace,
                                        replay_trace)
        from paddle_tpu.models import llama as L

        cfg = L.llama_tiny(num_hidden_layers=2)
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        eng = ServingEngine(L, params, cfg, num_slots=2, max_len=24,
                            page_size=4, decode_chunk=2,
                            priority_admission=True, max_queue=3)
        trace = generate_trace(
            99, duration_s=0.5, rate=24.0,
            tenants=[TenantSpec("interactive", priority=2),
                     TenantSpec("batch", share=2.0)],
            prompt_len=(3, 8), max_new_tokens=(2, 8))
        result = replay_trace(
            eng, trace, dt_per_step=0.02,
            episodes=[Episode("burst", at_s=0.25, n_requests=10)])
        card = build_scorecard(result)
        card = _json.loads(_json.dumps(card))    # survives the wire
        assert card["verdict"]["pass"], card["verdict"]
        # exactly one typed terminal state per submission (trace +
        # burst), no accounting hole
        assert result.offered == len(trace.requests) + 10
        assert len(result.terminal) == result.offered
        states = set(card["deterministic"]["terminal"])
        assert states <= {"completed", "shed", "expired", "rejected"}, \
            states
        assert sum(card["deterministic"]["terminal"].values()) \
            == result.offered
        # the burst overran slots+queue: typed sheds with retry hints
        sheds = [r for r in result.terminal.values()
                 if r["state"] == "shed"]
        assert sheds, "burst did not shed over the bounded queue"
        for rec in sheds:
            assert rec.get("retry_after_s") is not None, rec
        assert card["deterministic"]["shed_by_reason"], card
        assert card["deterministic"]["goodput"]["request_goodput"] < 1.0

    @case("failover_replay")
    def _():
        # exactly-once failover on the real backend: a fleet replay
        # with FLAGS_serving_failover on kills one replica mid-trace;
        # the victim's journaled in-flight work must re-dispatch onto
        # survivors and settle — zero ``lost``, lineage recorded,
        # token conservation intact
        import tempfile
        from paddle_tpu.inference import ServingEngine
        from paddle_tpu.loadgen import (Episode, TenantSpec,
                                        build_scorecard, generate_trace)
        from paddle_tpu.loadgen.replay import replay_fleet
        from paddle_tpu.models import llama as L
        from paddle_tpu.monitor import federation as fed

        cfg = L.llama_tiny(num_hidden_layers=1)
        params = L.init_params(cfg, jax.random.PRNGKey(3))
        fed.reset()
        try:
            trace = generate_trace(
                41, duration_s=1.2, rate=24.0,
                tenants=[TenantSpec("t0"), TenantSpec("t1")],
                prompt_len=(3, 8), max_new_tokens=(4, 12))
            with tempfile.TemporaryDirectory() as hb_dir:
                res = replay_fleet(
                    lambda name: ServingEngine(
                        L, params, cfg, num_slots=2, max_len=24,
                        page_size=4, decode_chunk=2, failover=True),
                    trace, replicas=2,
                    episodes=[Episode("kill", at_s=0.3,
                                      replica="replica1")],
                    dt_per_tick=0.02, steps_per_tick=1,
                    heartbeat_dir=hb_dir, heartbeat_timeout=6.0,
                    failover=True)
            counts = res.terminal_counts()
            assert counts.get("lost", 0) == 0, counts
            assert len(res.terminal) == res.offered
            assert res.failover["counters"]["stranded"] >= 1, \
                res.failover
            assert any(r.get("recovered_from")
                       for r in res.terminal.values())
            card = build_scorecard(res)
            assert card["verdict"]["pass"], card["verdict"]
        finally:
            fed.reset()

    @case("ragged_paged_attention_kernel")
    def _():
        # the pallas kernel compiled NATIVELY (not interpret) vs the jnp
        # reference at a serving-like shape
        from paddle_tpu.kernels import paged_attention as PA
        B, nh, kvh, hd, ps, maxp = 4, 8, 2, 128, 16, 8
        P = B * maxp
        q = jnp.asarray(rng.normal(size=(B, nh, hd)), jnp.bfloat16)
        kp = jnp.asarray(rng.normal(size=(P, kvh, ps, hd)), jnp.bfloat16)
        vp = jnp.asarray(rng.normal(size=(P, kvh, ps, hd)), jnp.bfloat16)
        bt = jnp.asarray(rng.permutation(P).reshape(B, maxp), jnp.int32)
        ln = jnp.asarray([17, 64, 128, 99], jnp.int32)
        got = jax.jit(lambda *a: PA.ragged_paged_attention(
            *a, interpret=not on_tpu))(q, kp, vp, bt, ln)
        want = PA.paged_attention_ref(q, kp, vp, bt, ln)
        np.testing.assert_allclose(
            np.asarray(got).astype(np.float32),
            np.asarray(want).astype(np.float32), rtol=3e-2, atol=3e-2)

    @case("packed_train_step")
    def _():
        # sequence-packed training on the real chip: the NATIVE segment
        # flash kernel must engage (dispatch counter, not a silent
        # fallback), the loss must be finite, and an aligned trace
        # (documents exactly one row long) must match the equivalent
        # unpacked batch
        from paddle_tpu import kernels
        from paddle_tpu.io.packing import pack_documents, packed_train_batch
        from paddle_tpu.models import llama as L
        cfg = L.llama_tiny(num_hidden_layers=2, dtype=jnp.bfloat16)
        S = 128
        docs = [rng.integers(0, cfg.vocab_size, (ln,)).astype(np.int32)
                for ln in (96, 32, 64, 48, 128, 16)]
        batch = packed_train_batch(pack_documents(docs, S))
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        opt = L.adamw_init(params)
        step = L.make_train_step(cfg, lr=1e-3, donate=False,
                                 guard=False)
        kernels.reset_dispatch_stats()
        _, _, loss = step(params, opt, batch)
        assert np.isfinite(float(loss)), f"packed loss {float(loss)}"
        st = kernels.dispatch_stats()
        if on_tpu:
            assert st["varlen"] > 0, \
                f"segment kernel did not engage: {st}"
        # parity on an aligned trace: one doc per row -> packing is the
        # identity layout, so packed loss == unpacked loss
        docs2 = [rng.integers(0, cfg.vocab_size, (S,)).astype(np.int32)
                 for _ in range(2)]
        b2 = packed_train_batch(pack_documents(docs2, S))
        _, _, lp = step(params, opt, b2)
        ids = np.stack(docs2)
        labels = np.full((2, S), -100, np.int32)
        labels[:, :-1] = ids[:, 1:]
        _, _, lu = step(params, opt, (jnp.asarray(ids),
                                      jnp.asarray(labels)))
        np.testing.assert_allclose(float(lp), float(lu),
                                   rtol=2e-2, atol=2e-2)

    @case("checkpoint_save_kill_resume")
    def _():
        # crash-consistency on the real machine: a child process commits
        # step 1, is kill -9'd (via the fault harness) mid-step-2 save,
        # and THIS process must restore step 1 bit-for-bit
        import subprocess
        import tempfile

        from paddle_tpu.distributed.checkpoint import CheckpointManager
        from paddle_tpu.testing import faults as _faults

        root = os.path.join(tempfile.mkdtemp(prefix="smoke_ckpt_"), "root")
        child = (
            "import os, sys\n"
            "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            "import numpy as _np\n"
            "import paddle_tpu as _pt\n"
            "from paddle_tpu.distributed.checkpoint import "
            "CheckpointManager\n"
            "m = CheckpointManager(sys.argv[1], keep_last_n=3)\n"
            "w = _np.arange(12, dtype='float32').reshape(3, 4)\n"
            "m.save(1, {'w': _pt.to_tensor(w + 1), 'step': 1})\n"
            "m.save(2, {'w': _pt.to_tensor(w + 2), 'step': 2})\n"
            "print('SAVED2')\n")
        r = subprocess.run(
            [sys.executable, "-c", child, root],
            capture_output=True, text=True, timeout=300,
            env=dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
                     FLAGS_fault_injection="checkpoint.rename:kill:2"))
        if r.returncode != _faults.KILL_EXIT_CODE or "SAVED2" in r.stdout:
            raise RuntimeError(
                f"child survived the injected kill: rc={r.returncode} "
                f"{r.stderr[-500:]}")
        mgr = CheckpointManager(root)
        target = {"w": paddle.to_tensor(np.zeros((3, 4), "float32")),
                  "step": 0}
        step = mgr.restore_latest(target)
        got = np.asarray(target["w"].numpy())
        want = np.arange(12, dtype="float32").reshape(3, 4) + 1
        if step != 1 or not np.array_equal(got, want):
            raise RuntimeError(
                f"resume after kill wrong: step={step} w={got.tolist()}")

    @case("nan_skip_resume")
    def _():
        # the anomaly sentinel end to end on the real chip: a corrupt
        # batch (fault-injected NaN) must leave the guarded step's
        # params byte-identical, the loop must SKIP it and keep
        # training, and the loss must still converge-ish afterwards
        from paddle_tpu.models import llama as L
        from paddle_tpu.testing import faults as _faults
        from paddle_tpu.training.sentinel import AnomalySentinel, \
            SentinelLoop

        cfg = L.llama_tiny(num_hidden_layers=2, vocab_size=64)
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        opt = L.adamw_init(params)
        step = L.make_train_step(cfg, lr=1e-3, guard=True, donate=False)

        def batch(i):
            # DISTINCT deterministic batches (identical batches would
            # alias in the quarantine, which is hash-keyed) with a
            # LEARNABLE pattern (consecutive ids mod vocab), so the
            # post-skip loss provably drops
            r = np.random.default_rng(1000 + i)
            start = r.integers(0, cfg.vocab_size, (2, 1))
            ids = ((start + np.arange(33)) % cfg.vocab_size).astype(
                np.int32)
            return ids[:, :-1], ids[:, 1:]

        # 1) a NaN-corrupted batch leaves params byte-identical
        inf_cap = jnp.asarray(np.inf, jnp.float32)
        try:
            _faults.inject("smoke.batch", action="corrupt")
            bad = _faults.corrupt("smoke.batch", (
                jnp.asarray(batch(0)[0], jnp.float32),))  # float leaf
        finally:
            _faults.clear()
        assert not np.isfinite(np.asarray(bad[0])).all(), \
            "corrupt action did not plant a non-finite value"
        bad_ids = np.array(batch(0)[0])
        bad_ids[0, 0] = np.iinfo(np.int32).min      # int-pipeline rot
        p2, o2, _, h = step(params, opt,
                            (bad_ids, batch(0)[1]), inf_cap)
        assert not bool(h["finite"]), "guard missed the corrupt batch"
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(p2)):
            if not np.array_equal(np.asarray(a), np.asarray(b)):
                raise RuntimeError("anomalous step mutated params")

        # 2) loop: corrupt the 3rd batch mid-run -> exactly one skip,
        # training continues, loss drops vs the start
        def make_stream():
            return (batch(i) for i in range(40))

        loop = SentinelLoop(step, params, opt, make_stream,
                            sentinel=AnomalySentinel())
        _, _, first_loss, _ = step(params, opt, batch(0), inf_cap)
        try:
            _faults.inject("train.batch", action="corrupt", nth=3)
            out = loop.run(40)
        finally:
            _faults.clear()
        if out["skipped"] != 1 or out["applied"] != 39:
            raise RuntimeError(f"skip accounting wrong: {out}")
        if not (out["last_loss"] < float(first_loss)):
            raise RuntimeError(
                f"no convergence after skip: first {float(first_loss)} "
                f"last {out['last_loss']}")

    @case("rank_kill_resume")
    def _():
        # survivable multi-host training end to end (ISSUE 14): a
        # 2-process world is launched through the elastic manager; on
        # run 0 rank 1 kill -9s itself mid-gather; rank 0 must log a
        # typed PeerLostError NAMING rank 1 (tombstone fast path) and
        # exit through coordinated_abort; the elastic restart resumes
        # the per-rank DataLoader from committed state and the stitched
        # sample log shows every index consumed exactly once
        import re
        import tempfile

        from paddle_tpu.distributed.fleet.elastic import \
            AdaptiveElasticManager

        work = tempfile.mkdtemp(prefix="smoke_rank_kill_")
        worker = os.path.join(work, "worker.py")
        with open(worker, "w") as f:
            f.write(
                "import os, sys, time\n"
                "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
                "import jax; jax.config.update('jax_platforms', 'cpu')\n"
                "import numpy as np\n"
                "import paddle_tpu.distributed as dist\n"
                "from paddle_tpu.distributed import collective as coll\n"
                "from paddle_tpu.distributed.fleet import elastic\n"
                "from paddle_tpu.io import DataLoader\n"
                "from paddle_tpu.io.dataset import Dataset\n"
                "N, BS, TOTAL = 16, 2, 8\n"
                "class DS(Dataset):\n"
                "    def __len__(self): return N\n"
                "    def __getitem__(self, i):\n"
                "        return np.asarray([i], np.int64)\n"
                "log_path = sys.argv[1]\n"
                "dist.init_parallel_env()\n"
                "rank, run = dist.get_rank(), elastic.elastic_run_index()\n"
                "loader = DataLoader(DS(), batch_size=BS, shuffle=True,\n"
                "                    seed=5)\n"
                "start, state = elastic.load_state(\n"
                "    {'data': loader.state_dict(), 'step': 0})\n"
                "if start: loader.set_state_dict(state['data'])\n"
                "step = int(start)\n"
                "with coll.abort_on_collective_fault():\n"
                "    log = open(f'{log_path}.rank{rank}', 'a')\n"
                "    for batch in loader:\n"
                "        if step >= TOTAL: break\n"
                "        ids = ' '.join(str(int(x)) for x in\n"
                "                       np.asarray(batch.numpy()).ravel())\n"
                "        log.write(f'run={run} step={step} ids={ids}\\n')\n"
                "        log.flush()\n"
                "        step += 1\n"
                "        # collective save: EVERY rank participates in\n"
                "        # the commit-status gathers\n"
                "        elastic.save_state(step,\n"
                "            {'data': dict(loader.state_dict()),\n"
                "             'step': step}, blocking=True)\n"
                "        if run == 0 and rank == 1 and step == 3:\n"
                "            os.kill(os.getpid(), 9)  # mid-gather kill\n"
                "        dist.all_gather_object([], step,\n"
                "                               tag=f'r{run}s{step}',\n"
                "                               timeout_s=45)\n"
                "print(f'SMOKE_DONE rank={rank} run={run}', flush=True)\n")
        log = os.path.join(work, "samples")
        # readmit_after=0: the killed slot re-admits immediately — the
        # restart keeps the full world size
        mgr = AdaptiveElasticManager(max_restarts=2, restart_delay=0.2,
                                     readmit_after=0.0)
        rc = mgr.run_adaptive(
            worker, (log,), nproc_per_node=2,
            ckpt_dir=os.path.join(work, "ckpt"),
            log_dir=os.path.join(work, "logs"),
            extra_env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO})
        if rc != 0:
            raise RuntimeError(f"elastic world never completed: rc={rc}")
        wl = ""
        for run_dir in sorted(os.listdir(os.path.join(work, "logs"))):
            for fn in sorted(os.listdir(
                    os.path.join(work, "logs", run_dir))):
                if fn.startswith("workerlog"):
                    wl += open(os.path.join(work, "logs", run_dir,
                                            fn)).read()
        if "PeerLostError" not in wl or "[1]" not in wl:
            raise RuntimeError(
                f"survivor did not raise a typed error naming rank 1:\n"
                f"{wl[-2000:]}")
        restarts = [d for _, s, d in mgr.events if s == "restart"]
        if not restarts:
            raise RuntimeError("elastic manager recorded no restart")
        # rank 0's stitched sample log: every step exactly once
        lines = [ln for ln in open(f"{log}.rank0").read().splitlines()
                 if ln]
        steps = [int(re.search(r"step=(\d+)", ln).group(1))
                 for ln in lines]
        if steps != list(range(8)):
            raise RuntimeError(f"sample accounting broken: {steps}")
        ids = [int(x) for ln in lines
               for x in re.search(r"ids=(.*)$", ln).group(1).split()]
        if sorted(ids) != list(range(16)):
            raise RuntimeError(f"samples not exactly-once: {sorted(ids)}")

    @case("flash_block_autotune_bench_shape")
    def _():
        # pre-tune the bench shapes; winners land in the REPO cache that
        # bench.py reads (never measuring inside its own watchdog budget)
        os.environ["PADDLE_TPU_AUTOTUNE_CACHE"] = os.path.join(
            REPO, "autotune_cache.json")
        os.environ["PADDLE_TPU_AUTOTUNE"] = "1"
        from paddle_tpu.kernels import autotune as at
        # The module cache tracks its resolved path and evicts when the
        # env var just set above moves it — no _CACHE rebinding needed
        # even though earlier smoke cases already loaded the home-dir
        # cache.
        # rung-1 dense shape + the MoE rung's shape (DeepSeekMoE-16B
        # slice at b8/s1024: 16 heads, d128) so both bench rungs run
        # tuned blocks
        for b, h, kvh, s, d in ((4, 32, 8, 2048, 128),
                                (8, 16, 16, 1024, 128)):
            blocks = at.flash_blocks((b, s, h, d), (b, s, kvh, d),
                                     jnp.bfloat16, True)
            print(f"tuned blocks for s={s}: {blocks}", file=sys.stderr)
            # a silent all-candidates-failed sweep falls back to the
            # defaults — that is a smoke FAILURE, not a timing tie. The
            # dispatch decision record carries the exact key + source.
            (key, used), = [(k, u) for k, u in at.used_blocks().items()
                            if f"q{s}k{s}" in k]
            if on_tpu and used["source"] not in ("measured", "cache"):
                raise RuntimeError(
                    f"autotune sweep did not measure: {used} "
                    f"(cache entry: {at._CACHE.get(key)})")
        # varlen (segment-kernel) blocks at the packed-training rung's
        # shape: the rung's packed row count is a deterministic function
        # of the shared heavy-tailed trace (io.packing), so the sweep
        # here lands on exactly the key bench.py will look up
        from paddle_tpu.io import packing as pk
        lens = pk.heavy_tailed_lengths(2048, 24, seed=7)
        pb = pk.pack_documents(
            [np.zeros(ln, np.int32) for ln in lens], 2048)["ids"].shape[0]
        vblocks = at.varlen_blocks((pb, 2048, 32, 128),
                                   (pb, 2048, 8, 128), jnp.bfloat16, True)
        print(f"tuned varlen blocks for b={pb}: {vblocks}",
              file=sys.stderr)
        (key, used), = [(k, u) for k, u in at.used_blocks().items()
                        if k.startswith("varlen:") and "q2048" in k]
        if on_tpu and used["source"] not in ("measured", "cache"):
            raise RuntimeError(
                f"varlen autotune sweep did not measure: {used} "
                f"(cache entry: {at._CACHE.get(key)})")
        # fused-CE vocab-chunk sweeps at the bench rungs' loss shapes:
        # dense rung (b4*s2048 tokens, 32k vocab, d4096) and the MoE
        # rung (b2*s1024, 102k vocab, d2048)
        for n, d, v in ((8192, 4096, 32000), (2048, 2048, 102400)):
            chunk = at.ce_chunk(n, d, v, jnp.bfloat16)
            print(f"tuned ce chunk for n={n} v={v}: {chunk}",
                  file=sys.stderr)
            (key, used), = [(k, u) for k, u in at.used_blocks().items()
                            if f"n{n}v{v}" in k]
            if on_tpu and used["source"] not in ("measured", "cache"):
                raise RuntimeError(
                    f"ce autotune sweep did not measure: {used} "
                    f"(cache entry: {at._CACHE.get(key)})")

    fails = [k for k, v in results.items() if v != "ok"]
    _emit({"skipped": None, "results": results,
           "platform": devs[0].platform,
           "device_kind": devs[0].device_kind,
           "n_devices": len(devs), "failed": fails})
    return 1 if fails else 0


if __name__ == "__main__":
    sys.exit(main())
