"""Eager-dispatch microbenchmark (VERDICT-r4 item 6).

Measures small-tensor op-by-op eager throughput against raw jnp and a
jitted chain — the cost of the @op_fn dispatcher + tape bookkeeping that
the reference pays in generated C++ (eager_gen.py:301). Prints one
BENCH-style JSON line; the committed record lives in BENCH_EAGER.json.

Budget (regression-tested in tests/test_eager_overhead.py): grad-mode
eager forward <= 5x raw jnp on the same chain. Round-4 measured ~1.9x
after the deferred/jit-cached vjp work (was ~37x with per-op
jax.vjp tracing at forward time).
"""
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, iters=300):
    fn(); fn()
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    return (time.perf_counter() - t0) / iters


def main():
    import paddle_tpu as paddle

    n = 64
    xw = np.random.default_rng(0).normal(size=(n, n)).astype("float32")
    xj = jnp.asarray(xw)
    wj = jnp.asarray(xw)

    t_raw = timeit(lambda: jnp.tanh(xj @ wj + xj).block_until_ready())
    jf = jax.jit(lambda x, w: jnp.tanh(x @ w + x))
    t_jit = timeit(lambda: jf(xj, wj).block_until_ready())

    xp = paddle.to_tensor(xw)
    wp = paddle.to_tensor(xw)
    with paddle.no_grad():
        t_ng = timeit(lambda: paddle.tanh(
            paddle.matmul(xp, wp) + xp)._data.block_until_ready())

    xg = paddle.to_tensor(xw, stop_gradient=False)
    t_g = timeit(lambda: paddle.tanh(
        paddle.matmul(xg, wp) + xg)._data.block_until_ready())

    def step():
        loss = paddle.tanh(paddle.matmul(xg, wp) + xg).mean()
        loss.backward()
        g = xg.grad._data.block_until_ready()
        xg.clear_grad()
        return g
    t_step = timeit(step, 100)

    ops_per_chain = 3
    payload = {
        "metric": "eager_dispatch_overhead_vs_raw_jnp",
        "value": round(t_g / t_raw, 2),
        "unit": "x (grad-mode fwd chain, lower is better)",
        "vs_baseline": round(5.0 / max(t_g / t_raw, 1e-9), 2),
        "extra": {
            "raw_jnp_us": round(t_raw * 1e6, 1),
            "jit_us": round(t_jit * 1e6, 1),
            "eager_no_grad_us": round(t_ng * 1e6, 1),
            "eager_grad_us": round(t_g * 1e6, 1),
            "eager_fwd_bwd_us": round(t_step * 1e6, 1),
            "no_grad_overhead_x": round(t_ng / t_raw, 2),
            "grad_overhead_x": round(t_g / t_raw, 2),
            "eager_ops_per_sec_grad": round(ops_per_chain / t_g),
            "budget_x": 5.0,
            "platform": jax.default_backend(),
        },
    }
    print(json.dumps(payload))


if __name__ == "__main__":
    sys.exit(main())
