#!/usr/bin/env python
"""Metric-name drift check: every metric the code registers must be
documented in docs/observability.md.

The observability doc's "what is instrumented" tables are the contract
operators build dashboards against; a metric added in code but not in
the doc is invisible drift. This script:

1. scans ``paddle_tpu/`` (plus ``bench.py``) for string-literal metric
   names passed to the registration/observation calls
   (``inc/observe/set_gauge/counter/gauge/histogram/timed`` and the
   latency helper) — f-string templated names are skipped (they are
   families; the doc covers them with ``<placeholder>`` patterns);
2. parses the backtick-quoted names out of ``docs/observability.md``,
   expanding two shorthands the tables use:
   - pipe alternation in a segment: ``a.b.hit|miss`` -> a.b.hit, a.b.miss
   - ``<placeholder>`` segments match any single segment:
     ``op.<name>.calls`` matches ``op.matmul.calls``;
3. fails (exit 1) listing any registered name no doc pattern covers.

Run standalone (``python scripts/check_metrics_docs.py``) or from
tier-1 via tests/test_trace.py.
"""
from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOC = os.path.join(REPO, "docs", "observability.md")

# registration/observation entry points whose FIRST string argument is
# a metric name; the optional leading underscore catches the lazy-import
# aliases modules bind (``from . import inc as _inc``)
_CALL_RE = re.compile(
    r"""\b_?(?:inc|observe|set_gauge|counter|gauge|histogram|timed|
             observe_latency)\s*\(\s*
        (f?)["']([a-zA-Z0-9_.{}<>|-]+)["']""",
    re.VERBOSE)

# a plausible metric name: dotted lowercase segments (filters out call
# sites whose first string arg is prose, a format string, or a kind
# tag like get_or_create("counter", ...))
_NAME_RE = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)+$")

# doc tokens worth treating as metric patterns
_DOC_TOKEN_RE = re.compile(r"`([a-zA-Z0-9_.<>|{}-]+)`")


def registered_names(root: str = None) -> set:
    """Literal metric names registered under paddle_tpu/ + bench.py."""
    root = root or REPO
    names = set()
    files = [os.path.join(root, "bench.py")]
    for dirpath, dirnames, filenames in os.walk(
            os.path.join(root, "paddle_tpu")):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        files.extend(os.path.join(dirpath, f) for f in filenames
                     if f.endswith(".py"))
    for path in files:
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
        except OSError:
            continue
        for m in _CALL_RE.finditer(src):
            is_fstring, name = m.group(1), m.group(2)
            if is_fstring or "{" in name:
                continue            # templated family: doc uses <...>
            if _NAME_RE.match(name):
                names.add(name)
    return names


def doc_patterns(doc_path: str = DOC) -> list:
    """Compiled regex patterns for every metric-shaped doc token."""
    with open(doc_path, encoding="utf-8") as f:
        text = f.read()
    patterns = []
    for token in _DOC_TOKEN_RE.findall(text):
        if "." not in token:
            continue
        for expanded in _expand_pipes(token):
            patterns.append(_to_regex(expanded))
    return patterns


def _expand_pipes(token: str) -> list:
    """``a.b.hit|miss`` -> [a.b.hit, a.b.miss] (per segment, cross
    product across segments)."""
    outs = [""]
    for i, seg in enumerate(token.split(".")):
        alts = seg.split("|")
        outs = [(o + "." if i else "") + a for o in outs for a in alts]
    return outs


def _to_regex(pattern: str):
    """``op.<name>.calls`` -> regex with one-segment wildcards."""
    parts = []
    for seg in pattern.split("."):
        if seg.startswith("<") and seg.endswith(">"):
            parts.append(r"[a-z0-9_]+")
        else:
            parts.append(re.escape(seg))
    return re.compile(r"^" + r"\.".join(parts) + r"$")


def undocumented(root: str = None, doc_path: str = DOC) -> list:
    pats = doc_patterns(doc_path)
    missing = []
    for name in sorted(registered_names(root)):
        if not any(p.match(name) for p in pats):
            missing.append(name)
    return missing


def main() -> int:
    names = registered_names()
    if not names:
        print("check_metrics_docs: found NO registered metric names — "
              "the scanner regex is broken", file=sys.stderr)
        return 2
    missing = undocumented()
    if missing:
        print("metrics registered in code but missing from "
              "docs/observability.md tables:", file=sys.stderr)
        for name in missing:
            print(f"  - {name}", file=sys.stderr)
        print(f"({len(missing)} undocumented of {len(names)} scanned; "
              "add them to the tables in docs/observability.md)",
              file=sys.stderr)
        return 1
    print(f"check_metrics_docs: OK ({len(names)} literal metric names, "
          "all documented)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
