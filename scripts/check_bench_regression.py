#!/usr/bin/env python
"""Bench-trajectory regression guard.

The repo checks in one ``BENCH_r<NN>.json`` per round (the driver's
end-of-round capture: a dict with the bench stdout in ``tail`` /
``parsed``), but nothing ever READ the trajectory — a PR could halve
decode throughput and tier-1 would stay green. This script is the
guard:

1. parse every ``BENCH_r*.json`` in round order, extracting the
   allowlisted rungs (headline tokens/s plus the named sub-rungs the
   bench embeds under ``extra`` — MoE, decode, serving, packing,
   trace replay);
   runs that failed (``value`` <= 0, an ``error`` field, or a dead
   tunnel) are SKIPPED, not treated as zeros;
2. the NEWEST successful run is the candidate; each rung's baseline is
   the best of (a) every EARLIER successful run's value and (b) a
   numeric entry in ``BASELINE.json``'s ``published`` map, when one
   exists;
3. fail (exit 1) when a candidate rung undercuts its baseline by more
   than the noise tolerance (default 15% — container/bench spread is
   ~10% per ROADMAP.md, and TPU-tunnel runs swing a few % more).

All rungs are higher-is-better by construction of the allowlist; a
rung missing from the newest run (bench evolved) is reported but not a
failure, and with fewer than one successful prior run the guard
passes trivially — it engages as the trajectory grows. Runs from
tier-1 (tests/test_operator_plane.py) on the checked-in files and
standalone::

    python scripts/check_bench_regression.py [--tolerance 0.15] [-v]
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# rung name -> dotted path into the parsed headline record. Only rungs
# listed here are guarded (all higher-is-better); new bench rungs are
# opted in deliberately, not guarded by accident.
ALLOWLIST = {
    "llama_train_tokens_per_sec_per_chip": "value",
    "moe_train_tokens_per_sec": "extra.moe.tokens_per_sec",
    "decode_tokens_per_sec": "extra.decode.decode_tokens_per_sec",
    "int8_decode_tokens_per_sec": "extra.decode.int8_decode_tokens_per_sec",
    "prefill_tokens_per_sec": "extra.decode.prefill_tokens_per_sec",
    "int4_decode_tokens_per_sec": "extra.decode.int4_decode_tokens_per_sec",
    "serving_tokens_per_sec": "extra.serving_paged.serving_tokens_per_sec",
    # quantized memory plane (FLAGS_serving_kv_quant): the serving rung's
    # int8-pool arm must keep pace with its own trajectory
    "serving_kv_quant_tokens_per_sec":
        "extra.serving_paged.kv_quant.tokens_per_sec",
    "packed_tokens_per_sec": "extra.training_packed.packed_tokens_per_sec",
    # trace-replay goodput (loadgen harness): useful decode tokens per
    # wall second across the seeded overload trace — a PR that sheds
    # more work or slows the engine under burst load fails here
    "serving_replay_goodput_tokens_per_sec":
        "extra.serving_trace_replay.goodput_tokens_per_sec",
}

# LOWER-is-better rungs (measured exec-ms distributions from the
# performance plane's extra.metrics.exec block). Guarded separately:
# the floor is the BEST (minimum) prior value and a candidate fails by
# EXCEEDING it beyond tolerance. Absence on old BENCH_r*.json files
# (the block predates them) simply contributes no floor — skipped,
# never zero-floored.
ALLOWLIST_LOWER = {
    "headline_exec_ms_p50": "extra.metrics.exec.headline.p50_ms",
    "decode_exec_ms_p50": "extra.metrics.exec.decode.p50_ms",
    # serving SLO p99s (extra.metrics.slo, fed by the serving rung's
    # post-warmup latency histograms): a PR that regresses tail
    # latency without touching throughput now fails the guard
    "serving_ttft_ms_p99": "extra.metrics.slo.ttft_p99_ms",
    "serving_tpot_ms_p99": "extra.metrics.slo.tpot_p99_ms",
    # trace-replay p99 TTFT (per-request cost samples of the replay's
    # completed requests, via the scorecard's timing plane)
    "serving_replay_ttft_ms_p99":
        "extra.serving_trace_replay.ttft_p99_ms",
    # failover-on kill replay: p99 strand -> survivor-terminal wall
    # seconds (the exactly-once layer's recovery tail)
    "serving_failover_recovery_s_p99":
        "extra.serving_failover_replay.recovery_s_p99",
    # shared-prefix replay (radix KV cache on, pinned prefix trace):
    # completed-request p50 TTFT and the deterministic prefill-FLOPs-
    # per-request proxy (2·N_params·tokens_prefilled/completed) — a PR
    # that erodes the prefix cache's prefill skipping fails here even
    # if throughput elsewhere holds
    "serving_prefix_ttft_ms_p50":
        "extra.serving_prefix_replay.ttft_p50_ms",
    "serving_prefix_prefill_flops_per_request":
        "extra.serving_prefix_replay.prefill_flops_per_request",
}

# must-be-ZERO invariants, checked on the NEWEST successful run only
# (there is no trajectory to compare — the value is a contract, not a
# measurement). Absence is a skip (the rung didn't run); any positive
# value is a regression. The failover replay's `lost` count is the
# whole point of the durability layer: with FLAGS_serving_failover on,
# a scripted kill must strand work into recovery, never into `lost`.
ALLOWLIST_ZERO = {
    "serving_failover_lost": "extra.serving_failover_replay.lost",
}

# static MINIMUM floors, checked on the NEWEST successful run only —
# like ALLOWLIST_ZERO these are contracts, not trajectories: the value
# must meet the named floor outright (no tolerance — the floor already
# leaves headroom below the theoretical value). Absence is a skip.
# The kv-quant concurrency ratio is pure pool arithmetic (f32 pools are
# ~4x int8+scales, bf16 ~2x), so 1.8x holds on every backend the bench
# runs on.
ALLOWLIST_MIN = {
    "serving_kv_quant_concurrency_at_fixed_pool_bytes": (
        "extra.serving_paged.kv_quant"
        ".servable_concurrency_at_fixed_pool_bytes", 1.8),
}

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def _dig(record: dict, path: str):
    cur = record
    for seg in path.split("."):
        if not isinstance(cur, dict) or seg not in cur:
            return None
        cur = cur[seg]
    return cur if isinstance(cur, (int, float)) else None


def _headline_record(blob: dict):
    """The headline bench JSON line of one BENCH_r file: ``parsed``
    when the driver stored it, else the first parseable ``{"metric":
    ...}`` line of ``tail``."""
    parsed = blob.get("parsed")
    if isinstance(parsed, dict) and "metric" in parsed:
        return parsed
    for line in (blob.get("tail") or "").splitlines():
        line = line.strip()
        if line.startswith("{") and '"metric"' in line:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "metric" in rec:
                return rec
    return None


def extract_rungs(blob: dict, allowlist=None):
    """{rung: value} for one BENCH_r blob, or None when the run failed
    (no headline, an error field, or a non-positive headline value)."""
    allowlist = allowlist if allowlist is not None else ALLOWLIST
    rec = _headline_record(blob)
    if rec is None or rec.get("error"):
        return None
    headline = rec.get("value")
    if not isinstance(headline, (int, float)) or headline <= 0:
        return None
    out = {}
    for rung, path in allowlist.items():
        v = _dig(rec, path)
        if v is not None and v > 0:
            out[rung] = float(v)
    return out or None


def load_trajectory(root=REPO, allowlist=None):
    """[(round_number, {rung: value})] for every successful checked-in
    run, round-ascending. Self-measured / eager files are excluded by
    the BENCH_r<NN>.json pattern."""
    out = []
    for path in glob.glob(os.path.join(root, "BENCH_r*.json")):
        m = _ROUND_RE.search(os.path.basename(path))
        if not m:
            continue
        try:
            with open(path, encoding="utf-8") as f:
                blob = json.load(f)
        except (OSError, ValueError):
            continue
        rungs = extract_rungs(blob, allowlist)
        if rungs:
            out.append((int(m.group(1)), rungs))
    out.sort()
    return out


def published_baselines(root=REPO, allowlist=None):
    """Numeric entries of BASELINE.json's ``published`` map that name
    an allowlisted rung (the map is empty today; the hook exists so a
    hand-published number becomes part of the floor)."""
    allowlist = allowlist if allowlist is not None else ALLOWLIST
    try:
        with open(os.path.join(root, "BASELINE.json"),
                  encoding="utf-8") as f:
            base = json.load(f)
    except (OSError, ValueError):
        return {}
    pub = base.get("published") or {}
    return {k: float(v) for k, v in pub.items()
            if k in allowlist and isinstance(v, (int, float)) and v > 0}


def _newest_record(root=REPO):
    """(round, headline_record) of the NEWEST successful run, or
    (None, None)."""
    best = None
    for path in glob.glob(os.path.join(root, "BENCH_r*.json")):
        m = _ROUND_RE.search(os.path.basename(path))
        if not m:
            continue
        try:
            with open(path, encoding="utf-8") as f:
                blob = json.load(f)
        except (OSError, ValueError):
            continue
        rec = _headline_record(blob)
        if rec is None or rec.get("error"):
            continue
        v = rec.get("value")
        if not isinstance(v, (int, float)) or v <= 0:
            continue
        rnd = int(m.group(1))
        if best is None or rnd > best[0]:
            best = (rnd, rec)
    return best if best is not None else (None, None)


def newest_zero_rungs(root=REPO):
    """(round, {rung: value}) of the ALLOWLIST_ZERO paths on the
    NEWEST successful run — zeros KEPT, unlike :func:`extract_rungs`
    (this check exists precisely to tell 0 from >0)."""
    rnd, rec = _newest_record(root)
    if rec is None:
        return None, {}
    out = {}
    for rung, p in ALLOWLIST_ZERO.items():
        v = _dig(rec, p)
        if v is not None:
            out[rung] = float(v)
    return rnd, out


def newest_min_rungs(root=REPO):
    """(round, {rung: (value, floor)}) of the ALLOWLIST_MIN paths on
    the NEWEST successful run."""
    rnd, rec = _newest_record(root)
    if rec is None:
        return None, {}
    out = {}
    for rung, (p, floor) in ALLOWLIST_MIN.items():
        v = _dig(rec, p)
        if v is not None:
            out[rung] = (float(v), float(floor))
    return rnd, out


def check(root=REPO, tolerance=0.15, allowlist=None, verbose=False):
    """Returns (ok, report_lines)."""
    traj = load_trajectory(root, allowlist)
    lines = []
    if not traj:
        lines.append("bench guard: no successful BENCH_r*.json run yet "
                     "— nothing to guard (pass)")
        return True, lines
    newest_round, newest = traj[-1]
    prior = traj[:-1]
    floors: dict = dict(published_baselines(root, allowlist))
    for _, rungs in prior:
        for rung, v in rungs.items():
            floors[rung] = max(floors.get(rung, 0.0), v)
    # lower-is-better rungs (measured exec ms): best prior = MINIMUM.
    # Runs predating the exec block contribute nothing here — their
    # absence is a skip, never a 0 ceiling that every candidate would
    # "exceed".
    lower_allow = ALLOWLIST_LOWER if allowlist is None else {}
    traj_lower = load_trajectory(root, lower_allow) if lower_allow \
        else []
    lower_by_round = dict(traj_lower)
    newest_lower = lower_by_round.get(newest_round, {})
    ceilings: dict = dict(published_baselines(root, lower_allow))
    for rnd, rungs in traj_lower:
        if rnd == newest_round:
            continue
        for rung, v in rungs.items():
            prev = ceilings.get(rung)
            ceilings[rung] = v if prev is None else min(prev, v)
    # must-be-zero invariants ride the NEWEST run alone — no baseline
    # needed, so they apply even on the first successful run
    zero_ok = True
    zero_lines = []
    if allowlist is None:
        _, zvals = newest_zero_rungs(root)
        for rung, v in sorted(zvals.items()):
            if v > 0:
                zero_ok = False
                zero_lines.append(
                    f"  ✗ {rung}: {v:g} — must-be-zero invariant "
                    "violated: REGRESSION")
            elif verbose:
                zero_lines.append(
                    f"  ✓ {rung}: 0 (invariant holds)")
        # static minimum floors: same newest-run-only discipline
        _, mvals = newest_min_rungs(root)
        for rung, (v, floor) in sorted(mvals.items()):
            if v < floor:
                zero_ok = False
                zero_lines.append(
                    f"  ✗ {rung}: {v:g} undercuts the static floor "
                    f"{floor:g}: REGRESSION")
            elif verbose:
                zero_lines.append(
                    f"  ✓ {rung}: {v:g} >= static floor {floor:g}")
    if not floors and not ceilings:
        lines.append(f"bench guard: r{newest_round:02d} is the first "
                     "successful run — baseline established, nothing "
                     "to compare"
                     f"{' (pass)' if zero_ok else ''}")
        lines.extend(zero_lines)
        return zero_ok, lines
    ok = True
    for rung, floor in sorted(floors.items()):
        v = newest.get(rung)
        if v is None:
            lines.append(f"  ~ {rung}: absent from r{newest_round:02d} "
                         f"(baseline {floor:.2f}) — not a failure")
            continue
        limit = floor * (1.0 - tolerance)
        ratio = v / floor
        if v < limit:
            ok = False
            lines.append(
                f"  ✗ {rung}: {v:.2f} is {ratio:.3f}x of baseline "
                f"{floor:.2f} — below the {1 - tolerance:.2f}x noise "
                "floor: REGRESSION")
        elif verbose:
            lines.append(f"  ✓ {rung}: {v:.2f} vs baseline {floor:.2f} "
                         f"({ratio:.3f}x)")
    for rung, ceiling in sorted(ceilings.items()):
        v = newest_lower.get(rung)
        if v is None:
            lines.append(f"  ~ {rung}: absent from r{newest_round:02d} "
                         f"(baseline {ceiling:.2f} ms) — not a failure")
            continue
        limit = ceiling * (1.0 + tolerance)
        ratio = v / ceiling
        if v > limit:
            ok = False
            lines.append(
                f"  ✗ {rung}: {v:.2f} ms is {ratio:.3f}x of baseline "
                f"{ceiling:.2f} ms — above the {1 + tolerance:.2f}x "
                "noise ceiling (lower is better): REGRESSION")
        elif verbose:
            lines.append(f"  ✓ {rung}: {v:.2f} ms vs baseline "
                         f"{ceiling:.2f} ms ({ratio:.3f}x, lower is "
                         "better)")
    lines.extend(zero_lines)
    ok = ok and zero_ok
    lines.insert(0, f"bench guard: r{newest_round:02d} vs "
                    f"{len(prior)} prior run(s) + published floors, "
                    f"tolerance {tolerance:.0%}: "
                    f"{'ok' if ok else 'REGRESSION'}")
    return ok, lines


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=REPO)
    ap.add_argument("--tolerance", type=float, default=0.15,
                    help="allowed fractional shortfall vs baseline "
                         "(default 0.15)")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)
    ok, lines = check(args.root, args.tolerance, verbose=args.verbose)
    print("\n".join(lines))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
