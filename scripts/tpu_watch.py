"""Tunnel watcher: capture the driver bench the moment the TPU lives.

The axon tunnel relay comes and goes (round 4: dead all round; round 5:
one ~12-minute window that fit the smoke sweep but not the bench). This
watcher loops forever:

1. Probe the relay's loopback ports with a 2s TCP connect (cheap, no
   chip claim).
2. On an open port, verify PJRT init actually completes in a bounded
   subprocess (the round-5 pathology was TCP-accept + init-hang).
3. Run ``python bench.py`` with a generous self-measure deadline
   (BENCH_DEADLINE_S, default 3000s) — this also warms the persistent
   XLA compile cache, so any later run (including the driver's
   end-of-round one) deserializes instead of recompiling.
4. On a measured result (value > 0), immediately re-run with the
   default driver budget for the run-to-run stability record, then
   exit 0.

Artifacts: BENCH_SELF_r05.json (run 1) and BENCH_SELF_r05_run2.json
(run 2), each the bench's own JSON line plus provenance fields.
"""
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
from bench import _RELAY_PORTS as RELAY_PORTS  # noqa: E402  single source
from bench import _relay_alive as relay_alive  # noqa: E402

def _env_float(name, default):
    """A bad override must not crash the watcher at the moment the
    scarce TPU window finally opens (mirrors bench.py's guard)."""
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return float(default)


PROBE_EVERY_S = _env_float("TPU_WATCH_PROBE_S", "60")
RUN1_DEADLINE_S = _env_float("TPU_WATCH_RUN1_DEADLINE_S", "3000")


def log(msg):
    print(f"[tpu_watch {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr)
    sys.stderr.flush()


def pjrt_alive(timeout_s=150):
    """TCP-accept is not enough: init must complete (round-5 pathology)."""
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); print(d[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s, cwd=REPO)
        return r.returncode == 0 and ("tpu" in r.stdout or "axon" in r.stdout)
    except subprocess.TimeoutExpired:
        return False


def run_bench(out_path, deadline_env, budget_s):
    env = dict(os.environ)
    if deadline_env:
        env["BENCH_DEADLINE_S"] = deadline_env
    else:   # "driver budget" must mean the bench's own default, even if
        env.pop("BENCH_DEADLINE_S", None)   # the watcher's shell set one
    t0 = time.time()
    try:
        r = subprocess.run([sys.executable, "bench.py"], cwd=REPO,
                           capture_output=True, text=True, timeout=budget_s,
                           env=env)
    except subprocess.TimeoutExpired:
        log(f"bench exceeded its outer {budget_s}s timeout")
        return None
    line = None
    for ln in (r.stdout or "").splitlines():
        ln = ln.strip()
        if ln.startswith("{"):
            line = ln
    if line is None:
        log(f"bench emitted no JSON (rc {r.returncode}); "
            f"stderr tail: {(r.stderr or '')[-300:]}")
        return None
    try:
        payload = json.loads(line)
    except ValueError:
        log(f"unparseable bench line: {line[:200]}")
        return None
    payload["provenance"] = {
        "self_measured": True,
        "script": "scripts/tpu_watch.py",
        "wall_clock_s": round(time.time() - t0, 1),
        "bench_deadline_env": deadline_env or "(default)",
    }
    with open(os.path.join(REPO, out_path), "w") as f:
        json.dump(payload, f, indent=1)
    log(f"{out_path}: value={payload.get('value')} "
        f"mfu={payload.get('extra', {}).get('mfu')}")
    return payload


def main():
    log(f"watching relay ports {RELAY_PORTS[0]}..{RELAY_PORTS[-1]}")
    while True:
        if not relay_alive():
            time.sleep(PROBE_EVERY_S)
            continue
        log("relay port open; verifying PJRT init")
        if not pjrt_alive():
            log("PJRT init hung/failed; relay is up but chipless")
            time.sleep(PROBE_EVERY_S * 2)
            continue
        log("TPU live — bench run 1 (generous deadline, warms "
            "compile cache)")
        p1 = run_bench("BENCH_SELF_r05.json", str(RUN1_DEADLINE_S),
                       RUN1_DEADLINE_S + 300)
        if not p1 or not p1.get("value"):
            log("run 1 did not measure; re-probing")
            time.sleep(PROBE_EVERY_S)
            continue
        log("re-running the smoke sweep (tunes the MoE rung shape)")
        try:
            r = subprocess.run([sys.executable, "scripts/tpu_smoke.py"],
                               cwd=REPO, timeout=1800)
            if r.returncode != 0:
                log(f"smoke re-run FAILED rc={r.returncode} — run 2 "
                    "proceeds with whatever the cache already holds")
        except subprocess.TimeoutExpired:
            log("smoke re-run timed out; continuing to bench run 2")
        log("bench run 2 (default driver budget, cache-warm)")
        run_bench("BENCH_SELF_r05_run2.json", None, 1200)
        log("done")
        return 0


if __name__ == "__main__":
    sys.exit(main())
