"""Autoregressive generation with the static KV cache — jit once,
decode at HBM-bandwidth speed.

Run:  python examples/generate_llama.py  (TPU or CPU)

Shows the serving path: prefill fills a static [L, B, max_len, kv, hd]
ring cache, then the whole greedy loop runs as ONE compiled program
(lax.scan over decode steps) — no per-token retrace, no concat-grown
cache. The eager Layer model reaches the same path via
``LlamaForCausalLM.generate``.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.models import llama as L

on_tpu = jax.default_backend() in ("tpu", "axon")
if on_tpu:
    cfg = L.llama_3_8b(num_hidden_layers=4, vocab_size=32000, remat=False)
    batch, prompt_len, new = 8, 128, 64
else:
    cfg = L.llama_tiny(num_hidden_layers=2, dtype=jnp.bfloat16)
    batch, prompt_len, new = 2, 16, 8

print(f"params: {L.count_params(cfg) / 1e6:.1f}M  device: "
      f"{jax.devices()[0].device_kind}")

params = jax.jit(lambda: L.init_params(cfg, jax.random.PRNGKey(0)))()
ids = jnp.asarray(np.random.default_rng(0).integers(
    0, cfg.vocab_size, (batch, prompt_len)), jnp.int32)

# greedy — temperature=0.7 + key=PRNGKey(..) would sample instead
gen = jax.jit(lambda p, i: L.generate(p, i, cfg, max_new_tokens=new))
toks = gen(params, ids)                       # compile + warmup
float(toks[0, -1])                            # hard sync

t0 = time.perf_counter()
toks = gen(params, ids)
float(toks[0, -1])
dt = time.perf_counter() - t0
print(f"decoded {batch}x{new} tokens in {dt * 1e3:.0f} ms "
      f"({batch * new / dt:.0f} tok/s, {dt / new * 1e3:.2f} ms/token)")
print("greedy:", np.asarray(toks[0])[:16])

# nucleus sampling and beam search ride the same compiled-loop design
sampled = L.generate(params, ids[:2], cfg, max_new_tokens=16,
                     temperature=0.8, top_p=0.95,
                     key=jax.random.PRNGKey(42))
print("top-p 0.95:", np.asarray(sampled[0]))
beams, scores = L.beam_search(params, ids[:2], cfg, max_new_tokens=16,
                              num_beams=4, length_penalty=0.6)
print(f"beam-4 (score {float(scores[0]):.2f}):", np.asarray(beams[0]))

# weight-only int8 serving: the quantized pytree drops into the same
# jitted loop (decode is HBM-bound — int8 weights measured 1.4x on-chip)
qparams = jax.jit(L.quantize_weights)(params)
toks8 = jax.jit(lambda p, i: L.generate(p, i, cfg, max_new_tokens=new))(
    qparams, ids)
print("int8 greedy:", np.asarray(toks8[0])[:16])
