"""Long-context training via ring attention (sequence/context
parallelism over the mesh — the SEP capability).

Run (8 simulated devices):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/long_context_ring_attention.py

Each device holds a sequence shard; keys/values rotate around the ring
(ppermute over ICI on real hardware) with an online-softmax accumulator,
so attention over the FULL sequence is computed without any device ever
holding all of it.
"""
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.kernels.ring_attention import ring_attention

devs = jax.devices()
mesh = Mesh(np.array(devs), ("sp",))
B, S, H, D = 2, 8 * 128, 4, 32          # sequence 1024 over 8 shards
rng = np.random.default_rng(0)
q, k, v = (jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32) * 0.1
           for _ in range(3))

sharded = NamedSharding(mesh, P(None, "sp", None, None))
qs, ks, vs = (jax.device_put(t, sharded) for t in (q, k, v))

out = ring_attention(qs, ks, vs, mesh, axis="sp",
                                    causal=True)
jax.block_until_ready(out)

# exact parity with single-device attention ([B,S,H,D] -> heads-major)
qh, kh, vh = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) / jnp.sqrt(jnp.float32(D))
mask = jnp.tril(jnp.ones((S, S), bool))
ref = jnp.einsum("bhqk,bhkd->bhqd",
                 jax.nn.softmax(jnp.where(mask, logits, -jnp.inf)), vh)
ref = jnp.swapaxes(ref, 1, 2)
np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4,
                           atol=2e-5)
print(f"ring attention over {len(devs)} sequence shards: exact parity OK "
      f"(seq={S}, per-device {S // len(devs)})")
