"""Save a model with paddle.jit.save and serve it through
paddle.inference.Predictor — the deployment path (StableHLO artifact).

Run:  python examples/serve_predictor.py
"""
import os
import tempfile

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.jit as jit
import paddle_tpu.nn as nn
from paddle_tpu import inference

# 1. train-side: build + save
net = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 2))
path = os.path.join(tempfile.mkdtemp(), "model")
jit.save(net, path, input_spec=[jit.InputSpec([None, 4], "float32")])
print("saved:", path)

# 2. serve-side (fresh process in real deployments)
config = inference.Config(path)
predictor = inference.create_predictor(config)
x = np.random.default_rng(0).normal(size=(3, 4)).astype("float32")
(in_name,) = predictor.get_input_names()
predictor.get_input_handle(in_name).copy_from_cpu(x)
predictor.run()
(out_name,) = predictor.get_output_names()
out = predictor.get_output_handle(out_name).copy_to_cpu()
print("served output:", out.shape)

# parity with the live layer
want = net(paddle.to_tensor(x)).numpy()
np.testing.assert_allclose(out, want, rtol=1e-5)
print("parity with eager: OK")
