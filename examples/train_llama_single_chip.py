"""Train a Llama slice on one chip — the bench.py recipe as a readable
example.

Run:  python examples/train_llama_single_chip.py  (TPU or CPU)

Shows the functional training path: config -> init_params ->
make_train_step (jitted, donated buffers) -> loop. On TPU the Pallas
flash-attention kernel engages automatically (kernels.auto_register).
With FLAGS_enable_sentinel=1 the step is built GUARDED (in-graph
NaN/spike gate, paddle_tpu/training/sentinel.py) and this loop drives
it — an anomalous batch is skipped with params untouched.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models import llama as L

on_tpu = jax.default_backend() in ("tpu", "axon")
if on_tpu:
    cfg = L.llama_3_8b(num_hidden_layers=4, vocab_size=32000,
                       remat_policy="full")
    batch, seq = 4, 2048
else:
    cfg = L.llama_tiny(num_hidden_layers=2, dtype=jnp.bfloat16)
    batch, seq = 4, 128

print(f"params: {L.count_params(cfg) / 1e6:.1f}M  device: "
      f"{jax.devices()[0].device_kind}")

params = L.init_params(cfg, jax.random.PRNGKey(0))
opt_state = L.adamw_init(params)
step = L.make_train_step(cfg, lr=3e-4)   # guard follows the sentinel flag

sentinel = None
if L.resolve_guard(None):
    from paddle_tpu.training.sentinel import AnomalySentinel
    sentinel = AnomalySentinel()
    print("sentinel: guarded step (skip-on-anomaly)")

rng = np.random.default_rng(0)
for i in range(10):
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq + 1)),
                      jnp.int32)
    t0 = time.perf_counter()
    if sentinel is None:
        params, opt_state, loss = step(params, opt_state, ids)
    else:
        cap = jnp.asarray(sentinel.gnorm_cap(), jnp.float32)
        params, opt_state, loss, health = step(params, opt_state, ids, cap)
        if sentinel.observe(finite=health["finite"],
                            grad_norm=health["grad_norm"],
                            loss=loss) != "ok":
            print(f"step {i}: anomalous batch SKIPPED")
            continue
    lv = float(loss)                       # hard sync
    dt = time.perf_counter() - t0
    print(f"step {i}: loss {lv:.4f}  ({batch * seq / dt:,.0f} tok/s)")
