"""Hybrid-parallel training over a device mesh (dp x fsdp x tp).

Run on a multi-chip host, or simulate with 8 virtual CPU devices:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/train_multichip_hybrid.py

The mesh + GSPMD shardings replace the reference's NCCL process groups:
parameters shard on fsdp (ZeRO-3), activations on dp x fsdp, attention
heads on tp; XLA inserts the collectives over ICI.
"""
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from paddle_tpu.models import llama as L

devs = np.array(jax.devices())
assert devs.size % 2 == 0, "need an even device count"
mesh = Mesh(devs.reshape(devs.size // 2, 1, 2), ("dp", "fsdp", "tp"))
print(f"mesh: {dict(zip(mesh.axis_names, mesh.devices.shape))}")

cfg = L.llama_tiny(num_hidden_layers=2, hidden_size=64,
                   num_attention_heads=4, num_key_value_heads=2,
                   dtype=jnp.float32)
with mesh:
    # guard=False: this example demonstrates GSPMD sharding; see
    # train_llama_single_chip.py for the sentinel-guarded step
    step = L.make_train_step(cfg, mesh=mesh, lr=1e-3, guard=False)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    opt_state = L.adamw_init(params)
    rng = np.random.default_rng(0)
    for i in range(5):
        ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 33)),
                          jnp.int32)
        params, opt_state, loss = step(params, opt_state, ids)
        print(f"step {i}: loss {float(loss):.4f}")
print("sharded training OK")
