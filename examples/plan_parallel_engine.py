"""Let the auto-parallel Engine's cost model pick the mesh split.

8B parameters on 8 memory-tight chips: naive data parallelism needs
~128 GB/chip of param+grad+optimizer state, so the planner must find a
hybrid (fsdp shards state, tp shards compute) — and show its work.

Run:  python examples/plan_parallel_engine.py
"""
from paddle_tpu.distributed.engine import plan_parallel

plan = plan_parallel(
    8,
    dict(num_params=8e9, num_layers=32, hidden_size=4096,
         seq_length=2048, dtype="bfloat16"),
    global_batch_size=8, hbm_bytes=17.5e9, chips_per_host=2,
    sharding_stage=3, use_recompute=True)

print(plan.describe())
print("considered:", plan.candidates_considered,
      "feasible:", plan.candidates_feasible)
for alt in plan.alternatives:
    print("  runner-up:", {k: alt[k] for k in
                           ("dp_degree", "sharding_degree", "mp_degree",
                            "pp_degree", "micro_batch_size")})
dp, fsdp, tp = plan.mesh_shape
assert fsdp > 1, "planner should shard state for this scenario"
print(f"plan: dp={dp} fsdp={fsdp} tp={tp} -> "
      "build_mesh() yields the ('dp','fsdp','tp') Mesh for GSPMD")
print("engine planning: OK")
