"""Train a CNN with the high-level Model API (hapi) on synthetic data.

Run:  python examples/train_vision_hapi.py
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.io import Dataset, DataLoader
from paddle_tpu.vision.models import mobilenet_v3_small


class SynthImages(Dataset):
    def __init__(self, n=64):
        rng = np.random.default_rng(0)
        self.x = rng.normal(size=(n, 3, 32, 32)).astype("float32")
        self.y = rng.integers(0, 10, (n, 1)).astype("int64")

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


net = mobilenet_v3_small(num_classes=10)
model = paddle.Model(net)
model.prepare(
    optimizer=paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=net.parameters()),
    loss=nn.CrossEntropyLoss(),
    metrics=paddle.metric.Accuracy())
model.fit(DataLoader(SynthImages(), batch_size=16, shuffle=True),
          epochs=1, verbose=1)
print("hapi training OK")
