"""Export a model to ONNX with the in-tree jaxpr -> ONNX converter
(opset 17): parameters become initializers, matmuls become Einsum,
conv/pool/gather map directly, and scan-over-layers decoders unroll.

Run:  python examples/export_onnx.py
"""
import os
import tempfile

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn

net = nn.Sequential(nn.Conv2D(3, 8, 3, padding=1), nn.ReLU(),
                    nn.MaxPool2D(2, 2), nn.Flatten(),
                    nn.Linear(8 * 4 * 4, 10), nn.Softmax())
net.eval()

path = os.path.join(tempfile.mkdtemp(), "cnn")
onnx_path = paddle.onnx.export(
    net, path, input_spec=[np.zeros((1, 3, 8, 8), "float32")])
size = os.path.getsize(onnx_path)
print(f"exported: {onnx_path} ({size} bytes)")

# inspect the graph through the same schema a consumer would use
from paddle_tpu.onnx import onnx_pb2 as P

model = P.ModelProto.FromString(open(onnx_path, "rb").read())
ops = [n.op_type for n in model.graph.node]
print("opset:", model.opset_import[0].version)
print("nodes:", ops)
print("initializers:", len(model.graph.initializer))
assert "Conv" in ops and "MaxPool" in ops
print("onnx export: OK")
