// Native data-feed core: multi-threaded batch assembly with a
// prefetching ring of preallocated buffers.
//
// Reference capability: paddle/fluid/framework/data_feed.cc — the C++
// DataFeed/BlockingQueue pipeline that keeps devices fed without the
// Python interpreter on the per-batch path. TPU-native shape: the hot
// host work for accelerator input pipelines over memory-resident /
// memory-mapped datasets is row GATHER (collate N sample rows into one
// contiguous batch). This core runs that gather on a worker pool over
// a depth-K ring of reusable batch buffers, with epoch shuffling
// (xorshift Fisher-Yates) done natively too. Python touches one ctypes
// call per batch.
//
// C ABI (ctypes-friendly), no Python.h: the wrapper owns numpy arrays
// and passes raw pointers; lifetimes are managed on the Python side.
#include <atomic>
#include <map>
#include <memory>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

namespace {

struct Source {
  const uint8_t* data;
  uint64_t row_bytes;
};

struct Batch {
  std::vector<std::vector<uint8_t>> bufs;  // one per source
  uint64_t rows = 0;
  uint64_t epoch = 0;
  uint64_t index = 0;
};

struct Pipeline {
  std::vector<Source> sources;
  uint64_t n_rows = 0;
  uint64_t batch = 0;
  bool drop_last = false;
  bool shuffle = false;
  uint64_t seed = 0;
  uint64_t epochs = 0;          // 0 = endless
  int n_threads = 1;

  std::vector<uint64_t> perm;               // identity / unshuffled
  // per-epoch shuffled permutations (created by the first task of the
  // epoch, read lock-free through shared_ptr by concurrent gathers)
  std::map<uint64_t, std::shared_ptr<std::vector<uint64_t>>> epoch_perms;
  std::mutex perm_mu;
  uint64_t issued = 0;                      // tasks handed out (gated)
  uint64_t batches_per_epoch = 0;

  // ring of reusable buffers
  std::queue<Batch*> free_q;
  std::queue<Batch*> ready_q;   // producer -> consumer, ordered
  std::mutex mu;
  std::condition_variable cv_free, cv_ready;
  std::vector<Batch*> all;
  std::vector<std::thread> workers;
  std::atomic<bool> stop{false};
  uint64_t produced_seq = 0;    // order tickets so batches stay ordered
  uint64_t emitted_seq = 0;
  std::mutex order_mu;
  std::condition_variable cv_order;

  ~Pipeline() {
    stop.store(true);
    // lock each waiter's mutex before notifying: a worker that checked
    // the predicate pre-stop but hasn't blocked yet would otherwise
    // miss the wakeup and hang the join below
    { std::lock_guard<std::mutex> g(mu); }
    { std::lock_guard<std::mutex> g(order_mu); }
    cv_free.notify_all();
    cv_ready.notify_all();
    cv_order.notify_all();
    for (auto& t : workers) {
      if (t.joinable()) t.join();
    }
    for (auto* b : all) delete b;
  }
};

uint64_t xorshift(uint64_t* s) {
  uint64_t x = *s;
  x ^= x << 13;
  x ^= x >> 7;
  x ^= x << 17;
  return *s = x;
}

void shuffle_perm(std::vector<uint64_t>& perm, uint64_t seed,
                  uint64_t epoch) {
  uint64_t s = seed * 0x9E3779B97F4A7C15ull + epoch + 1;
  for (uint64_t i = perm.size(); i > 1; --i) {
    uint64_t j = xorshift(&s) % i;
    std::swap(perm[i - 1], perm[j]);
  }
}

void gather_rows(const Source& src, const uint64_t* idx, uint64_t n,
                 uint8_t* dst) {
  for (uint64_t i = 0; i < n; ++i) {
    std::memcpy(dst + i * src.row_bytes,
                src.data + idx[i] * src.row_bytes, src.row_bytes);
  }
}

std::shared_ptr<std::vector<uint64_t>> epoch_perm(Pipeline* p,
                                                  uint64_t epoch) {
  std::lock_guard<std::mutex> g(p->perm_mu);
  auto it = p->epoch_perms.find(epoch);
  if (it != p->epoch_perms.end()) return it->second;
  auto perm = std::make_shared<std::vector<uint64_t>>(p->perm);
  if (p->shuffle) shuffle_perm(*perm, p->seed, epoch);
  p->epoch_perms[epoch] = perm;
  // keep the map tiny: in-flight tasks span a bounded epoch window
  while (p->epoch_perms.size() > 4) {
    p->epoch_perms.erase(p->epoch_perms.begin());
  }
  return perm;
}

void worker_loop(Pipeline* p) {
  size_t depth = p->all.size();
  while (!p->stop.load()) {
    uint64_t task;
    {
      // gate issuance to the ring depth: every in-flight task owns a
      // buffer, so the ordered publication below can never starve a
      // lower-numbered task of one (deadlock when n_threads > depth)
      std::unique_lock<std::mutex> lk(p->order_mu);
      p->cv_order.wait(lk, [&] {
        return p->stop.load() ||
               p->issued - p->produced_seq < depth;
      });
      if (p->stop.load()) break;
      task = p->issued++;
    }
    uint64_t epoch = task / p->batches_per_epoch;
    uint64_t bidx = task % p->batches_per_epoch;
    if (p->epochs && epoch >= p->epochs) break;

    auto perm = epoch_perm(p, epoch);

    uint64_t start = bidx * p->batch;
    uint64_t rows = std::min(p->batch, p->n_rows - start);

    Batch* b = nullptr;
    {
      std::unique_lock<std::mutex> lk(p->mu);
      p->cv_free.wait(lk, [&] {
        return p->stop.load() || !p->free_q.empty();
      });
      if (p->stop.load()) break;
      b = p->free_q.front();
      p->free_q.pop();
    }
    b->rows = rows;
    b->epoch = epoch;
    b->index = bidx;
    for (size_t s = 0; s < p->sources.size(); ++s) {
      gather_rows(p->sources[s], perm->data() + start, rows,
                  b->bufs[s].data());
    }
    // publish in task order so consumers see deterministic sequence
    {
      std::unique_lock<std::mutex> lk(p->order_mu);
      p->cv_order.wait(lk, [&] {
        return p->stop.load() || p->produced_seq == task;
      });
      if (p->stop.load()) break;
      {
        std::lock_guard<std::mutex> g(p->mu);
        p->ready_q.push(b);
      }
      p->produced_seq = task + 1;
      p->cv_order.notify_all();
      p->cv_ready.notify_one();
    }
  }
}

}  // namespace

extern "C" {

void* df_pipeline_create(const void** srcs, const uint64_t* row_bytes,
                         uint64_t n_sources, uint64_t n_rows,
                         uint64_t batch, int drop_last, int shuffle,
                         uint64_t seed, uint64_t epochs, int n_threads,
                         int depth) {
  auto* p = new Pipeline();
  for (uint64_t s = 0; s < n_sources; ++s) {
    p->sources.push_back(
        {static_cast<const uint8_t*>(srcs[s]), row_bytes[s]});
  }
  p->n_rows = n_rows;
  p->batch = batch;
  p->drop_last = drop_last != 0;
  p->shuffle = shuffle != 0;
  p->seed = seed;
  p->epochs = epochs;
  p->n_threads = n_threads < 1 ? 1 : n_threads;
  p->batches_per_epoch =
      p->drop_last ? n_rows / batch : (n_rows + batch - 1) / batch;
  if (p->batches_per_epoch == 0) {
    delete p;
    return nullptr;
  }
  p->perm.resize(n_rows);
  for (uint64_t i = 0; i < n_rows; ++i) p->perm[i] = i;
  if (depth < 2) depth = 2;
  for (int d = 0; d < depth; ++d) {
    auto* b = new Batch();
    for (auto& src : p->sources) {
      b->bufs.emplace_back(batch * src.row_bytes);
    }
    p->all.push_back(b);
    p->free_q.push(b);
  }
  for (int t = 0; t < p->n_threads; ++t) {
    p->workers.emplace_back(worker_loop, p);
  }
  return p;
}

// Pop the next batch into dsts (one pointer per source). Returns the
// number of rows, 0 at end of the final epoch.
uint64_t df_pipeline_next(void* handle, void** dsts, uint64_t* epoch,
                          uint64_t* index) {
  auto* p = static_cast<Pipeline*>(handle);
  uint64_t total = p->epochs ? p->epochs * p->batches_per_epoch : 0;
  if (total && p->emitted_seq >= total) return 0;
  Batch* b = nullptr;
  {
    std::unique_lock<std::mutex> lk(p->mu);
    p->cv_ready.wait(lk, [&] {
      return p->stop.load() || !p->ready_q.empty();
    });
    if (p->stop.load()) return 0;
    b = p->ready_q.front();
    p->ready_q.pop();
  }
  for (size_t s = 0; s < p->sources.size(); ++s) {
    std::memcpy(dsts[s], b->bufs[s].data(),
                b->rows * p->sources[s].row_bytes);
  }
  uint64_t rows = b->rows;
  if (epoch) *epoch = b->epoch;
  if (index) *index = b->index;
  {
    std::lock_guard<std::mutex> g(p->mu);
    p->free_q.push(b);
  }
  p->cv_free.notify_one();
  p->emitted_seq += 1;
  return rows;
}

void df_pipeline_destroy(void* handle) {
  delete static_cast<Pipeline*>(handle);
}

// standalone multi-call gather (no pipeline): used for benchmarking and
// as the collate primitive
void df_gather(const void* src, uint64_t row_bytes, const uint64_t* idx,
               uint64_t n, void* dst) {
  Source s{static_cast<const uint8_t*>(src), row_bytes};
  gather_rows(s, idx, n, static_cast<uint8_t*>(dst));
}

}  // extern "C"
