"""Benchmark: Llama decoder training throughput on the available device.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Metric: training tokens/sec on a Llama block stack sized to fit the chip,
plus model FLOPs utilisation (MFU) computed from the 6*N*tokens estimate.
vs_baseline is MFU / 0.40 (BASELINE.json north star: >=40% MFU).
"""
import json
import sys
import time

import numpy as np


def main():
    if "--smoke" in sys.argv:
        # CPU smoke: don't claim the shared TPU chip.
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models import llama as L

    dev = jax.devices()[0]
    on_tpu = dev.platform in ("tpu", "axon") or "TPU" in (dev.device_kind or "")
    # Single-chip benchmark config: a 4-layer 8B-shaped slice on TPU
    # (fits one chip's HBM with remat), tiny on CPU fallback.
    if on_tpu:
        cfg = L.llama_3_8b(num_hidden_layers=4)
        batch, seq, iters = 4, 2048, 10
    else:
        cfg = L.llama_tiny(num_hidden_layers=2, dtype=jnp.bfloat16)
        batch, seq, iters = 4, 128, 5

    params = L.init_params(cfg, jax.random.PRNGKey(0))
    opt_state = L.adamw_init(params)
    step = L.make_train_step(cfg, lr=1e-4)
    ids = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (batch, seq + 1)), jnp.int32)

    # warmup/compile
    params, opt_state, loss = step(params, opt_state, ids)
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_state, loss = step(params, opt_state, ids)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    tokens = batch * seq * iters
    tps = tokens / dt
    # 6ND (fwd+bwd) + remat fwd (~2ND more) -> use 6ND for standard MFU
    n_params = L.count_params(cfg)
    flops_per_token = 6 * n_params
    peak = 459e12 if on_tpu else 1e12   # v5p bf16 peak; CPU nominal
    mfu = tps * flops_per_token / peak
    print(json.dumps({
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tps, 2),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {"mfu": round(mfu, 4), "params": n_params,
                  "platform": dev.platform, "batch": batch, "seq": seq,
                  "layers": cfg.num_hidden_layers,
                  "loss": float(loss)},
    }))


if __name__ == "__main__":
    main()
