"""Benchmark: Llama decoder training throughput on the available device.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Metric: training tokens/sec on a Llama block stack sized to fit the chip,
plus model FLOPs utilisation (MFU) computed from the 6*N*tokens estimate.
vs_baseline is MFU / 0.40 (BASELINE.json north star: >=40% MFU).

Hardened against shared-TPU backend flakes: backend init is probed with
retries, and any failure still emits a parseable JSON line (value 0 +
error detail) instead of a stack dump. Param/optimizer init runs inside a
single jitted program (no eager op-by-op device traffic). The run records
whether the Pallas flash-attention kernel actually engaged at the bench
shapes (kernels.dispatch_stats) and flags a fallback in the JSON output so
a silent fallback can't quietly cost MFU unnoticed.
"""
import json
import os
import sys
import time

import numpy as np


def _peak_flops(dev) -> float:
    """bf16 peak FLOP/s per chip by TPU generation (device_kind, or the
    axon tunnel's PALLAS_AXON_TPU_GEN env)."""
    table = {"v6e": 918e12, "v5p": 459e12, "v5e": 197e12,
             "v4": 275e12, "v3": 123e12}
    kind = (dev.device_kind or "").lower().replace(" ", "")
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    for k, v in table.items():
        if k in kind or k in gen:
            return v
    return 459e12   # assume v5p (BASELINE.json north-star hardware)


def _emit(payload):
    print(json.dumps(payload))


def _fail(metric, msg):
    payload = {"metric": metric, "value": 0.0, "unit": "tokens/s",
               "vs_baseline": 0.0, "error": msg[-2000:]}
    # If a prior successful on-chip measurement exists in-tree (taken
    # before a tunnel outage), point the record at it.
    self_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_SELF_r03.json")
    if os.path.exists(self_path):
        payload["see_also"] = (
            "BENCH_SELF_r03.json — self-measured on-chip result from "
            "earlier in the session (45.75% MFU), recorded before the "
            "TPU tunnel outage")
    _emit(payload)


def _probe_backend(retries=3, delay=10.0, hang_timeout=180):
    """Initialize the jax backend with retries (shared-TPU tunnel can be
    transiently unavailable). A SIGALRM watchdog converts an init *hang*
    (observed failure mode of the tunnel) into an exception so the caller
    can still emit the JSON error line. Returns the first device."""
    import signal

    import jax

    last = None
    for i in range(retries):
        def _alarm(signum, frame):
            raise TimeoutError(
                f"backend init hang (> {hang_timeout}s)")

        old = signal.signal(signal.SIGALRM, _alarm)
        signal.alarm(hang_timeout)
        try:
            return jax.devices()[0]
        except Exception as e:  # init failure OR watchdog timeout
            last = e
            time.sleep(delay * (i + 1))
        finally:
            signal.alarm(0)
            signal.signal(signal.SIGALRM, old)
    raise RuntimeError(f"backend init failed after {retries} tries: {last}")


def _preflight_kernels(on_tpu):
    """Lower + run each Pallas kernel standalone (fwd AND bwd) at tiny
    shapes before the timed loop. A kernel that fails de-registers itself
    so the model traces the XLA fallback — a kernel bug costs MFU, never
    the whole bench number (BENCH_r02 recorded 0.0 because a lowering
    error inside the first train step killed everything)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu import kernels

    if not on_tpu:
        return {}
    failures = {}

    def try_kernel(name, fn):
        try:
            jax.block_until_ready(fn())
        except Exception as e:
            failures[name] = f"{type(e).__name__}: {e}"[:500]

    def flash_case():
        q = jnp.ones((1, 256, 2, 128), jnp.bfloat16)

        def loss(q):
            return jnp.sum(kernels.flash_attention(
                q, q, q, causal=True, interpret=False).astype(jnp.float32))
        return jax.jit(jax.grad(loss))(q)

    def rms_case():
        x = jnp.ones((64, 1024), jnp.bfloat16)
        w = jnp.ones((1024,), jnp.bfloat16)

        def loss(x, w):
            return jnp.sum(kernels.fused_rms_norm(
                x, w, 1e-6, 64, False).astype(jnp.float32))
        return jax.jit(jax.grad(loss, argnums=(0, 1)))(x, w)

    try_kernel("flash", flash_case)
    try_kernel("rms", rms_case)
    if failures:
        sys.stderr.write(f"kernel preflight failures: {failures}\n")
        # re-register only the kernels that survived preflight
        kernels.unregister()
        kernels.register(flash="flash" not in failures,
                         rms="rms" not in failures, tpu_only=True)
    return failures


def main():
    metric = "llama_train_tokens_per_sec_per_chip"
    try:
        if "--smoke" in sys.argv:
            # CPU smoke: don't claim the shared TPU chip.
            import jax
            jax.config.update("jax_platforms", "cpu")
        import jax
        import jax.numpy as jnp

        dev = _probe_backend()
        from paddle_tpu import kernels
        from paddle_tpu.models import llama as L
    except Exception as e:
        _fail(metric, f"{type(e).__name__}: {e}")
        return

    on_tpu = dev.platform in ("tpu", "axon") or "TPU" in (dev.device_kind or "")
    # The axon tunnel's chipless compile helper needs the accelerator type
    # spelled out or it can bail with exit code 1 on large programs.
    if on_tpu and "v5 lite" in (dev.device_kind or "").lower():
        os.environ.setdefault("TPU_ACCELERATOR_TYPE", "v5litepod-1")

    # Single-chip benchmark ladder: 8B-shaped decoder slices sized to one
    # chip's HBM (v5e = 16G: f32 adam moments cap the param count at ~1.1B;
    # "full" remat because "dots" blows the compile-time HBM plan). Each rung
    # is tried in order; a rung that OOMs or fails to compile steps down so
    # a memory regression degrades the number instead of zeroing it.
    if on_tpu:
        ladder = [
            (dict(num_hidden_layers=4, vocab_size=32000,
                  remat_policy="full"), 4, 2048, 20),
            (dict(num_hidden_layers=3, vocab_size=32000,
                  remat_policy="full"), 2, 2048, 20),
            (dict(num_hidden_layers=2, vocab_size=16000,
                  remat_policy="full"), 2, 1024, 10),
        ]
    else:
        ladder = [(None, 4, 128, 5)]

    preflight = _preflight_kernels(on_tpu)

    last_err = None
    for cfg_kw, batch, seq, iters in ladder:
        if cfg_kw is None:
            cfg = L.llama_tiny(num_hidden_layers=2, dtype=jnp.bfloat16)
        else:
            cfg = L.llama_3_8b(**cfg_kw)
        try:
            # One jitted program builds params + opt state directly on device.
            @jax.jit
            def init():
                p = L.init_params(cfg, jax.random.PRNGKey(0))
                return p, L.adamw_init(p)

            params, opt_state = init()
            jax.block_until_ready(params["embed"])

            step = L.make_train_step(cfg, lr=1e-4)
            ids = jnp.asarray(np.random.default_rng(0).integers(
                0, cfg.vocab_size, (batch, seq + 1)), jnp.int32)

            # warmup/compile — and record which attention kernel got traced in
            kernels.reset_dispatch_stats()
            params, opt_state, loss = step(params, opt_state, ids)
            float(loss)  # hard sync: block_until_ready is unreliable via axon
            stats = kernels.dispatch_stats()
            flash_missed = on_tpu and stats["flash"] == 0
            if flash_missed:
                # Fast path missed: still bench, but flag it in the JSON line
                # (not just stderr) so the record shows the degraded path.
                sys.stderr.write(
                    f"WARNING: pallas flash kernel did not engage: {stats}\n")

            t0 = time.perf_counter()
            for _ in range(iters):
                params, opt_state, loss = step(params, opt_state, ids)
            final_loss = float(loss)  # device->host fetch = full pipeline drain
            dt = time.perf_counter() - t0
            break
        except Exception as e:
            last_err = f"{type(e).__name__}: {e}"
            sys.stderr.write(
                f"bench rung {cfg_kw} failed, stepping down: {last_err[:300]}\n")
            # Release the failed rung's HBM (params + adam moments) and its
            # executable before trying a smaller rung.
            params = opt_state = step = init = ids = loss = None
            jax.clear_caches()
    else:
        _fail(metric, f"all bench rungs failed; last: {last_err}")
        return

    tokens = batch * seq * iters
    tps = tokens / dt
    # 6ND (fwd+bwd) -> standard MFU (remat recompute not credited)
    n_params = L.count_params(cfg)
    flops_per_token = 6 * n_params
    peak = _peak_flops(dev) if on_tpu else 1e12   # CPU nominal
    mfu = tps * flops_per_token / peak
    payload = {
        "metric": metric,
        "value": round(tps, 2),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {"mfu": round(mfu, 4), "params": n_params,
                  "platform": dev.platform, "batch": batch, "seq": seq,
                  "layers": cfg.num_hidden_layers,
                  "vocab": cfg.vocab_size,
                  "flash_dispatch": stats,
                  "loss": final_loss},
    }
    if preflight:
        payload["extra"]["kernel_preflight_failures"] = preflight
    if flash_missed:
        payload["warning"] = "pallas flash kernel did not engage (XLA fallback)"
    _emit(payload)


if __name__ == "__main__":
    main()
