"""Benchmark: Llama decoder training throughput on the available device.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Metric: training tokens/sec on a Llama block stack sized to fit the chip,
plus model FLOPs utilisation (MFU) computed from the 6*N*tokens estimate.
vs_baseline is MFU / 0.40 (BASELINE.json north star: >=40% MFU).

Un-hangable by construction (round-3 lesson: BENCH_r03 was rc=124 because
only backend *init* had a watchdog while compile/run/`float(loss)` could
block forever through a dead tunnel relay):

1. A daemon watchdog THREAD (not SIGALRM — a signal handler cannot
   interrupt a blocked PJRT C call, but a thread can ``os._exit``) enforces
   a global deadline plus per-stage budgets (init / preflight / compile /
   timed loop). On expiry it prints the JSON failure line naming the stage
   that hung, flushes, and exits. Every path emits exactly one JSON line.
2. Before claiming the TPU, the axon tunnel relay is probed with a 2s TCP
   connect to its known loopback ports. A dead relay fails in seconds with
   a structured error instead of a 25-minute hang into rc=124.
3. On TPU, at most TWO ladder rungs are attempted (first choice + one
   fallback) so a degraded tunnel can't triple the hang exposure.

Param/optimizer init runs inside a single jitted program (no eager
op-by-op device traffic). The run records whether the Pallas
flash-attention kernel actually engaged at the bench shapes
(kernels.dispatch_stats) and flags a fallback in the JSON output so a
silent fallback can't quietly cost MFU unnoticed.
"""
import json
import os
import socket
import sys
import threading
import time

import numpy as np

# ---------------------------------------------------------------------------
# Watchdog: global + per-stage deadlines enforced from a daemon thread.
# ---------------------------------------------------------------------------

_T0 = time.monotonic()
try:
    _GLOBAL_DEADLINE_S = float(os.environ.get("BENCH_DEADLINE_S", "840"))
except ValueError:   # bad override must not crash before the JSON line
    _GLOBAL_DEADLINE_S = 840.0   # 14 min
_EMIT_LOCK = threading.Lock()
_EMITTED = False
_STAGE = {"name": "startup", "deadline": _T0 + _GLOBAL_DEADLINE_S}
_METRIC = "llama_train_tokens_per_sec_per_chip"


def _host_block():
    """Host attribution stamped into EVERY bench JSON ``extra`` block:
    container CPU-quota swings (nproc) explain wall-clock movement that
    is not a code regression — ROADMAP's standing "check nproc before
    concluding regression" ask, made machine-readable."""
    import platform as _platform
    blk = {"nproc": os.cpu_count(), "machine": _platform.machine(),
           "hostname": socket.gethostname()}
    try:
        jx = sys.modules.get("jax")
        if jx is not None:
            blk["jax_backend"] = str(jx.default_backend())
    except Exception:                           # noqa: BLE001
        pass
    blk["class"] = "tpu" if str(blk.get("jax_backend", "")).startswith(
        ("tpu",)) else "cpu"
    return blk


def _emit(payload):
    """Print the single JSON result line (exactly once, race-safe)."""
    global _EMITTED
    with _EMIT_LOCK:
        if _EMITTED:
            return False
        _EMITTED = True
    try:
        payload.setdefault("extra", {})["host"] = _host_block()
    except Exception:                           # noqa: BLE001
        pass                 # attribution must never eat the result line
    print(json.dumps(payload))
    sys.stdout.flush()
    return True


def _fail(msg, **extra):
    payload = {"metric": _METRIC, "value": 0.0, "unit": "tokens/s",
               "vs_baseline": 0.0, "error": msg[-2000:],
               "elapsed_s": round(time.monotonic() - _T0, 1)}
    if extra:
        payload["extra"] = extra
    # NOTE: this record means the CURRENT run FAILED (value 0.0). The
    # pointer below names an UNVERIFIED self-measured result from an
    # earlier session, kept only so a reader can find the provenance
    # trail — it says nothing about this run's health.
    self_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_SELF_r03.json")
    if os.path.exists(self_path):
        payload["see_also"] = (
            "THIS RUN FAILED (value=0.0). BENCH_SELF_r03.json is an "
            "unverified, self-measured on-chip result from an earlier "
            "session (45.75% MFU, recorded before a tunnel outage); it "
            "does not reflect the current run.")
    _emit(payload)


def _stage(name, budget_s):
    """Enter a named stage with its own time budget (watchdog-enforced)."""
    # Deadline BEFORE name: the watchdog polls without a lock, and the new
    # name paired with an already-expired old deadline would kill a
    # healthy run at a stage boundary.
    _STAGE["deadline"] = min(time.monotonic() + budget_s,
                             _T0 + _GLOBAL_DEADLINE_S)
    _STAGE["name"] = name


# Once the DENSE rung has a measured result, it is staged here; a
# watchdog firing in a later optional stage (the MoE rung) must emit
# the measured headline number, not zero it.
_PARTIAL = {"payload": None}


def _watchdog_fire():
    """Emit on deadline expiry: the staged headline snapshot if the
    dense rung already measured (a late optional stage must not zero
    the run), else the failure record. Unit-tested directly; the loop
    below only adds the timer and the os._exit."""
    partial = _PARTIAL["payload"]
    if partial is not None:
        partial.setdefault("extra", {})["late_stage_timeout"] = (
            f"stage '{_STAGE['name']}' exceeded its deadline "
            "after the headline measurement completed")
        _emit(partial)
    else:
        _fail(f"deadline exceeded in stage '{_STAGE['name']}' "
              f"(global budget {_GLOBAL_DEADLINE_S:.0f}s); the "
              f"bench process was killed by its own watchdog "
              f"instead of hanging into the driver's timeout",
              stage=_STAGE["name"])


def _watchdog():
    while True:
        time.sleep(1.0)
        now = time.monotonic()
        if now > _STAGE["deadline"]:
            _watchdog_fire()
            sys.stdout.flush()
            sys.stderr.flush()
            os._exit(2)


def _arm_watchdog():
    # Armed from main(), not at import: importing bench (e.g. in a unit
    # test) must not schedule an os._exit or a spurious JSON line.
    threading.Thread(target=_watchdog, daemon=True).start()


# ---------------------------------------------------------------------------
# Tunnel relay liveness probe.
# ---------------------------------------------------------------------------

# Loopback ports the axon tunnel relay listens on (observed from the relay
# process; stable across sessions). One open port == relay alive. An
# unrelated listener on these ports would defeat the probe, but in this
# container they belong to the relay alone — and a false "alive" is still
# bounded by the backend-init stage budget, just slower to diagnose.
_RELAY_PORTS = (8082, 8083, 8087, 8102, 8103, 8107, 8112, 8113, 8117)


def _axon_tunnel_expected():
    """True when this process will try to reach the TPU through the axon
    loopback relay (sitecustomize registers the 'axon' PJRT plugin)."""
    return bool(os.environ.get("PALLAS_AXON_POOL_IPS")) and \
        "axon" in os.environ.get("JAX_PLATFORMS", "")


def _relay_alive(timeout=2.0):
    for port in _RELAY_PORTS:
        try:
            socket.create_connection(("127.0.0.1", port),
                                     timeout=timeout).close()
            return True
        except OSError:
            continue
    return False


def _peak_flops(dev) -> float:
    """bf16 peak FLOP/s per chip (monitor/mfu.py owns the table now;
    PADDLE_TPU_PEAK_FLOPS overrides — the CPU-smoke denominator)."""
    from paddle_tpu.monitor import mfu as _mfu
    return _mfu.peak_flops(dev)


def _probe_backend(retries=2, delay=5.0):
    """Initialize the jax backend with retries (shared-TPU tunnel can be
    transiently unavailable). The watchdog thread bounds a hang; this
    only needs to turn init *errors* into retries."""
    import jax

    last = None
    for i in range(retries):
        try:
            return jax.devices()[0]
        except Exception as e:
            last = e
            time.sleep(delay * (i + 1))
    raise RuntimeError(f"backend init failed after {retries} tries: {last}")


def _autotune_setup():
    """Driver-bench autotune policy: NEVER measure (candidate sweeps are
    minutes of pallas compiles that would run inside the watchdog-budgeted
    trace; a tunnel hang there is not an Exception and would zero the
    run). Instead read the tuned blocks committed by scripts/tpu_smoke.py
    into the repo cache; a cache miss silently uses the known-good
    128/128 defaults."""
    os.environ.setdefault("PADDLE_TPU_AUTOTUNE", "cached")
    repo_cache = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "autotune_cache.json")
    if os.path.exists(repo_cache):
        os.environ.setdefault("PADDLE_TPU_AUTOTUNE_CACHE", repo_cache)


def _autotune_summary():
    """The block choices this process's dispatches actually used."""
    try:
        from paddle_tpu.kernels import autotune as _at
        return _at.used_blocks()
    except Exception:
        return {}


def _enable_monitor():
    """Turn on the runtime metrics registry for this bench process
    (PADDLE_TPU_BENCH_MONITOR=0 opts out). Failure is never fatal —
    metrics are a reporting extra, not a bench dependency."""
    if os.environ.get("PADDLE_TPU_BENCH_MONITOR", "1") == "0":
        return
    try:
        from paddle_tpu.core import flags as _pt_flags
        _pt_flags.set_flags({"enable_monitor": True})
    except Exception as e:                      # noqa: BLE001
        sys.stderr.write(f"monitor unavailable: {e}\n")


def _metrics_summary():
    """Monitor snapshot distilled for the JSON line — compile counts,
    cache hit rates, peak tensor bytes — plus the full run-id-keyed
    snapshot (paddle_tpu.monitor.dump_json) for offline digging."""
    try:
        from paddle_tpu import monitor
        if not monitor.enabled():
            return {"disabled": True}
        snap = monitor.snapshot()
        c = snap.get("counters", {})
        g = snap.get("gauges", {})
        hits, misses = c.get("jit.cache.hit", 0), c.get("jit.cache.miss", 0)
        at_h = c.get("autotune.cache.hit", 0)
        at_m = c.get("autotune.cache.miss", 0)
        h = snap.get("histograms", {})
        return {
            "compile_count": misses,
            "jit_cache_hit_rate": round(hits / (hits + misses), 4)
            if hits + misses else None,
            "autotune_cache_hit_rate": round(at_h / (at_h + at_m), 4)
            if at_h + at_m else None,
            "peak_tensor_bytes": g.get("tensor.bytes.peak"),
            # fault-tolerant checkpoint layer (distributed/checkpoint):
            # zeros when the bench run never checkpointed
            "checkpoint": {
                "saves": c.get("ckpt.saves", 0),
                "save_bytes": c.get("ckpt.save.bytes", 0),
                "commit_failures": c.get("ckpt.commit.failures", 0),
                "restore_fallbacks": c.get("ckpt.restore.fallbacks", 0),
                "gc_deleted": c.get("ckpt.gc.deleted", 0),
                "gc_debris": c.get("ckpt.gc.debris", 0),
                "save_duration_ms": h.get("ckpt.save.duration_ms"),
            },
            # paged serving engine (inference/engine.py): page-pool and
            # batch-occupancy health of the serving_paged rung
            "serving": {
                "pages_total": g.get("serving.pages.total"),
                "pages_in_use": g.get("serving.pages.in_use"),
                "batch_occupancy": g.get("serving.batch.occupancy"),
                "queue_depth": g.get("serving.queue.depth"),
                "admitted": c.get("serving.requests.admitted", 0),
                "completed": c.get("serving.requests.completed", 0),
                "preempted": c.get("serving.requests.preempted", 0),
                "tokens_generated": c.get("serving.tokens.generated", 0),
                "tokens_prefilled": c.get("serving.tokens.prefilled", 0),
                "tokens_discarded": c.get("serving.tokens.discarded", 0),
                # SLO distributions (count/min/max/avg + interpolated
                # p50/p90/p95/p99) fed by the serving_paged rung
                "latency": {
                    name: h.get(f"serving.latency.{name}")
                    for name in ("queue_wait_ms", "ttft_ms",
                                 "tpot_ms", "e2e_ms")
                },
            },
            # sequence-packed training (io/packing.py + the segment
            # flash kernel): pack efficiency, block skipping, and the
            # varlen dispatch counters of the training_packed rung
            "packing": {
                "efficiency": g.get("packing.efficiency"),
                "blocks_skipped": g.get("packing.blocks.skipped"),
                "blocks_total": g.get("packing.blocks.total"),
                "tokens_real": c.get("packing.tokens.real", 0),
                "tokens_padding": c.get("packing.tokens.padding", 0),
                "varlen_dispatch": _varlen_dispatch_counters(),
            },
            # numerics plane (monitor/numerics.py): per-layer grad
            # stats, worst-layer attribution, quantization SQNR audit,
            # KV-page absmax — zeros/None when the run never enabled
            # FLAGS_enable_numerics or sampled KV pages
            "numerics": _numerics_block(),
            # SLO accounting plane (monitor/slo.py): p99 TTFT/TPOT the
            # regression guard's lower-is-better rungs read, windowed
            # compliance + burn rates, tenant count, autoscale signals
            "slo": _slo_block(),
            # fleet SLO federation (monitor/federation.py): frames the
            # serving rung's replica published + the federated verdict
            "federation": _federation_block(),
            # request forensics plane (monitor/forensics.py): timeline
            # store occupancy, scheduler decision counts, and the
            # violation-cause attribution over the run's requests
            "forensics": _forensics_block(),
            # operator plane (monitor/memory.py + monitor/programs.py):
            # HBM occupancy at end of run (empty on backends that
            # report nothing — never fabricated) and the compiled-
            # program introspection registry's totals
            "hbm": monitor.memory.update_hbm_gauges()["totals"],
            "programs": {
                "count": len(monitor.programs.programs_snapshot()),
                "flops_total": c.get("jit.program.flops", 0),
            },
            # comm + roofline attribution (monitor/roofline.py): runs
            # the bounded pending analyses so collective counts exist,
            # then condenses to the operator-facing numbers — full
            # per-program detail stays on the /roofline endpoint
            "roofline": _roofline_block(),
            "snapshot": monitor.dump_json(
                run_id=f"bench-{os.getpid()}-{int(time.time())}"),
        }
    except Exception as e:                      # noqa: BLE001
        return {"error": f"{type(e).__name__}: {e}"[:200]}


# Per-rung measured execution-time distributions (filled by the
# headline/decode rungs, emitted as extra.metrics.exec): the MEASURED
# side of the performance plane — a few explicitly timed
# dispatch->outputs-ready executions of the already-compiled step,
# taken AFTER each rung's throughput windows so the async pipeline the
# rung measures stays unperturbed.
_EXEC_BLOCK: dict = {}


def _exec_summary(ms_list):
    """{samples, p50_ms, p99_ms, mean_ms, max_ms} of a measured
    exec-ms list (with few samples the p99 degrades toward max — the
    sample count is in the block so readers can judge)."""
    srt = sorted(float(m) for m in ms_list)
    return {
        "samples": len(srt),
        "p50_ms": round(float(np.percentile(srt, 50)), 3),
        "p99_ms": round(float(np.percentile(srt, 99)), 3),
        "mean_ms": round(sum(srt) / len(srt), 3),
        "max_ms": round(srt[-1], 3),
    }


def _measured_exec(name, fn, n=5):
    """n explicitly timed executions of ``fn`` through
    monitor.exectime.time_call (block-until-ready discipline), summarized
    for extra.metrics.exec. Failure degrades to an error entry."""
    try:
        from paddle_tpu.monitor import exectime as _et
        ms = []
        for _ in range(int(n)):
            _, one = _et.time_call(("bench", name), fn)
            ms.append(one)
        return _exec_summary(ms)
    except Exception as e:                      # noqa: BLE001
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def _roofline_block():
    try:
        from paddle_tpu.monitor import roofline as _roofline
        rs = _roofline.roofline_snapshot(analyze=True, max_analyze=8)
        peaks = rs["peaks"]
        return {
            "peak_hbm_bytes_per_sec": peaks["peak_hbm_bytes_per_sec"],
            "hbm_source": peaks["hbm_source"],
            "ridge_point_flops_per_byte":
                peaks["ridge_point_flops_per_byte"],
            "programs_classified": len(
                [p for p in rs["programs"] if p["verdict"]]),
            "verdict_counts": rs["attribution"]["verdict_counts"],
            "comm_fraction": rs["attribution"]["comm_fraction"],
            "dominant": rs["attribution"]["dominant"],
            "comm": rs["comm"],
        }
    except Exception as e:                      # noqa: BLE001
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def _numerics_block():
    """extra.metrics.numerics: the numerics plane condensed — step
    coverage, worst layer, the quant audit's floor SQNR, KV-page
    absmax distribution bounds. Full per-tensor detail stays on the
    /numerics endpoint."""
    try:
        from paddle_tpu.monitor import numerics as _nm
        snap = _nm.numerics_snapshot(n=0)
        kv = snap["kv"]
        quant = snap["quant"] or {}
        return {
            "steps": snap["total_steps"],
            "tensors_tracked": len(snap["tensors"]),
            "worst_layer": snap["worst_layer"],
            "top_movers": snap["top_movers"][:3],
            "quant_tensors": len(quant.get("tensors", {})),
            "quant_min_sqnr_db": quant.get("min_sqnr_db"),
            "kv_samples": kv["samples"],
            "kv_pages": kv["pages"],
            "kv_absmax_max": kv["max"],
        }
    except Exception as e:                      # noqa: BLE001
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def _slo_block():
    """extra.metrics.slo: the SLO accounting plane condensed. The
    ``ttft_p99_ms``/``tpot_p99_ms`` rungs are the serving latency
    histograms' interpolated p99s (post-warmup observations — the
    serving rung resets them after compile warmup), the lower-is-
    better floors ``scripts/check_bench_regression.py`` guards. Full
    per-tenant detail stays on the ``/slo`` endpoint."""
    try:
        from paddle_tpu import monitor
        from paddle_tpu.monitor import slo as _slo
        reg = monitor.registry()

        def _p99(name):
            h = reg.get(f"serving.latency.{name}")
            if h is None or not h.count:
                return None
            v = h.quantile(0.99)
            return round(v, 3) if v is not None else None

        rep = _slo.compliance_report()
        tenants = _slo.tenants_snapshot()
        return {
            "ttft_p99_ms": _p99("ttft_ms"),
            "tpot_p99_ms": _p99("tpot_ms"),
            "e2e_p99_ms": _p99("e2e_ms"),
            "objectives": {k: v["objective"]
                           for k, v in rep["objectives"].items()},
            "compliance": {k: v["compliance"]
                           for k, v in rep["objectives"].items()},
            "burn_slow": {k: v["burn_slow"]
                          for k, v in rep["objectives"].items()},
            "alerting": rep["alerting"],
            "window_requests": rep["window"]["size"],
            "tenants": len(tenants["tenants"]),
            "autoscale": _slo.update_autoscale_gauges(),
        }
    except Exception as e:                      # noqa: BLE001
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def _forensics_block():
    """extra.metrics.forensics: the request forensics plane condensed —
    timeline-store occupancy, per-kind scheduler decision counts, and
    the SLO violation-cause attribution table. Full timelines stay on
    the ``/forensics`` and ``/requests/<rid>`` endpoints."""
    try:
        from paddle_tpu.monitor import forensics as _forensics
        p = _forensics.forensics_payload(slowest_n=4)
        return {
            "tracked": p["tracked"],
            "evicted": p["evicted"],
            "terminal_by_state": p["terminal_by_state"],
            "decisions_by_kind": p["decisions"]["by_kind"],
            "attribution": p["attribution"],
            "slowest": p["slowest"],
        }
    except Exception as e:                      # noqa: BLE001
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def _federation_block():
    """extra.metrics.federation: the fleet SLO federation condensed —
    which replicas published frames this run and the last federated
    verdict (alerting objectives, summed demand, worst burner). The
    serving rung attaches a local-only publisher, so single-process
    bench runs still exercise the frame path end to end."""
    try:
        from paddle_tpu.monitor import federation as _fed
        snap = _fed.fleet_serving_snapshot()
        rep = snap.get("report")
        if not snap.get("frames"):
            return {"available": False}
        out = {
            "available": True,
            "replicas": sorted(snap["frames"]),
            "frames_seq": {n: f.get("seq")
                           for n, f in snap["frames"].items()},
        }
        if rep:
            att = rep.get("attribution") or []
            out["alerting"] = rep.get("alerting")
            out["demand_estimate_sum"] = (rep.get("demand") or {}) \
                .get("demand_estimate_sum")
            out["worst_replica"] = att[0]["replica"] if att else None
        return out
    except Exception as e:                      # noqa: BLE001
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def _varlen_dispatch_counters():
    try:
        from paddle_tpu import kernels
        stats = kernels.dispatch_stats()
        return {k: stats[k] for k in ("varlen", "varlen_fallback")}
    except Exception:
        return {}


def _sentinel_train_step(make, cfg, **kw):
    """Build a family's train step honoring ``FLAGS_enable_sentinel``
    and return ``(uniform 3-in/3-out callable, guarded?)``. Guarded,
    the bench drives the in-graph gate with the cap at +inf — the
    device-side guard cost (norm reduction + predicated update) IS
    what the <2%-regression acceptance measures; the host policy
    engine never sits in a timed loop."""
    from paddle_tpu.core import flags as _f
    step = make(cfg, **kw)
    if not _f.flag_value("enable_sentinel"):
        return step, False
    import jax.numpy as jnp
    cap = jnp.asarray(float("inf"), jnp.float32)

    def run(params, opt_state, batch):
        params, opt_state, loss, _health = step(params, opt_state,
                                                batch, cap)
        return params, opt_state, loss
    # keep monitor.mfu.lowered_flops working on the wrapper: forward
    # .lower to the underlying jitted step (cap appended) so the MFU
    # block stays nonzero on the guarded path
    run.lower = lambda p, o, b: step.lower(p, o, b, cap)
    return run, True


def _preflight_kernels(on_tpu):
    """Lower + run each Pallas kernel standalone (fwd AND bwd) at tiny
    shapes before the timed loop. A kernel that fails de-registers itself
    so the model traces the XLA fallback — a kernel bug costs MFU, never
    the whole bench number (BENCH_r02 recorded 0.0 because a lowering
    error inside the first train step killed everything)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu import kernels

    if not on_tpu:
        return {}
    failures = {}

    def try_kernel(name, fn):
        try:
            jax.block_until_ready(fn())
        except Exception as e:
            failures[name] = f"{type(e).__name__}: {e}"[:500]

    def flash_case():
        q = jnp.ones((1, 256, 2, 128), jnp.bfloat16)

        def loss(q):
            return jnp.sum(kernels.flash_attention(
                q, q, q, causal=True, interpret=False).astype(jnp.float32))
        return jax.jit(jax.grad(loss))(q)

    def rms_case():
        x = jnp.ones((64, 1024), jnp.bfloat16)
        w = jnp.ones((1024,), jnp.bfloat16)

        def loss(x, w):
            return jnp.sum(kernels.fused_rms_norm(
                x, w, 1e-6, 64, False).astype(jnp.float32))
        return jax.jit(jax.grad(loss, argnums=(0, 1)))(x, w)

    try_kernel("flash", flash_case)
    try_kernel("rms", rms_case)
    if failures:
        sys.stderr.write(f"kernel preflight failures: {failures}\n")
        # re-register only the kernels that survived preflight
        kernels.unregister()
        kernels.register(flash="flash" not in failures,
                         rms="rms" not in failures, tpu_only=True)
    return failures


def main():
    try:
        _main()
    except BaseException as e:   # every path must emit the one JSON line
        _fail(f"unhandled {type(e).__name__}: {e}")
        raise


def _enable_compile_cache():
    """Persist XLA executables across bench processes. The first compile
    of the rung-1 train step through the tunnel can eat most of the
    init+compile budget; a warm cache turns the driver's re-run into a
    deserialize. Failure to enable is never fatal (a custom PJRT plugin
    may not support executable serialization — entries just don't land).
    Opt out with PADDLE_TPU_COMPILE_CACHE=0."""
    if os.environ.get("PADDLE_TPU_COMPILE_CACHE", "1") == "0":
        return
    try:
        import jax

        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get("PADDLE_TPU_COMPILE_CACHE_DIR",
                           os.path.join(os.path.expanduser("~"), ".cache",
                                        "paddle_tpu", "xla_cache")))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:                      # noqa: BLE001
        sys.stderr.write(f"compile cache unavailable: {e}\n")


def _main():
    smoke = "--smoke" in sys.argv
    _arm_watchdog()
    _enable_compile_cache()
    # Before ANY paddle_tpu import: the autotune cache path env var must
    # be in place when modules first load (the cache also resolves its
    # path lazily now, but ordering here keeps the policy obvious).
    _autotune_setup()

    _stage("relay-probe", 30)
    # Probe even under --smoke: when the axon sitecustomize has registered
    # the tunnel plugin, backend init blocks on a dead relay even for the
    # CPU platform (see memory: axon-tunnel-failure-modes) — fail fast
    # rather than burn the init budget.
    if _axon_tunnel_expected() and not _relay_alive():
        _fail("tpu tunnel relay dead: no relay loopback port "
              f"{_RELAY_PORTS[0]}-{_RELAY_PORTS[-1]} accepts connections; "
              "refusing to touch the backend (init would hang). "
              "Re-run when the tunnel is restored.")
        return

    _stage("backend-init", 180)
    try:
        if smoke:
            # CPU smoke: don't claim the shared TPU chip.
            import jax
            jax.config.update("jax_platforms", "cpu")
        import jax
        import jax.numpy as jnp

        dev = _probe_backend()
        from paddle_tpu import kernels
        from paddle_tpu.models import llama as L
        _enable_monitor()
    except Exception as e:
        _fail(f"{type(e).__name__}: {e}")
        return

    on_tpu = dev.platform in ("tpu", "axon") or "TPU" in (dev.device_kind or "")
    # The axon tunnel's chipless compile helper needs the accelerator type
    # spelled out or it can bail with exit code 1 on large programs.
    if on_tpu and "v5 lite" in (dev.device_kind or "").lower():
        os.environ.setdefault("TPU_ACCELERATOR_TYPE", "v5litepod-1")

    # Single-chip benchmark ladder: 8B-shaped decoder slices sized to one
    # chip's HBM (v5e = 16G). Rung 1 is the measured round-5 optimum:
    # 4 layers with "dots" remat (backward recomputes no matmuls) and the
    # plain einsum+xent loss — at 32k vocab / 4 layers there is HBM
    # headroom, and the materialized-logits loss measured FASTER than the
    # blockwise fused CE scan (18.9k vs 18.3k tok/s on-chip; the fused
    # path wins when HBM is tight or vocab is large, as in rung 2 and the
    # MoE rung). "dots" at 6 layers over-commits HBM and the tunnel's
    # remote-compile helper rejects it, so rung 2 is the proven 6-layer
    # "full"-remat fused-CE config (55.2% MFU on-chip) — a rung-1
    # regression degrades the number instead of zeroing it. On TPU at
    # most TWO rungs run — a degraded tunnel can't stack compile hangs.
    if on_tpu:
        ladder = [
            (dict(num_hidden_layers=4, vocab_size=32000,
                  remat_policy="dots", fused_ce=False), 4, 2048, 20,
             "bfloat16"),
            (dict(num_hidden_layers=6, vocab_size=32000,
                  remat_policy="full"), 4, 2048, 20, "bfloat16"),
        ]
    else:
        ladder = [(None, 4, 128, 5, "float32")]

    _stage("kernel-preflight", 150)
    preflight = _preflight_kernels(on_tpu)

    last_err = None
    for cfg_kw, batch, seq, iters, moments in ladder:
        if cfg_kw is None:
            cfg = L.llama_tiny(num_hidden_layers=2, dtype=jnp.bfloat16)
        else:
            cfg = L.llama_3_8b(**cfg_kw)
        mdt = jnp.bfloat16 if moments == "bfloat16" else jnp.float32
        try:
            _stage("init+compile", 480)
            # One jitted program builds params + opt state directly on device.
            @jax.jit
            def init():
                p = L.init_params(cfg, jax.random.PRNGKey(0))
                return p, L.adamw_init(p, moment_dtype=mdt)

            params, opt_state = init()
            jax.block_until_ready(params["embed"])

            step, guarded = _sentinel_train_step(L.make_train_step, cfg,
                                                 lr=1e-4)
            ids = jnp.asarray(np.random.default_rng(0).integers(
                0, cfg.vocab_size, (batch, seq + 1)), jnp.int32)

            # warmup/compile — and record which attention kernel got traced in
            kernels.reset_dispatch_stats()
            params, opt_state, loss = step(params, opt_state, ids)
            float(loss)  # hard sync: block_until_ready is unreliable via axon
            stats = kernels.dispatch_stats()
            flash_missed = on_tpu and stats["flash"] == 0
            if flash_missed:
                # Fast path missed: still bench, but flag it in the JSON line
                # (not just stderr) so the record shows the degraded path.
                sys.stderr.write(
                    f"WARNING: pallas flash kernel did not engage: {stats}\n")

            _stage("timed-loop", 240)
            # two independent timed windows: the r3 stability ask —
            # a single sample can't show run-to-run variance, two
            # back-to-back windows bound it in one bench invocation.
            # Each window is one StepTimer compute phase (closed AFTER
            # the drain so async dispatch isn't mistaken for compute),
            # so the goodput block in extra.metrics reports the same
            # tokens/s the headline does, through the production seam.
            from paddle_tpu import monitor as _pt_monitor
            stim = _pt_monitor.StepTimer("bench.headline")
            t0 = time.perf_counter()
            with stim.compute():
                for _ in range(iters):
                    params, opt_state, loss = step(params, opt_state, ids)
                float(loss)           # drain before closing window 1
            stim.end_step(useful_tokens=batch * seq * iters)
            t1 = time.perf_counter()
            with stim.compute():
                for _ in range(iters):
                    params, opt_state, loss = step(params, opt_state, ids)
                # device->host fetch = pipeline drain
                final_loss = float(loss)
            stim.end_step(useful_tokens=batch * seq * iters)
            t2 = time.perf_counter()
            window_dts = [t1 - t0, t2 - t1]
            iters *= 2
            dt = t2 - t0
            goodput_report = stim.report()
            break
        except Exception as e:
            last_err = f"{type(e).__name__}: {e}"
            sys.stderr.write(
                f"bench rung {cfg_kw} failed, stepping down: {last_err[:300]}\n")
            # Release the failed rung's HBM (params + adam moments) and its
            # executable before trying a smaller rung.
            params = opt_state = step = init = ids = loss = None
            jax.clear_caches()
    else:
        _fail(f"all bench rungs failed; last: {last_err}")
        return

    tokens = batch * seq * iters
    tps = tokens / dt
    # 6ND (fwd+bwd) -> standard MFU (remat recompute not credited)
    n_params = L.count_params(cfg)
    flops_per_token = 6 * n_params
    peak = _peak_flops(dev)   # CPU: 1e12 nominal or PADDLE_TPU_PEAK_FLOPS
    mfu = tps * flops_per_token / peak
    # MEASURED MFU: XLA's own cost analysis of the compiled train step
    # (re-trace + HLO lowering, no second compile) — credits remat
    # recompute, attention and loss flops the 6ND estimate misses.
    from paddle_tpu.monitor import mfu as _mfu_mod
    program_flops = _mfu_mod.lowered_flops(step, params, opt_state,
                                           ids) or 0.0
    _mfu_mod.record_program_flops(program_flops, source="bench")
    mfu_block = {
        "program_flops_per_step": program_flops,
        "steps_per_sec": round(iters / dt, 4),
        "achieved_flops_per_sec": round(program_flops * iters / dt, 2),
        "peak_flops_per_sec": peak,
        "mfu": round(_mfu_mod.mfu(program_flops, iters / dt, peak=peak),
                     6),
        "mfu_6nd": round(mfu, 6),
        "source": "xla_cost_analysis",
    }
    payload = {
        "metric": _METRIC,
        "value": round(tps, 2),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {"mfu": round(mfu, 4), "params": n_params,
                  "platform": dev.platform, "batch": batch, "seq": seq,
                  "layers": cfg.num_hidden_layers,
                  "vocab": cfg.vocab_size,
                  "moment_dtype": moments,
                  "tps_windows": [round(batch * seq * (iters // 2) / w, 2)
                                  for w in window_dts],
                  "window_spread_pct": round(
                      abs(window_dts[0] - window_dts[1])
                      / (dt / 2) * 100, 2),
                  "flash_dispatch": stats,
                  "autotune": _autotune_summary(),
                  # NaN/inf would make the line unparseable as strict JSON
                  "loss": final_loss if np.isfinite(final_loss)
                  else repr(final_loss),
                  "elapsed_s": round(time.monotonic() - _T0, 1)},
    }
    if preflight:
        payload["extra"]["kernel_preflight_failures"] = preflight
    if guarded:
        # the headline tokens/s was measured THROUGH the sentinel's
        # in-graph guard (gate + norm aux; cap at +inf)
        payload["extra"]["sentinel_guarded"] = True
    if flash_missed:
        payload["warning"] = "pallas flash kernel did not engage (XLA fallback)"

    # The headline number is now measured: stage a SNAPSHOT (not the
    # live dict — the MoE stage keeps mutating it, and the watchdog
    # thread must never serialize a dict mid-mutation) so a watchdog
    # firing in the optional MoE stage emits it instead of zeroing the
    # run.
    _PARTIAL["payload"] = dict(payload, extra=dict(payload["extra"]))

    # Measured exec-ms distribution of the headline train step
    # (extra.metrics.exec.headline), BEFORE the MoE stage releases the
    # step's HBM. Donated buffers force the rebind-through-a-box shape.
    _stage("exec-measure", 90)
    _exec_state = [params, opt_state]

    def _headline_once():
        p, o, loss_ = step(_exec_state[0], _exec_state[1], ids)
        _exec_state[0], _exec_state[1] = p, o
        return loss_

    _EXEC_BLOCK["headline"] = _measured_exec("headline", _headline_once,
                                             n=5)
    params, opt_state = _exec_state

    # Second flagship family: a DeepSeekMoE-shaped expert-parallel rung
    # (BASELINE.json config matrix). Measured after the dense rung
    # releases its HBM; failure degrades to an error entry in the JSON.
    try:
        _stage("moe-rung", 300)
        params = opt_state = step = init = ids = None
        jax.clear_caches()
        payload["extra"]["moe"] = _moe_rung(on_tpu, dev)
    except Exception as e:                      # noqa: BLE001
        payload["extra"]["moe"] = {
            "error": f"{type(e).__name__}: {e}"[:500]}

    # Serving rung: KV-cache greedy decode throughput on the 8B-shaped
    # slice (static ring cache, jit-once loop). Optional like the MoE
    # rung — failure degrades to an error entry.
    try:
        _stage("decode-rung", 240)
        jax.clear_caches()
        payload["extra"]["decode"] = _decode_rung(on_tpu)
    except Exception as e:                      # noqa: BLE001
        payload["extra"]["decode"] = {
            "error": f"{type(e).__name__}: {e}"[:500]}

    # Paged serving rung: the continuous-batching engine over a
    # MIXED-LENGTH request trace (paged KV cache + ragged attention) vs
    # the uniform-batch ring decode of the same trace. Optional.
    try:
        _stage("serving-paged-rung", 240)
        jax.clear_caches()
        payload["extra"]["serving_paged"] = _serving_paged_rung(on_tpu)
    except Exception as e:                      # noqa: BLE001
        payload["extra"]["serving_paged"] = {
            "error": f"{type(e).__name__}: {e}"[:500]}
    # Pin the guarded SLO block to the serving_paged rung's post-warmup
    # observations NOW: the trace-replay rung below runs more requests
    # through the same process-global latency histograms, and folding
    # those into extra.metrics.slo would silently change what the
    # lower-is-better ttft/tpot guard rungs measure between rounds.
    _slo_snapshot = _slo_block()

    # Trace-replay rung: the deterministic loadgen harness end to end —
    # seeded multi-tenant arrival trace + scripted overload burst
    # through the overload-policy engine, scored by the SLO scorecard
    # (loadgen/scorecard.py). Optional like the rungs above.
    try:
        _stage("serving-trace-replay-rung", 240)
        jax.clear_caches()
        payload["extra"]["serving_trace_replay"] = \
            _serving_trace_replay_rung(on_tpu)
    except Exception as e:                      # noqa: BLE001
        payload["extra"]["serving_trace_replay"] = {
            "error": f"{type(e).__name__}: {e}"[:500]}

    # Shared-prefix replay rung: the SAME pinned prefix-sharing trace
    # replayed with the radix KV cache off then on — the guard reads
    # cache-on p50 TTFT and the deterministic prefill-FLOPs-per-request
    # proxy (scripts/check_bench_regression.py, lower-is-better).
    try:
        _stage("serving-prefix-replay-rung", 240)
        jax.clear_caches()
        payload["extra"]["serving_prefix_replay"] = \
            _serving_prefix_replay_rung(on_tpu)
    except Exception as e:                      # noqa: BLE001
        payload["extra"]["serving_prefix_replay"] = {
            "error": f"{type(e).__name__}: {e}"[:500]}

    # Packed-training rung: a heavy-tailed document-length trace trained
    # sequence-PACKED (segment-masked flash attention, io/packing.py)
    # vs the SAME trace trained one-document-per-row padded. Equal
    # useful tokens on both sides — padding rows are exactly the waste
    # packing exists to reclaim. Optional like the rungs above.
    try:
        _stage("training-packed-rung", 240)
        jax.clear_caches()
        payload["extra"]["training_packed"] = _training_packed_rung(on_tpu)
    except Exception as e:                      # noqa: BLE001
        payload["extra"]["training_packed"] = {
            "error": f"{type(e).__name__}: {e}"[:500]}

    _stage("report", 30)
    # Re-capture the dispatch record now that every rung has traced:
    # the earlier snapshot (taken for the partial-payload safety copy)
    # misses the MoE and decode stages' block/chunk decisions.
    payload["extra"]["autotune"] = _autotune_summary()
    payload["extra"]["metrics"] = _metrics_summary()
    # the serving_paged-scoped snapshot captured before the trace
    # replay ran (see the comment at the capture site)
    payload["extra"]["metrics"]["slo"] = _slo_snapshot
    # the full trace-replay scorecard (deterministic + timing planes)
    try:
        from paddle_tpu.loadgen import last_scorecard as _last_card
        if _last_card() is not None:
            payload["extra"]["metrics"]["scorecard"] = _last_card()
    except Exception:                           # noqa: BLE001
        pass
    payload["extra"]["metrics"]["mfu"] = mfu_block
    payload["extra"]["metrics"]["goodput"] = goodput_report
    # per-rung measured exec-ms p50/p99 (the headline/decode programs)
    payload["extra"]["metrics"]["exec"] = dict(_EXEC_BLOCK)
    payload["extra"]["elapsed_s"] = round(time.monotonic() - _T0, 1)
    _emit(payload)


def _decode_one_batch(L, cfg, params, batch, prompt, new,
                      measure_exec=False):
    """Timed prefill + greedy decode scan at one batch size. Returns
    (decode_tps, decode_dt, prefill_dt, exec_ms_list-or-None);
    ``measure_exec`` adds a few explicitly timed decode executions for
    the extra.metrics.exec block (fresh same-shape caches, so donation
    is not in play)."""
    import time as _time

    import jax
    import jax.numpy as jnp
    from jax import lax

    ids = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (batch, prompt)), jnp.int32)
    M = prompt + new

    pf = jax.jit(lambda p, i: L.prefill(p, i, cfg, L.init_cache(
        cfg, batch, M)))

    def _decode_scan(p, cache, logits):
        def body(carry, _):
            cache, logits = carry
            tok = jnp.argmax(logits, -1).astype(jnp.int32)
            cache, logits = L.decode_step(p, cache, tok, cfg)
            return (cache, logits), tok
        (cache, logits), toks = lax.scan(body, (cache, logits), None,
                                         length=new)
        return toks.T

    dec = jax.jit(_decode_scan)

    cache, logits = pf(params, ids)               # compile + warmup
    float(logits[0, 0])
    t0 = _time.perf_counter()
    cache, logits = pf(params, ids)
    float(logits[0, 0])                           # axon-safe hard sync
    prefill_dt = _time.perf_counter() - t0

    toks = dec(params, cache, logits)             # compile + warmup
    float(toks[0, -1])
    cache2, logits2 = pf(params, ids)             # fresh same-shape cache
    float(logits2[0, 0])
    t0 = _time.perf_counter()
    toks = dec(params, cache2, logits2)
    float(toks[0, -1])
    dt = _time.perf_counter() - t0
    exec_ms = None
    if measure_exec:
        from paddle_tpu.monitor import exectime as _et
        exec_ms = []
        for _ in range(4):
            c3, l3 = pf(params, ids)
            float(l3[0, 0])
            _toks, one = _et.time_call(("bench", "decode"), dec,
                                       params, c3, l3)
            exec_ms.append(one)
    return batch * new / dt, dt, prefill_dt, exec_ms


def _decode_rung(on_tpu):
    """Greedy KV-cache decode throughput (models.llama generate path):
    batch x new-token throughput after a prompt prefill, swept over
    batch sizes so batch scaling is tracked per run (ROUND5_NOTES
    measured b16/b32 ad hoc; now every bench records them).
    Inference-mode config (no remat — no backward to rematerialise)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.models import llama as L

    if on_tpu:
        cfg = L.llama_3_8b(num_hidden_layers=4, vocab_size=32000,
                           remat=False)
        batches, prompt, new = (8, 16, 32), 128, 64
    else:
        cfg = L.llama_tiny(num_hidden_layers=2)
        batches, prompt, new = (2, 4), 8, 4

    params = jax.jit(lambda: L.init_params(cfg, jax.random.PRNGKey(0)))()
    jax.block_until_ready(params["embed"])

    batch = batches[0]
    tps, dt, prefill_dt, exec_ms = _decode_one_batch(
        L, cfg, params, batch, prompt, new, measure_exec=True)
    if exec_ms:
        _EXEC_BLOCK["decode"] = _exec_summary(exec_ms)
    out = {
        "config": f"llama_3_8b[{cfg.num_hidden_layers}L]" if on_tpu
        else "llama_tiny[2L]",
        "batch": batch, "prompt": prompt, "new_tokens": new,
        "decode_tokens_per_sec": round(tps, 2),
        "ms_per_token": round(dt / new * 1000, 3),
        "prefill_ms": round(prefill_dt * 1000, 1),
        "prefill_tokens_per_sec": round(batch * prompt / prefill_dt, 2),
    }
    # batch-scaling sweep: a failed larger batch (HBM/compile-helper
    # limits at b32 on some tunnels) records an error, never kills the
    # rung
    scaling = {}
    for b in batches[1:]:
        try:
            btps, _, _, _ = _decode_one_batch(L, cfg, params, b, prompt,
                                              new)
            scaling[f"b{b}"] = round(btps, 2)
        except Exception as e:                    # noqa: BLE001
            scaling[f"b{b}"] = f"FAIL: {type(e).__name__}: {e}"[:200]
        jax.clear_caches()
    out["batch_scaling_tokens_per_sec"] = scaling

    # Weight-only int8 serving variant: decode is HBM-bound, so int8
    # weights cut the dominant traffic (~1.4x measured). Optional —
    # failure records an error note, never kills the rung.
    try:
        qp = jax.jit(L.quantize_weights)(params)
        jax.block_until_ready(qp["layers"]["wq"]["q"])
        qtps, qdt, _, _ = _decode_one_batch(L, cfg, qp, batch, prompt,
                                            new)
        out["int8_decode_tokens_per_sec"] = round(qtps, 2)
        out["int8_ms_per_token"] = round(qdt / new * 1000, 3)
    except Exception as e:                        # noqa: BLE001
        out["int8_error"] = f"{type(e).__name__}: {e}"[:300]

    # Packed int4 weight-only variant: halves the weight bytes again
    # over int8 (two nibbles per byte, unpacked in-register at the
    # matmul). Same optional discipline as the int8 arm.
    try:
        qp4 = jax.jit(lambda p: L.quantize_weights(
            p, weight_dtype="int4"))(params)
        jax.block_until_ready(qp4["layers"]["wq"]["q4"])
        q4tps, q4dt, _, _ = _decode_one_batch(L, cfg, qp4, batch,
                                              prompt, new)
        out["int4_decode_tokens_per_sec"] = round(q4tps, 2)
        out["int4_ms_per_token"] = round(q4dt / new * 1000, 3)
    except Exception as e:                        # noqa: BLE001
        out["int4_error"] = f"{type(e).__name__}: {e}"[:300]
    return out


def _serving_paged_rung(on_tpu):
    """Mixed-length request trace through the continuous-batching
    engine (paged KV cache + ragged paged attention) vs the SAME trace
    served as uniform static batches on the ring-buffer path. Equal
    total generated tokens on both sides; the uniform side pays
    max-length padding for every request — exactly the waste paged
    serving exists to reclaim."""
    import time as _time

    import jax
    import jax.numpy as jnp

    from paddle_tpu.inference import Request, ServingEngine
    from paddle_tpu.models import llama as L

    if on_tpu:
        cfg = L.llama_3_8b(num_hidden_layers=4, vocab_size=32000,
                           remat=False)
        slots, page, n_req, chunk = 8, 16, 24, 4
        plens, glens = (32, 64, 96, 128), (16, 32, 48, 64)
    else:
        cfg = L.llama_tiny(num_hidden_layers=2)
        slots, page, n_req, chunk = 4, 4, 32, 8
        # heavy-tailed generation lengths — the serving distribution
        # paged batching exists for (uniform batching pays max_g for all)
        plens, glens = (4, 8, 16), (4, 8, 16, 64)

    params = jax.jit(lambda: L.init_params(cfg, jax.random.PRNGKey(0)))()
    jax.block_until_ready(params["embed"])
    rng = np.random.default_rng(42)
    # the shared loadgen trace construction (longest-generation-first
    # makespan ordering inside); passing the live rng preserves this
    # rung's historical draw sequence exactly — prompt tokens below
    # continue from where the trace draws left off
    from paddle_tpu.loadgen.traces import mixed_length_trace
    trace = mixed_length_trace(plens, glens, n_req, rng)
    max_p, max_g = max(p for p, _ in trace), max(g for _, g in trace)
    max_len = max_p + max_g
    useful = sum(g for _, g in trace)

    def reqs(base_rid=0):
        return [Request(rid=base_rid + i,
                        prompt=rng.integers(0, cfg.vocab_size,
                                            (p,)).astype(np.int32),
                        max_new_tokens=g)
                for i, (p, g) in enumerate(trace)]

    eng = ServingEngine(L, params, cfg, num_slots=slots,
                        max_len=max_len, page_size=page,
                        decode_chunk=chunk)
    # local-only federation frames (explicit: never falls back to a
    # configured PADDLE_HEARTBEAT_DIR or global KV client — a bench
    # publisher must not litter a live heartbeat dir): the
    # extra.metrics.federation block reports a real publisher's output
    eng.publish_frames("bench-replica0", local_only=True)
    from paddle_tpu.inference.engine import EngineStats
    eng.run(reqs(0))            # warmup: compiles every prefill bucket
    # drop warmup observations: a TTFT that includes an XLA compile is
    # a cold-start story, not the steady-state SLO the rung reports
    from paddle_tpu import monitor as _mon
    _latency_names = ("queue_wait_ms", "ttft_ms", "tpot_ms", "e2e_ms")
    for _nm in _latency_names:
        _m = _mon.registry().get(f"serving.latency.{_nm}")
        if _m is not None:
            _m.reset()

    # uniform-batch baseline: waves of ``slots`` requests, every wave
    # padded to the global max prompt/gen (the static-shape serving
    # pattern the ring decode rung measures)
    gen = jax.jit(lambda p, i: L.generate(p, i, cfg,
                                          max_new_tokens=max_g))
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (slots, max_p)),
                      jnp.int32)
    toks = gen(params, ids)                       # compile + warmup
    float(toks[0, -1])
    waves = -(-n_req // slots)

    # INTERLEAVED best-of-3 windows: this container's wall clock swings
    # 2x between seconds, so alternating the two sides keeps a noise
    # burst from landing on only one of them
    dt = uniform_dt = float("inf")
    for w in range(1, 4):
        eng.stats = EngineStats()
        t0 = _time.perf_counter()
        eng.run(reqs(n_req * w))
        dt = min(dt, _time.perf_counter() - t0)
        t0 = _time.perf_counter()
        for _ in range(waves):
            toks = gen(params, ids)
        float(toks[0, -1])
        uniform_dt = min(uniform_dt, _time.perf_counter() - t0)

    s = eng.stats
    pool = eng.cache.num_pages
    latency = {}
    for _nm in _latency_names:
        _m = _mon.registry().get(f"serving.latency.{_nm}")
        if _m is not None and _m.count:
            latency[_nm] = {
                "count": _m.count,
                **{k: round(v, 3) for k, v in
                   _m.quantiles((0.5, 0.95, 0.99)).items()},
            }
    out = {
        "config": f"llama_3_8b[{cfg.num_hidden_layers}L]" if on_tpu
        else "llama_tiny[2L]",
        "latency_ms": latency,
        "requests": n_req, "num_slots": slots,
        "page_size": eng.page_size,
        "trace_prompt_lens": sorted(set(p for p, _ in trace)),
        "trace_gen_lens": sorted(set(g for _, g in trace)),
        "tokens_generated": s.tokens_generated,
        "serving_tokens_per_sec": round(useful / dt, 2),
        "uniform_batch_tokens_per_sec": round(useful / uniform_dt, 2),
        "speedup_vs_uniform": round(uniform_dt / dt, 3),
        "batch_occupancy": round(s.occupancy(), 4),
        "page_pool_utilization": round(s.peak_pages_in_use / pool, 4),
        "preempted": s.preempted,
        "engine": s.as_dict(),
    }

    # Quantized-memory-plane arm (FLAGS_serving_kv_quant): the same
    # trace on int8 page pools. Throughput rides the regular guard;
    # ``servable_concurrency_at_fixed_pool_bytes`` is the tentpole's
    # capacity claim — per-KV-token pool bytes full-precision vs
    # quantized (codes + scale planes), i.e. how many more concurrent
    # sequences the same HBM pool budget holds (guarded as a static
    # >= 1.8x floor in scripts/check_bench_regression.py). Optional —
    # failure records an error note, never kills the rung.
    try:
        # int8 pages tile at 32 sublanes: round the page up on TPU so
        # the quantized arm measures the kernel, not the jnp fallback
        qpage = -(-eng.page_size // 32) * 32 if on_tpu else eng.page_size
        qeng = ServingEngine(L, params, cfg, num_slots=slots,
                             max_len=max_len, page_size=qpage,
                             decode_chunk=chunk, kv_quant=True)
        qeng.run(reqs(10_000))          # warmup: compiles every bucket
        qdt = float("inf")
        for w in range(1, 4):
            qeng.stats = EngineStats()
            t0 = _time.perf_counter()
            qeng.run(reqs(10_000 + n_req * w))
            qdt = min(qdt, _time.perf_counter() - t0)
        fp_per_tok = (sum(a.nbytes for a in jax.tree.leaves(eng.cache.pool))
                      / (eng.cache.num_pages * eng.page_size))
        q_per_tok = (sum(a.nbytes for a in jax.tree.leaves(qeng.cache.pool))
                     / (qeng.cache.num_pages * qeng.page_size))
        out["kv_quant"] = {
            "page_size": qeng.page_size,
            "tokens_per_sec": round(useful / qdt, 2),
            "pool_bytes_per_kv_token": round(q_per_tok, 2),
            "full_precision_bytes_per_kv_token": round(fp_per_tok, 2),
            "servable_concurrency_at_fixed_pool_bytes":
                round(fp_per_tok / q_per_tok, 3),
        }
    except Exception as e:                        # noqa: BLE001
        out["kv_quant_error"] = f"{type(e).__name__}: {e}"[:300]
    return out


def _serving_trace_replay_rung(on_tpu):
    """Deterministic trace replay through the overload-policy engine:
    a seeded multi-tenant arrival trace (loadgen/traces.py) with a
    scripted mid-trace overload burst replays open-loop on the virtual
    clock (loadgen/replay.py), and the SLO scorecard folds the typed
    terminal states into the goodput / p99-TTFT numbers the regression
    guard reads (``extra.serving_trace_replay.*``). The terminal-state
    and token counts are a pure function of the trace seed + engine
    flags — only the latency/wall numbers move between runs."""
    import dataclasses as _dc
    import time as _time

    import jax

    from paddle_tpu.inference import ServingEngine
    from paddle_tpu.inference.engine import EngineStats
    from paddle_tpu.loadgen import (Episode, TenantSpec, build_scorecard,
                                    generate_trace, replay_trace)
    from paddle_tpu.models import llama as L

    if on_tpu:
        cfg = L.llama_3_8b(num_hidden_layers=4, vocab_size=32000,
                           remat=False)
        slots, page, chunk = 8, 16, 4
        rate = 40.0
    else:
        cfg = L.llama_tiny(num_hidden_layers=2)
        slots, page, chunk = 4, 4, 8
        rate = 48.0

    trace = generate_trace(
        1616, duration_s=1.0, rate=rate,
        tenants=[TenantSpec("interactive", share=1.0, priority=2),
                 TenantSpec("batch", share=2.0, priority=0)],
        prompt_len=(4, 16), max_new_tokens=(4, 24), alpha=1.3,
        burst=(0.5, 0.2, 2.0))
    episodes = [Episode("burst", at_s=0.55, n_requests=6 * slots)]

    params = jax.jit(lambda: L.init_params(cfg, jax.random.PRNGKey(0)))()
    jax.block_until_ready(params["embed"])
    # headroom covers the burst injections (drawn from the same
    # prompt/gen ranges the trace config echoes)
    eng = ServingEngine(L, params, cfg, num_slots=slots,
                        max_len=16 + 24, page_size=page,
                        decode_chunk=chunk, priority_admission=True,
                        max_queue=2 * slots)
    eng.publish_frames("replay-replica0", local_only=True)

    # warmup: the SAME arrival schedule under rid-shifted identities
    # compiles every prefill bucket without colliding with the measured
    # run's rids (the replay harvests only its own submissions, so the
    # warmup outputs parked on the engine stay invisible). The global
    # serving.latency histograms are NOT reset here — they belong to
    # the serving_paged rung's guarded SLO block; this rung's p99s come
    # from its own per-request cost samples via the scorecard.
    warm = _dc.replace(trace, requests=[
        _dc.replace(r, rid=r.rid + 500_000) for r in trace.requests])
    replay_trace(eng, warm, dt_per_step=0.01)

    eng.stats = EngineStats()
    t0 = _time.perf_counter()
    result = replay_trace(eng, trace, dt_per_step=0.01,
                          episodes=episodes)
    dt = _time.perf_counter() - t0
    card = build_scorecard(result)

    det = card["deterministic"]
    lat = card["timing"]["latency_ms"]
    return {
        "config": f"llama_3_8b[{cfg.num_hidden_layers}L]" if on_tpu
        else "llama_tiny[2L]",
        "trace_sha256": det["trace"]["sha256"],
        "trace_requests": det["trace"]["requests"],
        "offered_requests": det["goodput"]["offered_requests"],
        "terminal": det["terminal"],
        "shed_by_reason": det["shed_by_reason"],
        "request_goodput": det["goodput"]["request_goodput"],
        "token_goodput": det["goodput"]["token_goodput"],
        "useful_tokens": det["tokens"]["useful"],
        # the two guarded rungs: useful decode tokens per wall second
        # (higher is better) and completed-request p99 TTFT (lower)
        "goodput_tokens_per_sec": round(det["tokens"]["useful"] / dt, 2),
        "ttft_p99_ms": (lat.get("ttft_ms") or {}).get("p99"),
        "latency_ms": lat,
        "verdict": card["verdict"],
        "wall_s": round(dt, 3),
    }


def _serving_prefix_replay_rung(on_tpu):
    """Shared-prefix trace replay: one tenant whose every prompt opens
    with the same system prefix (loadgen v2 traces), replayed through
    the engine with the radix prefix cache OFF then ON. Terminal-state
    and emitted-token equality are reported (`terminal_match` /
    `tokens_match` — identical math; in bf16 an argmax near-tie can
    flip across the differently-shaped prefill programs, so these are
    diagnostics, not guards); the guard reads the cache-on
    completed-request p50 TTFT and the DETERMINISTIC
    prefill-FLOPs-per-request proxy 2·N_params·tokens_prefilled /
    completed — prefill work the cache skips moves that number even
    when wall clock is noisy."""
    import dataclasses as _dc
    import time as _time

    import jax

    from paddle_tpu.inference import ServingEngine
    from paddle_tpu.inference.engine import EngineStats
    from paddle_tpu.loadgen import (TenantSpec, build_scorecard,
                                    generate_trace, replay_trace)
    from paddle_tpu.loadgen.scorecard import (last_scorecard,
                                              set_last_scorecard)
    from paddle_tpu.models import llama as L

    if on_tpu:
        cfg = L.llama_3_8b(num_hidden_layers=4, vocab_size=32000,
                           remat=False)
        slots, page, chunk = 8, 16, 4
        rate, pfx, plen = 28.0, 64, (72, 128)
    else:
        cfg = L.llama_tiny(num_hidden_layers=2)
        slots, page, chunk = 4, 4, 8
        rate, pfx, plen = 36.0, 16, (20, 32)

    trace = generate_trace(
        1717, duration_s=1.0, rate=rate,
        tenants=[TenantSpec("assistant", share=3.0, prefix_len=pfx),
                 TenantSpec("adhoc", share=1.0)],
        prompt_len=plen, max_new_tokens=(4, 16), alpha=1.3)

    params = jax.jit(lambda: L.init_params(cfg, jax.random.PRNGKey(0)))()
    jax.block_until_ready(params["embed"])
    n_params = L.count_params(cfg)
    prior_card = last_scorecard()

    def _one(prefix_on):
        eng = ServingEngine(L, params, cfg, num_slots=slots,
                            max_len=plen[1] + 16, page_size=page,
                            decode_chunk=chunk, prefix_cache=prefix_on)
        # warmup compiles every (tail, ctx-pages) prefill bucket AND —
        # cache on — seeds the radix: the prefix stream is a pure
        # function of (seed, tenant), so the rid-shifted warmup shares
        # the measured run's prefixes exactly
        warm = _dc.replace(trace, requests=[
            _dc.replace(r, rid=r.rid + 500_000) for r in trace.requests])
        replay_trace(eng, warm, dt_per_step=0.01)
        eng.stats = EngineStats()
        t0 = _time.perf_counter()
        result = replay_trace(eng, trace, dt_per_step=0.01)
        dt = _time.perf_counter() - t0
        card = build_scorecard(result, include_fleet=False)
        stats = {}
        for s in result.engine_stats.values():
            for k, v in s.items():
                if isinstance(v, (int, float)):
                    stats[k] = stats.get(k, 0) + v
        completed = card["deterministic"]["terminal"].get("completed", 0)
        lat = card["timing"]["latency_ms"]
        toks = {rid: eng.outputs[rid].tokens.tolist()
                for rid in (r.rid for r in trace.requests)
                if rid in eng.outputs}
        return {
            "ttft_p50_ms": (lat.get("ttft_ms") or {}).get("p50"),
            "prefill_flops_per_request":
                round(2.0 * n_params * stats.get("tokens_prefilled", 0)
                      / completed, 2) if completed else None,
            "tokens_prefilled": int(stats.get("tokens_prefilled", 0)),
            "completed": completed,
            "terminal": card["deterministic"]["terminal"],
            "prefix_cache": card["deterministic"]["prefix_cache"],
            "wall_s": round(dt, 3),
        }, toks

    off, toks_off = _one(False)
    on, toks_on = _one(True)
    # restore the trace-replay rung's scorecard for the metrics embed
    set_last_scorecard(prior_card)
    return {
        "config": f"llama_3_8b[{cfg.num_hidden_layers}L]" if on_tpu
        else "llama_tiny[2L]",
        "trace_sha256": trace.sha256(),
        "trace_requests": len(trace.requests),
        "prefix_len": pfx,
        # guarded (lower-is-better): the CACHE-ON numbers
        "ttft_p50_ms": on["ttft_p50_ms"],
        "prefill_flops_per_request": on["prefill_flops_per_request"],
        "hit_rate": on["prefix_cache"]["hit_rate"],
        "prefill_tokens_saved":
            on["prefix_cache"]["prefill_tokens_saved"],
        "evictions": on["prefix_cache"]["evictions"],
        "cache_off": {k: off[k] for k in
                      ("ttft_p50_ms", "prefill_flops_per_request",
                       "tokens_prefilled", "wall_s")},
        "tokens_prefilled": on["tokens_prefilled"],
        "terminal": on["terminal"],
        "terminal_match": on["terminal"] == off["terminal"],
        "tokens_match": toks_on == toks_off,
        "wall_s": on["wall_s"],
    }


def _training_packed_rung(on_tpu):
    """Sequence-packed training throughput: a heavy-tailed
    document-length trace (io.packing.heavy_tailed_lengths — the same
    deterministic trace scripts/tpu_smoke.py pre-tunes the varlen
    kernel blocks for) is trained twice with equal useful tokens:

    - packed: greedy first-fit rows + per-token segment ids through the
      segment-masked flash kernel (inter-document block skipping);
    - padded: one document per row, padded to the row length — the
      static-shape baseline every fixed-[B, S] pipeline pays.

    Reports useful tokens/s both ways, the padding fraction reclaimed,
    and the block-skip fraction of the packed attention grid."""
    import time as _time

    import jax
    import jax.numpy as jnp

    from paddle_tpu import kernels, monitor
    from paddle_tpu.io import packing as PK
    from paddle_tpu.models import llama as L

    if on_tpu:
        cfg = L.llama_3_8b(num_hidden_layers=4, vocab_size=32000,
                           remat_policy="dots", fused_ce=False)
        S, n_docs, iters = 2048, 24, 6
    else:
        cfg = L.llama_tiny(num_hidden_layers=2)
        S, n_docs, iters = 128, 24, 3

    lens = PK.heavy_tailed_lengths(S, n_docs, seed=7)
    rng = np.random.default_rng(7)
    docs = [rng.integers(0, cfg.vocab_size, (ln,)).astype(np.int32)
            for ln in lens]
    packed = PK.pack_documents(docs, S)
    pbatch = tuple(jnp.asarray(a) for a in
                   (packed["ids"], packed["labels"],
                    packed["segment_ids"], packed["positions"]))
    b_packed = packed["ids"].shape[0]
    useful = int((packed["labels"] >= 0).sum())

    # padded baseline: one doc per row, chunked into waves of b_packed
    # rows so both sides run the same [b_packed, S] step shape
    ids_pad = np.zeros((n_docs, S), np.int32)
    lab_pad = np.full((n_docs, S), -100, np.int32)
    for i, d in enumerate(docs):
        ids_pad[i, :len(d)] = d
        lab_pad[i, :len(d) - 1] = d[1:]
    waves = -(-n_docs // b_packed)
    pad_rows = waves * b_packed
    ids_pad = np.pad(ids_pad, ((0, pad_rows - n_docs), (0, 0)))
    lab_pad = np.pad(lab_pad, ((0, pad_rows - n_docs), (0, 0)),
                     constant_values=-100)
    pad_batches = [(jnp.asarray(ids_pad[w * b_packed:(w + 1) * b_packed]),
                    jnp.asarray(lab_pad[w * b_packed:(w + 1) * b_packed]))
                   for w in range(waves)]

    # buffer donation like the headline rung — always rebind the
    # returned params/opt so the donated buffers are never reused
    step, guarded = _sentinel_train_step(L.make_train_step, cfg, lr=1e-4)

    @jax.jit
    def init():
        p = L.init_params(cfg, jax.random.PRNGKey(0))
        return p, L.adamw_init(p, moment_dtype=jnp.bfloat16)

    params, opt = init()
    jax.block_until_ready(params["embed"])

    kernels.reset_dispatch_stats()
    params, opt, loss = step(params, opt, pbatch)   # compile + warmup
    float(loss)
    varlen_stats = {k: v for k, v in kernels.dispatch_stats().items()
                    if k.startswith("varlen")}
    params, opt, loss = step(params, opt, pad_batches[0])
    float(loss)

    t0 = _time.perf_counter()
    for _ in range(iters):
        params, opt, loss = step(params, opt, pbatch)
    packed_loss = float(loss)
    packed_dt = _time.perf_counter() - t0

    t0 = _time.perf_counter()
    for _ in range(iters):
        for wb in pad_batches:
            params, opt, loss = step(params, opt, wb)
    float(loss)
    padded_dt = _time.perf_counter() - t0

    # block-skip fraction at the blocks the dispatch would use (the
    # cached/tuned varlen blocks, else the 128/128 defaults)
    from paddle_tpu.kernels import autotune as _at
    bq, bk = _at.varlen_blocks(
        (b_packed, S, cfg.num_attention_heads, cfg.head_dim),
        (b_packed, S, cfg.num_key_value_heads, cfg.head_dim),
        cfg.dtype, True)
    bq, bk = min(bq, S), min(bk, S)
    skipped, total = kernels.count_skipped_blocks(
        packed["segment_ids"], packed["segment_ids"],
        packed["positions"], packed["positions"], bq, bk, True)
    monitor.set_gauge("packing.blocks.skipped", skipped,
                      doc="attention block pairs skipped, packed rung")
    monitor.set_gauge("packing.blocks.total", total,
                      doc="attention block pairs in the packed grid")

    slots_padded = pad_rows * S
    slots_packed = b_packed * S
    return {
        "config": f"llama_3_8b[{cfg.num_hidden_layers}L]" if on_tpu
        else "llama_tiny[2L]",
        "seq_len": S, "documents": n_docs,
        "packed_rows": b_packed, "padded_rows": pad_rows,
        "useful_tokens_per_step": useful,
        "packing_efficiency": round(PK.packing_efficiency(packed), 4),
        "packed_tokens_per_sec": round(useful * iters / packed_dt, 2),
        "padded_tokens_per_sec": round(useful * iters / padded_dt, 2),
        "speedup_vs_padded": round(padded_dt / packed_dt, 3),
        "padding_fraction_reclaimed": round(
            (slots_padded - slots_packed) / slots_padded, 4),
        "blocks_skipped": skipped, "blocks_total": total,
        "block_skip_fraction": round(skipped / total, 4) if total else 0.0,
        "varlen_blocks": [bq, bk],
        "varlen_dispatch": varlen_stats,
        "sentinel_guarded": guarded,
        "loss": packed_loss if np.isfinite(packed_loss)
        else repr(packed_loss),
    }


def _moe_rung(on_tpu, dev):
    """Single-chip MoE measurement (DeepSeekMoE-16B slice on TPU,
    moe_tiny on CPU). Returns the extra['moe'] dict. MFU is reported
    against ACTIVE parameters (shared + top-k routed + dense), the
    honest utilisation figure for a sparse model."""
    import time as _time

    import jax
    import jax.numpy as jnp

    from paddle_tpu.models import llama as L
    from paddle_tpu.models import moe as M

    if on_tpu:
        # Round-5 measured optimum: capacity gather dispatch (2.1x the
        # dense-dispatch rung at equal batch), materialized einsum loss
        # (fused CE loses ~4% here; 8k tokens x 102k vocab still fits),
        # batch 8 (b16 regresses under HBM pressure, b32 fails the
        # tunnel's remote-compile helper), "dots" remat (+3% — the saved
        # expert activations are C-sized under capacity dispatch) with a
        # full-remat retry in case the tunnel's compile helper rejects
        # the dots program.
        cfgs = [M.deepseek_moe_16b(num_hidden_layers=2,
                                   dispatch_mode="capacity",
                                   fused_ce=False, remat_policy=p)
                for p in ("dots", "full")]
        batch, seq, iters = 8, 1024, 8
        mdt = jnp.bfloat16
    else:
        cfgs = [M.moe_tiny(num_hidden_layers=2)]
        batch, seq, iters = 2, 64, 3
        mdt = jnp.float32

    for cfg in cfgs:
        try:
            @jax.jit
            def init():
                p = M.init_params(cfg, jax.random.PRNGKey(1))
                return p, L.adamw_init(p, moment_dtype=mdt)

            params, opt_state = init()
            jax.block_until_ready(params["embed"])
            step, guarded = _sentinel_train_step(M.make_train_step,
                                                 cfg, lr=1e-4)
            ids = jnp.asarray(np.random.default_rng(1).integers(
                0, cfg.vocab_size, (batch, seq + 1)), jnp.int32)

            params, opt_state, loss = step(params, opt_state, ids)
            float(loss)   # compile + warmup; hard sync
            break
        except Exception:
            if cfg is cfgs[-1]:
                raise      # no rung left — outer handler records it
            params = opt_state = None
            jax.clear_caches()
    t0 = _time.perf_counter()
    for _ in range(iters):
        params, opt_state, loss = step(params, opt_state, ids)
    final_loss = float(loss)
    dt = _time.perf_counter() - t0

    tps = batch * seq * iters / dt
    total = M.count_params(cfg)
    c = cfg
    routed = (c.num_hidden_layers * c.num_experts
              * 3 * c.hidden_size * c.intermediate_size)
    active = total - routed + routed * c.num_experts_per_tok // c.num_experts
    peak = _peak_flops(dev) if on_tpu else 1e12
    mfu_active = tps * 6 * active / peak
    dispatch = cfg.dispatch_mode or "capacity"   # single-device auto
    return {
        "config": "deepseek_moe_16b[2L]" if on_tpu else "moe_tiny[2L]",
        "dispatch": dispatch,
        "capacity": (M.moe_capacity(cfg, batch * seq)
                     if dispatch == "capacity" else None),
        "tokens_per_sec": round(tps, 2),
        "mfu_active": round(mfu_active, 4),
        "params_total": total, "params_active": int(active),
        "batch": batch, "seq": seq,
        "sentinel_guarded": guarded,
        "loss": final_loss if np.isfinite(final_loss)
        else repr(final_loss),
    }


if __name__ == "__main__":
    main()
