"""Train-loop anomaly sentinel (ISSUE 6): the in-graph NaN/spike guard
in ``make_train_step(guard=True)`` (params byte-identical on an
anomalous step — llama and MoE, kernel and fallback attention arms),
the ``testing/faults.py`` ``corrupt`` value action driving it, the
host-side skip/rollback escalation ladder with deterministic
fast-forward replay, the hang watchdog's stall forensics, the hapi
eager guard, serving-engine request isolation, and the off-flag
zero-overhead contract."""
import importlib
import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import monitor
from paddle_tpu.distributed.checkpoint import CheckpointManager
from paddle_tpu.models import llama as L
from paddle_tpu.models import moe as M
from paddle_tpu.testing import faults
from paddle_tpu.training import sentinel as S

FA = importlib.import_module("paddle_tpu.kernels.flash_attention")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

B, T, V = 2, 16, 64
INF_CAP = jnp.asarray(np.inf, jnp.float32)


@pytest.fixture(autouse=True)
def _clean():
    yield
    faults.clear()
    pt.set_flags({"FLAGS_enable_sentinel": False,
                  "FLAGS_enable_monitor": False})
    monitor.reset()


def _batch(i, vocab=V):
    """Deterministic batch #i of the canonical test stream."""
    r = np.random.RandomState(1000 + i)
    ids = r.randint(0, vocab, size=(B, T + 1)).astype(np.int32)
    return ids[:, :-1], ids[:, 1:]


def _stream(n=10_000, poison=()):
    """Fresh deterministic iterator over the canonical stream; batches
    whose index is in ``poison`` carry a NaN-equivalent int corruption
    IN THE DATA (the same batch poisons every replay — persistent
    bit-rot, not a transient injection)."""
    def gen():
        for i in range(n):
            inp, lab = _batch(i)
            if i in poison:
                inp = inp.copy()
                inp[0, 0] = np.iinfo(np.int32).min
            yield inp, lab
    return gen()


def _tree_identical(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    return all(np.asarray(x).dtype == np.asarray(y).dtype
               and np.array_equal(np.asarray(x), np.asarray(y),
                                  equal_nan=True)
               for x, y in zip(la, lb))


def _llama():
    cfg = L.llama_tiny(vocab_size=V)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params, L.adamw_init(params)


# ---------------------------------------------------------------------------
# faults.corrupt — the value-point action
# ---------------------------------------------------------------------------

class TestCorruptAction:
    def test_disarmed_is_identity(self):
        b = _batch(0)
        assert faults.corrupt("train.batch", b) is b

    def test_nth_hit_semantics(self):
        faults.inject("train.batch", "corrupt", nth=2)
        first = faults.corrupt("train.batch", _batch(0))
        assert int(first[0].min()) >= 0          # 1st hit: untouched
        second = faults.corrupt("train.batch", _batch(1))
        assert int(second[0].flat[0]) == np.iinfo(np.int32).min
        third = faults.corrupt("train.batch", _batch(2))
        assert int(third[0].min()) >= 0          # fired once, done

    def test_float_leaf_gets_nan_and_inf(self):
        x = {"a": np.ones((3,), np.float32)}
        faults.inject("p", "corrupt", nth=1)
        assert np.isnan(faults.corrupt("p", x)["a"][0])
        faults.clear()
        faults.inject("p", "corrupt_inf", nth=1)
        assert np.isposinf(faults.corrupt("p", x)["a"][0])
        assert np.all(x["a"] == 1.0)             # original untouched

    def test_jax_array_leaf(self):
        faults.inject("p", "corrupt", nth=1)
        out = faults.corrupt("p", jnp.ones((2, 2)))
        assert np.isnan(np.asarray(out)[0, 0])

    def test_unsigned_int_leaf_goes_out_of_range(self):
        """uint corruption must plant iinfo.max — iinfo.min is 0, a
        VALID token id, i.e. a silent no-op."""
        faults.inject("p", "corrupt", nth=1)
        out = faults.corrupt("p", np.zeros((4,), np.uint32))
        assert int(out[0]) == np.iinfo(np.uint32).max

    def test_plain_hit_neither_fires_nor_consumes_corrupt(self):
        faults.inject("train.batch", "corrupt", nth=1)
        faults.hit("train.batch")                # value-less declaration
        out = faults.corrupt("train.batch", _batch(0))
        assert int(out[0].flat[0]) == np.iinfo(np.int32).min

    def test_raise_fires_at_value_point(self):
        faults.inject("p", "raise", nth=1)
        with pytest.raises(faults.FaultInjected):
            faults.corrupt("p", _batch(0))

    @pytest.mark.chaos
    @pytest.mark.slow  # tier-1 budget: subprocess; flag-arming also covered by PR 2's chaos tests
    def test_env_armed_chaos_run(self):
        """FLAGS_fault_injection arms the corrupt value point in a
        fresh process — the chaos-run entry to the anomaly paths."""
        code = (
            "import numpy as np\n"
            "import paddle_tpu  # arms faults from the flag\n"
            "from paddle_tpu.testing import faults\n"
            "out = faults.corrupt('train.batch',"
            " np.ones((2,), np.float32))\n"
            "assert np.isnan(out[0]), out\n"
            "print('CHAOS_OK')\n"
        )
        r = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=300, cwd=REPO,
            env=dict(os.environ, JAX_PLATFORMS="cpu",
                     FLAGS_fault_injection="train.batch:corrupt"))
        assert r.returncode == 0, r.stderr[-2000:]
        assert "CHAOS_OK" in r.stdout


# ---------------------------------------------------------------------------
# in-graph guard: anomalous step is all-or-nothing on device
# ---------------------------------------------------------------------------

class TestGuardedStep:
    def test_llama_nan_batch_params_byte_identical_then_continues(self):
        cfg, params, opt = _llama()
        step = L.make_train_step(cfg, guard=True, donate=False)
        p1, o1, loss1, h1 = step(params, opt, _batch(0), INF_CAP)
        assert bool(h1["finite"]) and np.isfinite(float(loss1))
        faults.inject("train.batch", "corrupt", nth=1)
        bad = faults.corrupt("train.batch", _batch(1))
        p2, o2, loss2, h2 = step(p1, o1, bad, INF_CAP)
        assert not bool(h2["finite"])
        assert _tree_identical(p1, p2)           # params untouched
        assert _tree_identical(o1, o2)           # opt state untouched
        # training continues: the next clean batch applies
        p3, o3, loss3, h3 = step(p2, o2, _batch(2), INF_CAP)
        assert bool(h3["finite"]) and np.isfinite(float(loss3))
        assert not _tree_identical(p2, p3)

    @pytest.mark.slow  # tier-1 budget (ISSUE 20 rebalance): guard family re-run;
    # llama_nan_batch_params_byte_identical_then_continues keeps the seam fast
    def test_moe_nan_batch_params_byte_identical(self):
        cfg = M.moe_tiny(vocab_size=V)
        params = M.init_params(cfg, jax.random.PRNGKey(0))
        opt = M.adamw_init(params)
        step = M.make_train_step(cfg, guard=True, donate=False)
        p1, o1, loss1, h1 = step(params, opt, _batch(0), INF_CAP)
        assert bool(h1["finite"])
        faults.inject("train.batch", "corrupt_inf", nth=1)
        bad = faults.corrupt("train.batch", _batch(1))
        p2, o2, _, h2 = step(p1, o1, bad, INF_CAP)
        assert not bool(h2["finite"])
        assert _tree_identical(p1, p2) and _tree_identical(o1, o2)

    def test_guard_holds_on_both_attention_arms(self):
        """The all-or-nothing contract is attention-impl-independent:
        the same poisoned PACKED batch through the interpret-mode
        segment kernel and the jnp fallback both gate the update."""
        from paddle_tpu.io import packing as PK
        from paddle_tpu.nn.functional import attention as att
        cfg, params, opt = _llama()
        step = L.make_train_step(cfg, guard=True, donate=False)
        rng = np.random.default_rng(5)
        docs = [rng.integers(0, V, (ln,)).astype(np.int32)
                for ln in (40, 24)]
        pb = PK.packed_train_batch(PK.pack_documents(docs, 64))
        bad = (np.where(np.arange(64)[None] == 0,
                        np.iinfo(np.int32).min, pb[0]).astype(np.int32),
               ) + tuple(pb[1:])
        prev = att._SEGMENT_IMPL
        try:
            for impl in (None,                    # jnp fallback
                         lambda *a, **kw: FA.flash_attention_segments(
                             *a, **kw, interpret=True)):
                att.register_segment_impl(impl)
                p2, o2, _, h2 = step(params, opt, bad, INF_CAP)
                assert not bool(h2["finite"])
                assert _tree_identical(params, p2)
                assert _tree_identical(opt, o2)
        finally:
            att.register_segment_impl(prev)

    def test_out_of_range_token_id_is_anomalous(self):
        """One id == vocab_size is flagged by the guard's id-range
        check. (What the gather itself does is PLATFORM-dependent —
        XLA:CPU's jnp.take fills NaN, TPU clamps and trains on
        garbage silently — which is exactly why the explicit ids_ok
        check exists; the loss value is asserted on neither.)"""
        cfg, params, opt = _llama()
        step = L.make_train_step(cfg, guard=True, donate=False)
        inp, lab = _batch(0)
        inp = inp.copy()
        inp[0, 3] = V                            # one past the edge
        p2, _, _, h = step(params, opt, (inp, lab), INF_CAP)
        assert not bool(h["finite"])
        assert _tree_identical(params, p2)

    def test_spike_cap_gates_finite_step(self):
        cfg, params, opt = _llama()
        step = L.make_train_step(cfg, guard=True, donate=False)
        tight = jnp.asarray(1e-9, jnp.float32)
        p2, o2, loss, h = step(params, opt, _batch(0), tight)
        assert np.isfinite(float(loss))
        assert np.isfinite(float(h["grad_norm"]))
        assert not bool(h["finite"])             # gated by the cap
        assert _tree_identical(params, p2) and _tree_identical(opt, o2)

    @pytest.mark.slow  # tier-1 budget (ISSUE 19 rebalance): duplicated by numerics'
    # guarded_update_math_unchanged_by_numerics fast pin
    def test_guarded_update_math_matches_unguarded(self):
        """With an infinite cap and clean data the guarded step applies
        EXACTLY the unguarded update (the cond's true branch is the
        same program)."""
        cfg, params, opt = _llama()
        g = L.make_train_step(cfg, guard=True, donate=False)
        u = L.make_train_step(cfg, guard=False, donate=False)
        pg, og, lg, _ = g(params, opt, _batch(0), INF_CAP)
        pu, ou, lu = u(params, opt, _batch(0))
        assert float(lg) == float(lu)
        assert _tree_identical(pg, pu) and _tree_identical(og, ou)

    def test_off_flag_step_is_3_in_3_out(self):
        """guard=None + flag off -> the historical step program: no cap
        argument, no health output, zero extra device outputs."""
        cfg, params, opt = _llama()
        step = L.make_train_step(cfg, donate=False)
        out = step(params, opt, _batch(0))
        assert len(out) == 3                     # params, opt, loss
        with pytest.raises(TypeError):
            step(params, opt, _batch(0), INF_CAP)

    def test_flag_selects_guarded_step(self):
        cfg, params, opt = _llama()
        pt.set_flags({"FLAGS_enable_sentinel": True})
        step = L.make_train_step(cfg, donate=False)
        out = step(params, opt, _batch(0), INF_CAP)
        assert len(out) == 4 and "finite" in out[3]


# ---------------------------------------------------------------------------
# host policy: spike detector + escalation ladder
# ---------------------------------------------------------------------------

class TestAnomalySentinel:
    def test_warmup_cap_is_inf_then_tracks_ema(self):
        sent = S.AnomalySentinel(S.SentinelConfig(
            agree=False, warmup_steps=3, spike_sigma=6.0))
        assert sent.gnorm_cap() == float("inf")
        for g in (1.0, 1.1, 0.9):
            assert sent.observe(finite=True, grad_norm=g) == S.OK
        cap = sent.gnorm_cap()
        assert np.isfinite(cap) and cap > 1.1
        assert cap < 10.0                        # sigma-scaled, not wild

    def test_consecutive_resets_on_healthy(self):
        sent = S.AnomalySentinel(S.SentinelConfig(agree=False))
        assert sent.observe(finite=False) == S.SKIP
        assert sent.consecutive == 1
        assert sent.observe(finite=True, grad_norm=1.0) == S.OK
        assert sent.consecutive == 0

    def test_rollback_verdict_needs_manager_and_n_consecutive(self):
        sent = S.AnomalySentinel(S.SentinelConfig(
            agree=False, max_consecutive=2))
        assert sent.observe(finite=False) == S.SKIP        # no manager
        assert sent.observe(finite=False) == S.SKIP
        sent2 = S.AnomalySentinel(S.SentinelConfig(
            agree=False, max_consecutive=2), manager=object())
        assert sent2.observe(finite=False) == S.SKIP
        assert sent2.observe(finite=False) == S.ROLLBACK

    def test_quarantine_membership_by_content_hash(self):
        sent = S.AnomalySentinel(S.SentinelConfig(agree=False))
        b = _batch(0)
        sent.observe(finite=False, batch=b)
        assert sent.is_quarantined(_batch(0))    # same content
        assert not sent.is_quarantined(_batch(1))

    def test_max_rollbacks_refuses_to_thrash(self):
        sent = S.AnomalySentinel(S.SentinelConfig(
            agree=False, max_rollbacks=0), manager=object())
        with pytest.raises(RuntimeError, match="max_rollbacks"):
            sent.rollback({})

    def test_anomaly_metrics_emitted(self):
        pt.set_flags({"FLAGS_enable_monitor": True})
        monitor.reset()
        sent = S.AnomalySentinel(S.SentinelConfig(agree=False))
        sent.observe(finite=False, loss=float("nan"), batch=_batch(0))
        sent.observe(finite=True, grad_norm=1.0)
        snap = monitor.snapshot()
        assert snap["counters"]["train.anomaly.steps"] == 1
        assert snap["counters"]["train.anomaly.nonfinite"] == 1
        assert snap["gauges"]["train.anomaly.quarantined"] == 1
        assert snap["gauges"]["train.anomaly.consecutive"] == 0


# ---------------------------------------------------------------------------
# SentinelLoop: skip / rollback / fast-forward end to end
# ---------------------------------------------------------------------------

def _loop(make_stream, tmp_path=None, *, interval=2, max_consec=2,
          warmup=100):
    cfg, params, opt = _llama()
    step = L.make_train_step(cfg, guard=True, donate=False)
    mgr = None
    if tmp_path is not None:
        mgr = CheckpointManager(str(tmp_path / "ckpt"),
                                save_interval_steps=interval,
                                async_save=False)
    sent = S.AnomalySentinel(
        S.SentinelConfig(agree=False, max_consecutive=max_consec,
                         warmup_steps=warmup), manager=mgr)
    return S.SentinelLoop(step, params, opt, make_stream,
                          sentinel=sent, manager=mgr)


class TestSentinelLoop:
    def test_transient_corruption_skipped_training_continues(self):
        loop = _loop(lambda: _stream())
        faults.inject("train.batch", "corrupt", nth=3)
        out = loop.run(6)
        assert out == {"steps": 6, "applied": 5, "skipped": 1,
                       "rollbacks": 0, "quarantined": 1,
                       "last_loss": out["last_loss"]}
        assert np.isfinite(out["last_loss"])

    def test_rollback_lands_on_latest_step(self, tmp_path):
        """Two consecutive poisoned DATA batches escalate to a rollback
        that restores exactly ``latest_step()``; the fast-forwarded
        replay skips the quarantined batches by hash and completes."""
        poison = {4, 5}
        loop = _loop(lambda: _stream(poison=poison), tmp_path)
        out = loop.run(8)
        mgr = loop.manager
        assert out["rollbacks"] == 1
        assert out["quarantined"] == 2
        # rollback happened at step 6 (consecutive=2) and restored the
        # newest committed step at that moment: step 4
        assert 4 in mgr.all_steps()
        # replay consumed the stream to step 8 with both poisoned
        # batches skipped: 8 batches seen, 2 never applied
        assert out["steps"] == 8
        assert out["applied"] == 6
        assert np.isfinite(out["last_loss"])

    @pytest.mark.slow  # tier-1 budget: two full rollback scenarios; the path stays covered by test_rollback_lands_on_latest_step
    def test_replay_is_deterministic(self, tmp_path):
        """The whole skip->rollback->fast-forward scenario, run twice
        from scratch, lands on bit-identical parameters."""
        a = _loop(lambda: _stream(poison={4, 5}), tmp_path / "a")
        b = _loop(lambda: _stream(poison={4, 5}), tmp_path / "b")
        ra, rb = a.run(8), b.run(8)
        assert ra == rb
        assert _tree_identical(a.params, b.params)
        assert _tree_identical(a.opt_state, b.opt_state)

    def test_fast_forward_positions_fresh_stream(self):
        s = S.fast_forward(_stream(), 3)
        inp, _ = next(s)
        want, _ = _batch(3)
        np.testing.assert_array_equal(inp, want)

    @pytest.mark.slow  # tier-1 budget: third full rollback run, metric-count assertions only
    def test_quarantined_replay_counts_metrics(self, tmp_path):
        pt.set_flags({"FLAGS_enable_monitor": True})
        monitor.reset()
        loop = _loop(lambda: _stream(poison={4, 5}), tmp_path)
        loop.run(8)
        snap = monitor.snapshot()
        assert snap["counters"]["train.anomaly.rollbacks"] == 1
        assert snap["counters"]["train.anomaly.quarantine.skips"] == 2
        assert snap["counters"]["train.anomaly.steps"] == 2


# ---------------------------------------------------------------------------
# hang watchdog
# ---------------------------------------------------------------------------

class TestHangWatchdog:
    def test_stall_dumps_stacks_and_flight_record(self, tmp_path):
        sp = str(tmp_path / "stall.json")
        wd = S.HangWatchdog(0.2, poll_s=0.02, stall_path=sp)
        with wd:
            deadline = time.monotonic() + 5.0
            while wd.stalls == 0 and time.monotonic() < deadline:
                time.sleep(0.02)
        assert wd.stalls == 1                    # fires once per stall
        payload = json.load(open(sp))
        assert payload["kind"] == "paddle_tpu.watchdog_stall"
        assert payload["heartbeat_age_s"] > 0.2
        assert any("MainThread" in k for k in payload["threads"])
        # every stack is a list of formatted frames
        assert all(isinstance(v, list) and v
                   for v in payload["threads"].values())
        fr = json.load(open(sp + ".flight.json"))
        assert fr["kind"] == "paddle_tpu.flight_record"
        assert fr["reason"] == "watchdog.stall"

    def test_heartbeat_rearms_after_stall(self, tmp_path):
        wd = S.HangWatchdog(0.15, poll_s=0.02,
                            stall_path=str(tmp_path / "s.json"))
        with wd:
            deadline = time.monotonic() + 5.0
            while wd.stalls == 0 and time.monotonic() < deadline:
                time.sleep(0.02)
            wd.heartbeat()                       # loop recovered
            deadline = time.monotonic() + 5.0
            while wd.stalls < 2 and time.monotonic() < deadline:
                time.sleep(0.02)
        assert wd.stalls == 2                    # re-armed and re-fired

    def test_steptimer_end_step_feeds_heartbeat(self):
        """Any StepTimer.end_step anywhere in the process is a
        heartbeat — the hapi fit loop and bench feed the watchdog for
        free, monitor on or off."""
        wd = S.HangWatchdog(60.0, poll_s=0.05)
        with wd:
            before = wd._last
            time.sleep(0.01)
            stim = monitor.StepTimer("wd.test")
            with stim:
                stim.end_step()
            assert wd._last > before
        # stopped: listener deregistered
        from paddle_tpu.monitor import steptimer as st
        assert wd.heartbeat not in st._STEP_LISTENERS

    @pytest.mark.slow  # tier-1 budget (ISSUE 19 rebalance): subprocess forensics; stall_dumps_stacks
    # pins the watchdog dump path in-process
    def test_exit_on_stall_subprocess_leaves_forensics(self, tmp_path):
        """A wedged step in a real process: the watchdog dumps the
        stall JSON + flight record and exits non-zero so process-level
        supervision (elastic heartbeat) can restart the worker."""
        sp = str(tmp_path / "stall.json")
        code = (
            "import time\n"
            "import paddle_tpu as pt\n"
            "from paddle_tpu.training.sentinel import HangWatchdog\n"
            "pt.set_flags({'FLAGS_enable_monitor': True})\n"
            "from paddle_tpu.monitor import trace\n"
            "trace.instant('about.to.wedge', step=7)\n"
            f"wd = HangWatchdog(0.3, poll_s=0.05, stall_path={sp!r},\n"
            "                  exit_on_stall=True, exit_code=42)\n"
            "wd.start()\n"
            "time.sleep(120)\n"                  # the wedged 'step'
            "raise SystemExit('watchdog did not fire')\n"
        )
        r = subprocess.run(
            [sys.executable, "-c", code], capture_output=True,
            text=True, timeout=300, cwd=REPO,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        assert r.returncode == 42, (r.returncode, r.stderr[-2000:])
        assert "watchdog stall" in r.stderr      # faulthandler mirror
        payload = json.load(open(sp))            # parseable JSON
        assert payload["kind"] == "paddle_tpu.watchdog_stall"
        assert payload["threads"]
        fr = json.load(open(sp + ".flight.json"))
        assert any(e["name"] == "about.to.wedge" for e in fr["events"])

    def test_bad_deadline_rejected(self):
        with pytest.raises(ValueError):
            S.HangWatchdog(0.0)


# ---------------------------------------------------------------------------
# hapi eager guard
# ---------------------------------------------------------------------------

class TestHapiEagerGuard:
    class _Owner:
        pass

    def test_off_flag_never_skips(self):
        assert S.guard_eager_update(self._Owner(), [1.0]) is False

    def test_nonfinite_loss_skips_and_counts(self):
        pt.set_flags({"FLAGS_enable_sentinel": True,
                      "FLAGS_enable_monitor": True})
        monitor.reset()
        owner = self._Owner()
        assert S.guard_eager_update(owner, [0.5]) is False
        assert S.guard_eager_update(owner, [float("nan")]) is True
        assert S.guard_eager_update(owner, [0.4]) is False
        snap = monitor.snapshot()
        assert snap["counters"]["train.anomaly.steps"] == 1
        assert owner._anomaly_sentinel.consecutive == 0

    def test_accumulation_window_poisoned_by_nonupdate_microbatch(self):
        """A NaN loss on a NON-update micro-batch taints the whole
        accumulation window: the NaN is already summed into the
        accumulated grads, so the window's update must skip even though
        the final micro-batch's own loss is finite."""
        pt.set_flags({"FLAGS_enable_sentinel": True})
        owner = self._Owner()
        assert S.guard_eager_update(owner, [float("nan")],
                                    update=False) is True
        assert S.guard_eager_update(owner, [0.5]) is True   # window skips
        assert owner._anomaly_sentinel.anomalies == 1
        # next window is clean again
        assert S.guard_eager_update(owner, [0.4], update=False) is True
        assert S.guard_eager_update(owner, [0.3]) is False

    def test_fit_accumulated_nan_microbatch_params_survive(self):
        """End to end: accumulate_grad_batches=2 with the corrupt batch
        landing on the NON-update micro-batch — without window
        poisoning the finite second micro-batch would apply the
        NaN-accumulated grads."""
        pt.set_flags({"FLAGS_enable_sentinel": True})
        from paddle_tpu import nn, optimizer
        from paddle_tpu.hapi import Model
        from paddle_tpu.io import Dataset

        class _Reg(Dataset):
            rng = np.random.RandomState(0)
            x = rng.randn(32, 4).astype(np.float32)
            y = np.zeros((32, 2), np.float32)

            def __getitem__(self, i):
                return self.x[i], self.y[i]

            def __len__(self):
                return len(self.x)

        net = nn.Linear(4, 2)
        model = Model(net)
        model.prepare(
            optimizer=optimizer.SGD(learning_rate=0.1,
                                    parameters=net.parameters()),
            loss=nn.MSELoss())
        # batches 0..3; k=2 -> updates after batches 1 and 3. nth=3
        # poisons batch #2 (0-based), a NON-update micro-batch.
        faults.inject("train.batch", "corrupt", nth=3)
        model.fit(_Reg(), epochs=1, batch_size=8, shuffle=False,
                  verbose=0, accumulate_grad_batches=2)
        w = np.asarray(net.weight.numpy())
        assert np.all(np.isfinite(w))
        assert model._anomaly_sentinel.anomalies == 1

    def test_fit_skips_poisoned_batch_params_survive(self):
        """End to end through Model.fit: a corrupt-armed batch yields a
        non-finite loss; with the sentinel on, the optimizer step is
        SKIPPED and every parameter stays finite."""
        pt.set_flags({"FLAGS_enable_sentinel": True})
        from paddle_tpu import nn, optimizer
        from paddle_tpu.hapi import Model
        from paddle_tpu.io import Dataset

        class _Reg(Dataset):
            rng = np.random.RandomState(0)
            x = rng.randn(32, 4).astype(np.float32)
            y = np.zeros((32, 2), np.float32)

            def __getitem__(self, i):
                return self.x[i], self.y[i]

            def __len__(self):
                return len(self.x)

        net = nn.Linear(4, 2)
        model = Model(net)
        model.prepare(
            optimizer=optimizer.SGD(learning_rate=0.1,
                                    parameters=net.parameters()),
            loss=nn.MSELoss())
        faults.inject("train.batch", "corrupt", nth=2)
        model.fit(_Reg(), epochs=1, batch_size=8, shuffle=False,
                  verbose=0)
        w = np.asarray(net.weight.numpy())
        assert np.all(np.isfinite(w))
        assert model._anomaly_sentinel.anomalies == 1


# ---------------------------------------------------------------------------
# serving-engine request isolation (satellite)
# ---------------------------------------------------------------------------

@pytest.mark.serving
class TestEngineIsolation:
    def _engine(self):
        from paddle_tpu.inference.engine import ServingEngine
        cfg, params, _ = _llama()
        return ServingEngine(L, params, cfg, num_slots=2, max_len=32,
                             page_size=4, decode_chunk=3), cfg

    def test_malformed_submissions_typed_rejection(self):
        from paddle_tpu.inference.engine import Request, RequestRejected
        pt.set_flags({"FLAGS_enable_monitor": True})
        monitor.reset()
        eng, cfg = self._engine()
        bad = [
            (Request(rid=1, prompt=np.array([], np.int32),
                     max_new_tokens=4), "empty prompt"),
            (Request(rid=2, prompt=np.arange(40, dtype=np.int32) % V,
                     max_new_tokens=4), "exceeds max_len"),
            (Request(rid=3, prompt=np.arange(4, dtype=np.int32),
                     max_new_tokens=4,
                     temperature=float("nan")), "temperature"),
            (Request(rid=4, prompt=np.arange(4, dtype=np.int32),
                     max_new_tokens=0), "max_new_tokens"),
            (Request(rid=5, prompt=np.array([0, V], np.int32),
                     max_new_tokens=4), "token ids outside"),
            (Request(rid=6, prompt=np.array([0.5, 1.5], np.float32),
                     max_new_tokens=4), "integer"),
        ]
        for req, why in bad:
            with pytest.raises(RequestRejected, match=why):
                eng.submit(req)
        snap = monitor.snapshot()
        assert snap["counters"]["serving.requests.rejected"] == len(bad)
        assert len(eng.queue) == 0               # nothing leaked in
        assert eng.cache.alloc.used_pages == 0

    def test_submit_normalizes_coercible_fields(self):
        """Coercible-but-wrong-typed fields (temperature='0.7') must be
        written back normalized so they can't pass screening and still
        detonate in the scheduler; a non-integral max_new_tokens (2.9
        would silently budget as 2) is rejected outright."""
        from paddle_tpu.inference.engine import Request, RequestRejected
        eng, _ = self._engine()
        req = Request(rid=1, prompt=list(range(4)), max_new_tokens=3,
                      temperature="0.0")
        eng.submit(req)
        assert isinstance(req.prompt, np.ndarray)
        assert req.max_new_tokens == 3 and req.temperature == 0.0
        assert isinstance(req.temperature, float)
        out = eng.run()
        assert len(out[1].tokens) == 3           # served normally
        with pytest.raises(RequestRejected, match="integral"):
            eng.submit(Request(rid=2, prompt=np.arange(4, dtype=np.int32),
                               max_new_tokens=2.9))

    @pytest.mark.slow  # tier-1 budget (ISSUE 19 rebalance): poisoned-submit e2e; the typed-rejection
    # + normalization units pin the same isolation seam fast
    def test_engine_keeps_serving_after_poisoned_submit(self):
        """The isolation pin: a poisoned submission must not perturb
        the tokens of in-flight or subsequent requests — byte-identical
        to a run that never saw the poison."""
        from paddle_tpu.inference.engine import Request, RequestRejected

        def reqs():
            rng = np.random.default_rng(11)
            return [Request(rid=i,
                            prompt=rng.integers(0, V, (5 + i,))
                            .astype(np.int32), max_new_tokens=6)
                    for i in range(3)]

        clean, _ = self._engine()
        want = clean.run(reqs())

        eng, _ = self._engine()
        good = reqs()
        eng.submit(good[0])
        with pytest.raises(RequestRejected):
            eng.submit(Request(rid=99, prompt=np.array([], np.int32),
                               max_new_tokens=4))
        eng.submit(good[1])
        for _ in range(2):                       # poison mid-flight too
            eng.step()
        with pytest.raises(RequestRejected):
            eng.submit(Request(rid=98,
                               prompt=np.arange(99, dtype=np.int32) % V,
                               max_new_tokens=1))
        eng.submit(good[2])
        got = eng.run()
        for i in range(3):
            np.testing.assert_array_equal(got[i].tokens, want[i].tokens)
        eng.cache.alloc.check_invariants()
        assert eng.cache.alloc.used_pages == 0


# ---------------------------------------------------------------------------
# off-flag: zero registrations, zero extra device outputs
# ---------------------------------------------------------------------------

class TestOffFlagZeroOverhead:
    def test_no_metric_registrations_off_flag(self):
        """With FLAGS_enable_sentinel unset, building and running the
        default train step + a fit-loop batch registers NOTHING under
        train.anomaly.* / train.watchdog.* (monitor itself on)."""
        pt.set_flags({"FLAGS_enable_monitor": True})
        monitor.reset()
        cfg, params, opt = _llama()
        step = L.make_train_step(cfg, donate=False)
        step(params, opt, _batch(0))
        assert S.guard_eager_update(object.__new__(object), []) is False
        snap = monitor.snapshot()
        names = (list(snap.get("counters", {}))
                 + list(snap.get("gauges", {}))
                 + list(snap.get("histograms", {})))
        assert not [n for n in names
                    if n.startswith(("train.anomaly.",
                                     "train.watchdog."))]

    def test_no_step_listeners_by_default(self):
        from paddle_tpu.monitor import steptimer as st
        assert st._STEP_LISTENERS == []


# ---------------------------------------------------------------------------
# multi-host skip agreement (launch CLI, 2 processes)
# ---------------------------------------------------------------------------

class TestMultiHostAgreement:
    @pytest.mark.slow  # tier-1 budget: multi-process world, slow lane
    def test_any_rank_anomalous_all_ranks_skip(self, tmp_path):
        worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "_sentinel_agree_worker.py")
        log_dir = str(tmp_path / "logs")
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "2", "--log_dir", log_dir, worker],
            capture_output=True, text=True, timeout=420,
            env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO))
        logs = {}
        for rank in range(2):
            p = os.path.join(log_dir, f"workerlog.{rank}")
            logs[rank] = open(p).read() if os.path.exists(p) else ""
        blob = logs[0] + logs[1]
        assert r.returncode == 0, blob[-4000:]
        for rank in range(2):
            # only rank 0 was LOCALLY anomalous; both must skip
            assert f"VERDICT1 rank={rank} skip" in blob, blob[-4000:]
            assert f"VERDICT2 rank={rank} ok" in blob
            assert f"VERDICT3 rank={rank} ok" in blob
        # gathered-max norms keep the detector state bit-identical
        stats = sorted(l for l in blob.splitlines()
                       if l.startswith("STATS"))
        assert len(stats) == 2
        s0 = stats[0].split(" ", 2)[2]
        s1 = stats[1].split(" ", 2)[2]
        assert s0 == s1, (s0, s1)
