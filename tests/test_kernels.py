"""Pallas kernel library numeric tests (interpret mode on CPU — the
hardware-free kernel test path, mirroring the reference's OpTest numeric
comparisons vs reference implementations, SURVEY.md §4)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import importlib

# the package re-exports the callable under the submodule's name, so reach
# the module itself through sys.modules
fa_mod = importlib.import_module("paddle_tpu.kernels.flash_attention")
flash_attention = fa_mod.flash_attention
from paddle_tpu.kernels.rms_norm import rms_norm as fused_rms
from paddle_tpu.nn.functional.attention import sdpa_reference

RNG = np.random.default_rng(7)


def rand(shape, dtype=jnp.float32):
    return jnp.asarray(RNG.normal(size=shape), dtype)


class TestFlashAttention:
    @pytest.mark.parametrize("B,S,H,KV,D,causal", [
        (2, 128, 4, 4, 64, False),
        (2, 256, 4, 2, 64, True),     # GQA + causal
        (1, 128, 8, 2, 128, True),
    ])
    def test_forward_matches_reference(self, B, S, H, KV, D, causal):
        q, k, v = rand((B, S, H, D)), rand((B, S, KV, D)), rand((B, S, KV, D))
        ref = sdpa_reference(q, k, v, causal=causal)
        out = flash_attention(q, k, v, causal=causal, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_backward_matches_reference(self):
        B, S, H, KV, D = 2, 128, 4, 2, 64
        q, k, v = rand((B, S, H, D)), rand((B, S, KV, D)), rand((B, S, KV, D))

        def lf(q, k, v):
            return (flash_attention(q, k, v, causal=True,
                                    interpret=True) ** 2).sum()

        def lr(q, k, v):
            return (sdpa_reference(q, k, v, causal=True) ** 2).sum()

        g1 = jax.grad(lf, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=5e-4)

    def test_unsupported_shapes_detected(self):
        q = rand((1, 100, 4, 64))   # 100 not divisible by block
        k = v = rand((1, 100, 4, 64))
        assert not fa_mod.supported(q, k, v)

    def test_dispatch_seam(self):
        """register() routes F.scaled_dot_product_attention through the
        dispatcher (with XLA fallback for unsupported shapes)."""
        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F
        from paddle_tpu import kernels
        from paddle_tpu.nn.functional import attention as att
        q = rand((1, 64, 2, 32))
        try:
            kernels.register()
            assert att._FLASH_IMPL is not None
            out = F.scaled_dot_product_attention(
                paddle.to_tensor(np.asarray(q)),
                paddle.to_tensor(np.asarray(q)),
                paddle.to_tensor(np.asarray(q)), is_causal=True)
            ref = sdpa_reference(q, q, q, causal=True)
            np.testing.assert_allclose(out.numpy(), np.asarray(ref),
                                       rtol=1e-5, atol=1e-5)
        finally:
            kernels.unregister()


class TestFusedRMSNorm:
    def test_forward_backward_match(self):
        n, d = 256, 128
        x = rand((n, d))
        w = rand((d,)) * 0.1 + 1.0

        def ref(x, w):
            xf = x.astype(jnp.float32)
            r = jax.lax.rsqrt(jnp.mean(xf * xf, -1, keepdims=True) + 1e-6)
            return xf * r * w

        y = fused_rms(x, w, 1e-6, 256, True)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref(x, w)),
                                   rtol=1e-5, atol=1e-5)

        g1 = jax.grad(lambda x, w: (fused_rms(x, w, 1e-6, 256, True)
                                    ** 2).sum(), argnums=(0, 1))(x, w)
        g2 = jax.grad(lambda x, w: (ref(x, w) ** 2).sum(),
                      argnums=(0, 1))(x, w)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_3d_input(self):
        x = rand((4, 32, 64))
        w = jnp.ones((64,))
        y = fused_rms(x, w, 1e-6, 128, True)
        assert y.shape == x.shape


class TestCausalAlignment:
    def test_causal_cross_length_bottom_right(self):
        """causal with Sq != Sk must use bottom-right alignment like
        sdpa (chunked prefill pattern)."""
        q = rand((1, 64, 2, 32))
        k = rand((1, 128, 2, 32))
        v = rand((1, 128, 2, 32))
        ref = sdpa_reference(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_cross_length_backward(self):
        q = rand((1, 64, 2, 32))
        k = rand((1, 128, 2, 32))
        v = rand((1, 128, 2, 32))
        g1 = jax.grad(lambda q, k, v: (flash_attention(
            q, k, v, causal=True, interpret=True) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(lambda q, k, v: (sdpa_reference(
            q, k, v, causal=True) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=5e-4)


class TestDispatchGuards:
    def test_rms_broadcastable_weight_falls_back(self):
        """2-D / broadcastable weights must take the XLA path, with the
        same promoted output dtype as the unregistered op."""
        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F
        from paddle_tpu import kernels
        x = paddle.to_tensor(np.random.randn(8, 128).astype("float32"))
        w2d = paddle.to_tensor(np.ones((1, 128), "float32"))
        ref = F.rms_norm(x, w2d).numpy()
        try:
            kernels.register()
            out = F.rms_norm(x, w2d).numpy()
        finally:
            kernels.unregister()
        np.testing.assert_allclose(out, ref, rtol=1e-6)

    def test_rms_dtype_promotion_matches(self):
        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F
        from paddle_tpu import kernels
        x = paddle.to_tensor(np.random.randn(8, 128).astype("float32")).astype("bfloat16")
        w = paddle.to_tensor(np.ones((128,), "float32"))
        ref = F.rms_norm(x, w)
        try:
            kernels.register()
            out = F.rms_norm(x, w)
        finally:
            kernels.unregister()
        assert out.dtype == ref.dtype, (out.dtype, ref.dtype)

    def test_lazy_register_no_backend_probe(self):
        """auto_register's dispatchers only probe the backend at call
        time; registering must not initialize anything."""
        from paddle_tpu import kernels
        from paddle_tpu.nn.functional import attention as att
        try:
            kernels.register(tpu_only=True)
            assert att._FLASH_IMPL is not None
            # off-TPU it must route to the XLA reference path
            q = rand((1, 64, 2, 32))
            out = att._FLASH_IMPL(q, q, q, causal=True)
            ref = sdpa_reference(q, q, q, causal=True)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref))
        finally:
            kernels.unregister()
