"""Pipeline schedule family tests: validity, memory bounds, makespan, and
loss/grad parity vs single-stage on heterogeneous stages.

Mirrors the reference's hybrid_parallel_pp_* loss-parity discipline
(test/collective/fleet/, SURVEY.md §4) realized single-process.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.nn import functional as F
from paddle_tpu.distributed.fleet.pipeline_schedules import (
    Action, build_schedule, fthenb, interleaved_1f1b, one_f_one_b,
    peak_live_activations, validate_schedule, zero_bubble_h1)
from paddle_tpu.distributed.fleet.pp_layers import PipelineLayer, LayerDesc
from paddle_tpu.distributed.fleet.pipeline_runtime import PipelineParallel


# ---------------------------------------------------------------------------
# schedule statics
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("S,M", [(2, 2), (2, 6), (4, 4), (4, 8), (3, 5)])
@pytest.mark.parametrize("name", ["FThenB", "1F1B", "ZBH1"])
def test_schedule_valid(name, S, M):
    validate_schedule(build_schedule(name, S, M), M)


@pytest.mark.parametrize("S,M,v", [(2, 2, 2), (2, 4, 2), (4, 4, 2),
                                   (2, 4, 3), (4, 8, 2)])
def test_interleaved_valid(S, M, v):
    validate_schedule(build_schedule("1F1B-Interleave", S, M, v), M, v)


def test_interleaved_requires_multiple():
    with pytest.raises(ValueError):
        interleaved_1f1b(4, 6, 2)


def test_memory_bounds():
    S, M = 4, 8
    gp = fthenb(S, M)
    fb = one_f_one_b(S, M)
    zb = zero_bubble_h1(S, M)
    for s in range(S):
        assert peak_live_activations(gp[s]) == M
        assert peak_live_activations(fb[s]) <= min(S - s, M)
        assert peak_live_activations(zb[s]) <= min(2 * (S - s), M)


def _makespan(sched, costs):
    """Tick simulation: each stage executes its next action when its data
    dependency is satisfied (produced at an earlier finish time)."""
    S = len(sched)
    # dependency products: F(p,m) -> y; B/BI(p,m) -> dx
    finish = {}
    ptr = [0] * S
    t_free = [0] * S
    P_total = S  # v=1 only

    def dep_time(s, a):
        p = a.chunk * S + s
        if a.kind == "F":
            return 0 if p == 0 else finish.get(("y", p - 1, a.micro))
        if a.kind in ("B", "BI"):
            if p == P_total - 1:
                return finish.get(("y", p, a.micro))
            return finish.get(("dx", p + 1, a.micro))
        return finish.get(("bi", p, a.micro))   # BW after BI

    done = 0
    total = sum(len(x) for x in sched)
    while done < total:
        progressed = False
        for s in range(S):
            if ptr[s] >= len(sched[s]):
                continue
            a = sched[s][ptr[s]]
            d = dep_time(s, a)
            if d is None:
                continue
            start = max(t_free[s], d)
            end = start + costs[a.kind]
            t_free[s] = end
            p = a.chunk * S + s
            if a.kind == "F":
                finish[("y", p, a.micro)] = end
            elif a.kind == "B":
                finish[("dx", p, a.micro)] = end
            elif a.kind == "BI":
                finish[("dx", p, a.micro)] = end
                finish[("bi", p, a.micro)] = end
            ptr[s] += 1
            done += 1
            progressed = True
        assert progressed, "schedule deadlocked in simulation"
    return max(t_free)


@pytest.mark.parametrize("S,M", [(3, 6), (4, 8), (4, 16)])
def test_zero_bubble_beats_1f1b(S, M):
    # F=1 tick; full B = BI+BW = 2 ticks; split jobs 1 tick each.
    costs = {"F": 1, "B": 2, "BI": 1, "BW": 1}
    t_1f1b = _makespan(one_f_one_b(S, M), costs)
    t_zb = _makespan(zero_bubble_h1(S, M), costs)
    assert t_zb < t_1f1b


# ---------------------------------------------------------------------------
# loss/grad parity on heterogeneous stages (embedding -> blocks -> CE head)
# ---------------------------------------------------------------------------

VOCAB, DIM, CLS = 17, 16, 5


class Block(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(DIM, DIM)

    def forward(self, x):
        return F.tanh(self.fc(x))


class Head(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(DIM, CLS)

    def forward(self, x):
        return self.fc(x)


def _ce(logits, labels):
    # mean CE over all positions
    return F.cross_entropy(logits.reshape([-1, CLS]),
                           labels.reshape([-1])).mean()


def _build_pipe(parts):
    descs = [LayerDesc(nn.Embedding, VOCAB, DIM),
             LayerDesc(Block), LayerDesc(Block), LayerDesc(Block),
             LayerDesc(Block), LayerDesc(Block),
             LayerDesc(Head)]
    return PipelineLayer(descs, num_stages=parts, loss_fn=_ce)


def _eager_reference(pipe, ids, labels):
    """Single-stage: full forward + tape backward."""
    for p in pipe.parameters():
        p.clear_grad()
    out = pipe(ids)
    loss = _ce(out, labels)
    loss.backward()
    grads = {n: np.array(p.grad.numpy())
             for n, p in pipe.named_parameters() if p.grad is not None}
    for p in pipe.parameters():
        p.clear_grad()
    return float(loss.numpy()), grads


def _run_schedule(pipe, ids, labels, schedule, num_stages, num_micro,
                  devices=None):
    pp = PipelineParallel(pipe, num_micro=num_micro, schedule=schedule,
                          num_stages=num_stages, devices=devices)
    loss = pp.forward_backward_pipeline(ids, labels)
    grads = {n: np.array(p.grad.numpy())
             for n, p in pipe.named_parameters() if p.grad is not None}
    for p in pipe.parameters():
        p.clear_grad()
    return float(loss.numpy()), grads


@pytest.mark.parametrize("schedule,num_stages", [
    ("FThenB", 4), ("1F1B", 4), ("ZBH1", 4),
    ("1F1B", 7),                      # one layer per stage, non-uniform
    ("1F1B-Interleave", 2),           # 7 parts not divisible -> skip below
])
def test_pipeline_parity(schedule, num_stages):
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, VOCAB, (8, 6)).astype("int32"))
    labels = paddle.to_tensor(rng.integers(0, CLS, (8, 6)).astype("int32"))

    if schedule == "1F1B-Interleave":
        parts = 4                     # 2 stages x 2 chunks
    else:
        parts = num_stages
    pipe = _build_pipe(parts if parts != 7 else 7)
    ref_loss, ref_grads = _eager_reference(pipe, ids, labels)

    loss, grads = _run_schedule(pipe, ids, labels, schedule, num_stages,
                                num_micro=4)
    assert np.allclose(loss, ref_loss, rtol=1e-5, atol=1e-5)
    assert set(grads) == set(ref_grads)
    for n in ref_grads:
        np.testing.assert_allclose(grads[n], ref_grads[n],
                                   rtol=1e-4, atol=1e-5, err_msg=n)


def test_pipeline_parity_multi_device():
    """Stages placed on distinct CPU devices — exercises the activation
    transfer ('p2p') path."""
    rng = np.random.default_rng(1)
    ids = paddle.to_tensor(rng.integers(0, VOCAB, (8, 6)).astype("int32"))
    labels = paddle.to_tensor(rng.integers(0, CLS, (8, 6)).astype("int32"))
    pipe = _build_pipe(4)
    ref_loss, ref_grads = _eager_reference(pipe, ids, labels)
    loss, grads = _run_schedule(pipe, ids, labels, "1F1B", 4, num_micro=4,
                                devices="auto")
    assert np.allclose(loss, ref_loss, rtol=1e-5, atol=1e-5)
    for n in ref_grads:
        np.testing.assert_allclose(grads[n], ref_grads[n],
                                   rtol=1e-4, atol=1e-5, err_msg=n)


def test_pipeline_train_batch_step():
    """train_batch applies the optimizer and the loss goes down."""
    rng = np.random.default_rng(2)
    ids = paddle.to_tensor(rng.integers(0, VOCAB, (8, 6)).astype("int32"))
    labels = paddle.to_tensor(rng.integers(0, CLS, (8, 6)).astype("int32"))
    pipe = _build_pipe(4)
    pp = PipelineParallel(pipe, num_micro=4, schedule="1F1B")
    opt = paddle.optimizer.SGD(learning_rate=0.5,
                               parameters=pipe.parameters())
    losses = [float(pp.train_batch(ids, labels, opt).numpy())
              for _ in range(6)]
    assert losses[-1] < losses[0]
