"""ZeRO stage 1/2/3 semantics with memory evidence (VERDICT r2 ask 8).

Reference capability: python/paddle/distributed/fleet/meta_parallel/sharding/
group_sharded_stage2.py:46 (per-rank grad segments) and
group_sharded_stage3.py:85 (parameter sharding with memory reduction).
Evidence here is live-array accounting (distributed.per_device_bytes) on
the 8-virtual-device CPU mesh: stage-3 must actually store ~1/8 of the
parameter bytes per device, stage-2 ~1/8 of the gradient bytes, and the
sharded run must match the unsharded run numerically.
"""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.distributed as dist


def make_mesh(*shape, names=None):
    return dist.ProcessMesh(
        np.arange(int(np.prod(shape))).reshape(shape), names)


def total_bytes(params):
    return sum(int(np.prod(p.shape)) * p._data.dtype.itemsize
               for p in params)


def build_mlp(seed=3):
    pt.seed(seed)
    import paddle_tpu.nn as nn

    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.l1 = nn.Linear(64, 128)
            self.l2 = nn.Linear(128, 64)
            self.l3 = nn.Linear(64, 8)

        def forward(self, x):
            h = pt.nn.functional.gelu(self.l1(x))
            h = pt.nn.functional.gelu(self.l2(h))
            return self.l3(h)

    return MLP()


class TestZeroMemoryEvidence:
    @pytest.mark.slow  # tier-1 budget (ISSUE 3): heavy; run in the slow lane
    def test_stage3_param_bytes_one_over_n(self):
        mesh = make_mesh(8, names=["dp"])
        model = build_mlp()
        params = list(model.parameters())
        full = total_bytes(params)

        opt = dist.shard_optimizer(
            pt.optimizer.AdamW(learning_rate=1e-3, parameters=params),
            dist.ShardingStage3("dp", mesh))
        x = pt.to_tensor(np.random.randn(8, 64).astype("float32"))
        model(x).sum().backward()
        opt.step()

        per_dev = dist.per_device_bytes(model.parameters())
        assert len(per_dev) == 8
        # dim-0-divisible params shard 8-ways; biases of size 8 shard too;
        # only the (8,)-shaped l3 bias may replicate. Require < 1.30/8.
        for d, nbytes in per_dev.items():
            assert nbytes <= full * 1.30 / 8, (
                f"stage-3 device {d} stores {nbytes}B of {full}B "
                f"(> 1.30/8)")

    def test_stage1_params_replicated_moments_sharded(self):
        mesh = make_mesh(8, names=["dp"])
        model = build_mlp()
        params = list(model.parameters())
        full = total_bytes(params)

        opt = dist.shard_optimizer(
            pt.optimizer.AdamW(learning_rate=1e-3, parameters=params),
            dist.ShardingStage1("dp", mesh))
        x = pt.to_tensor(np.random.randn(8, 64).astype("float32"))
        model(x).sum().backward()
        opt.step()

        # params stay full on every device at stage 1
        per_dev = dist.per_device_bytes(model.parameters())
        for d, nbytes in per_dev.items():
            assert nbytes >= full * 0.99

        # but moment accumulators are ~1/8 per device
        accs = []
        for acc_map in opt._inner._accumulators.values():
            accs.extend(a for a in acc_map.values()
                        if hasattr(a, "addressable_shards"))
        assert accs
        acc_total = sum(int(np.prod(a.shape)) * a.dtype.itemsize
                        for a in accs)
        acc_per_dev = dist.per_device_bytes(accs)
        for d, nbytes in acc_per_dev.items():
            assert nbytes <= acc_total * 1.30 / 8

    def test_stage2_gradient_scatter_view(self):
        """Stage-2: after placement, each device owns ~1/8 of grad bytes
        (the reduce-scatter view) while params remain replicated."""
        mesh = make_mesh(8, names=["dp"])
        model = build_mlp()
        params = list(model.parameters())
        opt = dist.shard_optimizer(
            pt.optimizer.SGD(learning_rate=0.1, parameters=params),
            dist.ShardingStage2("dp", mesh))
        x = pt.to_tensor(np.random.randn(8, 64).astype("float32"))
        model(x).sum().backward()
        opt._apply_stage()

        grads = [p.grad for p in params if p.grad is not None]
        assert grads
        gfull = total_bytes(grads)
        g_per_dev = dist.per_device_bytes(grads)
        for d, nbytes in g_per_dev.items():
            assert nbytes <= gfull * 1.30 / 8, (
                f"stage-2 grads on {d}: {nbytes}B of {gfull}B")
        # params NOT sharded at stage 2
        p_per_dev = dist.per_device_bytes(params)
        pfull = total_bytes(params)
        for d, nbytes in p_per_dev.items():
            assert nbytes >= pfull * 0.99

    def test_stage3_beats_stage1_memory(self):
        """The headline claim: stage-3 per-device param+moment footprint is
        a small fraction of stage-1's."""
        def footprint(stage_cls):
            mesh = make_mesh(8, names=["dp"])
            model = build_mlp()
            params = list(model.parameters())
            opt = dist.shard_optimizer(
                pt.optimizer.AdamW(learning_rate=1e-3, parameters=params),
                stage_cls("dp", mesh))
            x = pt.to_tensor(np.random.randn(8, 64).astype("float32"))
            model(x).sum().backward()
            opt.step()
            tensors = list(model.parameters())
            for acc_map in opt._inner._accumulators.values():
                tensors.extend(a for a in acc_map.values()
                               if hasattr(a, "addressable_shards"))
            return max(dist.per_device_bytes(tensors).values())

        s1 = footprint(dist.ShardingStage1)
        s3 = footprint(dist.ShardingStage3)
        # stage-1 keeps params replicated (params ≈ 1/3 of p+m1+m2 bytes);
        # stage-3 shards everything: expect <= ~45% of stage-1's footprint
        assert s3 <= 0.45 * s1, (s1, s3)


class TestZeroParity:
    @pytest.mark.parametrize("stage_cls", [dist.ShardingStage1,
                                           dist.ShardingStage2,
                                           dist.ShardingStage3])
    def test_training_matches_unsharded(self, stage_cls):
        mesh = make_mesh(8, names=["dp"])
        rng = np.random.default_rng(0)
        xin = rng.normal(size=(8, 64)).astype("float32")
        tgt = rng.normal(size=(8, 8)).astype("float32")

        def run(shard):
            model = build_mlp(seed=11)
            params = list(model.parameters())
            opt = pt.optimizer.AdamW(learning_rate=1e-2, parameters=params)
            if shard:
                opt = dist.shard_optimizer(opt, stage_cls("dp", mesh))
            losses = []
            for _ in range(5):
                loss = ((model(pt.to_tensor(xin))
                         - pt.to_tensor(tgt)) ** 2).mean()
                loss.backward()
                opt.step()
                opt.clear_grad()
                losses.append(float(loss.numpy()))
            return losses

        base = run(False)
        sharded = run(True)
        np.testing.assert_allclose(sharded, base, rtol=2e-4, atol=1e-5)
        assert sharded[-1] < sharded[0]
