"""Operator plane (monitor/server.py + programs.py + memory.py +
fleet.py, scripts/check_bench_regression.py).

The load-bearing contracts:

- **Off path**: with both monitor flags unset, building/running an
  engine leaves ZERO server threads, sockets, and metric
  registrations — the operator plane is free until asked for.
- **Server lifecycle**: port-0 ephemeral bind, idempotent start,
  clean stop (socket released, thread joined), concurrent scrapes
  while a ServingEngine decodes on the main thread.
- **Liveness**: /healthz flips non-200 when a HangWatchdog deadline is
  blown and recovers on heartbeat; broken providers report but never
  fail liveness; dead (garbage-collected) owners self-prune.
- **Introspection**: a fresh to_static compile appears in /programs
  with signature/compile-ms/FLOPs and a lazily-analyzed XLA memory
  breakdown; serving programs register with their donation maps.
- **Exposition conformance**: expose_text emits strictly parseable
  Prometheus text format 0.0.4 (HELP/TYPE discipline, escaping,
  cumulative le buckets, _sum/_count consistency).
- **Fleet aggregation**: min/max/sum/per-host views + divergence, the
  same on every rank (2-process launch CLI, slow lane), served from
  rank 0's /metrics?scope=fleet without peers joining the scrape.
- **Bench guard**: the checked-in BENCH_r*.json trajectory passes;
  synthetic regressions beyond the noise tolerance fail.
"""
import importlib.util
import json
import math
import os
import re
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import monitor
from paddle_tpu.monitor import exposition
from paddle_tpu.monitor import fleet
from paddle_tpu.monitor import memory as mon_memory
from paddle_tpu.monitor import programs
from paddle_tpu.monitor import server
from paddle_tpu.monitor.registry import StatRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def mon():
    """Monitor flag on, clean registry; server + flags torn down."""
    monitor.reset()
    server.stop_server()
    pt.set_flags({"FLAGS_enable_monitor": True})
    yield monitor
    server.stop_server()
    pt.set_flags({"FLAGS_enable_monitor": False,
                  "FLAGS_enable_monitor_server": False})
    monitor.reset()


def _get(url, timeout=10):
    """(status, body-bytes) — non-2xx does not raise."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _tiny_engine(num_slots=2, max_new=None):
    from paddle_tpu.inference import ServingEngine
    from paddle_tpu.models import llama as L
    cfg = L.llama_tiny()
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    return ServingEngine(L, params, cfg, num_slots=num_slots,
                         max_len=32, page_size=4, decode_chunk=3), cfg


def _requests(cfg, n, max_new=4, seed=0):
    from paddle_tpu.inference import Request
    rng = np.random.default_rng(seed)
    return [Request(rid=i,
                    prompt=rng.integers(0, cfg.vocab_size, (5,))
                    .astype(np.int32),
                    max_new_tokens=max_new) for i in range(n)]


def _server_threads():
    return [t for t in threading.enumerate()
            if t.name == "paddle-tpu-monitor-server"]


# ---------------------------------------------------------------------------
# server lifecycle
# ---------------------------------------------------------------------------

class TestServerLifecycle:
    def test_flag_off_no_thread_no_socket_no_registrations(self):
        """The acceptance off-path: both flags unset -> building and
        running an engine starts nothing and registers nothing."""
        monitor.reset()
        server.stop_server()
        pt.set_flags({"FLAGS_enable_monitor": False,
                      "FLAGS_enable_monitor_server": False})
        assert server.maybe_start() is None
        eng, cfg = _tiny_engine()
        eng.run(_requests(cfg, 1))
        assert server.get_server() is None
        assert server.bound_port() is None
        assert _server_threads() == []
        assert monitor.snapshot() == {}
        assert programs.programs_snapshot() == []
        # ...and no health-provider entry either: a fully-off process
        # must not grow the provider map one entry per engine
        _, payload = server.health()
        assert not any(k.startswith("serving:")
                       for k in payload["providers"])

    def test_ephemeral_bind_scrape_and_stop(self, mon):
        srv = server.start_server(port=0)
        assert srv.port > 0
        assert server.bound_port() == srv.port
        monitor.inc("lifecycle.probe", 2, doc="probe")
        status, body = _get(f"{srv.url}/metrics")
        assert status == 200
        assert "lifecycle_probe 2" in body.decode()
        port = srv.port
        server.stop_server()
        assert server.get_server() is None
        # the socket is actually released
        with pytest.raises(OSError):
            socket.create_connection(("127.0.0.1", port), timeout=0.5)
        time.sleep(0.05)
        assert _server_threads() == []

    def test_start_idempotent_and_maybe_start_gated(self, mon):
        srv = server.start_server(port=0)
        assert server.start_server() is srv
        # flag still off -> maybe_start returns the RUNNING server?
        # no: maybe_start is the flag-gated seam; with the flag off it
        # must stay a no-op branch even while a manual server runs
        assert server.maybe_start() is None
        pt.set_flags({"FLAGS_enable_monitor_server": True})
        assert server.maybe_start() is srv

    def test_engine_entrypoint_starts_server(self, mon):
        pt.set_flags({"FLAGS_enable_monitor_server": True})
        eng, cfg = _tiny_engine()
        srv = server.get_server()
        assert srv is not None, "ServingEngine did not start the server"
        status, body = _get(f"{srv.url}/healthz")
        assert status == 200
        providers = json.loads(body)["providers"]
        assert any(k.startswith("serving:") for k in providers)

    def test_root_index_and_404(self, mon):
        srv = server.start_server(port=0)
        status, body = _get(f"{srv.url}/")
        assert status == 200
        assert "/metrics" in json.loads(body)["routes"]
        status, _ = _get(f"{srv.url}/nope")
        assert status == 404

    def test_flight_endpoint_live_record(self, mon):
        from paddle_tpu.monitor import trace
        srv = server.start_server(port=0)
        with trace.span("op.test", tag=1):
            pass
        status, body = _get(f"{srv.url}/flight")
        assert status == 200
        payload = json.loads(body)
        assert payload["kind"] == "paddle_tpu.flight_record"
        assert payload["reason"] == "operator_scrape"
        assert any(e["name"] == "op.test" for e in payload["events"])
        assert "metrics" in payload


class TestConcurrentScrapes:
    @pytest.mark.slow  # tier-1 budget (ISSUE 19 rebalance): concurrency stress; ephemeral_bind_scrape +
    # engine_entrypoint keep the scrape seam fast
    def test_scrapes_during_live_engine_run(self, mon):
        """The acceptance scenario: while the engine decodes, /metrics
        returns conformant text carrying the serving SLO histograms and
        jit.program.* FLOPs, and concurrent scrapers never error."""
        srv = server.start_server(port=0)
        eng, cfg = _tiny_engine()
        for r in _requests(cfg, 4, max_new=8):
            eng.submit(r)
        results = []
        stop = threading.Event()

        def scraper(route):
            while not stop.is_set():
                status, body = _get(f"{srv.url}{route}")
                results.append((route, status))
                if status != 200:
                    return

        threads = [threading.Thread(target=scraper, args=(route,))
                   for route in ("/metrics", "/healthz", "/programs")
                   for _ in range(2)]
        for t in threads:
            t.start()
        try:
            outs = eng.run()
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)
        assert len(outs) == 4
        assert results, "scrapers never ran"
        assert all(status == 200 for _, status in results), \
            [r for r in results if r[1] != 200]
        status, body = _get(f"{srv.url}/metrics")
        text = body.decode()
        families = parse_prometheus(text)   # conformant under load
        assert "serving_latency_ttft_ms" in families
        assert families["serving_latency_ttft_ms"]["type"] == "histogram"
        assert "jit_program_flops" in families
        assert families["jit_program_flops"]["samples"][0][2] > 0
        assert "serving_tokens_generated" in families


# ---------------------------------------------------------------------------
# /healthz
# ---------------------------------------------------------------------------

class TestHealthz:
    def test_watchdog_stall_flips_503_and_recovers(self, mon):
        from paddle_tpu.training.sentinel import HangWatchdog
        srv = server.start_server(port=0)
        wd = HangWatchdog(deadline_s=0.2, poll_s=0.05, name="hz")
        with wd:
            status, body = _get(f"{srv.url}/healthz")
            assert status == 200
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                status, body = _get(f"{srv.url}/healthz")
                if status == 503:
                    break
                time.sleep(0.05)
            assert status == 503, "healthz never flipped on the stall"
            payload = json.loads(body)
            assert payload["status"] == "unhealthy"
            rep = next(v for k, v in payload["providers"].items()
                       if k.startswith("watchdog:hz:"))
            assert rep["ok"] is False
            assert rep["last_heartbeat_age_s"] > 0.2
            # recovery: a heartbeat re-arms liveness on the next probe
            wd.heartbeat()
            status, body = _get(f"{srv.url}/healthz")
            assert status == 200
            assert json.loads(body)["status"] == "ok"
        # stop() unregisters exactly this instance's provider
        ok, payload = server.health()
        assert not any(k.startswith("watchdog:hz")
                       for k in payload["providers"])

    def test_broken_provider_reports_but_keeps_liveness(self, mon):
        def boom():
            raise RuntimeError("telemetry hook crashed")
        server.register_health_provider("boom", boom)
        try:
            ok, payload = server.health()
            assert ok
            assert "telemetry hook crashed" in \
                payload["providers"]["boom"]["error"]
        finally:
            server.unregister_health_provider("boom")

    def test_dead_owner_self_prunes_and_engines_coexist(self, mon):
        eng, cfg = _tiny_engine()
        eng2, _ = _tiny_engine(num_slots=1)
        ok, payload = server.health()
        serving = {k: v for k, v in payload["providers"].items()
                   if k.startswith("serving:")}
        # two live engines = two providers (neither evicts the other)
        assert len(serving) == 2
        assert {v["num_slots"] for v in serving.values()} == {1, 2}
        del eng, eng2
        import gc
        gc.collect()
        ok, payload = server.health()
        assert not any(k.startswith("serving:")
                       for k in payload["providers"])

    def test_sentinel_loop_ladder_state(self, mon):
        from paddle_tpu.training.sentinel import (AnomalySentinel,
                                                  SentinelConfig,
                                                  SentinelLoop)
        sent = AnomalySentinel(SentinelConfig(agree=False, name="hzt"))
        loop = SentinelLoop(lambda *a: None, {}, {},
                            lambda: iter(()), sentinel=sent)
        ok, payload = server.health()
        key, rep = next((k, v) for k, v in payload["providers"].items()
                        if k.startswith("sentinel:"))
        assert ok and rep["ok"] and rep["rollbacks"] == 0
        # a loop that burned its rollback budget is alive but cannot
        # recover itself -> unhealthy (supervisor should replace it)
        sent.rollbacks = sent.config.max_rollbacks
        ok, payload = server.health()
        assert not ok
        assert payload["providers"][key]["ok"] is False
        del loop


# ---------------------------------------------------------------------------
# /programs + /memory introspection
# ---------------------------------------------------------------------------

class TestPrograms:
    def test_fresh_compile_lands_in_programs_endpoint(self, mon):
        import paddle_tpu.jit as jit
        import paddle_tpu.nn as nn
        srv = server.start_server(port=0)
        net = nn.Linear(4, 2)
        sf = jit.to_static(net.forward)
        x = pt.to_tensor(np.ones((3, 4), "float32"))
        with pt.no_grad():
            sf(x)
            sf(x)
        status, body = _get(f"{srv.url}/programs")
        assert status == 200
        recs = json.loads(body)["programs"]
        rec = next(r for r in recs if r["name"] == "forward")
        assert "float32[3,4]" in rec["signature"]
        assert rec["compile_ms"] > 0
        assert rec["flops"] > 0
        assert rec["hits"] == 1
        # the endpoint resolved the lazy XLA memory analysis
        assert rec["memory"] is not None
        for k in ("argument_bytes", "output_bytes", "temp_bytes"):
            assert k in rec["memory"]
        # ...and the byte gauges now exist for /metrics
        gauges = monitor.snapshot()["gauges"]
        assert "jit.program.last_argument_bytes" in gauges
        assert gauges["jit.program.count"] >= 1

    @pytest.mark.slow
    def test_serving_programs_carry_donation_map(self, mon):
        # engine-construction-heavy; the concurrent-scrape acceptance
        # test already proves serving programs register with FLOPs, so
        # the donation-map pin rides the slow lane
        eng, cfg = _tiny_engine()
        eng.run(_requests(cfg, 2))
        recs = programs.programs_snapshot()
        by_name = {r["name"]: r for r in recs}
        chunk = next(v for k, v in by_name.items()
                     if k.startswith("serving.decode_chunk"))
        assert chunk["donated_args"] == [1, 2]     # the KV pools
        prefill = next(v for k, v in by_name.items()
                       if k.startswith("serving.prefill"))
        assert prefill["donated_args"] == [2, 3]
        assert chunk["flops"] > 0

    @pytest.mark.slow
    def test_monitor_reset_recovers_serving_registration(self, mon):
        """The registry is the dedup: after monitor.reset() mid-run, a
        live engine's next dispatch re-registers its programs (an
        engine-local seen-set would leave /programs and the headroom
        temp reservation empty forever). Engine-construction-heavy ->
        slow lane."""
        eng, cfg = _tiny_engine()
        eng.run(_requests(cfg, 1))
        assert programs.programs_snapshot()
        monitor.reset()
        assert programs.programs_snapshot() == []
        eng.run(_requests(cfg, 1, seed=1))
        names = [r["name"] for r in programs.programs_snapshot()]
        assert any(n.startswith("serving.") for n in names), names

    def test_registry_bounded_fifo(self, mon):
        for i in range(300):
            programs.record_program(("t", i), f"p{i}", source="test")
        snap = programs.programs_snapshot()
        assert len(snap) == 256
        assert programs.evicted_count() == 44
        assert snap[0]["name"] == "p299"           # newest first
        assert all(r["name"] != "p0" for r in snap)

    def test_monitor_off_registers_nothing(self):
        monitor.reset()
        pt.set_flags({"FLAGS_enable_monitor": False})
        import paddle_tpu.jit as jit
        import paddle_tpu.nn as nn
        sf = jit.to_static(nn.Linear(3, 3).forward)
        with pt.no_grad():
            sf(pt.to_tensor(np.ones((2, 3), "float32")))
        assert programs.programs_snapshot() == []
        assert monitor.snapshot() == {}

    def test_dead_owner_analyzer_reports_not_raises(self, mon):
        import paddle_tpu.jit as jit
        import paddle_tpu.nn as nn
        net = nn.Linear(4, 2)
        sf = jit.to_static(net.forward)
        with pt.no_grad():
            sf(pt.to_tensor(np.ones((2, 4), "float32")))
        del sf, net
        import gc
        gc.collect()
        programs.analyze_pending()
        rec = programs.programs_snapshot()[0]
        assert rec["memory"] is None
        assert "ReferenceError" in rec.get("analyze_error", "") or \
            rec.get("analyze_error")


class TestMemoryIntrospection:
    def test_device_helper_backend_safe(self):
        from paddle_tpu.device.memory import memory_stats

        class NoneDev:
            def memory_stats(self):
                return None

        class RaisingDev:
            def memory_stats(self):
                raise RuntimeError("backend says no")

        class PartialDev:
            def memory_stats(self):
                return {"bytes_in_use": 5}

        assert memory_stats(NoneDev()) == {}
        assert memory_stats(RaisingDev()) == {}
        assert memory_stats(PartialDev()) == {"bytes_in_use": 5}

    def test_cuda_parity_path_uses_helper(self):
        # CPU backend reports nothing -> the paddle-parity queries
        # answer 0 without raising (the old behavior, now via the
        # shared helper)
        from paddle_tpu.device import cuda
        assert cuda.memory_allocated() == 0
        assert cuda.max_memory_allocated() == 0
        assert cuda.get_device_properties().total_memory == 0

    def test_no_fake_gauges_on_silent_backend(self, mon):
        stats = mon_memory.update_hbm_gauges(stats_fn=lambda: [{}, {}])
        assert stats["totals"] == {}
        gauges = monitor.snapshot().get("gauges", {})
        assert not any(k.startswith("device.hbm") for k in gauges)

    def test_hbm_gauges_sum_reporting_devices(self, mon):
        fake = [{"bytes_in_use": 10, "bytes_limit": 100,
                 "peak_bytes_in_use": 40},
                {},                                  # silent device
                {"bytes_in_use": 30, "bytes_limit": 100}]
        stats = mon_memory.update_hbm_gauges(stats_fn=lambda: fake)
        assert stats["devices_reporting"] == 2
        g = monitor.snapshot()["gauges"]
        assert g["device.hbm.bytes_in_use"] == 40
        assert g["device.hbm.bytes_limit"] == 200
        assert g["device.hbm.peak_bytes_in_use"] == 40
        assert g["device.hbm.headroom_bytes"] == 160

    def test_headroom_composes_pages_and_program_temps(self, mon):
        monitor.set_gauge("serving.pages.total", 20)
        monitor.set_gauge("serving.pages.in_use", 5)
        programs.record_program(
            ("hr", 0), "big", source="test",
            analyzer=lambda: {"temp_bytes": 30})
        programs.analyze_pending()
        fake = [{"bytes_in_use": 10, "bytes_limit": 110}]
        hr = mon_memory.headroom(stats_fn=lambda: fake)
        assert hr["pages_total"] == 20
        assert hr["pages_free_fraction"] == 0.75
        assert hr["program_temp_bytes_max"] == 30
        assert hr["hbm_free_bytes"] == 100
        assert hr["est_admittable_bytes"] == 70
        g = monitor.snapshot()["gauges"]
        assert g["serving.headroom.pages_free_fraction"] == 0.75

    def test_memory_endpoint(self, mon):
        srv = server.start_server(port=0)
        status, body = _get(f"{srv.url}/memory")
        assert status == 200
        payload = json.loads(body)
        assert "hbm" in payload and "headroom" in payload
        # CPU backend: nothing reported, nothing fabricated
        assert payload["hbm"]["totals"] == {}
        assert payload["headroom"]["hbm_free_bytes"] is None


# ---------------------------------------------------------------------------
# Prometheus exposition conformance (strict format)
# ---------------------------------------------------------------------------

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_SAMPLE_RE = re.compile(
    rf"^({_NAME})(\{{(?:[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\.)*\""
    rf"(?:,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\.)*\")*)?\}})? "
    r"(-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?|\+Inf|-Inf|NaN)$")
_LABEL_RE = re.compile(r"([a-zA-Z_][a-zA-Z0-9_]*)=\"((?:[^\"\\\n]|\\.)*)\"")


def _unescape_label(v: str) -> str:
    return (v.replace("\\n", "\n").replace('\\"', '"')
            .replace("\\\\", "\\"))


def _parse_value(s: str) -> float:
    return {"+Inf": math.inf, "-Inf": -math.inf,
            "NaN": math.nan}.get(s, None) if s in ("+Inf", "-Inf", "NaN") \
        else float(s)


def parse_prometheus(text: str) -> dict:
    """Strict 0.0.4 parser. Raises AssertionError on any violation:
    unknown line shape, sample before its TYPE, duplicate TYPE, help
    after samples started. Returns {family: {"type", "help",
    "samples": [(name, labels-dict, value)]}}."""
    families: dict = {}
    assert text.endswith("\n") or text == "", "missing trailing newline"
    for line in text.splitlines():
        assert line == line.strip("\r"), f"stray CR in {line!r}"
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            assert re.fullmatch(_NAME, name), f"bad HELP name {name!r}"
            fam = families.setdefault(
                name, {"type": None, "help": None, "samples": []})
            assert not fam["samples"], f"HELP after samples for {name}"
            fam["help"] = help_text
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            assert re.fullmatch(_NAME, name), f"bad TYPE name {name!r}"
            assert kind in ("counter", "gauge", "histogram", "summary",
                            "untyped"), f"bad kind {kind!r}"
            fam = families.setdefault(
                name, {"type": None, "help": None, "samples": []})
            assert fam["type"] is None, f"duplicate TYPE for {name}"
            fam["type"] = kind
            continue
        assert not line.startswith("#"), f"unparseable comment {line!r}"
        if not line:
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable sample line {line!r}"
        sname, labels_blob, value = m.group(1), m.group(2), m.group(3)
        labels = {}
        if labels_blob:
            labels = {k: _unescape_label(v)
                      for k, v in _LABEL_RE.findall(labels_blob)}
        # a histogram's series attach to the base family
        base = sname
        for suffix in ("_bucket", "_sum", "_count"):
            if sname.endswith(suffix) and sname[:-len(suffix)] \
                    in families and families[sname[:-len(suffix)]][
                        "type"] == "histogram":
                base = sname[:-len(suffix)]
        fam = families.get(base)
        assert fam is not None and fam["type"] is not None, \
            f"sample {sname!r} before its TYPE line"
        fam["samples"].append((sname, labels, _parse_value(value)))
    return families


class TestExpositionConformance:
    def _nasty_registry(self):
        r = StatRegistry()
        r.counter("ops.total",
                  'line1\nline2 "quoted" and \\backslash').incr(3)
        g = r.gauge("queue.depth", "plain doc")
        g.set(7)
        h = r.histogram("lat.ms", "latency", buckets=(1.0, 5.0, 25.0))
        for v in (0.5, 3.0, 4.0, 100.0):
            h.observe(v)
        return r

    def test_strict_parse_and_histogram_consistency(self):
        text = exposition.expose_text(self._nasty_registry())
        fams = parse_prometheus(text)
        assert fams["ops_total"]["type"] == "counter"
        assert fams["ops_total"]["samples"] == [("ops_total", {}, 3)]
        # HELP escaping: the raw newline/quote/backslash survive the
        # round trip as escapes, not as format-breaking bytes
        assert "\n" not in fams["ops_total"]["help"]
        assert fams["ops_total"]["help"] == \
            'line1\\nline2 "quoted" and \\\\backslash'
        hist = fams["lat_ms"]
        assert hist["type"] == "histogram"
        buckets = [(s[1]["le"], s[2]) for s in hist["samples"]
                   if s[0] == "lat_ms_bucket"]
        # le ascending, counts cumulative (nondecreasing), +Inf last
        les = [float("inf") if le == "+Inf" else float(le)
               for le, _ in buckets]
        assert les == sorted(les) and les[-1] == float("inf")
        counts = [c for _, c in buckets]
        assert counts == sorted(counts)
        assert buckets == [("1", 1), ("5", 3), ("25", 3), ("+Inf", 4)]
        count = next(s[2] for s in hist["samples"]
                     if s[0] == "lat_ms_count")
        total = next(s[2] for s in hist["samples"]
                     if s[0] == "lat_ms_sum")
        assert count == 4 == counts[-1]
        assert total == pytest.approx(107.5)

    def test_canonical_pin(self):
        """Exact output pin for a minimal registry — scrapers parse
        bytes, so the format is a contract, not a style."""
        r = StatRegistry()
        r.counter("a.count", "doc A").incr(2)
        r.gauge("b.val").set(1.5)
        assert exposition.expose_text(r) == (
            "# HELP a_count doc A\n"
            "# TYPE a_count counter\n"
            "a_count 2\n"
            "# TYPE b_val gauge\n"
            "b_val 1.5\n")

    def test_label_value_escaping_round_trip(self):
        nasty = 'a\\b"c\nd'
        line = exposition.render_sample("m.x", {"host": nasty}, 1)
        m = _SAMPLE_RE.match(line)
        assert m, f"escaped sample does not parse: {line!r}"
        (k, v), = _LABEL_RE.findall(m.group(2))
        assert k == "host"
        assert _unescape_label(v) == nasty

    @pytest.mark.slow
    def test_live_registry_scrape_is_conformant(self, mon):
        # the real registry under a busy engine, via HTTP — redundant
        # with the strict parse inside test_scrapes_during_live_engine_
        # run (same scrape, same parser), so it rides the slow lane
        srv = server.start_server(port=0)
        eng, cfg = _tiny_engine()
        eng.run(_requests(cfg, 2))
        status, body = _get(f"{srv.url}/metrics")
        assert status == 200
        fams = parse_prometheus(body.decode())
        for fam in fams.values():
            assert fam["type"] in ("counter", "gauge", "histogram")


# ---------------------------------------------------------------------------
# fleet aggregation
# ---------------------------------------------------------------------------

class TestFleetAggregation:
    def test_single_process_aggregate(self, mon):
        monitor.set_gauge("fa.gauge", 12.5, doc="g")
        monitor.inc("fa.count", 4, doc="c")
        monitor.observe("fa.lat", 3.0, doc="h")
        agg = fleet.aggregated_snapshot(name="t1")
        assert agg["world_size"] == 1
        s = agg["aggregate"]["scalars"]["fa.gauge"]
        assert s["min"] == s["max"] == s["sum"] == 12.5
        assert s["hosts"] == [12.5]
        h = agg["aggregate"]["histograms"]["fa.lat"]
        assert h["count"] == 1 and h["sum"] == 3.0
        assert agg["divergence"] == []          # one host: no spread
        assert fleet.last_aggregate() is agg

    def test_aggregate_hosts_math_and_divergence(self):
        snaps = [
            {"gauges": {"g.ema": 1.0, "g.only0": 5},
             "counters": {"c.tok": 100}},
            {"gauges": {"g.ema": 1.1}, "counters": {"c.tok": 100}},
            {"gauges": {"g.ema": 9.0}, "counters": {"c.tok": 100}},
        ]
        agg = fleet.aggregate_hosts(snaps)
        ema = agg["scalars"]["g.ema"]
        assert ema["min"] == 1.0 and ema["max"] == 9.0
        assert ema["sum"] == pytest.approx(11.1)
        assert agg["scalars"]["g.only0"]["hosts"] == [5, None, None]
        div = fleet.divergence(agg)
        # the drifting EMA dominates; the identical counter is absent
        assert div[0]["metric"] == "g.ema"
        assert all(d["metric"] != "c.tok" for d in div)
        # a gauge straddling zero (mean ~0) must not blow the ratio up
        # to ~1e9 and bury real divergence — bounded by 2 via |max|
        agg0 = fleet.aggregate_hosts([{"gauges": {"z": 1.0}},
                                      {"gauges": {"z": -1.0}}])
        d0 = fleet.divergence(agg0)
        assert d0[0]["relative_spread"] == pytest.approx(2.0)

    def test_fleet_scope_endpoint_single_process(self, mon):
        srv = server.start_server(port=0)
        monitor.set_gauge("fa.scrape", 3, doc="g")
        status, body = _get(f"{srv.url}/metrics?scope=fleet")
        assert status == 200
        text = body.decode()
        assert 'fa_scrape{agg="sum"} 3' in text
        assert 'fa_scrape{host="0"} 3' in text
        assert "paddle_fleet_world_size 1" in text
        # single-host fleet view is computed FRESH per scrape — a
        # cached payload would freeze the view at its first value
        monitor.set_gauge("fa.scrape", 9, doc="g")
        _, body = _get(f"{srv.url}/metrics?scope=fleet")
        assert 'fa_scrape{agg="sum"} 9' in body.decode()

    def test_fleet_text_of_synthetic_aggregate_parses(self):
        payload = {
            "world_size": 2,
            "aggregate": fleet.aggregate_hosts([
                {"gauges": {"x.y": 1}}, {"gauges": {"x.y": 3}}]),
        }
        fams = parse_prometheus(fleet.expose_fleet_text(payload))
        samples = {(s[0], tuple(sorted(s[1].items()))): s[2]
                   for s in fams["x_y"]["samples"]}
        assert samples[("x_y", (("agg", "min"),))] == 1
        assert samples[("x_y", (("agg", "max"),))] == 3
        assert samples[("x_y", (("agg", "sum"),))] == 4
        assert samples[("x_y", (("host", "1"),))] == 3

    @pytest.mark.slow
    @pytest.mark.slow  # tier-1 budget (ISSUE 19 rebalance): subprocess launch; single-process aggregate +
    # synthetic-aggregate parse pin the math fast
    def test_two_process_launch_agreement(self, tmp_path):
        """Cross-host gather via the launch CLI (KV-store transport —
        no compiled collectives, so it runs on the jax-0.4.37 CPU
        backend where cross-process XLA collectives do not)."""
        worker = os.path.join(REPO, "tests", "_fleet_agg_worker.py")
        log_dir = str(tmp_path / "logs")
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "2", "--log_dir", log_dir, worker],
            capture_output=True, text=True, timeout=420,
            env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO))
        logs = {}
        for rank in range(2):
            p = os.path.join(log_dir, f"workerlog.{rank}")
            logs[rank] = open(p).read() if os.path.exists(p) else ""
        blob = logs[0] + logs[1]
        assert r.returncode == 0, blob[-4000:]
        for rank in range(2):
            assert (f"AGG rank={rank} min=10.0 max=20.0 sum=30.0 "
                    "hosts=[10.0, 20.0]") in blob, blob[-4000:]
            assert f"SHARED rank={rank} min=7 max=7 sum=14" in blob
            assert f"HIST rank={rank} count=2 sum=11.0" in blob
            assert f"DIVERGENT rank={rank} yes" in blob
        # rank 0 served the cached aggregate over HTTP with labels
        assert "FLEETSCRAPE rank=0 min=ok host1=ok" in blob, blob[-4000:]
        # both ranks computed the byte-identical aggregate
        digests = sorted(l.split()[-1] for l in blob.splitlines()
                         if l.startswith("DIGEST"))
        assert len(digests) == 2 and digests[0] == digests[1]


# ---------------------------------------------------------------------------
# bench-trajectory regression guard
# ---------------------------------------------------------------------------

def _load_guard():
    path = os.path.join(REPO, "scripts", "check_bench_regression.py")
    spec = importlib.util.spec_from_file_location(
        "check_bench_regression", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _bench_blob(value, extra=None, error=None):
    rec = {"metric": "llama_train_tokens_per_sec_per_chip",
           "value": value, "unit": "tokens/s"}
    if extra:
        rec["extra"] = extra
    if error:
        rec["error"] = error
    return {"n": 5, "cmd": "python bench.py", "rc": 0,
            "tail": json.dumps(rec) + "\n", "parsed": rec}


class TestBenchRegressionGuard:
    def test_checked_in_trajectory_is_green(self):
        """The tier-1 guard itself: the repo's own bench trajectory
        must pass (this is what keeps future rounds honest)."""
        guard = _load_guard()
        ok, lines = guard.check(REPO)
        assert ok, "\n".join(lines)

    def _write(self, root, rnd, blob):
        with open(os.path.join(root, f"BENCH_r{rnd:02d}.json"),
                  "w") as f:
            json.dump(blob, f)

    def test_regression_beyond_tolerance_fails(self, tmp_path):
        guard = _load_guard()
        root = str(tmp_path)
        self._write(root, 1, _bench_blob(1000.0))
        self._write(root, 2, _bench_blob(800.0))    # -20% > 15% tol
        ok, lines = guard.check(root)
        assert not ok
        assert any("REGRESSION" in l for l in lines)

    def test_noise_within_tolerance_passes(self, tmp_path):
        guard = _load_guard()
        root = str(tmp_path)
        self._write(root, 1, _bench_blob(1000.0))
        self._write(root, 2, _bench_blob(900.0))    # -10% < 15% tol
        ok, lines = guard.check(root)
        assert ok, "\n".join(lines)

    def test_failed_runs_are_skipped_not_zero(self, tmp_path):
        guard = _load_guard()
        root = str(tmp_path)
        self._write(root, 1, _bench_blob(1000.0))
        self._write(root, 2, _bench_blob(
            0.0, error="tpu tunnel relay dead"))
        self._write(root, 3, _bench_blob(990.0))
        ok, lines = guard.check(root)
        assert ok, "\n".join(lines)    # r02 must not read as a 0 floor
        traj = guard.load_trajectory(root)
        assert [rnd for rnd, _ in traj] == [1, 3]

    def test_sub_rungs_guarded_via_allowlist(self, tmp_path):
        guard = _load_guard()
        root = str(tmp_path)
        self._write(root, 1, _bench_blob(
            1000.0, extra={"decode": {"decode_tokens_per_sec": 500.0}}))
        self._write(root, 2, _bench_blob(
            1000.0, extra={"decode": {"decode_tokens_per_sec": 300.0}}))
        ok, lines = guard.check(root)
        assert not ok
        assert any("decode_tokens_per_sec" in l and "REGRESSION" in l
                   for l in lines)
        # a metric OUTSIDE the allowlist never fails the guard
        self._write(root, 2, _bench_blob(
            1000.0, extra={"decode": {"ms_per_token": 99999.0}}))
        ok, _ = guard.check(root)
        assert ok

    def test_missing_rung_in_newest_is_not_failure(self, tmp_path):
        guard = _load_guard()
        root = str(tmp_path)
        self._write(root, 1, _bench_blob(
            1000.0, extra={"moe": {"tokens_per_sec": 100.0}}))
        self._write(root, 2, _bench_blob(1005.0))   # moe rung dropped
        ok, lines = guard.check(root)
        assert ok, "\n".join(lines)
        assert any("absent" in l for l in lines)

    def test_published_floor_from_baseline_json(self, tmp_path):
        guard = _load_guard()
        root = str(tmp_path)
        with open(os.path.join(root, "BASELINE.json"), "w") as f:
            json.dump({"published": {
                "llama_train_tokens_per_sec_per_chip": 2000.0}}, f)
        self._write(root, 1, _bench_blob(1000.0))   # half the published
        ok, lines = guard.check(root)
        assert not ok
        assert any("REGRESSION" in l for l in lines)

    def test_cli_exit_codes(self, tmp_path):
        guard = _load_guard()
        root = str(tmp_path)
        self._write(root, 1, _bench_blob(1000.0))
        assert guard.main(["--root", root]) == 0
        self._write(root, 2, _bench_blob(500.0))
        assert guard.main(["--root", root]) == 1
