"""Worker for the multi-host fleet-aggregation test (run via the
launch CLI, not collected by pytest).

Each rank records rank-distinct metrics, then both ranks call the
COLLECTIVE ``monitor.fleet.aggregated_snapshot()`` at the same program
point (the tagged KV gather — no compiled collectives, so it runs on
the CPU backend where cross-process XLA collectives do not). The
parent test asserts:

- min/max/sum over the rank-distinct gauge are exact on BOTH ranks
  (every rank returns the same aggregate);
- the per-host view carries each rank's own value;
- the divergence report surfaces the rank-skewed metric;
- rank 0's operator-plane server serves the cached aggregate at
  ``/metrics?scope=fleet`` without any peer participating in the
  scrape.
"""
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import urllib.request  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.distributed as dist  # noqa: E402
from paddle_tpu import monitor  # noqa: E402
from paddle_tpu.monitor import fleet, server  # noqa: E402


def main():
    dist.init_parallel_env()
    rank = dist.get_rank()
    paddle.set_flags({"FLAGS_enable_monitor": True})

    monitor.set_gauge("test.fleet.rank_gauge", 10.0 * (rank + 1),
                      doc="rank-distinct gauge (divergence bait)")
    monitor.inc("test.fleet.shared_counter", 7,
                doc="identical on every rank")
    monitor.observe("test.fleet.lat_ms", 5.0 + rank, doc="latency-ish")

    agg = fleet.aggregated_snapshot(name="aggtest")
    s = agg["aggregate"]["scalars"]["test.fleet.rank_gauge"]
    print(f"AGG rank={rank} min={s['min']} max={s['max']} "
          f"sum={s['sum']} hosts={s['hosts']}", flush=True)
    sc = agg["aggregate"]["scalars"]["test.fleet.shared_counter"]
    print(f"SHARED rank={rank} min={sc['min']} max={sc['max']} "
          f"sum={sc['sum']}", flush=True)
    hist = agg["aggregate"]["histograms"]["test.fleet.lat_ms"]
    print(f"HIST rank={rank} count={hist['count']} sum={hist['sum']}",
          flush=True)
    div = [d["metric"] for d in agg["divergence"]]
    print(f"DIVERGENT rank={rank} "
          f"{'yes' if 'test.fleet.rank_gauge' in div else 'no'}",
          flush=True)

    if rank == 0:
        srv = server.start_server(port=0)
        txt = urllib.request.urlopen(
            f"{srv.url}/metrics?scope=fleet", timeout=10).read().decode()
        has_min = 'test_fleet_rank_gauge{agg="min"} 10' in txt
        has_h1 = 'test_fleet_rank_gauge{host="1"} 20' in txt
        print(f"FLEETSCRAPE rank=0 min={'ok' if has_min else 'MISSING'} "
              f"host1={'ok' if has_h1 else 'MISSING'}", flush=True)
        server.stop_server()
    # both ranks must agree on the whole aggregate payload
    import zlib
    digest = zlib.crc32(json.dumps(agg["aggregate"],
                                   sort_keys=True).encode())
    print(f"DIGEST rank={rank} {digest:08x}", flush=True)


if __name__ == "__main__":
    main()
    sys.exit(0)
