"""Tests for nn.utils (weight/spectral norm, grad clip, vector
transforms), nn.quant (weight-only int8/int4), and sparse.nn."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def t(x, **kw):
    return paddle.to_tensor(x, **kw)


class TestNNUtils:
    def test_weight_norm_roundtrip_and_training(self):
        lin = nn.Linear(4, 3)
        w0 = lin.weight.numpy().copy()
        nn.utils.weight_norm(lin, "weight")
        x = t(np.random.default_rng(0).normal(size=(2, 4))
              .astype("float32"))
        out = lin(x)
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   x.numpy() @ w0 + lin.bias.numpy(),
                                   rtol=1e-5)
        # g and v are the trainable parameters now
        names = dict(lin.named_parameters())
        assert "weight_g" in names and "weight_v" in names
        loss = lin(x).sum()
        loss.backward()
        assert np.abs(names["weight_g"].grad.numpy()).sum() > 0
        nn.utils.remove_weight_norm(lin, "weight")
        np.testing.assert_allclose(lin.weight.numpy(), w0, rtol=1e-5)

    def test_spectral_norm_bounds_sv(self):
        lin = nn.Linear(6, 6)
        lin.weight.set_value((np.eye(6) * 5.0).astype("float32"))
        nn.utils.spectral_norm(lin, "weight", n_power_iterations=20)
        _ = lin(t(np.ones((1, 6), "float32")))
        sv = np.linalg.svd(lin.weight.numpy(), compute_uv=False)[0]
        assert abs(sv - 1.0) < 1e-3

    def test_clip_helpers(self):
        p = paddle.create_parameter([3], "float32")
        (t(np.ones(3, "float32")) * p * 100.0).sum().backward()
        total = nn.utils.clip_grad_norm_([p], max_norm=1.0)
        assert float(total.numpy()) > 1.0
        assert abs(np.linalg.norm(p.grad.numpy()) - 1.0) < 1e-4
        nn.utils.clip_grad_value_([p], 0.1)
        assert np.abs(p.grad.numpy()).max() <= 0.1 + 1e-7

    def test_vector_transforms(self):
        lin = nn.Linear(3, 2)
        vec = nn.utils.parameters_to_vector(lin.parameters())
        assert vec.shape == [3 * 2 + 2]
        w0 = lin.weight.numpy().copy()
        nn.utils.vector_to_parameters(vec * 2.0, lin.parameters())
        np.testing.assert_allclose(lin.weight.numpy(), 2.0 * w0, rtol=1e-6)


class TestWeightOnlyQuant:
    def test_int8_roundtrip_and_linear(self):
        from paddle_tpu.nn.quant import (weight_dequantize,
                                         weight_only_linear,
                                         weight_quantize)

        w = np.random.default_rng(1).normal(size=(8, 4)).astype("float32")
        q, s = weight_quantize(t(w))
        assert np.asarray(q.numpy()).dtype == np.int8
        deq = weight_dequantize(q, s, out_dtype="float32")
        np.testing.assert_allclose(np.asarray(deq.numpy()), w,
                                   atol=np.abs(w).max() / 100)
        x = t(np.random.default_rng(2).normal(size=(2, 8))
              .astype("float32"))
        out = weight_only_linear(x, q, weight_scale=s)
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   x.numpy() @ w, rtol=0.05, atol=0.05)

    def test_int4_pack_roundtrip(self):
        from paddle_tpu.nn.quant import (weight_dequantize,
                                         weight_only_linear,
                                         weight_quantize)

        w = np.random.default_rng(3).normal(size=(6, 5)).astype("float32")
        q4, s4 = weight_quantize(t(w), algo="weight_only_int4")
        assert np.asarray(q4.numpy()).shape[0] == 3    # packed pairs
        deq = weight_dequantize(q4, s4, algo="weight_only_int4",
                                out_dtype="float32")
        np.testing.assert_allclose(np.asarray(deq.numpy()), w,
                                   atol=np.abs(w).max() / 6)
        x = t(np.random.default_rng(4).normal(size=(2, 6))
              .astype("float32"))
        out = weight_only_linear(x, q4, weight_scale=s4,
                                 weight_dtype="int4")
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   x.numpy() @ w, rtol=0.25, atol=0.4)

    def test_llm_int8(self):
        from paddle_tpu.nn.quant import llm_int8_linear, weight_quantize

        w = np.random.default_rng(5).normal(size=(4, 3)).astype("float32")
        q, s = weight_quantize(t(w), algo="llm.int8")
        x = np.random.default_rng(6).normal(size=(2, 4)).astype("float32")
        x[0, 1] = 20.0                 # outlier column
        out = llm_int8_linear(t(x), q, weight_scale=s)
        np.testing.assert_allclose(np.asarray(out.numpy()), x @ w,
                                   rtol=0.05, atol=0.2)


class TestSparseNN:
    def test_activations_and_softmax(self):
        import paddle_tpu.sparse as sp

        dense = np.array([[0.0, 7.0], [2.0, 0.0]], "float32")
        x = sp.sparse_coo_tensor_from_dense(t(dense))
        out = sp.nn.ReLU6()(x)
        np.testing.assert_allclose(np.asarray(out.to_dense().numpy()),
                                   [[0.0, 6.0], [2.0, 0.0]])
        sm = sp.nn.functional.softmax(x)
        arr = np.asarray(sm.to_dense().numpy())
        np.testing.assert_allclose(arr, [[0.0, 1.0], [1.0, 0.0]])

    def test_subm_conv_preserves_sites(self):
        import paddle_tpu.sparse as sp

        img = np.zeros((1, 4, 4, 2), "float32")
        img[0, 1, 1] = [1.0, 2.0]
        xs = sp.sparse_coo_tensor_from_dense(t(img))
        conv = sp.nn.SubmConv2D(2, 3, 3)
        out = np.asarray(conv(xs).to_dense().numpy())
        assert out.shape == (1, 4, 4, 3)
        # output only at the input's active site (submanifold property)
        mask = np.zeros((4, 4), bool)
        mask[1, 1] = True
        assert (np.abs(out[0][~mask]).sum()) == 0.0

    def test_sparse_conv_and_pool(self):
        import paddle_tpu.sparse as sp

        img = np.zeros((1, 4, 4, 4, 2), "float32")
        img[0, 1, 1, 1] = [1.0, -1.0]
        xs = sp.sparse_coo_tensor_from_dense(t(img))
        conv = sp.nn.Conv3D(2, 3, 2, stride=2)
        out = conv(xs)
        assert list(out.shape) == [1, 2, 2, 2, 3]
        pooled = sp.nn.MaxPool3D(2, 2)(xs)
        assert list(pooled.shape) == [1, 2, 2, 2, 2]

    def test_sparse_batchnorm(self):
        import paddle_tpu.sparse as sp

        rng = np.random.default_rng(7)
        dense = rng.normal(size=(2, 3, 3, 3, 4)).astype("float32")
        xs = sp.sparse_coo_tensor_from_dense(t(dense))
        bn = sp.nn.BatchNorm(4)
        out = bn(xs)
        assert list(out.shape) == list(dense.shape)
        sync = sp.nn.SyncBatchNorm.convert_sync_batchnorm(bn)
        assert isinstance(sync, sp.nn.SyncBatchNorm)


class TestReviewRegressions3:
    def test_sparse_maxpool_negative_actives(self):
        import paddle_tpu.sparse as sp

        img = np.zeros((1, 2, 2, 2, 2), "float32")
        img[0, 0, 0, 0] = [1.0, -1.0]       # all-negative channel 1
        xs = sp.sparse_coo_tensor_from_dense(t(img))
        out = np.asarray(sp.nn.MaxPool3D(2, 2)(xs).to_dense().numpy())
        # max over STORED values: channel 1's true max is -1, not 0
        np.testing.assert_allclose(out[0, 0, 0, 0], [1.0, -1.0])

    def test_sync_bn_keeps_stats(self):
        import jax.numpy as jnp

        import paddle_tpu.sparse as sp

        bn = sp.nn.BatchNorm(2)
        bn._mean._data = jnp.asarray([5.0, 6.0])
        sync = sp.nn.SyncBatchNorm.convert_sync_batchnorm(bn)
        np.testing.assert_allclose(np.asarray(sync._mean._data), [5.0, 6.0])

    def test_spectral_norm_zero_iters(self):
        lin = nn.Linear(3, 3)
        nn.utils.spectral_norm(lin, "weight", n_power_iterations=0)
        out = lin(t(np.ones((1, 3), "float32")))
        assert np.isfinite(np.asarray(out.numpy())).all()

    def test_weight_norm_dim_none_scalar_g(self):
        lin = nn.Linear(4, 3)
        w0 = lin.weight.numpy().copy()
        nn.utils.weight_norm(lin, "weight", dim=None)
        g = dict(lin.named_parameters())["weight_g"]
        assert int(np.prod(g.shape)) == 1           # whole-tensor norm
        x = t(np.random.default_rng(0).normal(size=(2, 4))
              .astype("float32"))
        np.testing.assert_allclose(np.asarray(lin(x).numpy()),
                                   x.numpy() @ w0 + lin.bias.numpy(),
                                   rtol=1e-5)

    def test_int4_odd_in_features_raises(self):
        from paddle_tpu.nn.quant import weight_quantize

        w = np.zeros((5, 3), "float32")
        with pytest.raises(ValueError, match="even"):
            weight_quantize(t(w), algo="weight_only_int4")

    def test_sparse_softmax_axis_guard(self):
        import paddle_tpu.sparse as sp

        x = sp.sparse_coo_tensor_from_dense(
            t(np.eye(2, dtype="float32")))
        with pytest.raises(ValueError, match="last axis"):
            sp.nn.functional.softmax(x, axis=0)
