"""Adversarial per-op depth testing (VERDICT-r3 LoC diagnostic: "the
residual gap is depth per op ... dtype sweeps, layout variants, and
edge-case semantics our jnp one-liners haven't been pushed through. The
fix is adversarial parity testing").

Oracle: torch CPU (baked into the image), which matches the reference's
kernel semantics for this op set. Sweeps: dtypes (f32/f16/bf16/i32/i64/
bool), empty tensors, 0-d scalars, NaN/Inf propagation, negative
operands of pow/sqrt/log, integer division/modulo sign conventions,
keepdim reductions, broadcasting corner shapes, argmax ties, softmax
with -inf rows, clip with crossed bounds."""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle

F32, F16, BF16 = "float32", "float16", "bfloat16"
I32, I64 = "int32", "int64"


def _t(a, dtype=None):
    return paddle.to_tensor(np.asarray(a) if dtype is None
                            else np.asarray(a).astype(dtype))


def _torch(a, dtype=None):
    t = torch.tensor(np.asarray(a))
    if dtype == BF16:
        t = t.to(torch.bfloat16)
    elif dtype == F16:
        t = t.to(torch.float16)
    return t


def _np(x):
    if isinstance(x, torch.Tensor):
        a = x.float().numpy() if x.dtype in (torch.bfloat16,
                                             torch.float16) else x.numpy()
    else:
        a = x.numpy() if hasattr(x, "numpy") else x
    a = np.asarray(a)
    # ml_dtypes bfloat16 registers with numpy kind 'V'; name-sniff the
    # half types and widen for comparison
    if a.dtype.kind == "f" or a.dtype.name in ("bfloat16", "float16"):
        return a.astype(np.float64)
    return a


def _close(got, want, dtype=F32):
    rtol = {F32: 1e-5, F16: 1e-2, BF16: 3e-2}.get(dtype, 0)
    np.testing.assert_allclose(_np(got), _np(want), rtol=rtol,
                               atol=rtol, equal_nan=True)


BINARY = [("add", torch.add), ("subtract", torch.subtract),
          ("multiply", torch.multiply), ("divide", torch.divide),
          ("maximum", torch.maximum), ("minimum", torch.minimum)]


class TestBinaryDtypeSweep:
    @pytest.mark.parametrize("name,tfn", BINARY)
    @pytest.mark.parametrize("dtype", [F32, F16, BF16])
    def test_float_dtypes_with_specials(self, name, tfn, dtype):
        rng = np.random.default_rng(0)
        a = rng.normal(size=(4, 5)).astype(np.float32)
        b = rng.normal(size=(4, 5)).astype(np.float32)
        a[0, 0], b[0, 1] = np.nan, np.inf
        got = getattr(paddle, name)(_t(a, dtype), _t(b, dtype))
        want = tfn(_torch(a, dtype), _torch(b, dtype))
        assert str(got.dtype).endswith(dtype)
        _close(got, want, dtype)

    @pytest.mark.parametrize("name,tfn", [("add", torch.add),
                                          ("multiply", torch.multiply)])
    def test_int_and_empty_and_scalar(self, name, tfn):
        a = np.array([[2, -3], [7, 0]], np.int32)
        got = getattr(paddle, name)(_t(a), _t(a.T.copy()))
        _close(got, tfn(_torch(a), _torch(a.T.copy())))
        # empty
        e = np.zeros((0, 3), np.float32)
        got = getattr(paddle, name)(_t(e), _t(e))
        assert tuple(got.shape) == (0, 3)
        # 0-d
        got = getattr(paddle, name)(_t(np.float32(2.0)),
                                    _t(np.float32(3.0)))
        _close(got, tfn(torch.tensor(2.0), torch.tensor(3.0)))

    def test_integer_division_and_mod_signs(self):
        # the reference (like python/numpy) floors toward -inf for mod,
        # and floor_divide floors (torch.floor_divide matches)
        a = np.array([7, -7, 7, -7], np.int32)
        b = np.array([3, 3, -3, -3], np.int32)
        _close(paddle.floor_divide(_t(a), _t(b)),
               torch.floor_divide(_torch(a), _torch(b)))
        _close(paddle.mod(_t(a), _t(b)),
               torch.remainder(_torch(a), _torch(b)))

    def test_pow_negative_base_and_broadcast(self):
        a = np.array([[-2.0], [3.0]], np.float32)     # [2,1]
        b = np.array([2.0, 3.0, 0.5], np.float32)     # [3]
        got = paddle.pow(_t(a), _t(b))                # -> [2,3], nan at
        want = torch.pow(_torch(a), _torch(b))        # (-2)**0.5
        _close(got, want)


class TestUnaryEdges:
    @pytest.mark.parametrize("name,tfn,data", [
        ("sqrt", torch.sqrt, [4.0, 0.0, -1.0, np.inf]),
        ("log", torch.log, [1.0, 0.0, -1.0, np.e]),
        ("exp", torch.exp, [0.0, 710.0, -710.0]),     # overflow -> inf
        ("rsqrt", torch.rsqrt, [4.0, 0.25, 0.0]),
        ("floor", torch.floor, [1.5, -1.5, -0.0, 2.0]),
        ("ceil", torch.ceil, [1.5, -1.5, -0.0, 2.0]),
        ("round", torch.round, [0.5, 1.5, 2.5, -0.5, -1.5]),  # banker's
        ("tanh", torch.tanh, [0.0, 100.0, -100.0]),
        ("sigmoid", torch.sigmoid, [0.0, 100.0, -100.0]),
        ("abs", torch.abs, [-0.0, 1.0, -np.inf]),
    ])
    def test_float32_specials(self, name, tfn, data):
        a = np.asarray(data, np.float32)
        _close(getattr(paddle, name)(_t(a)), tfn(_torch(a)))

    @pytest.mark.parametrize("dtype", [F16, BF16])
    def test_half_dtypes_roundtrip(self, dtype):
        a = np.linspace(-3, 3, 17, dtype=np.float32)
        got = paddle.tanh(_t(a, dtype))
        want = torch.tanh(_torch(a, dtype))
        assert str(got.dtype).endswith(dtype)
        _close(got, want, dtype)

    def test_sign_negative_zero_and_nan(self):
        a = np.array([-0.0, 0.0, -3.0, 7.0], np.float32)
        got = _np(paddle.sign(_t(a)))
        want = _np(torch.sign(_torch(a)))
        np.testing.assert_allclose(got, want)
        # NaN: the reference's Eigen sign is IEEE (nan -> nan); torch
        # CPU returns 0 here — WE follow the reference
        assert np.isnan(_np(paddle.sign(_t(np.array([np.nan],
                                                    np.float32)))))[0]


class TestReductionEdges:
    def test_keepdim_and_empty_axis(self):
        rng = np.random.default_rng(1)
        a = rng.normal(size=(3, 4, 5)).astype(np.float32)
        _close(paddle.sum(_t(a), axis=[0, 2], keepdim=True),
               torch.sum(_torch(a), dim=(0, 2), keepdim=True))
        _close(paddle.mean(_t(a), axis=-1),
               torch.mean(_torch(a), dim=-1))

    def test_reduce_over_empty(self):
        e = np.zeros((0, 4), np.float32)
        got = _np(paddle.sum(_t(e), axis=0))
        np.testing.assert_allclose(got, np.zeros(4))
        # mean over empty = nan (reference/numpy semantics)
        m = _np(paddle.mean(_t(e), axis=0))
        assert np.isnan(m).all()

    def test_max_min_nan_propagation(self):
        a = np.array([1.0, np.nan, 3.0], np.float32)
        assert np.isnan(_np(paddle.max(_t(a))))
        assert np.isnan(_np(paddle.min(_t(a))))

    def test_argmax_first_tie_and_int(self):
        a = np.array([[1, 5, 5, 0], [7, 7, 2, 7]], np.int32)
        got = _np(paddle.argmax(_t(a), axis=1))
        want = _np(torch.argmax(_torch(a), dim=1))
        np.testing.assert_array_equal(got, want)

    def test_cumsum_dtypes(self):
        a = np.array([[1, 2], [3, 4]], np.int32)
        _close(paddle.cumsum(_t(a), axis=0),
               torch.cumsum(_torch(a), dim=0))
        f = np.array([0.1, 0.2, np.inf, 1.0], np.float32)
        _close(paddle.cumsum(_t(f), axis=0),
               torch.cumsum(_torch(f), dim=0))


class TestShapeAndSelectEdges:
    def test_clip_crossed_bounds(self):
        # min > max: the reference clamps sequentially (max wins),
        # matching torch.clamp
        a = np.array([-5.0, 0.0, 5.0], np.float32)
        _close(paddle.clip(_t(a), min=2.0, max=1.0),
               torch.clamp(_torch(a), min=2.0, max=1.0))

    def test_where_dtype_and_broadcast(self):
        c = np.array([[True], [False]])
        a = np.array([1.0, 2.0], np.float32)
        b = np.array([[9.0, 8.0], [7.0, 6.0]], np.float32)
        _close(paddle.where(_t(c), _t(a), _t(b)),
               torch.where(_torch(c), _torch(a), _torch(b)))

    def test_concat_empty_member(self):
        a = np.zeros((0, 3), np.float32)
        b = np.ones((2, 3), np.float32)
        got = paddle.concat([_t(a), _t(b)], axis=0)
        assert tuple(got.shape) == (2, 3)

    def test_gather_and_index_select_bounds(self):
        a = np.arange(12, dtype=np.float32).reshape(3, 4)
        idx = np.array([2, 0, 2], np.int64)
        _close(paddle.index_select(_t(a), _t(idx), axis=0),
               torch.index_select(_torch(a), 0, _torch(idx)))

    def test_topk_values_match(self):
        a = np.array([3.0, 1.0, 3.0, 2.0], np.float32)
        vals, _ = paddle.topk(_t(a), k=2)
        tvals, _ = torch.topk(_torch(a), k=2)
        _close(vals, tvals)


class TestSoftmaxEdges:
    def test_fully_masked_row(self):
        a = np.full((2, 3), -np.inf, np.float32)
        a[0] = [1.0, 2.0, 3.0]
        got = _np(paddle.nn.functional.softmax(_t(a), axis=-1))
        want = _np(torch.softmax(_torch(a), dim=-1))
        np.testing.assert_allclose(got, want, rtol=1e-6, equal_nan=True)

    def test_half_precision_large_logits(self):
        a = (np.array([[10000.0, 9999.0, 0.0]], np.float32))
        got = paddle.nn.functional.softmax(_t(a, BF16), axis=-1)
        want = torch.softmax(_torch(a, BF16), dim=-1)
        _close(got, want, BF16)


class TestCastEdges:
    @pytest.mark.parametrize("src,dst", [
        (F32, I32), (F32, "bool"), (I32, F32), ("bool", F32),
        (F32, BF16), (BF16, F32), (F32, F16),
    ])
    def test_cast_matrix(self, src, dst):
        a = np.array([0.0, 1.0, -1.5, 2.5], np.float32)
        got = paddle.cast(_t(a, src if src != "bool" else None)
                          if src != "bool" else _t(a != 0), dst)
        assert str(got.dtype).endswith(dst)

    def test_float_to_int_truncates_toward_zero(self):
        a = np.array([1.9, -1.9, 0.5, -0.5], np.float32)
        got = _np(paddle.cast(_t(a), I32))
        want = _np(_torch(a).to(torch.int32))
        np.testing.assert_array_equal(got, want)
