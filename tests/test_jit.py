"""jit/to_static tests (reference strategy: test/dygraph_to_static/ —
eager vs compiled output parity, program caching, save/load roundtrip)."""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu import jit


def t(x, sg=True):
    return paddle.to_tensor(np.asarray(x, dtype=np.float32), stop_gradient=sg)


class SmallNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(4, 8)
        self.fc2 = nn.Linear(8, 2)

    def forward(self, x):
        return self.fc2(nn.functional.relu(self.fc1(x)))


class TestToStatic:
    def test_matches_eager(self):
        net = SmallNet()
        x = t(np.random.randn(3, 4))
        eager = net(x).numpy()
        snet = jit.to_static(SmallNet())
        snet.set_state_dict(net.state_dict())
        np.testing.assert_allclose(snet(x).numpy(), eager, rtol=1e-5)

    def test_program_cache_per_shape(self):
        net = jit.to_static(SmallNet())
        net.eval()
        net(t(np.random.randn(2, 4)))
        net(t(np.random.randn(5, 4)))
        net(t(np.random.randn(2, 4)))
        assert len(net.forward.concrete_programs) == 2

    def test_function_to_static(self):
        @jit.to_static
        def f(a, b):
            return paddle.matmul(a, b) + 1.0

        a, b = t(np.random.randn(2, 3)), t(np.random.randn(3, 2))
        want = a.numpy() @ b.numpy() + 1.0
        np.testing.assert_allclose(f(a, b).numpy(), want, rtol=1e-5)

    def test_training_backward_through_compiled(self):
        paddle.seed(0)
        net_e = SmallNet()
        net_s = jit.to_static(SmallNet())
        net_s.set_state_dict(net_e.state_dict())
        x = t(np.random.randn(4, 4))
        y = t(np.random.randn(4, 2))

        le = paddle.mean((net_e(x) - y) ** 2)
        le.backward()
        ls = paddle.mean((net_s(x) - y) ** 2)
        ls.backward()
        assert abs(float(le) - float(ls)) < 1e-5
        np.testing.assert_allclose(net_e.fc1.weight.grad.numpy(),
                                   net_s.fc1.weight.grad.numpy(),
                                   rtol=1e-4, atol=1e-6)

    def test_compiled_training_converges(self):
        paddle.seed(0)
        net = jit.to_static(SmallNet())
        o = opt.Adam(learning_rate=1e-2, parameters=net.parameters())
        x = t(np.random.randn(16, 4))
        y = t(np.random.randn(16, 2))
        first = last = None
        for _ in range(50):
            loss = paddle.mean((net(x) - y) ** 2)
            loss.backward()
            o.step()
            o.clear_grad()
            first = first if first is not None else float(loss)
            last = float(loss)
        assert last < first * 0.5

    def test_input_grad_flows(self):
        net = jit.to_static(SmallNet())
        x = t(np.random.randn(2, 4), sg=False)
        loss = paddle.sum(net(x))
        loss.backward()
        assert x.grad is not None and x.grad.shape == [2, 4]

    def test_buffer_update_under_jit(self):
        class BNNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.bn = nn.BatchNorm1D(4, data_format="NCL")

            def forward(self, x):
                return self.bn(x)

        net = jit.to_static(BNNet())
        net.train()
        before = net.bn._mean.numpy().copy()
        with paddle.no_grad():
            net(t(np.random.randn(8, 4, 5) * 3 + 2))
        after = net.bn._mean.numpy()
        assert not np.allclose(before, after)

    def test_enable_to_static_toggle(self):
        net = jit.to_static(SmallNet())
        jit.enable_to_static(False)
        try:
            x = t(np.random.randn(2, 4))
            out = net(x)  # falls back to eager
            assert out.shape == [2, 2]
            assert len(net.forward.concrete_programs) == 0
        finally:
            jit.enable_to_static(True)


class TestSaveLoad:
    def test_roundtrip(self):
        net = SmallNet()
        net.eval()
        x = t(np.random.randn(3, 4))
        want = net(x).numpy()
        d = tempfile.mkdtemp()
        path = os.path.join(d, "model")
        jit.save(net, path, input_spec=[jit.InputSpec([3, 4], "float32")])
        assert os.path.exists(path + ".pdmodel")
        loaded = jit.load(path)
        got = loaded(x).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_dynamic_batch_export(self):
        """InputSpec dims of None export symbolically: one artifact serves
        any batch size (shape polymorphism via jax.export)."""
        net = SmallNet()
        net.eval()
        d = tempfile.mkdtemp()
        path = os.path.join(d, "dyn")
        jit.save(net, path, input_spec=[jit.InputSpec([None, 4], "float32")])
        loaded = jit.load(path)
        for bs in (1, 3, 7):
            x = t(np.random.randn(bs, 4))
            got = loaded(x).numpy()
            np.testing.assert_allclose(got, net(x).numpy(), rtol=1e-5)

    def test_save_uses_to_static_spec(self):
        """jit.save without input_spec falls back to the spec passed at
        to_static decoration time."""
        net = SmallNet()
        net.eval()
        net_s = jit.to_static(net,
                              input_spec=[jit.InputSpec([2, 4], "float32")])
        d = tempfile.mkdtemp()
        path = os.path.join(d, "spec_fallback")
        jit.save(net_s, path)
        loaded = jit.load(path)
        assert loaded(t(np.random.randn(2, 4))).shape == [2, 2]

    @pytest.mark.slow  # tier-1 budget (ISSUE 3): heavy; run in the slow lane
    def test_generate_loop_exports_and_serves(self):
        """The whole KV-cache generate loop (prefill + scan of decode
        steps) saves as ONE StableHLO artifact and serves greedily —
        the deployment story for the decode path."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.models import llama as L

        cfg = L.llama_tiny(num_hidden_layers=2)
        params = L.init_params(cfg, jax.random.PRNGKey(0))

        def serve(ids):
            return L.generate(params, ids, cfg, max_new_tokens=4)

        d = tempfile.mkdtemp()
        path = os.path.join(d, "decoder")
        jit.save(serve, path, input_spec=[jit.InputSpec([2, 5], "int32")])
        loaded = jit.load(path)
        ids = np.random.default_rng(0).integers(
            0, cfg.vocab_size, (2, 5)).astype("int32")
        got = loaded(paddle.to_tensor(ids)).numpy()
        want = np.asarray(serve(jnp.asarray(ids)))
        np.testing.assert_array_equal(got, want)

    def test_loaded_artifact_is_hermetic(self):
        """Load must not need the original class (serving parity)."""
        net = SmallNet()
        net.eval()
        d = tempfile.mkdtemp()
        path = os.path.join(d, "m2")
        jit.save(net, path, input_spec=[jit.InputSpec([2, 4], "float32")])
        loaded = jit.load(path)
        out = loaded(t(np.random.randn(2, 4)))
        assert out.shape == [2, 2]


class TestTraceGuards:
    def test_value_dependent_branch_raises_helpfully(self):
        import pytest
        import paddle_tpu.jit as jit

        @jit.to_static
        def f(x):
            if (x.sum() > 0).item():     # value-dependent Python branch
                return x * 2
            return x - 1

        with pytest.raises(RuntimeError, match="to_static.*branches on"):
            f(paddle.to_tensor(np.ones((3,), "float32")))


class TestBatchBucketing:
    def test_bucketed_capture_compiles_once_per_bucket(self):
        import numpy as np

        import paddle_tpu as paddle
        import paddle_tpu.jit as jit
        import paddle_tpu.nn as nn

        lin = nn.Linear(4, 2)
        sf = jit.to_static(lambda x: lin(x), bucket_batch=True)
        outs = {}
        with paddle.no_grad():
            for n in (1, 2, 3, 5, 7, 8):
                x = paddle.to_tensor(
                    np.arange(n * 4, dtype="float32").reshape(n, 4))
                o = sf(x)
                assert o.shape == [n, 2]
                outs[n] = np.asarray(o.numpy())
        # one program per bucket (1, 2, 4, 8), not per batch size
        assert len(sf._programs) == 4
        # results match the eager layer exactly (padding sliced away)
        for n, got in outs.items():
            x = paddle.to_tensor(
                np.arange(n * 4, dtype="float32").reshape(n, 4))
            np.testing.assert_allclose(got, np.asarray(lin(x).numpy()),
                                       rtol=1e-6)

    def test_custom_bucket_sizes(self):
        import numpy as np

        import paddle_tpu as paddle
        import paddle_tpu.jit as jit

        sf = jit.to_static(lambda x: x * 2.0, bucket_batch=True,
                           bucket_sizes=[4, 16])
        with paddle.no_grad():
            for n in (2, 3, 4):
                o = sf(paddle.to_tensor(np.ones((n, 2), "float32")))
                assert o.shape == [n, 2]
        assert len(sf._programs) == 1   # all landed in the 4-bucket

    def test_bucketing_skipped_under_grad(self):
        import numpy as np

        import paddle_tpu as paddle
        import paddle_tpu.jit as jit
        import paddle_tpu.nn as nn

        lin = nn.Linear(4, 1)
        sf = jit.StaticFunction(
            lambda x: lin(x).sum(), layer=lin, bucket_batch=True)
        x = paddle.to_tensor(np.ones((3, 4), "float32"))
        loss = sf(x)          # grad recording on -> exact shapes, taped
        loss.backward()
        g = np.asarray(lin.weight.grad.numpy())
        np.testing.assert_allclose(g, np.full((4, 1), 3.0), rtol=1e-6)
        # no padding happened: program cached under the exact batch key
        assert all(k is not None for k in sf._programs)

    def test_bucketing_beyond_largest_bucket(self):
        import numpy as np

        import paddle_tpu as paddle
        import paddle_tpu.jit as jit

        sf = jit.to_static(lambda x: x + 1.0, bucket_batch=True,
                           bucket_sizes=[2, 4])
        with paddle.no_grad():
            o = sf(paddle.to_tensor(np.zeros((7, 2), "float32")))
        assert o.shape == [7, 2]

    def test_bucketing_non_tensor_leading_arg(self):
        import numpy as np

        import paddle_tpu as paddle
        import paddle_tpu.jit as jit

        sf = jit.to_static(lambda s, x: x * s, bucket_batch=True)
        with paddle.no_grad():
            o = sf(2.0, paddle.to_tensor(np.ones((3, 2), "float32")))
        np.testing.assert_allclose(np.asarray(o.numpy()),
                                   np.full((3, 2), 2.0))


class TestGraphBreakFallback:
    def test_full_graph_false_falls_back(self):
        import warnings

        import numpy as np

        import paddle_tpu as paddle
        import paddle_tpu.jit as jit

        def branchy(x):
            if float(x.sum().numpy() if hasattr(x.sum(), 'numpy')
                     else x.sum()) > 0:   # data-dependent python branch
                return x * 2.0
            return x - 1.0

        def branchy_traced(x):
            # under tracing x.sum() is a tracer; bool() raises
            s = x.sum()
            if s > 0:
                return x * 2.0
            return x - 1.0

        sf = jit.to_static(branchy_traced, full_graph=False)
        x = paddle.to_tensor(np.ones((2, 2), "float32"))
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            out = sf(x)
        assert any("graph break" in str(wi.message) for wi in w)
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   np.full((2, 2), 2.0))
        # second call with the same signature: silent eager, no rewarn
        out2 = sf(x)
        np.testing.assert_allclose(np.asarray(out2.numpy()),
                                   np.full((2, 2), 2.0))

    def test_full_graph_true_raises(self):
        import numpy as np
        import pytest

        import paddle_tpu as paddle
        import paddle_tpu.jit as jit

        def branchy(x):
            if x.sum() > 0:
                return x * 2.0
            return x

        sf = jit.to_static(branchy, full_graph=True)
        with pytest.raises(RuntimeError, match="branches on a tensor"):
            sf(paddle.to_tensor(np.ones((2, 2), "float32")))


class TestSeqBucketing:
    """Sequence-length bucketing policy (the dynamic-shape serving
    answer for variable-length prompts): right-padding is EXACT for
    causal models; outputs slice back; one compile per bucket."""

    def test_causal_llama_exact_and_bucketed(self):
        import jax

        from paddle_tpu.models import llama as L

        cfg = L.llama_tiny(num_hidden_layers=1)
        params = L.init_params(cfg, jax.random.PRNGKey(0))

        calls = []

        def fwd(ids):
            calls.append(tuple(ids.shape))
            raw = getattr(ids, "_data", ids)   # raw tracer inside jit
            return paddle.Tensor(L.forward(params, raw, cfg))

        f = jit.to_static(fwd, bucket_seq=True,
                           seq_bucket_sizes=[16, 32])
        rng = np.random.default_rng(0)
        with paddle.no_grad():
            for s in (9, 11, 13):
                ids = paddle.to_tensor(rng.integers(
                    0, cfg.vocab_size, (2, s)).astype("int64"))
                got = f(ids)
                assert list(got.shape) == [2, s, cfg.vocab_size]
                want = L.forward(params, np.asarray(ids.numpy()), cfg)
                np.testing.assert_allclose(
                    np.asarray(got.numpy()), np.asarray(want),
                    rtol=2e-5, atol=2e-5)
        # every call traced at the SAME bucket (16): one signature
        assert set(calls) == {(2, 16)}, calls

    def test_training_skips_seq_padding(self):
        def fwd(x):
            return x * 2.0

        f = jit.to_static(fwd, bucket_seq=True)
        x = paddle.to_tensor(np.ones((2, 9), "float32"),
                             stop_gradient=False)
        out = f(x)              # grads on -> exact shapes
        assert list(out.shape) == [2, 9]
