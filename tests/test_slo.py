"""SLO accounting plane (monitor/slo.py + engine cost attribution,
/slo route, tenant exposition, autoscale signals, bench-guard rungs).

The load-bearing contracts:

- **Cost attribution**: every retired request carries a RequestCost
  with tokens, CUMULATIVE queue wait across preemption re-queues (the
  histogram still observes each wait once — pinned by
  sum(record waits) == histogram sum AND histogram count ==
  admissions), page-seconds, slot share, modeled FLOPs — with ZERO
  added device synchronizations at any sample rate (pinned via the
  exectime ``_block_until_ready`` indirection).
- **Burn-rate math**: compliance / fast+slow burn / budget remaining
  pinned against synthetic traces with known violation patterns;
  insufficient data answers None, never fabricated; warn flips and
  recovers; off-flag = zero registrations.
- **Tenant cardinality + escaping**: hostile tenant names round-trip
  through the exposition escaping; the cap collapses overflow into
  ``_other`` and never grows the registry.
- **Autoscale honesty**: no engine ticks -> no gauges; the demand
  model components pin exactly; drain_safe flips on idle.
"""
import json
import math
import re
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import monitor
from paddle_tpu.monitor import exectime
from paddle_tpu.monitor import fleet
from paddle_tpu.monitor import server
from paddle_tpu.monitor import slo
from paddle_tpu.monitor import trace


@pytest.fixture
def mon():
    """Monitor on, clean state; everything torn down after."""
    monitor.reset()
    server.stop_server()
    pt.set_flags({"FLAGS_enable_monitor": True})
    yield monitor
    server.stop_server()
    server.unregister_health_provider("slo_burn")
    slo._PROVIDER_REGISTERED[0] = False
    slo.set_objectives(ttft_p99_ms=None, tpot_p99_ms=None,
                       e2e_p99_ms=None, availability=None)
    slo.set_max_tenants(None)
    slo.set_window(None)
    exectime.set_sample_rate(None)
    pt.set_flags({"FLAGS_enable_monitor": False,
                  "FLAGS_enable_monitor_server": False})
    monitor.reset()


def _engine(**kw):
    import jax
    from paddle_tpu.inference import ServingEngine
    from paddle_tpu.models import llama as L
    cfg = L.llama_tiny()
    params = L.init_params(cfg, jax.random.PRNGKey(3))
    return ServingEngine(L, params, cfg, **kw), cfg


def _reqs(cfg, lens, new, tenants=None, seed=0):
    from paddle_tpu.inference import Request
    rng = np.random.default_rng(seed)
    tenants = tenants or ["default"] * len(lens)
    return [Request(rid=i,
                    prompt=rng.integers(0, cfg.vocab_size,
                                        (n,)).astype(np.int32),
                    max_new_tokens=m, tenant=t)
            for i, (n, m, t) in enumerate(zip(lens, new, tenants))]


def _completed(rec=None, tenant="default", **latencies):
    """A synthetic completed-request record for the burn-math tests."""
    out = {"tenant": tenant, "rejected": False, "prefill_tokens": 4,
           "decode_tokens": 4, "queue_wait_ms": 1.0,
           "page_seconds": 0.01, "slot_steps": 4, "model_flops": 100.0,
           "ttft_ms": 10.0, "tpot_ms": 5.0, "e2e_ms": 50.0}
    out.update(rec or {})
    out.update(latencies)
    return out


# ---------------------------------------------------------------------------
# engine cost attribution
# ---------------------------------------------------------------------------

@pytest.mark.serving
class TestCostAttribution:
    def test_cost_record_populates(self, mon):
        eng, cfg = _engine(num_slots=2, max_len=32, page_size=4,
                           decode_chunk=2)
        reqs = _reqs(cfg, lens=(5, 3, 6), new=(4, 4, 4),
                     tenants=("alpha", "beta", "alpha"))
        outs = eng.run(reqs)
        for r in reqs:
            o = outs[r.rid]
            c = o.cost
            assert c is not None and o.tenant == r.tenant
            assert c.tenant == r.tenant
            assert c.prefill_tokens == len(r.prompt)
            assert c.decode_tokens == len(o.tokens) - 1  # first token
            #                        is sampled by prefill, not decode
            assert c.discarded_tokens == 0 and c.preemptions == 0
            assert c.queue_wait_ms >= 0
            assert c.page_seconds > 0
            assert c.slot_steps > 0 and c.grid_steps >= c.slot_steps
            assert c.slot_share is not None and 0 < c.slot_share <= 1
            # CPU cost-analysis reports FLOPs, so attribution is live
            assert c.model_flops > 0
            assert c.ttft_ms is not None and c.e2e_ms is not None
            assert c.e2e_ms >= c.ttft_ms
        # per-tenant aggregates agree with the records exactly
        tl = slo.tenants_snapshot()["tenants"]
        assert set(tl) == {"alpha", "beta"}
        assert tl["alpha"]["completed"] == 2
        assert tl["alpha"]["prefill_tokens"] == 5 + 6
        assert tl["beta"]["decode_tokens"] == \
            outs[1].cost.decode_tokens
        total_flops = sum(outs[r.rid].cost.model_flops for r in reqs)
        agg_flops = sum(t["model_flops"] for t in tl.values())
        assert agg_flops == pytest.approx(total_flops)

    def test_queue_wait_cumulative_across_preemption(self, mon):
        """The satellite pin: one preemption + re-admission -> the
        record keeps the CUMULATIVE wait while the histogram observes
        each individual wait once (count == admissions, and the
        per-request sums partition the histogram's total)."""
        eng, cfg = _engine(num_slots=2, max_len=16, page_size=4,
                           num_pages=5, decode_chunk=2)
        reqs = _reqs(cfg, lens=(4, 4, 4), new=(8, 8, 8))
        outs = eng.run(reqs)
        s = eng.stats
        assert s.preempted >= 1                # tiny pool forces it
        pre = [outs[r.rid] for r in reqs
               if outs[r.rid].cost.preemptions >= 1]
        assert pre, "no request was preempted"
        assert pre[0].cost.discarded_tokens > 0
        h = monitor.registry().get("serving.latency.queue_wait_ms")
        # each ADMISSION (first or re-) observed exactly once
        assert h.count == s.admitted > len(reqs)
        # the cumulative per-request sums partition the histogram's
        # total: a record missing its re-queue wait would break this
        total = sum(outs[r.rid].cost.queue_wait_ms for r in reqs)
        assert total == pytest.approx(h.sum, rel=1e-6)

    @pytest.mark.slow  # tier-1 budget (ISSUE 19 rebalance): same zero-sync contract as numerics'
    # KV-sampling pin; cost-record population test stays fast
    def test_zero_added_syncs_at_any_rate(self, mon, monkeypatch):
        """The acceptance pin: cost attribution rides the per-chunk
        emitted-grid download — at exec sample rate 0 AND rate 1 the
        engine adds zero ``block_until_ready`` synchronizations."""
        calls = []
        monkeypatch.setattr(
            exectime, "_block_until_ready",
            lambda outputs: calls.append(1))
        for rate in (0, 1):
            exectime.set_sample_rate(rate)
            eng, cfg = _engine(num_slots=2, max_len=32, page_size=4,
                               decode_chunk=2)
            outs = eng.run(_reqs(cfg, lens=(4, 5), new=(4, 4)))
            assert len(outs) == 2
            assert outs[0].cost.page_seconds > 0   # plane was live
            assert calls == [], f"rate {rate} added {len(calls)} syncs"

    def test_off_path_no_cost_no_registrations(self):
        monitor.reset()
        assert not monitor.enabled()
        eng, cfg = _engine(num_slots=2, max_len=32, page_size=4,
                           decode_chunk=2)
        outs = eng.run(_reqs(cfg, lens=(4,), new=(3,)))
        assert outs[0].cost is None
        assert outs[0].tenant == "default"
        assert monitor.snapshot() == {}
        assert slo.records() == []
        assert slo.tenants_snapshot()["tenants"] == {}
        assert slo.update_autoscale_gauges() == {"available": False}

    def test_tenant_priority_validation(self, mon):
        from paddle_tpu.inference import Request, RequestRejected
        eng, cfg = _engine(num_slots=2, max_len=32, page_size=4,
                           decode_chunk=2)
        prompt = np.array([1, 2, 3], np.int32)
        # coercible-but-wrong-typed fields are normalized onto the
        # request (the PR 6 screening discipline)
        r = Request(rid=0, prompt=prompt, max_new_tokens=2,
                    tenant=7, priority=np.int64(2))
        eng.submit(r)
        assert r.tenant == "7" and r.priority == 2
        # non-integral priority is refused before any engine state
        with pytest.raises(RequestRejected, match="priority"):
            eng.submit(Request(rid=1, prompt=prompt, max_new_tokens=2,
                               priority=1.5))
        # infinities reject TYPED, not as an escaping OverflowError
        with pytest.raises(RequestRejected, match="priority"):
            eng.submit(Request(rid=7, prompt=prompt, max_new_tokens=2,
                               priority=float("inf")))
        with pytest.raises(RequestRejected, match="max_new_tokens"):
            eng.submit(Request(rid=8, prompt=prompt,
                               max_new_tokens=float("inf")))
        # oversized tenant label is refused (128-char limit)
        with pytest.raises(RequestRejected, match="tenant"):
            eng.submit(Request(rid=2, prompt=prompt, max_new_tokens=2,
                               tenant="x" * 200))
        # empty/None tenant coerces to "default"
        r3 = Request(rid=3, prompt=prompt, max_new_tokens=2, tenant="")
        eng.submit(r3)
        assert r3.tenant == "default"
        # rejections entered the availability window — but none of
        # these tenants had completed a request yet, and a rejection
        # cannot CLAIM a label slot (squatting defense), so they all
        # collapse into _other
        rej = [x for x in slo.records() if x["rejected"]]
        assert len(rej) == 4
        assert {x["tenant"] for x in rej} == {slo.OVERFLOW_TENANT}
        eng.run()
        tl = slo.tenants_snapshot()["tenants"]
        assert tl[slo.OVERFLOW_TENANT]["rejected"] == 4
        assert tl["default"]["completed"] == 1     # the ""->default
        assert tl["7"]["completed"] == 1           # the coerced int
        # engine kept serving after the poisoned submissions
        assert len(eng.outputs) == 2
        # a rejection claiming an ALREADY-tracked tenant attributes
        with pytest.raises(RequestRejected):
            eng.submit(Request(rid=9, prompt=prompt, max_new_tokens=2,
                               tenant="7", priority=0.5))
        assert slo.tenants_snapshot()["tenants"]["7"]["rejected"] == 1


# ---------------------------------------------------------------------------
# burn-rate math (synthetic traces)
# ---------------------------------------------------------------------------

class TestBurnRateMath:
    def test_compliance_and_burn_pinned(self, mon):
        slo.set_objectives(ttft_p99_ms=100.0, availability=0.9)
        # 10 completed: 2 violate the 100ms TTFT objective
        for i in range(10):
            slo.record_request(_completed(
                ttft_ms=200.0 if i < 2 else 50.0))
        rep = slo.compliance_report()
        t = rep["objectives"]["ttft_p99_ms"]
        assert t["samples_slow"] == 10
        assert t["compliance"] == pytest.approx(0.8)
        # bad_frac 0.2 / budget 0.01 = 20x burn; budget overdrawn
        assert t["burn_slow"] == pytest.approx(20.0)
        assert t["burn_fast"] == pytest.approx(20.0)  # fast ⊇ all 10
        assert t["budget_remaining"] == pytest.approx(-19.0)
        # availability: one rejection among 11 -> bad_frac 1/11 over
        # a 0.1 budget
        slo.record_rejected("default")
        a = slo.compliance_report()["objectives"]["availability"]
        assert a["samples_slow"] == 11
        assert a["compliance"] == pytest.approx(10 / 11)
        assert a["burn_slow"] == pytest.approx((1 / 11) / 0.1)
        # rejected records are NOT relevant to latency windows
        t2 = slo.compliance_report()["objectives"]["ttft_p99_ms"]
        assert t2["samples_slow"] == 10
        # gauges mirror the report
        g = monitor.snapshot()["gauges"]
        assert g["slo.ttft_p99_ms.burn_slow"] == pytest.approx(20.0)
        assert g["slo.window.requests"] == 11

    def test_insufficient_data_answers_none(self, mon):
        slo.set_objectives(ttft_p99_ms=100.0)
        for _ in range(4):                   # below the 5-sample floor
            slo.record_request(_completed(ttft_ms=500.0,
                                          tpot_ms=None))
        t = slo.compliance_report()["objectives"]["ttft_p99_ms"]
        assert t["compliance"] is None
        assert t["burn_fast"] is None and t["burn_slow"] is None
        assert t["budget_remaining"] is None and not t["alerting"]
        # a missing latency (1-token request has no TPOT) is simply
        # not relevant — never counted as good OR bad
        tp = slo.compliance_report()["objectives"]["tpot_p99_ms"]
        assert tp["samples_slow"] == 0
        slo.record_request(_completed(ttft_ms=500.0,    # 5th answers
                                      tpot_ms=None))
        t = slo.compliance_report()["objectives"]["ttft_p99_ms"]
        assert t["compliance"] == 0.0
        assert t["burn_slow"] == pytest.approx(100.0)

    def test_warn_flips_and_recovers(self, mon):
        slo.set_objectives(ttft_p99_ms=100.0)
        for _ in range(8):                            # all violating
            slo.record_request(_completed(ttft_ms=900.0))
        rep = slo.compliance_report()
        assert "ttft_p99_ms" in rep["alerting"]
        assert rep["objectives"]["ttft_p99_ms"]["burn_fast"] \
            == pytest.approx(100.0)
        assert monitor.snapshot()["gauges"]["slo.alerting"] == 1
        hz = slo._slo_provider()
        assert hz["ok"] is True and hz["level"] == "warn"
        assert "ttft_p99_ms" in hz["alerting"]
        # recovery: enough good requests to flush the fast window
        for _ in range(rep["fast_window"]):
            slo.record_request(_completed(ttft_ms=10.0))
        rep2 = slo.compliance_report()
        assert "ttft_p99_ms" not in rep2["alerting"]
        assert rep2["objectives"]["ttft_p99_ms"]["burn_fast"] \
            == pytest.approx(0.0)
        assert monitor.snapshot()["gauges"]["slo.alerting"] == 0

    def test_tenant_compliance_windowed(self, mon):
        slo.set_objectives(ttft_p99_ms=100.0)
        for _ in range(6):
            slo.record_request(_completed(tenant="good", ttft_ms=10.0))
        for _ in range(6):
            slo.record_request(_completed(tenant="bad", ttft_ms=500.0))
        slo.record_request(_completed(tenant="thin"))
        tc = slo.tenant_compliance()
        assert tc["good"]["ttft_p99_ms"] == 1.0
        assert tc["bad"]["ttft_p99_ms"] == 0.0
        assert tc["bad"]["availability"] == 1.0     # not rejected
        # below the min-sample floor: None, never fabricated
        assert tc["thin"]["ttft_p99_ms"] is None
        assert tc["thin"]["requests_in_window"] == 1

    def test_off_flag_zero_registration(self):
        monitor.reset()
        assert not monitor.enabled()
        slo.record_request(_completed())
        slo.record_rejected("ghost")
        slo.note_sched_tick(4, 2, 2, 0.5)
        assert slo.records() == []
        assert slo.tenants_snapshot()["tenants"] == {}
        assert monitor.snapshot() == {}

    def test_window_bounded(self, mon):
        slo.set_window(16)
        for i in range(50):
            slo.record_request(_completed(tenant=f"t{i % 2}"))
        assert slo.window_capacity() == 16
        assert len(slo.records()) == 16
        assert slo.total_records() == 50
        # tenant aggregates keep the LIFETIME sums, not the window's
        tl = slo.tenants_snapshot()["tenants"]
        assert tl["t0"]["requests"] + tl["t1"]["requests"] == 50

    def test_objective_validation(self):
        with pytest.raises(ValueError, match="unknown"):
            slo.set_objectives(nope=1.0)
        with pytest.raises(ValueError, match="out of range"):
            slo.set_objectives(availability=1.5)
        with pytest.raises(ValueError, match="out of range"):
            slo.set_objectives(ttft_p99_ms=0)

    def test_env_objectives(self, mon, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_SLO_TTFT_P99_MS", "42.5")
        assert slo.objectives()["ttft_p99_ms"] == 42.5
        slo.set_objectives(ttft_p99_ms=7.0)        # override wins
        assert slo.objectives()["ttft_p99_ms"] == 7.0
        # availability >= 1.0 from the env would zero the error budget
        # and silently disable burn rates — falls back to the default
        # (the same input set_objectives rejects loudly)
        monkeypatch.setenv("PADDLE_TPU_SLO_AVAILABILITY", "1.0")
        assert slo.objectives()["availability"] == 0.995


# ---------------------------------------------------------------------------
# tenant exposition + cardinality
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(v):
    return (v.replace("\\n", "\n").replace('\\"', '"')
            .replace("\\\\", "\\"))


def _tenant_samples(text, family):
    """{tenant: value} for one slo_tenant_* family, asserting the
    TYPE line precedes its samples (the strict-format discipline)."""
    out = {}
    type_seen = False
    for line in text.splitlines():
        if line == f"# TYPE {family} counter":
            type_seen = True
            continue
        m = _SAMPLE_RE.match(line)
        if m and m.group(1) == family:
            assert type_seen, f"sample before TYPE for {family}"
            labels = dict((k, _unescape(v)) for k, v in
                          _LABEL_RE.findall(m.group(2) or ""))
            out[labels["tenant"]] = float(m.group(3))
    return out


class TestTenantExposition:
    def test_hostile_tenant_round_trips(self, mon):
        nasty = 'evil"\n\\tenant'
        slo.record_request(_completed(tenant=nasty))
        slo.record_request(_completed(tenant="plain"))
        text = monitor.expose_text()
        samples = _tenant_samples(text, "slo_tenant_requests")
        assert samples == {nasty: 1.0, "plain": 1.0}
        # the raw bytes never appear unescaped: every line still
        # parses as exactly one sample or comment
        for line in text.splitlines():
            assert line.startswith("#") or _SAMPLE_RE.match(line), \
                repr(line)

    def test_every_cost_family_exposed(self, mon):
        slo.record_request(_completed(tenant="acme"))
        text = monitor.expose_text()
        for field in ("requests", "completed", "rejected",
                      "prefill_tokens", "decode_tokens",
                      "discarded_tokens", "queue_wait_ms",
                      "page_seconds", "slot_steps", "model_flops",
                      "preemptions"):
            fam = f"slo_tenant_{field}"
            assert f"# TYPE {fam} counter" in text, fam
            assert _tenant_samples(text, fam), fam

    def test_cardinality_cap_collapses_to_other(self, mon):
        slo.set_max_tenants(3)
        for i in range(10):
            slo.record_request(_completed(tenant=f"tenant-{i}"))
        snap = slo.tenants_snapshot()
        tl = snap["tenants"]
        real = [t for t in tl if t != slo.OVERFLOW_TENANT]
        assert sorted(real) == ["tenant-0", "tenant-1", "tenant-2"]
        assert tl[slo.OVERFLOW_TENANT]["requests"] == 7
        assert snap["overflow_records"] == 7
        # the ring records carry the COLLAPSED key too, so window
        # views can never resurrect unbounded names
        assert {r["tenant"] for r in slo.records()} == \
            set(real) | {slo.OVERFLOW_TENANT}

    def test_cap_never_grows_registry(self, mon):
        slo.set_max_tenants(2)
        # warm the window past min-samples and materialize every
        # slo.* gauge the plane will ever register (gauges refresh
        # pull-shaped inside compliance_report) BEFORE the churn
        for _ in range(8):
            slo.record_request(_completed(tenant="a"))
        slo.compliance_report()
        n_metrics = len(monitor.registry())
        for i in range(20):
            slo.record_request(_completed(tenant=f"hostile-{i}"))
        slo.compliance_report()
        # tenant churn grows NEITHER the registry nor the label space
        assert len(monitor.registry()) == n_metrics
        tl = slo.tenants_snapshot()["tenants"]
        assert len(tl) <= 3                    # 2 real + _other

    def test_rejection_cannot_claim_label_slot(self, mon):
        # unauthenticated garbage with fresh tenant claims must not
        # squat the bounded label space: rejections only attribute to
        # tenants that EARNED a slot by completing a request
        slo.record_rejected("squatter")
        assert "squatter" not in slo.tenants_snapshot()["tenants"]
        assert slo.tenants_snapshot()["tenants"][
            slo.OVERFLOW_TENANT]["rejected"] == 1
        slo.record_request(_completed(tenant="squatter"))
        slo.record_rejected("squatter")        # now tracked: honored
        assert slo.tenants_snapshot()["tenants"][
            "squatter"]["rejected"] == 1

    def test_empty_without_records(self, mon):
        assert slo.tenant_exposition_text() == ""
        assert "slo_tenant" not in monitor.expose_text()


# ---------------------------------------------------------------------------
# autoscale signals
# ---------------------------------------------------------------------------

class TestAutoscale:
    def test_no_ticks_no_gauges(self, mon):
        out = slo.update_autoscale_gauges()
        assert out == {"available": False}
        assert monitor.registry().get(
            "serving.autoscale.demand_estimate") is None

    def test_demand_model_pinned(self, mon):
        # queue grows 0 -> 8; last tick: half the slots live, 3/4 of
        # the page pool used, 8 queued on a 4-slot engine
        for qd in (0, 2, 4, 8):
            slo.note_sched_tick(qd, 2, 4, 0.25)
        out = slo.update_autoscale_gauges()
        assert out["available"] and not out["drain_safe"]
        assert out["utilization"] == pytest.approx(0.75)  # page leg
        assert out["backlog_slots"] == pytest.approx(2.0)
        assert out["queue_depth_trend_per_s"] is not None
        assert out["queue_depth_trend_per_s"] > 0
        growth = out["queue_depth_trend_per_s"] * out["horizon_s"] / 4
        assert out["demand_estimate"] == pytest.approx(
            0.75 + 2.0 + growth, rel=1e-3)
        assert out["desired_capacity_hint"] == \
            math.ceil(out["demand_estimate"] - 1e-9)
        g = monitor.snapshot()["gauges"]
        assert g["serving.autoscale.demand_estimate"] > 0
        assert g["serving.autoscale.drain_safe"] == 0

    def test_drain_safe_on_idle(self, mon):
        slo.note_sched_tick(4, 2, 2, 0.5)
        slo.note_sched_tick(0, 0, 2, 1.0)
        out = slo.update_autoscale_gauges()
        assert out["drain_safe"] and out["utilization"] == 0.0
        assert out["demand_estimate"] == 0.0    # negative trend clamped
        assert out["desired_capacity_hint"] == 0
        assert monitor.snapshot()["gauges"][
            "serving.autoscale.drain_safe"] == 1

    def test_headroom_leg_composes(self, mon):
        slo.note_sched_tick(0, 1, 4, 1.0)
        hr = {"est_admittable_bytes": 25,
              "hbm": {"totals": {"bytes_limit": 100,
                                 "bytes_in_use": 60}}}
        out = slo.update_autoscale_gauges(headroom=hr)
        assert out["memory_utilization"] == pytest.approx(0.75)
        assert out["utilization"] == pytest.approx(0.75)  # beats 0.25
        assert out["est_admittable_bytes"] == 25
        # a silent backend contributes nothing — never fabricated
        out2 = slo.update_autoscale_gauges(
            headroom={"est_admittable_bytes": None,
                      "hbm": {"totals": {}}})
        assert out2["memory_utilization"] is None
        assert out2["utilization"] == pytest.approx(0.25)  # slot leg

    def test_engine_feeds_ticks(self, mon):
        eng, cfg = _engine(num_slots=2, max_len=32, page_size=4,
                           decode_chunk=2)
        reqs = _reqs(cfg, lens=(4, 4, 4, 4), new=(6, 6, 6, 6))
        for r in reqs:
            eng.submit(r)
        eng.step()                                # mid-run: backlog up
        mid = slo.update_autoscale_gauges()
        assert mid["available"] and not mid["drain_safe"]
        assert mid["demand_estimate"] >= 1.0
        eng.run()
        end = slo.update_autoscale_gauges()
        assert end["drain_safe"] and end["demand_estimate"] == 0.0


# ---------------------------------------------------------------------------
# routes, healthz, flight record, fleet
# ---------------------------------------------------------------------------

def _get(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


@pytest.mark.serving
class TestSurfaces:
    @pytest.mark.slow  # tier-1 budget (ISSUE 19 rebalance): route e2e; flight-record/healthz/fleet
    # surface tests + pinned burn math keep the route covered fast
    def test_slo_route_end_to_end(self, mon):
        srv = server.start_server(port=0)
        eng, cfg = _engine(num_slots=2, max_len=32, page_size=4,
                           decode_chunk=2)
        eng.run(_reqs(cfg, lens=(4, 5, 3, 6, 4, 5), new=(3,) * 6,
                      tenants=["a", "b"] * 3))
        status, body = _get(f"{srv.url}/slo")
        assert status == 200
        p = json.loads(body)
        assert p["kind"] == "paddle_tpu.slo"
        av = p["compliance"]["objectives"]["availability"]
        assert av["compliance"] == 1.0 and av["burn_slow"] == 0.0
        assert set(p["tenants"]["tenants"]) == {"a", "b"}
        assert p["autoscale"]["available"]
        # the route is listed at the root index
        _, idx = _get(f"{srv.url}/")
        assert "/slo" in json.loads(idx)["routes"]
        # /metrics carries the tenant series and autoscale gauges
        _, mtext = _get(f"{srv.url}/metrics")
        mtext = mtext.decode()
        assert 'slo_tenant_requests{tenant="a"}' in mtext
        assert "serving_autoscale_drain_safe" in mtext

    def test_healthz_warn_provider(self, mon):
        slo.set_objectives(ttft_p99_ms=1.0)     # everything violates
        srv = server.start_server(port=0)
        eng, cfg = _engine(num_slots=2, max_len=32, page_size=4,
                           decode_chunk=2)
        eng.run(_reqs(cfg, lens=(4,) * 6, new=(3,) * 6))
        status, body = _get(f"{srv.url}/healthz")
        hz = json.loads(body)
        assert status == 200, hz             # warn level: never 503
        rep = hz["providers"]["slo_burn"]
        assert rep["level"] == "warn"
        assert "ttft_p99_ms" in rep["alerting"]
        assert rep["burn_fast"]["ttft_p99_ms"] > 14.4

    def test_flight_record_carries_slo_block(self, mon):
        slo.record_request(_completed(tenant="boxed"))
        payload = trace.flight_payload(reason="test")
        assert payload["slo"]["kind"] == "paddle_tpu.slo"
        assert "boxed" in payload["slo"]["tenants"]["tenants"]
        json.dumps(payload["slo"])           # strictly serializable

    def test_fleet_aggregate_carries_tenants(self, mon):
        slo.record_request(_completed(tenant="acme", model_flops=10.0))
        slo.record_request(_completed(tenant="acme", model_flops=5.0))
        agg = fleet.aggregated_snapshot(name="slo-test")
        t = agg["aggregate"]["slo_tenants"]["acme"]
        assert t["requests"] == 2
        assert t["model_flops"] == pytest.approx(15.0)
        text = fleet.expose_fleet_text(agg)
        assert 'slo_tenant_requests{tenant="acme",agg="sum"} 2' in text

    def test_monitor_reset_empties_plane(self, mon):
        slo.record_request(_completed(tenant="gone"))
        slo.note_sched_tick(1, 1, 2, 0.5)
        monitor.reset()
        assert slo.records() == []
        assert slo.tenants_snapshot()["tenants"] == {}
        assert slo.update_autoscale_gauges() == {"available": False}
        assert slo.tenant_exposition_text() == ""


# ---------------------------------------------------------------------------
# bench-guard lower rungs
# ---------------------------------------------------------------------------

def _load_guard():
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "check_bench_regression",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
            "scripts", "check_bench_regression.py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    return m


class TestBenchGuardSloRungs:
    def test_slo_rungs_in_lower_allowlist(self):
        g = _load_guard()
        assert g.ALLOWLIST_LOWER["serving_ttft_ms_p99"] == \
            "extra.metrics.slo.ttft_p99_ms"
        assert g.ALLOWLIST_LOWER["serving_tpot_ms_p99"] == \
            "extra.metrics.slo.tpot_p99_ms"

    def test_extraction_and_absence_skip(self, tmp_path):
        g = _load_guard()
        blob = {"parsed": {"metric": "x", "value": 100.0, "extra": {
            "metrics": {"slo": {"ttft_p99_ms": 12.5,
                                "tpot_p99_ms": 3.25}}}}}
        rungs = g.extract_rungs(blob, g.ALLOWLIST_LOWER)
        assert rungs["serving_ttft_ms_p99"] == 12.5
        assert rungs["serving_tpot_ms_p99"] == 3.25
        # absence on an old blob contributes nothing (skip, not zero)
        old = {"parsed": {"metric": "x", "value": 100.0, "extra": {}}}
        assert g.extract_rungs(old, g.ALLOWLIST_LOWER) is None
        # trajectory: old round without the block + new round with it
        # -> no ceiling yet, guard passes
        (tmp_path / "BENCH_r01.json").write_text(json.dumps(old))
        (tmp_path / "BENCH_r02.json").write_text(json.dumps(blob))
        ok, lines = g.check(str(tmp_path))
        assert ok, lines
        # a later round regressing TTFT beyond tolerance FAILS
        worse = {"parsed": {"metric": "x", "value": 100.0, "extra": {
            "metrics": {"slo": {"ttft_p99_ms": 20.0,
                                "tpot_p99_ms": 3.30}}}}}
        (tmp_path / "BENCH_r03.json").write_text(json.dumps(worse))
        ok, lines = g.check(str(tmp_path))
        assert not ok
        assert any("serving_ttft_ms_p99" in ln and "REGRESSION" in ln
                   for ln in lines)

    def test_checked_in_trajectory_still_green(self):
        g = _load_guard()
        ok, lines = g.check()
        assert ok, "\n".join(lines)


# ---------------------------------------------------------------------------
# overhead harness (slow lane — the acceptance measurement)
# ---------------------------------------------------------------------------

def measure_slo_overhead(windows=6):
    """Median per-window engine overhead with the whole monitor plane
    (incl. PR 12 cost attribution) ON vs OFF, interleaved windows of
    the serving_paged CPU trace shape. Returns (median_pct, pcts).
    Measured on this container: see CHANGES.md."""
    import time as _time

    import jax
    from paddle_tpu.inference import Request, ServingEngine
    from paddle_tpu.models import llama as L

    cfg = L.llama_tiny(num_hidden_layers=2)
    params = jax.jit(lambda: L.init_params(cfg, jax.random.PRNGKey(0)))()
    jax.block_until_ready(params["embed"])
    rng = np.random.default_rng(42)
    trace_lens = [(int(rng.choice((4, 8, 16))),
                   int(rng.choice((4, 8, 16)))) for _ in range(16)]
    trace_lens.sort(key=lambda t: -t[1])
    max_len = max(p for p, _ in trace_lens) + max(g for _, g in
                                                  trace_lens)

    def run_once(base):
        eng = ServingEngine(L, params, cfg, num_slots=4,
                            max_len=max_len, page_size=4,
                            decode_chunk=8)
        reqs = [Request(rid=base + i,
                        prompt=rng.integers(0, cfg.vocab_size, (p,))
                        .astype(np.int32), max_new_tokens=g,
                        tenant=f"t{i % 4}")
                for i, (p, g) in enumerate(trace_lens)]
        t0 = _time.perf_counter()
        eng.run(reqs)
        return _time.perf_counter() - t0

    def window(flag, base):
        pt.set_flags({"FLAGS_enable_monitor": flag})
        return run_once(base)

    window(False, 0), window(True, 10_000)        # compile + warm
    pcts = []
    for w in range(windows):
        t_off = window(False, 20_000 + w * 1000)
        t_on = window(True, 50_000 + w * 1000)
        pcts.append((t_on - t_off) / t_off * 100.0)
    pt.set_flags({"FLAGS_enable_monitor": False})
    monitor.reset()
    pcts.sort()
    mid = len(pcts) // 2
    med = pcts[mid] if len(pcts) % 2 else (pcts[mid - 1]
                                           + pcts[mid]) / 2
    return med, pcts


@pytest.mark.slow
@pytest.mark.serving
def test_slo_overhead_harness():
    """Cost attribution is pure host arithmetic at seams that already
    synchronized: the monitor-on engine (SLO plane included) stays
    within noise of monitor-off. The tier-1 bound is loose (shared
    2-core container swings ±10% window to window); the <1% acceptance
    number is the interleaved-window median recorded in CHANGES.md."""
    med, pcts = measure_slo_overhead()
    assert med < 10.0, (med, pcts)


# ---------------------------------------------------------------------------
# acting-half signals (ISSUE 13): pure demand model, retry hints, burn
# cache, shed/expired accounting
# ---------------------------------------------------------------------------

class TestActingSignals:
    def test_demand_model_matches_gauge_payload(self, mon):
        # the pure function and the tick-driven gauge path are ONE
        # model: identical fields for identical inputs
        slo.note_sched_tick(3, 2, 4, 0.5)
        via_gauges = slo.update_autoscale_gauges()
        pure = slo.demand_model(3, 2, 4, 0.5)
        for k, v in pure.items():
            assert via_gauges[k] == v, (k, v, via_gauges[k])

    def test_retry_after_hint_math(self, mon):
        horizon = slo.demand_model(0, 0, 1, 1.0)["horizon_s"]
        # idle: floor of 1s
        assert slo.retry_after_hint(slo.demand_model(0, 0, 2, 1.0)) \
            == 1.0
        # demand 2.0 -> one replica's worth of excess -> one horizon
        p = slo.demand_model(2, 2, 2, 0.0)   # util 1 + backlog 1
        assert p["demand_estimate"] == 2.0
        assert slo.retry_after_hint(p) == pytest.approx(horizon)
        # deep backlog clamps at 2 x horizon
        deep = slo.demand_model(100, 2, 2, 0.0)
        assert slo.retry_after_hint(deep) == pytest.approx(2 * horizon)
        # no ticks at all: flat 1.0, never an error
        assert slo.retry_after_hint() == 1.0

    def test_shed_counts_against_availability(self, mon):
        for _ in range(6):
            slo.record_request(_completed(tenant="t"))
        for _ in range(2):
            slo.record_shed("t")
        rep = slo.compliance_report()
        av = rep["objectives"]["availability"]
        assert av["samples_slow"] == 8
        assert av["compliance"] == pytest.approx(6 / 8)
        agg = slo.tenants_snapshot()["tenants"]
        # sheds ride the rejection column plus their own; the claimed
        # tenant had earned its slot by completing
        assert agg["t"]["shed"] == 2 and agg["t"]["rejected"] == 2

    def test_expired_bad_for_availability_excluded_from_latency(
            self, mon):
        for _ in range(6):
            slo.record_request(_completed(tenant="t"))
        # an expired request with a tiny e2e must NOT score as a good
        # e2e sample — excluded from latency windows, bad for
        # availability
        slo.record_request({"tenant": "t", "expired": True,
                            "e2e_ms": 0.5, "queue_wait_ms": 3.0,
                            "page_seconds": 0.01})
        rep = slo.compliance_report()
        assert rep["objectives"]["availability"]["samples_slow"] == 7
        assert rep["objectives"]["availability"]["compliance"] \
            == pytest.approx(6 / 7)
        assert rep["objectives"]["e2e_p99_ms"]["samples_slow"] == 6
        agg = slo.tenants_snapshot()["tenants"]["t"]
        assert agg["expired"] == 1 and agg["completed"] == 6
        # expired costs still fold (it consumed resources)
        assert agg["queue_wait_ms"] == pytest.approx(6 * 1.0 + 3.0)

    def test_burn_alerting_cached_and_monitor_gated(self, mon):
        import paddle_tpu as pt
        slo.set_objectives(e2e_p99_ms=1.0)
        for _ in range(40):
            slo.record_request(_completed(e2e_ms=100.0))
        assert slo.burn_alerting(max_age_s=0) is True
        # cached verdict survives a reset for the TTL...
        monitor.reset()
        assert slo.burn_alerting(max_age_s=3600) is False  # reset
        #          cleared the cache stamp, so this recomputed: False
        # ...and the monitor-off path never reads the window
        pt.set_flags({"FLAGS_enable_monitor": False})
        assert slo.burn_alerting(max_age_s=0) is False
        pt.set_flags({"FLAGS_enable_monitor": True})

    def test_cost_carrying_shed_folds_consumption(self, mon):
        # review fix: a shed of work that already consumed resources
        # (displaced/drained after queue wait) folds its cost columns
        # into the tenant aggregates; a malformed rejection still
        # folds nothing
        slo.record_request(_completed(tenant="t"))      # earn the slot
        slo.record_request({"tenant": "t", "rejected": True,
                            "shed": True, "queue_wait_ms": 5.0,
                            "prefill_tokens": 7})
        slo.record_request({"tenant": "t", "rejected": True,
                            "queue_wait_ms": 99.0})     # malformed
        agg = slo.tenants_snapshot()["tenants"]["t"]
        assert agg["shed"] == 1 and agg["rejected"] == 2
        assert agg["queue_wait_ms"] == pytest.approx(1.0 + 5.0)
        assert agg["prefill_tokens"] == 4 + 7
