"""hapi Model / metric / vision / distribution tests (SURVEY.md §2.7
parity rows; assertion style follows test/legacy_test/test_model.py and
test_metrics.py in the reference)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu import distribution as D
from paddle_tpu.io import Dataset
from paddle_tpu.metric import Accuracy, Auc, Precision, Recall
from paddle_tpu.vision import models, transforms
from paddle_tpu.vision.datasets import FakeData

RNG = np.random.default_rng(5)


class Blobs(Dataset):
    def __init__(self, n=192, labeled=True):
        rng = np.random.default_rng(0)
        centers = rng.normal(size=(4, 8)) * 3
        self.y = rng.integers(0, 4, size=n)
        self.x = (centers[self.y]
                  + rng.normal(size=(n, 8))).astype("float32")
        self.y = self.y.astype("int64")
        self.labeled = labeled

    def __getitem__(self, i):
        return (self.x[i], self.y[i]) if self.labeled else self.x[i]

    def __len__(self):
        return len(self.x)


class TestHapiModel:
    def _fit(self, **kw):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))
        model = paddle.Model(net)
        model.prepare(
            optimizer=opt.Adam(learning_rate=1e-2,
                               parameters=net.parameters()),
            loss=nn.CrossEntropyLoss(), metrics=Accuracy())
        model.fit(Blobs(), epochs=3, batch_size=64, verbose=0, **kw)
        return model

    def test_fit_evaluate_predict(self):
        model = self._fit()
        res = model.evaluate(Blobs(), batch_size=64, verbose=0)
        assert res["acc"] > 0.9, res
        preds = model.predict(Blobs(64, labeled=False), batch_size=32,
                              stack_outputs=True, verbose=0)
        assert preds[0].shape == (64, 4)

    def test_save_load_roundtrip(self, tmp_path):
        model = self._fit()
        path = str(tmp_path / "ck")
        model.save(path)
        net2 = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))
        m2 = paddle.Model(net2)
        m2.prepare(loss=nn.CrossEntropyLoss(), metrics=Accuracy())
        m2.load(path)
        r1 = model.evaluate(Blobs(), batch_size=64, verbose=0)
        r2 = m2.evaluate(Blobs(), batch_size=64, verbose=0)
        np.testing.assert_allclose(r1["loss"], r2["loss"], rtol=1e-6)

    def test_early_stopping(self):
        from paddle_tpu.hapi.callbacks import EarlyStopping
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 4))
        model = paddle.Model(net)
        # lr=0: loss can never improve, so patience=0 stops at epoch 2
        model.prepare(optimizer=opt.Adam(learning_rate=0.0,
                                         parameters=net.parameters()),
                      loss=nn.CrossEntropyLoss())
        es = EarlyStopping(monitor="loss", patience=0)
        model.fit(Blobs(), eval_data=Blobs(), epochs=50, batch_size=64,
                  verbose=0, callbacks=[es])
        assert model.stop_training

    def test_summary_counts(self):
        net = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))
        info = paddle.summary(net)
        assert info["total_params"] == 8 * 32 + 32 + 32 * 4 + 4


class TestMetrics:
    def test_accuracy_topk(self):
        m = Accuracy(topk=(1, 2))
        pred = np.array([[0.1, 0.9, 0], [0.8, 0.1, 0.1]], "float32")
        label = np.array([1, 2], "int64")
        m.update(m.compute(pred, label))
        top1, top2 = m.accumulate()
        assert abs(top1 - 0.5) < 1e-6
        assert abs(top2 - 0.5) < 1e-6   # sample2: label 2 not in top2

    def test_precision_recall(self):
        p = Precision()
        r = Recall()
        preds = np.array([0.9, 0.8, 0.2, 0.7], "float32")
        labels = np.array([1, 0, 1, 1], "int64")
        p.update(preds, labels)
        r.update(preds, labels)
        assert abs(p.accumulate() - 2 / 3) < 1e-6
        assert abs(r.accumulate() - 2 / 3) < 1e-6

    def test_auc_perfect(self):
        auc = Auc()
        preds = np.array([[0.9, 0.1], [0.8, 0.2], [0.2, 0.8],
                          [0.1, 0.9]], "float32")
        labels = np.array([0, 0, 1, 1])
        auc.update(preds, labels)
        assert abs(auc.accumulate() - 1.0) < 1e-6


class TestVision:
    def test_transform_pipeline(self):
        t = transforms.Compose([
            transforms.Resize(40), transforms.RandomCrop(32),
            transforms.RandomHorizontalFlip(),
            transforms.Normalize(mean=[0.5] * 3, std=[0.5] * 3,
                                 data_format="HWC"),
            transforms.Transpose()])
        img = RNG.integers(0, 255, (48, 64, 3)).astype("uint8")
        assert t(img).shape == (3, 32, 32)

    def test_resize_bilinear_matches_scale(self):
        from paddle_tpu.vision.transforms import functional as VF
        img = np.arange(16, dtype="float32").reshape(4, 4)
        out = VF.resize(img, (2, 2))
        assert out.shape == (2, 2)
        assert out[0, 0] < out[1, 1]

    @pytest.mark.parametrize("builder,inshape,classes", [
        (lambda: models.LeNet(), (2, 1, 28, 28), 10),
        pytest.param(lambda: models.resnet18(num_classes=10),
                     (2, 3, 32, 32), 10, marks=pytest.mark.slow,
                     # tier-1 budget (ISSUE 8): ~15s forward; LeNet
                     # keeps the vision Model surface covered and
                     # test_lenet_trains_on_fakedata keeps the fit loop
                     id="resnet18"),
        pytest.param(lambda: models.mobilenet_v2(num_classes=5),
                     (2, 3, 32, 32), 5, marks=pytest.mark.slow,
                     # tier-1 budget (ISSUE 5): heaviest vision forward
                     # (~27s); LeNet+resnet18 keep the surface covered
                     id="mobilenet_v2"),
    ])
    def test_model_forward_shapes(self, builder, inshape, classes):
        net = builder()
        x = paddle.to_tensor(RNG.normal(size=inshape).astype("float32"))
        assert net(x).shape == [inshape[0], classes]

    @pytest.mark.slow  # tier-1 budget (ISSUE 20 rebalance): convergence run; fit_evaluate_predict
    # + model_forward_shapes keep the hapi fit seam fast
    def test_lenet_trains_on_fakedata(self):
        paddle.seed(0)
        net = models.LeNet()
        model = paddle.Model(net)
        model.prepare(
            optimizer=opt.Adam(learning_rate=1e-3,
                               parameters=net.parameters()),
            loss=nn.CrossEntropyLoss(), metrics=Accuracy())
        data = FakeData(size=64, image_shape=(1, 28, 28), num_classes=10)
        model.fit(data, epochs=1, batch_size=32, verbose=0)

    def test_pretrained_raises(self):
        with pytest.raises(NotImplementedError):
            models.resnet18(pretrained=True)


class TestDistribution:
    def test_normal_moments_and_logprob(self):
        paddle.seed(0)
        n = D.Normal(0.0, 1.0)
        s = n.sample([20000]).numpy()
        assert abs(s.mean()) < 0.05 and abs(s.std() - 1) < 0.05
        assert abs(float(n.log_prob(0.0)) + 0.9189385) < 1e-5

    def test_kl_closed_forms(self):
        kl = float(D.kl_divergence(D.Normal(0., 1.), D.Normal(0., 1.)))
        assert abs(kl) < 1e-6
        kl2 = float(D.kl_divergence(D.Normal(0., 1.), D.Normal(1., 2.)))
        assert abs(kl2 - (np.log(2) + (1 + 1) / 8 - 0.5)) < 1e-5

    def test_categorical(self):
        c = D.Categorical(logits=np.zeros(4, "float32"))
        assert abs(float(c.entropy()) - np.log(4)) < 1e-5
        lp = c.log_prob(np.array([0, 3]))
        np.testing.assert_allclose(lp.numpy(), np.log(0.25), rtol=1e-5)

    def test_sampling_statistics(self):
        paddle.seed(3)
        g = D.Gamma(2.0, 4.0)
        assert abs(float(g.sample([20000]).numpy().mean()) - 0.5) < 0.02
        b = D.Bernoulli(probs=0.3)
        assert abs(float(b.sample([20000]).numpy().mean()) - 0.3) < 0.02

    def test_multinomial_counts(self):
        m = D.Multinomial(10, np.array([0.2, 0.3, 0.5], "float32"))
        s = m.sample([50]).numpy()
        assert (s.sum(-1) == 10).all()


class TestReduceLROnPlateauWithFit:
    def test_plateau_callback_reduces_lr_through_fit(self):
        import numpy as np

        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu.io import DataLoader, Dataset

        class Zeros(Dataset):
            def __getitem__(self, i):
                return (np.zeros(4, "float32"), np.zeros(1, "float32"))

            def __len__(self):
                return 8

        net = nn.Linear(4, 1)
        # weights start at a fixed point of the data (all-zero targets &
        # inputs): loss is constant -> guaranteed plateau
        net.weight.set_value(np.zeros((4, 1), "float32"))
        net.bias.set_value(np.zeros((1,), "float32"))
        opt = paddle.optimizer.SGD(learning_rate=1.0,
                                   parameters=net.parameters())
        model = paddle.Model(net)
        model.prepare(optimizer=opt, loss=nn.MSELoss())
        cb = paddle.callbacks.ReduceLROnPlateau(monitor="loss", factor=0.5,
                                                patience=1, verbose=0)
        loader = DataLoader(Zeros(), batch_size=4)
        model.fit(loader, eval_data=loader, epochs=4, verbose=0,
                  callbacks=[cb])
        assert opt.get_lr() < 1.0
