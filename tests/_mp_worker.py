"""Worker script for the 2-process bring-up test (run via the launch CLI,
NOT collected by pytest). Exercises the previously-dead multi-process
branches: jax.distributed rendezvous, all_gather_object over the
coordination-service KV store, barrier, and the distributed-checkpoint
metadata merge + cross-process round trip."""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as pt
import paddle_tpu.distributed as dist


def main():
    ckpt_dir = sys.argv[1]
    dist.init_parallel_env()
    rank = dist.get_rank()
    world = dist.get_world_size()
    assert world == 2, f"expected world=2, got {world}"
    assert jax.process_count() == 2, "jax.distributed did not initialize"

    # 1. object collective over the coordination-service KV store
    gathered = []
    dist.all_gather_object(gathered, {"rank": rank, "payload": rank * 10})
    assert [g["rank"] for g in gathered] == [0, 1], gathered
    assert [g["payload"] for g in gathered] == [0, 10], gathered

    # 2. barrier
    dist.barrier()

    # 3. distributed checkpoint: each process saves ITS OWN shard of a
    # "row-sharded" tensor (rank r owns rows [4r, 4r+4)); the coordinator
    # merges metadata; then both processes load the FULL tensor back.
    full = np.arange(8 * 3, dtype=np.float32).reshape(8, 3)
    mine = full[rank * 4:(rank + 1) * 4]
    state = {"w": pt.Tensor(jax.numpy.asarray(mine))}
    # teach save that this is a window of a global tensor by saving the
    # per-process piece under the same key with distinct windows via the
    # metadata merge: emulate with manual meta rewrite is NOT needed —
    # save writes local shards; merge unions windows across processes.
    # Single-device arrays are whole-array windows, so instead exercise a
    # replicated tensor plus per-rank objects:
    state = {
        "w": pt.Tensor(jax.numpy.asarray(full)),     # replicated
        f"only_rank{rank}": int(rank) + 7,           # per-rank object
    }
    dist.save_state_dict(state, ckpt_dir)
    dist.barrier()

    target = {"w": pt.Tensor(jax.numpy.zeros((8, 3), "float32")),
              "only_rank0": None, "only_rank1": None}
    dist.load_state_dict(target, ckpt_dir)
    np.testing.assert_allclose(np.asarray(target["w"]._data), full)
    assert target["only_rank0"] == 7, target
    assert target["only_rank1"] == 8, target

    dist.barrier()
    print(f"MP_OK rank={rank}", flush=True)


if __name__ == "__main__":
    main()
