"""Attribute-complete parity sweep vs the reference (VERDICT-r3 item 5).

Round 3's sweep compared only ``__all__`` lists, so a module attribute
imported into a reference ``__init__`` but not exported (incubate.asp)
could hide. This sweep widens the definition of "public name" to:

  __all__  ∪  top-level def/class  ∪  names bound by RELATIVE imports

per reference namespace (AST only — reference code is never imported),
minus a denylist of the reference's own implementation plumbing
(LayerHelper, check_type, ...) that leaks into its module namespaces.

Every swept name must either resolve on the corresponding paddle_tpu
module or appear in docs/attr_delta.json with a category:
  - "na":       not applicable on TPU (CUDA/XPU/IPU/PS-era/monkey-patch
                internals) — permanent, with a reason
  - "pending":  a real gap queued for implementation
The test FAILS on any unexplained miss — the next asp can't hide — and
also fails if a delta entry now resolves (stale list)."""
import ast
import json
import os

import pytest

REF = "/root/reference/python/paddle"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DELTA_PATH = os.path.join(REPO, "docs", "attr_delta.json")

NAMESPACES = [
    "", "nn", "nn.functional", "nn.initializer", "nn.utils", "nn.quant",
    "tensor", "linalg", "fft", "signal", "optimizer", "optimizer.lr",
    "metric", "io", "amp", "autograd", "jit", "static", "static.nn",
    "distribution", "distributed", "vision", "vision.models", "vision.ops",
    "vision.transforms", "vision.datasets", "audio", "text", "sparse",
    "sparse.nn", "geometric", "incubate", "incubate.nn",
    "incubate.autograd", "incubate.asp", "quantization", "device", "hub",
    "onnx", "utils", "callbacks", "profiler", "utils.cpp_extension",
    "utils.unique_name", "distributed.sharding",
]

# Implementation plumbing the reference's module namespaces leak (its own
# framework internals / imported helper symbols, not API a user of the
# reference would call as paddle.<ns>.<name>).
_PLUMBING = {
    "LayerHelper", "check_variable_and_dtype", "check_type", "check_dtype",
    "check_shape", "core", "Variable", "in_dygraph_mode",
    "in_dynamic_mode", "in_dynamic_or_pir_mode", "in_pir_mode",
    "convert_np_dtype_to_dtype_", "convert_dtype", "dygraph_only",
    "deprecated", "signature_safe_contextmanager", "extract_cuda_device_id",
    "default_main_program", "autoincreased_step_counter",
    "magic_method_func", "tensor_method_func", "monkey_patch_dtype",
    "monkey_patch_math_tensor", "monkey_patch_program",
    "monkey_patch_value", "monkey_patch_variable", "IrGuard", "ir_guard",
    # reference vision.ops imports these nn symbols for its own blocks
    "BatchNorm2D", "Conv2D", "ReLU", "Sequential", "Normal",
    # reference fft/signal bind their C-op helpers at module top level
    "fft_c2c", "fft_c2r", "fft_r2c", "fftn_c2c", "fftn_c2r", "fftn_r2c",
    "is_floating_point", "is_integer", "is_complex", "is_persistable",
    "setitem", "backward_mode", "ir_backward",
}


def _public_names(init_path):
    tree = ast.parse(open(init_path, encoding="utf-8").read())
    names = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    try:
                        names |= {e.value for e in node.value.elts
                                  if isinstance(e, ast.Constant)}
                    except Exception:
                        pass
        elif isinstance(node, (ast.FunctionDef, ast.ClassDef)):
            if not node.name.startswith("_"):
                names.add(node.name)
        elif isinstance(node, ast.ImportFrom) and (node.level or 0) >= 1:
            for a in node.names:
                nm = a.asname or a.name
                if not nm.startswith("_") and nm != "*":
                    names.add(nm)
    return names - _PLUMBING


def _load_delta():
    with open(DELTA_PATH) as f:
        return json.load(f)


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference absent")
class TestAttributeParity:
    def test_every_public_attribute_resolves_or_is_recorded(self):
        import importlib

        delta = _load_delta()
        unexplained = {}
        stale = {}
        for ns in NAMESPACES:
            ref_dir = os.path.join(REF, *ns.split(".")) if ns else REF
            init = os.path.join(ref_dir, "__init__.py")
            if not os.path.exists(init):
                init = ref_dir + ".py"
                if not os.path.exists(init):
                    continue
            names = _public_names(init)
            try:
                mod = importlib.import_module(
                    "paddle_tpu" + ("." + ns if ns else ""))
            except ImportError:
                mod = None   # whole module absent: every name must be
                             # recorded in the delta file
            ns_key = ns or "paddle"
            recorded = set(delta.get(ns_key, {}))
            for n in sorted(names):
                have = mod is not None and hasattr(mod, n)
                if not have and n not in recorded:
                    unexplained.setdefault(ns_key, []).append(n)
                elif have and n in recorded:
                    stale.setdefault(ns_key, []).append(n)
        assert not unexplained, (
            "public reference attributes neither implemented nor recorded "
            f"in docs/attr_delta.json: {json.dumps(unexplained, indent=1)}")
        assert not stale, (
            "docs/attr_delta.json entries that now resolve — remove them: "
            f"{json.dumps(stale, indent=1)}")

    def test_delta_entries_have_category_and_reason(self):
        delta = _load_delta()
        for ns, entries in delta.items():
            assert isinstance(entries, dict), ns
            for name, info in entries.items():
                assert info.get("category") in ("na", "pending"), \
                    f"{ns}.{name}: category must be na|pending"
                assert info.get("reason"), f"{ns}.{name}: missing reason"


class TestNewSurfaceBehavior:
    """Spot behavior checks for the burn-down batch (not just hasattr)."""

    def test_signal_frame_overlap_add_roundtrip(self):
        import numpy as np

        import paddle_tpu as paddle
        from paddle_tpu import signal

        x = paddle.to_tensor(np.arange(12, dtype="float32"))
        f = signal.frame(x, 4, 4)           # hop == frame: no overlap
        assert f.shape == [4, 3]
        r = signal.overlap_add(f, 4)
        np.testing.assert_allclose(r.numpy(), x.numpy())

    def test_async_save_roundtrip(self, tmp_path):
        import numpy as np

        import paddle_tpu as paddle

        obj = {"w": paddle.to_tensor(np.ones((3, 3), "float32"))}
        p = str(tmp_path / "ck.pdparams")
        paddle.async_save(obj, p)
        paddle.clear_async_save_task_queue()
        loaded = paddle.load(p)
        np.testing.assert_allclose(np.asarray(loaded["w"].numpy()),
                                   np.ones((3, 3)))

    def test_ptq_calibrates_and_fake_quants(self):
        import numpy as np

        import paddle_tpu as paddle
        import paddle_tpu.quantization as Q
        from paddle_tpu import nn

        net = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 4))
        ptq = Q.ImperativePTQ(Q.PTQConfig(Q.AbsmaxQuantizer(),
                                          Q.PerChannelAbsmaxQuantizer()))
        m = ptq.quantize(net, inplace=True)
        rng = np.random.default_rng(0)
        before = np.asarray(m[0].weight.numpy()).copy()
        for _ in range(2):
            m(paddle.to_tensor(rng.normal(size=(4, 8)).astype("float32")))
        th = ptq.save_quantized_model(m, None)
        assert len(th) == 2
        after = np.asarray(m[0].weight.numpy())
        # fake-quant-dequant changed the weights but only slightly
        assert not np.array_equal(before, after)
        np.testing.assert_allclose(before, after, atol=np.abs(before).max()
                                   / 100)

    def test_group_sharded_parallel_levels(self):
        import pytest

        import paddle_tpu.distributed as dist
        from paddle_tpu import nn, optimizer

        net = nn.Linear(4, 4)
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=net.parameters())
        model, wrapped, scaler = dist.sharding.group_sharded_parallel(
            net, opt, "os_g")
        assert model is net and scaler is None
        with pytest.raises(ValueError, match="level"):
            dist.sharding.group_sharded_parallel(net, opt, "bogus")
        with pytest.raises(NotImplementedError):
            dist.sharding.group_sharded_parallel(net, opt, "os",
                                                 offload=True)

    def test_legacy_lr_decays_return_schedulers(self):
        from paddle_tpu.optimizer import lr

        s = lr.cosine_decay(0.1, 10, 2)
        assert hasattr(s, "step") and s.get_lr() == 0.1
        s = lr.piecewise_decay([3, 6], [0.1, 0.01, 0.001])
        s.step(); s.step(); s.step(); s.step()
        assert s.get_lr() == 0.01

    def test_tensor_array_family(self):
        import paddle_tpu.tensor as T

        arr = T.create_array()
        T.array_write(1.5, 0, arr)
        T.array_write(2.5, 1, arr)
        assert T.array_length(arr) == 2 and T.array_read(arr, 1) == 2.5

    def test_asp_helper_and_autotune_facade(self):
        from paddle_tpu.incubate import autotune
        from paddle_tpu.incubate.asp import ASPHelper

        autotune.set_config({"kernel": {"enable": True}})
        assert autotune.get_config()["kernel"]["enable"]
        assert callable(ASPHelper.prune_model)
