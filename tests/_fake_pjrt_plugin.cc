// Minimal PJRT C-API plugin for the plugin-device seam test
// (tests/test_device_plugin.py). Shaped exactly like a vendor plugin —
// exports GetPjrtApi returning a versioned PJRT_Api — but owns no
// hardware: PJRT_Client_Create reports UNIMPLEMENTED through the real
// error protocol, so registration succeeds and backend initialization
// fails CLEANLY (the registered-but-unavailable state the framework
// must handle).
#include <cstring>
#include <string>

#include "tensorflow/compiler/xla/pjrt/c/pjrt_c_api.h"

struct PJRT_Error {
  std::string message;
};

static void ErrorDestroy(PJRT_Error_Destroy_Args* args) {
  delete args->error;
}

static void ErrorMessage(PJRT_Error_Message_Args* args) {
  args->message = args->error->message.c_str();
  args->message_size = args->error->message.size();
}

static PJRT_Error* ErrorGetCode(PJRT_Error_GetCode_Args* args) {
  args->code = PJRT_Error_Code_UNIMPLEMENTED;
  return nullptr;
}

static PJRT_Error* PluginInitialize(PJRT_Plugin_Initialize_Args*) {
  return nullptr;
}

static PJRT_Error* PluginAttributes(PJRT_Plugin_Attributes_Args* args) {
  args->num_attributes = 0;
  args->attributes = nullptr;
  return nullptr;
}

static PJRT_Error* ClientCreate(PJRT_Client_Create_Args*) {
  return new PJRT_Error{
      "fake_pjrt test plugin: no hardware behind this plugin"};
}

extern "C" const PJRT_Api* GetPjrtApi() {
  static PJRT_Api api;
  std::memset(&api, 0, sizeof(api));
  api.struct_size = PJRT_Api_STRUCT_SIZE;
  api.pjrt_api_version.struct_size = PJRT_Api_Version_STRUCT_SIZE;
  api.pjrt_api_version.major_version = PJRT_API_MAJOR;
  api.pjrt_api_version.minor_version = PJRT_API_MINOR;
  api.PJRT_Error_Destroy = ErrorDestroy;
  api.PJRT_Error_Message = ErrorMessage;
  api.PJRT_Error_GetCode = ErrorGetCode;
  api.PJRT_Plugin_Initialize = PluginInitialize;
  api.PJRT_Plugin_Attributes = PluginAttributes;
  api.PJRT_Client_Create = ClientCreate;
  return &api;
}
