"""Row-sparse embedding gradients (SelectedRows equivalent) — VERDICT-r5
item 3. Reference: paddle/phi/core/selected_rows.h + embedding sparse
grad kernels + adam lazy_mode.

Contract under test:
- Embedding(sparse=True).backward produces param.grad with
  is_selected_rows() True, holding O(tokens) rows/values — never a
  dense [V, D] array.
- coalesce() merges duplicate ids; semantics match the dense scatter.
- Optimizers update O(unique rows): untouched param rows AND untouched
  moment rows are bit-identical; training parity vs the dense path
  (SGD exact; Adam vs a lazy-mode oracle).
- Every dense-style consumer (hooks, clip utils, paddle.grad, exotic
  optimizers) degrades to a correct dense grad.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.core.selected_rows import SelectedRows, SelectedRowsGrad

V, D = 50, 8


def _emb(sparse=True, v=V, d=D, seed=0):
    paddle.seed(seed)
    return nn.Embedding(v, d, sparse=sparse)


def _ids(*vals):
    return paddle.to_tensor(np.asarray(vals, "int32"))


class TestSparseBackward:
    def test_grad_is_selected_rows_with_flat_ids(self):
        e = _emb()
        out = e(_ids(3, 7, 3, 11))
        out.sum().backward()
        g = e.weight.grad
        assert isinstance(g, SelectedRowsGrad) and g.is_selected_rows()
        assert list(g.shape) == [V, D]          # metadata, no densify
        assert g.is_selected_rows()             # shape access kept it sparse
        np.testing.assert_array_equal(np.sort(np.asarray(g.sr.rows)),
                                      [3, 3, 7, 11])
        assert g.sr.values.shape == (4, D)

    def test_semantics_match_dense_path(self):
        ids = np.array([[1, 4, 1], [4, 9, 0]], "int32")
        es, ed = _emb(True), _emb(False)
        ed.weight.set_value(np.asarray(es.weight.numpy()))
        up = np.random.default_rng(0).normal(size=(2, 3, D)).astype("f4")
        (es(paddle.to_tensor(ids)) * paddle.to_tensor(up)).sum().backward()
        (ed(paddle.to_tensor(ids)) * paddle.to_tensor(up)).sum().backward()
        assert es.weight.grad.is_selected_rows()
        assert not ed.weight.grad.is_selected_rows()
        np.testing.assert_allclose(
            np.asarray(es.weight.grad.sr.to_dense_array()),
            np.asarray(ed.weight.grad.numpy()), rtol=1e-6)

    def test_padding_idx_rows_zeroed(self):
        e = nn.Embedding(V, D, padding_idx=2, sparse=True)
        e(_ids(2, 5)).sum().backward()
        sr = e.weight.grad.sr.coalesce()
        dense = np.asarray(sr.to_dense_array())
        np.testing.assert_allclose(dense[2], np.zeros(D))
        assert float(np.abs(dense[5]).sum()) > 0

    def test_two_backwards_concatenate_then_coalesce(self):
        e = _emb()
        e(_ids(1, 2)).sum().backward()
        e(_ids(2, 3)).sum().backward()
        g = e.weight.grad
        assert g.is_selected_rows() and g.sr.rows.shape[0] == 4
        sr = g.sr.coalesce()
        rows = np.asarray(sr.rows)
        assert rows.shape[0] == 4               # static shape kept
        assert (rows < V).sum() == 3            # {1, 2, 3} + one sentinel
        assert set(rows[rows < V]) == {1, 2, 3}
        dense = np.asarray(sr.to_dense_array())
        np.testing.assert_allclose(dense[2], np.full(D, 2.0))

    def test_memory_at_128k_vocab(self):
        # the VERDICT memory assertion: grad payload is O(tokens·D),
        # not O(V·D) — at 128k vocab the dense grad would be 32 MB f32
        e = nn.Embedding(131072, 64, sparse=True)
        ids = paddle.to_tensor(
            np.random.default_rng(1).integers(0, 131072, 256).astype("i4"))
        e(ids).sum().backward()
        g = e.weight.grad
        assert g.is_selected_rows()
        dense_bytes = 131072 * 64 * 4
        assert g.sr.nbytes < dense_bytes / 100, (g.sr.nbytes, dense_bytes)

    def test_mixed_dense_use_falls_back_dense(self):
        e = _emb()
        loss = e(_ids(1, 2)).sum() + (e.weight * 2.0).sum()
        loss.backward()
        g = e.weight.grad
        assert not g.is_selected_rows()          # mixed -> densified
        dense = np.asarray(g.numpy())
        np.testing.assert_allclose(dense[1], np.full(D, 3.0), rtol=1e-6)
        np.testing.assert_allclose(dense[0], np.full(D, 2.0), rtol=1e-6)

    def test_nonleaf_weight_uses_dense_path(self):
        e = _emb()
        w2 = e.weight * 1.0                      # op output, not a leaf
        out = paddle.nn.functional.embedding(_ids(1), w2, sparse=True)
        out.sum().backward()
        assert not e.weight.grad.is_selected_rows()

    def test_hook_sees_dense(self):
        e = _emb()
        seen = {}
        e.weight.register_hook(lambda g: seen.setdefault(
            "shape", list(g.shape)))
        e(_ids(4)).sum().backward()
        assert seen["shape"] == [V, D]
        assert not e.weight.grad.is_selected_rows()

    def test_paddle_grad_returns_dense(self):
        e = _emb()
        out = e(_ids(1, 1)).sum()
        (g,) = paddle.grad([out], [e.weight])
        assert not g.is_selected_rows()
        np.testing.assert_allclose(np.asarray(g.numpy())[1],
                                   np.full(D, 2.0), rtol=1e-6)

    def test_under_no_grad_and_jit(self):
        e = _emb()
        with paddle.no_grad():
            out = e(_ids(1))
        assert out.shape == [1, D]
        f = paddle.jit.to_static(lambda x: e(x).sum())
        val = f(_ids(1, 2))
        assert np.isfinite(float(val.numpy()))

    def test_clear_grad_set_to_zero_drops_sparse(self):
        e = _emb()
        e(_ids(1)).sum().backward()
        e.weight.clear_gradient(set_to_zero=True)
        assert e.weight.grad is None


class TestSparseOptimizers:
    def _fit_pair(self, opt_cls, steps=3, **kw):
        es, ed = _emb(True, seed=7), _emb(False, seed=7)
        ed.weight.set_value(np.asarray(es.weight.numpy()))
        os_, od = (opt_cls(parameters=[es.weight], **kw),
                   opt_cls(parameters=[ed.weight], **kw))
        rng = np.random.default_rng(0)
        for _ in range(steps):
            ids = paddle.to_tensor(rng.integers(0, V, 6).astype("i4"))
            for e, o in ((es, os_), (ed, od)):
                (e(ids) ** 2).sum().backward()
                o.step()
                o.clear_grad()
        return es, ed

    def test_sgd_exact_parity_and_untouched_rows(self):
        es, ed = self._fit_pair(opt.SGD, learning_rate=0.1)
        np.testing.assert_allclose(np.asarray(es.weight.numpy()),
                                   np.asarray(ed.weight.numpy()), rtol=1e-6)

    def test_sgd_untouched_rows_bit_identical(self):
        e = _emb()
        before = np.asarray(e.weight.numpy()).copy()
        o = opt.SGD(learning_rate=0.5, parameters=[e.weight])
        (e(_ids(3, 9)) ** 2).sum().backward()
        o.step()
        after = np.asarray(e.weight.numpy())
        touched = {3, 9}
        for r in range(V):
            if r in touched:
                assert np.abs(after[r] - before[r]).max() > 0
            else:
                np.testing.assert_array_equal(after[r], before[r])

    def test_adam_default_exact_dense_parity(self):
        # lazy_mode=False (default): sparse grads give BIT-level dense
        # Adam semantics — moments decay everywhere — while the dense
        # [V, D] grad buffer never exists
        es, ed = self._fit_pair(opt.Adam, steps=4, learning_rate=0.05)
        np.testing.assert_allclose(np.asarray(es.weight.numpy()),
                                   np.asarray(ed.weight.numpy()),
                                   rtol=1e-5, atol=1e-7)

    def test_adamw_default_exact_dense_parity(self):
        es, ed = self._fit_pair(opt.AdamW, steps=4, learning_rate=0.05,
                                weight_decay=0.1)
        np.testing.assert_allclose(np.asarray(es.weight.numpy()),
                                   np.asarray(ed.weight.numpy()),
                                   rtol=1e-5, atol=1e-7)

    def test_adam_lazy_oracle_and_moment_rows(self):
        e = _emb(seed=3)
        o = opt.Adam(learning_rate=0.1, lazy_mode=True,
                     parameters=[e.weight])
        w0 = np.asarray(e.weight.numpy()).astype("f8").copy()
        (e(_ids(5, 5, 12)) ** 2).sum().backward()
        sr = e.weight.grad.sr.coalesce()
        g = np.zeros((V, D))
        rows_np = np.asarray(sr.rows)
        real = rows_np < V                       # drop sentinel slots
        np.add.at(g, rows_np[real], np.asarray(sr.values, "f8")[real])
        o.step()
        after = np.asarray(e.weight.numpy())
        st = o._accumulators[id(e.weight)]
        m1, m2 = np.asarray(st["moment1"]), np.asarray(st["moment2"])
        for r in range(V):
            if r in (5, 12):
                m1_o = 0.1 * g[r]
                m2_o = 0.001 * g[r] ** 2
                upd = 0.1 * (m1_o / 0.1) / (np.sqrt(m2_o / 0.001) + 1e-8)
                np.testing.assert_allclose(after[r], w0[r] - upd, rtol=1e-4)
                np.testing.assert_allclose(m1[r], m1_o, rtol=1e-4)
            else:
                np.testing.assert_array_equal(after[r], w0[r])
                np.testing.assert_array_equal(m1[r], np.zeros(D))
                np.testing.assert_array_equal(m2[r], np.zeros(D))

    def test_adamw_lazy_decay_touched_rows_only(self):
        e = _emb(seed=5)
        before = np.asarray(e.weight.numpy()).copy()
        o = opt.AdamW(learning_rate=0.01, weight_decay=0.5, lazy_mode=True,
                      parameters=[e.weight])
        (e(_ids(2)) ** 2).sum().backward()
        o.step()
        after = np.asarray(e.weight.numpy())
        assert np.abs(after[2] - before[2]).max() > 0
        np.testing.assert_array_equal(after[3], before[3])  # no decay leak

    def test_adagrad_exact_parity(self):
        # dense Adagrad's moment/update are zero wherever grad is zero,
        # so lazy == dense exactly (unlike Momentum, where dense keeps
        # applying stale velocity to untouched rows)
        es, ed = self._fit_pair(opt.Adagrad, learning_rate=0.05)
        np.testing.assert_allclose(np.asarray(es.weight.numpy()),
                                   np.asarray(ed.weight.numpy()),
                                   rtol=1e-5, atol=1e-7)

    def test_momentum_exact_dense_parity(self):
        # momentum is non-lazy (reference SelectedRows momentum kernel):
        # velocity decays on all rows -> exact dense equivalence
        es, ed = self._fit_pair(opt.Momentum, steps=3, learning_rate=0.05,
                                momentum=0.9)
        np.testing.assert_allclose(np.asarray(es.weight.numpy()),
                                   np.asarray(ed.weight.numpy()),
                                   rtol=1e-5, atol=1e-7)

    def test_rmsprop_falls_back_densified(self):
        es, ed = self._fit_pair(opt.RMSProp, learning_rate=0.05, rho=0.9)
        np.testing.assert_allclose(np.asarray(es.weight.numpy()),
                                   np.asarray(ed.weight.numpy()), rtol=1e-6)

    def test_global_norm_clip_mixed_sparse_dense(self):
        es, dense_p = _emb(True, seed=9), None
        lin = nn.Linear(D, 4)
        clip = paddle.nn.ClipGradByGlobalNorm(0.01)
        o = opt.SGD(learning_rate=1.0, grad_clip=clip,
                    parameters=[es.weight] + list(lin.parameters()))
        # all-dense twin
        ed = _emb(False, seed=9)
        lin2 = nn.Linear(D, 4)
        for a, b in zip(lin2.parameters(), lin.parameters()):
            a.set_value(np.asarray(b.numpy()))
        o2 = opt.SGD(learning_rate=1.0,
                     grad_clip=paddle.nn.ClipGradByGlobalNorm(0.01),
                     parameters=[ed.weight] + list(lin2.parameters()))
        ids = _ids(1, 2, 3)
        (lin(es(ids)) ** 2).sum().backward()
        (lin2(ed(ids)) ** 2).sum().backward()
        assert es.weight.grad.is_selected_rows()
        o.step()
        o2.step()
        np.testing.assert_allclose(np.asarray(es.weight.numpy()),
                                   np.asarray(ed.weight.numpy()),
                                   rtol=1e-5, atol=1e-7)

    def test_nn_utils_clip_works_on_sparse_via_densify(self):
        e = _emb()
        (e(_ids(1, 2)) ** 2).sum().backward()
        nn.utils.clip_grad_norm_([e.weight], 0.001)
        assert not e.weight.grad.is_selected_rows()   # degraded, correct
        assert float(np.linalg.norm(
            np.asarray(e.weight.grad.numpy()))) <= 0.0011


class TestSelectedRowsObject:
    def test_coalesce_sums_duplicates(self):
        sr = SelectedRows(jnp.asarray([4, 1, 4], jnp.int32),
                          jnp.asarray([[1.], [2.], [3.]]), (6, 1))
        c = sr.coalesce()
        # static-shape device coalesce: unique rows first, sentinel
        # (dense_shape[0]) pads the duplicate slots with zero values
        np.testing.assert_array_equal(np.asarray(c.rows), [1, 4, 6])
        np.testing.assert_allclose(np.asarray(c.values),
                                   [[2.], [4.], [0.]])
        np.testing.assert_allclose(np.asarray(c.to_dense_array()),
                                   np.asarray(sr.to_dense_array()))

    def test_coalesce_is_pure_device(self):
        # must be jittable (static shapes, no host round-trip): the
        # optimizer calls it every step
        import jax

        def f(rows, vals):
            return SelectedRows(rows, vals, (6, 1)).coalesce().values

        out = jax.jit(f)(jnp.asarray([4, 1, 4], jnp.int32),
                         jnp.asarray([[1.], [2.], [3.]]))
        np.testing.assert_allclose(np.asarray(out), [[2.], [4.], [0.]])

    def test_double_backward_create_graph_densifies(self):
        # create_graph routes the sparse node through its dense
        # pure_spec: higher-order grads work, grads come back dense
        e = _emb()
        out = (e(_ids(1, 2)) ** 2).sum()
        (g,) = paddle.grad([out], [e.weight], create_graph=True)
        assert not g.is_selected_rows()
        g2 = (g ** 2).sum()
        (gg,) = paddle.grad([g2], [e.weight])
        assert list(gg.shape) == [V, D]
        assert np.isfinite(np.asarray(gg.numpy())).all()

    def test_add_concat_and_shape_mismatch(self):
        a = SelectedRows(jnp.asarray([0], jnp.int32),
                         jnp.ones((1, 2)), (4, 2))
        b = SelectedRows(jnp.asarray([3], jnp.int32),
                         jnp.ones((1, 2)), (4, 2))
        assert (a + b).rows.shape[0] == 2
        c = SelectedRows(jnp.asarray([0], jnp.int32),
                         jnp.ones((1, 2)), (5, 2))
        with pytest.raises(ValueError, match="mismatch"):
            a + c

    def test_grad_facade_densify_degrades_permanently(self):
        sr = SelectedRows(jnp.asarray([1], jnp.int32),
                          jnp.ones((1, 3)), (4, 3))
        g = SelectedRowsGrad(sr)
        assert g.is_selected_rows()
        arr = np.asarray(g.numpy())               # dense-style access
        np.testing.assert_allclose(arr[1], np.ones(3))
        assert not g.is_selected_rows()
        with pytest.raises(RuntimeError, match="densified"):
            g.sr
