"""Flagship model family tests (SURVEY.md §7 phase 8 start): functional
Llama core vs eager Layer model, sharded hybrid-parallel train step on the
8-device CPU mesh (the reference's N-local-process strategy, SURVEY.md §4).
"""
import dataclasses
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt
from paddle_tpu.models import llama as L


def tiny(**kw):
    return L.llama_tiny(**kw)


class TestKVCacheDecode:
    """Static ring-buffer decode path vs the full forward (reference:
    nn/layer/transformer.py gen_cache incremental decoding)."""

    def _setup(self, seed=0, B=2, S=7):
        cfg = tiny()
        params = L.init_params(cfg, jax.random.PRNGKey(seed))
        ids = jnp.asarray(np.random.default_rng(seed).integers(
            0, cfg.vocab_size, (B, S)), jnp.int32)
        return cfg, params, ids

    def test_prefill_matches_forward_last_logits(self):
        cfg, params, ids = self._setup()
        cache = L.init_cache(cfg, ids.shape[0], 16)
        cache, logits = L.prefill(params, ids, cfg, cache)
        full = L.forward(params, ids, cfg)
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(full[:, -1, :]),
                                   rtol=2e-4, atol=2e-4)
        assert int(cache["pos"]) == ids.shape[1]

    @pytest.mark.slow  # tier-1 budget (ISSUE 5): heavy; the greedy/beam
    # naive-loop parities below keep KV-cache decode covered in tier-1
    def test_decode_steps_match_full_forward(self):
        cfg, params, ids = self._setup(seed=1)
        B, S = ids.shape
        extra = jnp.asarray(np.random.default_rng(9).integers(
            0, cfg.vocab_size, (B, 3)), jnp.int32)
        cache = L.init_cache(cfg, B, S + 3)
        cache, logits = L.prefill(params, ids, cfg, cache)
        seq = ids
        for t in range(3):
            tok = extra[:, t]
            cache, logits = L.decode_step(params, cache, tok, cfg)
            seq = jnp.concatenate([seq, tok[:, None]], axis=1)
            full = L.forward(params, seq, cfg)[:, -1, :]
            np.testing.assert_allclose(np.asarray(logits),
                                       np.asarray(full),
                                       rtol=2e-4, atol=2e-4)

    def test_greedy_generate_matches_naive_loop(self):
        cfg, params, ids = self._setup(seed=2, B=2, S=5)
        got = L.generate(params, ids, cfg, max_new_tokens=4)
        # naive: re-run the full forward for every new token
        seq = ids
        want = []
        for _ in range(4):
            nxt = jnp.argmax(L.forward(params, seq, cfg)[:, -1, :],
                             axis=-1).astype(jnp.int32)
            want.append(nxt)
            seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.stack(want, axis=1))

    def test_generate_jits_once_and_reruns(self):
        cfg, params, ids = self._setup(seed=3)
        gen = jax.jit(lambda p, i: L.generate(p, i, cfg,
                                              max_new_tokens=3))
        a = gen(params, ids)
        b = gen(params, ids + 0)
        assert a.shape == (2, 3)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_cache_overflow_typed_error(self):
        from paddle_tpu.core import enforce as E
        cfg, params, ids = self._setup()
        with pytest.raises(E.EnforceError):
            L.generate(params, ids, cfg, max_new_tokens=4, max_len=8)
        cache = L.init_cache(cfg, 2, 4)
        with pytest.raises(E.EnforceError):
            L.prefill(params, ids, cfg, cache)

    @pytest.mark.skipif(
        jax.__version__.startswith("0.4.")
        and jax.default_backend() == "cpu",
        reason="environment limit: jax 0.4.x CPU GSPMD partitioning "
               "reassociates the attention/matmul reductions enough to "
               "flip greedy argmax ties vs the single-device program; "
               "exact-token equality holds on jax >= 0.5 and on TPU")
    def test_tp_sharded_generate_matches_single_device(self):
        """Distributed serving: the same jit-once generate program runs
        with GSPMD tensor-parallel-sharded weights (param_specs over a
        (dp,fsdp,tp) mesh) and must produce identical greedy tokens."""
        cfg, params, ids = self._setup(seed=5)
        want = np.asarray(L.generate(params, ids, cfg, max_new_tokens=4))
        devs = np.array(jax.devices()[:8]).reshape(1, 2, 4)
        mesh = Mesh(devs, ("dp", "fsdp", "tp"))
        specs = L.param_specs(cfg)
        pshard = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                              is_leaf=lambda s: isinstance(s, P))
        sharded = jax.device_put(params, pshard)
        with mesh:
            got = np.asarray(jax.jit(
                lambda p, i: L.generate(p, i, cfg, max_new_tokens=4))(
                    sharded, ids))
        np.testing.assert_array_equal(got, want)

    def test_temperature_sampling_draws_valid_tokens(self):
        cfg, params, ids = self._setup(seed=4)
        toks = L.generate(params, ids, cfg, max_new_tokens=5,
                          temperature=1.0, key=jax.random.PRNGKey(7))
        t = np.asarray(toks)
        assert t.shape == (2, 5)
        assert (t >= 0).all() and (t < cfg.vocab_size).all()

    def test_gqa_decode_matches_full_forward(self):
        # grouped-query attention through the cache: kv heads < q heads
        cfg = tiny(num_attention_heads=4, num_key_value_heads=2)
        params = L.init_params(cfg, jax.random.PRNGKey(6))
        ids = jnp.asarray(np.random.default_rng(6).integers(
            0, cfg.vocab_size, (2, 6)), jnp.int32)
        cache = L.init_cache(cfg, 2, 9)
        cache, logits = L.prefill(params, ids, cfg, cache)
        tok = jnp.argmax(logits, -1).astype(jnp.int32)
        seq = jnp.concatenate([ids, tok[:, None]], axis=1)
        cache, logits = L.decode_step(params, cache, tok, cfg)
        full = L.forward(params, seq, cfg)[:, -1, :]
        np.testing.assert_allclose(np.asarray(logits), np.asarray(full),
                                   rtol=2e-4, atol=2e-4)

    def test_top_k_restricts_support(self):
        # with top_k=1, temperature sampling must equal greedy
        cfg, params, ids = self._setup(seed=7)
        greedy = L.generate(params, ids, cfg, max_new_tokens=4)
        topk1 = L.generate(params, ids, cfg, max_new_tokens=4,
                           temperature=1.3, top_k=1,
                           key=jax.random.PRNGKey(11))
        np.testing.assert_array_equal(np.asarray(greedy),
                                      np.asarray(topk1))

    def test_beam_search_k1_equals_greedy(self):
        cfg, params, ids = self._setup(seed=10)
        greedy = np.asarray(L.generate(params, ids, cfg,
                                       max_new_tokens=4))
        toks, scores = L.beam_search(params, ids, cfg, max_new_tokens=4,
                                     num_beams=1)
        np.testing.assert_array_equal(np.asarray(toks), greedy)
        assert np.isfinite(np.asarray(scores)).all()

    @pytest.mark.slow  # tier-1 budget (ISSUE 19 rebalance): beam-vs-naive sweep; greedy cache parity +
    # the beam invariant units keep the seam fast
    def test_beam_search_matches_naive_reference(self):
        """Differential test: the jitted static beam search must agree
        with a naive python beam search that re-runs the full forward
        for every candidate prefix."""
        cfg, params, ids = self._setup(seed=11, B=1, S=4)
        K, T = 2, 3
        toks, scores = L.beam_search(params, ids, cfg, max_new_tokens=T,
                                     num_beams=K)

        def logp_next(prefix):
            lg = L.forward(params, jnp.asarray(prefix[None]), cfg)
            return np.asarray(
                jax.nn.log_softmax(lg[0, -1].astype(jnp.float32)))

        prompt = np.asarray(ids[0])
        beams = [(0.0, prompt, [])]
        for _ in range(T):
            cands = []
            for sc, pref, out in beams:
                lp = logp_next(pref)
                top = np.argsort(lp)[::-1][:K]
                for t in top:
                    cands.append((sc + lp[t],
                                  np.concatenate([pref, [t]]),
                                  out + [int(t)]))
            cands.sort(key=lambda x: -x[0])
            beams = cands[:K]
        want_toks = beams[0][2]
        want_score = beams[0][0]
        np.testing.assert_array_equal(np.asarray(toks)[0], want_toks)
        np.testing.assert_allclose(float(scores[0]), want_score,
                                   rtol=1e-4)

    def test_beam_search_eos_freezes_beam(self):
        cfg, params, ids = self._setup(seed=12)
        base, _ = L.beam_search(params, ids, cfg, max_new_tokens=5,
                                num_beams=2)
        base = np.asarray(base)
        eos = int(base[0, 1])
        toks, _ = L.beam_search(params, ids, cfg, max_new_tokens=5,
                                num_beams=2, eos_token_id=eos,
                                pad_token_id=-1)
        toks = np.asarray(toks)
        for b in range(toks.shape[0]):
            row = toks[b].tolist()
            if eos in row:
                i = row.index(eos)
                assert all(t == -1 for t in row[i + 1:]), row

    def test_eos_stops_and_pads(self):
        cfg, params, ids = self._setup(seed=9)
        # find what greedy emits, then declare its SECOND token the EOS:
        # position 0..1 must be emitted as-is, everything after padded
        base = np.asarray(L.generate(params, ids, cfg, max_new_tokens=5))
        eos = int(base[0, 1])
        got = np.asarray(L.generate(params, ids, cfg, max_new_tokens=5,
                                    eos_token_id=eos, pad_token_id=-1))
        assert got[0, 1] == eos            # the EOS itself is emitted
        assert (got[0, 2:] == -1).all()    # then padding
        # a row that never hits EOS is untouched
        for b in range(base.shape[0]):
            if eos not in base[b]:
                np.testing.assert_array_equal(got[b], base[b])

    def test_top_p_tiny_equals_greedy_and_validates(self):
        cfg, params, ids = self._setup(seed=8)
        # a tiny nucleus keeps only the argmax token
        nucleus = L.generate(params, ids, cfg, max_new_tokens=4,
                             temperature=1.0, top_p=1e-6,
                             key=jax.random.PRNGKey(13))
        greedy = L.generate(params, ids, cfg, max_new_tokens=4)
        np.testing.assert_array_equal(np.asarray(nucleus),
                                      np.asarray(greedy))
        from paddle_tpu.core import enforce as E
        with pytest.raises(E.EnforceError):
            L.generate(params, ids, cfg, max_new_tokens=2, top_p=0.0)


class TestWeightOnlyDecode:
    """Serving with weight-only int8 weights (reference:
    nn.quant.weight_quantize in the inference pipelines): the quantized
    pytree drops into every functional entry point."""

    def _quant_and_deq(self, seed=0):
        cfg = tiny()
        params = L.init_params(cfg, jax.random.PRNGKey(seed))
        qp = L.quantize_weights(params)
        # fp tree with the DEQUANTIZED weights: running it through the
        # plain path must match the quantized path bit-for-bit (proves
        # the _mm routing computes exactly dequant-then-matmul)
        deq = {"embed": params["embed"], "ln_f": params["ln_f"],
               "layers": {}}
        for k, w in qp["layers"].items():
            if isinstance(w, dict):
                deq["layers"][k] = (w["q"].astype(jnp.float32)
                                    * w["s"][:, None, :])
            else:
                deq["layers"][k] = w
        deq["lm_head"] = (qp["lm_head"]["q"].astype(jnp.float32)
                          * qp["lm_head"]["s"][:, None])
        return cfg, params, qp, deq

    def test_quantized_forward_equals_dequantized(self):
        cfg, _, qp, deq = self._quant_and_deq()
        ids = jnp.asarray(np.random.default_rng(0).integers(
            0, cfg.vocab_size, (2, 9)), jnp.int32)
        a = L.forward(qp, ids, cfg)
        b = L.forward(deq, ids, cfg)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)

    def test_quantized_logits_close_to_fp(self):
        cfg, params, qp, _ = self._quant_and_deq(seed=1)
        ids = jnp.asarray(np.random.default_rng(1).integers(
            0, cfg.vocab_size, (2, 9)), jnp.int32)
        fp = np.asarray(L.forward(params, ids, cfg))
        q = np.asarray(L.forward(qp, ids, cfg))
        # per-channel int8 keeps logits close on a tiny random model
        denom = np.maximum(np.abs(fp).max(), 1e-6)
        assert np.abs(q - fp).max() / denom < 0.05

    def test_quantized_generate_and_beam(self):
        cfg, _, qp, deq = self._quant_and_deq(seed=2)
        ids = jnp.asarray(np.random.default_rng(2).integers(
            0, cfg.vocab_size, (2, 6)), jnp.int32)
        gq = np.asarray(L.generate(qp, ids, cfg, max_new_tokens=4))
        gd = np.asarray(L.generate(deq, ids, cfg, max_new_tokens=4))
        np.testing.assert_array_equal(gq, gd)
        bq, _ = L.beam_search(qp, ids, cfg, max_new_tokens=3, num_beams=2)
        bd, _ = L.beam_search(deq, ids, cfg, max_new_tokens=3,
                              num_beams=2)
        np.testing.assert_array_equal(np.asarray(bq), np.asarray(bd))


class TestFunctionalLlama:
    def test_forward_shapes_gqa(self):
        cfg = tiny()
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        ids = jnp.asarray(np.random.default_rng(0).integers(
            0, cfg.vocab_size, (2, 16)))
        logits = L.forward(params, ids, cfg)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert logits.dtype == jnp.float32

    def test_param_count_matches_init(self):
        cfg = tiny()
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        assert L.count_params(cfg) == sum(
            x.size for x in jax.tree.leaves(params))

    def test_causality(self):
        """Changing a future token must not change past logits."""
        cfg = tiny(num_hidden_layers=1)
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (1, 12))
        ids2 = ids.copy()
        ids2[0, -1] = (ids2[0, -1] + 1) % cfg.vocab_size
        l1 = L.forward(params, jnp.asarray(ids), cfg)
        l2 = L.forward(params, jnp.asarray(ids2), cfg)
        np.testing.assert_allclose(l1[0, :-1], l2[0, :-1], rtol=2e-5,
                                   atol=2e-5)

    def test_train_step_converges(self):
        cfg = tiny()
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        ost = L.adamw_init(params)
        step = L.make_train_step(cfg, lr=1e-2)
        ids = jnp.asarray(np.random.default_rng(0).integers(
            0, cfg.vocab_size, (4, 17)))
        losses = []
        for _ in range(10):
            params, ost, loss = step(params, ost, ids)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.75, losses

    @pytest.mark.slow  # tier-1 budget (ISSUE 5): heavy; run in slow lane
    def test_remat_matches_no_remat(self):
        cfg = tiny(remat=False)
        cfg_r = tiny(remat=True)
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        ids = jnp.asarray(np.random.default_rng(0).integers(
            0, cfg.vocab_size, (2, 9)))
        g1 = jax.grad(lambda p: L.loss_fn(p, ids, cfg))(params)
        g2 = jax.grad(lambda p: L.loss_fn(p, ids, cfg_r))(params)
        for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


class TestShardedLlama:
    def _mesh(self):
        return Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2),
                    ("dp", "fsdp", "tp"))

    @pytest.mark.skipif(
        jax.__version__.startswith("0.4.")
        and jax.default_backend() == "cpu",
        reason="environment limit: jax 0.4.x CPU GSPMD float "
               "reassociation drifts the post-adam weights past the "
               "2e-4 tolerance; passes on jax >= 0.5 and on TPU")
    def test_sharded_step_matches_single_device(self):
        """Hybrid dp/fsdp/tp(+sp) sharded loss == single-device loss."""
        # fused_ce=False: the single-device ref must compute the SAME
        # einsum loss the GSPMD path uses, else adam amplifies the
        # blockwise-vs-materialised rounding delta past the tolerance
        # (fused-vs-einsum equivalence is tested in test_fused_ce.py)
        cfg = dataclasses.replace(tiny(), fused_ce=False)
        mesh = self._mesh()
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        ids = jnp.asarray(np.random.default_rng(0).integers(
            0, cfg.vocab_size, (4, 17)))

        ref_step = L.make_train_step(cfg, lr=1e-2, donate=False)
        ref_params, ref_ost, ref_loss = ref_step(
            params, L.adamw_init(params), ids)

        sp_params = L.shard_params(params, cfg, mesh)
        s_step = L.make_train_step(cfg, mesh, lr=1e-2, sp=True,
                                   donate=False)
        s_ids = jax.device_put(
            ids, NamedSharding(mesh, P(("dp", "fsdp"), None)))
        s_params, s_ost, s_loss = s_step(
            sp_params, L.adamw_init(sp_params), s_ids)

        np.testing.assert_allclose(float(ref_loss), float(s_loss),
                                   rtol=1e-5)
        # updated weights match too (GSPMD == single-device math)
        for a, b in zip(jax.tree.leaves(ref_params),
                        jax.tree.leaves(s_params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)

    def test_param_placement(self):
        cfg = tiny()
        mesh = self._mesh()
        params = L.shard_params(L.init_params(cfg, jax.random.PRNGKey(0)),
                                cfg, mesh)
        assert params["layers"]["wq"].sharding.spec == P(None, "fsdp", "tp")
        assert params["embed"].sharding.spec == P("tp", "fsdp")


class TestEagerLlama:
    def test_eager_matches_functional_forward(self):
        """The Layer model and functional core compute the same function
        when weights are copied across."""
        cfg = tiny(num_hidden_layers=2)
        m = L.LlamaForCausalLM(cfg)
        params = L.init_params(cfg, jax.random.PRNGKey(3))
        # copy functional params into the Layer model
        m.embed_tokens.weight.set_value(np.asarray(params["embed"]))
        for i, layer in enumerate(m.layers):
            lp = jax.tree.map(lambda x: np.asarray(x[i]), params["layers"])
            layer.input_layernorm.weight.set_value(lp["ln1"])
            layer.q_proj.weight.set_value(lp["wq"])
            layer.k_proj.weight.set_value(lp["wk"])
            layer.v_proj.weight.set_value(lp["wv"])
            layer.o_proj.weight.set_value(lp["wo"])
            layer.post_attention_layernorm.weight.set_value(lp["ln2"])
            layer.gate_proj.weight.set_value(lp["gate"])
            layer.up_proj.weight.set_value(lp["up"])
            layer.down_proj.weight.set_value(lp["down"])
        m.norm.weight.set_value(np.asarray(params["ln_f"]))
        m.lm_head.weight.set_value(np.asarray(params["lm_head"]).T)

        ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 11))
        ref = L.forward(params, jnp.asarray(ids), cfg)
        out = m(paddle.to_tensor(ids))
        np.testing.assert_allclose(out.numpy(), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    @pytest.mark.slow  # tier-1 budget (ISSUE 3): heavy; run in the slow lane
    def test_functional_params_roundtrip_and_generate(self):
        """Layer -> functional export computes the identical function,
        and the eager .generate delegates onto the static-cache path."""
        cfg = tiny(num_hidden_layers=2)
        m = L.LlamaForCausalLM(cfg)
        params = m.functional_params()
        ids = np.random.default_rng(5).integers(0, cfg.vocab_size, (2, 9))
        ref = L.forward(params, jnp.asarray(ids), cfg)
        out = m(paddle.to_tensor(ids))
        np.testing.assert_allclose(out.numpy(), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)
        toks = m.generate(paddle.to_tensor(ids), max_new_tokens=3)
        want = L.generate(params, jnp.asarray(ids, jnp.int32), cfg,
                          max_new_tokens=3)
        np.testing.assert_array_equal(toks.numpy(), np.asarray(want))
        # num_beams routes to beam search through the same entry point
        bt = m.generate(paddle.to_tensor(ids), max_new_tokens=3,
                        num_beams=2)
        bw, _ = L.beam_search(params, jnp.asarray(ids, jnp.int32), cfg,
                              max_new_tokens=3, num_beams=2)
        np.testing.assert_array_equal(bt.numpy(), np.asarray(bw))

    @pytest.mark.slow  # tier-1 budget (ISSUE 20 rebalance): convergence run;
    # eager_matches_functional_forward keeps the Layer-vs-functional seam fast
    def test_eager_training_memorizes(self):
        cfg = tiny(num_hidden_layers=1)
        m = L.LlamaForCausalLM(cfg)
        o = opt.AdamW(learning_rate=3e-3, parameters=m.parameters())
        data = np.random.default_rng(0).integers(
            0, cfg.vocab_size, (4, 17)).astype(np.int64)
        inp = paddle.to_tensor(data[:, :-1])
        tgt = paddle.to_tensor(data[:, 1:])
        first = last = None
        for _ in range(30):
            logits = m(inp)
            loss = F.cross_entropy(logits.reshape([-1, cfg.vocab_size]),
                                   tgt.reshape([-1]))
            loss.backward()
            o.step()
            o.clear_grad()
            if first is None:
                first = float(loss)
            last = float(loss)
        assert last < first * 0.7, (first, last)


class TestGraftEntry:
    def test_entry_jits(self):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "__graft_entry__", "__graft_entry__.py")
        g = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(g)
        fn, args = g.entry()
        out = jax.jit(fn)(*args)
        assert out.shape[-1] == 256

    @pytest.mark.slow  # tier-1 budget (ISSUE 3): heavy; run in the slow lane
    def test_dryrun_multichip(self):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "__graft_entry__", "__graft_entry__.py")
        g = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(g)
        g.dryrun_multichip(8)
