"""Segmented (mixed) capture on graph breaks — VERDICT-r4 item 10.

A to_static(full_graph=False) function with one data-dependent Python
branch must run as TWO compiled segments around the eager island (not
whole-call eager), produce eager-identical results on both branch
outcomes, and replay cached compiled paths (guard tree) without
re-recording."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.jit as pjit
from paddle_tpu.jit import segment


def _fn(x):
    h = paddle.tanh(x + x) * 2.0            # segment 1 (sign-preserving)
    if h.sum() > 0:                         # eager island: bool() on a
        out = h + 100.0                     # traced comparison -> guard
    else:                                   # value True/False, so every
        out = h - 100.0                     # same-branch input replays
    return out * 1.5                        # the cached compiled path


def _mk(val):
    return paddle.to_tensor(np.full((4, 4), val, "float32"))


class TestSegmentedCapture:
    def setup_method(self):
        segment.reset_stats()

    def test_two_compiled_segments_and_parity(self):
        f = pjit.to_static(_fn, full_graph=False)
        xp = _mk(0.5)
        with paddle.no_grad():
            with pytest.warns(UserWarning, match="compiled segments"):
                got = f(xp)
        want = _fn(_mk(0.5))
        np.testing.assert_allclose(np.asarray(got.numpy()),
                                   np.asarray(want.numpy()), rtol=1e-6)
        s = segment.STATS
        assert s["recordings"] == 1
        assert s["segments_compiled"] == 2, s   # break + final
        # the recording pass replays uncompiled; the compiled slices
        # serve cached calls:
        with paddle.no_grad():
            f(_mk(0.4))
        assert segment.STATS["segments_executed"] == 2, segment.STATS

    def test_cached_path_replays_without_rerecording(self):
        f = pjit.to_static(_fn, full_graph=False)
        with paddle.no_grad():
            with pytest.warns(UserWarning):
                f(_mk(0.5))
            before = dict(segment.STATS)
            out = f(_mk(0.25))   # same branch outcome -> cached path
        s = segment.STATS
        assert s["recordings"] == before["recordings"]          # no re-record
        assert s["segments_compiled"] == before["segments_compiled"]
        assert s["cached_path_hits"] == before["cached_path_hits"] + 1
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   np.asarray(_fn(_mk(0.25)).numpy()),
                                   rtol=1e-6)

    def test_other_branch_records_second_path_then_caches(self):
        f = pjit.to_static(_fn, full_graph=False)
        with paddle.no_grad():
            with pytest.warns(UserWarning):
                f(_mk(0.5))                  # path A
            out_b = f(_mk(-0.5))             # path B: new recording
            s1 = dict(segment.STATS)
            assert s1["recordings"] == 2
            out_b2 = f(_mk(-0.25))           # path B again: cached
        s2 = segment.STATS
        assert s2["recordings"] == 2
        assert s2["cached_path_hits"] >= 1
        np.testing.assert_allclose(np.asarray(out_b.numpy()),
                                   np.asarray(_fn(_mk(-0.5)).numpy()),
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(out_b2.numpy()),
                                   np.asarray(_fn(_mk(-0.25)).numpy()),
                                   rtol=1e-6)

    def test_grad_enabled_segments_and_tapes(self):
        # VERDICT-r5 item 4: training calls run as compiled segments
        # too — the slices record as GradNodes, so backward() works
        f = pjit.to_static(_fn, full_graph=False)
        x = paddle.to_tensor(np.full((4, 4), 0.5, "float32"),
                             stop_gradient=False)
        with pytest.warns(UserWarning, match="compiled segments"):
            out = f(x)
        out.sum().backward()
        assert x.grad is not None
        assert segment.STATS["recordings"] == 1
        xe = paddle.to_tensor(np.full((4, 4), 0.5, "float32"),
                              stop_gradient=False)
        _fn(xe).sum().backward()
        np.testing.assert_allclose(np.asarray(x.grad.numpy()),
                                   np.asarray(xe.grad.numpy()), rtol=1e-6)

    def test_layer_with_params_segmented(self):
        from paddle_tpu import nn

        lin = nn.Linear(4, 4)

        def model(x):
            h = lin(x)
            if float(h.mean()) > 1000.0:
                return h * 0.0
            return h + 1.0

        f = pjit.to_static(model, full_graph=False)
        x = _mk(0.3)
        with paddle.no_grad():
            with pytest.warns(UserWarning):
                got = f(x)
            want = model(x)
            np.testing.assert_allclose(np.asarray(got.numpy()),
                                       np.asarray(want.numpy()), rtol=1e-5)
            # parameters ride as live jit inputs: updating the weight
            # must be visible to the cached compiled path
            lin.weight.set_value(
                np.asarray(lin.weight.numpy()) * 2.0)
            got2 = f(x)
            want2 = model(x)
            np.testing.assert_allclose(np.asarray(got2.numpy()),
                                       np.asarray(want2.numpy()),
                                       rtol=1e-5)


class TestSegmentedCorrectnessHardening:
    """Review-found silent-corruption scenarios (all fixed)."""

    def setup_method(self):
        segment.reset_stats()

    def test_nested_tensor_args_are_live_inputs(self):
        # a tensor nested in a list must NOT be baked at record time
        def f(xs, y):
            if y.sum() > 0:
                return xs[0] * y + 1.0
            return xs[0] - y

        g = pjit.to_static(f, full_graph=False)
        with paddle.no_grad():
            with pytest.warns(UserWarning):
                g([_mk(1.0)], _mk(2.0))
            got = g([_mk(5.0)], _mk(2.0))     # same sig, cached path
        want = f([_mk(5.0)], _mk(2.0))
        np.testing.assert_allclose(np.asarray(got.numpy()),
                                   np.asarray(want.numpy()), rtol=1e-6)

    def test_param_derived_scalar_stays_live_and_guarded(self):
        from paddle_tpu import nn

        lin = nn.Linear(4, 4)

        def model(x):
            s = float(lin.weight.abs().max())   # param-derived guard
            h = lin(x) / s
            if h.sum() > 0:
                return h + 1.0
            return h - 1.0

        f = pjit.to_static(model, full_graph=False)
        x = _mk(0.3)
        with paddle.no_grad():
            with pytest.warns(UserWarning):
                f(x)
            # update weights: the cached path must RE-DERIVE s (it is a
            # recorded op over a live _ParamRef, guarded by value — the
            # new s misses the float guard, forcing a correct re-record)
            lin.weight.set_value(np.asarray(lin.weight.numpy()) * 3.0)
            got = f(x)
            want = model(x)
        np.testing.assert_allclose(np.asarray(got.numpy()),
                                   np.asarray(want.numpy()), rtol=1e-5)

    def test_divergent_branch_consumes_other_intermediate(self):
        # path A returns a, path B returns b: B's replay needs b from
        # the shared prefix slice, which was pruned for A's needs until
        # the union-pruned prefix replacement
        def f(x):
            a = paddle.tanh(x)
            b = paddle.exp(x)
            if x.sum() > 0:
                return a
            return b

        g = pjit.to_static(f, full_graph=False)
        with paddle.no_grad():
            with pytest.warns(UserWarning):
                g(_mk(1.0))                    # path A recorded
            out_b = g(_mk(-1.0))               # path B recorded
            rec_after_b = segment.STATS["recordings"]
            out_b2 = g(_mk(-2.0))              # path B must now be CACHED
            out_a2 = g(_mk(2.0))               # path A still cached too
        assert segment.STATS["recordings"] == rec_after_b, segment.STATS
        np.testing.assert_allclose(np.asarray(out_b.numpy()),
                                   np.asarray(f(_mk(-1.0)).numpy()),
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(out_b2.numpy()),
                                   np.asarray(f(_mk(-2.0)).numpy()),
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(out_a2.numpy()),
                                   np.asarray(f(_mk(2.0)).numpy()),
                                   rtol=1e-6)


class TestGuardSaturation:
    """ADVICE-r4: a continuous float guard must not degrade to per-call
    re-recording forever — at MAX_PATHS_PER_SIG the signature is pinned
    back to plain eager (strictly faster than symbolize+replay)."""

    def test_continuous_guard_pins_eager(self, monkeypatch):
        monkeypatch.setattr(segment, "MAX_PATHS_PER_SIG", 3)
        calls = {"n": 0}

        def f(x):
            calls["n"] += 1
            s = float(paddle.exp(x).sum())   # differs every call
            return x * s

        g = pjit.to_static(f, full_graph=False)
        segment.reset_stats()
        outs = []
        with paddle.no_grad():
            import warnings
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                for i in range(6):
                    outs.append(g(_mk(0.1 * (i + 1))))
        # 3 recordings fill the tree; the 4th call saturates -> eager
        assert segment.STATS["recordings"] == 3, segment.STATS
        # correctness never wavers, cached or eager
        for i, o in enumerate(outs):
            want = f(_mk(0.1 * (i + 1)))
            np.testing.assert_allclose(np.asarray(o.numpy()),
                                       np.asarray(want.numpy()),
                                       rtol=1e-6)
        # once pinned, calls go straight to fn (no recorder involvement)
        n_before = calls["n"]
        with paddle.no_grad():
            g(_mk(9.9))
        assert calls["n"] == n_before + 1
        assert segment.STATS["recordings"] == 3


class TestTrainingSegments:
    """Training-mode segmented capture (VERDICT-r5 item 4): a train step
    with a data-dependent Python branch runs as compiled segments
    fwd+bwd; loss AND grads match eager on both branch outcomes."""

    def setup_method(self):
        segment.reset_stats()

    def _model(self):
        from paddle_tpu import nn
        paddle.seed(42)
        return nn.Linear(4, 4)

    @staticmethod
    def _step(lin, x):
        h = lin(x)
        if h.sum() > 0:                     # graph break under grad
            out = paddle.tanh(h) * 2.0
        else:
            out = paddle.exp(h) * 0.5
        return (out ** 2).mean()

    def test_loss_and_grad_parity_both_branches(self):
        lin_s = self._model()
        lin_e = self._model()
        # ONE StaticFunction across both branches: the second branch
        # grafts onto the first recording's tree, and parity must hold
        # down the multi-path taped tree
        f = pjit.to_static(
            lambda x: self._step(lin_s, x), full_graph=False)
        for pv in (0.6, -0.6):              # both branch outcomes
            xs = paddle.to_tensor(np.full((3, 4), pv, "float32"),
                                  stop_gradient=False)
            xe = paddle.to_tensor(np.full((3, 4), pv, "float32"),
                                  stop_gradient=False)
            import warnings
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                loss_s = f(xs)
            loss_e = self._step(lin_e, xe)
            np.testing.assert_allclose(float(loss_s.numpy()),
                                       float(loss_e.numpy()), rtol=1e-6)
            loss_s.backward()
            loss_e.backward()
            np.testing.assert_allclose(np.asarray(xs.grad.numpy()),
                                       np.asarray(xe.grad.numpy()),
                                       rtol=1e-5)
            np.testing.assert_allclose(
                np.asarray(lin_s.weight.grad.numpy()),
                np.asarray(lin_e.weight.grad.numpy()), rtol=1e-5)
            lin_s.weight.clear_gradient()
            lin_e.weight.clear_gradient()
            lin_s.bias.clear_gradient()
            lin_e.bias.clear_gradient()

    def test_cached_training_replay_no_rerecord(self):
        lin = self._model()
        f = pjit.to_static(lambda x: self._step(lin, x),
                           full_graph=False)
        import warnings
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            f(paddle.to_tensor(np.full((3, 4), 0.5, "float32"),
                               stop_gradient=False)).backward()
        rec = segment.STATS["recordings"]
        loss = f(paddle.to_tensor(np.full((3, 4), 0.7, "float32"),
                                  stop_gradient=False))
        loss.backward()
        assert segment.STATS["recordings"] == rec       # cached path
        assert segment.STATS["cached_path_hits"] >= 1

    def test_full_training_loop_matches_eager(self):
        from paddle_tpu import optimizer as popt
        lin_s, lin_e = self._model(), self._model()
        f = pjit.to_static(lambda x: self._step(lin_s, x),
                           full_graph=False)
        os_ = popt.SGD(learning_rate=0.1, parameters=lin_s.parameters())
        oe = popt.SGD(learning_rate=0.1, parameters=lin_e.parameters())
        rng = np.random.default_rng(0)
        import warnings
        for i in range(6):
            xv = rng.normal(size=(3, 4)).astype("f4")
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                ls = f(paddle.to_tensor(xv))
            le = self._step(lin_e, paddle.to_tensor(xv))
            ls.backward()
            le.backward()
            os_.step(); os_.clear_grad()
            oe.step(); oe.clear_grad()
        np.testing.assert_allclose(np.asarray(lin_s.weight.numpy()),
                                   np.asarray(lin_e.weight.numpy()),
                                   rtol=1e-5, atol=1e-7)
        # weights moved (training actually happened)
        fresh = self._model()
        assert np.abs(np.asarray(lin_s.weight.numpy())
                      - np.asarray(fresh.weight.numpy())).max() > 1e-4

    def test_eval_then_train_same_signature(self):
        # one signature serves both modes: no-grad replay (arrays) and
        # taped replay (Tensors) share the guard tree and slices
        lin = self._model()
        f = pjit.to_static(lambda x: self._step(lin, x),
                           full_graph=False)
        import warnings
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            with paddle.no_grad():
                v_eval = f(paddle.to_tensor(np.full((3, 4), 0.5, "f4")))
            rec = segment.STATS["recordings"]
            loss = f(paddle.to_tensor(np.full((3, 4), 0.5, "f4")))
        assert segment.STATS["recordings"] == rec       # reused path
        loss.backward()
        assert lin.weight.grad is not None
        np.testing.assert_allclose(float(v_eval.numpy()),
                                   float(loss.numpy()), rtol=1e-6)
