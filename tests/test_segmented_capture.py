"""Segmented (mixed) capture on graph breaks — VERDICT-r4 item 10.

A to_static(full_graph=False) function with one data-dependent Python
branch must run as TWO compiled segments around the eager island (not
whole-call eager), produce eager-identical results on both branch
outcomes, and replay cached compiled paths (guard tree) without
re-recording."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.jit as pjit
from paddle_tpu.jit import segment


def _fn(x):
    h = paddle.tanh(x + x) * 2.0            # segment 1 (sign-preserving)
    if h.sum() > 0:                         # eager island: bool() on a
        out = h + 100.0                     # traced comparison -> guard
    else:                                   # value True/False, so every
        out = h - 100.0                     # same-branch input replays
    return out * 1.5                        # the cached compiled path


def _mk(val):
    return paddle.to_tensor(np.full((4, 4), val, "float32"))


class TestSegmentedCapture:
    def setup_method(self):
        segment.reset_stats()

    def test_two_compiled_segments_and_parity(self):
        f = pjit.to_static(_fn, full_graph=False)
        xp = _mk(0.5)
        with paddle.no_grad():
            with pytest.warns(UserWarning, match="compiled segments"):
                got = f(xp)
        want = _fn(_mk(0.5))
        np.testing.assert_allclose(np.asarray(got.numpy()),
                                   np.asarray(want.numpy()), rtol=1e-6)
        s = segment.STATS
        assert s["recordings"] == 1
        assert s["segments_compiled"] == 2, s   # break + final
        # the recording pass replays uncompiled; the compiled slices
        # serve cached calls:
        with paddle.no_grad():
            f(_mk(0.4))
        assert segment.STATS["segments_executed"] == 2, segment.STATS

    def test_cached_path_replays_without_rerecording(self):
        f = pjit.to_static(_fn, full_graph=False)
        with paddle.no_grad():
            with pytest.warns(UserWarning):
                f(_mk(0.5))
            before = dict(segment.STATS)
            out = f(_mk(0.25))   # same branch outcome -> cached path
        s = segment.STATS
        assert s["recordings"] == before["recordings"]          # no re-record
        assert s["segments_compiled"] == before["segments_compiled"]
        assert s["cached_path_hits"] == before["cached_path_hits"] + 1
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   np.asarray(_fn(_mk(0.25)).numpy()),
                                   rtol=1e-6)

    def test_other_branch_records_second_path_then_caches(self):
        f = pjit.to_static(_fn, full_graph=False)
        with paddle.no_grad():
            with pytest.warns(UserWarning):
                f(_mk(0.5))                  # path A
            out_b = f(_mk(-0.5))             # path B: new recording
            s1 = dict(segment.STATS)
            assert s1["recordings"] == 2
            out_b2 = f(_mk(-0.25))           # path B again: cached
        s2 = segment.STATS
        assert s2["recordings"] == 2
        assert s2["cached_path_hits"] >= 1
        np.testing.assert_allclose(np.asarray(out_b.numpy()),
                                   np.asarray(_fn(_mk(-0.5)).numpy()),
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(out_b2.numpy()),
                                   np.asarray(_fn(_mk(-0.25)).numpy()),
                                   rtol=1e-6)

    def test_grad_enabled_keeps_eager_fallback(self):
        f = pjit.to_static(_fn, full_graph=False)
        x = paddle.to_tensor(np.full((4, 4), 0.5, "float32"),
                             stop_gradient=False)
        with pytest.warns(UserWarning, match="eagerly"):
            out = f(x)
        out.sum().backward()                 # the eager path tapes
        assert x.grad is not None
        assert segment.STATS["recordings"] == 0
        # the signature is NOT pinned eager: a later no-grad call of
        # the same signature gets segmented capture
        with paddle.no_grad():
            with pytest.warns(UserWarning, match="compiled segments"):
                f(paddle.to_tensor(np.full((4, 4), 0.5, "float32")))
        assert segment.STATS["recordings"] == 1

    def test_layer_with_params_segmented(self):
        from paddle_tpu import nn

        lin = nn.Linear(4, 4)

        def model(x):
            h = lin(x)
            if float(h.mean()) > 1000.0:
                return h * 0.0
            return h + 1.0

        f = pjit.to_static(model, full_graph=False)
        x = _mk(0.3)
        with paddle.no_grad():
            with pytest.warns(UserWarning):
                got = f(x)
            want = model(x)
            np.testing.assert_allclose(np.asarray(got.numpy()),
                                       np.asarray(want.numpy()), rtol=1e-5)
            # parameters ride as live jit inputs: updating the weight
            # must be visible to the cached compiled path
            lin.weight.set_value(
                np.asarray(lin.weight.numpy()) * 2.0)
            got2 = f(x)
            want2 = model(x)
            np.testing.assert_allclose(np.asarray(got2.numpy()),
                                       np.asarray(want2.numpy()),
                                       rtol=1e-5)


class TestSegmentedCorrectnessHardening:
    """Review-found silent-corruption scenarios (all fixed)."""

    def setup_method(self):
        segment.reset_stats()

    def test_nested_tensor_args_are_live_inputs(self):
        # a tensor nested in a list must NOT be baked at record time
        def f(xs, y):
            if y.sum() > 0:
                return xs[0] * y + 1.0
            return xs[0] - y

        g = pjit.to_static(f, full_graph=False)
        with paddle.no_grad():
            with pytest.warns(UserWarning):
                g([_mk(1.0)], _mk(2.0))
            got = g([_mk(5.0)], _mk(2.0))     # same sig, cached path
        want = f([_mk(5.0)], _mk(2.0))
        np.testing.assert_allclose(np.asarray(got.numpy()),
                                   np.asarray(want.numpy()), rtol=1e-6)

    def test_param_derived_scalar_stays_live_and_guarded(self):
        from paddle_tpu import nn

        lin = nn.Linear(4, 4)

        def model(x):
            s = float(lin.weight.abs().max())   # param-derived guard
            h = lin(x) / s
            if h.sum() > 0:
                return h + 1.0
            return h - 1.0

        f = pjit.to_static(model, full_graph=False)
        x = _mk(0.3)
        with paddle.no_grad():
            with pytest.warns(UserWarning):
                f(x)
            # update weights: the cached path must RE-DERIVE s (it is a
            # recorded op over a live _ParamRef, guarded by value — the
            # new s misses the float guard, forcing a correct re-record)
            lin.weight.set_value(np.asarray(lin.weight.numpy()) * 3.0)
            got = f(x)
            want = model(x)
        np.testing.assert_allclose(np.asarray(got.numpy()),
                                   np.asarray(want.numpy()), rtol=1e-5)

    def test_divergent_branch_consumes_other_intermediate(self):
        # path A returns a, path B returns b: B's replay needs b from
        # the shared prefix slice, which was pruned for A's needs until
        # the union-pruned prefix replacement
        def f(x):
            a = paddle.tanh(x)
            b = paddle.exp(x)
            if x.sum() > 0:
                return a
            return b

        g = pjit.to_static(f, full_graph=False)
        with paddle.no_grad():
            with pytest.warns(UserWarning):
                g(_mk(1.0))                    # path A recorded
            out_b = g(_mk(-1.0))               # path B recorded
            rec_after_b = segment.STATS["recordings"]
            out_b2 = g(_mk(-2.0))              # path B must now be CACHED
            out_a2 = g(_mk(2.0))               # path A still cached too
        assert segment.STATS["recordings"] == rec_after_b, segment.STATS
        np.testing.assert_allclose(np.asarray(out_b.numpy()),
                                   np.asarray(f(_mk(-1.0)).numpy()),
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(out_b2.numpy()),
                                   np.asarray(f(_mk(-2.0)).numpy()),
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(out_a2.numpy()),
                                   np.asarray(f(_mk(2.0)).numpy()),
                                   rtol=1e-6)


class TestGuardSaturation:
    """ADVICE-r4: a continuous float guard must not degrade to per-call
    re-recording forever — at MAX_PATHS_PER_SIG the signature is pinned
    back to plain eager (strictly faster than symbolize+replay)."""

    def test_continuous_guard_pins_eager(self, monkeypatch):
        monkeypatch.setattr(segment, "MAX_PATHS_PER_SIG", 3)
        calls = {"n": 0}

        def f(x):
            calls["n"] += 1
            s = float(paddle.exp(x).sum())   # differs every call
            return x * s

        g = pjit.to_static(f, full_graph=False)
        segment.reset_stats()
        outs = []
        with paddle.no_grad():
            import warnings
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                for i in range(6):
                    outs.append(g(_mk(0.1 * (i + 1))))
        # 3 recordings fill the tree; the 4th call saturates -> eager
        assert segment.STATS["recordings"] == 3, segment.STATS
        # correctness never wavers, cached or eager
        for i, o in enumerate(outs):
            want = f(_mk(0.1 * (i + 1)))
            np.testing.assert_allclose(np.asarray(o.numpy()),
                                       np.asarray(want.numpy()),
                                       rtol=1e-6)
        # once pinned, calls go straight to fn (no recorder involvement)
        n_before = calls["n"]
        with paddle.no_grad():
            g(_mk(9.9))
        assert calls["n"] == n_before + 1
        assert segment.STATS["recordings"] == 3
