"""Tensor facade tests (reference analogue: test/legacy_test tensor tests)."""
import numpy as np
import pytest

import paddle_tpu as pt


class TestTensorBasics:
    def test_to_tensor(self):
        t = pt.to_tensor([[1.0, 2.0], [3.0, 4.0]])
        assert t.shape == [2, 2]
        assert t.dtype == np.float32
        assert t.stop_gradient is True
        np.testing.assert_allclose(t.numpy(), [[1, 2], [3, 4]])

    def test_to_tensor_dtype(self):
        t = pt.to_tensor([1, 2, 3], dtype="float32")
        assert t.dtype == np.float32
        # int64 canonicalizes to int32 on TPU (x64 disabled), by design.
        t = pt.to_tensor([1.0], dtype="int64")
        assert np.issubdtype(t.dtype, np.integer)

    def test_default_float32(self):
        t = pt.to_tensor(3.14)
        assert t.dtype == np.float32

    def test_item_scalar(self):
        assert pt.to_tensor(42).item() == 42
        assert abs(float(pt.to_tensor(1.5)) - 1.5) < 1e-6

    def test_operators(self):
        x = pt.to_tensor([1.0, 2.0])
        y = pt.to_tensor([3.0, 4.0])
        np.testing.assert_allclose((x + y).numpy(), [4, 6])
        np.testing.assert_allclose((x - y).numpy(), [-2, -2])
        np.testing.assert_allclose((x * y).numpy(), [3, 8])
        np.testing.assert_allclose((y / x).numpy(), [3, 2])
        np.testing.assert_allclose((x ** 2).numpy(), [1, 4])
        np.testing.assert_allclose((-x).numpy(), [-1, -2])
        np.testing.assert_allclose((2.0 + x).numpy(), [3, 4])
        np.testing.assert_allclose((2.0 - x).numpy(), [1, 0])

    def test_comparison(self):
        x = pt.to_tensor([1.0, 5.0])
        y = pt.to_tensor([3.0, 3.0])
        np.testing.assert_array_equal((x < y).numpy(), [True, False])
        np.testing.assert_array_equal((x >= y).numpy(), [False, True])
        np.testing.assert_array_equal((x == x).numpy(), [True, True])

    def test_getitem(self):
        x = pt.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
        np.testing.assert_allclose(x[0].numpy(), [0, 1, 2, 3])
        np.testing.assert_allclose(x[1, 2].numpy(), 6)
        np.testing.assert_allclose(x[:, 1].numpy(), [1, 5, 9])
        np.testing.assert_allclose(x[::2].numpy(), [[0, 1, 2, 3], [8, 9, 10, 11]])

    def test_setitem(self):
        x = pt.to_tensor(np.zeros((3, 3), np.float32))
        x[1] = 5.0
        np.testing.assert_allclose(x.numpy()[1], [5, 5, 5])
        x[0, 0] = 7.0
        assert x.numpy()[0, 0] == 7

    def test_inplace_helpers(self):
        x = pt.to_tensor([1.0, 2.0])
        x.add_(1.0)
        np.testing.assert_allclose(x.numpy(), [2, 3])
        x.scale_(2.0)
        np.testing.assert_allclose(x.numpy(), [4, 6])
        x.zero_()
        np.testing.assert_allclose(x.numpy(), [0, 0])

    def test_set_value(self):
        x = pt.to_tensor([1.0, 2.0])
        x.set_value(np.array([9.0, 9.0], np.float32))
        np.testing.assert_allclose(x.numpy(), [9, 9])
        with pytest.raises(ValueError):
            x.set_value(np.zeros((3,), np.float32))

    def test_astype(self):
        x = pt.to_tensor([1.5, 2.5])
        y = x.astype("int32")
        assert y.dtype == np.int32

    def test_detach_clone(self):
        x = pt.to_tensor([1.0], stop_gradient=False)
        d = x.detach()
        assert d.stop_gradient
        c = x.clone()
        assert not c.stop_gradient  # clone is differentiable

    def test_shape_props(self):
        x = pt.to_tensor(np.zeros((2, 3, 4), np.float32))
        assert x.shape == [2, 3, 4]
        assert x.ndim == 3
        assert x.size == 24
        assert x.numel() == 24
        assert len(x) == 2

    def test_iteration(self):
        x = pt.to_tensor([[1.0], [2.0]])
        rows = list(x)
        assert len(rows) == 2

    def test_parameter(self):
        p = pt.Parameter(np.ones((2, 2), np.float32) * 1)
        assert not p.stop_gradient
        assert p.persistable


class TestDtype:
    def test_set_default(self):
        pt.set_default_dtype("bfloat16")
        try:
            t = pt.zeros([2])
            assert t.dtype == pt.bfloat16
        finally:
            pt.set_default_dtype("float32")

    def test_flags(self):
        pt.set_flags({"FLAGS_check_nan_inf": True})
        assert pt.get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"] is True
        pt.set_flags({"FLAGS_check_nan_inf": False})

    def test_nan_check(self):
        pt.set_flags({"FLAGS_check_nan_inf": True})
        try:
            x = pt.to_tensor([1.0, 0.0])
            with pytest.raises(FloatingPointError):
                pt.log(pt.to_tensor([-1.0]))
        finally:
            pt.set_flags({"FLAGS_check_nan_inf": False})


class TestSaveLoad:
    def test_roundtrip(self, tmp_path):
        obj = {"w": pt.to_tensor([[1.0, 2.0]]), "step": 3,
               "nested": [pt.to_tensor([5])]}
        p = tmp_path / "ckpt.pdparams"
        pt.save(obj, p)
        loaded = pt.load(p)
        np.testing.assert_allclose(loaded["w"].numpy(), [[1, 2]])
        assert loaded["step"] == 3
        np.testing.assert_allclose(loaded["nested"][0].numpy(), [5])


class TestEnforce:
    """core.enforce — typed error discipline (reference:
    paddle/common/{errors.h,enforce.h})."""

    def test_codes_and_builtin_bases(self):
        from paddle_tpu.core import enforce as E

        assert E.InvalidArgumentError.code == 1
        assert issubclass(E.InvalidArgumentError, ValueError)
        assert issubclass(E.NotFoundError, KeyError)
        assert issubclass(E.UnimplementedError, NotImplementedError)
        assert issubclass(E.ExecutionTimeoutError, TimeoutError)
        assert E.ExternalError.code == 12

    def test_message_shape_and_hint(self):
        from paddle_tpu.core import enforce as E

        with pytest.raises(E.InvalidArgumentError) as ei:
            E.enforce_eq(3, 4, "axis mismatch", hint="transpose first")
        msg = str(ei.value)
        assert msg.startswith("InvalidArgument: axis mismatch")
        assert "expected 3 == 4" in msg and "[Hint: transpose first]" in msg
        # typed error still caught as the builtin
        with pytest.raises(ValueError):
            E.enforce_gt(1, 2)

    def test_shape_enforce_wildcards(self):
        import numpy as np

        from paddle_tpu.core import enforce as E

        E.enforce_shape(np.zeros((2, 5)), (-1, 5))
        with pytest.raises(E.InvalidArgumentError, match="expected"):
            E.enforce_shape(np.zeros((2, 5)), (2, 4), name="weight")

    def test_enforce_not_none(self):
        from paddle_tpu.core import enforce as E

        assert E.enforce_not_none(3, "x") == 3
        with pytest.raises(E.NotFoundError):
            E.enforce_not_none(None, "param")
