"""Packaging (VERDICT-r4 item 8): the wheel builds, installs into a
fresh venv, imports, runs autograd, and ships the launch console script.
Reference capability: setup.py:890 build_steps (wheel pipeline)."""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
class TestWheel:
    def test_wheel_builds_installs_and_imports(self, tmp_path):
        wheel_dir = tmp_path / "wheels"
        r = subprocess.run(
            [sys.executable, "-m", "pip", "wheel", REPO, "--no-deps",
             "--no-build-isolation", "-w", str(wheel_dir)],
            capture_output=True, text=True, timeout=600)
        assert r.returncode == 0, r.stderr[-3000:]
        wheels = list(wheel_dir.glob("paddle_tpu-*.whl"))
        assert len(wheels) == 1, wheels

        venv = tmp_path / "venv"
        subprocess.run([sys.executable, "-m", "venv", str(venv)],
                       check=True, timeout=300)
        vpy = venv / "bin" / "python"
        # PYTHONPATH="" + --force-reinstall: with the repo on the
        # inherited PYTHONPATH (plus its egg-info), pip would see
        # "paddle-tpu already installed" and silently skip the wheel
        r = subprocess.run(
            [str(vpy), "-m", "pip", "install", "--no-deps", "--no-index",
             "--force-reinstall", str(wheels[0])],
            capture_output=True, text=True, timeout=300,
            env=dict(os.environ, PYTHONPATH=""))
        assert r.returncode == 0, r.stderr[-3000:]

        # deps (jax, numpy) are baked into the outer environment, not on
        # an index — surface them to the venv via a .pth, keeping
        # paddle_tpu itself resolved from the installed wheel
        import jax
        site = subprocess.run(
            [str(vpy), "-c",
             "import site; print(site.getsitepackages()[0])"],
            capture_output=True, text=True, timeout=60)
        baked = os.path.dirname(os.path.dirname(jax.__file__))
        with open(os.path.join(site.stdout.strip(), "_deps.pth"), "w") as f:
            f.write(baked + "\n")

        code = (
            "import os, paddle_tpu as paddle, numpy as np\n"
            "assert 'venv' in paddle.__file__, paddle.__file__\n"
            "x = paddle.to_tensor(np.ones((4, 4), 'float32'),"
            " stop_gradient=False)\n"
            "(x @ x).sum().backward()\n"
            "assert x.grad is not None\n"
            "print('WHEEL_OK', paddle.version.full_version)\n")
        r = subprocess.run(
            [str(vpy), "-c", code], capture_output=True, text=True,
            timeout=300, cwd=str(tmp_path),
            env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=""))
        assert r.returncode == 0, r.stderr[-3000:]
        assert "WHEEL_OK 0.1.0" in r.stdout

        # console entry point
        launch = venv / "bin" / "paddle-tpu-launch"
        assert launch.exists()
        r = subprocess.run(
            [str(launch), "--help"], capture_output=True, text=True,
            timeout=120,
            env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=""))
        assert r.returncode == 0 and "nproc_per_node" in r.stdout
