"""Full-stack hybrid integration: fleet.init + AMP + GradScaler +
shard_optimizer + tensor parallelism + a pipeline schedule, on the
8-virtual-device CPU mesh, with loss parity against a plain single-device
fp32 run (VERDICT r2 ask 9; reference pattern:
test/collective/fleet/hybrid_parallel_mp_amp.py and
hybrid_parallel_pp_fp16.py).
"""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.amp as amp
import paddle_tpu.distributed as dist
import paddle_tpu.distributed.fleet as fleet
import paddle_tpu.nn as nn


def _data(seed=0, n=16, din=16, dout=16):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, din)).astype("float32")
    t = rng.normal(size=(n, dout)).astype("float32")
    return x, t


class _RefNet(nn.Layer):
    """Plain single-device twin of the MP net (same init seeds)."""

    def __init__(self, w1, b1, w2, b2):
        super().__init__()
        self.l1 = nn.Linear(16, 32)
        self.l2 = nn.Linear(32, 16)
        self.l1.weight.set_value(pt.to_tensor(w1))
        self.l1.bias.set_value(pt.to_tensor(b1))
        self.l2.weight.set_value(pt.to_tensor(w2))
        self.l2.bias.set_value(pt.to_tensor(b2))

    def forward(self, x):
        return self.l2(pt.nn.functional.gelu(self.l1(x)))


class TestFullStackHybrid:
    @pytest.mark.slow  # tier-1 budget (ISSUE 3): heavy; run in the slow lane
    def test_mp_sharding_amp_scaler_parity(self):
        """fleet.init(dp=2, sharding=2, mp=2) + Column/RowParallel + AMP
        auto_cast + GradScaler + fleet.distributed_optimizer (ZeRO-1 over
        the sharding axis): loss trajectory matches the single-device fp32
        run to bf16 tolerance."""
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                                   "pp_degree": 1, "sharding_degree": 2}
        hcg = fleet.init(is_collective=True, strategy=strategy)
        try:
            assert hcg.get_model_parallel_world_size() == 2
            assert hcg.get_sharding_parallel_world_size() == 2

            pt.seed(5)
            col = fleet.ColumnParallelLinear(16, 32, gather_output=False,
                                             has_bias=True)
            row = fleet.RowParallelLinear(32, 16, input_is_parallel=True,
                                          has_bias=True)

            class MPNet(nn.Layer):
                def __init__(self):
                    super().__init__()
                    self.col, self.row = col, row

                def forward(self, x):
                    return self.row(pt.nn.functional.gelu(self.col(x)))

            model = fleet.distributed_model(MPNet())
            # capture identical initial weights for the reference twin
            w1 = np.asarray(col.weight.numpy())
            b1 = np.asarray(col.bias.numpy())
            w2 = np.asarray(row.weight.numpy())
            b2 = np.asarray(row.bias.numpy())

            opt = pt.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=model.parameters())
            opt = fleet.distributed_optimizer(opt, strategy)
            scaler = amp.GradScaler(init_loss_scaling=1024.0)

            xin, tgt = _data()
            losses = []
            for _ in range(5):
                with amp.auto_cast(dtype="bfloat16"):
                    out = model(pt.to_tensor(xin))
                    loss = ((out.astype("float32")
                             - pt.to_tensor(tgt)) ** 2).mean()
                scaler.scale(loss).backward()
                scaler.step(opt)
                scaler.update()
                opt.clear_grad()
                losses.append(float(loss.numpy()))

            # ZeRO-1 evidence inside the full stack: moments sharded
            inner = opt._inner if hasattr(opt, "_inner") else opt
            accs = [a for m in inner._accumulators.values()
                    for a in m.values() if hasattr(a, "addressable_shards")]
            assert accs
            sharded = [a for a in accs
                       if len({s.data.shape for s in a.addressable_shards
                               }) and list(a.addressable_shards)[0].data.shape
                       != a.shape]
            assert sharded, "no optimizer state actually sharded"

            # single-device fp32 reference
            ref = _RefNet(w1, b1, w2, b2)
            ropt = pt.optimizer.AdamW(learning_rate=1e-2,
                                      parameters=ref.parameters())
            ref_losses = []
            for _ in range(5):
                loss = ((ref(pt.to_tensor(xin))
                         - pt.to_tensor(tgt)) ** 2).mean()
                loss.backward()
                ropt.step()
                ropt.clear_grad()
                ref_losses.append(float(loss.numpy()))

            np.testing.assert_allclose(losses, ref_losses, rtol=0.05,
                                       atol=5e-3)
            assert losses[-1] < losses[0]
        finally:
            dist.set_mesh(None)
            fleet.fleet._hcg = None

    def test_pp_schedule_with_amp_scaler_parity(self):
        """PipelineParallel (1F1B) + GradScaler vs single-stage fp32."""
        from paddle_tpu.distributed.fleet import (LayerDesc, PipelineLayer,
                                                  PipelineParallel)

        def build(num_stages):
            pt.seed(9)
            descs = [LayerDesc(nn.Linear, 16, 16) for _ in range(4)]
            return PipelineLayer(
                descs, num_stages=num_stages,
                loss_fn=lambda out, lab: ((out - lab) ** 2).mean())

        xin, tgt = _data(seed=3, n=8, din=16, dout=16)

        def run(num_stages, use_scaler):
            pipe = build(num_stages)
            pp = PipelineParallel(pipe, num_micro=4, schedule="1F1B")
            opt = pt.optimizer.SGD(learning_rate=0.05,
                                   parameters=pipe.parameters())
            scaler = amp.GradScaler(init_loss_scaling=256.0) \
                if use_scaler else None
            losses = []
            for _ in range(4):
                loss = pp.train_batch(pt.to_tensor(xin), pt.to_tensor(tgt),
                                      optimizer=opt, scaler=scaler)
                losses.append(float(loss.numpy()))
            return losses

        base = run(1, False)
        hybrid = run(4, True)
        np.testing.assert_allclose(hybrid, base, rtol=2e-3, atol=1e-4)
        assert hybrid[-1] < hybrid[0]
