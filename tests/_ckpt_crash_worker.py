"""Worker for the multi-process crash-consistency test (run via the
launch CLI, not collected by pytest).

Both ranks save a committed step-1 checkpoint, then start a step-2 save
during which the coordinator is killed (kill -9 equivalent) by the
fault-injection harness, armed from the FLAGS_fault_injection env var
set by the test (e.g. ``checkpoint.rename:kill:2`` — the coordinator's
second rename hit is step 2's commit). The launcher's fail-fast watcher
then tears down the surviving rank. The parent test asserts that the
step-1 checkpoint is still committed, manifest-clean, and bit-for-bit
restorable while step 2 never became visible.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as pt
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.checkpoint import CheckpointManager


def _state(step: int):
    return {
        "w": pt.Tensor(jax.numpy.asarray(
            np.arange(12, dtype=np.float32).reshape(3, 4) + step)),
        "step": step,
    }


def main():
    root = sys.argv[1]
    mode = sys.argv[2] if len(sys.argv) > 2 else "crash"
    dist.init_parallel_env()
    assert dist.get_world_size() == 2, dist.get_world_size()
    mgr = CheckpointManager(root, keep_last_n=3)
    if mode == "restore":
        # the multi-host restore path: candidate agreement + per-step
        # verification gathers, then every rank loads the same step
        target = {"w": pt.Tensor(jax.numpy.zeros((3, 4), "float32")),
                  "step": 0}
        step = mgr.restore_latest(target)
        got = np.asarray(target["w"]._data)
        want = np.arange(12, dtype=np.float32).reshape(3, 4) + step
        assert np.array_equal(got, want), (step, got)
        assert target["step"] == step
        print(f"RESTORED{step} rank={dist.get_rank()}", flush=True)
        return
    mgr.save(1, _state(1))
    print(f"SAVED1 rank={dist.get_rank()}", flush=True)
    # the armed kill fires inside this save on the coordinator; the
    # other rank blocks in the commit barrier until fail-fast reaps it
    mgr.save(2, _state(2))
    print(f"SAVED2 rank={dist.get_rank()}", flush=True)


if __name__ == "__main__":
    main()
