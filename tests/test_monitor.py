"""monitor subsystem tests: registry types, thread-safety smoke,
Prometheus text exposition, snapshot determinism, hot-path
instrumentation (op dispatch / jit cache / tensor bytes / dataloader /
collectives), and the off-path guard (flag unset -> empty registry, no
import-time side effects).

Reference strategy: the monitor.h stats are exercised in the reference
via test/cpp/fluid/platform/monitor_test.cc (register, add, read back);
here the python registry carries the same contract plus the exposition
formats the reference exports through pybind."""
import gc
import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import monitor
from paddle_tpu.monitor import StatRegistry
from paddle_tpu.monitor.exposition import sanitize_name


@pytest.fixture
def mon():
    """Fresh registry with the flag ON; teardown disables BEFORE reset
    so late Tensor finalizers can't resurrect the byte gauges."""
    monitor.reset()
    pt.set_flags({"FLAGS_enable_monitor": True})
    yield monitor
    pt.set_flags({"FLAGS_enable_monitor": False})
    monitor.reset()


class TestRegistryTypes:
    def test_counter(self):
        r = StatRegistry()
        c = r.counter("c", "doc")
        c.incr()
        c.incr(5)
        c.add(2)
        assert c.value == 8
        c.reset()
        assert c.value == 0

    def test_gauge(self):
        r = StatRegistry()
        g = r.gauge("g")
        g.set(10)
        g.add(5)
        g.sub(3)
        assert g.value == 12

    def test_gauge_peak_pair(self):
        r = StatRegistry()
        live, peak = r.gauge("live"), r.gauge("peak")
        live.add_and_max_into(100, peak)
        live.add_and_max_into(-40, peak)
        live.add_and_max_into(30, peak)
        assert live.value == 90 and peak.value == 100

    def test_histogram_stats(self):
        r = StatRegistry()
        h = r.histogram("h", buckets=(1.0, 10.0, 100.0))
        for v in (0.5, 5.0, 50.0, 500.0):
            h.observe(v)
        s = h.snapshot()
        assert s["count"] == 4 and s["sum"] == 555.5
        assert s["min"] == 0.5 and s["max"] == 500.0
        assert s["avg"] == pytest.approx(138.875)
        cum = h.cumulative_buckets()
        assert cum == [(1.0, 1), (10.0, 2), (100.0, 3),
                       (float("inf"), 4)]

    def test_empty_histogram_snapshot(self):
        h = StatRegistry().histogram("h")
        assert h.snapshot() == {"count": 0, "sum": 0.0, "min": None,
                                "max": None, "avg": None}

    def test_same_name_same_object(self):
        r = StatRegistry()
        assert r.counter("x") is r.counter("x")

    def test_type_conflict_raises(self):
        r = StatRegistry()
        r.counter("x")
        with pytest.raises(TypeError):
            r.gauge("x")

    def test_registry_snapshot_nested_and_empty(self):
        r = StatRegistry()
        assert r.snapshot() == {}
        r.counter("a").incr(3)
        r.gauge("b").set(7)
        r.histogram("c").observe(1.0)
        s = r.snapshot()
        assert s["counters"] == {"a": 3}
        assert s["gauges"] == {"b": 7}
        assert s["histograms"]["c"]["count"] == 1

    def test_reset_empties(self):
        r = StatRegistry()
        r.counter("a").incr()
        r.reset()
        assert len(r) == 0 and r.snapshot() == {}


class TestThreadSafety:
    def test_concurrent_counter_exact(self):
        r = StatRegistry()
        c = r.counter("n")

        def worker():
            for _ in range(2000):
                c.incr()

        ts = [threading.Thread(target=worker) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert c.value == 16000

    def test_concurrent_histogram_exact_count(self):
        r = StatRegistry()
        h = r.histogram("h")

        def worker(i):
            for k in range(500):
                h.observe(float(i * 500 + k))

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert h.count == 3000
        assert h.cumulative_buckets()[-1][1] == 3000

    def test_concurrent_create_same_metric(self):
        r = StatRegistry()
        got = []

        def worker():
            got.append(r.counter("shared"))

        ts = [threading.Thread(target=worker) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert all(g is got[0] for g in got)


class TestExposition:
    def test_sanitize(self):
        assert sanitize_name("op.matmul.calls") == "op_matmul_calls"
        assert sanitize_name("9lives") == "_9lives"

    def test_prometheus_text(self):
        r = StatRegistry()
        r.counter("op.add.calls", "adds").incr(3)
        r.gauge("tensor.bytes.live").set(1024)
        r.histogram("lat.ms", buckets=(1.0, 10.0)).observe(5.0)
        from paddle_tpu.monitor.exposition import expose_text
        text = expose_text(r)
        assert "# HELP op_add_calls adds" in text
        assert "# TYPE op_add_calls counter" in text
        assert "op_add_calls 3" in text
        assert "# TYPE tensor_bytes_live gauge" in text
        assert "tensor_bytes_live 1024" in text
        assert "# TYPE lat_ms histogram" in text
        assert 'lat_ms_bucket{le="1"} 0' in text
        assert 'lat_ms_bucket{le="10"} 1' in text
        assert 'lat_ms_bucket{le="+Inf"} 1' in text
        assert "lat_ms_sum 5" in text
        assert "lat_ms_count 1" in text

    def test_module_expose_text(self, mon):
        monitor.counter("a.b").incr()
        assert "a_b 1" in monitor.expose_text()


class TestSnapshotDeterminism:
    def test_snapshots_identical_and_sorted(self):
        r = StatRegistry()
        for name in ("zeta", "alpha", "mid"):
            r.counter(name).incr()
        s1, s2 = r.snapshot(), r.snapshot()
        assert s1 == s2
        assert json.dumps(s1) == json.dumps(s2)
        assert list(s1["counters"]) == ["alpha", "mid", "zeta"]

    def test_dump_json_shape_and_file(self, mon, tmp_path):
        monitor.counter("x").incr(2)
        path = str(tmp_path / "m.json")
        payload = monitor.dump_json(run_id="r42", path=path)
        assert payload["run_id"] == "r42"
        assert payload["metrics"]["counters"]["x"] == 2
        assert json.load(open(path))["run_id"] == "r42"


class TestGatedHelpers:
    def test_off_path_is_noop(self):
        monitor.reset()
        assert not monitor.enabled()
        monitor.inc("nope")
        monitor.observe("nope.h", 1.0)
        monitor.set_gauge("nope.g", 5)
        monitor.record_op("add", 100)
        monitor.tensor_bytes(1024)
        assert monitor.snapshot() == {}

    def test_on_path_registers(self, mon):
        monitor.inc("yes", 2)
        monitor.observe("yes.h", 1.0)
        monitor.set_gauge("yes.g", 5)
        s = monitor.snapshot()
        assert s["counters"]["yes"] == 2
        assert s["gauges"]["yes.g"] == 5
        assert s["histograms"]["yes.h"]["count"] == 1

    def test_timed_context(self, mon):
        with monitor.timed("block.ms"):
            pass
        assert monitor.snapshot()["histograms"]["block.ms"]["count"] == 1


class TestOpDispatchInstrumentation:
    def test_eager_op_counts(self, mon):
        x = pt.to_tensor(np.ones((4, 4), "float32"))
        y = pt.to_tensor(np.ones((4, 4), "float32"))
        _ = x + y
        s = monitor.snapshot()
        assert s["counters"]["op.add.calls"] >= 1
        assert s["histograms"]["op.dispatch.wall_ns"]["count"] >= 1

    def test_flag_off_no_op_counters(self):
        monitor.reset()
        x = pt.to_tensor(np.ones((2,), "float32"))
        _ = x + x
        assert "counters" not in monitor.snapshot()


class TestTensorBytes:
    def _live(self):
        return monitor.snapshot().get("gauges", {}).get(
            "tensor.bytes.live", 0)

    def test_live_and_peak_track_construction(self, mon):
        before = self._live()
        t = pt.to_tensor(np.zeros((128, 128), "float32"))
        after = self._live()
        assert after - before >= 128 * 128 * 4
        peak = monitor.snapshot()["gauges"]["tensor.bytes.peak"]
        assert peak >= after
        del t
        gc.collect()
        assert self._live() < after

    def test_peak_survives_frees(self, mon):
        t = pt.to_tensor(np.zeros((256, 256), "float32"))
        peak = monitor.snapshot()["gauges"]["tensor.bytes.peak"]
        del t
        gc.collect()
        assert monitor.snapshot()["gauges"]["tensor.bytes.peak"] == peak

    def test_flag_flip_does_not_pin_live(self, mon):
        # a tensor counted while ON must still return its bytes when
        # freed after the flag goes OFF (asymmetric gating)
        t = pt.to_tensor(np.zeros((64, 64), "float32"))
        live_on = self._live()
        pt.set_flags({"FLAGS_enable_monitor": False})
        del t
        gc.collect()
        pt.set_flags({"FLAGS_enable_monitor": True})
        assert self._live() <= live_on - 64 * 64 * 4

    def test_reset_drops_straggler_frees(self, mon):
        # reset() with counted tensors alive: their later frees must
        # not resurrect the gauges at negative values
        t = pt.to_tensor(np.zeros((64, 64), "float32"))
        monitor.reset()
        del t
        gc.collect()
        assert "tensor.bytes.live" not in monitor.snapshot().get(
            "gauges", {})

    def test_straggler_free_cannot_corrupt_next_generation(self, mon):
        # reset() then a NEW allocation recreates the gauges; a
        # pre-reset tensor's free belongs to the old generation and
        # must not subtract from them (it would go negative)
        t1 = pt.to_tensor(np.zeros((256, 256), "float32"))
        monitor.reset()
        t2 = pt.to_tensor(np.zeros((8, 8), "float32"))
        del t1
        gc.collect()
        live = monitor.snapshot()["gauges"]["tensor.bytes.live"]
        assert live >= 8 * 8 * 4, live
        del t2


class TestDataLoaderInstrumentation:
    def test_batches_counted(self, mon):
        from paddle_tpu.io import DataLoader
        from paddle_tpu.io.dataset import TensorDataset
        xs = pt.to_tensor(np.arange(32, dtype="float32").reshape(16, 2))
        dl = DataLoader(TensorDataset([xs]), batch_size=4)
        n = sum(1 for _ in dl)
        s = monitor.snapshot()
        assert s["counters"]["dataloader.batches"] == n == 4
        assert s["histograms"]["dataloader.batch_interval_ms"]["count"] == 4
        assert s["gauges"]["dataloader.last_epoch_batches_per_sec"] > 0


class TestCollectiveInstrumentation:
    def test_compiled_collective_counts_at_trace(self, mon):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.distributed import comm_ops
        out = jax.vmap(lambda x: comm_ops.all_reduce(x, axis="i"),
                       axis_name="i")(jnp.ones((4, 2), jnp.float32))
        assert out.shape == (4, 2)
        s = monitor.snapshot()
        assert s["counters"]["dist.all_reduce.calls"] == 1
        assert s["counters"]["dist.all_reduce.bytes"] == 2 * 4

    def test_eager_collective_counts_per_call(self, mon):
        import paddle_tpu.distributed as dist
        t = pt.to_tensor(np.ones((8,), "float32"))
        dist.all_reduce(t)
        dist.all_reduce(t)
        s = monitor.snapshot()
        assert s["counters"]["dist.eager.all_reduce.calls"] == 2
        assert s["counters"]["dist.eager.all_reduce.bytes"] == 2 * 32


class TestJitCacheInstrumentation:
    def test_hit_miss_compile_latency(self, mon):
        import paddle_tpu.nn as nn
        from paddle_tpu import jit

        lin = nn.Linear(4, 4)

        @jit.to_static
        def f(x):
            return lin(x)

        x = pt.to_tensor(np.ones((2, 4), "float32"))
        with pt.no_grad():
            f(x)
            f(x)
            f(pt.to_tensor(np.ones((3, 4), "float32")))   # new signature
        s = monitor.snapshot()
        assert s["counters"]["jit.cache.miss"] == 2
        assert s["counters"]["jit.cache.hit"] == 1
        assert s["counters"]["jit.recompile"] == 1
        assert s["histograms"]["jit.compile_ms"]["count"] == 2


class TestAutotuneInstrumentation:
    def test_hit_and_miss_counted(self, mon, tmp_path):
        import jax.numpy as jnp

        from paddle_tpu.kernels import autotune as at
        at._FAILED_KEYS.clear()   # other test modules share the process
        cache = at.AutotuneCache(str(tmp_path / "c.json"))
        at.flash_blocks((2, 1024, 4, 128), (2, 1024, 2, 128),
                        jnp.bfloat16, True,
                        measure=lambda bq, bk: 1.0, cache=cache)
        at.flash_blocks((2, 1024, 4, 128), (2, 1024, 2, 128),
                        jnp.bfloat16, True,
                        measure=lambda bq, bk: 1.0, cache=cache)
        s = monitor.snapshot()
        assert s["counters"]["autotune.cache.miss"] == 1
        assert s["counters"]["autotune.cache.hit"] == 1
        assert s["counters"]["autotune.sweeps"] == 1


class TestAcceptance:
    def test_jitted_two_step_train_loop_snapshot(self, mon):
        """The ISSUE acceptance path: FLAGS_enable_monitor=1 + a jitted
        two-step train loop -> snapshot holds op-dispatch counters, jit
        cache hit/miss counts, and peak tensor bytes."""
        import paddle_tpu.nn as nn
        from paddle_tpu import jit
        from paddle_tpu.optimizer import SGD

        class LossNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(8, 8)

            def forward(self, x):
                return (self.lin(x) ** 2).mean()

        net = jit.to_static(LossNet())
        opt = SGD(learning_rate=0.01, parameters=net.parameters())
        x = pt.to_tensor(np.random.randn(4, 8).astype("float32"))
        for _ in range(2):
            loss = net(x)
            loss.backward()
            opt.step()
            opt.clear_grad()
        s = monitor.snapshot()
        op_counters = [k for k in s["counters"] if k.startswith("op.")
                       and k.endswith(".calls")]
        assert op_counters, s["counters"]
        assert s["counters"]["jit.cache.miss"] >= 1
        assert s["counters"]["jit.cache.hit"] >= 1
        assert s["gauges"]["tensor.bytes.peak"] > 0
        # and the whole thing round-trips through both expositions
        assert "jit_cache_miss" in monitor.expose_text()
        assert monitor.dump_json(run_id="t")["metrics"] == s


class TestOffPathGuard:
    def test_no_import_time_side_effects(self):
        """tier-1 guard (ISSUE satellite): with JAX_PLATFORMS=cpu and
        the flag unset, importing the package registers NOTHING —
        snapshot() is {} and the monitor reports disabled."""
        code = (
            "import paddle_tpu as pt\n"
            "from paddle_tpu import monitor\n"
            "assert not monitor.enabled()\n"
            "assert monitor.snapshot() == {}, monitor.snapshot()\n"
            "assert monitor.expose_text() == ''\n"
            "x = pt.to_tensor([1.0, 2.0]); _ = x + x\n"
            "assert monitor.snapshot() == {}, monitor.snapshot()\n"
            "print('GUARD_OK')\n"
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("FLAGS_enable_monitor", None)
        out = subprocess.run(
            [sys.executable, "-c", code],
            env=env, capture_output=True, text=True, timeout=300,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        assert out.returncode == 0, out.stderr[-2000:]
        assert "GUARD_OK" in out.stdout
