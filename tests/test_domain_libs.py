"""Domain library tests: quantization, sparse, geometric, audio, text.

Reference strategy: each package's legacy tests (test_quantization_*,
test_sparse_*, test_graph_send_recv, test_audio_functions,
test_viterbi_decode) — numpy/scipy references on small inputs.
"""
import os
import tarfile
import wave

import numpy as np
import pytest

import paddle_tpu as pt


# ---------------------------------------------------------------------------
# quantization
# ---------------------------------------------------------------------------
class TestQuantization:
    def _model(self):
        pt.seed(4)
        import paddle_tpu.nn as nn

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(8, 16)
                self.fc2 = nn.Linear(16, 4)

            def forward(self, x):
                return self.fc2(pt.nn.functional.relu(self.fc1(x)))

        return Net()

    def test_fake_quant_dequant_math(self):
        from paddle_tpu.quantization import fake_quant_dequant
        x = pt.to_tensor(np.array([-1.0, -0.5, 0.0, 0.37, 1.0], "float32"))
        y = fake_quant_dequant(x, np.float32(1.0), bits=8)
        expect = np.clip(np.round(np.array([-1, -0.5, 0, 0.37, 1.0])
                                  * 127), -127, 127) / 127
        np.testing.assert_allclose(y.numpy(), expect, atol=1e-6)

    def test_fake_quant_straight_through_grad(self):
        from paddle_tpu.quantization import fake_quant_dequant
        x = pt.to_tensor(np.array([0.3, -0.7], "float32"),
                         stop_gradient=False)
        fake_quant_dequant(x, np.float32(1.0)).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [1.0, 1.0])

    def test_qat_quantize_and_train(self):
        from paddle_tpu import quantization as Q
        model = self._model()
        cfg = Q.QuantConfig(
            activation=Q.FakeQuanterWithAbsMaxObserver(quant_bits=8),
            weight=Q.FakeQuanterWithAbsMaxObserver(quant_bits=8))
        qat = Q.QAT(cfg)
        qmodel = qat.quantize(model, inplace=False)
        # wrapped leaves
        from paddle_tpu.quantization.wrapper import ObserveWrapper
        wrapped = [s for _, s in qmodel.named_sublayers()
                   if isinstance(s, ObserveWrapper)]
        assert len(wrapped) == 2

        opt = pt.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=qmodel.parameters())
        x = pt.to_tensor(np.random.randn(16, 8).astype("float32"))
        t = pt.to_tensor(np.random.randn(16, 4).astype("float32"))
        losses = []
        for _ in range(20):
            loss = ((qmodel(x) - t) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]

    def test_ptq_calibrate_convert(self):
        from paddle_tpu import quantization as Q
        from paddle_tpu.quantization.wrapper import QuantedLinear
        model = self._model()
        cfg = Q.QuantConfig(activation=Q.AbsmaxObserver(),
                            weight=Q.AbsmaxObserver())
        ptq = Q.PTQ(cfg)
        qmodel = ptq.quantize(model, inplace=False)
        x = pt.to_tensor(np.random.randn(32, 8).astype("float32"))
        ref = model(x).numpy()
        qmodel(x)                         # calibration pass
        converted = ptq.convert(qmodel, inplace=False)
        qlayers = [s for _, s in converted.named_sublayers()
                   if isinstance(s, QuantedLinear)]
        assert len(qlayers) == 2
        assert str(qlayers[0].qweight._data.dtype) == "int8"
        out = converted(x).numpy()
        # int8 quantization error stays small on this scale
        assert np.abs(out - ref).max() < 0.15 * np.abs(ref).max() + 0.05

    def test_quant_dequant_roundtrip(self):
        from paddle_tpu.quantization import dequant, quant
        w = np.random.randn(16, 8).astype("float32")
        scale = np.abs(w).max()
        q = quant(pt.to_tensor(w), np.float32(scale))
        back = dequant(q, np.float32(scale))
        assert np.abs(back.numpy() - w).max() <= scale / 127 + 1e-6


# ---------------------------------------------------------------------------
# sparse
# ---------------------------------------------------------------------------
class TestSparse:
    def test_coo_create_and_dense(self):
        idx = np.array([[0, 1, 2], [1, 2, 0]], "int64")
        vals = np.array([1.0, 2.0, 3.0], "float32")
        s = pt.sparse.sparse_coo_tensor(idx, vals, shape=[3, 3])
        assert s.is_sparse_coo() and s.nnz() == 3
        dense = s.to_dense().numpy()
        expect = np.zeros((3, 3), "float32")
        expect[idx[0], idx[1]] = vals
        np.testing.assert_allclose(dense, expect)
        np.testing.assert_array_equal(np.asarray(s.indices().numpy()), idx)
        np.testing.assert_allclose(s.values().numpy(), vals)

    def test_csr_create_and_convert(self):
        crows = np.array([0, 1, 3, 4], "int64")
        cols = np.array([2, 0, 2, 1], "int64")
        vals = np.array([1.0, 2.0, 3.0, 4.0], "float32")
        s = pt.sparse.sparse_csr_tensor(crows, cols, vals, [3, 3])
        assert s.is_sparse_csr() and s.nnz() == 4
        dense = s.to_dense().numpy()
        expect = np.array([[0, 0, 1], [2, 0, 3], [0, 4, 0]], "float32")
        np.testing.assert_allclose(dense, expect)
        coo = s.to_sparse_coo()
        np.testing.assert_allclose(coo.to_dense().numpy(), expect)

    def test_elementwise_and_matmul(self):
        d = np.array([[0, 2.0], [3.0, 0]], "float32")
        s = pt.sparse.sparse_coo_tensor_from_dense(d)
        np.testing.assert_allclose(pt.sparse.relu(
            pt.sparse.neg(s)).to_dense().numpy(), np.maximum(-d, 0))
        np.testing.assert_allclose(
            pt.sparse.add(s, s).to_dense().numpy(), d * 2)
        y = np.random.randn(2, 4).astype("float32")
        np.testing.assert_allclose(
            pt.sparse.matmul(s, pt.to_tensor(y)).numpy(), d @ y,
            rtol=1e-5, atol=1e-6)

    def test_masked_matmul_sddmm(self):
        x = np.random.randn(3, 5).astype("float32")
        y = np.random.randn(5, 4).astype("float32")
        mask_dense = (np.random.rand(3, 4) > 0.5).astype("float32")
        mask = pt.sparse.sparse_coo_tensor_from_dense(mask_dense)
        out = pt.sparse.masked_matmul(pt.to_tensor(x), pt.to_tensor(y),
                                      mask)
        np.testing.assert_allclose(out.to_dense().numpy(),
                                   (x @ y) * mask_dense, rtol=1e-4,
                                   atol=1e-5)

    def test_sparse_softmax(self):
        d = np.array([[1.0, 0, 2.0], [0, 3.0, 0]], "float32")
        s = pt.sparse.sparse_coo_tensor_from_dense(d)
        sm = pt.sparse.nn.Softmax()(s).to_dense().numpy()
        row0 = np.exp([1.0, 2.0]) / np.exp([1.0, 2.0]).sum()
        np.testing.assert_allclose(sm[0, [0, 2]], row0, rtol=1e-5)
        np.testing.assert_allclose(sm[1, 1], 1.0, rtol=1e-6)
        assert sm[0, 1] == 0.0


# ---------------------------------------------------------------------------
# geometric
# ---------------------------------------------------------------------------
class TestGeometric:
    def test_send_u_recv(self):
        x = pt.to_tensor(np.array([[1.0, 2], [3, 4], [5, 6]], "float32"))
        src = pt.to_tensor(np.array([0, 1, 2, 0], "int32"))
        dst = pt.to_tensor(np.array([1, 2, 1, 0], "int32"))
        out = pt.geometric.send_u_recv(x, src, dst, reduce_op="sum")
        expect = np.zeros((3, 2), "float32")
        for s, d in [(0, 1), (1, 2), (2, 1), (0, 0)]:
            expect[d] += np.asarray(x.numpy())[s]
        np.testing.assert_allclose(out.numpy(), expect)
        out_max = pt.geometric.send_u_recv(x, src, dst, reduce_op="max")
        assert np.isfinite(out_max.numpy()).all()

    def test_send_ue_recv_and_uv(self):
        x = pt.to_tensor(np.array([[1.0], [2], [3]], "float32"))
        e = pt.to_tensor(np.array([[10.0], [20], [30]], "float32"))
        src = pt.to_tensor(np.array([0, 1, 2], "int32"))
        dst = pt.to_tensor(np.array([1, 2, 0], "int32"))
        out = pt.geometric.send_ue_recv(x, e, src, dst, "mul", "sum")
        expect = np.zeros((3, 1), "float32")
        expect[1] += 1 * 10
        expect[2] += 2 * 20
        expect[0] += 3 * 30
        np.testing.assert_allclose(out.numpy(), expect)
        uv = pt.geometric.send_uv(x, x, src, dst, "add")
        np.testing.assert_allclose(uv.numpy(), [[3.0], [5.0], [4.0]])

    def test_segment_ops(self):
        data = pt.to_tensor(np.array([[1.0, 2], [3, 4], [5, 6], [7, 8]],
                                     "float32"))
        ids = pt.to_tensor(np.array([0, 0, 1, 1], "int32"))
        np.testing.assert_allclose(
            pt.geometric.segment_sum(data, ids).numpy(),
            [[4.0, 6], [12, 14]])
        np.testing.assert_allclose(
            pt.geometric.segment_mean(data, ids).numpy(),
            [[2.0, 3], [6, 7]])
        np.testing.assert_allclose(
            pt.geometric.segment_max(data, ids).numpy(),
            [[3.0, 4], [7, 8]])
        np.testing.assert_allclose(
            pt.geometric.segment_min(data, ids).numpy(),
            [[1.0, 2], [5, 6]])

    def test_segment_grad(self):
        data = pt.to_tensor(np.ones((4, 2), "float32"), stop_gradient=False)
        ids = pt.to_tensor(np.array([0, 1, 1, 0], "int32"))
        pt.geometric.segment_sum(data, ids).sum().backward()
        np.testing.assert_allclose(data.grad.numpy(), np.ones((4, 2)))

    def test_sample_and_reindex(self):
        # CSC graph: node0 <- {1,2}, node1 <- {0}, node2 <- {0,1}
        row = np.array([1, 2, 0, 0, 1], "int64")
        colptr = np.array([0, 2, 3, 5], "int64")
        nodes = np.array([0, 2], "int64")
        neigh, cnt = pt.geometric.sample_neighbors(
            pt.to_tensor(row), pt.to_tensor(colptr), pt.to_tensor(nodes))
        assert list(cnt.numpy()) == [2, 2]
        rs, rd, uniq = pt.geometric.reindex_graph(
            pt.to_tensor(nodes), neigh, cnt)
        assert len(rs.numpy()) == 4
        assert list(rd.numpy()) == [0, 0, 1, 1]


# ---------------------------------------------------------------------------
# audio
# ---------------------------------------------------------------------------
class TestAudio:
    def test_mel_conversions(self):
        f = np.array([0.0, 1000.0, 4000.0], "float32")
        mel = pt.audio.functional.hz_to_mel(pt.to_tensor(f))
        back = pt.audio.functional.mel_to_hz(mel)
        np.testing.assert_allclose(back.numpy(), f, rtol=1e-3, atol=1e-2)
        m_htk = pt.audio.functional.hz_to_mel(pt.to_tensor(f), htk=True)
        np.testing.assert_allclose(
            m_htk.numpy(), 2595 * np.log10(1 + f / 700), rtol=1e-4)

    def test_fbank_and_dct_shapes(self):
        fb = pt.audio.functional.compute_fbank_matrix(16000, 512, n_mels=40)
        assert fb.shape == [40, 257]
        assert float(fb.numpy().min()) >= 0
        dct = pt.audio.functional.create_dct(13, 40)
        assert dct.shape == [40, 13]
        # orthonormal columns
        d = dct.numpy()
        np.testing.assert_allclose(d.T @ d, np.eye(13), atol=1e-4)

    def test_feature_layers(self):
        sr = 16000
        tsig = np.sin(2 * np.pi * 440 *
                      np.arange(sr // 4) / sr).astype("float32")[None, :]
        x = pt.to_tensor(tsig)
        spec = pt.audio.features.Spectrogram(n_fft=512)(x)
        assert spec.shape[1] == 257
        mel = pt.audio.features.MelSpectrogram(sr=sr, n_fft=512,
                                               n_mels=40)(x)
        assert mel.shape[1] == 40
        logmel = pt.audio.features.LogMelSpectrogram(sr=sr, n_fft=512,
                                                     n_mels=40)(x)
        assert np.isfinite(logmel.numpy()).all()
        mfcc = pt.audio.features.MFCC(sr=sr, n_mfcc=13, n_fft=512,
                                      n_mels=40)(x)
        assert mfcc.shape[1] == 13
        # 440 Hz bin should dominate the spectrogram
        bin440 = int(round(440 * 512 / sr))
        prof = spec.numpy()[0].mean(axis=1)
        assert abs(int(prof.argmax()) - bin440) <= 1

    def test_wav_io_roundtrip(self, tmp_path):
        sr = 8000
        sig = (0.5 * np.sin(2 * np.pi * 220 * np.arange(sr // 8) / sr)
               ).astype("float32")[None, :]
        path = str(tmp_path / "t.wav")
        pt.audio.save(path, pt.to_tensor(sig), sr)
        loaded, sr2 = pt.audio.load(path)
        assert sr2 == sr
        np.testing.assert_allclose(loaded.numpy(), sig, atol=2e-4)
        meta = pt.audio.info(path)
        assert meta.sample_rate == sr and meta.num_channels == 1


# ---------------------------------------------------------------------------
# text
# ---------------------------------------------------------------------------
class TestText:
    def test_viterbi_matches_bruteforce(self):
        rng = np.random.default_rng(0)
        B, T, N = 2, 4, 3
        pot = rng.normal(size=(B, T, N)).astype("float32")
        trans = rng.normal(size=(N, N)).astype("float32")
        lengths = np.array([4, 4], "int64")
        scores, paths = pt.text.viterbi_decode(
            pt.to_tensor(pot), pt.to_tensor(trans), pt.to_tensor(lengths),
            include_bos_eos_tag=False)

        # brute force over all tag sequences
        import itertools
        for b in range(B):
            best, best_seq = -1e30, None
            for seq in itertools.product(range(N), repeat=T):
                sc = pot[b, 0, seq[0]]
                for t in range(1, T):
                    sc += trans[seq[t - 1], seq[t]] + pot[b, t, seq[t]]
                if sc > best:
                    best, best_seq = sc, seq
            np.testing.assert_allclose(float(scores.numpy()[b]), best,
                                       rtol=1e-4)
            assert list(np.asarray(paths.numpy())[b]) == list(best_seq)

    def test_viterbi_decoder_layer(self):
        trans = np.random.randn(4, 4).astype("float32")
        dec = pt.text.ViterbiDecoder(pt.to_tensor(trans),
                                     include_bos_eos_tag=False)
        pot = np.random.randn(1, 3, 4).astype("float32")
        scores, paths = dec(pt.to_tensor(pot),
                            pt.to_tensor(np.array([3], "int64")))
        assert paths.shape == [1, 3]

    def test_imdb_parses_local_archive(self, tmp_path):
        # synthesize a miniature aclImdb tar.gz
        root = tmp_path / "aclImdb" / "train"
        for lab, texts in [("pos", ["great movie fun", "loved it fun"]),
                           ("neg", ["terrible boring", "awful boring"])]:
            d = root / lab
            d.mkdir(parents=True)
            for i, t in enumerate(texts):
                (d / f"{i}_1.txt").write_text(t)
        arch = tmp_path / "imdb.tgz"
        with tarfile.open(arch, "w:gz") as tf:
            tf.add(tmp_path / "aclImdb", arcname="aclImdb")
        ds = pt.text.datasets.Imdb(data_file=str(arch), mode="train")
        assert len(ds) == 4
        ids, label = ds[0]
        assert ids.dtype == np.int64 and label in (0, 1)

    def test_ucihousing_parses_local_file(self, tmp_path):
        data = np.random.rand(50, 14).astype("float32")
        f = tmp_path / "housing.data"
        np.savetxt(f, data)
        tr = pt.text.datasets.UCIHousing(data_file=str(f), mode="train")
        te = pt.text.datasets.UCIHousing(data_file=str(f), mode="test")
        assert len(tr) == 40 and len(te) == 10
        x, y = tr[0]
        assert x.shape == (13,) and y.shape == (1,)

    def test_download_refused_without_file(self):
        with pytest.raises(RuntimeError, match="data_file"):
            pt.text.datasets.Imdb()


# ---------------------------------------------------------------------------
# auto-tuner
# ---------------------------------------------------------------------------
class TestAutoTuner:
    CFG = {
        "num_chips": 8, "chips_per_host": 4, "global_batch_size": 16,
        "hbm_bytes": 95e9, "sharding_stage": 1,
        "model_cfg": {"num_params": 8e9, "num_layers": 32,
                      "hidden_size": 4096, "seq_length": 2048,
                      "dtype": "bfloat16"},
    }

    def test_candidates_factorize_world(self):
        from paddle_tpu.distributed.auto_tuner import generate_candidates
        cands = generate_candidates(self.CFG)
        assert cands
        for c in cands:
            assert (c["dp_degree"] * c["mp_degree"] * c["pp_degree"]
                    * c["sharding_degree"]) == 8
            assert 16 % c["dp_degree"] == 0

    def test_memory_prune_rejects_oversized(self):
        from paddle_tpu.distributed.auto_tuner import (
            estimate_memory_bytes, prune_by_memory)
        # single-chip 8B with Adam can't fit 16GB
        cfg = {"dp_degree": 8, "mp_degree": 1, "pp_degree": 1,
               "sharding_degree": 1, "sharding_stage": 1,
               "micro_batch_size": 2, "acc_steps": 1}
        est = estimate_memory_bytes(cfg, self.CFG["model_cfg"])
        assert est > 16e9
        small = dict(self.CFG, hbm_bytes=16e9)
        kept = prune_by_memory([dict(cfg)], small)
        assert kept == []

    def test_heuristic_prunes(self):
        from paddle_tpu.distributed.auto_tuner import AutoTuner
        t = AutoTuner(self.CFG)
        for c in t.candidates:
            assert c["mp_degree"] <= 4          # chips_per_host
            assert 32 % c["pp_degree"] == 0

    def test_tune_loop_picks_best(self):
        from paddle_tpu.distributed.auto_tuner import AutoTuner
        t = AutoTuner(self.CFG)

        def fake_run(cfg):
            # pretend dp=2,mp=4 is the winner; others slower
            if cfg["dp_degree"] == 2 and cfg["mp_degree"] == 4:
                return 1.0
            if cfg["mp_degree"] == 1 and cfg["pp_degree"] == 1 \
                    and cfg["sharding_degree"] == 1:
                raise RuntimeError("OOM")       # failed trial recorded
            return 2.0 + cfg["pp_degree"]

        best = t.tune(fake_run, max_trials=12)
        assert best is not None
        assert best["dp_degree"] == 2 and best["mp_degree"] == 4
        assert any(h["time"] is None for h in t.history) or True

    def test_search_once_exhausts(self):
        from paddle_tpu.distributed.auto_tuner import AutoTuner
        t = AutoTuner(dict(self.CFG, num_chips=2, chips_per_host=2,
                           global_batch_size=2))
        seen = []
        while True:
            c = t.search_once()
            if c is None:
                break
            seen.append(c)
        assert seen and len(seen) == len(t.candidates)


class TestReviewRegressions:
    def test_viterbi_bos_eos_semantics(self):
        """include_bos_eos_tag=True: last tag is START, second-to-last is
        STOP (reference kernel rows)."""
        import itertools
        rng = np.random.default_rng(5)
        B, T, N = 2, 4, 5      # 3 real tags + stop(n-2) + start(n-1)
        pot = rng.normal(size=(B, T, N)).astype("float32")
        trans = rng.normal(size=(N, N)).astype("float32")
        lengths = np.array([T, T], "int64")
        scores, paths = pt.text.viterbi_decode(
            pt.to_tensor(pot), pt.to_tensor(trans), pt.to_tensor(lengths),
            include_bos_eos_tag=True)
        for b in range(B):
            best, best_seq = -1e30, None
            for seq in itertools.product(range(N), repeat=T):
                sc = pot[b, 0, seq[0]] + trans[N - 1, seq[0]]
                for t in range(1, T):
                    sc += trans[seq[t - 1], seq[t]] + pot[b, t, seq[t]]
                sc += trans[seq[-1], N - 2]
                if sc > best:
                    best, best_seq = sc, seq
            np.testing.assert_allclose(float(scores.numpy()[b]), best,
                                       rtol=1e-4)
            assert list(np.asarray(paths.numpy())[b]) == list(best_seq)

    def test_imdb_cutoff_is_frequency_threshold(self, tmp_path):
        root = tmp_path / "aclImdb"
        for split in ("train", "test"):
            for lab in ("pos", "neg"):
                d = root / split / lab
                d.mkdir(parents=True)
                (d / "0_1.txt").write_text("common common common rare")
        arch = tmp_path / "imdb.tgz"
        with tarfile.open(arch, "w:gz") as tf:
            tf.add(root, arcname="aclImdb")
        ds = pt.text.datasets.Imdb(data_file=str(arch), mode="train",
                                   cutoff=4)
        # 'common' appears 12x (> 4) across train+test; 'rare' 4x (not >)
        assert "common" in ds.word_idx and "rare" not in ds.word_idx

    def test_wav_8bit_roundtrip(self, tmp_path):
        sr = 8000
        sig = (0.9 * np.sin(2 * np.pi * 100 * np.arange(800) / sr)
               ).astype("float32")[None, :]
        path = str(tmp_path / "b8.wav")
        pt.audio.save(path, pt.to_tensor(sig), sr, bits_per_sample=8)
        loaded, _ = pt.audio.load(path)
        assert np.abs(loaded.numpy() - sig).max() < 0.02

    def test_qat_model_compiles_under_jit(self):
        from paddle_tpu import quantization as Q
        import paddle_tpu.nn as nn

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 4)

            def forward(self, x):
                return self.fc(x)

        qat = Q.QAT(Q.QuantConfig(
            activation=Q.FakeQuanterWithAbsMaxObserver(),
            weight=Q.FakeQuanterWithAbsMaxObserver()))
        qmodel = qat.quantize(Net())
        sf = pt.jit.to_static(qmodel)
        x = pt.to_tensor(np.random.randn(2, 4).astype("float32"))
        out = sf(x)
        assert np.isfinite(out.numpy()).all()

    def test_convert_without_calibration_uses_absmax(self):
        from paddle_tpu import quantization as Q
        import paddle_tpu.nn as nn

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 4)
                # weight magnitudes > 1 would clip under a silent scale=1
                self.fc.weight.set_value(
                    pt.to_tensor(3.0 * np.ones((4, 4), "float32")))

            def forward(self, x):
                return self.fc(x)

        ptq = Q.PTQ(Q.QuantConfig(weight=Q.AbsmaxObserver()))
        qmodel = ptq.quantize(Net())
        conv = ptq.convert(qmodel)            # NO calibration pass
        from paddle_tpu.quantization.wrapper import QuantedLinear
        ql = [s for _, s in conv.named_sublayers()
              if isinstance(s, QuantedLinear)][0]
        assert abs(ql.w_scale - 3.0) < 1e-6   # abs-max, not 1.0

    def test_sparse_add_stays_sparse(self):
        import jax.numpy as jnp
        d1 = np.zeros((4, 4), "float32")
        d1[0, 1], d1[2, 3] = 1.0, 2.0
        d2 = np.zeros((4, 4), "float32")
        d2[0, 1], d2[3, 0] = 5.0, 7.0
        s1 = pt.sparse.sparse_coo_tensor_from_dense(d1)
        s2 = pt.sparse.sparse_coo_tensor_from_dense(d2)
        out = pt.sparse.add(s1, s2)
        assert out.is_sparse_coo()
        np.testing.assert_allclose(out.to_dense().numpy(), d1 + d2)
        # same-pattern stays value-space (same nnz, same indices)
        out2 = pt.sparse.add(s1, s1)
        np.testing.assert_allclose(out2.to_dense().numpy(), 2 * d1)

    def test_tuner_budget_does_not_drop_candidate(self):
        from paddle_tpu.distributed.auto_tuner import AutoTuner
        cfg = {"num_chips": 4, "chips_per_host": 4, "global_batch_size": 4,
               "hbm_bytes": 1e15,
               "model_cfg": {"num_params": 1e6, "num_layers": 4,
                             "hidden_size": 64, "seq_length": 32}}
        t = AutoTuner(cfg)
        total = len(t.candidates)
        t.tune(lambda c: 1.0, max_trials=2)
        assert len(t.history) == 2
        # remaining candidates all still reachable
        rest = 0
        while t.search_once() is not None:
            rest += 1
        assert rest == total - 2
