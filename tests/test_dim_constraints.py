"""DimExpr-lite symbolic dim constraints (VERDICT-r4 item 7).

Reference: paddle/pir/include/dialect/shape/ — symbolic dims with
relations, checked by the compiler and used by CINN's symbolic buckets.
Here: named InputSpec dims + to_static(constraints=[...]) checked at
the call boundary, pruning the bucketing ladder to admissible sizes.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import jit
from paddle_tpu.core import enforce as E
from paddle_tpu.jit.api import InputSpec, StaticFunction
from paddle_tpu.jit.constraints import DimConstraints


def _t(*shape):
    return paddle.to_tensor(np.ones(shape, "float32"))


class TestDimConstraints:
    def test_parse_rejects_calls(self):
        with pytest.raises(E.InvalidArgumentError, match="disallowed"):
            DimConstraints(["__import__('os').system('x') == 0"])

    def test_parse_rejects_no_names(self):
        with pytest.raises(E.InvalidArgumentError, match="names no"):
            DimConstraints(["3 == 3"])

    def test_parse_rejects_non_int_constant(self):
        with pytest.raises(E.InvalidArgumentError, match="not an integer"):
            DimConstraints(["S == 'x'"])

    def test_check_and_admits(self):
        c = DimConstraints(["S % 8 == 0", "B <= 64", "S >= B"])
        c.check({"S": 16, "B": 4})                    # fine
        with pytest.raises(E.InvalidArgumentError, match="S % 8"):
            c.check({"S": 12, "B": 4})
        with pytest.raises(E.InvalidArgumentError, match="S >= B"):
            c.check({"S": 16, "B": 32})
        c.check({"B": 4})                             # S unbound: skipped
        assert c.admits("S", 16) and not c.admits("S", 12)
        # multi-dim relations never veto a single-dim bucket choice
        assert c.admits("S", 0)
        assert c.prune("S", [8, 12, 16, 20, 24]) == [8, 16, 24]


class TestToStaticConstraints:
    def test_equality_via_shared_name(self):
        @jit.to_static(input_spec=[InputSpec([None, "S"]),
                                   InputSpec([None, "S"])])
        def f(a, b):
            return a + b

        out = f(_t(2, 8), _t(2, 8))
        assert tuple(out.shape) == (2, 8)
        with pytest.raises(E.InvalidArgumentError, match="bound to both"):
            f(_t(2, 8), _t(2, 6))

    def test_relational_constraint_checked(self):
        @jit.to_static(input_spec=[InputSpec([None, "S"])],
                       constraints=["S % 8 == 0"])
        def f(x):
            return x * 2

        assert tuple(f(_t(1, 16)).shape) == (1, 16)
        with pytest.raises(E.InvalidArgumentError,
                           match="constraint violated"):
            f(_t(1, 12))

    def test_fixed_int_dim_checked(self):
        @jit.to_static(input_spec=[InputSpec(["B", 4])])
        def f(x):
            return x + 1

        f(_t(3, 4))
        with pytest.raises(E.InvalidArgumentError, match="fixes it to 4"):
            f(_t(3, 5))

    def test_constraints_require_named_dims(self):
        with pytest.raises(E.InvalidArgumentError, match="no named dims"):
            jit.to_static(input_spec=[InputSpec([None, None])],
                          constraints=["S % 8 == 0"])(lambda x: x)

    def test_bucket_pruning_explicit_sizes(self):
        # Without the constraint, seq 8 would pad into the 12-bucket and
        # compile a program whose shape the user declared impossible;
        # pruning steps over it to 16.
        @jit.to_static(input_spec=[InputSpec([None, "S"])],
                       constraints=["S % 8 == 0"],
                       bucket_seq=True, seq_bucket_sizes=[12, 16])
        def f(x):
            return x * 3

        with paddle.no_grad():
            out = f(_t(2, 8))
        assert tuple(out.shape) == (2, 8)        # unpadded back
        compiled_seqs = {k[0][1][0][1][1] for k in f._programs}
        assert compiled_seqs == {16}, compiled_seqs

    def test_bucket_pruning_pow2_ladder(self):
        # "S % 96 == 0": the power-of-two ladder is inadmissible; the
        # bounded scan lands on the smallest admitted size >= n.
        admit = DimConstraints(["S % 96 == 0"])
        pick = StaticFunction._pick_bucket
        assert pick(96, None, admit=lambda b: admit.admits("S", b)) == 96
        assert pick(100, None,
                    admit=lambda b: admit.admits("S", b)) == 192

    def test_named_dims_without_constraints_still_bind(self):
        @jit.to_static(input_spec=[InputSpec(["B", "B"])])
        def f(x):
            return x.sum()

        f(_t(4, 4))
        with pytest.raises(E.InvalidArgumentError, match="bound to both"):
            f(_t(4, 5))
