"""Blockwise fused cross-entropy (kernels/fused_ce.py) parity tests.

Oracle: the materialising logsumexp xent. Checks fwd, grads wrt x AND
head, non-divisible vocab (masked tail chunk), bf16 inputs, jit, and the
llama loss_fn integration (fused vs einsum path must match)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import kernels
from paddle_tpu.kernels.fused_ce import fused_cross_entropy


def _naive(x, head, labels):
    logits = jnp.einsum("...d,vd->...v", x, head,
                        preferred_element_type=jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return jnp.mean(logz - gold)


def _case(n=6, s=7, d=16, v=33, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(n, s, d)), dtype)
    head = jnp.asarray(rng.normal(size=(v, d)) * 0.3, dtype)
    labels = jnp.asarray(rng.integers(0, v, (n, s)), jnp.int32)
    return x, head, labels


class TestFusedCE:
    @pytest.mark.parametrize("v,chunk", [(32, 8), (33, 8), (7, 16), (40, 40)])
    def test_forward_parity(self, v, chunk):
        x, head, labels = _case(v=v)
        got = fused_cross_entropy(x, head, labels, vocab_chunk=chunk)
        want = _naive(x, head, labels)
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)

    def test_grad_parity(self):
        x, head, labels = _case(v=33)
        gf = jax.grad(lambda x, h: fused_cross_entropy(
            x, h, labels, vocab_chunk=8), argnums=(0, 1))(x, head)
        gn = jax.grad(lambda x, h: _naive(x, h, labels),
                      argnums=(0, 1))(x, head)
        np.testing.assert_allclose(gf[0], gn[0], rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(gf[1], gn[1], rtol=1e-5, atol=1e-6)

    def test_bf16_inputs(self):
        x, head, labels = _case(v=32, dtype=jnp.bfloat16)
        got = fused_cross_entropy(x, head, labels, vocab_chunk=8)
        want = _naive(x, head, labels)
        np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)
        g = jax.grad(lambda x: fused_cross_entropy(
            x, head, labels, vocab_chunk=8))(x)
        assert g.dtype == jnp.bfloat16

    def test_jit_and_reductions(self):
        x, head, labels = _case(v=20)
        f = jax.jit(lambda x: fused_cross_entropy(
            x, head, labels, vocab_chunk=8, reduction="none"))
        per_tok = f(x)
        assert per_tok.shape == labels.shape
        np.testing.assert_allclose(jnp.mean(per_tok),
                                   _naive(x, head, labels),
                                   rtol=1e-6, atol=1e-6)
        s = fused_cross_entropy(x, head, labels, vocab_chunk=8,
                                reduction="sum")
        np.testing.assert_allclose(s, jnp.sum(per_tok), rtol=1e-6)

    def test_dispatcher_counts_and_fallback(self):
        x, head, labels = _case(v=16)
        kernels.reset_dispatch_stats()
        kernels.dispatched_fused_ce(x, head, labels, vocab_chunk=8)
        assert kernels.dispatch_stats()["fused_ce"] == 1
        # 1-D x is outside the guard -> fallback path, same math
        x1, l1 = x[0, 0], labels[0, 0]
        out = kernels.dispatched_fused_ce(x1, head, l1, vocab_chunk=8)
        assert kernels.dispatch_stats()["fused_ce_fallback"] == 1
        np.testing.assert_allclose(out, _naive(x1, head, l1), rtol=1e-6)

    @pytest.mark.slow  # tier-1 budget (ISSUE 3): heavy; run in the slow lane
    def test_llama_loss_fused_matches_einsum(self):
        from paddle_tpu.models import llama as L

        cfg_f = L.llama_tiny(num_hidden_layers=2, fused_ce=True,
                             fused_ce_chunk=64)
        cfg_e = L.llama_tiny(num_hidden_layers=2, fused_ce=False)
        params = L.init_params(cfg_f, jax.random.PRNGKey(0))
        ids = jnp.asarray(np.random.default_rng(0).integers(
            0, cfg_f.vocab_size, (2, 17)), jnp.int32)
        lf = L.loss_fn(params, ids, cfg_f)
        le = L.loss_fn(params, ids, cfg_e)
        np.testing.assert_allclose(lf, le, rtol=1e-5, atol=1e-6)
        gf = jax.grad(lambda p: L.loss_fn(p, ids, cfg_f))(params)
        ge = jax.grad(lambda p: L.loss_fn(p, ids, cfg_e))(params)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            a, b, rtol=1e-4, atol=1e-5), gf, ge)

    @pytest.mark.slow  # tier-1 budget (ISSUE 20 rebalance): train-step integration dup;
    # grad_parity + dispatcher_fused_path_matches keep the seam fast
    def test_train_step_still_works(self):
        from paddle_tpu.models import llama as L

        cfg = L.llama_tiny(num_hidden_layers=2, fused_ce=True,
                           fused_ce_chunk=64)
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        opt = L.adamw_init(params)
        step = L.make_train_step(cfg, lr=1e-3)
        ids = jnp.asarray(np.random.default_rng(0).integers(
            0, cfg.vocab_size, (2, 33)), jnp.int32)
        losses = []
        for _ in range(5):
            params, opt, loss = step(params, opt, ids)
            losses.append(float(loss))
        assert losses[-1] < losses[0]


class TestIgnoreIndex:
    """ADVICE-r4 medium: -100 padded labels must zero out, not poison the
    mean with the masked-lane -1e30 gold logit; mean divides by valid
    count (reference F.cross_entropy ignore_index semantics)."""

    def _masked_oracle(self, x, head, labels, ignore=-100):
        valid = (labels != ignore) & (labels >= 0) & (labels < head.shape[0])
        safe = jnp.where(valid, labels, 0)
        logits = jnp.einsum("...d,vd->...v", x, head,
                            preferred_element_type=jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
        per = jnp.where(valid, logz - gold, 0.0)
        return jnp.sum(per) / jnp.maximum(jnp.sum(valid.astype(per.dtype)), 1)

    def test_padded_labels_finite_and_match_oracle(self):
        x, head, labels = _case(v=33)
        labels = labels.at[:, -3:].set(-100)   # right-padding convention
        got = fused_cross_entropy(x, head, labels, vocab_chunk=8)
        want = self._masked_oracle(x, head, labels)
        assert np.isfinite(float(got)) and float(got) < 1e6
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)

    def test_grad_zero_on_ignored(self):
        x, head, labels = _case(v=33)
        labels = labels.at[0, :].set(-100)
        gx = jax.grad(lambda x: fused_cross_entropy(
            x, head, labels, vocab_chunk=8))(x)
        np.testing.assert_allclose(gx[0], np.zeros_like(gx[0]), atol=1e-9)
        assert float(jnp.abs(gx[1:]).max()) > 0
        gn = jax.grad(lambda x: self._masked_oracle(x, head, labels))(x)
        np.testing.assert_allclose(gx, gn, rtol=1e-5, atol=1e-6)

    def test_out_of_range_label_masked(self):
        x, head, labels = _case(v=33)
        labels = labels.at[1, 2].set(77)       # > V, not ignore_index
        got = fused_cross_entropy(x, head, labels, vocab_chunk=8)
        assert np.isfinite(float(got)) and float(got) < 1e6

    def test_custom_ignore_index(self):
        x, head, labels = _case(v=33)
        labels = labels.at[:, 0].set(0)
        a = fused_cross_entropy(x, head, labels, ignore_index=0,
                                vocab_chunk=8)
        want = self._masked_oracle(x, head, labels, ignore=0)
        np.testing.assert_allclose(a, want, rtol=1e-6, atol=1e-6)

    def test_all_ignored_is_zero_not_nan(self):
        x, head, labels = _case(v=33)
        labels = jnp.full_like(labels, -100)
        got = fused_cross_entropy(x, head, labels, vocab_chunk=8)
        assert float(got) == 0.0

    def test_dispatcher_fused_path_matches(self):
        x, head, labels = _case(v=33)
        labels = labels.at[:, -2:].set(-100)
        kernels.reset_dispatch_stats()
        a = kernels.dispatched_fused_ce(x, head, labels)
        assert kernels.dispatch_stats()["fused_ce"] == 1
        b = self._masked_oracle(x, head, labels)
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)

    def test_dispatcher_fallback_masks_identically(self, monkeypatch):
        # force the materialising fallback on a full batch: its masking
        # (zeroed ignored tokens, valid-count mean) must match both the
        # oracle and the fused kernel on identical inputs
        from paddle_tpu.kernels import fused_ce as _fce
        x, head, labels = _case(v=33)
        labels = labels.at[:, ::2].set(-100)
        want = self._masked_oracle(x, head, labels)
        fused = fused_cross_entropy(x, head, labels, vocab_chunk=8)
        monkeypatch.setattr(_fce, "supported", lambda *a: False)
        kernels.reset_dispatch_stats()
        fell = kernels.dispatched_fused_ce(x, head, labels)
        assert kernels.dispatch_stats()["fused_ce_fallback"] == 1
        np.testing.assert_allclose(fell, want, rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(fell, fused, rtol=1e-6, atol=1e-6)
