"""Tests for the API-surface completion sweep part 2: distributions,
optimizers, vision transforms/models, static extras, sparse long tail,
incubate graph ops, distributed compat."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def t(x, **kw):
    return paddle.to_tensor(x, **kw)


class TestDistributions:
    def test_multivariate_normal(self):
        import scipy.stats as st

        from paddle_tpu.distribution import MultivariateNormal

        loc = np.array([1.0, -1.0], "float32")
        cov = np.array([[2.0, 0.5], [0.5, 1.0]], "float32")
        d = MultivariateNormal(t(loc), covariance_matrix=t(cov))
        x = np.array([[0.0, 0.0], [1.0, -1.0]], "float32")
        want = st.multivariate_normal(loc, cov).logpdf(x)
        np.testing.assert_allclose(np.asarray(d.log_prob(t(x)).numpy()),
                                   want, rtol=1e-4)
        want_ent = st.multivariate_normal(loc, cov).entropy()
        np.testing.assert_allclose(float(d.entropy().numpy()), want_ent,
                                   rtol=1e-4)
        s = d.sample((500,))
        assert s.shape == [500, 2]
        # KL(d, d) == 0
        np.testing.assert_allclose(float(d.kl_divergence(d).numpy()), 0.0,
                                   atol=1e-5)

    def test_cauchy(self):
        import scipy.stats as st

        from paddle_tpu.distribution import Cauchy

        d = Cauchy(t(0.5), t(2.0))
        x = np.array([0.0, 1.0, 5.0], "float32")
        np.testing.assert_allclose(np.asarray(d.log_prob(t(x)).numpy()),
                                   st.cauchy(0.5, 2.0).logpdf(x), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(d.cdf(t(x)).numpy()),
                                   st.cauchy(0.5, 2.0).cdf(x), rtol=1e-5)
        with pytest.raises(ValueError):
            d.mean

    def test_binomial(self):
        import scipy.stats as st

        from paddle_tpu.distribution import Binomial

        d = Binomial(t(10.0), t(0.3))
        k = np.array([0.0, 3.0, 10.0], "float32")
        np.testing.assert_allclose(np.asarray(d.log_prob(t(k)).numpy()),
                                   st.binom(10, 0.3).logpmf(k), rtol=1e-4)
        np.testing.assert_allclose(float(d.entropy().numpy()),
                                   st.binom(10, 0.3).entropy(), rtol=1e-3)

    def test_independent(self):
        from paddle_tpu.distribution import Independent, Normal

        base = Normal(t(np.zeros(3, "float32")), t(np.ones(3, "float32")))
        d = Independent(base, 1)
        assert d.event_shape == (3,)
        lp = d.log_prob(t(np.zeros(3, "float32")))
        np.testing.assert_allclose(
            float(lp.numpy()),
            float(np.sum(np.asarray(base.log_prob(
                t(np.zeros(3, "float32"))).numpy()))), rtol=1e-6)

    def test_transformed(self):
        import scipy.stats as st

        from paddle_tpu.distribution import (ExpTransform, Normal,
                                             TransformedDistribution)

        d = TransformedDistribution(Normal(t(0.0), t(1.0)),
                                    [ExpTransform()])
        x = np.array([0.5, 1.0, 2.0], "float32")
        np.testing.assert_allclose(np.asarray(d.log_prob(t(x)).numpy()),
                                   st.lognorm(s=1.0).logpdf(x), rtol=1e-4)

    def test_transforms_roundtrip(self):
        from paddle_tpu.distribution import (AffineTransform,
                                             SigmoidTransform,
                                             StickBreakingTransform,
                                             TanhTransform)

        x = np.array([-1.5, 0.2, 2.0], "float32")
        for tr in [AffineTransform(t(1.0), t(2.0)), SigmoidTransform(),
                   TanhTransform()]:
            y = tr.forward(t(x))
            back = tr.inverse(y)
            np.testing.assert_allclose(np.asarray(back.numpy()), x,
                                       rtol=1e-4, atol=1e-5)
        sb = StickBreakingTransform()
        y = sb.forward(t(x))
        arr = np.asarray(y.numpy())
        assert arr.shape == (4,)
        np.testing.assert_allclose(arr.sum(), 1.0, rtol=1e-5)
        back = sb.inverse(y)
        np.testing.assert_allclose(np.asarray(back.numpy()), x, rtol=1e-3,
                                   atol=1e-4)

    def test_continuous_bernoulli(self):
        from paddle_tpu.distribution import ContinuousBernoulli

        d = ContinuousBernoulli(t(np.array([0.3], "float32")))
        lp = d.log_prob(t(np.array([0.5], "float32")))
        assert np.isfinite(float(lp.numpy()))
        m = float(d.mean.numpy())
        assert 0.0 < m < 0.5
        s = d.sample((200,))
        arr = np.asarray(s.numpy())
        assert ((arr > 0) & (arr < 1)).all()


def _fit(opt_cls, steps=150, **kw):
    rng = np.random.default_rng(0)
    xw = rng.normal(size=(32, 4)).astype("float32")
    true_w = np.array([[1.0], [-2.0], [0.5], [3.0]], "float32")
    yv = xw @ true_w
    lin = nn.Linear(4, 1)
    opt = opt_cls(learning_rate=kw.pop("lr", 0.1),
                  parameters=lin.parameters(), **kw)
    for _ in range(steps):
        loss = nn.functional.mse_loss(lin(t(xw)), t(yv))
        loss.backward()
        opt.step()
        opt.clear_grad()
    return float(loss.numpy())


class TestNewOptimizers:
    def test_adadelta(self):
        from paddle_tpu.optimizer import Adadelta

        # adadelta's step size bootstraps from sqrt(eps): slow start,
        # monotone progress is the property to check
        start = _fit(Adadelta, steps=1, lr=1.0)
        assert _fit(Adadelta, steps=800, lr=1.0) < 0.25 * start

    def test_nadam(self):
        from paddle_tpu.optimizer import NAdam

        assert _fit(NAdam, lr=0.1) < 0.1

    def test_radam(self):
        from paddle_tpu.optimizer import RAdam

        assert _fit(RAdam, lr=0.1) < 0.1

    def test_asgd(self):
        from paddle_tpu.optimizer import ASGD

        assert _fit(ASGD, lr=0.05, batch_num=4) < 0.5

    def test_rprop(self):
        from paddle_tpu.optimizer import Rprop

        assert _fit(Rprop, lr=0.01) < 0.5

    def test_lbfgs(self):
        from paddle_tpu.optimizer import LBFGS

        rng = np.random.default_rng(1)
        xw = rng.normal(size=(32, 4)).astype("float32")
        true_w = np.array([[1.0], [-2.0], [0.5], [3.0]], "float32")
        yv = xw @ true_w
        lin = nn.Linear(4, 1)
        opt = LBFGS(learning_rate=1.0, max_iter=20,
                    line_search_fn="strong_wolfe",
                    parameters=lin.parameters())

        def closure():
            opt.clear_grad()
            loss = nn.functional.mse_loss(lin(t(xw)), t(yv))
            loss.backward()
            return loss

        for _ in range(5):
            final = opt.step(closure)
        assert float(final.numpy()) < 1e-2

    def test_lookahead_modelaverage(self):
        from paddle_tpu.incubate import LookAhead, ModelAverage
        from paddle_tpu.optimizer import SGD

        rng = np.random.default_rng(2)
        xw = rng.normal(size=(16, 3)).astype("float32")
        yv = xw @ np.array([[1.0], [2.0], [-1.0]], "float32")
        lin = nn.Linear(3, 1)
        inner = SGD(learning_rate=0.1, parameters=lin.parameters())
        opt = LookAhead(inner, alpha=0.5, k=2)
        ma = ModelAverage(0.15, parameters=lin.parameters())
        for _ in range(60):
            loss = nn.functional.mse_loss(lin(t(xw)), t(yv))
            loss.backward()
            opt.step()
            opt.clear_grad()
            ma.step()
        assert float(loss.numpy()) < 0.2
        before = lin.weight.numpy().copy()
        ma.apply()
        ma.restore()
        np.testing.assert_allclose(lin.weight.numpy(), before)


class TestVisionSurface:
    def test_affine_perspective_erase(self):
        import paddle_tpu.vision.transforms as T

        img = np.arange(5 * 5 * 3, dtype="uint8").reshape(5, 5, 3)
        # identity affine returns the image
        out = T.affine(img, 0.0, (0, 0), 1.0, (0.0, 0.0))
        np.testing.assert_array_equal(out, img)
        # identity perspective
        pts = [(0, 0), (4, 0), (4, 4), (0, 4)]
        out = T.perspective(img, pts, pts)
        np.testing.assert_array_equal(out, img)
        er = T.erase(img, 1, 1, 2, 2, 0)
        assert (er[1:3, 1:3] == 0).all() and er[0, 0, 0] == img[0, 0, 0]

    def test_random_transform_classes(self):
        import paddle_tpu.vision.transforms as T

        img = np.random.default_rng(0).integers(
            0, 255, (8, 8, 3)).astype("uint8")
        assert T.Grayscale()(img).shape[:2] == (8, 8)
        assert T.RandomAffine(10, translate=(0.1, 0.1),
                              scale=(0.9, 1.1), shear=5)(img).shape == \
            img.shape
        assert T.RandomPerspective(prob=1.0)(img).shape == img.shape
        out = T.RandomErasing(prob=1.0)(img)
        assert out.shape == img.shape

    @pytest.mark.slow  # tier-1 budget (ISSUE 3): heavy; run in the slow lane
    def test_new_model_families_forward(self):
        import paddle_tpu.vision.models as M

        x = t(np.random.default_rng(0).normal(size=(1, 3, 64, 64))
              .astype("float32"))
        assert M.mobilenet_v1(num_classes=10)(x).shape == [1, 10]
        assert M.mobilenet_v3_small(num_classes=10)(x).shape == [1, 10]
        assert M.squeezenet1_1(num_classes=10)(x).shape == [1, 10]
        assert M.shufflenet_v2_x0_25(num_classes=10)(x).shape == [1, 10]
        y = M.densenet121(num_classes=10)(x)
        assert y.shape == [1, 10]

    @pytest.mark.slow  # tier-1 budget (ISSUE 3): heavy; run in the slow lane
    def test_alexnet_googlenet_inception(self):
        import paddle_tpu.vision.models as M

        x = t(np.random.default_rng(0).normal(size=(1, 3, 224, 224))
              .astype("float32"))
        assert M.alexnet(num_classes=7)(x).shape == [1, 7]
        out, a1, a2 = M.googlenet(num_classes=7)(x)
        assert out.shape == [1, 7] and a1.shape == [1, 7]
        x2 = t(np.random.default_rng(0).normal(size=(1, 3, 299, 299))
               .astype("float32"))
        assert M.inception_v3(num_classes=7)(x2).shape == [1, 7]

    @pytest.mark.slow  # tier-1 budget (ISSUE 3): heavy; run in the slow lane
    def test_resnext_wide(self):
        import paddle_tpu.vision.models as M

        x = t(np.random.default_rng(0).normal(size=(1, 3, 64, 64))
              .astype("float32"))
        assert M.resnext50_32x4d(num_classes=5)(x).shape == [1, 5]
        assert M.wide_resnet50_2(num_classes=5)(x).shape == [1, 5]

    def test_vision_ops_new(self):
        import paddle_tpu.vision.ops as vops

        # matrix_nms: two overlapping boxes, one distinct
        boxes = np.array([[[0, 0, 10, 10], [0, 0, 10, 10],
                           [20, 20, 30, 30]]], "float32")
        scores = np.array([[[0.9, 0.85, 0.8]]], "float32")
        rois, num = vops.matrix_nms(t(boxes), t(scores), 0.1, 0.01,
                                    10, 10, background_label=-1)
        assert np.asarray(num.numpy())[0] >= 2
        r = np.asarray(rois.numpy())
        assert r.shape[1] == 6

    def test_yolo_loss_differentiable(self):
        import paddle_tpu.vision.ops as vops

        rng = np.random.default_rng(3)
        na, nc, h, w = 3, 4, 4, 4
        x = t(rng.normal(size=(2, na * (5 + nc), h, w))
              .astype("float32"), stop_gradient=False)
        gt = t(np.array([[[0.5, 0.5, 0.3, 0.4]]] * 2, "float32"))
        gl = t(np.array([[1]] * 2, "int32"))
        loss = vops.yolo_loss(x, gt, gl, anchors=[10, 13, 16, 30, 33, 23],
                              anchor_mask=[0, 1, 2], class_num=nc,
                              ignore_thresh=0.5, downsample_ratio=32)
        assert loss.shape == [2]
        loss.sum().backward()
        assert np.isfinite(np.asarray(x.grad.numpy())).all()

    def test_read_file(self, tmp_path):
        import paddle_tpu.vision.ops as vops

        p = tmp_path / "blob.bin"
        p.write_bytes(b"\x01\x02\x03")
        out = vops.read_file(str(p))
        np.testing.assert_array_equal(np.asarray(out.numpy()), [1, 2, 3])


class TestStaticExtras:
    def test_create_parameter_and_gradients(self):
        import paddle_tpu.static as static

        w = static.create_parameter([3, 2], "float32")
        assert w.shape == [3, 2]
        gv = static.create_global_var([1], 2.5, "float32")
        np.testing.assert_allclose(np.asarray(gv.numpy()), [2.5])

    def test_program_serialize_roundtrip(self):
        import paddle_tpu.static as static

        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [None, 3], "float32")
            lin = nn.Linear(3, 2)
            y = lin(x)
            loss = paddle.mean(y)
        data = static.serialize_program([x], [loss])
        prog2 = static.deserialize_program(data)
        exe = static.Executor()
        arr = np.random.default_rng(0).normal(size=(4, 3)).astype("float32")
        (o1,) = exe.run(main, feed={"x": arr}, fetch_list=[loss])
        (o2,) = exe.run(prog2, feed={"x": arr},
                        fetch_list=[prog2._loaded_fetch[0]])
        np.testing.assert_allclose(o1, o2, rtol=1e-6)

    def test_static_save_load(self, tmp_path):
        import paddle_tpu.static as static

        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [None, 3], "float32")
            lin = nn.Linear(3, 2)
            y = lin(x)
        prefix = str(tmp_path / "model")
        static.save(main, prefix)
        old = lin.weight.numpy().copy()
        lin.weight.set_value(np.zeros_like(old))
        static.load(main, prefix)
        np.testing.assert_allclose(lin.weight.numpy(), old)

    def test_static_nn_builders(self):
        import paddle_tpu.static as static

        paddle.enable_static()
        try:
            main = static.Program()
            with static.program_guard(main):
                x = static.data("x", [None, 6], "float32")
                h = static.nn.fc(x, 4, activation="relu")
                img = static.data("img", [None, 3, 8, 8], "float32")
                c = static.nn.conv2d(img, 4, 3, padding=1)
                ln = static.nn.layer_norm(h)
            exe = static.Executor()
            arr = np.random.default_rng(0).normal(size=(2, 6)) \
                .astype("float32")
            im = np.random.default_rng(1).normal(size=(2, 3, 8, 8)) \
                .astype("float32")
            (hv, cv, lv) = exe.run(main, feed={"x": arr, "img": im},
                                   fetch_list=[h, c, ln])
            assert hv.shape == (2, 4) and (hv >= 0).all()
            assert cv.shape == (2, 4, 8, 8)
            assert lv.shape == (2, 4)
        finally:
            paddle.disable_static()

    def test_ema(self):
        import paddle_tpu.static as static

        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [None, 2], "float32")
            lin = nn.Linear(2, 1)
            y = lin(x)
        ema = static.ExponentialMovingAverage(0.5)
        with static.program_guard(main):
            ema.update()
        w0 = lin.weight.numpy().copy()
        lin.weight.set_value(w0 + 1.0)
        with static.program_guard(main):
            ema.update()
        with static.program_guard(main):
            with ema.apply():
                applied = lin.weight.numpy().copy()
        restored = lin.weight.numpy()
        np.testing.assert_allclose(restored, w0 + 1.0)
        assert not np.allclose(applied, restored)

    def test_compiled_program_and_places(self):
        import paddle_tpu.static as static

        cp = static.CompiledProgram(static.Program(),
                                    static.BuildStrategy())
        assert cp.ops() == []
        assert len(static.cuda_places()) >= 1


class TestSparseLongTail:
    def test_sparse_unaries_and_matvec(self):
        import paddle_tpu.sparse as sp

        dense = np.array([[0.0, 0.5], [0.25, 0.0]], "float32")
        s = sp.sparse_coo_tensor_from_dense(t(dense))
        np.testing.assert_allclose(
            np.asarray(sp.asin(s).to_dense().numpy()),
            np.arcsin(dense), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(sp.tan(s).to_dense().numpy()),
            np.where(dense != 0, np.tan(dense), 0.0), rtol=1e-5)
        v = np.array([1.0, 2.0], "float32")
        np.testing.assert_allclose(np.asarray(sp.mv(s, t(v)).numpy()),
                                   dense @ v, rtol=1e-5)
        r = sp.reshape(s, [1, 4])
        assert list(r.shape) == [1, 4]
        sl = sp.slice(s, [0], [0], [1])
        assert list(sl.shape) == [1, 2]
        out = sp.addmm(t(np.ones((2, 2), "float32")), s,
                       t(np.ones((2, 2), "float32")), beta=2.0)
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   2.0 + dense @ np.ones((2, 2)), rtol=1e-5)


class TestIncubateGraph:
    def test_segment_and_send_recv(self):
        import paddle_tpu.incubate as inc

        data = t(np.array([[1.0], [2.0], [3.0]], "float32"))
        ids = t(np.array([0, 0, 1], "int64"))
        np.testing.assert_allclose(
            np.asarray(inc.segment_sum(data, ids).numpy()),
            [[3.0], [3.0]])
        x = t(np.eye(3, dtype="float32"))
        out = inc.graph_send_recv(x, t(np.array([0, 1], "int64")),
                                  t(np.array([2, 2], "int64")))
        np.testing.assert_allclose(np.asarray(out.numpy())[2],
                                   [1.0, 1.0, 0.0])

    def test_softmax_mask_fuse(self):
        import scipy.special as ssp

        import paddle_tpu.incubate as inc

        x = np.random.default_rng(0).normal(size=(1, 1, 3, 3)) \
            .astype("float32")
        m = np.zeros_like(x)
        np.testing.assert_allclose(
            np.asarray(inc.softmax_mask_fuse(t(x), t(m)).numpy()),
            ssp.softmax(x, axis=-1), rtol=1e-5)
        ut = inc.softmax_mask_fuse_upper_triangle(t(x))
        arr = np.asarray(ut.numpy())[0, 0]
        assert arr[0, 1] == 0.0 and arr[0, 0] == 1.0

    def test_khop_sampler(self):
        import paddle_tpu.incubate as inc

        # CSC graph: 3 nodes, edges into each node from the next
        row = t(np.array([1, 2, 0], "int64"))
        colptr = t(np.array([0, 1, 2, 3], "int64"))
        seeds = t(np.array([0], "int64"))
        src, dst, nodes, counts = inc.graph_khop_sampler(
            row, colptr, seeds, [1, 1])
        assert len(np.asarray(nodes.numpy())) >= 1


class TestDistributedCompat:
    def test_strategy_and_parallel_mode(self):
        import paddle_tpu.distributed as dist

        s = dist.Strategy({"sharding": {"enable": True, "stage": 2}})
        assert s.sharding.enable and s.sharding.stage == 2
        assert dist.ParallelMode.PIPELINE_PARALLEL == 2

    def test_dist_model_train(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.optimizer import SGD

        lin = nn.Linear(3, 1)
        loss_fn = nn.MSELoss()
        opt = SGD(learning_rate=0.1, parameters=lin.parameters())
        model, _ = dist.to_static(lin, None, loss_fn, opt)
        model.train()
        x = t(np.random.default_rng(0).normal(size=(8, 3)).astype("float32"))
        y = t(np.zeros((8, 1), "float32"))
        l0 = float(model(x, y).numpy())
        for _ in range(20):
            last = float(model(x, y).numpy())
        assert last < l0
        sd = model.state_dict()
        assert "weight" in sd

    def test_gloo_shims_and_ps_gates(self):
        import paddle_tpu.distributed as dist

        dist.gloo_init_parallel_env(0, 1, "127.0.0.1:1234")
        dist.gloo_release()
        with pytest.raises(NotImplementedError):
            dist.InMemoryDataset()

    def test_persistables_roundtrip(self, tmp_path):
        import paddle_tpu.distributed.io as dio
        import paddle_tpu.static as static

        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [None, 2], "float32")
            lin = nn.Linear(2, 2)
            y = lin(x)
        dio.save_persistables(None, str(tmp_path), main)
        old = lin.weight.numpy().copy()
        lin.weight.set_value(np.zeros_like(old))
        dio.load_persistables(None, str(tmp_path), main)
        np.testing.assert_allclose(lin.weight.numpy(), old)


class TestMiscSurface:
    def test_metric_accuracy_fn(self):
        from paddle_tpu.metric import accuracy

        pred = t(np.array([[0.1, 0.9], [0.8, 0.2]], "float32"))
        label = t(np.array([1, 1], "int64"))
        np.testing.assert_allclose(float(accuracy(pred, label).numpy()),
                                   0.5)

    def test_amp_supported(self):
        assert paddle.amp.is_bfloat16_supported() is True
        assert paddle.amp.is_float16_supported() in (True, False)

    def test_saved_tensors_hooks(self):
        from paddle_tpu.autograd import PyLayer, saved_tensors_hooks

        packed = []

        def pack(x):
            packed.append(True)
            return x.numpy()

        def unpack(h):
            return t(np.asarray(h))

        class Sq(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * x

            @staticmethod
            def backward(ctx, g):
                (x,) = ctx.saved_tensor()
                return g * 2.0 * x

        with saved_tensors_hooks(pack, unpack):
            x = t([3.0], stop_gradient=False)
            y = Sq.apply(x)
        y.backward()
        assert packed and float(x.grad.numpy()[0]) == 6.0

    def test_jacobian_hessian(self):
        from paddle_tpu.autograd import hessian, jacobian

        x = t(np.array([1.0, 2.0], "float32"), stop_gradient=False)
        y = (x * x).sum()
        h = hessian(y, x)
        np.testing.assert_allclose(np.asarray(h.numpy()),
                                   2.0 * np.eye(2), rtol=1e-5)
        x2 = t(np.array([1.0, 2.0], "float32"), stop_gradient=False)
        y2 = x2 * x2
        j = jacobian(y2, x2)
        np.testing.assert_allclose(np.asarray(j.numpy()),
                                   np.diag([2.0, 4.0]), rtol=1e-5)

    def test_get_worker_info_main(self):
        import paddle_tpu.io as pio

        assert pio.get_worker_info() is None

    def test_quanter_surface(self):
        import paddle_tpu.quantization as q

        assert issubclass(q.AbsmaxObserver, object)
        assert q.BaseQuanter is not None

    def test_initializer_bilinear(self):
        from paddle_tpu.nn.initializer import Bilinear

        w = Bilinear()((2, 2, 4, 4))
        arr = np.asarray(w)
        assert arr.shape == (2, 2, 4, 4)
        # symmetric triangle filter
        np.testing.assert_allclose(arr[0, 0], arr[0, 0][::-1, ::-1])

    def test_profiler_enums_and_protobuf(self, tmp_path):
        import paddle_tpu.profiler as prof

        assert prof.SortedKeys.CPUTotal is not None
        assert prof.SummaryView.OverView is not None
        p = prof.Profiler(on_trace_ready=prof.export_protobuf(
            str(tmp_path)))
        p.start()
        with prof.RecordEvent("step"):
            _ = paddle.to_tensor([1.0]) + 1.0
        p.stop()
        import os

        assert any(f.endswith(".pb") for f in os.listdir(tmp_path))


class TestTransformLogDets:
    def test_elementwise_fldj_matches_autodiff(self):
        """Every elementwise transform's forward_log_det_jacobian must
        equal log|f'(x)| computed by autodiff."""
        import jax
        import jax.numpy as jnp

        from paddle_tpu.distribution import (AffineTransform, ExpTransform,
                                             PowerTransform,
                                             SigmoidTransform,
                                             TanhTransform)

        x = np.array([-1.2, -0.3, 0.4, 1.5], "float32")
        cases = [
            (ExpTransform(), x),
            (AffineTransform(t(0.5), t(-2.0)), x),
            (SigmoidTransform(), x),
            (TanhTransform(), x * 0.5),
            (PowerTransform(t(2.0)), np.abs(x) + 0.5),
        ]
        for tr, xv in cases:
            fldj = np.asarray(tr.forward_log_det_jacobian(t(xv)).numpy())
            deriv = jax.vmap(jax.grad(
                lambda v: tr._forward(v)))(jnp.asarray(xv))
            want = np.log(np.abs(np.asarray(deriv)))
            np.testing.assert_allclose(
                fldj, want, rtol=1e-4, atol=1e-5,
                err_msg=type(tr).__name__)

    def test_stickbreaking_fldj_matches_jacobian_det(self):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.distribution import StickBreakingTransform

        tr = StickBreakingTransform()
        x = np.array([0.3, -0.7, 1.1], "float32")
        fldj = float(tr.forward_log_det_jacobian(t(x)).numpy())
        # square Jacobian of the first K outputs (the K+1-th is
        # determined by the simplex constraint)
        jac = jax.jacfwd(lambda v: tr._forward(v)[:-1])(jnp.asarray(x))
        want = float(jnp.linalg.slogdet(jac)[1])
        np.testing.assert_allclose(fldj, want, rtol=1e-4)

    def test_inverse_log_det_is_negative_forward(self):
        from paddle_tpu.distribution import SigmoidTransform

        tr = SigmoidTransform()
        x = np.array([0.2, -1.0], "float32")
        y = tr.forward(t(x))
        ildj = np.asarray(tr.inverse_log_det_jacobian(y).numpy())
        fldj = np.asarray(tr.forward_log_det_jacobian(t(x)).numpy())
        np.testing.assert_allclose(ildj, -fldj, rtol=1e-4)
