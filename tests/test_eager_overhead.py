"""Eager-dispatch overhead budget (VERDICT-r4 item 6).

The reference's dygraph hot loop is generated C++ (eager_gen.py:301);
ours is Python @op_fn dispatch + tape bookkeeping with a deferred,
jit-cached vjp. Budget: grad-mode eager forward must stay within 8x raw
jnp on a small op chain (measured ~1.9-2.7x on this box; the budget
leaves headroom for CI noise while still catching a return of the
per-op-retrace regime, which measured ~37x)."""
import time

import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle

BUDGET_X = 8.0


def _best_of(fn, rounds=3, iters=60):
    fn(); fn()
    best = float("inf")
    for _ in range(rounds):
        t0 = time.perf_counter()
        for _ in range(iters):
            fn()
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


class TestEagerOverheadBudget:
    def test_grad_mode_forward_within_budget(self):
        n = 64
        xw = np.random.default_rng(0).normal(size=(n, n)).astype("float32")
        xj = jnp.asarray(xw)
        t_raw = _best_of(
            lambda: jnp.tanh(xj @ xj + xj).block_until_ready())

        xg = paddle.to_tensor(xw, stop_gradient=False)
        wp = paddle.to_tensor(xw)
        t_g = _best_of(lambda: paddle.tanh(
            paddle.matmul(xg, wp) + xg)._data.block_until_ready())
        assert t_g / t_raw < BUDGET_X, \
            f"eager grad-mode overhead {t_g / t_raw:.1f}x > {BUDGET_X}x"

    def test_deferred_vjp_backward_correct(self):
        # the overhead fix defers vjp to backward through a jit cache —
        # make sure a mixed chain (matmul + add + tanh + mean) still
        # produces the exact jax.grad result, twice (cache-hit path)
        import jax

        xw = np.random.default_rng(1).normal(size=(8, 8)).astype("float32")

        c = jnp.asarray(xw).T   # constant operand (stop_gradient below)

        def jax_ref(x):
            return jnp.mean(jnp.tanh(x @ c + x))

        want = jax.grad(jax_ref)(jnp.asarray(xw))
        for _ in range(2):
            xt = paddle.to_tensor(xw, stop_gradient=False)
            y = paddle.tanh(
                paddle.matmul(xt, paddle.to_tensor(xw).t()) + xt).mean()
            y.backward()
            np.testing.assert_allclose(np.asarray(xt.grad.numpy()), want,
                                       rtol=1e-5, atol=1e-6)

    def test_dropout_deferred_vjp_mask_consistent(self):
        # randomness enters ops via key kwargs; the deferred backward
        # re-executes the forward with the SAME key, so grad must be
        # exactly mask/keep_prob (0/scale pattern matching the output)
        import paddle_tpu.nn.functional as F

        x = paddle.to_tensor(np.ones((64, 64), "float32"),
                             stop_gradient=False)
        y = F.dropout(x, p=0.5, training=True)
        y.sum().backward()
        out = np.asarray(y.numpy())
        g = np.asarray(x.grad.numpy())
        np.testing.assert_allclose(g, np.where(out != 0, 2.0, 0.0))
