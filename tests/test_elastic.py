"""Elastic scale-in + scale-out (VERDICT-r5 item 5).

Reference: fleet/elastic/manager.py:124 — etcd membership watching
re-forms the world between nnodes=min:max. The CI contract here: kill
one of 3 workers mid-training -> the world continues at 2 (resumed from
checkpoint) -> the worker is re-admitted -> world back at 3 -> training
completes, with a loss trajectory CONTINUOUS across all three worlds
(full-batch GD is world-size invariant, so every logged step must match
the single-process oracle).
"""
import os
import re
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "_elastic_worker.py")

TOTAL_STEPS, LR, N, D = 24, 0.1, 12, 4   # mirror _elastic_worker.py


def _oracle():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(N, D)).astype(np.float32)
    w_true = rng.normal(size=(D,)).astype(np.float32)
    Y = X @ w_true
    w = np.zeros(D, np.float32)
    losses = []
    for _ in range(TOTAL_STEPS):
        pred = X @ w
        losses.append(float(np.mean((pred - Y) ** 2)))
        g = 2.0 * X.T @ (pred - Y) / N
        w = w - LR * g
    return losses


@pytest.mark.slow
class TestElasticScaleOut:
    def test_kill_continue_readmit_rescale(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import (
            AdaptiveElasticManager, ElasticStatus)

        log_root = tmp_path / "logs"
        members = tmp_path / "members"
        members.mkdir()
        # Event-driven re-admission: a watcher tails the workerlogs and
        # announces the recovered worker (worker0.up) only after the
        # SHRUNKEN world has demonstrably trained >=2 steps. A wall-clock
        # readmit_after raced under suite load (world-2 launch+compile
        # time varies), readmitting before world 2 logged a step or
        # after it had already finished.
        import threading

        def _announce_when_world2_trains():
            deadline = __import__("time").monotonic() + 420
            while __import__("time").monotonic() < deadline:
                n = 0
                for p in sorted(log_root.glob("run*/workerlog.*")):
                    try:
                        n += len(re.findall(r"STEP run=\d+ world=2 "
                                            r"rank=0", p.read_text()))
                    except OSError:
                        pass
                if n >= 2:
                    (members / "worker0.up").touch()
                    return
                __import__("time").sleep(0.3)

        announcer = threading.Thread(target=_announce_when_world2_trains,
                                     daemon=True)
        announcer.start()
        mgr = AdaptiveElasticManager(max_restarts=6, min_nproc=2,
                                     restart_delay=0.1)
        rc = mgr.run_adaptive(
            WORKER, nproc_per_node=3,
            membership_dir=str(members),
            ckpt_dir=str(tmp_path / "ckpt"),
            log_dir=str(log_root),
            extra_env={"KILL_AT_STEP": "2", "STEP_SLEEP": "0.8",
                       "ELASTIC_TOTAL_STEPS": "24",
                       "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu"})
        announcer.join(timeout=5)
        logs = ""
        for p in sorted(log_root.glob("run*/workerlog.*")):
            logs += p.read_text()
        assert rc == 0, logs[-8000:]

        steps = re.findall(
            r"STEP run=(\d+) world=(\d+) rank=(\d+) step=(\d+) "
            r"loss=([\d.eE+-]+)", logs)
        assert steps, logs[-4000:]
        worlds_seen = [int(w) for _, w, r, _, _ in steps if r == "0"]
        # the three phases: full world, shrunken world, re-grown world
        assert 3 in worlds_seen and 2 in worlds_seen
        assert worlds_seen[-1] == 3, worlds_seen
        # completion happened at the re-grown world
        m = re.findall(r"ELASTIC_DONE run=(\d+) rank=\d+ world=(\d+)",
                       logs)
        assert m and all(w == "3" for _, w in m), m

        # loss continuity: every logged step (any run, any world) must
        # match the single-process oracle at that step index
        oracle = _oracle()
        final_steps = set()
        for run, world, rank, step, loss in steps:
            i = int(step)
            assert abs(float(loss) - oracle[i]) < 1e-4, (
                run, world, i, float(loss), oracle[i])
            final_steps.add(i)
        assert max(final_steps) == TOTAL_STEPS - 1
        # the manager recorded a crash restart AND a scale-out restart
        restarts = [d for _, s, d in mgr.events
                    if s == ElasticStatus.RESTART]
        assert any(d.get("reason") == "scale-out" for d in restarts), \
            mgr.events
        assert any("attempt" in d for d in restarts), mgr.events

    def test_capacity_readmission_logic(self):
        from paddle_tpu.distributed.fleet.elastic import (
            AdaptiveElasticManager)
        import time as _t

        m = AdaptiveElasticManager(readmit_after=0.2)
        assert m._capacity(3, None) == 3
        m._down_times.append(_t.time())
        assert m._capacity(3, None) == 2
        _t.sleep(0.25)
        assert m._capacity(3, None) == 3          # backoff expiry

    def test_capacity_up_file_readmission(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import (
            AdaptiveElasticManager)
        import time as _t

        m = AdaptiveElasticManager()               # no auto-readmit
        m._down_times.append(_t.time())
        assert m._capacity(3, str(tmp_path)) == 2
        (tmp_path / "worker0.up").touch()          # announcement
        assert m._capacity(3, str(tmp_path)) == 3
        # consumed: a second check does not double-credit
        m._down_times.append(_t.time())
        assert m._capacity(3, str(tmp_path)) == 2


# ===========================================================================
# Serving-replica elasticity (ISSUE 13): AdaptiveElasticManager.run_serving
# acts on the autoscale demand signals — scale toward the hint within
# bounds, drain (and only drain-safe replicas are ever stopped), replace
# heartbeat-stale replicas, checkpoint before stopping.
# ===========================================================================

import json
import subprocess
import threading
import time


class _FakeReplica:
    """Controllable demand source with the engine's signal surface."""

    def __init__(self, demand=0.0, drain_safe=True):
        self.demand = demand
        self._drain_safe = drain_safe
        self.draining = False

    def autoscale_payload(self):
        return {"demand_estimate": self.demand,
                "desired_capacity_hint": int(np.ceil(self.demand)),
                "drain_safe": self._drain_safe}

    def begin_drain(self):
        self.draining = True


class TestServingElasticity:
    def test_scales_toward_hint_within_bounds(self):
        from paddle_tpu.distributed.fleet.elastic import (
            AdaptiveElasticManager)

        replicas = {}
        stopped = []

        def spawn(name):
            # the first replica reports the fleet's demand; later ones
            # idle (drain-safe) — the classic scale-out-then-settle
            r = _FakeReplica(demand=2.6 if name == "replica0" else 0.0)
            replicas[name] = r
            return r

        def stop(name, h):
            stopped.append(name)

        mgr = AdaptiveElasticManager()
        done = threading.Event()
        out = {}

        def run():
            out.update(mgr.run_serving(
                spawn, stop, min_replicas=1, max_replicas=3,
                poll_interval=0.01, drain_timeout=2.0, max_ticks=400,
                stop_event=done))

        th = threading.Thread(target=run, daemon=True)
        th.start()
        deadline = time.monotonic() + 5
        while len(replicas) < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert sorted(replicas) == ["replica0", "replica1", "replica2"]
        assert not stopped                      # no premature scale-in
        replicas["replica0"].demand = 0.2       # load fell off
        deadline = time.monotonic() + 5
        while len(stopped) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        done.set()
        th.join(timeout=5)
        # newest drained first, min bound respected
        assert stopped == ["replica2", "replica1"]
        assert out["replicas"] == ["replica0"]
        reasons = [d.get("reason") for _, s, d in mgr.events]
        assert reasons.count("scale-out") == 2
        assert reasons.count("scale-in") == 2
        # every drained replica was told to stop admitting first
        assert replicas["replica1"].draining
        assert replicas["replica2"].draining

    def test_scale_down_waits_for_drain_safe_live_requests(self):
        # acceptance: a replica with a LIVE request held open is never
        # stopped — the controller waits on its drain_safe signal and
        # stops it only after the live decode finishes
        import jax
        from paddle_tpu.distributed.fleet.elastic import (
            AdaptiveElasticManager)
        from paddle_tpu.inference import Request, ServingEngine
        from paddle_tpu.models import llama as L

        cfg = L.llama_tiny()
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        fake = _FakeReplica(demand=1.6)      # forces scale-out to 2
        engines = {}
        stopped = []

        def spawn(name):
            if name == "replica0":
                return fake
            eng = ServingEngine(L, params, cfg, num_slots=2,
                                max_len=32, page_size=4,
                                decode_chunk=2)
            rng = np.random.default_rng(0)
            eng.submit(Request(
                rid=1,
                prompt=rng.integers(0, cfg.vocab_size, (5,))
                .astype(np.int32),
                max_new_tokens=8))
            eng.step()                       # live decode held open
            engines[name] = eng
            return eng

        def stop(name, h):
            stopped.append(name)

        mgr = AdaptiveElasticManager()
        done = threading.Event()

        def run():
            mgr.run_serving(spawn, stop, min_replicas=1,
                            max_replicas=2, poll_interval=0.01,
                            drain_timeout=30.0, max_ticks=100_000,
                            stop_event=done)

        th = threading.Thread(target=run, daemon=True)
        th.start()
        deadline = time.monotonic() + 10
        while "replica1" not in engines and time.monotonic() < deadline:
            time.sleep(0.01)
        eng = engines["replica1"]
        fake.demand = 0.1                    # scale-in wanted now
        deadline = time.monotonic() + 10
        while not eng.draining and time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng.draining                  # drain began...
        time.sleep(0.3)
        assert stopped == []                 # ...but NOT stopped: the
        #                                      live request is open
        assert not eng.autoscale_payload()["drain_safe"]
        eng.run()                            # finish the live decode
        deadline = time.monotonic() + 10
        while not stopped and time.monotonic() < deadline:
            time.sleep(0.01)
        assert stopped == ["replica1"]       # stopped only drain-safe
        assert eng.outputs[1].finish_reason == "completed"
        done.set()
        th.join(timeout=5)

    def test_drain_timeout_never_stops(self):
        from paddle_tpu.distributed.fleet.elastic import (
            AdaptiveElasticManager)

        stuck = _FakeReplica(drain_safe=False)
        stopped = []
        mgr = AdaptiveElasticManager()
        ok = mgr._drain_and_stop(
            "r", stuck,
            signals=lambda n, h: h.autoscale_payload(),
            drain=lambda n, h: h.begin_drain(),
            stop=lambda n, h: stopped.append(n),
            drain_timeout=0.05, poll_interval=0.01)
        assert ok is False and stopped == [] and stuck.draining

    def test_stale_heartbeat_replaced(self, tmp_path):
        from paddle_tpu.distributed import heartbeat
        from paddle_tpu.distributed.fleet.elastic import (
            AdaptiveElasticManager, ElasticStatus)

        hb = str(tmp_path / "hb")
        spawned = []
        stopped = []

        def spawn(name):
            spawned.append(name)
            return _FakeReplica()

        def stop(name, h):
            stopped.append(name)

        # the test beats for every replica EXCEPT replica1 — the wedged
        # one goes stale (never-beat grace = one timeout from spawn)
        beat_stop = threading.Event()

        def beater():
            while not beat_stop.is_set():
                for n in list(spawned):
                    if n != "replica1":
                        heartbeat.touch_named(hb, n)
                time.sleep(0.03)

        threading.Thread(target=beater, daemon=True).start()
        mgr = AdaptiveElasticManager(max_restarts=5)
        done = threading.Event()

        def run():
            mgr.run_serving(spawn, stop, min_replicas=2,
                            max_replicas=3, poll_interval=0.02,
                            heartbeat_dir=hb, heartbeat_timeout=0.25,
                            max_ticks=100_000, stop_event=done)

        th = threading.Thread(target=run, daemon=True)
        th.start()
        deadline = time.monotonic() + 10
        while "replica1" not in stopped and time.monotonic() < deadline:
            time.sleep(0.02)
        done.set()
        beat_stop.set()
        th.join(timeout=5)
        assert "replica1" in stopped          # wedged replica removed
        assert len(spawned) >= 3              # and replaced (min=2)
        details = [d for _, s, d in mgr.events
                   if d.get("reason") == "stale-replace"]
        assert details and details[0]["replica"] == "replica1"
        assert mgr.restarts >= 1              # burned restart budget

    @pytest.mark.faults
    @pytest.mark.chaos
    def test_kill_mid_drain_leaves_committed_checkpoint(self, tmp_path):
        # kill -9 between the drain checkpoint's atomic commit and the
        # replica stop: the parent must find exactly the committed
        # step, restorable — nothing torn, nothing uncommitted
        from paddle_tpu.distributed.checkpoint import CheckpointManager
        from paddle_tpu.testing import faults

        root = str(tmp_path / "ckpt")
        child = (
            "import sys\n"
            "import numpy as np\n"
            "import paddle_tpu as pt\n"
            "from paddle_tpu.distributed.fleet.elastic import (\n"
            "    AdaptiveElasticManager)\n"
            "class H:\n"
            "    def autoscale_payload(self):\n"
            "        return {'drain_safe': True, 'demand_estimate': 0.0}\n"
            "    def begin_drain(self):\n"
            "        pass\n"
            "state = {'w': pt.to_tensor(np.arange(6, dtype='float32')),\n"
            "         'step': 7}\n"
            "mgr = AdaptiveElasticManager()\n"
            "h = H()\n"
            "mgr._drain_and_stop('replica0', h,\n"
            "    signals=lambda n, x: x.autoscale_payload(),\n"
            "    drain=lambda n, x: x.begin_drain(),\n"
            "    stop=lambda n, x: None, drain_timeout=5,\n"
            "    poll_interval=0.01, state_fn=lambda: state,\n"
            "    ckpt_dir=sys.argv[1])\n"
            "print('SURVIVED')\n")
        r = subprocess.run(
            [sys.executable, "-c", child, root],
            capture_output=True, text=True, timeout=300,
            env=dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
                     FLAGS_fault_injection="drain.stop:kill:1"))
        assert r.returncode == faults.KILL_EXIT_CODE, \
            (r.returncode, r.stderr[-800:])
        assert "SURVIVED" not in r.stdout
        mgr = CheckpointManager(root)
        assert mgr.latest_step() == 1          # committed before death
        import paddle_tpu as pt
        target = {"w": pt.to_tensor(np.zeros(6, "float32")), "step": 0}
        assert mgr.restore_latest(target) == 1
        np.testing.assert_array_equal(
            np.asarray(target["w"].numpy()),
            np.arange(6, dtype="float32"))
        assert target["step"] == 7

    def test_committed_drain_excluded_from_capacity(self):
        # review fix: a drain that times out leaves the replica
        # SHEDDING (no un-drain exists) — it must stop counting as
        # capacity, so a demand rise mid-drain spawns a replacement,
        # and the drain keeps retrying until it completes
        from paddle_tpu.distributed.fleet.elastic import (
            AdaptiveElasticManager)

        feeder = _FakeReplica(demand=1.6)        # scale-out to 2
        stuck = []
        spawned = []
        stopped = []

        def spawn(name):
            spawned.append(name)
            if name == "replica1":
                r = _FakeReplica(demand=0.0, drain_safe=False)
                stuck.append(r)                  # drain will hang
                return r
            return feeder if name == "replica0" else _FakeReplica()

        def stop(name, h):
            stopped.append(name)

        mgr = AdaptiveElasticManager()
        done = threading.Event()

        def run():
            mgr.run_serving(spawn, stop, min_replicas=1,
                            max_replicas=3, poll_interval=0.01,
                            drain_timeout=0.05, max_ticks=100_000,
                            stop_event=done)

        th = threading.Thread(target=run, daemon=True)
        th.start()
        deadline = time.monotonic() + 5
        while "replica1" not in spawned and time.monotonic() < deadline:
            time.sleep(0.01)
        feeder.demand = 0.2                      # scale-in replica1...
        deadline = time.monotonic() + 5
        while not (stuck and stuck[0].draining) \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert stuck[0].draining and not stopped  # ...drain committed,
        #                                           times out, no stop
        # let the CROSS-TICK drain deadline pass: the timeout event
        # must record exactly once while the drain keeps retrying
        deadline = time.monotonic() + 5
        while not any(d.get("reason") == "drain-timeout"
                      for _, s, d in mgr.events) \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert not stopped
        feeder.demand = 1.6                      # demand rises mid-drain
        deadline = time.monotonic() + 5
        while len(spawned) < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        # the shedding replica no longer counts: a REPLACEMENT spawned
        assert len(spawned) == 3, spawned
        assert not stopped                       # still never stopped
        stuck[0]._drain_safe = True              # live work finished
        deadline = time.monotonic() + 5
        while "replica1" not in stopped and time.monotonic() < deadline:
            time.sleep(0.01)
        assert stopped == ["replica1"]           # committed drain lands
        done.set()
        th.join(timeout=5)
        reasons = [d.get("reason") for _, s, d in mgr.events]
        assert reasons.count("drain-timeout") == 1   # transition, not
        #                                              one per retry

    def test_stop_event_interrupts_drain_wait(self):
        # review fix: a controller shutdown must not hang behind a
        # drain_timeout-long wait on an undrainable replica
        from paddle_tpu.distributed.fleet.elastic import (
            AdaptiveElasticManager)

        stuck = _FakeReplica(drain_safe=False)
        stopped = []
        mgr = AdaptiveElasticManager()
        ev = threading.Event()
        out = []

        def run():
            out.append(mgr._drain_and_stop(
                "r", stuck,
                signals=lambda n, h: h.autoscale_payload(),
                drain=lambda n, h: h.begin_drain(),
                stop=lambda n, h: stopped.append(n),
                drain_timeout=60.0, poll_interval=0.01,
                stop_event=ev))

        th = threading.Thread(target=run, daemon=True)
        th.start()
        time.sleep(0.1)
        assert th.is_alive()                     # waiting on drain_safe
        ev.set()
        th.join(timeout=2)
        assert not th.is_alive() and out == [False] and stopped == []

    def test_stale_replace_budget_matches_training_semantics(self,
                                                             tmp_path):
        # review fix: the serving stale-replace budget stops at
        # max_restarts like the training paths, not N+1
        from paddle_tpu.distributed.fleet.elastic import (
            AdaptiveElasticManager, ElasticStatus)

        hb = str(tmp_path / "hb")
        os.makedirs(hb)
        spawned = []

        def spawn(name):
            spawned.append(name)
            return _FakeReplica()              # never beats

        stopped = []
        mgr = AdaptiveElasticManager(max_restarts=2)
        out = mgr.run_serving(
            spawn, lambda n, h: stopped.append(n), min_replicas=1,
            max_replicas=2, poll_interval=0.01, heartbeat_dir=hb,
            heartbeat_timeout=0.05, max_ticks=100_000)
        reasons = [d.get("reason") for _, s, d in mgr.events]
        assert reasons.count("stale-replace") == 2    # == budget, not 3
        assert mgr.restarts == 2
        assert any(s == ElasticStatus.ERROR
                   and d.get("reason") == "restart budget exhausted"
                   for _, s, d in mgr.events)
        assert "replicas" in out                      # clean summary

    def test_total_fleet_never_exceeds_max_replicas(self):
        # review fix: a replacement for a committed-but-stuck drain
        # waits for the drain to land rather than pushing the TOTAL
        # fleet (draining included) past max_replicas
        from paddle_tpu.distributed.fleet.elastic import (
            AdaptiveElasticManager)

        feeder = _FakeReplica(demand=1.6)
        stuck = []
        spawned = []
        stopped = []

        def spawn(name):
            spawned.append(name)
            if name == "replica1":
                r = _FakeReplica(demand=0.0, drain_safe=False)
                stuck.append(r)
                return r
            return feeder if name == "replica0" else _FakeReplica()

        mgr = AdaptiveElasticManager()
        done = threading.Event()

        def run():
            mgr.run_serving(spawn, lambda n, h: stopped.append(n),
                            min_replicas=1, max_replicas=2,
                            poll_interval=0.01, drain_timeout=0.05,
                            max_ticks=100_000, stop_event=done)

        th = threading.Thread(target=run, daemon=True)
        th.start()
        deadline = time.monotonic() + 5
        while len(spawned) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        feeder.demand = 0.2                  # trigger the scale-in
        deadline = time.monotonic() + 5
        while not (stuck and stuck[0].draining) \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert stuck and stuck[0].draining
        feeder.demand = 1.6                  # demand high mid-drain
        time.sleep(0.3)                      # would overshoot without
        #                                      the hard bound
        assert len(spawned) == 2, spawned    # fleet held at max (=2)
        stuck[0]._drain_safe = True          # drain lands...
        deadline = time.monotonic() + 5
        while len(spawned) < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(spawned) == 3             # ...THEN the replacement
        assert stopped == ["replica1"]
        done.set()
        th.join(timeout=5)
