"""Elastic scale-in + scale-out (VERDICT-r5 item 5).

Reference: fleet/elastic/manager.py:124 — etcd membership watching
re-forms the world between nnodes=min:max. The CI contract here: kill
one of 3 workers mid-training -> the world continues at 2 (resumed from
checkpoint) -> the worker is re-admitted -> world back at 3 -> training
completes, with a loss trajectory CONTINUOUS across all three worlds
(full-batch GD is world-size invariant, so every logged step must match
the single-process oracle).
"""
import os
import re
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "_elastic_worker.py")

TOTAL_STEPS, LR, N, D = 24, 0.1, 12, 4   # mirror _elastic_worker.py


def _oracle():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(N, D)).astype(np.float32)
    w_true = rng.normal(size=(D,)).astype(np.float32)
    Y = X @ w_true
    w = np.zeros(D, np.float32)
    losses = []
    for _ in range(TOTAL_STEPS):
        pred = X @ w
        losses.append(float(np.mean((pred - Y) ** 2)))
        g = 2.0 * X.T @ (pred - Y) / N
        w = w - LR * g
    return losses


@pytest.mark.slow
class TestElasticScaleOut:
    def test_kill_continue_readmit_rescale(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import (
            AdaptiveElasticManager, ElasticStatus)

        log_root = tmp_path / "logs"
        members = tmp_path / "members"
        members.mkdir()
        # Event-driven re-admission: a watcher tails the workerlogs and
        # announces the recovered worker (worker0.up) only after the
        # SHRUNKEN world has demonstrably trained >=2 steps. A wall-clock
        # readmit_after raced under suite load (world-2 launch+compile
        # time varies), readmitting before world 2 logged a step or
        # after it had already finished.
        import threading

        def _announce_when_world2_trains():
            deadline = __import__("time").monotonic() + 420
            while __import__("time").monotonic() < deadline:
                n = 0
                for p in sorted(log_root.glob("run*/workerlog.*")):
                    try:
                        n += len(re.findall(r"STEP run=\d+ world=2 "
                                            r"rank=0", p.read_text()))
                    except OSError:
                        pass
                if n >= 2:
                    (members / "worker0.up").touch()
                    return
                __import__("time").sleep(0.3)

        announcer = threading.Thread(target=_announce_when_world2_trains,
                                     daemon=True)
        announcer.start()
        mgr = AdaptiveElasticManager(max_restarts=6, min_nproc=2,
                                     restart_delay=0.1)
        rc = mgr.run_adaptive(
            WORKER, nproc_per_node=3,
            membership_dir=str(members),
            ckpt_dir=str(tmp_path / "ckpt"),
            log_dir=str(log_root),
            extra_env={"KILL_AT_STEP": "2", "STEP_SLEEP": "0.8",
                       "ELASTIC_TOTAL_STEPS": "24",
                       "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu"})
        announcer.join(timeout=5)
        logs = ""
        for p in sorted(log_root.glob("run*/workerlog.*")):
            logs += p.read_text()
        assert rc == 0, logs[-8000:]

        steps = re.findall(
            r"STEP run=(\d+) world=(\d+) rank=(\d+) step=(\d+) "
            r"loss=([\d.eE+-]+)", logs)
        assert steps, logs[-4000:]
        worlds_seen = [int(w) for _, w, r, _, _ in steps if r == "0"]
        # the three phases: full world, shrunken world, re-grown world
        assert 3 in worlds_seen and 2 in worlds_seen
        assert worlds_seen[-1] == 3, worlds_seen
        # completion happened at the re-grown world
        m = re.findall(r"ELASTIC_DONE run=(\d+) rank=\d+ world=(\d+)",
                       logs)
        assert m and all(w == "3" for _, w in m), m

        # loss continuity: every logged step (any run, any world) must
        # match the single-process oracle at that step index
        oracle = _oracle()
        final_steps = set()
        for run, world, rank, step, loss in steps:
            i = int(step)
            assert abs(float(loss) - oracle[i]) < 1e-4, (
                run, world, i, float(loss), oracle[i])
            final_steps.add(i)
        assert max(final_steps) == TOTAL_STEPS - 1
        # the manager recorded a crash restart AND a scale-out restart
        restarts = [d for _, s, d in mgr.events
                    if s == ElasticStatus.RESTART]
        assert any(d.get("reason") == "scale-out" for d in restarts), \
            mgr.events
        assert any("attempt" in d for d in restarts), mgr.events

    def test_capacity_readmission_logic(self):
        from paddle_tpu.distributed.fleet.elastic import (
            AdaptiveElasticManager)
        import time as _t

        m = AdaptiveElasticManager(readmit_after=0.2)
        assert m._capacity(3, None) == 3
        m._down_times.append(_t.time())
        assert m._capacity(3, None) == 2
        _t.sleep(0.25)
        assert m._capacity(3, None) == 3          # backoff expiry

    def test_capacity_up_file_readmission(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import (
            AdaptiveElasticManager)
        import time as _t

        m = AdaptiveElasticManager()               # no auto-readmit
        m._down_times.append(_t.time())
        assert m._capacity(3, str(tmp_path)) == 2
        (tmp_path / "worker0.up").touch()          # announcement
        assert m._capacity(3, str(tmp_path)) == 3
        # consumed: a second check does not double-credit
        m._down_times.append(_t.time())
        assert m._capacity(3, str(tmp_path)) == 2
