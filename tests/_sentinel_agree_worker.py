"""Worker for the multi-host skip-agreement test (run via the launch
CLI, not collected by pytest).

Both ranks drive one AnomalySentinel through three observations:

1. rank 0 LOCALLY anomalous, rank 1 healthy — the agreement gather must
   make EVERY rank skip (any-rank-anomalous -> all-ranks-skip), or the
   fleet splits into updated and non-updated halves.
2. both healthy, but with DIFFERENT local grad norms — the gather keeps
   the EMA state host-identical (max norm wins), so the caps fed to the
   next device step agree across the fleet.
3. both healthy again — verdict OK everywhere, identical caps.

Prints one parseable line per observation; the parent test asserts both
ranks printed the same verdicts and bit-identical cap state.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import paddle_tpu.distributed as dist  # noqa: E402
from paddle_tpu.training import sentinel as S  # noqa: E402


def main():
    dist.init_parallel_env()
    rank = dist.get_rank()
    sent = S.AnomalySentinel(S.SentinelConfig(warmup_steps=1,
                                              name="agreetest"))
    # 1: rank 0 anomalous locally -> everyone must skip
    v1 = sent.observe(finite=(rank != 0), grad_norm=float("nan"))
    # 2: healthy, rank-dependent norms -> gather max keeps EMA identical
    v2 = sent.observe(finite=True, grad_norm=1.0 + rank)
    # 3: healthy again
    v3 = sent.observe(finite=True, grad_norm=2.0)
    for i, v in enumerate((v1, v2, v3), 1):
        print(f"VERDICT{i} rank={rank} {v}", flush=True)
    print(f"STATS rank={rank} n={sent.stats.n} "
          f"mean={sent.stats.mean!r} cap={sent.gnorm_cap()!r} "
          f"consecutive={sent.consecutive}", flush=True)


if __name__ == "__main__":
    main()
    sys.exit(0)
