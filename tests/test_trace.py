"""End-to-end tracing + SLO latency + MFU/goodput layer
(monitor/trace.py, monitor/steptimer.py, monitor/mfu.py, the
Histogram quantile estimator, and the serving-engine lifecycle
instrumentation).

The load-bearing contracts:

- the trace ring is BOUNDED (flight records stay small) yet always
  holds the most recent events;
- a firing fault point / preemption leaves a parseable flight record
  (last spans + full metrics snapshot) — including through
  ``os._exit`` kills (subprocess case);
- the ``serving.latency.*`` histograms populate through a REAL
  ServingEngine trace and their interpolated quantiles agree with
  numpy on synthetic data, degrading to the observed max (never
  inf/NaN) under a hostile bucket layout;
- ``serving.tokens.generated - serving.tokens.discarded`` equals the
  tokens actually emitted to clients, preemption or not;
- with the flag off, every seam registers NOTHING;
- every literal metric name registered in code is documented in
  docs/observability.md (drift check).
"""
import importlib.util
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import monitor
from paddle_tpu.monitor import StepTimer, trace
from paddle_tpu.monitor import mfu as mfu_mod
from paddle_tpu.monitor.registry import Histogram
from paddle_tpu.testing import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def mon():
    """Fresh registry + empty trace ring with the flag ON; teardown
    disables BEFORE reset so late finalizers can't re-register."""
    monitor.reset()
    pt.set_flags({"FLAGS_enable_monitor": True})
    yield monitor
    pt.set_flags({"FLAGS_enable_monitor": False})
    # restore the as-imported destination state (an explicit None would
    # mean "disarmed, env ignored" — see set_flight_record_path)
    trace._FLIGHT_PATH[0] = trace._UNSET
    monitor.reset()


# ---------------------------------------------------------------------------
# ring buffer
# ---------------------------------------------------------------------------

class TestRing:
    def test_span_records_duration_and_attrs(self, mon):
        with trace.span("unit.phase", step=3, kind="test"):
            time.sleep(0.001)
        evs = trace.events()
        ev = evs[-1]
        assert ev["name"] == "unit.phase" and ev["ph"] == "X"
        assert ev["dur_ns"] >= 1_000_000
        assert ev["args"] == {"step": 3, "kind": "test"}

    def test_nesting_by_timestamp_containment(self, mon):
        with trace.span("outer"):
            with trace.span("inner"):
                pass
        inner, outer = trace.events()[-2:]
        assert (inner["name"], outer["name"]) == ("inner", "outer")
        assert outer["t_ns"] <= inner["t_ns"]
        assert inner["t_ns"] + inner["dur_ns"] \
            <= outer["t_ns"] + outer["dur_ns"]

    def test_instant(self, mon):
        trace.instant("mark", rid=7)
        ev = trace.events()[-1]
        assert ev["ph"] == "i" and ev["dur_ns"] == 0
        assert ev["args"] == {"rid": 7}

    def test_ring_is_bounded(self, mon):
        trace.clear()
        cap = trace.capacity()
        extra = 64
        for i in range(cap + extra):
            trace.instant("flood", i=i)
        evs = trace.events()
        assert len(evs) == cap                     # bounded
        assert trace.total_events() == cap + extra  # lifetime count
        # and it holds the MOST RECENT events (flight-recorder contract)
        assert evs[0]["args"]["i"] == extra
        assert evs[-1]["args"]["i"] == cap + extra - 1

    def test_reused_span_instance_repairs_t0(self, mon):
        sp = trace.span("reused")
        with sp:
            pass
        with sp:
            pass
        spans = [e for e in trace.events() if e["name"] == "reused"]
        assert len(spans) == 2
        assert spans[1]["t_ns"] > spans[0]["t_ns"]

    def test_off_path_records_nothing(self):
        monitor.reset()
        assert not monitor.enabled()
        with trace.span("off.span", x=1):
            pass
        trace.instant("off.instant")
        assert trace.events() == []
        assert monitor.snapshot() == {}


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_explicit_disarm_overrides_env(self, mon, monkeypatch,
                                           tmp_path):
        """set_flight_record_path(None) disarms even when the env var
        is set — the API always wins over the environment."""
        path = str(tmp_path / "fr.json")
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_RECORD", path)
        assert trace.flight_record_path() == path
        trace.set_flight_record_path(None)
        assert trace.flight_record_path() is None
        assert trace.dump_flight_record() is None
        assert not os.path.exists(path)

    def test_unarmed_dump_is_noop(self, mon, tmp_path):
        trace.set_flight_record_path(None)
        assert os.environ.get("PADDLE_TPU_FLIGHT_RECORD") is None
        assert trace.dump_flight_record() is None

    def test_manual_dump_payload(self, mon, tmp_path):
        path = str(tmp_path / "box.json")
        monitor.inc("manual.counter", 3)
        with trace.span("manual.span"):
            pass
        payload = trace.dump_flight_record(path, reason="manual-test")
        on_disk = json.load(open(path))
        assert on_disk == json.loads(json.dumps(payload))
        assert on_disk["kind"] == "paddle_tpu.flight_record"
        assert on_disk["reason"] == "manual-test"
        assert on_disk["metrics"]["counters"]["manual.counter"] == 3
        assert any(e["name"] == "manual.span" for e in on_disk["events"])

    def test_fault_raise_dumps_black_box(self, mon, tmp_path):
        """A firing raise-action fault point writes the armed flight
        record BEFORE unwinding, with the fault stamped in the ring."""
        path = str(tmp_path / "black_box.json")
        trace.set_flight_record_path(path)
        monitor.inc("pre.crash.work", 11)
        with trace.span("pre.crash.phase"):
            pass
        with faults.injected("checkpoint.write", action="raise"):
            with pytest.raises(faults.FaultInjected):
                faults.hit("checkpoint.write")
        rec = json.load(open(path))
        assert rec["reason"] == "fault:checkpoint.write:raise"
        fired = [e for e in rec["events"] if e["name"] == "fault.fired"]
        assert fired and fired[-1]["args"] == {
            "point": "checkpoint.write", "action": "raise"}
        assert any(e["name"] == "pre.crash.phase" for e in rec["events"])
        assert rec["metrics"]["counters"]["pre.crash.work"] == 11

    def test_preemption_hook_dumps_black_box(self, mon, tmp_path):
        """CheckpointManager.finalize_on_preemption (the SIGTERM hook
        body) writes the black box before finalizing anything."""
        from paddle_tpu.distributed.checkpoint.manager import \
            CheckpointManager
        path = str(tmp_path / "preempt_box.json")
        trace.set_flight_record_path(path)
        mgr = CheckpointManager(str(tmp_path / "root"))
        mgr.save(1, {"w": pt.to_tensor(np.ones((2,), "float32"))})
        trace.instant("about.to.die")
        mgr.finalize_on_preemption(timeout=2.0)
        rec = json.load(open(path))
        assert rec["reason"] == "fault:preemption.sigterm:preempt"
        assert any(e["name"] == "about.to.die" for e in rec["events"])
        assert rec["metrics"]["counters"]["ckpt.saves"] == 1

    def test_kill_fault_leaves_parseable_record(self, tmp_path):
        """Acceptance: a kill (os._exit, no atexit/flushes) at a fault
        point leaves a parseable flight record holding the final spans
        + the full metrics snapshot."""
        path = str(tmp_path / "kill_box.json")
        code = (
            "import paddle_tpu as pt\n"
            "from paddle_tpu import monitor\n"
            "from paddle_tpu.monitor import trace\n"
            "from paddle_tpu.testing import faults\n"
            "pt.set_flags({'FLAGS_enable_monitor': True})\n"
            "monitor.inc('crash.test.counter', 7)\n"
            "monitor.observe('crash.test.ms', 2.5)\n"
            "with trace.span('crash.test.phase', step=3):\n"
            "    trace.instant('crash.test.mark')\n"
            "faults.inject('checkpoint.write', action='kill')\n"
            "faults.hit('checkpoint.write')\n"
            "raise SystemExit('fault did not fire')\n"
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PADDLE_TPU_FLIGHT_RECORD=path)
        env.pop("FLAGS_enable_monitor", None)
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True,
                             timeout=300, cwd=REPO)
        assert out.returncode == faults.KILL_EXIT_CODE, out.stderr[-2000:]
        rec = json.load(open(path))       # parseable despite os._exit
        assert rec["reason"] == "fault:checkpoint.write:kill"
        names = [e["name"] for e in rec["events"]]
        assert "crash.test.phase" in names
        assert "crash.test.mark" in names
        assert "fault.fired" in names
        assert rec["metrics"]["counters"]["crash.test.counter"] == 7
        assert rec["metrics"]["histograms"]["crash.test.ms"]["count"] == 1


# ---------------------------------------------------------------------------
# chrome-trace export
# ---------------------------------------------------------------------------

class TestChromeExport:
    def test_export_shape(self, mon, tmp_path):
        with trace.span("phase.a", step=1):
            pass
        trace.instant("mark.b")
        path = str(tmp_path / "trace.json")
        trace.export_chrome_trace(path, include_profiler=False)
        evs = json.load(open(path))["traceEvents"]
        spans = [e for e in evs if e.get("name") == "phase.a"]
        marks = [e for e in evs if e.get("name") == "mark.b"]
        assert spans and spans[0]["ph"] == "X" and "dur" in spans[0]
        assert spans[0]["ts"] >= 0 and spans[0]["args"] == {"step": 1}
        assert marks and marks[0]["ph"] == "i"

    def test_merges_profiler_host_spans(self, mon, tmp_path):
        from paddle_tpu import profiler
        rec = profiler._get_recorder()
        rec.start()
        with profiler.RecordEvent("host.prof.span"):
            pass
        rec.stop()
        with trace.span("sched.span"):
            pass
        path = str(tmp_path / "merged.json")
        trace.export_chrome_trace(path)
        evs = json.load(open(path))["traceEvents"]
        own = [e for e in evs if e.get("name") == "sched.span"]
        prof = [e for e in evs if e.get("name") == "host.prof.span"]
        assert own and own[0]["pid"] == 0
        assert prof and prof[0]["pid"] == 1     # second process track
        # one timeline: both offsets computed from the shared t0
        assert prof[0]["ts"] >= 0 and own[0]["ts"] >= 0


# ---------------------------------------------------------------------------
# histogram quantiles
# ---------------------------------------------------------------------------

class TestQuantiles:
    def test_matches_numpy_on_uniform_data(self):
        rng = np.random.default_rng(7)
        data = rng.uniform(0.0, 100.0, size=2000)
        h = Histogram("h", buckets=tuple(float(b) for b in range(1, 101)))
        for v in data:
            h.observe(v)
        for q in (0.5, 0.9, 0.99):
            est = h.quantile(q)
            want = float(np.percentile(data, q * 100))
            # interpolation error is bounded by the bucket width (1.0)
            # plus the rank-definition delta; 1.5 covers both
            assert abs(est - want) < 1.5, (q, est, want)

    def test_snapshot_carries_quantiles(self, mon):
        for v in (1.0, 2.0, 3.0, 4.0):
            monitor.observe("q.h", v, buckets=(1.0, 2.0, 4.0, 8.0))
        s = monitor.snapshot()["histograms"]["q.h"]
        for key in ("p50", "p90", "p95", "p99"):
            assert s["min"] <= s[key] <= s["max"]
        assert s["p50"] <= s["p99"]

    def test_below_data_buckets_degrade_to_observed_max(self):
        """Buckets entirely below the data pile everything into +Inf;
        the degraded answer is the observed max — never inf/NaN."""
        h = Histogram("h", buckets=(0.001, 0.01))
        for v in (5.0, 10.0, 20.0):
            h.observe(v)
        for q in (0.01, 0.5, 0.99, 1.0):
            est = h.quantile(q)
            assert np.isfinite(est)
            assert est == 20.0
        snap = h.snapshot()
        assert np.isfinite(snap["p99"]) and snap["p99"] == 20.0

    def test_partial_overflow_clamps_to_observed_range(self):
        h = Histogram("h", buckets=(10.0,))
        h.observe(5.0)
        h.observe(50.0)
        assert h.quantile(0.99) == 50.0          # +Inf bucket -> max
        assert h.quantile(0.25) >= 5.0           # clamped to min
        assert np.isfinite(h.quantile(0.25))

    def test_empty_and_invalid(self):
        h = Histogram("h")
        assert h.quantile(0.5) is None
        assert h.quantiles() == {}
        with pytest.raises(ValueError):
            h.quantile(1.5)

    def test_quantiles_dict_keys(self):
        h = Histogram("h", buckets=(1.0, 2.0))
        h.observe(1.5)
        qs = h.quantiles((0.5, 0.95))
        assert set(qs) == {"p50", "p95"}


# ---------------------------------------------------------------------------
# serving lifecycle -> latency histograms (real engine trace)
# ---------------------------------------------------------------------------

@pytest.mark.serving
class TestServingLatency:
    def _engine(self, **kw):
        import jax
        from paddle_tpu.inference import ServingEngine
        from paddle_tpu.models import llama as L
        cfg = L.llama_tiny()
        params = L.init_params(cfg, jax.random.PRNGKey(3))
        return ServingEngine(L, params, cfg, **kw), cfg

    def _reqs(self, cfg, rng, lens, new):
        from paddle_tpu.inference import Request
        return [Request(rid=i,
                        prompt=rng.integers(0, cfg.vocab_size,
                                            (n,)).astype(np.int32),
                        max_new_tokens=m)
                for i, (n, m) in enumerate(zip(lens, new))]

    def test_lifecycle_populates_slo_histograms(self, mon):
        eng, cfg = self._engine(num_slots=3, max_len=48, page_size=4,
                                decode_chunk=2)
        rng = np.random.default_rng(11)
        reqs = self._reqs(cfg, rng, lens=(3, 7, 5, 9, 4, 6),
                          new=(4, 3, 5, 2, 6, 3))
        outs = eng.run(reqs)
        assert sorted(outs) == [r.rid for r in reqs]
        n = len(reqs)
        reg = monitor.registry()
        ttft = reg.get("serving.latency.ttft_ms")
        e2e = reg.get("serving.latency.e2e_ms")
        qw = reg.get("serving.latency.queue_wait_ms")
        tpot = reg.get("serving.latency.tpot_ms")
        assert ttft.count == n          # one first token per request
        assert e2e.count == n           # one retirement per request
        assert qw.count == n            # one admission per request
        # every request generated >= 2 tokens -> has a decode phase
        assert tpot.count == n
        for h in (ttft, e2e, qw, tpot):
            s = h.snapshot()
            assert s["min"] >= 0 and np.isfinite(s["p99"])
            assert s["p50"] <= s["p99"]
        # e2e covers ttft by construction (same t0, later stamp)
        assert e2e.snapshot()["avg"] >= ttft.snapshot()["avg"]
        # lifecycle milestones landed in the trace ring per request
        names = [(e["name"], e.get("args", {}).get("rid"))
                 for e in trace.events()]
        for r in reqs:
            for ev in ("serving.enqueue", "serving.admit",
                       "serving.first_token", "serving.retire"):
                assert (ev, r.rid) in names
        # no preemption happened, so nothing was discarded and
        # generated == emitted (the easy half of the audit pin; the
        # preemption case below pins the hard half)
        s = eng.stats
        assert s.preempted == 0 and s.tokens_discarded == 0
        assert s.tokens_generated == \
            sum(len(outs[r.rid].tokens) for r in reqs)

    def test_token_invariant_drained_engine(self, mon):
        """generated - discarded == tokens emitted to clients, with
        and without preemption (the double-counting audit pin)."""
        eng, cfg = self._engine(num_slots=2, max_len=16, page_size=4,
                                num_pages=5, decode_chunk=2)
        rng = np.random.default_rng(5)
        reqs = self._reqs(cfg, rng, lens=(4, 4, 4), new=(8, 8, 8))
        outs = eng.run(reqs)
        s = eng.stats
        emitted = sum(len(outs[r.rid].tokens) for r in reqs)
        assert s.preempted >= 1            # tiny pool forces eviction
        assert s.tokens_discarded > 0
        assert s.tokens_generated - s.tokens_discarded == emitted
        # prefill counts the full prompt per ADMISSION (a preempted
        # request re-prefills); every prompt here is 4 tokens
        assert s.tokens_prefilled == s.admitted * 4
        # monitor counters agree with engine stats exactly
        c = monitor.snapshot()["counters"]
        assert c["serving.tokens.generated"] == s.tokens_generated
        assert c["serving.tokens.discarded"] == s.tokens_discarded
        assert c["serving.tokens.prefilled"] == s.tokens_prefilled
        # TTFT: exactly one sample per completed request even though
        # preempted requests prefilled more than once — a discarded
        # run's first token never lands in the histogram
        assert s.admitted > s.completed
        assert monitor.registry().get(
            "serving.latency.ttft_ms").count == s.completed

    def test_engine_off_path_registers_nothing(self):
        monitor.reset()
        assert not monitor.enabled()
        eng, cfg = self._engine(num_slots=2, max_len=32, page_size=4,
                                decode_chunk=2)
        rng = np.random.default_rng(2)
        eng.run(self._reqs(cfg, rng, lens=(3, 4), new=(3, 3)))
        assert monitor.snapshot() == {}
        assert trace.events() == []


# ---------------------------------------------------------------------------
# StepTimer: phase split + goodput
# ---------------------------------------------------------------------------

class TestStepTimer:
    def test_phase_split_and_goodput(self, mon):
        st = StepTimer("unit")
        with st.data_wait():
            time.sleep(0.005)
        with st.compute():
            time.sleep(0.01)
        st.end_step(useful_tokens=1000)
        rep = st.report()
        assert rep["steps"] == 1 and rep["useful_tokens"] == 1000
        assert rep["compute_s"] >= 0.009
        assert rep["data_wait_s"] >= 0.004
        assert rep["goodput_tokens_per_sec"] > 0
        assert 0 < rep["compute_fraction"] <= 1.0
        s = monitor.snapshot()
        assert s["histograms"]["train.step.compute_ms"]["count"] == 1
        assert s["histograms"]["train.step.data_wait_ms"]["count"] == 1
        assert s["histograms"]["train.step.total_ms"]["count"] == 1
        assert s["counters"]["train.tokens.useful"] == 1000
        assert s["gauges"]["train.goodput.tokens_per_sec"] > 0
        assert 0 < s["gauges"]["train.goodput.compute_fraction"] <= 1.0
        # each phase left one span on the step timeline
        names = [e["name"] for e in trace.events()]
        assert "step.compute" in names and "step.data_wait" in names

    def test_iter_data_bills_data_wait(self, mon):
        st = StepTimer("loop")

        def slow_loader():
            for i in range(3):
                time.sleep(0.002)
                yield i

        seen = []
        for item in st.iter_data(slow_loader()):
            with st.compute():
                seen.append(item)
            st.end_step(useful_tokens=10)
        assert seen == [0, 1, 2]
        rep = st.report()
        assert rep["steps"] == 3
        assert rep["data_wait_s"] >= 0.005
        h = monitor.snapshot()["histograms"]["train.step.data_wait_ms"]
        # 3 yields + the StopIteration probe are each one next() wait
        assert h["count"] == 4

    def test_phase_exit_releases_ambient_target(self, mon):
        """A phase context restores the previous ambient target on
        exit: a completed loop's timer must not keep collecting
        ambient time (a checkpoint save after fit returns would bill
        to — and keep alive — a dead timer)."""
        from paddle_tpu.monitor import steptimer as st_mod
        st = StepTimer("loop")
        with st.compute():
            assert getattr(st_mod._ACTIVE, "timer", None) is st
        assert getattr(st_mod._ACTIVE, "timer", None) is not st
        outer = StepTimer("outer")
        with outer:                      # scoped activation nests...
            with st.compute():
                assert st_mod._ACTIVE.timer is st
            assert st_mod._ACTIVE.timer is outer
        # ...and releases when the scope closes
        assert getattr(st_mod._ACTIVE, "timer", None) is not outer

    def test_ambient_checkpoint_billing(self, mon, tmp_path):
        """CheckpointManager.save inside an active timer's scope bills
        its wall time to that timer's checkpoint bucket, without the
        loop threading the timer into the manager."""
        from paddle_tpu.distributed.checkpoint.manager import \
            CheckpointManager
        mgr = CheckpointManager(str(tmp_path / "root"))
        st = StepTimer("fit")
        with st:
            mgr.save(1, {"w": pt.to_tensor(np.ones((64,), "float32"))})
        st.end_step()
        rep = st.report()
        assert rep["checkpoint_s"] > 0
        h = monitor.snapshot()["histograms"]["train.step.checkpoint_ms"]
        assert h["count"] == 1

    def test_standalone_checkpoint_lands_in_histogram(self, mon,
                                                      tmp_path):
        from paddle_tpu.distributed.checkpoint.manager import \
            CheckpointManager
        mgr = CheckpointManager(str(tmp_path / "root"))
        mgr.save(1, {"w": pt.to_tensor(np.ones((8,), "float32"))})
        h = monitor.snapshot()["histograms"]["train.step.checkpoint_ms"]
        assert h["count"] == 1      # ambient orphan timer caught it

    def test_off_path_reports_empty(self):
        monitor.reset()
        assert not monitor.enabled()
        st = StepTimer("off")
        with st.data_wait():
            pass
        with st.compute():
            pass
        st.end_step(useful_tokens=5)
        assert st.report() == {}
        assert monitor.snapshot() == {}


# ---------------------------------------------------------------------------
# MFU accounting
# ---------------------------------------------------------------------------

class TestMFU:
    def test_peak_flops_env_override(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_PEAK_FLOPS", "123.5")
        assert mfu_mod.peak_flops() == 123.5

    def test_peak_flops_cpu_nominal(self, monkeypatch):
        monkeypatch.delenv("PADDLE_TPU_PEAK_FLOPS", raising=False)
        import jax
        dev = jax.devices()[0]
        if dev.platform == "cpu":
            assert mfu_mod.peak_flops(dev) == 1e12

    def test_cost_analysis_flops_shapes(self):
        assert mfu_mod.cost_analysis_flops(None) == 0.0
        assert mfu_mod.cost_analysis_flops({"flops": 32.0}) == 32.0
        assert mfu_mod.cost_analysis_flops(
            [{"flops": 8.0}, {"flops": 4.0}]) == 12.0
        assert mfu_mod.cost_analysis_flops({"flops": -1}) == 0.0
        assert mfu_mod.cost_analysis_flops({"bytes": 9}) == 0.0

    def test_lowered_flops_nonzero_on_matmul(self):
        import jax
        f = jax.jit(lambda x: x @ x)
        x = np.ones((16, 16), np.float32)
        flops = mfu_mod.lowered_flops(f, x)
        assert flops > 0
        # a 16x16 matmul is 2*16^3 = 8192 MACs worth; cost analysis
        # should be in that ballpark, not wildly off
        assert flops >= 2 * 16 ** 3

    def test_mfu_math(self):
        assert mfu_mod.mfu(1e6, 10.0, peak=1e7) == pytest.approx(1.0)
        assert mfu_mod.mfu(0.0, 10.0, peak=1e7) == 0.0
        assert mfu_mod.mfu(1e6, 10.0, peak=0.0) == 0.0

    def test_jit_compile_seam_records_program_flops(self, mon):
        """A to_static cache miss records the compiled program's
        XLA-cost-analysis FLOPs into jit.program.flops."""
        from paddle_tpu import jit

        def f(x):
            return x @ x + 1.0

        sf = jit.to_static(f)
        x = pt.to_tensor(np.ones((8, 8), "float32"))
        sf(x)
        s = monitor.snapshot()
        assert s["counters"].get("jit.program.flops", 0) > 0
        assert s["gauges"].get("jit.program.last_flops", 0) > 0
        before = s["counters"]["jit.program.flops"]
        sf(x)                       # cache hit: no second capture
        after = monitor.snapshot()["counters"]["jit.program.flops"]
        assert after == before

    def test_training_program_counts_backward_flops(self, mon):
        """The grad-path capture lowers the executed vjp composition:
        a training call's recorded FLOPs must exceed the same model's
        forward-only program (backward included, not forward alone)."""
        from paddle_tpu import jit

        def f(x):
            return (x @ x).mean()

        with pt.no_grad():
            jit.to_static(f)(pt.to_tensor(np.ones((8, 8), "float32")))
        fwd = monitor.snapshot()["gauges"]["jit.program.last_flops"]
        assert fwd > 0

        x = pt.to_tensor(np.ones((8, 8), "float32"))
        x.stop_gradient = False
        jit.to_static(f)(x)
        train = monitor.snapshot()["gauges"]["jit.program.last_flops"]
        assert train > fwd


# ---------------------------------------------------------------------------
# docs drift check (tier-1 entry point for scripts/check_metrics_docs.py)
# ---------------------------------------------------------------------------

class TestMetricsDocsDrift:
    def _load(self):
        path = os.path.join(REPO, "scripts", "check_metrics_docs.py")
        spec = importlib.util.spec_from_file_location(
            "check_metrics_docs", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_every_registered_metric_is_documented(self):
        mod = self._load()
        names = mod.registered_names()
        # the scanner must actually find the instrumentation layer
        assert len(names) >= 30, sorted(names)
        assert "serving.latency.ttft_ms" in names
        assert "train.step.total_ms" in names
        assert "jit.program.flops" in names
        assert mod.undocumented() == []

    def test_doc_pattern_shorthands(self, tmp_path):
        mod = self._load()
        doc = tmp_path / "doc.md"
        doc.write_text("| `a.b.hit|miss` | `op.<name>.calls` |\n")
        pats = mod.doc_patterns(str(doc))
        covered = lambda n: any(p.match(n) for p in pats)  # noqa: E731
        assert covered("a.b.hit") and covered("a.b.miss")
        assert covered("op.matmul.calls")
        assert not covered("a.b.evictions")
        assert not covered("op.matmul.calls.extra")
