"""Radix shared-prefix KV cache + n-gram speculative decode.

Covers the two serving latency flags end to end:

- allocator cache holds (`cache_hold`/`cache_release`/`alloc_prefix`)
  and the extended `check_invariants` refcount audit;
- the radix itself: page-aligned match cap, committed-only insert,
  LRU leaf eviction that can never free a live sequence's page;
- engine parity: flags off ⇒ byte-identical scheduling and tokens;
  cache on ⇒ identical tokens with measured hits / prefill shrink;
  spec on ⇒ greedy token identity by construction;
- loadgen trace schema v2 (per-tenant shared prefixes): golden-pinned
  draw sequence, v1 back-compat load, replay determinism cache-on;
- a slow-lane cache-thrash chaos case (eviction + preemption + CoW
  interleavings under a deliberately starved pool).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.inference import Request, ServingEngine
from paddle_tpu.inference.paged import (PageAllocator, PagedKVCache,
                                        PrefixCache)
from paddle_tpu.models import llama as L


@pytest.fixture(scope="module")
def tiny():
    # f32 on purpose: the parity tests compare tokens across
    # differently-shaped programs (full vs shared prefill, turbo chunk
    # vs verify window). The math is identical, but this random tiny
    # model's logit gaps (~5e-3) sit inside bf16 cross-program noise
    # (~2e-3), so bf16 argmax ties can flip with any XLA change. In
    # f32 the noise is ~1e-6 and the identity pin is robust; the bf16
    # pool cast path keeps its coverage in test_paged.py.
    cfg = L.llama_tiny(num_hidden_layers=2, dtype=jnp.float32)
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _prompts_with_prefix(rng, vocab, prefix_len, tails):
    prefix = rng.integers(0, vocab, (prefix_len,)).astype(np.int32)
    return [np.concatenate([prefix, rng.integers(0, vocab, (n,))
                            .astype(np.int32)]) for n in tails]


# ---------------------------------------------------------------------------
# allocator holds + radix (pure host, no compiles)
# ---------------------------------------------------------------------------

class TestAllocatorHolds:
    def test_hold_release_refcount_math(self):
        a = PageAllocator(num_pages=6, page_size=4, max_pages_per_seq=4)
        pages = a.alloc(0, 8)
        a.advance(0, 8)
        a.cache_hold(pages[0])
        a.check_invariants()                 # seq + hold == ref
        with pytest.raises(Exception):
            a.cache_hold(pages[0])           # double hold
        assert a.cache_release(pages[0]) == 0    # seq still holds it
        a.cache_hold(pages[0])
        a.free(0)
        a.check_invariants()                 # hold alone keeps ref == 1
        assert a.cache_release(pages[0]) == 1    # last ref -> freed
        assert a.free_pages == 6
        a.check_invariants()

    def test_alloc_prefix_forks_shared_pages(self):
        a = PageAllocator(num_pages=8, page_size=4, max_pages_per_seq=4)
        pages = a.alloc(0, 12)
        a.advance(0, 12)
        a.alloc_prefix(1, pages[:2], 12)     # fork 2, take 1 fresh
        assert a.seq_pages(1)[:2] == pages[:2]
        assert a._ref[pages[0]] == 2 and a._ref[pages[1]] == 2
        a.check_invariants()
        a.free(1)
        assert a._ref[pages[0]] == 1
        a.check_invariants()
        with pytest.raises(Exception):       # tail page must be fresh
            a.alloc_prefix(2, pages[:3], 12)

    def test_invariants_catch_hold_drift(self):
        a = PageAllocator(num_pages=4, page_size=4, max_pages_per_seq=2)
        a.alloc(0, 4)
        a._cache_hold[a.seq_pages(0)[0]] = 1     # hold without a ref
        with pytest.raises(Exception):
            a.check_invariants()


class TestRadix:
    def _cache(self, num_pages=8, ps=4):
        alloc = PageAllocator(num_pages=num_pages, page_size=ps,
                              max_pages_per_seq=num_pages)
        return alloc, PrefixCache(alloc)

    def test_match_caps_below_full_prompt(self):
        alloc, pc = self._cache()
        toks = np.arange(8, dtype=np.int32)
        pages = alloc.alloc(0, 8)
        alloc.advance(0, 8)
        pc.insert(toks, pages)
        alloc.free(0)
        # exact-length prompt: at least one tail token stays uncached
        n, got = pc.match(toks)
        assert n == 4 and got == pages[:1]
        n, got = pc.match(np.arange(9, dtype=np.int32))
        assert n == 8 and got == pages
        alloc.check_invariants()

    def test_insert_commits_full_pages_only(self):
        alloc, pc = self._cache()
        pages = alloc.alloc(0, 8)
        alloc.advance(0, 6)                  # page 1 half-written
        pc.insert(np.arange(6, dtype=np.int32),
                  alloc.seq_pages(0))
        assert pc._nodes == 1                # only the full page
        alloc.free(0)
        alloc.check_invariants()
        assert alloc.free_pages == 7         # held page stays out

    def test_eviction_skips_live_holders(self):
        alloc, pc = self._cache(num_pages=4)
        toks = np.arange(9, dtype=np.int32)
        pages = alloc.alloc(0, 8)
        alloc.advance(0, 8)
        pc.insert(toks, pages)
        alloc.free(0)
        # a live sequence forks both cached pages
        alloc.alloc_prefix(1, pages, 12)
        assert pc.evict(4) == 0              # nothing evictable
        assert pc.reclaimable() == 0
        alloc.check_invariants()
        alloc.free(1)
        assert pc.reclaimable() == 2
        assert pc.evict(4) == 2              # now they go, LRU first
        alloc.check_invariants()
        assert alloc.free_pages == 4

    def test_lru_prefers_cold_leaves(self):
        alloc, pc = self._cache(num_pages=8)
        a = alloc.alloc(0, 4); alloc.advance(0, 4)
        pc.insert(np.arange(4, dtype=np.int32), a)
        alloc.free(0)
        b = alloc.alloc(1, 4); alloc.advance(1, 4)
        pc.insert(np.arange(100, 104, dtype=np.int32), b)
        alloc.free(1)
        pc.match(np.arange(5, dtype=np.int32))   # refresh A's stamp
        assert pc.evict(1) == 1
        n, _ = pc.match(np.arange(5, dtype=np.int32))
        assert n == 4                        # A survived, B evicted
        alloc.check_invariants()


# ---------------------------------------------------------------------------
# engine parity (jitted; kept tiny — tier-1 budget)
# ---------------------------------------------------------------------------

class TestEnginePrefixCache:
    def test_flags_off_is_inert(self, tiny):
        cfg, params = tiny
        eng = ServingEngine(L, params, cfg, num_slots=2, max_len=32,
                            page_size=4, decode_chunk=3)
        assert eng._prefix is None and not eng._spec_decode
        sd = eng.stats.as_dict()
        for k in ("prefix_lookups", "prefix_hits", "prefix_tokens_saved",
                  "prefix_evictions", "spec_rounds", "spec_drafted",
                  "spec_accepted"):
            assert sd[k] == 0

    def test_cache_on_token_parity_and_hits(self, tiny):
        cfg, params = tiny
        rng = np.random.default_rng(7)
        prompts = _prompts_with_prefix(rng, cfg.vocab_size, 8, (3, 5))

        def run(**kw):
            eng = ServingEngine(L, params, cfg, num_slots=2, max_len=32,
                                page_size=4, decode_chunk=3, **kw)
            outs = {}
            for i, p in enumerate(prompts):  # serial: retire seeds radix
                outs.update(eng.run([Request(rid=i, prompt=p,
                                             max_new_tokens=4)]))
            eng.cache.alloc.check_invariants()
            return eng, outs

        eng_off, outs_off = run()
        eng_on, outs_on = run(prefix_cache=True)
        for i in range(len(prompts)):
            np.testing.assert_array_equal(outs_on[i].tokens,
                                          outs_off[i].tokens)
        assert eng_on.stats.prefix_lookups == 2
        assert eng_on.stats.prefix_hits == 1
        assert eng_on.stats.prefix_tokens_saved == 8
        assert eng_on.stats.tokens_prefilled \
            == eng_off.stats.tokens_prefilled - 8
        # scheduling identical too: same decode-step count both ways
        assert eng_on.stats.decode_steps == eng_off.stats.decode_steps

    @pytest.mark.slow  # tier-1 budget (ISSUE 19 rebalance): pressure sweep; eviction_skips_live_holders +
    # lru_prefers_cold_leaves pin the same rules as units
    def test_eviction_pressure_audit(self, tiny):
        # a pool sized so the radix must be evicted to admit fresh
        # prompts: every admission passes the extended refcount audit
        # and all requests complete with full token counts
        cfg, params = tiny
        rng = np.random.default_rng(11)
        eng = ServingEngine(L, params, cfg, num_slots=1, max_len=16,
                            page_size=4, decode_chunk=2,
                            prefix_cache=True)
        for i in range(6):                   # distinct 8-token prompts
            p = rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)
            out = eng.run([Request(rid=i, prompt=p, max_new_tokens=3)])
            assert len(out[i].tokens) == 3
            eng.cache.alloc.check_invariants()
        assert eng.stats.prefix_evictions > 0


class TestSpecDecode:
    @pytest.mark.slow  # tier-1 budget (ISSUE 19 rebalance): spec greedy identity re-pinned fast by
    # test_quantization's spec composition (engine spec-on == flags-off
    # tokens); the sampled-path guard + golden pins below stay
    def test_greedy_token_identity(self, tiny):
        cfg, params = tiny
        rng = np.random.default_rng(13)
        rep = np.tile(rng.integers(0, cfg.vocab_size, (4,))
                      .astype(np.int32), 3)  # repetitive prompt: the
        # greedy generation goes periodic ~20 tokens in, so a 28-token
        # run exercises real acceptances, not just empty rounds
        rand = rng.integers(0, cfg.vocab_size, (7,)).astype(np.int32)
        for prompt, mnt, want_accept in ((rep, 28, True),
                                         (rand, 14, True)):
            outs = {}
            for spec in (False, True):
                eng = ServingEngine(L, params, cfg, num_slots=1,
                                    max_len=64, page_size=4,
                                    decode_chunk=2, spec_decode=spec)
                outs[spec] = eng.run([Request(rid=0, prompt=prompt,
                                              max_new_tokens=mnt)])
                eng.cache.alloc.check_invariants()
                if spec:
                    assert eng.stats.spec_rounds > 0
                    assert eng.stats.spec_drafted \
                        >= eng.stats.spec_accepted
                    if want_accept:
                        assert eng.stats.spec_accepted > 0
            np.testing.assert_array_equal(outs[True][0].tokens,
                                          outs[False][0].tokens)

    def test_spec_never_fires_for_sampled(self, tiny):
        cfg, params = tiny
        rng = np.random.default_rng(17)
        p = rng.integers(0, cfg.vocab_size, (5,)).astype(np.int32)
        eng = ServingEngine(L, params, cfg, num_slots=1, max_len=48,
                            page_size=4, decode_chunk=2,
                            spec_decode=True)
        eng.run([Request(rid=0, prompt=p, max_new_tokens=12,
                         temperature=0.7,
                         key=jax.random.PRNGKey(3))])
        assert eng.stats.spec_rounds == 0    # sampled ⇒ sequential path


# ---------------------------------------------------------------------------
# loadgen trace schema v2
# ---------------------------------------------------------------------------

class TestTraceV2:
    def _trace(self):
        from paddle_tpu.loadgen import TenantSpec, generate_trace
        return generate_trace(
            4242, duration_s=0.5, rate=24.0,
            tenants=[TenantSpec("sys", share=2.0, prefix_len=8),
                     TenantSpec("raw", share=1.0)],
            prompt_len=(10, 24), max_new_tokens=(3, 6))

    def test_golden_pin(self):
        tr = self._trace()
        assert tr.version == 2
        # the canonical-JSON pin for the v2 schema: any change to the
        # draw sequence, field set, or serialization breaks this hash
        assert tr.sha256() == ("b3772890d45a8ced90637c82c8da9d4a"
                               "19e318ffec2646aec578f890ba5bfc2d")
        assert all(r.prefix_len == 8 for r in tr.requests
                   if r.tenant == "sys")
        assert all(r.prefix_len == 0 for r in tr.requests
                   if r.tenant == "raw")

    def test_prefix_is_derived_not_drawn(self):
        # prefix_len must not consume rng draws: the same seed with
        # and without prefixes yields identical arrivals and lengths
        from paddle_tpu.loadgen import TenantSpec, generate_trace
        tr = self._trace()
        tr0 = generate_trace(
            4242, duration_s=0.5, rate=24.0,
            tenants=[TenantSpec("sys", share=2.0),
                     TenantSpec("raw", share=1.0)],
            prompt_len=(10, 24), max_new_tokens=(3, 6))
        assert [(r.rid, r.arrival_s, r.prompt_len, r.max_new_tokens,
                 r.tenant) for r in tr.requests] \
            == [(r.rid, r.arrival_s, r.prompt_len, r.max_new_tokens,
                 r.tenant) for r in tr0.requests]

    def test_v1_backcompat_load(self):
        import json
        from paddle_tpu.loadgen.traces import ArrivalTrace
        d = json.loads(self._trace().to_json())
        d["version"] = 1
        for r in d["requests"]:
            r.pop("prefix_len")
        v1 = ArrivalTrace.from_json(json.dumps(d))
        assert all(r.prefix_len == 0 for r in v1.requests)

    def test_prefix_tokens_pure_and_disjoint(self):
        from paddle_tpu.loadgen.traces import (prompt_tokens,
                                               tenant_prefix_tokens)
        a = tenant_prefix_tokens(4242, "sys", 8, 64)
        np.testing.assert_array_equal(
            a, tenant_prefix_tokens(4242, "sys", 8, 64))
        assert not np.array_equal(
            a, tenant_prefix_tokens(4242, "raw", 8, 64))
        # distinct stream family from every per-rid prompt stream
        assert not np.array_equal(a, prompt_tokens(4242, 0x70F1, 8, 64))

    def test_replay_prompt_concat(self):
        from paddle_tpu.loadgen.replay import _mk_request
        from paddle_tpu.loadgen.traces import (TraceRequest,
                                               tenant_prefix_tokens)
        tr = TraceRequest(rid=5, arrival_s=0.0, prompt_len=12,
                          max_new_tokens=2, tenant="sys", prefix_len=8)
        req = _mk_request(tr, 4242, 64, honor_deadlines=False)
        assert req.prompt.shape[0] == 12
        np.testing.assert_array_equal(
            req.prompt[:8], tenant_prefix_tokens(4242, "sys", 8, 64))
        assert req.prompt_spec["prefix_len"] == 8
        assert req.prompt_spec["tenant"] == "sys"

    def test_failover_rebuild_matches(self):
        from paddle_tpu.loadgen.replay import (_mk_request,
                                               _rebuild_request)
        from paddle_tpu.loadgen.traces import TraceRequest
        tr = TraceRequest(rid=5, arrival_s=0.0, prompt_len=12,
                          max_new_tokens=2, tenant="sys", prefix_len=8)
        req = _mk_request(tr, 4242, 64, honor_deadlines=False)
        rebuilt = _rebuild_request(
            {"rid": 5, "max_new_tokens": 2, "tenant": "sys",
             "prompt_spec": dict(req.prompt_spec)}, 64, None)
        np.testing.assert_array_equal(rebuilt.prompt, req.prompt)


class TestReplayDeterminism:
    @pytest.mark.slow  # two full replays + warm engine compiles
    def test_same_seed_cache_on(self, tiny):
        cfg, params = tiny
        from paddle_tpu.loadgen import (TenantSpec, build_scorecard,
                                        generate_trace, replay_trace)
        trace = generate_trace(
            77, duration_s=0.3, rate=30.0,
            tenants=[TenantSpec("sys", share=3.0, prefix_len=8),
                     TenantSpec("raw", share=1.0)],
            prompt_len=(10, 20), max_new_tokens=(3, 5))

        def run():
            eng = ServingEngine(L, params, cfg, num_slots=2, max_len=32,
                                page_size=4, decode_chunk=3,
                                prefix_cache=True)
            r = replay_trace(eng, trace, dt_per_step=0.02)
            eng.cache.alloc.check_invariants()
            return r

        r1, r2 = run(), run()
        assert {k: v["tokens"] for k, v in r1.terminal.items()} \
            == {k: v["tokens"] for k, v in r2.terminal.items()}
        card = build_scorecard(r1, include_fleet=False)
        blk = card["deterministic"]["prefix_cache"]
        assert blk["hits"] > 0 and blk["prefill_tokens_saved"] > 0
        blk2 = build_scorecard(r2, include_fleet=False)
        assert blk == blk2["deterministic"]["prefix_cache"]
        assert card["deterministic"]["engine_flags"]["prefix_cache"]


@pytest.mark.slow
class TestCacheThrashChaos:
    @pytest.mark.slow  # tier-1 budget (ISSUE 19 rebalance): interleaving chaos; the radix unit tests +
    # same-seed determinism pin keep the seam fast
    def test_thrash_interleavings(self, tiny):
        # deliberately starved pool + rotating prefix families: every
        # admission round interleaves radix eviction, CoW forks and
        # preemption re-prefills; the audit must hold at every retire
        # and the tokens must match the cache-off run exactly
        cfg, params = tiny
        rng = np.random.default_rng(23)
        fams = [rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)
                for _ in range(3)]
        prompts = [np.concatenate(
            [fams[i % 3],
             rng.integers(0, cfg.vocab_size, (2 + i % 4,))
             .astype(np.int32)]) for i in range(10)]
        reqs = [Request(rid=i, prompt=p, max_new_tokens=4)
                for i, p in enumerate(prompts)]

        def run(**kw):
            eng = ServingEngine(L, params, cfg, num_slots=2, max_len=24,
                                page_size=4, decode_chunk=2, **kw)
            outs = eng.run([Request(rid=r.rid, prompt=r.prompt,
                                    max_new_tokens=r.max_new_tokens)
                            for r in reqs])
            eng.cache.alloc.check_invariants()
            return eng, outs

        eng_off, outs_off = run()
        eng_on, outs_on = run(prefix_cache=True, spec_decode=True)
        for i in range(len(reqs)):
            np.testing.assert_array_equal(outs_on[i].tokens,
                                          outs_off[i].tokens)
        assert eng_on.stats.completed == len(reqs)
