"""paddle.vision.ops + grid_sample/affine_grid tests.

Reference strategy: test/legacy_test/test_nms_op.py, test_roi_align_op.py,
test_grid_sampler_op.py — numpy references on small inputs."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.vision import ops as vops
import paddle_tpu.nn.functional as F


def t(x, sg=True):
    return pt.to_tensor(np.asarray(x), stop_gradient=sg)


class TestNMS:
    def test_basic_suppression(self):
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30]],
                         "float32")
        scores = np.array([0.9, 0.8, 0.7], "float32")
        keep = vops.nms(t(boxes), 0.5, scores=t(scores))
        np.testing.assert_array_equal(np.asarray(keep.numpy()), [0, 2])

    def test_categories_batched(self):
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11]], "float32")
        scores = np.array([0.9, 0.8], "float32")
        cats = np.array([0, 1], "int64")
        keep = vops.nms(t(boxes), 0.5, scores=t(scores),
                        category_idxs=t(cats), categories=[0, 1])
        assert len(keep.numpy()) == 2     # different classes: both kept

    def test_top_k(self):
        boxes = np.array([[0, 0, 1, 1], [5, 5, 6, 6], [9, 9, 10, 10]],
                         "float32")
        scores = np.array([0.1, 0.9, 0.5], "float32")
        keep = vops.nms(t(boxes), 0.5, scores=t(scores), top_k=2)
        np.testing.assert_array_equal(np.asarray(keep.numpy()), [1, 2])


class TestRoIOps:
    def test_roi_align_uniform_image(self):
        x = np.ones((1, 2, 8, 8), "float32")
        boxes = np.array([[0, 0, 4, 4]], "float32")
        out = vops.roi_align(t(x), t(boxes), t(np.array([1], "int32")), 2)
        assert out.shape == [1, 2, 2, 2]
        np.testing.assert_allclose(out.numpy(), np.ones((1, 2, 2, 2)),
                                   rtol=1e-5)

    def test_roi_align_gradient_flows(self):
        x = t(np.random.randn(1, 1, 8, 8).astype("float32"), sg=False)
        boxes = t(np.array([[1, 1, 6, 6]], "float32"))
        out = vops.roi_align(x, boxes, t(np.array([1], "int32")), 2)
        out.sum().backward()
        assert np.isfinite(x.grad.numpy()).all()
        assert np.abs(x.grad.numpy()).sum() > 0

    def test_roi_align_linear_ramp(self):
        # value = column index; aligned bilinear average of a linear ramp
        # equals the ramp at bin centers
        xv = np.tile(np.arange(8, dtype="float32")[None, None, None, :],
                     (1, 1, 8, 1))
        boxes = np.array([[0, 0, 7, 7]], "float32")
        out = vops.roi_align(t(xv), t(boxes), t(np.array([1], "int32")),
                             output_size=7, sampling_ratio=1,
                             aligned=False)
        got = out.numpy()[0, 0, 3]        # middle row
        assert got[0] < got[-1]
        np.testing.assert_allclose(np.diff(got), np.diff(got)[0] *
                                   np.ones(6), rtol=1e-3)

    def test_roi_pool_max(self):
        x = np.zeros((1, 1, 8, 8), "float32")
        x[0, 0, 2, 2] = 5.0
        x[0, 0, 6, 6] = 7.0
        boxes = np.array([[0, 0, 7, 7]], "float32")
        out = vops.roi_pool(t(x), t(boxes), t(np.array([1], "int32")), 2)
        np.testing.assert_allclose(out.numpy()[0, 0],
                                   [[5.0, 0.0], [0.0, 7.0]])

    def test_psroi_pool_shapes(self):
        x = np.random.randn(1, 2 * 2 * 3, 8, 8).astype("float32")
        boxes = np.array([[0, 0, 7, 7]], "float32")
        out = vops.psroi_pool(t(x), t(boxes), t(np.array([1], "int32")), 2)
        assert out.shape == [1, 3, 2, 2]

    def test_roi_layers(self):
        x = t(np.random.randn(1, 2, 8, 8).astype("float32"))
        boxes = t(np.array([[0, 0, 4, 4]], "float32"))
        bn = t(np.array([1], "int32"))
        assert vops.RoIAlign(2)(x, boxes, bn).shape == [1, 2, 2, 2]
        assert vops.RoIPool(2)(x, boxes, bn).shape == [1, 2, 2, 2]


class TestBoxOps:
    def test_box_coder_roundtrip(self):
        priors = np.array([[0, 0, 10, 10], [5, 5, 20, 25]], "float32")
        targets = np.array([[1, 1, 12, 9], [4, 6, 22, 30]], "float32")
        enc = vops.box_coder(t(priors), [1.0, 1.0, 1.0, 1.0], t(targets),
                             code_type="encode_center_size")
        # decode the diagonal (each target against its own prior)
        deltas = np.asarray(enc.numpy())[np.arange(2), np.arange(2)]
        dec = vops.box_coder(t(priors), [1.0, 1.0, 1.0, 1.0],
                             t(deltas), code_type="decode_center_size")
        np.testing.assert_allclose(np.asarray(dec.numpy()), targets,
                                   rtol=1e-4, atol=1e-3)

    def test_prior_box(self):
        feat = t(np.zeros((1, 8, 4, 4), "float32"))
        img = t(np.zeros((1, 3, 32, 32), "float32"))
        boxes, var = vops.prior_box(feat, img, min_sizes=[8.0],
                                    aspect_ratios=[2.0], clip=True)
        assert boxes.shape[:2] == [4, 4] and boxes.shape[-1] == 4
        b = boxes.numpy()
        assert b.min() >= 0.0 and b.max() <= 1.0
        assert var.shape == boxes.shape

    def test_yolo_box_shapes(self):
        na, nc, h, w = 2, 3, 4, 4
        x = t(np.random.randn(1, na * (5 + nc), h, w).astype("float32"))
        img = t(np.array([[64, 64]], "int32"))
        boxes, scores = vops.yolo_box(x, img, anchors=[10, 13, 16, 30],
                                      class_num=nc, conf_thresh=0.0,
                                      downsample_ratio=16)
        assert boxes.shape == [1, na * h * w, 4]
        assert scores.shape == [1, na * h * w, nc]
        assert float(boxes.numpy().max()) <= 64.0

    def test_distribute_fpn(self):
        rois = np.array([[0, 0, 16, 16],      # small -> low level
                         [0, 0, 200, 200]], "float32")
        outs, restore, nums = vops.distribute_fpn_proposals(
            t(rois), 2, 5, 4, 224)
        sizes = [len(o.numpy()) for o in outs]
        assert sum(sizes) == 2 and sizes[0] >= 1
        r = np.asarray(restore.numpy()).reshape(-1)
        order = np.concatenate([o.numpy() for o in outs if len(o.numpy())])
        np.testing.assert_allclose(order[r], rois)


class TestGridSample:
    def test_identity_grid(self):
        x = np.random.randn(1, 2, 5, 5).astype("float32")
        ys, xs = np.meshgrid(np.linspace(-1, 1, 5), np.linspace(-1, 1, 5),
                             indexing="ij")
        grid = np.stack([xs, ys], -1)[None].astype("float32")
        out = F.grid_sample(t(x), t(grid))
        np.testing.assert_allclose(out.numpy(), x, rtol=1e-5, atol=1e-5)

    def test_zeros_padding(self):
        x = np.ones((1, 1, 4, 4), "float32")
        grid = np.full((1, 1, 1, 2), -3.0, "float32")   # far outside
        out = F.grid_sample(t(x), t(grid))
        np.testing.assert_allclose(out.numpy(), 0.0)

    def test_border_padding(self):
        x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
        grid = np.full((1, 1, 1, 2), 5.0, "float32")
        out = F.grid_sample(t(x), t(grid), padding_mode="border")
        np.testing.assert_allclose(out.numpy().ravel(), [15.0])

    def test_nearest_mode(self):
        x = np.arange(4, dtype="float32").reshape(1, 1, 2, 2)
        grid = np.array([[[[1.0, 1.0]]]], "float32")
        out = F.grid_sample(t(x), t(grid), mode="nearest")
        np.testing.assert_allclose(out.numpy().ravel(), [3.0])

    def test_affine_grid_identity(self):
        theta = np.array([[[1.0, 0, 0], [0, 1.0, 0]]], "float32")
        grid = F.affine_grid(t(theta), [1, 1, 3, 3])
        assert grid.shape == [1, 3, 3, 2]
        np.testing.assert_allclose(grid.numpy()[0, 0, 0], [-1, -1],
                                   atol=1e-6)
        np.testing.assert_allclose(grid.numpy()[0, 2, 2], [1, 1],
                                   atol=1e-6)

    def test_grid_sample_grad(self):
        x = t(np.random.randn(1, 1, 4, 4).astype("float32"), sg=False)
        grid = t(np.zeros((1, 2, 2, 2), "float32"), sg=False)
        out = F.grid_sample(x, grid)
        out.sum().backward()
        assert np.isfinite(x.grad.numpy()).all()
        assert np.isfinite(grid.grad.numpy()).all()


class TestDeformConv:
    def test_zero_offset_matches_conv(self):
        import jax.numpy as jnp
        rng = np.random.default_rng(0)
        x = rng.normal(size=(1, 2, 6, 6)).astype("float32")
        w = rng.normal(size=(3, 2, 3, 3)).astype("float32")
        oh = ow = 6
        offset = np.zeros((1, 2 * 3 * 3, oh, ow), "float32")
        out = vops.deform_conv2d(t(x), t(offset), t(w), padding=1)
        # reference: plain conv with same padding
        import jax
        ref = jax.lax.conv_general_dilated(
            jnp.asarray(x), jnp.asarray(w), (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        np.testing.assert_allclose(out.numpy(), np.asarray(ref),
                                   rtol=1e-3, atol=1e-4)

    def test_deform_layer(self):
        layer = vops.DeformConv2D(2, 4, 3, padding=1)
        x = t(np.random.randn(1, 2, 6, 6).astype("float32"))
        offset = t(np.zeros((1, 2 * 9, 6, 6), "float32"))
        out = layer(x, offset)
        assert out.shape == [1, 4, 6, 6]


class TestReviewRegressions:
    def test_psroi_pool_values(self):
        """Each output bin (i, j) must read channel group i*pw+j."""
        ph = pw = 2
        out_c = 3
        c = out_c * ph * pw
        # channel value = its group index g (constant map per channel)
        x = np.zeros((1, c, 8, 8), "float32")
        for g in range(ph * pw):
            x[0, g * out_c:(g + 1) * out_c] = g + 1
        # NOTE paddle layout: groups consecutive? reference uses
        # channel = (g * out_c + oc); we fill accordingly
        x = np.zeros((1, c, 8, 8), "float32")
        for g in range(ph * pw):
            for oc in range(out_c):
                x[0, g * out_c + oc] = 10 * g + oc
        boxes = np.array([[0, 0, 7, 7]], "float32")
        out = vops.psroi_pool(t(x), t(boxes), t(np.array([1], "int32")), 2)
        o = np.asarray(out.numpy())  # [1, out_c, 2, 2]
        for i in range(ph):
            for j in range(pw):
                g = i * pw + j
                for oc in range(out_c):
                    # reshape(r, ph*pw, out_c, ...) maps group g, chan oc
                    # to input channel g*out_c+oc with value 10g+oc
                    np.testing.assert_allclose(o[0, oc, i, j],
                                               10 * g + oc, rtol=1e-5)

    def test_grid_sample_reflection_not_align_corners(self):
        # reference semantics: reflect about pixel borders (-0.5, size-0.5)
        x = np.arange(4, dtype="float32").reshape(1, 1, 1, 4)
        # gx=-3.0 unnormalized for size=4, align_corners=False:
        # coord = ((-3+1)*4-1)/2 = -4.5 -> reflect -> ...
        grid = np.zeros((1, 1, 1, 2), "float32")
        grid[..., 0] = -3.0
        grid[..., 1] = 0.0
        out = F.grid_sample(t(x), t(grid), padding_mode="reflection",
                            align_corners=False)
        # unnormalized x = -4.5; reflect about [-0.5, 3.5]: |x-lo|=4 mod 8
        # = 4 >= span -> 8-4=4 -> +lo = 3.5 -> clip 3 -> value 3
        np.testing.assert_allclose(out.numpy().ravel(), [3.0], atol=1e-5)

    def test_deform_conv_dilation_used(self):
        rng = np.random.default_rng(1)
        x = rng.normal(size=(1, 1, 9, 9)).astype("float32")
        w = rng.normal(size=(1, 1, 3, 3)).astype("float32")
        offset = np.zeros((1, 18, 5, 5), "float32")
        out = vops.deform_conv2d(t(x), t(offset), t(w), padding=0,
                                 dilation=2)
        import jax
        import jax.numpy as jnp
        ref = jax.lax.conv_general_dilated(
            jnp.asarray(x), jnp.asarray(w), (1, 1), [(0, 0), (0, 0)],
            rhs_dilation=(2, 2),
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        assert out.shape == list(np.asarray(ref).shape)
        np.testing.assert_allclose(out.numpy(), np.asarray(ref),
                                   rtol=1e-3, atol=1e-4)

    def test_yolo_iou_aware_raises(self):
        x = t(np.zeros((1, 2 * 8, 4, 4), "float32"))
        with pytest.raises(NotImplementedError, match="iou_aware"):
            vops.yolo_box(x, t(np.array([[64, 64]], "int32")),
                          anchors=[10, 13, 16, 30], class_num=3,
                          iou_aware=True)

    def test_box_coder_decode_axis0(self):
        # axis=0 (the Paddle default): PriorBox [M,4] broadcasts to
        # [1, M, 4] against TargetBox [N, M, 4].
        priors = np.array([[0, 0, 10, 10], [5, 5, 20, 25]], "float32")
        deltas = np.zeros((3, 2, 4), "float32")   # N=3 targets, M=2 priors
        dec = vops.box_coder(t(priors), [1, 1, 1, 1], t(deltas),
                             code_type="decode_center_size", axis=0)
        assert dec.shape == [3, 2, 4]
        # zero deltas decode back to the priors themselves
        for nidx in range(3):
            np.testing.assert_allclose(np.asarray(dec.numpy())[nidx],
                                       priors, rtol=1e-5)

    def test_box_coder_decode_axis1(self):
        # axis=1: PriorBox [N,4] broadcasts to [N, 1, 4] against
        # TargetBox [N, M, 4] (priors align with target dim 0).
        priors = np.array([[0, 0, 10, 10], [5, 5, 20, 25],
                           [2, 2, 6, 8]], "float32")
        deltas = np.zeros((3, 4, 4), "float32")   # N=3, M=4
        dec = vops.box_coder(t(priors), [1, 1, 1, 1], t(deltas),
                             code_type="decode_center_size", axis=1)
        assert dec.shape == [3, 4, 4]
        # zero deltas decode each row back to its own prior
        for midx in range(4):
            np.testing.assert_allclose(np.asarray(dec.numpy())[:, midx],
                                       priors, rtol=1e-5)

    def test_prior_box_default_order(self):
        feat = t(np.zeros((1, 8, 1, 1), "float32"))
        img = t(np.zeros((1, 3, 32, 32), "float32"))
        boxes, _ = vops.prior_box(feat, img, min_sizes=[8.0],
                                  max_sizes=[16.0], aspect_ratios=[2.0])
        b = np.asarray(boxes.numpy())[0, 0]   # [nb, 4]
        widths = (b[:, 2] - b[:, 0]) * 32
        # default order: min(8), ar=2 (w=8*sqrt2), max(sqrt(8*16)=11.3)
        np.testing.assert_allclose(widths[0], 8.0, rtol=1e-4)
        np.testing.assert_allclose(widths[1], 8 * np.sqrt(2), rtol=1e-4)
        np.testing.assert_allclose(widths[2], np.sqrt(8 * 16), rtol=1e-4)

    def test_pass_manager_dce_requires_fetch(self):
        from paddle_tpu import static
        main = static.Program()
        with static.program_guard(main):
            x = static.data("xg", [2], "float32")
            y = pt.exp(x)
        with pytest.raises(ValueError, match="fetch"):
            static.PassManager(["dce"]).run(main)
