"""Typed collective fault layer (ISSUE 14): deadline loop, failed-rank
attribution, tombstone/abort fast paths, env knobs, and the elastic
manager's peer-failure rc mapping — all fast-lane (a dict-backed FakeKV
stands in for the coordination service; no subprocesses). The real
2-process kill -9 chaos pin lives in tests/test_rank_loss_chaos.py
(slow lane) and scripts/tpu_smoke.py's ``rank_kill_resume`` stage.
"""
import threading
import time

import pytest

import paddle_tpu as pt
from paddle_tpu import monitor
from paddle_tpu.distributed import collective as coll
from paddle_tpu.distributed import heartbeat as hb
from paddle_tpu.testing import faults


class FakeKV:
    """Dict-backed stand-in for the coordination-service client (same
    contract as tests/test_heartbeat_kv.py)."""

    def __init__(self):
        self.d = {}

    def key_value_set(self, k, v, allow_overwrite=False):
        if not allow_overwrite and k in self.d:
            raise RuntimeError(f"key exists: {k}")
        self.d[k] = v

    def key_value_try_get(self, k):
        if k not in self.d:
            raise KeyError(k)
        return self.d[k]

    def key_value_delete(self, k):
        self.d.pop(k, None)


@pytest.fixture
def no_markers(monkeypatch, tmp_path):
    """Isolate marker transports: a private heartbeat dir and gen 0."""
    monkeypatch.setenv("PADDLE_HEARTBEAT_DIR", str(tmp_path / "hb"))
    monkeypatch.delenv("PADDLE_ELASTIC_RUN", raising=False)
    return str(tmp_path / "hb")


def _want(tag, world):
    return {r: f"ag_{tag}_{r}" for r in range(world)}


class TestWaitForKeys:
    def test_all_resolved_returns_values(self, no_markers):
        kv = FakeKV()
        for r in range(3):
            kv.key_value_set(f"ag_t_{r}", f"v{r}")
        got = coll._wait_for_keys(kv, op="all_gather_object", tag="t",
                                  want=_want("t", 3), world=3,
                                  timeout_s=1.0)
        assert got == {0: "v0", 1: "v1", 2: "v2"}

    def test_late_key_resolves_within_deadline(self, no_markers):
        kv = FakeKV()
        kv.key_value_set("ag_t_0", "v0")
        threading.Timer(
            0.15, lambda: kv.key_value_set("ag_t_1", "v1")).start()
        got = coll._wait_for_keys(kv, op="all_gather_object", tag="t",
                                  want=_want("t", 2), world=2,
                                  timeout_s=10.0)
        assert got[1] == "v1"

    def test_timeout_names_exactly_the_missing_ranks(self, no_markers):
        kv = FakeKV()
        kv.key_value_set("ag_t_0", "v0")
        kv.key_value_set("ag_t_2", "v2")
        t0 = time.monotonic()
        with pytest.raises(coll.CollectiveTimeout) as ei:
            coll._wait_for_keys(kv, op="all_gather_object", tag="t",
                                want=_want("t", 4), world=4,
                                timeout_s=0.2)
        e = ei.value
        assert e.missing_ranks == [1, 3]
        assert e.op == "all_gather_object" and e.world == 4
        assert e.elapsed_s >= 0.2 and time.monotonic() - t0 < 5
        # the rendered message carries the attribution an operator greps
        assert "rank(s) [1, 3]" in str(e) and "tag=t" in str(e)
        # typed family: ExecutionTimeoutError -> TimeoutError builtin
        assert isinstance(e, TimeoutError)

    def test_tombstone_fast_path_beats_the_deadline(self, no_markers):
        kv = FakeKV()
        kv.key_value_set("ag_t_0", "v0")
        hb.mark_dead(1, "worker exited rc=137", dir_path=no_markers,
                     generation=0)
        t0 = time.monotonic()
        with pytest.raises(coll.PeerLostError) as ei:
            coll._wait_for_keys(kv, op="all_gather_object", tag="t",
                                want=_want("t", 2), world=2, me=0,
                                timeout_s=30.0)
        elapsed = time.monotonic() - t0
        assert elapsed < 5.0, "tombstone did not short-circuit the wait"
        e = ei.value
        assert e.lost_ranks == [1]
        assert "rc=137" in e.reasons[1]
        assert isinstance(e, RuntimeError)   # UnavailableError family

    def test_tombstone_kv_transport(self, no_markers):
        kv = FakeKV()
        kv.key_value_set("ag_t_0", "v0")
        hb.mark_dead(1, "kv-only death", dir_path=None, client=kv,
                     generation=0)
        # markers ride the SAME client the wait polls — no filesystem
        with pytest.raises(coll.PeerLostError):
            coll._wait_for_keys(kv, op="barrier", tag="t",
                                want=_want("t", 2), world=2, me=0,
                                timeout_s=30.0)

    def test_stale_generation_tombstone_is_ignored(self, no_markers,
                                                   monkeypatch):
        kv = FakeKV()
        hb.mark_dead(1, "previous world", dir_path=no_markers,
                     generation=0)
        monkeypatch.setenv("PADDLE_ELASTIC_RUN", "1")  # restarted world
        with pytest.raises(coll.CollectiveTimeout):
            coll._wait_for_keys(kv, op="barrier", tag="t",
                                want=_want("t", 2), world=2, me=0,
                                timeout_s=0.2)

    def test_abort_marker_fails_peers_fast(self, no_markers):
        kv = FakeKV()
        kv.key_value_set("ag_t_0", "v0")
        kv.key_value_set("ag_t_1", "v1")
        # rank 2 aborted in a DIFFERENT exchange; this wait still has
        # rank 2's key pending -> marker observed, typed, attributed
        hb.write_abort_marker(2, {"reason": "CollectiveTimeout: ..."},
                              dir_path=no_markers, generation=0)
        t0 = time.monotonic()
        with pytest.raises(coll.PeerLostError) as ei:
            coll._wait_for_keys(kv, op="all_gather_object", tag="t",
                                want=_want("t", 3), world=3, me=0,
                                timeout_s=30.0)
        assert time.monotonic() - t0 < 5.0
        assert ei.value.lost_ranks == [2]
        assert "abort" in ei.value.reasons[2]

    def test_own_abort_marker_does_not_self_trigger(self, no_markers):
        kv = FakeKV()
        hb.write_abort_marker(0, {"reason": "me"}, dir_path=no_markers,
                              generation=0)
        with pytest.raises(coll.CollectiveTimeout):
            coll._wait_for_keys(kv, op="barrier", tag="t",
                                want=_want("t", 2), world=2, me=0,
                                timeout_s=0.2)

    def test_sustained_transport_outage_raises_unavailable(
            self, no_markers, monkeypatch):
        # 'key not present' and 'coordination service unreachable' are
        # different failures: a dead coordinator must surface typed
        # (and WITHOUT blaming live peers) instead of burning the
        # whole deadline
        from paddle_tpu.core import enforce as E

        class DeadKV:
            def key_value_try_get(self, k):
                raise ConnectionError("coordinator unreachable")

        monkeypatch.setattr(coll, "_TRANSPORT_FAIL_S", 0.3)
        t0 = time.monotonic()
        with pytest.raises(E.UnavailableError) as ei:
            coll._wait_for_keys(DeadKV(), op="barrier", tag="t",
                                want=_want("t", 2), world=2, me=0,
                                timeout_s=60.0)
        assert time.monotonic() - t0 < 10.0
        assert "coordination service unreachable" in str(ei.value)
        assert not isinstance(ei.value, coll.PeerLostError)

    def test_absent_shaped_errors_do_not_trip_the_outage_clock(
            self, no_markers, monkeypatch):
        monkeypatch.setattr(coll, "_TRANSPORT_FAIL_S", 0.05)
        kv = FakeKV()   # raises KeyError for absent keys: normal block
        with pytest.raises(coll.CollectiveTimeout):
            coll._wait_for_keys(kv, op="barrier", tag="t",
                                want=_want("t", 2), world=2, me=0,
                                timeout_s=0.3)

    def test_kv_get_fault_point(self, no_markers):
        kv = FakeKV()
        kv.key_value_set("ag_t_0", "v0")
        with faults.injected("collective.kv_get", action="raise"):
            with pytest.raises(faults.FaultInjected):
                coll._wait_for_keys(kv, op="barrier", tag="t",
                                    want=_want("t", 1), world=1,
                                    timeout_s=1.0)


class TestKnobs:
    def test_env_override_parses(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_COLL_TIMEOUT_S", "5.5")
        assert coll.coll_timeout_s() == 5.5

    def test_default_and_bad_values_fall_back(self, monkeypatch):
        monkeypatch.delenv("PADDLE_TPU_COLL_TIMEOUT_S", raising=False)
        assert coll.coll_timeout_s() == coll.DEFAULT_COLL_TIMEOUT_S == 60.0
        monkeypatch.setenv("PADDLE_TPU_COLL_TIMEOUT_S", "garbage")
        assert coll.coll_timeout_s() == 60.0
        monkeypatch.setenv("PADDLE_TPU_COLL_TIMEOUT_S", "-3")
        assert coll.coll_timeout_s() == 60.0
        monkeypatch.setenv("PADDLE_TPU_COLL_TIMEOUT_S", "0")
        assert coll.coll_timeout_s() == 60.0

    def test_backoff_doubles_and_caps(self):
        d = coll._BACKOFF_FLOOR_S
        seen = []
        for _ in range(12):
            seen.append(d)
            d = coll._next_delay(d)
        assert seen[0] == pytest.approx(0.002)
        assert seen[1] == pytest.approx(0.004)
        assert max(seen) == pytest.approx(coll._BACKOFF_CAP_S)
        assert seen[-1] == seen[-2] == pytest.approx(0.1)  # capped

    def test_wait_uses_env_budget(self, monkeypatch, no_markers):
        monkeypatch.setenv("PADDLE_TPU_COLL_TIMEOUT_S", "0.15")
        kv = FakeKV()
        t0 = time.monotonic()
        with pytest.raises(coll.CollectiveTimeout) as ei:
            coll._wait_for_keys(kv, op="barrier", tag="t",
                                want=_want("t", 2), world=2)
        assert 0.1 < time.monotonic() - t0 < 5.0
        assert ei.value.timeout_s == pytest.approx(0.15)


class TestMetrics:
    def test_timeout_and_wait_ms_counted(self, no_markers):
        monitor.reset()
        pt.set_flags({"FLAGS_enable_monitor": True})
        try:
            kv = FakeKV()
            kv.key_value_set("ag_t_0", "v0")
            with pytest.raises(coll.CollectiveTimeout):
                coll._wait_for_keys(kv, op="all_gather_object", tag="t",
                                    want=_want("t", 2), world=2,
                                    timeout_s=0.1)
            hb.mark_dead(1, "dead", dir_path=None, client=kv,
                         generation=0)
            with pytest.raises(coll.PeerLostError):
                coll._wait_for_keys(kv, op="all_gather_object", tag="t2",
                                    want={1: "ag_t2_1"}, world=2, me=0,
                                    timeout_s=5.0)
            snap = monitor.snapshot()
            assert snap["counters"]["dist.collective.timeouts"] == 1
            assert snap["counters"]["dist.collective.peer_lost"] == 1
            assert snap["histograms"]["dist.collective.wait_ms"][
                "count"] == 2
        finally:
            pt.set_flags({"FLAGS_enable_monitor": False})
            monitor.reset()


class TestObjectCollectivePaths:
    """The exchange surfaces route through the typed wait (FakeKV +
    patched env; world-size-1 semantics byte-identical is covered by
    the existing test_distributed/test_comms_roofline suites)."""

    @pytest.fixture
    def fake_world(self, monkeypatch, no_markers):
        kv = FakeKV()
        from paddle_tpu.distributed import env as denv
        monkeypatch.setattr(coll, "_coord_client", lambda: kv)
        monkeypatch.setattr(denv, "get_world_size", lambda: 2)
        monkeypatch.setattr(denv, "get_rank", lambda: 0)
        coll.destroy_process_group()   # drop the cached world-1 group
        yield kv
        coll.destroy_process_group()

    def test_all_gather_object_attributes_missing_peer(self, fake_world):
        kv = fake_world
        with pytest.raises(coll.CollectiveTimeout) as ei:
            coll.all_gather_object([], {"x": 1}, tag="T1",
                                   timeout_s=0.15)
        assert ei.value.missing_ranks == [1]
        # our own contribution landed before the wait
        assert "ag_T1_0" in kv.d

    def test_all_gather_object_completes_when_peer_lands(self, fake_world):
        kv = fake_world
        import pickle
        kv.key_value_set("ag_T2_1", pickle.dumps({"r": 1}).hex())
        out = []
        coll.all_gather_object(out, {"r": 0}, tag="T2", timeout_s=5.0)
        assert out == [{"r": 0}, {"r": 1}]

    def test_barrier_attributes_missing_peer(self, fake_world):
        with pytest.raises(coll.CollectiveTimeout) as ei:
            coll.barrier(tag="B1", timeout_s=0.15)
        assert ei.value.missing_ranks == [1]
        assert ei.value.op == "barrier"

    def test_barrier_completes(self, fake_world):
        fake_world.key_value_set("bar_B2_1", "1")
        coll.barrier(tag="B2", timeout_s=5.0)

    def test_broadcast_object_list_waits_on_src(self, fake_world):
        kv = fake_world
        import pickle
        kv.key_value_set("bc_C1", pickle.dumps([7, 8]).hex())
        # receiving rank (0) with src=1
        got = coll.broadcast_object_list([0, 0], src=1, tag="C1",
                                         timeout_s=5.0)
        assert got == [7, 8]
        with pytest.raises(coll.CollectiveTimeout) as ei:
            coll.broadcast_object_list([0], src=1, tag="C2",
                                       timeout_s=0.15)
        assert ei.value.missing_ranks == [1]   # attributed to src

    def test_scatter_object_list_waits_on_src(self, fake_world):
        kv = fake_world
        import pickle
        kv.key_value_set("sc_S1_0", pickle.dumps("mine").hex())
        out = []
        coll.scatter_object_list(out, src=1, tag="S1", timeout_s=5.0)
        assert out == ["mine"]
        with pytest.raises(coll.CollectiveTimeout):
            coll.scatter_object_list([], src=1, tag="S2",
                                     timeout_s=0.15)

    def test_src_side_scatter_publishes_per_rank_keys(self, fake_world):
        kv = fake_world
        out = []
        coll.scatter_object_list(out, ["a", "b"], src=0, tag="S3",
                                 timeout_s=5.0)
        assert out == ["a"]
        assert "sc_S3_1" in kv.d
        assert "sc_S3_0" not in kv.d   # src takes its piece locally —
        #                                an unread key would just leak


class TestCoordinatedAbort:
    def test_rc_distinguishes_confirmed_death_from_timeout(self,
                                                           no_markers,
                                                           monkeypatch):
        # PeerLostError (confirmed dead) -> 123, CollectiveTimeout
        # (possibly wedged-but-alive) -> 122, so the elastic scale-in
        # heuristic stays engaged for deterministic wedges
        exits = []
        import os as _os
        monkeypatch.setattr(_os, "_exit", lambda code: exits.append(code))
        coll.coordinated_abort(
            coll.PeerLostError("barrier", 1, {1: "dead"}, 0.1, 2))
        coll.coordinated_abort(
            coll.CollectiveTimeout("barrier", 2, 60.0, {1}, 2, 60.0))
        assert exits == [coll.PEER_FAILURE_RC,
                         coll.COLLECTIVE_TIMEOUT_RC] == [123, 122]

    def test_abort_writes_marker_and_flight_record(self, no_markers,
                                                   tmp_path, monkeypatch):
        from paddle_tpu.monitor import trace as _trace
        flight = tmp_path / "box.json"
        _trace.set_flight_record_path(str(flight))
        try:
            exc = coll.PeerLostError("all_gather_object", "t",
                                     {1: "exited rc=137"}, 0.4, 2)
            coll.coordinated_abort(exc, exit_process=False)
        finally:
            _trace.set_flight_record_path(None)
        marker = hb.read_abort_marker(dir_path=no_markers, generation=0)
        assert marker is not None
        assert marker["rank"] == 0 and marker["lost_ranks"] == [1]
        assert "PeerLostError" in marker["reason"]
        assert flight.exists()

    def test_context_manager_marks_and_reraises(self, no_markers):
        with pytest.raises(coll.CollectiveTimeout):
            with coll.abort_on_collective_fault(exit_process=False):
                raise coll.CollectiveTimeout("barrier", 3, 1.0, {1}, 2,
                                             60.0)
        marker = hb.read_abort_marker(dir_path=no_markers, generation=0)
        assert marker is not None and marker["op"] == "barrier"

    def test_non_collective_errors_pass_through_unmarked(self, no_markers):
        with pytest.raises(ValueError):
            with coll.abort_on_collective_fault(exit_process=False):
                raise ValueError("unrelated")
        assert hb.read_abort_marker(dir_path=no_markers,
                                    generation=0) is None


class TestLauncherMarkers:
    def test_clear_run_markers_is_generation_scoped(self, tmp_path):
        # the sweep drops only OLDER generations: in a multi-node job
        # sharing a heartbeat dir, a later-starting controller must not
        # delete a peer node's live (current-generation) tombstones
        d = str(tmp_path)
        hb.mark_dead(0, "old world", dir_path=d, generation=0)
        hb.write_abort_marker(1, {"reason": "old"}, dir_path=d,
                              generation=0)
        hb.mark_dead(2, "peer node's live tombstone", dir_path=d,
                     generation=1)
        hb.mark_dead(5, "my own rank, stale by definition", dir_path=d,
                     generation=1)
        hb.write_abort_marker(3, {"reason": "pre-start abort"},
                              dir_path=d, generation=1)
        hb.touch_named(d, "replica0")   # unrelated files survive
        hb.clear_run_markers(d, generation=1, own_ranks=[4, 5])
        # older generation: swept entirely
        assert hb.dead_ranks([0], dir_path=d, generation=0) == {}
        assert hb.read_abort_marker(dir_path=d, generation=0) is None
        # current generation: a PEER node's tombstone survives...
        assert hb.dead_ranks([2], dir_path=d, generation=1) != {}
        # ...but my own ranks' markers and any pre-start abort marker
        # are provably stale and go
        assert hb.dead_ranks([5], dir_path=d, generation=1) == {}
        assert hb.read_abort_marker(dir_path=d, generation=1) is None
        import os
        assert "replica0.alive" in os.listdir(d)

    def test_markers_are_job_scoped(self, tmp_path, monkeypatch):
        # a later job reusing the same heartbeat dir at the same
        # generation must not honor its predecessor's markers: markers
        # carry the writing job's rendezvous address and readers match
        # it against their own PADDLE_MASTER
        d = str(tmp_path)
        hb.mark_dead(1, "old job corpse", dir_path=d, generation=0,
                     job="127.0.0.1:1111")
        hb.write_abort_marker(2, {"reason": "old"}, dir_path=d,
                              generation=0, job="127.0.0.1:1111")
        monkeypatch.setenv("PADDLE_MASTER", "127.0.0.1:2222")
        assert hb.dead_ranks([1], dir_path=d, generation=0) == {}
        assert hb.read_abort_marker(dir_path=d, generation=0) is None
        monkeypatch.setenv("PADDLE_MASTER", "127.0.0.1:1111")
        assert 1 in hb.dead_ranks([1], dir_path=d, generation=0)
        assert hb.read_abort_marker(dir_path=d,
                                    generation=0) is not None
        # markers without a job identity (direct API use) match anyone
        monkeypatch.setenv("PADDLE_MASTER", "127.0.0.1:9999")
        monkeypatch.delenv("PADDLE_MASTER", raising=False)
        hb.mark_dead(3, "unscoped", dir_path=d, generation=0)
        monkeypatch.setenv("PADDLE_MASTER", "127.0.0.1:9999")
        assert 3 in hb.dead_ranks([3], dir_path=d, generation=0)

    def test_untagged_reclamation_distance_two(self):
        # symmetric-exchange KV keys are deleted once provably dead
        # (<= seq-2), bounding coordination-service growth over a
        # long run
        kv = FakeKV()
        spent = []
        for seq in range(5):
            kv.key_value_set(f"bar_{seq}_0", "1")
            coll._reclaim_untagged(kv, spent, seq)
            spent.append((seq, f"bar_{seq}_0"))
        assert set(kv.d) == {"bar_3_0", "bar_4_0"}

    def test_plain_elastic_run_advances_generation(self):
        # plain ElasticManager.run must export PADDLE_ELASTIC_RUN per
        # relaunch, or the generation-scoped marker sweep would
        # preserve the previous incarnation's tombstones into the new
        # world (same gen) and instantly kill it
        from paddle_tpu.distributed.fleet.elastic import ElasticManager
        gens = []

        def launcher(script, args, nproc_per_node=1, extra_env=None,
                     **kw):
            gens.append((extra_env or {}).get("PADDLE_ELASTIC_RUN"))
            return 1 if len(gens) < 3 else 0

        m = ElasticManager(max_restarts=5, restart_delay=0.0,
                           launcher=launcher)
        assert m.run("job.py") == 0
        assert gens == ["0", "1", "2"]


class TestElasticPeerFailureMapping:
    def test_peer_rc_restarts_without_scale_in(self):
        from paddle_tpu.distributed.fleet.elastic import ElasticManager
        seen = []

        def launcher(script, args, nproc_per_node=1, **kw):
            seen.append(nproc_per_node)
            return coll.PEER_FAILURE_RC if len(seen) < 4 else 0

        m = ElasticManager(max_restarts=5, min_nproc=1,
                           restart_delay=0.0, launcher=launcher)
        assert m.run("job.py", nproc_per_node=3) == 0
        # peer-failure rcs never feed the sick-worker scale-in heuristic
        assert seen == [3, 3, 3, 3]
        reasons = [d.get("reason") for _, s, d in m.events
                   if s == "restart"]
        assert reasons == ["peer-failure"] * 3

    def test_ordinary_rc_still_scales_in(self):
        from paddle_tpu.distributed.fleet.elastic import ElasticManager
        seen = []

        def launcher(script, args, nproc_per_node=1, **kw):
            seen.append(nproc_per_node)
            return 1 if len(seen) < 5 else 0

        m = ElasticManager(max_restarts=5, min_nproc=1,
                           restart_delay=0.0, launcher=launcher)
        assert m.run("job.py", nproc_per_node=3) == 0
        assert seen[0] == 3 and seen[-1] < 3   # scale-in engaged
        assert any(d.get("reason") == "worker-failure"
                   for _, s, d in m.events if s == "restart")

    def test_adaptive_peer_rc_keeps_world_size(self):
        # review fix: a coordinated abort's rc must not mark a slot
        # down in run_adaptive — with no membership dir or readmit
        # backoff the slot would never re-admit and every later world
        # would run permanently shrunk off an INNOCENT rank's exit
        from paddle_tpu.distributed.fleet.elastic import \
            AdaptiveElasticManager
        seen = []

        def launcher(script, args, nproc_per_node=1, **kw):
            seen.append(nproc_per_node)
            return coll.PEER_FAILURE_RC if len(seen) < 3 else 0

        m = AdaptiveElasticManager(max_restarts=5, restart_delay=0.0,
                                   launcher=launcher)
        assert m.run_adaptive("job.py", nproc_per_node=3) == 0
        assert seen == [3, 3, 3]
        reasons = [d.get("reason") for _, s, d in m.events
                   if s == "restart"]
        assert reasons == ["peer-failure"] * 2

    def test_budget_still_bounds_peer_failures(self):
        from paddle_tpu.distributed.fleet.elastic import ElasticManager

        m = ElasticManager(max_restarts=2, restart_delay=0.0,
                           launcher=lambda *a, **k:
                           coll.PEER_FAILURE_RC)
        assert m.run("job.py") == coll.PEER_FAILURE_RC
        assert m.restarts == 2
