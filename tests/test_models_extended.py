"""MoE + DiT model family tests (BASELINE config matrix:
DeepSeekMoE/Qwen2-MoE for EP, DiT/SD3 for diffusion). Strategy mirrors
tests/test_models.py: tiny configs, loss decreases, sharded-vs-local
parity on the 8-device CPU mesh."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

import paddle_tpu as pt
from paddle_tpu.models import dit, moe


def mesh4(names):
    devs = np.array(jax.devices()[:8]).reshape(2, 2, 2, 1)
    return Mesh(devs, names)


class TestMoE:
    def test_forward_shapes_and_aux(self):
        cfg = moe.moe_tiny()
        params = moe.init_params(cfg, jax.random.key(0))
        ids = jnp.zeros((2, 16), jnp.int32)
        logits, aux = moe.forward(params, ids, cfg)
        assert logits.shape == (2, 16, cfg.vocab_size)
        # balanced-routing lower bound: aux >= 1 (equality at uniform)
        assert float(aux) >= cfg.num_hidden_layers * 0.99

    @pytest.mark.slow  # tier-1 budget (ISSUE 20 rebalance): convergence run; forward_shapes_and_aux +
    # topk_routing + ep_sharded_matches_local keep the MoE seam fast
    def test_training_decreases_loss(self):
        cfg = moe.moe_tiny()
        params = moe.init_params(cfg, jax.random.key(1))
        opt = moe.adamw_init(params)
        step = moe.make_train_step(cfg, lr=3e-3)
        ids = jnp.asarray(np.random.default_rng(0).integers(
            0, cfg.vocab_size, (4, 33)), jnp.int32)
        losses = []
        for _ in range(8):
            params, opt, loss = step(params, opt, ids)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_topk_routing_selects_k(self):
        cfg = moe.moe_tiny()
        params = moe.init_params(cfg, jax.random.key(2))
        ids = jnp.zeros((1, 8), jnp.int32)
        # run the router math directly on one layer slice
        x = jnp.take(params["embed"], ids, axis=0).reshape(8, -1)
        lp = jax.tree.map(lambda a: a[0], params["layers"])
        logits = x.astype(jnp.float32) @ lp["router"]
        topv, topi = jax.lax.top_k(jax.nn.softmax(logits, -1),
                                   cfg.num_experts_per_tok)
        assert topi.shape == (8, cfg.num_experts_per_tok)

    def test_ep_sharded_matches_local(self):
        cfg = moe.moe_tiny()
        params = moe.init_params(cfg, jax.random.key(3))
        ids = jnp.asarray(np.random.default_rng(1).integers(
            0, cfg.vocab_size, (4, 17)), jnp.int32)
        local = moe.loss_fn(params, ids, cfg)
        mesh = mesh4(("dp", "fsdp", "ep", "tp"))
        with mesh:
            sharded = jax.jit(
                lambda p, b: moe.loss_fn(p, b, cfg, mesh=mesh))(params, ids)
        np.testing.assert_allclose(float(local), float(sharded), rtol=2e-4)

    def test_config_factories(self):
        assert moe.deepseek_moe_16b().num_experts == 64
        assert moe.qwen2_moe_a14b().num_experts_per_tok == 8
        # param count sanity on tiny
        assert moe.count_params(moe.moe_tiny()) > 0


class TestMoECapacityDispatch:
    """GShard capacity gather dispatch (the single-chip default for the
    big configs; reference capacity_factor semantics from
    incubate/distributed/models/moe/gate)."""

    def _cfgs(self, **cap_kw):
        dense = moe.moe_tiny()
        capped = moe.moe_tiny(dispatch_mode="capacity", **cap_kw)
        return dense, capped

    @pytest.mark.slow  # tier-1 budget (ISSUE 3): heavy; run in the slow lane
    def test_matches_dense_when_nothing_drops(self):
        # capacity_factor = E/k makes C = T: no expert can overflow, so
        # capacity dispatch computes exactly the dense function
        dense, capped = self._cfgs(capacity_factor=2.0)  # E/k = 4/2
        params = moe.init_params(dense, jax.random.key(0))
        ids = jnp.asarray(np.random.default_rng(0).integers(
            0, dense.vocab_size, (2, 33)), jnp.int32)
        ld = jax.jit(lambda p: moe.loss_fn(p, ids, dense))(params)
        lc = jax.jit(lambda p: moe.loss_fn(p, ids, capped))(params)
        np.testing.assert_allclose(float(ld), float(lc), rtol=1e-5)
        # and grads agree too (the dispatch is differentiated through)
        gd = jax.grad(lambda p: moe.loss_fn(p, ids, dense))(params)
        gc = jax.grad(lambda p: moe.loss_fn(p, ids, capped))(params)
        for a, b in zip(jax.tree.leaves(gd), jax.tree.leaves(gc)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=1e-6)

    def test_over_capacity_slots_drop_not_crash(self):
        # capacity_factor tiny: C clamps to the minimum; most slots drop
        # but the loss stays finite and grads flow (dropped tokens keep
        # their shared-expert path)
        _, capped = self._cfgs(capacity_factor=0.01)
        assert moe.moe_capacity(capped, 64) == 8
        params = moe.init_params(capped, jax.random.key(1))
        ids = jnp.asarray(np.random.default_rng(1).integers(
            0, capped.vocab_size, (2, 33)), jnp.int32)
        loss, grads = jax.value_and_grad(
            lambda p: moe.loss_fn(p, ids, capped))(params)
        assert np.isfinite(float(loss))
        g = np.asarray(grads["layers"]["s_gate"])
        assert np.isfinite(g).all() and np.abs(g).sum() > 0

    def test_capacity_lane_alignment(self):
        big = moe.deepseek_moe_16b(num_hidden_layers=2)
        c = moe.moe_capacity(big, 2048)   # even share 192, x1.25 = 240
        assert c == 256 and c % 128 == 0
        # never exceeds the token count
        assert moe.moe_capacity(big, 64) <= 64

    @pytest.mark.slow  # tier-1 budget (ISSUE 20 rebalance): convergence run; matches_dense_when_nothing_drops
    # + dots_remat_policy_compiles keep the capacity-dispatch seam fast
    def test_trains_and_beats_init(self):
        cfg = moe.moe_tiny(dispatch_mode="capacity")
        params = moe.init_params(cfg, jax.random.key(2))
        opt = moe.adamw_init(params)
        step = moe.make_train_step(cfg, lr=3e-3)
        ids = jnp.asarray(np.random.default_rng(2).integers(
            0, cfg.vocab_size, (4, 33)), jnp.int32)
        losses = []
        for _ in range(8):
            params, opt, loss = step(params, opt, ids)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    @pytest.mark.slow  # tier-1 budget (ISSUE 20 rebalance): decode parity duplicated by
    # generate_greedy_matches_naive in this class
    def test_kv_cache_decode_matches_forward(self):
        # MoE incremental decode: prefill + steps pin to the full
        # forward's last logits (routing runs per decoded token)
        cfg = moe.moe_tiny()
        params = moe.init_params(cfg, jax.random.key(5))
        ids = jnp.asarray(np.random.default_rng(5).integers(
            0, cfg.vocab_size, (2, 6)), jnp.int32)
        cache = moe.init_cache(cfg, 2, 9)
        cache, logits = moe.prefill(params, ids, cfg, cache)
        full, _ = moe.forward(params, ids, cfg)
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(full[:, -1, :]),
                                   rtol=2e-4, atol=2e-4)
        seq = ids
        for _ in range(2):
            tok = jnp.argmax(logits, -1).astype(jnp.int32)
            seq = jnp.concatenate([seq, tok[:, None]], axis=1)
            cache, logits = moe.decode_step(params, cache, tok, cfg)
            full, _ = moe.forward(params, seq, cfg)
            np.testing.assert_allclose(np.asarray(logits),
                                       np.asarray(full[:, -1, :]),
                                       rtol=2e-4, atol=2e-4)

    @pytest.mark.slow  # tier-1 budget (ISSUE 3): heavy; run in the slow lane
    def test_beam_search_k1_equals_greedy(self):
        cfg = moe.moe_tiny()
        params = moe.init_params(cfg, jax.random.key(7))
        ids = jnp.asarray(np.random.default_rng(7).integers(
            0, cfg.vocab_size, (2, 5)), jnp.int32)
        greedy = np.asarray(moe.generate(params, ids, cfg,
                                         max_new_tokens=3))
        toks, scores = moe.beam_search(params, ids, cfg,
                                       max_new_tokens=3, num_beams=1)
        np.testing.assert_array_equal(np.asarray(toks), greedy)
        # and K=3 scores are at least as good as the greedy path's
        _, s3 = moe.beam_search(params, ids, cfg, max_new_tokens=3,
                                num_beams=3)
        assert (np.asarray(s3) >= np.asarray(scores) - 1e-5).all()

    def test_generate_greedy_matches_naive(self):
        cfg = moe.moe_tiny()
        params = moe.init_params(cfg, jax.random.key(6))
        ids = jnp.asarray(np.random.default_rng(6).integers(
            0, cfg.vocab_size, (2, 5)), jnp.int32)
        got = jax.jit(lambda p, i: moe.generate(
            p, i, cfg, max_new_tokens=3))(params, ids)
        seq = ids
        want = []
        for _ in range(3):
            logits, _ = moe.forward(params, seq, cfg)
            nxt = jnp.argmax(logits[:, -1, :], -1).astype(jnp.int32)
            want.append(nxt)
            seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.stack(want, axis=1))

    @pytest.mark.slow  # tier-1 budget (ISSUE 14 rebalance): MoE int8
    # decode parity duplicates the llama-family weight-only pins
    # (test_models TestWeightOnlyDecode) under the same contract
    def test_weight_only_int8_decode(self):
        # quantized tree == dequantized-fp tree through forward AND the
        # decode loop (same bit-exact contract as the llama family) —
        # under capacity dispatch, the measured on-chip configuration
        cfg = moe.moe_tiny(dispatch_mode="capacity")
        params = moe.init_params(cfg, jax.random.key(8))
        qp = moe.quantize_weights(params)
        deq = {"embed": params["embed"], "ln_f": params["ln_f"],
               "layers": {}}
        for k, w in qp["layers"].items():
            if isinstance(w, dict):
                s = w["s"]
                br = s[:, :, None, :] if w["q"].ndim == 4 else s[:, None, :]
                deq["layers"][k] = w["q"].astype(jnp.float32) * br
            else:
                deq["layers"][k] = w
        deq["lm_head"] = (qp["lm_head"]["q"].astype(jnp.float32)
                          * qp["lm_head"]["s"][:, None])
        ids = jnp.asarray(np.random.default_rng(8).integers(
            0, cfg.vocab_size, (2, 7)), jnp.int32)
        la, _ = moe.forward(qp, ids, cfg)
        lb, _ = moe.forward(deq, ids, cfg)
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=1e-6, atol=1e-6)
        ga = np.asarray(moe.generate(qp, ids, cfg, max_new_tokens=3))
        gb = np.asarray(moe.generate(deq, ids, cfg, max_new_tokens=3))
        np.testing.assert_array_equal(ga, gb)

    def test_dots_remat_policy_compiles(self):
        cfg = moe.moe_tiny(dispatch_mode="capacity", remat=True,
                           remat_policy="dots")
        params = moe.init_params(cfg, jax.random.key(3))
        opt = moe.adamw_init(params)
        step = moe.make_train_step(cfg, lr=1e-3)
        ids = jnp.asarray(np.random.default_rng(3).integers(
            0, cfg.vocab_size, (2, 17)), jnp.int32)
        params, opt, loss = step(params, opt, ids)
        assert np.isfinite(float(loss))


class TestDiT:
    def test_forward_shape(self):
        cfg = dit.dit_tiny()
        params = dit.init_params(cfg, jax.random.key(0))
        x = jnp.zeros((2, cfg.in_channels, cfg.image_size, cfg.image_size))
        t = jnp.array([0, 500], jnp.int32)
        y = jnp.array([1, 2], jnp.int32)
        out = dit.forward(params, x, t, y, cfg)
        assert out.shape == x.shape

    def test_zero_init_identity(self):
        """adaLN-Zero: at init the final projection is zero, so the
        prediction is exactly zero (the DiT identity-residual property)."""
        cfg = dit.dit_tiny()
        params = dit.init_params(cfg, jax.random.key(1))
        x = jnp.ones((1, cfg.in_channels, cfg.image_size, cfg.image_size))
        out = dit.forward(params, x, jnp.array([3], jnp.int32),
                          jnp.array([0], jnp.int32), cfg)
        np.testing.assert_allclose(np.asarray(out), 0.0, atol=1e-6)

    def test_patchify_roundtrip(self):
        cfg = dit.dit_tiny()
        x = jnp.asarray(np.random.randn(2, cfg.in_channels, cfg.image_size,
                                        cfg.image_size), jnp.float32)
        p = dit.patchify(x, cfg)
        assert p.shape == (2, cfg.num_patches,
                           cfg.patch_size ** 2 * cfg.in_channels)
        np.testing.assert_allclose(np.asarray(dit.unpatchify(p, cfg)),
                                   np.asarray(x), rtol=1e-6)

    @pytest.mark.slow  # tier-1 budget (ISSUE 20 rebalance): convergence run; forward_shape +
    # zero_init_identity + ddim_sampling_loop keep the DiT seam fast
    def test_training_decreases_loss(self):
        cfg = dit.dit_tiny()
        params = dit.init_params(cfg, jax.random.key(2))
        opt = dit.adamw_init(params)
        step = dit.make_train_step(cfg, lr=1e-3)
        rng = np.random.default_rng(0)
        x0 = jnp.asarray(rng.normal(size=(4, cfg.in_channels,
                                          cfg.image_size, cfg.image_size)),
                         jnp.float32)
        t = jnp.asarray(rng.integers(0, 1000, (4,)), jnp.int32)
        y = jnp.asarray(rng.integers(0, cfg.num_classes, (4,)), jnp.int32)
        noise = jnp.asarray(rng.normal(size=x0.shape), jnp.float32)
        losses = []
        for _ in range(10):
            params, opt, loss = step(params, opt, (x0, t, y, noise))
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_ddim_sampling_loop(self):
        cfg = dit.dit_tiny()
        params = dit.init_params(cfg, jax.random.key(0))
        y = jnp.asarray([1, 3], jnp.int32)
        x = jax.jit(lambda p, y: dit.ddim_sample(
            p, y, cfg, steps=5, key=jax.random.PRNGKey(0)))(params, y)
        assert x.shape == (2, cfg.in_channels, cfg.image_size,
                           cfg.image_size)
        assert np.isfinite(np.asarray(x)).all()
        # eta=0 DDIM is deterministic given the init-noise key
        x2 = dit.ddim_sample(params, y, cfg, steps=5,
                             key=jax.random.PRNGKey(0))
        np.testing.assert_allclose(np.asarray(x), np.asarray(x2),
                                   rtol=1e-5, atol=1e-6)

    def test_ddim_cfg_null_branch(self):
        # guidance_scale != 1 runs the conditional + null-label batch;
        # at a zero-init output head both branches predict 0 so the
        # guided trajectory must match the unguided one exactly
        cfg = dit.dit_tiny()
        params = dit.init_params(cfg, jax.random.key(1))
        y = jnp.asarray([0, 2], jnp.int32)
        a = dit.ddim_sample(params, y, cfg, steps=3,
                            key=jax.random.PRNGKey(1))
        b = dit.ddim_sample(params, y, cfg, steps=3, guidance_scale=4.0,
                            key=jax.random.PRNGKey(1))
        # final_w is zero-init -> eps == 0 for both branches
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)

    def test_sharded_matches_local(self):
        cfg = dit.dit_tiny()
        params = dit.init_params(cfg, jax.random.key(3))
        rng = np.random.default_rng(2)
        batch = (jnp.asarray(rng.normal(size=(4, cfg.in_channels,
                                              cfg.image_size,
                                              cfg.image_size)), jnp.float32),
                 jnp.asarray(rng.integers(0, 1000, (4,)), jnp.int32),
                 jnp.asarray(rng.integers(0, cfg.num_classes, (4,)),
                             jnp.int32),
                 jnp.asarray(rng.normal(size=(4, cfg.in_channels,
                                              cfg.image_size,
                                              cfg.image_size)), jnp.float32))
        local = dit.loss_fn(params, batch, cfg)
        devs = np.array(jax.devices()[:8]).reshape(2, 2, 2)
        mesh = Mesh(devs, ("dp", "fsdp", "tp"))
        with mesh:
            sharded = jax.jit(
                lambda p, b: dit.loss_fn(p, b, cfg, mesh=mesh))(params, batch)
        np.testing.assert_allclose(float(local), float(sharded), rtol=2e-4)


class TestMoEReviewRegressions:
    def test_gates_scale_outputs_not_inputs(self):
        """Router weights must scale expert OUTPUTS (nonlinear experts):
        doubling a token's router weight share must NOT change what the
        expert computes on it, only its contribution."""
        cfg = moe.moe_tiny(num_experts=2, num_experts_per_tok=1)
        params = moe.init_params(cfg, jax.random.key(0))
        lp = jax.tree.map(lambda a: a[0], params["layers"])
        h = jnp.asarray(np.random.default_rng(3).normal(
            size=(1, 4, cfg.hidden_size)), jnp.float32)
        out, _ = moe._moe_mlp(h, lp, cfg, None)
        # reference computation: for each token, MLP(x) of its top expert
        # times its (renormalized=1.0 for k=1) gate + shared expert
        x = h.reshape(4, -1)
        logits = x @ lp["router"]
        top = jnp.argmax(logits, axis=-1)
        expect = []
        for ti in range(4):
            e = int(top[ti])
            g = jax.nn.silu(x[ti] @ lp["e_gate"][e]) * (x[ti] @ lp["e_up"][e])
            routed = g @ lp["e_down"][e]
            sg = jax.nn.silu(x[ti] @ lp["s_gate"]) * (x[ti] @ lp["s_up"])
            expect.append(routed + sg @ lp["s_down"])
        np.testing.assert_allclose(np.asarray(out.reshape(4, -1)),
                                   np.asarray(jnp.stack(expect)),
                                   rtol=2e-4, atol=1e-5)


class TestDomainReviewRegressions:
    def test_tuner_local_bs_counts_sharding(self):
        from paddle_tpu.distributed.auto_tuner import generate_candidates
        cands = generate_candidates({"num_chips": 8, "global_batch_size": 8})
        for c in cands:
            ways = c["dp_degree"] * c["sharding_degree"]
            assert c["micro_batch_size"] * c["acc_steps"] == 8 // ways

    def test_quanter_frozen_in_eval(self):
        from paddle_tpu import quantization as Q
        import paddle_tpu.nn as nn

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 4)

            def forward(self, x):
                return self.fc(x)

        qat = Q.QAT(Q.QuantConfig(
            activation=Q.FakeQuanterWithAbsMaxObserver()))
        m = qat.quantize(Net())
        x1 = pt.to_tensor(np.ones((2, 4), "float32"))
        m.train()
        m(x1)
        from paddle_tpu.quantization.wrapper import ObserveWrapper
        w = [s for _, s in m.named_sublayers()
             if isinstance(s, ObserveWrapper)][0]
        s_before = w._act._scale
        m.eval()
        m(pt.to_tensor(100 * np.ones((2, 4), "float32")))
        assert w._act._scale == s_before      # eval must not recalibrate

    def test_quanted_state_dict_roundtrip(self):
        from paddle_tpu import quantization as Q
        import paddle_tpu.nn as nn

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 4)

            def forward(self, x):
                return self.fc(x)

        ptq = Q.PTQ(Q.QuantConfig(weight=Q.AbsmaxObserver()))
        m = ptq.quantize(Net())
        m(pt.to_tensor(np.random.randn(4, 4).astype("float32")))
        conv = ptq.convert(m)
        sd = conv.state_dict()
        assert any("qweight" in k for k in sd), list(sd)
        assert any("w_scale" in k for k in sd), list(sd)

    def test_sparse_scalar_add_densifies(self):
        d = np.array([[0.0, 1.0], [0.0, 0.0]], "float32")
        s = pt.sparse.sparse_coo_tensor_from_dense(d)
        out = pt.sparse.add(s, 1.0)
        np.testing.assert_allclose(out.to_dense().numpy(), d + 1.0)
        # mul keeps value space (zeros preserved)
        out2 = pt.sparse.multiply(s, 2.0)
        np.testing.assert_allclose(out2.to_dense().numpy(), d * 2.0)
        assert out2.nnz() == s.nnz()

    def test_segment_sum_under_jit(self):
        f = jax.jit(lambda d, i: pt.geometric.segment_sum(
            pt.Tensor(d), pt.Tensor(i))._data)
        d = jnp.asarray(np.ones((4, 2), "float32"))
        i = jnp.asarray(np.array([0, 1, 1, 0], "int32"))
        out = f(d, i)
        # jit path pads to the static upper bound (rows of data)
        np.testing.assert_allclose(np.asarray(out)[:2],
                                   [[2.0, 2.0], [2.0, 2.0]])

    def test_sample_neighbors_eids(self):
        row = np.array([1, 2, 0, 0, 1], "int64")
        colptr = np.array([0, 2, 3, 5], "int64")
        nodes = np.array([0, 2], "int64")
        n, c, e = pt.geometric.sample_neighbors(
            pt.to_tensor(row), pt.to_tensor(colptr), pt.to_tensor(nodes),
            return_eids=True)
        np.testing.assert_array_equal(np.asarray(e.numpy()), [0, 1, 3, 4])


class TestOCRRecognizer:
    @pytest.mark.slow  # tier-1 budget (ISSUE 3): heavy; run in the slow lane
    def test_ocr_rec_trains_with_ctc(self):
        import numpy as np

        import paddle_tpu as paddle
        from paddle_tpu.models import ocr
        from paddle_tpu.optimizer import Adam

        cfg = ocr.ocr_rec_tiny()
        model = ocr.OCRRecognizer(cfg)
        opt = Adam(learning_rate=1e-3, parameters=model.parameters())
        step = ocr.ctc_train_step(model, opt)
        rng = np.random.default_rng(0)
        imgs = paddle.to_tensor(
            rng.normal(size=(2, 3, cfg.image_height, 48)).astype("float32"))
        labels = paddle.to_tensor(
            rng.integers(1, cfg.num_classes, (2, 5)).astype("int32"))
        lens = paddle.to_tensor(np.array([5, 4], "int32"))
        l0 = float(step(imgs, labels, lens).numpy())
        for _ in range(8):
            last = float(step(imgs, labels, lens).numpy())
        assert np.isfinite(last) and last < l0

    def test_ctc_greedy_decode(self):
        import numpy as np

        from paddle_tpu.models import ocr

        # hand-built logits: frames argmax to [blank, 5, 5, blank, 3, 3]
        # -> collapse repeats, drop blanks -> [5, 3]
        C = 8
        frames = [0, 5, 5, 0, 3, 3]
        logits = np.full((1, len(frames), C), -5.0, np.float32)
        for t, k in enumerate(frames):
            logits[0, t, k] = 5.0
        texts, confs = ocr.ctc_greedy_decode(logits)
        assert texts == [[5, 3]]
        assert 0.9 < confs[0] <= 1.0
        # all-blank row decodes empty with zero confidence
        blank = np.zeros((1, 4, C), np.float32)
        blank[..., 0] = 9.0
        texts, confs = ocr.ctc_greedy_decode(blank)
        assert texts == [[]] and confs[0] == 0.0

    def test_ernie_config(self):
        from paddle_tpu.models import moe

        cfg = moe.ernie_4_5_a3b(num_hidden_layers=2)
        assert cfg.num_experts == 64 and cfg.num_experts_per_tok == 6


class TestScaleLowering:
    def test_llama_70b_shapes_lower_on_mesh(self):
        """BASELINE config matrix: Llama-3-70B shapes must COMPILE under
        the hybrid sharding (shape-level lowering only — no 70B of memory
        is materialized; jit.lower accepts ShapeDtypeStructs)."""
        import jax
        import numpy as np
        from jax.sharding import Mesh

        from paddle_tpu.models import llama as L

        cfg = L.LlamaConfig(
            vocab_size=128256, hidden_size=8192, intermediate_size=28672,
            num_hidden_layers=2,          # layer count is scan-stacked;
            num_attention_heads=64,       # 2 layers proves the shapes
            num_key_value_heads=8, max_position_embeddings=8192,
            rope_theta=500000.0)
        devs = np.array(jax.devices()[:8]).reshape(1, 4, 2)
        mesh = Mesh(devs, ("dp", "fsdp", "tp"))
        step = L.make_train_step(cfg, mesh, lr=1e-4, sp=True)
        pshape = jax.eval_shape(lambda k: L.init_params(cfg, k),
                                jax.random.PRNGKey(0))
        oshape = jax.eval_shape(L.adamw_init, pshape)
        ids = jax.ShapeDtypeStruct((4, 4097), np.int32)
        lowered = step.lower(pshape, oshape, ids)
        text = lowered.as_text()
        assert "sharding" in text          # GSPMD annotations present
        # the gate projection's declared placement shards the ffn dim on
        # tp and the hidden dim on fsdp (ZeRO-3 + Megatron TP)
        from jax.sharding import PartitionSpec as P

        specs = L.param_specs(cfg)
        assert specs["layers"]["gate"] == P(None, "fsdp", "tp")
        assert specs["embed"] == P("tp", "fsdp")
