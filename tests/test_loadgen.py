"""Loadgen harness: deterministic trace generation, open-loop replay,
SLO scorecard, and the guarded bench rungs (paddle_tpu/loadgen/).

The determinism contract under test: same seed ⇒ byte-identical
serialized trace AND identical terminal-state/token counts across two
replays on fresh engines (the scorecard's ``deterministic`` block is
diffed wholesale); wall-clock data stays quarantined in ``timing``.
"""
import importlib.util
import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.loadgen import (ArrivalTrace, Episode, TenantSpec,
                                build_scorecard, generate_trace,
                                heavy_tailed_lengths,
                                mixed_length_trace, prompt_tokens,
                                replay_fleet, replay_trace)
from paddle_tpu.loadgen import scorecard as sc
from paddle_tpu.loadgen.traces import TRACE_VERSION

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the draw sequence the packed-training bench rung and the smoke
# pre-tuning were swept under (autotune cache keys depend on it) —
# pinned byte-for-byte, see io/packing.py heavy_tailed_lengths
HEAVY_TAILED_GOLDEN_2048_24_7 = [
    512, 1024, 512, 128, 128, 1024, 128, 1024, 512, 256, 128, 128,
    128, 256, 256, 256, 2048, 512, 512, 2048, 128, 128, 512, 128]


# ---------------------------------------------------------------------------
# traces
# ---------------------------------------------------------------------------

class TestTraces:
    def test_heavy_tailed_pinned_golden(self):
        assert heavy_tailed_lengths(2048, 24, seed=7) \
            == HEAVY_TAILED_GOLDEN_2048_24_7

    def test_packing_delegate_is_byte_identical(self):
        # io.packing re-exports the loadgen implementation: the
        # historical import path must keep the exact draw sequence
        from paddle_tpu.io import packing as pk
        for args in ((2048, 24, 7), (512, 16, 3), (128, 40, 11)):
            assert pk.heavy_tailed_lengths(*args) \
                == heavy_tailed_lengths(*args)

    def test_mixed_length_trace_matches_inline_construction(self):
        # parity with the serving_paged rung's historical inline code,
        # including draw-sequence continuation: the bench passes its
        # live Generator and draws prompt tokens AFTER the trace, so
        # the helper must consume exactly the same number of draws
        plens, glens, n = (4, 8, 16), (4, 8, 16, 64), 32
        ref_rng = np.random.default_rng(42)
        ref = [(int(ref_rng.choice(plens)), int(ref_rng.choice(glens)))
               for _ in range(n)]
        ref.sort(key=lambda t: -t[1])
        rng = np.random.default_rng(42)
        got = mixed_length_trace(plens, glens, n, rng)
        assert got == ref
        np.testing.assert_array_equal(rng.integers(0, 1000, (8,)),
                                      ref_rng.integers(0, 1000, (8,)))

    def test_mixed_length_trace_accepts_int_seed(self):
        assert mixed_length_trace((4, 8), (4, 16), 10, 5) \
            == mixed_length_trace((4, 8), (4, 16), 10,
                                  np.random.default_rng(5))

    def test_same_seed_byte_identical_json(self):
        kw = dict(duration_s=1.0, rate=32.0,
                  tenants=[TenantSpec("a", priority=1),
                           TenantSpec("b", share=2.0,
                                      deadline_s=5.0)],
                  burst=(0.4, 0.2, 3.0))
        a, b = generate_trace(11, **kw), generate_trace(11, **kw)
        assert a.to_json() == b.to_json()
        assert a.sha256() == b.sha256()

    def test_different_seed_differs(self):
        assert generate_trace(11).to_json() \
            != generate_trace(12).to_json()

    def test_json_round_trip(self):
        tr = generate_trace(21, tenants=[TenantSpec("x", priority=3,
                                                    deadline_s=2.0)],
                            burst=(0.2, 0.1, 4.0))
        back = ArrivalTrace.from_json(tr.to_json())
        assert back.to_json() == tr.to_json()
        assert back.requests[0] == tr.requests[0]
        assert back.config == tr.config

    def test_newer_version_refused(self):
        tr = generate_trace(3, duration_s=0.1, rate=10.0)
        d = tr.as_dict()
        d["version"] = TRACE_VERSION + 1
        with pytest.raises(ValueError, match="newer"):
            ArrivalTrace.from_json(json.dumps(d))

    def test_burst_window_concentrates_arrivals(self):
        quiet = generate_trace(7, duration_s=1.0, rate=40.0)
        burst = generate_trace(7, duration_s=1.0, rate=40.0,
                               burst=(0.4, 0.2, 5.0))

        def in_window(tr):
            return sum(0.4 <= r.arrival_s < 0.6 for r in tr.requests)

        assert in_window(burst) > 2 * in_window(quiet)
        assert len(burst.requests) > len(quiet.requests)

    def test_tenant_mix_carries_priority_and_deadline(self):
        tr = generate_trace(9, duration_s=1.0, rate=64.0,
                            tenants=[TenantSpec("rt", priority=5,
                                                deadline_s=0.5),
                                     TenantSpec("bg", share=3.0)])
        by = {}
        for r in tr.requests:
            by.setdefault(r.tenant, []).append(r)
        assert set(by) == {"rt", "bg"}
        assert all(r.priority == 5 and r.deadline_s == 0.5
                   for r in by["rt"])
        assert all(r.priority == 0 and r.deadline_s is None
                   for r in by["bg"])
        # the 3x share tenant dominates the mix
        assert len(by["bg"]) > len(by["rt"])

    def test_lengths_respect_bounds_and_heavy_tail(self):
        tr = generate_trace(13, duration_s=2.0, rate=128.0,
                            prompt_len=(4, 64),
                            max_new_tokens=(4, 32), alpha=1.2)
        ps = [r.prompt_len for r in tr.requests]
        gs = [r.max_new_tokens for r in tr.requests]
        assert min(ps) >= 4 and max(ps) <= 64
        assert min(gs) >= 4 and max(gs) <= 32
        # heavy tail: median pinned near lo, but the tail is reached
        assert float(np.median(ps)) <= 16
        assert max(ps) >= 32

    def test_prompt_tokens_pure_function_of_seed_and_rid(self):
        a = prompt_tokens(11, 5, 16, 1000)
        b = prompt_tokens(11, 5, 16, 1000)
        np.testing.assert_array_equal(a, b)
        assert a.dtype == np.int32 and a.shape == (16,)
        assert not np.array_equal(a, prompt_tokens(11, 6, 16, 1000))

    def test_generate_trace_validates(self):
        with pytest.raises(ValueError, match="duration_s"):
            generate_trace(1, duration_s=0.0)
        with pytest.raises(ValueError, match="shares"):
            generate_trace(1, tenants=[TenantSpec("a", share=0.0)])

    def test_offered_tokens_and_tenants(self):
        tr = generate_trace(2, duration_s=0.5, rate=20.0,
                            tenants=[TenantSpec("z"), TenantSpec("a")])
        assert tr.offered_tokens() \
            == sum(r.max_new_tokens for r in tr.requests)
        assert tr.tenants() == sorted(tr.tenants())


# ---------------------------------------------------------------------------
# replay + scorecard (single engine)
# ---------------------------------------------------------------------------

def _mk_engine(**kw):
    import jax
    from paddle_tpu.inference import ServingEngine
    from paddle_tpu.models import llama as L
    cfg = L.llama_tiny(num_hidden_layers=1)
    params = L.init_params(cfg, jax.random.PRNGKey(3))
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_len", 24)
    kw.setdefault("page_size", 4)
    kw.setdefault("decode_chunk", 2)
    return ServingEngine(L, params, cfg, **kw)


def _small_trace(seed=77):
    return generate_trace(seed, duration_s=0.5, rate=24.0,
                          tenants=[TenantSpec("interactive",
                                              priority=2),
                                   TenantSpec("batch", share=2.0)],
                          prompt_len=(3, 8), max_new_tokens=(2, 8))


def _one_replay():
    eng = _mk_engine(priority_admission=True, max_queue=3)
    return replay_trace(
        eng, _small_trace(), dt_per_step=0.02,
        episodes=[Episode("burst", at_s=0.25, n_requests=10)])


@pytest.fixture(scope="module")
def replay_pair():
    """Two same-seed replays on FRESH engines — the determinism pair
    several tests below diff (module-scoped: the replays compile a
    model, so run them once)."""
    return _one_replay(), _one_replay()


@pytest.mark.serving
class TestReplay:
    def test_same_seed_identical_terminal_and_tokens(self, replay_pair):
        a, b = replay_pair
        assert a.trace.to_json() == b.trace.to_json()
        assert a.terminal_counts() == b.terminal_counts()
        assert a.useful_tokens() == b.useful_tokens()
        assert a.offered == b.offered
        assert a.offered_tokens == b.offered_tokens
        # full per-rid diff: state, tokens, tenant, typed reasons
        assert sorted(a.terminal) == sorted(b.terminal)
        for rid in a.terminal:
            ra = {k: v for k, v in a.terminal[rid].items()
                  if k != "retry_after_s"}    # demand-model hint is
            rb = {k: v for k, v in b.terminal[rid].items()  # timing
                  if k != "retry_after_s"}
            assert ra == rb, (rid, ra, rb)

    def test_scorecard_deterministic_block_identical(self, replay_pair):
        a, b = replay_pair
        ca = build_scorecard(a)["deterministic"]
        cb = build_scorecard(b)["deterministic"]
        assert json.dumps(ca, sort_keys=True) \
            == json.dumps(cb, sort_keys=True)

    def test_exactly_one_terminal_state_per_submission(self,
                                                       replay_pair):
        res = replay_pair[0]
        assert res.offered == len(res.trace.requests) + 10
        assert len(res.terminal) == res.offered
        states = {r["state"] for r in res.terminal.values()}
        assert states <= {"completed", "shed", "expired", "rejected"}

    def test_sheds_are_typed_with_retry_hints(self, replay_pair):
        res = replay_pair[0]
        sheds = [r for r in res.terminal.values()
                 if r["state"] == "shed"]
        assert sheds, "burst did not overload the bounded queue"
        for rec in sheds:
            assert rec.get("retry_after_s") is not None, rec
            assert rec.get("reason"), rec

    def test_scorecard_structure_and_verdict(self, replay_pair):
        card = build_scorecard(replay_pair[0])
        card = json.loads(json.dumps(card))     # wire round trip
        assert card["verdict"]["pass"], card["verdict"]
        det = card["deterministic"]
        assert det["trace"]["sha256"] == replay_pair[0].trace.sha256()
        assert det["engine_flags"]["priority_admission"] is True
        assert det["engine_flags"]["max_queue"] == 3
        assert sum(det["terminal"].values()) == det["goodput"][
            "offered_requests"]
        assert det["shed_by_reason"], det
        assert 0 < det["goodput"]["request_goodput"] < 1.0
        assert 0 < det["goodput"]["token_goodput"] <= 1.0
        assert set(det["per_tenant"]) \
            >= {"interactive", "batch", "burst"}
        assert 0 < det["fairness"]["jain_completion_index"] <= 1.0
        # episode admission counts live in the deterministic plane;
        # its SLO probe/wall stamps are quarantined in timing
        assert det["episodes"][0]["kind"] == "burst"
        assert "slo" not in det["episodes"][0]
        assert "wall_s" in card["timing"]

    def test_token_conservation(self, replay_pair):
        res = replay_pair[0]
        emitted = sum(r["tokens"] for r in res.terminal.values())
        st = res.engine_stats["engine0"]
        assert st["tokens_generated"] - st["tokens_discarded"] \
            == emitted

    def test_kill_episode_rejected_single_engine(self):
        with pytest.raises(ValueError, match="replay_fleet"):
            replay_trace(_mk_engine(), _small_trace(),
                         episodes=[Episode("kill", at_s=0.1)])

    def test_unknown_episode_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown episode"):
            Episode("explode", at_s=0.1)

    def test_drain_episode_sheds_queue_with_hints(self):
        eng = _mk_engine(max_queue=8)
        res = replay_trace(
            eng, _small_trace(5), dt_per_step=0.02,
            episodes=[Episode("drain", at_s=0.2)])
        card = build_scorecard(res)
        assert card["verdict"]["pass"], card["verdict"]
        # everything queued at drain-begin (and every later arrival)
        # sheds as "draining" with a retry hint
        assert card["deterministic"]["shed_by_reason"].get(
            "draining"), card["deterministic"]
        for rec in res.terminal.values():
            if rec["state"] == "shed":
                assert rec.get("retry_after_s") is not None, rec


class TestScorecardUnits:
    def test_shed_reason_typing(self):
        f = sc._shed_reason_type
        assert f("engine is draining") == "draining"
        assert f("displaced by rid 7") == "displaced"
        assert f("slo burn shed") == "slo_burn"
        assert f("queue full (8/8)") == "queue_full"
        assert f("???") == "other"
        assert f(None) == "other"

    def test_jain_index(self):
        assert sc._jain([1.0, 1.0, 1.0]) == 1.0
        assert sc._jain([]) is None
        assert abs(sc._jain([1.0, 0.0]) - 0.5) < 1e-9
        assert sc._jain([0.0, 0.0]) == 1.0

    def test_last_scorecard_lifecycle(self, replay_pair):
        sc.reset()
        assert sc.last_scorecard() is None
        card = build_scorecard(replay_pair[0])
        assert sc.last_scorecard() is card
        sc.reset()
        assert sc.last_scorecard() is None


@pytest.mark.serving
class TestScorecardRoute:
    @pytest.fixture
    def mon(self):
        from paddle_tpu import monitor
        from paddle_tpu.monitor import server
        monitor.reset()
        server.stop_server()
        pt.set_flags({"FLAGS_enable_monitor": True})
        yield monitor
        server.stop_server()
        pt.set_flags({"FLAGS_enable_monitor": False,
                      "FLAGS_enable_monitor_server": False})
        monitor.reset()

    @staticmethod
    def _get(url):
        try:
            with urllib.request.urlopen(url, timeout=10) as r:
                return r.status, r.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()

    def test_scorecard_route(self, mon, replay_pair):
        from paddle_tpu.monitor import server
        sc.reset()
        srv = server.start_server(port=0)
        code, body = self._get(f"{srv.url}/scorecard")
        assert code == 404
        assert json.loads(body)["available"] is False
        card = build_scorecard(replay_pair[0])
        code, body = self._get(f"{srv.url}/scorecard")
        assert code == 200
        served = json.loads(body)
        assert served["verdict"] == card["verdict"]
        assert served["deterministic"]["trace"]["sha256"] \
            == card["deterministic"]["trace"]["sha256"]
        code, body = self._get(f"{srv.url}/")
        assert "/scorecard" in json.loads(body)["routes"]

    def test_replay_metrics_counted(self, mon):
        res = _one_replay()
        snap = mon.snapshot()["counters"]
        assert snap.get("loadgen.replay.offered") == res.offered
        assert snap.get("loadgen.replay.completed") \
            == res.terminal_counts().get("completed")
        assert snap.get("loadgen.replay.shed") \
            == res.terminal_counts().get("shed")
        assert snap.get("loadgen.replay.tokens.useful") \
            == res.useful_tokens()
        build_scorecard(res)
        assert mon.snapshot()["counters"].get(
            "loadgen.scorecard.builds") == 1


# ---------------------------------------------------------------------------
# fleet replay
# ---------------------------------------------------------------------------

@pytest.mark.serving
class TestFleetReplay:
    def test_fleet_replay_two_replicas(self):
        # the fast fleet case tier-1 keeps: 2 replicas, local frames,
        # no kill — every request terminal, none lost, per-replica
        # stats and routing visible
        from paddle_tpu.monitor import federation as fed
        fed.reset()
        try:
            res = replay_fleet(lambda name: _mk_engine(),
                               _small_trace(31), replicas=2,
                               dt_per_tick=0.05, steps_per_tick=2)
            card = build_scorecard(res)
            assert card["verdict"]["pass"], card["verdict"]
            assert len(res.terminal) == len(res.trace.requests)
            assert res.terminal_counts().get("lost", 0) == 0
            assert set(res.engine_stats) == {"replica0", "replica1"}
            replicas_used = {r.get("replica")
                             for r in res.terminal.values()}
            assert replicas_used == {"replica0", "replica1"}
            assert res.fleet_events is not None
        finally:
            fed.reset()

    @pytest.mark.slow
    def test_fleet_kill_episode_recovers(self, tmp_path):
        # scripted replica kill through the fault-injection point: the
        # victim stops stepping, its heartbeat goes stale, the elastic
        # controller replaces it, its in-flight work is typed ``lost``
        # — and the scorecard still passes (the loss is scripted) with
        # a measured recovery_s
        from paddle_tpu.monitor import federation as fed
        fed.reset()
        try:
            trace = generate_trace(
                41, duration_s=1.2, rate=24.0,
                tenants=[TenantSpec("t0"), TenantSpec("t1")],
                prompt_len=(3, 8), max_new_tokens=(4, 12))
            res = replay_fleet(
                lambda name: _mk_engine(), trace, replicas=2,
                episodes=[Episode("kill", at_s=0.3,
                                  replica="replica1")],
                dt_per_tick=0.02, steps_per_tick=1,
                # generous vs CPU compile ticks: a healthy replica's
                # beat refreshes per tick, and a tick (even a fresh
                # replica's compile tick) stays well under this — only
                # the killed victim, which stops stepping entirely,
                # ever goes stale
                heartbeat_dir=str(tmp_path), heartbeat_timeout=6.0)
            kinds = [e["kind"] for e in res.episodes]
            assert "killed" in kinds, res.episodes
            assert "recovered" in kinds, res.episodes
            # the controller spawned a replacement beyond the initial 2
            assert len(res.engine_stats) >= 3, sorted(res.engine_stats)
            card = build_scorecard(res)
            assert card["verdict"]["pass"], card["verdict"]
            assert card["timing"]["recovery_s"] is not None
            assert card["timing"]["recovery_s"] >= 0
            # every submission still accounted in exactly one state
            assert len(res.terminal) == res.offered
            lost = [r for r in res.terminal.values()
                    if r["state"] == "lost"]
            for rec in lost:
                assert rec.get("replica") == "replica1", rec
        finally:
            fed.reset()

    @pytest.mark.slow
    def test_fleet_kill_failover_exactly_once(self, tmp_path):
        # the same scripted kill with FLAGS_serving_failover on: the
        # victim's journaled in-flight work is stranded, re-dispatched
        # through normal admission on a survivor, and ends in exactly
        # one terminal state with lineage — ZERO ``lost``, no token
        # delivered twice (token conservation holds even though the
        # victim's partial generation died with it)
        from paddle_tpu.monitor import federation as fed
        fed.reset()
        try:
            trace = generate_trace(
                41, duration_s=1.2, rate=24.0,
                tenants=[TenantSpec("t0"), TenantSpec("t1")],
                prompt_len=(3, 8), max_new_tokens=(4, 12))
            res = replay_fleet(
                lambda name: _mk_engine(failover=True), trace,
                replicas=2,
                episodes=[Episode("kill", at_s=0.3,
                                  replica="replica1")],
                dt_per_tick=0.02, steps_per_tick=1,
                heartbeat_dir=str(tmp_path), heartbeat_timeout=6.0,
                failover=True)
            kinds = [e["kind"] for e in res.episodes]
            assert "killed" in kinds and "recovered" in kinds
            counts = res.terminal_counts()
            assert counts.get("lost", 0) == 0, counts
            assert len(res.terminal) == res.offered
            # the durability layer saw the strand and settled it
            assert res.failover is not None
            ctr = res.failover["counters"]
            assert ctr["stranded"] >= 1
            assert ctr["redispatched"] + ctr["quarantined"] \
                + ctr["expired"] >= 1
            recovered = [r for r in res.terminal.values()
                         if r.get("recovered_from")]
            assert recovered, res.failover
            for rec in recovered:
                assert rec["recovered_from"] == ["replica1"], rec
                assert rec["state"] in ("completed", "expired",
                                        "shed", "quarantined")
                assert rec.get("failover_attempts", 0) >= 1
            card = build_scorecard(res)
            # token conservation inside the verdict pins "no token
            # delivered twice": emitted == generated - discarded even
            # with the re-dispatch regenerating from scratch
            assert card["verdict"]["pass"], card["verdict"]
            det_fo = card["deterministic"]["failover"]
            assert det_fo["recovered"] == ctr["recovered"]
            assert det_fo["failover_attempts"] >= 1
            t_fo = card["timing"]["failover"]
            assert t_fo["coordinator"]["counters"] == ctr
            if ctr["recovered"]:
                assert t_fo["recovery_s"]["count"] == len(
                    [r for r in res.terminal.values()
                     if r.get("recovery_s") is not None])
                assert t_fo["recovery_s"]["p99"] > 0
        finally:
            fed.reset()

    def test_fleet_flags_off_has_no_failover_surface(self):
        # flag off: no journal, no coordinator, zeroed deterministic
        # block — the flags-off determinism diff is unchanged
        from paddle_tpu.monitor import federation as fed
        fed.reset()
        try:
            res = replay_fleet(lambda name: _mk_engine(),
                               _small_trace(31), replicas=2,
                               dt_per_tick=0.05, steps_per_tick=2)
            assert res.failover is None
            assert res.engine_flags["failover"] is False
            card = build_scorecard(res)
            assert card["deterministic"]["failover"] == {
                "recovered": 0, "failover_attempts": 0,
                "quarantined": 0}
            assert "failover" not in card["timing"]
        finally:
            fed.reset()

    def test_kill_without_heartbeat_rejected(self):
        with pytest.raises(ValueError, match="heartbeat"):
            replay_fleet(lambda name: _mk_engine(), _small_trace(),
                         episodes=[Episode("kill", at_s=0.1)])


# ---------------------------------------------------------------------------
# bench-guard wiring for the serving_trace_replay rung
# ---------------------------------------------------------------------------

def _load_guard():
    path = os.path.join(REPO, "scripts", "check_bench_regression.py")
    spec = importlib.util.spec_from_file_location(
        "check_bench_regression_loadgen", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _bench_blob(value, extra=None):
    rec = {"metric": "llama_train_tokens_per_sec_per_chip",
           "value": value, "unit": "tokens/s"}
    if extra:
        rec["extra"] = extra
    return {"n": 5, "cmd": "python bench.py", "rc": 0,
            "tail": json.dumps(rec) + "\n", "parsed": rec}


def _replay_extra(goodput, ttft_p99):
    return {"serving_trace_replay": {
        "goodput_tokens_per_sec": goodput, "ttft_p99_ms": ttft_p99}}


def _failover_extra(lost, recovery_p99):
    return {"serving_failover_replay": {
        "lost": lost, "recovery_s_p99": recovery_p99}}


class TestReplayBenchGuard:
    def _write(self, root, rnd, blob):
        with open(os.path.join(root, f"BENCH_r{rnd:02d}.json"),
                  "w") as f:
            json.dump(blob, f)

    def test_rungs_in_allowlists(self):
        guard = _load_guard()
        assert guard.ALLOWLIST[
            "serving_replay_goodput_tokens_per_sec"] \
            == "extra.serving_trace_replay.goodput_tokens_per_sec"
        assert guard.ALLOWLIST_LOWER["serving_replay_ttft_ms_p99"] \
            == "extra.serving_trace_replay.ttft_p99_ms"

    def test_goodput_regression_fails(self, tmp_path):
        guard = _load_guard()
        root = str(tmp_path)
        self._write(root, 1, _bench_blob(1000.0,
                                         _replay_extra(300.0, 50.0)))
        self._write(root, 2, _bench_blob(1000.0,
                                         _replay_extra(200.0, 50.0)))
        ok, lines = guard.check(root)
        assert not ok
        assert any("serving_replay_goodput" in l and "REGRESSION" in l
                   for l in lines)

    def test_goodput_noise_within_tolerance_passes(self, tmp_path):
        guard = _load_guard()
        root = str(tmp_path)
        self._write(root, 1, _bench_blob(1000.0,
                                         _replay_extra(300.0, 50.0)))
        self._write(root, 2, _bench_blob(1000.0,
                                         _replay_extra(270.0, 52.0)))
        ok, lines = guard.check(root)
        assert ok, "\n".join(lines)

    def test_ttft_p99_increase_fails_lower_is_better(self, tmp_path):
        guard = _load_guard()
        root = str(tmp_path)
        self._write(root, 1, _bench_blob(1000.0,
                                         _replay_extra(300.0, 50.0)))
        self._write(root, 2, _bench_blob(1000.0,
                                         _replay_extra(300.0, 80.0)))
        ok, lines = guard.check(root)
        assert not ok
        assert any("serving_replay_ttft" in l and "REGRESSION" in l
                   for l in lines)

    def test_absence_on_old_rounds_is_skip_not_floor(self, tmp_path):
        # rounds predating the rung contribute no floor/ceiling, and a
        # newest round without it reports absence, never failure
        guard = _load_guard()
        root = str(tmp_path)
        self._write(root, 1, _bench_blob(1000.0))
        self._write(root, 2, _bench_blob(1000.0,
                                         _replay_extra(300.0, 50.0)))
        ok, lines = guard.check(root)
        assert ok, "\n".join(lines)
        self._write(root, 3, _bench_blob(1000.0))
        ok, lines = guard.check(root)
        assert ok, "\n".join(lines)
        assert any("serving_replay_goodput" in l and "absent" in l
                   for l in lines)

    def test_failover_rungs_in_allowlists(self):
        guard = _load_guard()
        assert guard.ALLOWLIST_LOWER["serving_failover_recovery_s_p99"] \
            == "extra.serving_failover_replay.recovery_s_p99"
        assert guard.ALLOWLIST_ZERO["serving_failover_lost"] \
            == "extra.serving_failover_replay.lost"

    def test_failover_lost_nonzero_fails_even_on_first_run(self,
                                                           tmp_path):
        # the invariant has no baseline: one run with a positive lost
        # count is already a failure (and zero passes)
        guard = _load_guard()
        root = str(tmp_path)
        self._write(root, 1, _bench_blob(1000.0,
                                         _failover_extra(1, 0.5)))
        ok, lines = guard.check(root)
        assert not ok
        assert any("serving_failover_lost" in l and "REGRESSION" in l
                   for l in lines)
        self._write(root, 1, _bench_blob(1000.0,
                                         _failover_extra(0, 0.5)))
        ok, lines = guard.check(root)
        assert ok, "\n".join(lines)

    def test_failover_recovery_p99_ceiling(self, tmp_path):
        guard = _load_guard()
        root = str(tmp_path)
        self._write(root, 1, _bench_blob(1000.0,
                                         _failover_extra(0, 1.0)))
        self._write(root, 2, _bench_blob(1000.0,
                                         _failover_extra(0, 2.0)))
        ok, lines = guard.check(root)
        assert not ok
        assert any("serving_failover_recovery" in l
                   and "REGRESSION" in l for l in lines)
        self._write(root, 2, _bench_blob(1000.0,
                                         _failover_extra(0, 1.05)))
        ok, lines = guard.check(root)
        assert ok, "\n".join(lines)

    def test_checked_in_trajectory_is_green(self):
        guard = _load_guard()
        ok, lines = guard.check(REPO)
        assert ok, "\n".join(lines)
