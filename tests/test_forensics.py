"""Request forensics plane (monitor/forensics.py + the engine /
failover / replay hooks, /forensics + /requests/<rid> routes,
scorecard attribution, chrome-trace links).

The load-bearing contracts:

- **Phase decomposition**: the incremental phase machine folds
  event-to-event time into named phases that sum to the timeline's
  e2e BY CONSTRUCTION — exact even when the bounded event list
  truncates, and matching the engine cost record's e2e at retirement
  (same clock, same stamp).
- **Terminal uniqueness**: every terminal request (completed /
  rejected / expired / shed / quarantined / lost) carries exactly one
  terminal timeline event — pinned under an overload + preemption +
  deadline chaos run and under the failover coordinator's
  strand/quarantine paths.
- **Cause attribution**: forced queue-wait violations name
  ``queue_wait`` as the top cause, forced preemption violations name
  ``preempted_out`` (the acceptance construction).
- **Off path**: flag off = zero registrations, zero timelines; flag
  on = zero ADDED device synchronizations at any exectime sample rate
  (the PR 12 ``_block_until_ready`` indirection pin, slow-marked).
- **Tenant-attributed lifecycle instants** (the satellite fix):
  ``serving.shed`` / ``serving.expire`` / ``serving.preempt`` trace
  instants carry ``tenant``.
"""
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import monitor
from paddle_tpu.monitor import exectime
from paddle_tpu.monitor import forensics
from paddle_tpu.monitor import server
from paddle_tpu.monitor import trace


@pytest.fixture
def mon():
    """Monitor on, clean state; everything torn down after."""
    monitor.reset()
    server.stop_server()
    pt.set_flags({"FLAGS_enable_monitor": True})
    yield monitor
    server.stop_server()
    exectime.set_sample_rate(None)
    pt.set_flags({"FLAGS_enable_monitor": False,
                  "FLAGS_enable_monitor_server": False})
    monitor.reset()


def _engine(**kw):
    import jax
    from paddle_tpu.inference import ServingEngine
    from paddle_tpu.models import llama as L
    cfg = L.llama_tiny()
    params = L.init_params(cfg, jax.random.PRNGKey(3))
    return ServingEngine(L, params, cfg, **kw), cfg


def _reqs(cfg, lens, new, tenants=None, seed=0, **kw):
    from paddle_tpu.inference import Request
    rng = np.random.default_rng(seed)
    tenants = tenants or ["default"] * len(lens)
    return [Request(rid=i,
                    prompt=rng.integers(0, cfg.vocab_size,
                                        (n,)).astype(np.int32),
                    max_new_tokens=m, tenant=t, **kw)
            for i, (n, m, t) in enumerate(zip(lens, new, tenants))]


_TERMINAL_KINDS = set(forensics._TERMINAL_KIND.values())


def _terminal_events(tl: dict):
    return [e for e in tl["events"] if e["kind"] in _TERMINAL_KINDS]


# ---------------------------------------------------------------------------
# constructed timelines: phase machine, bounds, terminal uniqueness
# ---------------------------------------------------------------------------

class TestTimeline:
    def test_phase_decomposition_sums_exactly(self, mon):
        f = forensics
        f.note(1, "enqueue", t=0.0, tenant="a", priority=2)
        f.note(1, "admit", t=0.5)
        f.note(1, "first_token", t=0.7)
        f.note(1, "preempt", t=1.0, policy="youngest")
        f.note(1, "admit", t=1.4)
        f.note(1, "first_token", t=1.5)
        f.note_terminal(1, "completed", t=2.0)
        tl = f.request_payload(1)
        assert tl["state"] == "completed"
        assert tl["tenant"] == "a" and tl["priority"] == 2
        assert tl["phases"] == {
            "queue_wait": 500.0,          # 0.0 -> 0.5
            "prefill": pytest.approx(200.0 + 100.0),  # both runs
            "decode": pytest.approx(300.0 + 500.0),
            "preempted_out": pytest.approx(400.0),    # 1.0 -> 1.4
        }
        assert tl["phase_sum_ms"] == pytest.approx(tl["e2e_ms"])
        assert tl["e2e_ms"] == pytest.approx(2000.0)
        # timeline TTFT falls back to the LAST first_token (the run
        # the client keeps)
        assert tl["ttft_ms"] == pytest.approx(1500.0)
        assert len(_terminal_events(tl)) == 1

    def test_defer_coalesces_and_truncation_keeps_sums(self, mon,
                                                       monkeypatch):
        monkeypatch.setattr(forensics, "_MAX_EVENTS", 6)
        f = forensics
        f.note(3, "enqueue", t=0.0)
        # same-reason defers coalesce into ONE event with a count
        for _ in range(50):
            f.note_defer(3, "no_free_slot", queue_depth=4)
        tl = f.request_payload(3)
        defers = [e for e in tl["events"] if e["kind"] == "defer"]
        assert len(defers) == 1 and defers[0]["count"] == 50
        # alternating reasons can't coalesce -> the event bound bites,
        # the first event (causal anchor) survives, phases stay exact
        for i in range(20):
            f.note_defer(3, f"r{i % 3}", queue_depth=4)
        f.note(3, "admit", t=4.0)
        f.note_terminal(3, "completed", t=5.0)
        tl = f.request_payload(3)
        assert tl["truncated_events"] > 0
        assert len(tl["events"]) <= 6 + 1      # bound + terminal
        assert tl["events"][0]["kind"] == "enqueue"
        assert tl["phases"]["queue_wait"] == pytest.approx(4000.0)
        assert tl["phase_sum_ms"] == pytest.approx(tl["e2e_ms"])

    def test_terminal_unique_and_resubmission_restarts(self, mon):
        f = forensics
        f.note(9, "enqueue", t=0.0)
        f.note_terminal(9, "expired", t=1.0)
        f.note_terminal(9, "completed", t=2.0)    # ignored: one terminal
        tl = f.request_payload(9)
        assert tl["state"] == "expired"
        assert len(_terminal_events(tl)) == 1
        # a NEW submission of a finished rid restarts the timeline
        # (the engine restarts the run's mutable state with it)
        f.note(9, "enqueue", t=3.0)
        tl = f.request_payload(9)
        assert tl["state"] is None
        assert [e["kind"] for e in tl["events"]] == ["enqueue"]

    def test_store_evicts_terminal_first(self, mon, monkeypatch):
        monkeypatch.setattr(forensics, "_MAX_REQUESTS", 4)
        f = forensics
        f.note(100, "enqueue", t=0.0)              # stays OPEN
        for rid in (101, 102, 103):
            f.note(rid, "enqueue", t=0.0)
            f.note_terminal(rid, "completed", t=1.0)
        f.note(104, "enqueue", t=0.0)              # 5th: evicts 101
        assert f.tracked() == 4
        assert f.has(100) and not f.has(101) and f.has(104)
        assert monitor.snapshot()["counters"][
            "serving.forensics.requests.evicted"] == 1

    def test_strand_recovery_phase_and_lineage(self, mon):
        f = forensics
        f.note(5, "enqueue", t=0.0, tenant="a")
        f.note(5, "admit", t=0.1)
        f.note(5, "strand", t=0.5, replica="r0",
               recovered_from=["r0"])
        f.note(5, "redispatch", t=1.0, replica="r1")
        f.note(5, "enqueue", t=1.1)    # survivor re-admission: the
        #                                strand phase keeps running
        f.note(5, "admit", t=2.5)
        f.note(5, "first_token", t=2.6)
        f.note_terminal(5, "completed", t=3.0)
        tl = f.request_payload(5)
        assert tl["recovered_from"] == ["r0"]
        assert tl["phases"]["stranded_recovery"] == \
            pytest.approx(2000.0)                  # 0.5 -> 2.5
        assert tl["phase_sum_ms"] == pytest.approx(tl["e2e_ms"])


# ---------------------------------------------------------------------------
# attribution: forced dominant causes (the acceptance construction)
# ---------------------------------------------------------------------------

class TestAttribution:
    def test_queue_wait_vs_preemption_dominant_cause(self, mon):
        f = forensics
        # forced queue-wait TTFT violations (objective default 1000ms)
        for rid in (1, 2):
            f.note(rid, "enqueue", t=0.0)
            f.note(rid, "admit", t=2.0)
            f.note(rid, "first_token", t=2.1)
            f.note_terminal(rid, "completed", t=2.2)
        a = f.attribution_table()["ttft_p99_ms"]
        assert a["violations"] == 2
        assert a["top_cause"] == "queue_wait"
        assert a["by_cause_pct"]["queue_wait"] == 100.0
        monitor.reset()
        # forced preemption violations: preempted-out dominates TTFT
        for rid in (1, 2, 3):
            f.note(rid, "enqueue", t=0.0)
            f.note(rid, "admit", t=0.1)
            f.note(rid, "preempt", t=0.2)
            f.note(rid, "admit", t=2.3)
            f.note(rid, "first_token", t=2.4)
            f.note_terminal(rid, "completed", t=2.5)
        a = f.attribution_table()["ttft_p99_ms"]
        assert a["violations"] == 3
        assert a["top_cause"] == "preempted_out"
        # decode never attributes a TTFT violation, only e2e
        e = f.attribution_table()["e2e_p99_ms"]
        assert e["completed"] == 3 and e["violations"] == 0

    def test_decision_ring_coalesces_and_counts(self, mon):
        f = forensics
        for _ in range(30):
            f.decision("defer", rid=1, reason="watermark", need=2)
        f.decision("admit", rid=1, group=1)
        p = f.forensics_payload()
        assert p["decisions"]["total"] == 31
        assert p["decisions"]["by_kind"] == {"admit": 1, "defer": 30}
        ring = p["decisions"]["ring"]
        assert len(ring) == 2 and ring[0]["count"] == 30
        # the metric counts DISTINCT records (post-coalescing)
        assert monitor.snapshot()["counters"][
            "serving.forensics.decisions"] == 2


# ---------------------------------------------------------------------------
# engine chaos: overload + preemption + deadline, one run
# ---------------------------------------------------------------------------

@pytest.mark.serving
class TestEngineForensics:
    def test_chaos_run_terminal_and_phase_contracts(self, mon):
        """One overloaded run producing every engine-side terminal
        state: displaced shed, queue-full shed, deadline expiry,
        malformed reject, preempted-then-completed — each with exactly
        one terminal event and phases summing to its e2e."""
        from paddle_tpu.inference import (EngineOverloaded, Request,
                                          RequestRejected)
        eng, cfg = _engine(num_slots=2, max_len=16, page_size=4,
                           num_pages=5, decode_chunk=2, max_queue=3)
        r = _reqs(cfg, lens=(4, 4, 4), new=(8, 8, 8),
                  tenants=["a", "b", "a"])
        r[2].deadline_s = 0.004          # spent long before admission
        for x in r:
            eng.submit(x)                # queue now full (max_queue=3)
        with pytest.raises(EngineOverloaded):
            eng.submit(Request(rid=3, prompt=r[0].prompt,
                               max_new_tokens=4, tenant="c"))
        # priority 1 displaces the oldest priority-0 request (rid 0)
        eng.submit(Request(rid=4, prompt=np.array(r[0].prompt),
                           max_new_tokens=8, tenant="b", priority=1))
        with pytest.raises(RequestRejected):
            eng.submit(Request(rid=5, prompt=r[0].prompt,
                               max_new_tokens=float("inf")))
        eng.run()
        assert eng.stats.preempted >= 1  # the tiny pool forces it
        want = {0: "shed", 1: "completed", 2: "expired", 3: "shed",
                4: "completed", 5: "rejected"}
        for rid, state in want.items():
            tl = forensics.request_payload(rid)
            assert tl is not None and tl["state"] == state, (rid, tl)
            assert len(_terminal_events(tl)) == 1, tl
            # phases sum to the timeline's e2e (cost-record e2e when
            # the engine stamped one — same clock, same stamp)
            if tl["e2e_ms"] is not None:
                assert tl["phase_sum_ms"] == pytest.approx(
                    tl["e2e_ms"], abs=1.0), (rid, tl)
        # submit-time refusals never entered the engine: terminal-only
        assert forensics.request_payload(3)["e2e_ms"] == 0.0
        assert forensics.request_payload(5)["phases"] == {}
        # a preemption event carries the victim-selection inputs
        pre = [e for rid in (1, 4)
               for e in forensics.request_payload(rid)["events"]
               if e["kind"] == "preempt"]
        assert pre, "no preempt event on any completed timeline"
        for e in pre:
            # victim priority/tenant fold into the timeline header;
            # the event keeps the remaining selection inputs
            assert {"policy", "slot", "prior_preemptions",
                    "work", "discarded"} <= set(e)
            assert e["policy"] in ("slo", "youngest")
        # and the preempted timeline accumulated preempted_out time
        owner = next(rid for rid in (1, 4)
                     if any(e["kind"] == "preempt" for e in
                            forensics.request_payload(rid)["events"]))
        assert forensics.request_payload(owner)["phases"][
            "preempted_out"] > 0
        # decision audit ring saw the policy actions
        kinds = set(forensics.forensics_payload()
                    ["decisions"]["by_kind"])
        assert {"shed", "displace", "admit", "preempt"} <= kinds
        # satellite pin: shed/expire/preempt lifecycle instants are
        # tenant-attributed
        evs = trace.events()
        for name, tenant in (("serving.shed", {"a", "c"}),
                             ("serving.expire", {"a"}),
                             ("serving.preempt", {"a", "b"})):
            hits = [e for e in evs if e["name"] == name]
            assert hits, name
            for e in hits:
                assert e["args"].get("tenant") in tenant, (name, e)

    def test_defer_reasons_recorded(self, mon):
        """A blocked queue records typed admission deferrals
        (coalesced — bounded events however long the wait)."""
        eng, cfg = _engine(num_slots=1, max_len=16, page_size=4,
                           num_pages=4, decode_chunk=2)
        eng.run(_reqs(cfg, lens=(4, 4), new=(8, 4)))
        tl = forensics.request_payload(1)
        defers = [e for e in tl["events"] if e["kind"] == "defer"]
        assert defers, tl
        assert all(e["reason"] in ("no_free_slot", "watermark",
                                   "alloc_failed", "tenant_cap")
                   for e in defers)

    def test_off_path_zero_registrations(self):
        monitor.reset()
        assert not monitor.enabled()
        eng, cfg = _engine(num_slots=2, max_len=32, page_size=4,
                           decode_chunk=2)
        eng.run(_reqs(cfg, lens=(4,), new=(3,)))
        assert forensics.tracked() == 0
        assert forensics.decisions() == []
        assert forensics.attribution_table() == {}
        assert forensics.flight_block() is None
        assert monitor.snapshot() == {}

    @pytest.mark.slow  # tier-1 budget: same zero-sync contract pinned
    # fast by the SLO plane's cost-record test; forensics rides the
    # identical seams
    def test_zero_added_syncs_at_any_rate(self, mon, monkeypatch):
        """Forensics is pure host bookkeeping at seams the engine
        already synchronized: at exec sample rate 0 AND 1, with a
        preemption-forcing pool, zero added block_until_ready."""
        calls = []
        monkeypatch.setattr(
            exectime, "_block_until_ready",
            lambda outputs: calls.append(1))
        for rate in (0, 1):
            exectime.set_sample_rate(rate)
            eng, cfg = _engine(num_slots=2, max_len=16, page_size=4,
                               num_pages=5, decode_chunk=2)
            eng.run(_reqs(cfg, lens=(4, 4, 4), new=(8, 8, 8)))
            assert eng.stats.preempted >= 1
            assert forensics.tracked() == 3     # plane was live
            assert calls == [], f"rate {rate} added {len(calls)} syncs"
            monitor.reset()


# ---------------------------------------------------------------------------
# failover coordinator: strand lineage + coordinator terminals
# ---------------------------------------------------------------------------

class _Req:
    """Duck-typed request: exactly the attributes the journal reads."""

    def __init__(self, rid, prompt=(1, 2, 3), max_new_tokens=4,
                 temperature=0.0, tenant="t0", priority=0,
                 deadline_s=None, prompt_spec=None, key=None):
        self.rid = rid
        self.prompt = np.asarray(prompt, np.int32)
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.tenant = tenant
        self.priority = priority
        self.deadline_s = deadline_s
        self.prompt_spec = prompt_spec
        self.key = key


class TestFailoverForensics:
    def test_strand_redispatch_lineage_and_quarantine(self, mon,
                                                      tmp_path):
        from paddle_tpu.inference import failover as fo
        j = fo.AdmissionJournal("r0", dir_path=str(tmp_path))
        j.admit(_Req(7))
        j.admit(_Req(8))
        c = fo.FailoverCoordinator(heartbeat_dir=str(tmp_path),
                                   quarantine_attempts=2)
        assert c.note_replaced("r0", now=10.0) == 2
        tl = forensics.request_payload(7)
        (ev,) = [e for e in tl["events"] if e["kind"] == "strand"]
        assert ev["replica"] == "r0" and ev["attempts"] == 1
        assert tl["recovered_from"] == ["r0"]
        # re-dispatch hop lands on the timeline
        for rec in c.due(11.0):
            c.redispatched(rec, "r1", now=11.0)
        tl = forensics.request_payload(7)
        assert [e["kind"] for e in tl["events"]].count("redispatch") \
            == 1
        # the survivor dies too: second strand quarantines (attempts
        # bound) with ONE coordinator terminal event
        j1 = fo.AdmissionJournal("r1", dir_path=str(tmp_path))
        for rid in (7, 8):
            q = _Req(rid)
            q._failover_attempts = 1          # lineage rides the req
            q._recovered_from = ["r0"]
            j1.admit(q)
        assert c.note_replaced("r1", now=20.0) == 2
        for rid in (7, 8):
            tl = forensics.request_payload(rid)
            assert tl["state"] == "quarantined", tl
            assert len(_terminal_events(tl)) == 1
        # breaker transitions land in the decision ring
        for _ in range(3):
            c.admission_result("r2", ok=False, now=30.0)
        kinds = forensics.forensics_payload()["decisions"]["by_kind"]
        assert kinds.get("breaker") == 1


# ---------------------------------------------------------------------------
# surfaces: routes, flight record, chrome trace, scorecard
# ---------------------------------------------------------------------------

def _get(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


class TestSurfaces:
    def _seed_plane(self):
        f = forensics
        f.note(42, "enqueue", t=0.0, tenant="a")
        f.note(42, "admit", t=1.5)
        f.note(42, "first_token", t=1.6)
        f.note_terminal(42, "completed", t=2.0)
        f.decision("admit", rid=42, group=1)

    def test_routes_end_to_end(self, mon):
        self._seed_plane()
        srv = server.start_server(port=0)
        status, body = _get(f"{srv.url}/forensics")
        assert status == 200
        p = json.loads(body)
        assert p["kind"] == "paddle_tpu.forensics"
        assert p["requests"]["42"]["state"] == "completed"
        assert p["attribution"]["ttft_p99_ms"]["top_cause"] \
            == "queue_wait"
        assert p["slowest"][0]["rid"] == 42
        status, body = _get(f"{srv.url}/requests/42")
        assert status == 200
        tl = json.loads(body)
        assert tl["rid"] == 42 and tl["phase_sum_ms"] == \
            pytest.approx(tl["e2e_ms"])
        status, body = _get(f"{srv.url}/requests/999")
        assert status == 404 and b"no timeline" in body
        _, idx = _get(f"{srv.url}/")
        routes = json.loads(idx)["routes"]
        assert "/forensics" in routes and "/requests/<rid>" in routes
        # the tracked gauge registered at payload build
        assert monitor.snapshot()["gauges"][
            "serving.forensics.requests.tracked"] == 1

    def test_flight_record_carries_forensics_block(self, mon):
        self._seed_plane()
        p = trace.flight_payload(reason="test")
        assert p["forensics"]["tracked"] == 1
        assert p["forensics"]["slowest"][0]["rid"] == 42
        assert p["forensics"]["attribution"]["ttft_p99_ms"][
            "violations"] == 1
        # guarded: a broken forensics payload never kills the dump
        import paddle_tpu.monitor.forensics as f

        def boom(*a, **k):
            raise RuntimeError("boom")
        orig = f.flight_block
        f.flight_block = boom
        try:
            assert trace.flight_payload()["forensics"] is None
        finally:
            f.flight_block = orig

    def test_chrome_trace_links_serving_events(self, mon, tmp_path):
        self._seed_plane()
        trace.instant("serving.retire", rid=42, tokens=3)
        trace.instant("serving.retire", rid=7777)      # no timeline
        out = tmp_path / "trace.json"
        trace.export_chrome_trace(str(out))
        evs = json.loads(out.read_text())["traceEvents"]
        linked = [e for e in evs
                  if e.get("args", {}).get("rid") == 42]
        assert linked
        assert all(e["args"]["forensics"] == "/requests/42"
                   for e in linked)
        bare = [e for e in evs
                if e.get("args", {}).get("rid") == 7777]
        assert bare and all("forensics" not in e["args"]
                            for e in bare)

    def test_scorecard_attribution_blocks(self, mon):
        from paddle_tpu.loadgen.replay import ReplayResult
        from paddle_tpu.loadgen.scorecard import build_scorecard
        from paddle_tpu.loadgen.traces import generate_trace
        self._seed_plane()
        tr = generate_trace(1, duration_s=0.1, rate=30.0)
        terminal = {
            0: {"state": "completed", "tenant": "a", "tokens": 4,
                "prompt_len": 4, "preemptions": 2},
            1: {"state": "completed", "tenant": "a", "tokens": 4,
                "prompt_len": 4, "preemptions": 0,
                "recovered_from": ["r0"]},
            2: {"state": "shed", "tenant": "b", "tokens": 0,
                "prompt_len": 4, "reason": "displaced by rid 9",
                "retry_after_s": 0.5},
            3: {"state": "expired", "tenant": "b", "tokens": 0,
                "prompt_len": 4},
        }
        res = ReplayResult(
            trace=tr, terminal=terminal, episodes=[],
            engine_stats={"engine0": {"generated": 8, "discarded": 0}},
            engine_flags={}, steps=10, dt_per_step=0.01, wall_s=1.0,
            offered=4, offered_tokens=16)
        card = build_scorecard(res)
        det = card["deterministic"]["attribution"]
        assert det == {"requests_preempted": 1, "preemptions": 2,
                       "displaced": 1, "expired": 1, "recovered": 1,
                       "quarantined": 0, "lost": 0}
        # the timing half is the forensics violation-cause table
        tim = card["timing"]["attribution"]
        assert tim["ttft_p99_ms"]["top_cause"] == "queue_wait"


# ---------------------------------------------------------------------------
# marginal overhead (the acceptance number, PR 12 interleaved harness)
# ---------------------------------------------------------------------------

def measure_forensics_overhead(windows=6):
    """Median per-window MARGINAL engine overhead of the forensics
    plane: both arms run monitor-ON (the plane the acceptance gate
    compares against), the baseline arm with every forensics entry
    point no-oped. Interleaved windows of the serving_paged CPU trace
    shape, PR 12 pattern. Returns (median_pct, pcts). Measured on
    this container: see CHANGES.md."""
    import time as _time

    import jax
    from paddle_tpu.inference import Request, ServingEngine
    from paddle_tpu.models import llama as L

    cfg = L.llama_tiny(num_hidden_layers=2)
    params = jax.jit(lambda: L.init_params(cfg, jax.random.PRNGKey(0)))()
    jax.block_until_ready(params["embed"])
    rng = np.random.default_rng(42)
    trace_lens = [(int(rng.choice((4, 8, 16))),
                   int(rng.choice((4, 8, 16)))) for _ in range(16)]
    trace_lens.sort(key=lambda t: -t[1])
    max_len = max(p for p, _ in trace_lens) + max(g for _, g in
                                                  trace_lens)
    pt.set_flags({"FLAGS_enable_monitor": True})
    hooks = ("note", "note_defer", "note_spec", "note_terminal",
             "decision")
    saved = {h: getattr(forensics, h) for h in hooks}

    def run_once(base, live):
        for h in hooks:
            setattr(forensics, h,
                    saved[h] if live else (lambda *a, **k: None))
        eng = ServingEngine(L, params, cfg, num_slots=4,
                            max_len=max_len, page_size=4,
                            decode_chunk=8)
        reqs = [Request(rid=base + i,
                        prompt=rng.integers(0, cfg.vocab_size, (p,))
                        .astype(np.int32), max_new_tokens=g,
                        tenant=f"t{i % 4}")
                for i, (p, g) in enumerate(trace_lens)]
        t0 = _time.perf_counter()
        eng.run(reqs)
        return _time.perf_counter() - t0

    try:
        run_once(0, False), run_once(10_000, True)    # compile + warm
        pcts = []
        for w in range(windows):
            t_off = run_once(20_000 + w * 1000, False)
            t_on = run_once(50_000 + w * 1000, True)
            pcts.append((t_on - t_off) / t_off * 100.0)
    finally:
        for h in hooks:
            setattr(forensics, h, saved[h])
        pt.set_flags({"FLAGS_enable_monitor": False})
        monitor.reset()
    pcts.sort()
    mid = len(pcts) // 2
    med = pcts[mid] if len(pcts) % 2 else (pcts[mid - 1]
                                           + pcts[mid]) / 2
    return med, pcts


@pytest.mark.slow
@pytest.mark.serving
def test_forensics_overhead_harness():
    """Timelines + decision ring are bounded host-side appends at
    seams that already synchronized: the forensics-live engine stays
    within noise of forensics-stubbed, monitor ON in both arms. The
    tier-1 bound is loose (shared container swings ±10% window to
    window); the <1% acceptance number is the interleaved-window
    median recorded in CHANGES.md and docs/observability.md."""
    med, pcts = measure_forensics_overhead()
    print(f"\nforensics marginal overhead: median {med:+.2f}% "
          f"windows {[f'{p:+.1f}' for p in pcts]}")
    assert med < 10.0, (med, pcts)
