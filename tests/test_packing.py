"""Sequence-packed training: segment-aware flash attention kernel
(interpret mode — the hardware-free kernel path), the varlen dispatch
surface, the greedy first-fit packing collator, and end-to-end
packed-vs-unpacked training parity for both model families."""
import importlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle  # noqa: F401  (framework init)
from paddle_tpu.io import packing as PK
from paddle_tpu.models import llama as L
from paddle_tpu.models import moe as M

FA = importlib.import_module("paddle_tpu.kernels.flash_attention")
AT = importlib.import_module("paddle_tpu.kernels.autotune")

RNG = np.random.default_rng(3)


def rand(shape, dtype=jnp.float32):
    return jnp.asarray(RNG.normal(size=shape), dtype)


def make_row(lens, s):
    """One packed row's (segment_ids, positions) from doc lengths
    (rest = padding)."""
    seg = np.full(s, -1, np.int32)
    pos = np.zeros(s, np.int32)
    o = 0
    for i, ln in enumerate(lens):
        seg[o:o + ln] = i
        pos[o:o + ln] = np.arange(ln)
        o += ln
    return seg, pos


def make_batch(rows, s):
    segs, poss = zip(*(make_row(r, s) for r in rows))
    return jnp.asarray(np.stack(segs)), jnp.asarray(np.stack(poss))


# ---------------------------------------------------------------------------
# kernel numerics (interpret mode vs the jnp reference)
# ---------------------------------------------------------------------------

class TestSegmentKernel:
    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_matches_reference(self, causal):
        B, S, H, KV, D = 2, 128, 4, 2, 32
        q, k, v = rand((B, S, H, D)), rand((B, S, KV, D)), rand((B, S, KV, D))
        seg, pos = make_batch([[50, 40, 30], [128]], S)
        ref = FA.segment_attention_ref(q, k, v, seg, seg, pos, pos,
                                       causal=causal)
        out = FA.flash_attention_segments(q, k, v, seg, seg, pos, pos,
                                          causal=causal, interpret=True,
                                          block_q=32, block_k=32)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.slow
    def test_forward_bf16(self):
        B, S, H, KV, D = 1, 128, 4, 2, 32
        q = rand((B, S, H, D), jnp.bfloat16)
        k = rand((B, S, KV, D), jnp.bfloat16)
        v = rand((B, S, KV, D), jnp.bfloat16)
        seg, pos = make_batch([[70, 58]], S)
        ref = FA.segment_attention_ref(q, k, v, seg, seg, pos, pos,
                                       causal=True)
        out = FA.flash_attention_segments(q, k, v, seg, seg, pos, pos,
                                          causal=True, interpret=True,
                                          block_q=64, block_k=64)
        np.testing.assert_allclose(
            np.asarray(out).astype(np.float32),
            np.asarray(ref).astype(np.float32), rtol=3e-2, atol=3e-2)

    def _grad_check(self, causal):
        """Both backward kernels (dq and dkv, GQA group-sum) through the
        custom_vjp in interpret mode."""
        B, S, H, KV, D = 2, 64, 4, 2, 16
        q, k, v = rand((B, S, H, D)), rand((B, S, KV, D)), rand((B, S, KV, D))
        seg, pos = make_batch([[30, 20, 10], [40, 24]], S)

        def lf(q, k, v):
            return (FA.flash_attention_segments(
                q, k, v, seg, seg, pos, pos, causal=causal,
                interpret=True, block_q=32, block_k=32) ** 2).sum()

        def lr(q, k, v):
            return (FA.segment_attention_ref(
                q, k, v, seg, seg, pos, pos, causal=causal) ** 2).sum()

        g1 = jax.grad(lf, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=5e-4)

    def test_grad_matches_reference_causal(self):
        self._grad_check(True)

    @pytest.mark.slow
    @pytest.mark.slow  # tier-1 budget (ISSUE 19 rebalance): causal grad parity above + the interpret-
    # kernel grad pin cover the backward seam
    def test_grad_matches_reference_noncausal(self):
        self._grad_check(False)

    def test_single_segment_matches_dense_flash(self):
        """One full-row document == the dense flash kernel (the packed
        kernel is a strict generalisation)."""
        B, S, H, D = 2, 128, 2, 32
        q, k, v = rand((B, S, H, D)), rand((B, S, H, D)), rand((B, S, H, D))
        seg = jnp.zeros((B, S), jnp.int32)
        pos = jnp.broadcast_to(jnp.arange(S), (B, S))
        dense = FA.flash_attention(q, k, v, causal=True, interpret=True)
        out = FA.flash_attention_segments(q, k, v, seg, seg, pos, pos,
                                          causal=True, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                                   rtol=1e-5, atol=1e-5)

    def test_padding_rows_exactly_zero_with_zero_grad(self):
        B, S, H, D = 1, 32, 2, 16
        q, k, v = rand((B, S, H, D)), rand((B, S, H, D)), rand((B, S, H, D))
        seg, pos = make_batch([[20]], S)     # 12 padding tokens

        out = FA.flash_attention_segments(q, k, v, seg, seg, pos, pos,
                                          causal=True, interpret=True,
                                          block_q=16, block_k=16)
        np.testing.assert_array_equal(np.asarray(out[:, 20:]), 0.0)
        # gradients w.r.t. padding-position k/v are exactly zero (no
        # real token attends across a segment boundary)
        g = jax.grad(lambda k: (FA.flash_attention_segments(
            q, k, v, seg, seg, pos, pos, causal=True, interpret=True,
            block_q=16, block_k=16)[:, :20] ** 2).sum())(k)
        np.testing.assert_array_equal(np.asarray(g[:, 20:]), 0.0)

    def test_block_skipping_preserves_output(self):
        """Block-aligned documents produce skippable off-diagonal blocks;
        skipping must not change the numerics."""
        B, S = 1, 128
        q, k, v = rand((B, S, 2, 16)), rand((B, S, 2, 16)), rand((B, S, 2, 16))
        seg, pos = make_batch([[32, 32, 32, 32]], S)
        skipped, total = FA.count_skipped_blocks(seg, seg, pos, pos,
                                                 32, 32, True)
        assert total == 16
        # block-diagonal layout: only the 4 diagonal blocks can run
        assert skipped == 12
        ref = FA.segment_attention_ref(q, k, v, seg, seg, pos, pos,
                                       causal=True)
        out = FA.flash_attention_segments(q, k, v, seg, seg, pos, pos,
                                          causal=True, interpret=True,
                                          block_q=32, block_k=32)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_count_skipped_blocks_causal_diagonal(self):
        """A single full-row document under causal recovers the dense
        above-the-diagonal skip count."""
        S, bq = 128, 32
        seg = jnp.zeros((1, S), jnp.int32)
        pos = jnp.arange(S)[None, :]
        skipped, total = FA.count_skipped_blocks(seg, seg, pos, pos,
                                                 bq, bq, True)
        n = S // bq
        assert total == n * n
        assert skipped == n * (n - 1) // 2     # strictly-above-diagonal

    def test_segments_supported_rules(self):
        q = rand((2, 128, 4, 32))
        k = rand((2, 128, 2, 32))
        assert FA.segments_supported(q, k, block_q=128, block_k=128)
        # k-side lane rule: a 64-wide k block over Sk=128 is neither
        # 128-divisible nor equal to Sk -> unsupported
        assert not FA.segments_supported(q, k, block_q=64, block_k=64)
        # non-divisible lengths fall back
        assert not FA.segments_supported(rand((2, 100, 4, 32)),
                                         rand((2, 100, 2, 32)))


# ---------------------------------------------------------------------------
# varlen functional surface (flash_attn_unpadded et al.)
# ---------------------------------------------------------------------------

class TestVarlenSurface:
    def test_cu_seqlens_overflow_guard(self):
        import paddle_tpu.nn.functional as F
        from paddle_tpu.core import enforce as E
        q = paddle.to_tensor(np.zeros((8, 1, 8), "float32"))
        cu_bad = paddle.to_tensor(np.array([0, 5, 12], "int32"))
        cu_ok = paddle.to_tensor(np.array([0, 5, 8], "int32"))
        with pytest.raises(E.InvalidArgumentError) as ei:
            F.flash_attn_unpadded(q, q, q, cu_bad, cu_ok)
        assert "12" in str(ei.value) and "8" in str(ei.value)
        with pytest.raises(E.InvalidArgumentError):
            F.flash_attn_unpadded(q, q, q, cu_ok, cu_bad)
        # cu[-1] < T stays the documented trailing-padding convention
        out, _ = F.flash_attn_unpadded(
            q, q, q, paddle.to_tensor(np.array([0, 5], "int32")),
            paddle.to_tensor(np.array([0, 5], "int32")))
        np.testing.assert_allclose(np.asarray(out.numpy())[5:], 0.0)

    def test_gqa_matches_per_sequence_reference(self):
        """GQA varlen path (grouped einsum, no kv repeat) vs dense
        per-sequence GQA attention."""
        import paddle_tpu.nn.functional as F
        rng = np.random.default_rng(5)
        lens = [6, 10]
        T, H, KV, D = sum(lens), 4, 2, 16
        q = rng.normal(size=(T, H, D)).astype("float32")
        k = rng.normal(size=(T, KV, D)).astype("float32")
        v = rng.normal(size=(T, KV, D)).astype("float32")
        cu = np.cumsum([0] + lens).astype("int32")
        out, _ = F.flash_attn_unpadded(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
            paddle.to_tensor(cu), paddle.to_tensor(cu), causal=True)
        out = np.asarray(out.numpy())
        for i, ln in enumerate(lens):
            lo, hi = cu[i], cu[i + 1]
            ref = F.sdpa_reference(jnp.asarray(q[None, lo:hi]),
                                   jnp.asarray(k[None, lo:hi]),
                                   jnp.asarray(v[None, lo:hi]), causal=True)
            np.testing.assert_allclose(out[lo:hi], np.asarray(ref)[0],
                                       rtol=1e-4, atol=1e-5)

    def test_varlen_dispatch_counter(self):
        """flash_attn_unpadded routes through the segment dispatcher —
        off-TPU that is the varlen_fallback counter. The dispatcher is
        (re)installed explicitly: an earlier test's kernels.unregister()
        teardown may have emptied the seam."""
        import paddle_tpu.nn.functional as F
        from paddle_tpu import kernels
        from paddle_tpu.nn.functional import attention as att
        q = paddle.to_tensor(np.random.default_rng(0).normal(
            size=(8, 2, 16)).astype("float32"))
        cu = paddle.to_tensor(np.array([0, 8], "int32"))
        prev = att._SEGMENT_IMPL
        att.register_segment_impl(kernels.dispatched_segment_attention)
        try:
            kernels.reset_dispatch_stats()
            F.flash_attn_unpadded(q, q, q, cu, cu, causal=True)
            stats = kernels.dispatch_stats()
        finally:
            att.register_segment_impl(prev)
        assert stats["varlen"] + stats["varlen_fallback"] == 1

    def test_sdpa_raw_segment_path_defaults_positions(self):
        """sdpa_raw(segment_ids=...) without positions uses the global
        arange — identical to segment-local for contiguous packing."""
        from paddle_tpu.nn.functional.attention import sdpa_raw
        B, S, H, D = 1, 32, 2, 16
        q, k, v = rand((B, S, H, D)), rand((B, S, H, D)), rand((B, S, H, D))
        seg, pos = make_batch([[20, 12]], S)
        a = sdpa_raw(q, k, v, is_causal=True, segment_ids=seg)
        b = sdpa_raw(q, k, v, is_causal=True, segment_ids=seg,
                     positions=pos)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# packing collator
# ---------------------------------------------------------------------------

class TestPackingCollator:
    def docs(self, lens, vocab=100, seed=0):
        rng = np.random.default_rng(seed)
        return [rng.integers(1, vocab, (ln,)).astype(np.int32)
                for ln in lens]

    def test_deterministic(self):
        docs = self.docs([17, 40, 9, 33, 64, 5])
        a = PK.pack_documents(docs, 64)
        b = PK.pack_documents(docs, 64)
        for key in a:
            np.testing.assert_array_equal(a[key], b[key])

    def test_first_fit_layout_and_positions(self):
        docs = self.docs([40, 30, 20])
        p = PK.pack_documents(docs, 64)
        # first-fit: [40, 20] in row 0 (20 fits the 24-slot gap), [30]
        # in row 1
        assert p["ids"].shape == (2, 64)
        seg = p["segment_ids"]
        assert list(seg[0, :40]) == [0] * 40
        assert list(seg[0, 40:60]) == [1] * 20
        assert list(seg[0, 60:]) == [-1] * 4
        assert list(seg[1, :30]) == [0] * 30
        # positions restart per document
        np.testing.assert_array_equal(p["positions"][0, 40:60],
                                      np.arange(20))

    def test_labels_stop_at_boundaries(self):
        docs = self.docs([4, 3])
        p = PK.pack_documents(docs, 8)
        ids, lab = p["ids"][0], p["labels"][0]
        # inside-doc next-token targets
        np.testing.assert_array_equal(lab[:3], ids[1:4])
        assert lab[3] == PK.IGNORE_INDEX         # last token of doc 0
        np.testing.assert_array_equal(lab[4:6], ids[5:7])
        assert lab[6] == PK.IGNORE_INDEX         # last token of doc 1
        assert lab[7] == PK.IGNORE_INDEX         # padding

    def test_long_docs_split_into_chunks(self):
        docs = self.docs([150])
        p = PK.pack_documents(docs, 64)
        assert p["ids"].shape[0] == 3            # 64 + 64 + 22
        assert PK.packing_efficiency(p) == pytest.approx(150 / 192)
        # each chunk restarts positions (its own segment)
        assert p["positions"][1, 0] == 0

    def test_efficiency_beats_padding(self):
        lens = PK.heavy_tailed_lengths(128, 32, seed=1)
        p = PK.pack_documents(self.docs(lens), 128)
        rows = p["ids"].shape[0]
        assert rows < len(lens)                  # packed tighter than 1/doc
        assert PK.packing_efficiency(p) > sum(lens) / (len(lens) * 128)

    def test_max_rows_overflow_raises(self):
        from paddle_tpu.core import enforce as E
        with pytest.raises(E.ResourceExhaustedError):
            PK.pack_documents(self.docs([60, 60, 60]), 64, max_rows=2)

    def test_collator_and_monitor_gauge(self):
        from paddle_tpu import monitor
        from paddle_tpu.core import flags as _flags
        coll = PK.PackingCollator(64)
        _flags.set_flags({"enable_monitor": True})
        try:
            monitor.reset()
            out = coll(self.docs([30, 30, 30]))
            snap = monitor.snapshot()
            assert snap["gauges"]["packing.efficiency"] == pytest.approx(
                PK.packing_efficiency(out), abs=1e-3)
            assert snap["counters"]["packing.documents"] == 3
        finally:
            _flags.set_flags({"enable_monitor": False})
            monitor.reset()


# ---------------------------------------------------------------------------
# end-to-end packed-vs-unpacked training parity
# ---------------------------------------------------------------------------

def _unpacked_batch(docs, maxl):
    ids = np.zeros((len(docs), maxl), np.int32)
    lab = np.full((len(docs), maxl), -100, np.int32)
    for i, d in enumerate(docs):
        ids[i, :len(d)] = d
        lab[i, :len(d) - 1] = d[1:]
    return jnp.asarray(ids), jnp.asarray(lab)


def _doc_trace(vocab, lens, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, (ln,)).astype(np.int32) for ln in lens]


class TestPackedTrainingParity:
    """Packed rows must emit IDENTICAL loss/grads to the equivalent
    unpacked (one-doc-per-row, ignore_index-padded) batch: same token
    contexts, same valid-token mean. MoE parity runs with the router
    aux loss off — the aux term is a batch statistic over ALL processed
    tokens, and the padded batch legitimately processes more of them."""

    def test_llama_loss_fp32(self):
        cfg = L.llama_tiny(vocab_size=64)
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        docs = _doc_trace(64, [40, 24])
        pb = PK.packed_train_batch(PK.pack_documents(docs, 64))
        ub = _unpacked_batch(docs, 40)
        lp = L.loss_fn(params, pb, cfg)
        lu = L.loss_fn(params, ub, cfg)
        np.testing.assert_allclose(float(lp), float(lu), rtol=1e-5)

    @pytest.mark.slow
    def test_llama_grads_fp32(self):
        cfg = L.llama_tiny(vocab_size=64)
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        docs = _doc_trace(64, [40, 24])
        pb = PK.packed_train_batch(PK.pack_documents(docs, 64))
        ub = _unpacked_batch(docs, 40)
        gp = jax.grad(lambda p: L.loss_fn(p, pb, cfg))(params)
        gu = jax.grad(lambda p: L.loss_fn(p, ub, cfg))(params)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5), gp, gu)

    def test_moe_loss_fp32(self):
        # grads parity for the MoE family runs in the slow lane
        # (test_moe_parity_larger_trace_with_grads_bf16)
        cfg = M.moe_tiny(vocab_size=64, router_aux_loss_coef=0.0)
        params = M.init_params(cfg, jax.random.PRNGKey(1))
        docs = _doc_trace(64, [40, 24], seed=2)
        pb = PK.packed_train_batch(PK.pack_documents(docs, 64))
        ub = _unpacked_batch(docs, 40)
        lp = M.loss_fn(params, pb, cfg)
        lu = M.loss_fn(params, ub, cfg)
        np.testing.assert_allclose(float(lp), float(lu), rtol=1e-5)

    def test_llama_packed_train_step_jits(self):
        cfg = L.llama_tiny(vocab_size=64)
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        opt = L.adamw_init(params)
        step = L.make_train_step(cfg, lr=1e-3)
        pb = PK.packed_train_batch(
            PK.pack_documents(_doc_trace(64, [40, 24, 30]), 64))
        p2, o2, loss = step(params, opt, pb)
        assert np.isfinite(float(loss))
        assert int(o2["step"]) == 1

    def test_kernel_interpret_mode_matches_fallback(self):
        """The same packed llama loss through the interpret-mode segment
        KERNEL vs the jnp fallback (the two dispatcher arms)."""
        from paddle_tpu.nn.functional import attention as att
        cfg = L.llama_tiny(vocab_size=64)
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        pb = PK.packed_train_batch(
            PK.pack_documents(_doc_trace(64, [40, 24]), 64))
        prev = att._SEGMENT_IMPL
        try:
            att.register_segment_impl(None)      # jnp reference
            l_ref = float(L.loss_fn(params, pb, cfg))
            att.register_segment_impl(
                lambda *a, **kw: FA.flash_attention_segments(
                    *a, **kw, interpret=True))
            l_kern = float(L.loss_fn(params, pb, cfg))
        finally:
            att.register_segment_impl(prev)
        np.testing.assert_allclose(l_kern, l_ref, rtol=1e-5)

    @pytest.mark.slow
    def test_moe_grads_fp32(self):
        cfg = M.moe_tiny(vocab_size=64, router_aux_loss_coef=0.0)
        params = M.init_params(cfg, jax.random.PRNGKey(1))
        docs = _doc_trace(64, [40, 24], seed=2)
        pb = PK.packed_train_batch(PK.pack_documents(docs, 64))
        ub = _unpacked_batch(docs, 40)
        gp = jax.grad(lambda p: M.loss_fn(p, pb, cfg))(params)
        gu = jax.grad(lambda p: M.loss_fn(p, ub, cfg))(params)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5), gp, gu)

    @pytest.mark.slow
    def test_llama_parity_bf16(self):
        cfg = L.llama_tiny(vocab_size=64, dtype=jnp.bfloat16)
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        docs = _doc_trace(64, [80, 48])
        pb = PK.packed_train_batch(PK.pack_documents(docs, 128))
        ub = _unpacked_batch(docs, 80)
        np.testing.assert_allclose(float(L.loss_fn(params, pb, cfg)),
                                   float(L.loss_fn(params, ub, cfg)),
                                   rtol=2e-2)

    @pytest.mark.slow
    def test_moe_parity_larger_trace_with_grads_bf16(self):
        cfg = M.moe_tiny(vocab_size=64, dtype=jnp.bfloat16,
                         router_aux_loss_coef=0.0)
        params = M.init_params(cfg, jax.random.PRNGKey(1))
        docs = _doc_trace(64, [60, 36, 20, 12], seed=3)
        pb = PK.packed_train_batch(PK.pack_documents(docs, 128))
        ub = _unpacked_batch(docs, 60)
        gp = jax.grad(lambda p: M.loss_fn(p, pb, cfg))(params)
        gu = jax.grad(lambda p: M.loss_fn(p, ub, cfg))(params)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=5e-2, atol=5e-2), gp, gu)

    @pytest.mark.slow
    def test_llama_grad_parity_through_interpret_kernel(self):
        """Full packed training grads with the interpret-mode segment
        kernel engaged (custom_vjp through the model) vs the fallback."""
        from paddle_tpu.nn.functional import attention as att
        cfg = L.llama_tiny(vocab_size=64)
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        pb = PK.packed_train_batch(
            PK.pack_documents(_doc_trace(64, [40, 24]), 64))
        prev = att._SEGMENT_IMPL
        try:
            att.register_segment_impl(None)
            g_ref = jax.grad(lambda p: L.loss_fn(p, pb, cfg))(params)
            att.register_segment_impl(
                lambda *a, **kw: FA.flash_attention_segments(
                    *a, **kw, interpret=True))
            g_kern = jax.grad(lambda p: L.loss_fn(p, pb, cfg))(params)
        finally:
            att.register_segment_impl(prev)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
            g_kern, g_ref)


# ---------------------------------------------------------------------------
# autotune: the varlen block knob
# ---------------------------------------------------------------------------

class TestVarlenAutotune:
    def _call(self, cache, measure):
        return AT.varlen_blocks((2, 256, 4, 32), (2, 256, 2, 32),
                                jnp.float32, True,
                                measure=measure, cache=cache)

    def test_measures_once_then_cached(self, tmp_path):
        cache = AT.AutotuneCache(str(tmp_path / "c.json"))
        calls = []

        def measure(bq, bk):
            calls.append((bq, bk))
            return 0.001 if (bq, bk) == (256, 128) else 0.01

        assert self._call(cache, measure) == (256, 128)
        n = len(calls)
        assert n >= 2
        assert self._call(cache, measure) == (256, 128)
        assert len(calls) == n                  # cache hit, no re-measure

    def test_key_space_disjoint_from_dense_flash(self, tmp_path):
        cache = AT.AutotuneCache(str(tmp_path / "c.json"))
        self._call(cache, lambda bq, bk: 0.001)
        keys = list(cache._mem)
        assert keys and all(k.startswith("varlen:") for k in keys)

    def test_candidates_respect_segment_lane_rule(self):
        # sk = 256: a 64-wide k block is illegal for the segment arrays
        for bq, bk in AT.varlen_candidates(2, 8, 256, 256, 32,
                                           jnp.float32):
            assert bk % 128 == 0 or bk == 256
