"""Worker for the elastic scale-in/scale-out test (run via the elastic
manager, not collected by pytest).

Full-batch deterministic GD sharded over whatever world it wakes up in:
the global math is identical at any world size, so the loss trajectory
must be CONTINUOUS across 3->2->3 world re-forms if (and only if)
checkpoint resume works. Logs one STEP line per step for the test to
stitch together.

Kill injection: on run 0, the highest rank exits hard at KILL_AT_STEP —
the crash the elastic manager must absorb.
"""
import os
import sys

_flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
          if "xla_force_host_platform_device_count" not in f]
_flags.append("--xla_force_host_platform_device_count=2")
os.environ["XLA_FLAGS"] = " ".join(_flags)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu.distributed as dist
from paddle_tpu.distributed import heartbeat
from paddle_tpu.distributed.fleet import elastic

TOTAL_STEPS = int(os.environ.get("ELASTIC_TOTAL_STEPS", "12"))
LR = 0.1
N, D = 12, 4          # 12 rows: divisible by worlds of 1, 2, 3


def _data():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(N, D)).astype(np.float32)
    w_true = rng.normal(size=(D,)).astype(np.float32)
    return X, X @ w_true


def _step_fn(w, x, y):
    def loss_fn(w):
        return jnp.mean((x @ w - y) ** 2)

    loss, g = jax.value_and_grad(loss_fn)(w)
    return w - LR * g, loss


def main():
    dist.init_parallel_env()
    rank = dist.get_rank()
    world = dist.get_world_size()
    run = elastic.elastic_run_index()
    devs = jax.devices()
    mesh = Mesh(np.array(devs), ("dp",))
    repl = NamedSharding(mesh, P())
    row = NamedSharding(mesh, P("dp"))

    # resume: reshard-on-load places w for THIS world's mesh
    start, state = elastic.load_state(
        {"w": jax.device_put(jnp.zeros((D,), jnp.float32), repl)})
    w = jax.device_put(jnp.asarray(state["w"]), repl)

    X, Y = _data()
    lo, hi = rank * (N // world), (rank + 1) * (N // world)
    gx = jax.make_array_from_process_local_data(row, X[lo:hi])
    gy = jax.make_array_from_process_local_data(row, Y[lo:hi])
    step_c = jax.jit(_step_fn, in_shardings=(repl, row, row),
                     out_shardings=(repl, repl)).lower(w, gx, gy).compile()

    kill_at = int(os.environ.get("KILL_AT_STEP", "-1"))
    step_sleep = float(os.environ.get("STEP_SLEEP", "0"))
    pending = None
    for step in range(start, TOTAL_STEPS):
        w, loss = step_c(w, gx, gy)
        heartbeat.beat(step)
        print(f"STEP run={run} world={world} rank={rank} step={step} "
              f"loss={float(loss):.6f}", flush=True)
        pending = elastic.save_state(step + 1, {"w": w},
                                     prev_handle=pending)
        if run == 0 and rank == world - 1 and step == kill_at:
            os._exit(17)      # simulated node failure
        if step_sleep:
            import time
            time.sleep(step_sleep)
    elastic.finish_saves(pending)
    dist.barrier()
    print(f"ELASTIC_DONE run={run} rank={rank} world={world}", flush=True)


if __name__ == "__main__":
    main()
