"""Crash-consistency tests for the fault-tolerant checkpoint layer.

The contract under test (ISSUE 2 acceptance): a kill -9 equivalent at
ANY point during save_state_dict / async_save_state_dict never corrupts
an existing checkpoint, and CheckpointManager.restore_latest() recovers
the last committed state bit-for-bit — including with a real
multi-process world under JAX_PLATFORMS=cpu.
"""
import json
import os
import signal
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.distributed import checkpoint as dckpt
from paddle_tpu.distributed.checkpoint import (CheckpointError,
                                               CheckpointManager)
from paddle_tpu.testing import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

W0 = np.arange(12, dtype=np.float32).reshape(3, 4)


def _state(step):
    return {"w": pt.to_tensor(W0 + step), "step": step}


def _template():
    return {"w": pt.to_tensor(np.zeros((3, 4), "float32")), "step": 0}


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.clear()


# -- commit protocol ---------------------------------------------------------

class TestCommitProtocol:
    def test_commit_artifacts_and_manifest(self, tmp_path):
        path = str(tmp_path / "ck")
        dckpt.save_state_dict(_state(1), path)
        names = set(os.listdir(path))
        assert {"COMMIT", "checkpoint.manifest", "0.metadata"} <= names
        with open(os.path.join(path, "checkpoint.manifest")) as f:
            manifest = json.load(f)
        # manifest covers the metadata and every shard file, with true sizes
        assert "0.metadata" in manifest["files"]
        for fname, rec in manifest["files"].items():
            assert os.path.getsize(os.path.join(path, fname)) == rec["size"]
        assert dckpt.is_committed(path)
        dckpt.verify_checkpoint(path)   # must not raise
        # no staging debris next to the committed dir
        assert [n for n in os.listdir(tmp_path) if ".tmp." in n] == []

    def test_load_refuses_uncommitted(self, tmp_path):
        path = str(tmp_path / "ck")
        dckpt.save_state_dict(_state(1), path)
        os.remove(os.path.join(path, "COMMIT"))
        with pytest.raises(CheckpointError, match="COMMIT"):
            dckpt.load_state_dict(_template(), path)

    def test_load_refuses_corrupt_shard(self, tmp_path):
        path = str(tmp_path / "ck")
        dckpt.save_state_dict(_state(1), path)
        shard = next(n for n in os.listdir(path) if n.endswith(".distcp"))
        with open(os.path.join(path, shard), "r+b") as f:
            f.seek(-1, os.SEEK_END)
            last = f.read(1)
            f.seek(-1, os.SEEK_END)
            f.write(bytes([last[0] ^ 0xFF]))
        with pytest.raises(CheckpointError, match="CRC32"):
            dckpt.load_state_dict(_template(), path)

    def test_load_refuses_truncated_file(self, tmp_path):
        path = str(tmp_path / "ck")
        dckpt.save_state_dict(_state(1), path)
        shard = os.path.join(
            path, next(n for n in os.listdir(path) if n.endswith(".distcp")))
        with open(shard, "r+b") as f:
            f.truncate(os.path.getsize(shard) - 7)
        with pytest.raises(CheckpointError, match="truncated|bytes"):
            dckpt.load_state_dict(_template(), path)

    def test_verify_skippable_for_legacy_dirs(self, tmp_path):
        path = str(tmp_path / "ck")
        dckpt.save_state_dict(_state(3), path)
        os.remove(os.path.join(path, "COMMIT"))
        tgt = _template()
        dckpt.load_state_dict(tgt, path, verify=False)
        np.testing.assert_array_equal(tgt["w"].numpy(), W0 + 3)


# -- in-process fault injection ---------------------------------------------

@pytest.mark.faults
class TestInjectedFaults:
    @pytest.mark.parametrize("point", ["checkpoint.write",
                                       "checkpoint.metadata",
                                       "checkpoint.rename",
                                       "checkpoint.commit"])
    def test_raise_mid_save_preserves_previous(self, tmp_path, point):
        mgr = CheckpointManager(str(tmp_path / "root"), keep_last_n=3)
        mgr.save(1, _state(1))
        with faults.injected(point, action="raise"):
            with pytest.raises(faults.FaultInjected):
                mgr.save(2, _state(2))
        assert mgr.latest_step() == 1
        tgt = _template()
        assert mgr.restore_latest(tgt) == 1
        np.testing.assert_array_equal(tgt["w"].numpy(), W0 + 1)
        assert tgt["step"] == 1

    def test_async_writer_fault_surfaces_and_previous_survives(
            self, tmp_path):
        mgr = CheckpointManager(str(tmp_path / "root"), keep_last_n=3,
                                async_save=True)
        mgr.save(1, _state(1), blocking=True)
        with faults.injected("checkpoint.rename", action="raise"):
            assert mgr.save(2, _state(2))
            with pytest.raises(faults.FaultInjected):
                mgr.wait()
        tgt = _template()
        assert mgr.restore_latest(tgt) == 1
        np.testing.assert_array_equal(tgt["w"].numpy(), W0 + 1)

    def test_collective_gather_point(self):
        import paddle_tpu.distributed as dist
        with faults.injected("collective.gather", action="raise"):
            with pytest.raises(faults.FaultInjected):
                dist.all_gather_object([], {"x": 1})

    def test_nth_semantics_and_counts(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path / "root"))
        base = faults.hit_count("checkpoint.write")
        with faults.injected("checkpoint.write", action="raise", nth=2):
            mgr.save(1, _state(1))            # first hit: passes
            with pytest.raises(faults.FaultInjected):
                mgr.save(2, _state(2))        # second hit: fires
        assert faults.hit_count("checkpoint.write") == base + 2
        assert mgr.latest_step() == 1


# -- kill -9 equivalents (subprocess) ---------------------------------------

_CRASH_CHILD = textwrap.dedent("""\
    import os, sys
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax; jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.distributed.checkpoint import CheckpointManager

    root, mode = sys.argv[1], sys.argv[2]
    W0 = np.arange(12, dtype=np.float32).reshape(3, 4)
    mgr = CheckpointManager(root, keep_last_n=3,
                            async_save=(mode == "async"))
    mgr.save(1, {"w": pt.to_tensor(W0 + 1), "step": 1}, blocking=True)
    print("SAVED1", flush=True)
    # FLAGS_fault_injection (env) armed a kill with nth=2: the second
    # hit of the point is inside THIS save
    mgr.save(2, {"w": pt.to_tensor(W0 + 2), "step": 2})
    mgr.wait()
    print("SAVED2", flush=True)      # unreachable when armed
""")


def _run_crash_child(tmp_path, mode, fault_spec):
    script = tmp_path / "child.py"
    script.write_text(_CRASH_CHILD)
    root = str(tmp_path / "root")
    r = subprocess.run(
        [sys.executable, str(script), root, mode],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
                 FLAGS_fault_injection=fault_spec))
    return root, r


@pytest.mark.faults
class TestKillMinusNine:
    @pytest.mark.parametrize("point", ["checkpoint.write",
                                       "checkpoint.metadata",
                                       "checkpoint.rename"])
    def test_kill_mid_sync_save(self, tmp_path, point):
        root, r = _run_crash_child(tmp_path, "sync", f"{point}:kill:2")
        assert r.returncode == faults.KILL_EXIT_CODE, r.stderr[-3000:]
        assert "SAVED1" in r.stdout and "SAVED2" not in r.stdout
        mgr = CheckpointManager(root)
        assert mgr.latest_step() == 1
        tgt = _template()
        assert mgr.restore_latest(tgt) == 1
        np.testing.assert_array_equal(tgt["w"].numpy(), W0 + 1)
        assert tgt["step"] == 1

    def test_kill_mid_async_save(self, tmp_path):
        root, r = _run_crash_child(tmp_path, "async",
                                   "checkpoint.write:kill:2")
        assert r.returncode == faults.KILL_EXIT_CODE, r.stderr[-3000:]
        mgr = CheckpointManager(root)
        tgt = _template()
        assert mgr.restore_latest(tgt) == 1
        np.testing.assert_array_equal(tgt["w"].numpy(), W0 + 1)


_SIGTERM_CHILD = textwrap.dedent("""\
    import os, signal, sys
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax; jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.distributed.checkpoint import CheckpointManager
    from paddle_tpu.testing import faults

    root = sys.argv[1]
    W0 = np.arange(12, dtype=np.float32).reshape(3, 4)
    mgr = CheckpointManager(root, keep_last_n=3, async_save=True)
    assert mgr.install_preemption_hook()
    # slow the writer down so the save is genuinely in flight when
    # SIGTERM lands
    faults.inject("checkpoint.rename", action="delay", delay_s=0.5)
    mgr.save(1, {"w": pt.to_tensor(W0 + 1), "step": 1})
    os.kill(os.getpid(), signal.SIGTERM)
    print("UNREACHABLE", flush=True)
""")


@pytest.mark.faults
class TestPreemption:
    def test_sigterm_finalizes_in_flight_save(self, tmp_path):
        script = tmp_path / "child.py"
        script.write_text(_SIGTERM_CHILD)
        root = str(tmp_path / "root")
        r = subprocess.run(
            [sys.executable, str(script), root],
            capture_output=True, text=True, timeout=300,
            env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO))
        # the hook re-delivers SIGTERM after finalizing
        assert r.returncode == -signal.SIGTERM, (r.returncode,
                                                 r.stderr[-3000:])
        assert "UNREACHABLE" not in r.stdout
        mgr = CheckpointManager(root)
        tgt = _template()
        assert mgr.restore_latest(tgt) == 1
        np.testing.assert_array_equal(tgt["w"].numpy(), W0 + 1)

    def test_finalize_joins_in_flight(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path / "root"), async_save=True)
        faults.inject("checkpoint.rename", action="delay", delay_s=0.2)
        mgr.save(1, _state(1))
        mgr.finalize_on_preemption()
        assert mgr.latest_step() == 1

    def test_emergency_save_of_interval_skipped_state(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path / "root"),
                                save_interval_steps=5)
        assert mgr.save(5, _state(5))
        assert not mgr.save(7, _state(7))     # interval-skipped
        mgr.finalize_on_preemption()
        assert mgr.latest_step() == 7         # emergency sync save
        tgt = _template()
        assert mgr.restore_latest(tgt) == 7
        np.testing.assert_array_equal(tgt["w"].numpy(), W0 + 7)


# -- multi-process crash (launch CLI, JAX_PLATFORMS=cpu) ---------------------

@pytest.mark.faults
class TestMultiProcessCrash:
    @pytest.mark.slow  # tier-1 budget (ISSUE 3): heavy; run in the slow lane
    def test_coordinator_killed_mid_commit(self, tmp_path):
        worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "_ckpt_crash_worker.py")
        root = str(tmp_path / "root")
        log_dir = str(tmp_path / "logs")
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "2", "--log_dir", log_dir, worker, root],
            capture_output=True, text=True, timeout=420,
            env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
                     FLAGS_fault_injection="checkpoint.rename:kill:2"))
        logs = ""
        for rank in range(2):
            p = os.path.join(log_dir, f"workerlog.{rank}")
            if os.path.exists(p):
                logs += open(p).read()
        assert r.returncode != 0, logs[-4000:]
        assert "SAVED2" not in logs, logs[-4000:]
        # step 1 survived the step-2 crash, bit-for-bit
        mgr = CheckpointManager(root)
        assert mgr.all_steps() == [1], (os.listdir(root), logs[-4000:])
        tgt = _template()
        assert mgr.restore_latest(tgt) == 1
        np.testing.assert_array_equal(tgt["w"].numpy(), W0 + 1)
        assert tgt["step"] == 1

        # a fresh 2-process world agrees on and restores the survivor
        # (the multi-host restore path: candidate-set + verification
        # gathers)
        log_dir2 = str(tmp_path / "logs2")
        r2 = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "2", "--log_dir", log_dir2, worker,
             root, "restore"],
            capture_output=True, text=True, timeout=420,
            env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO))
        logs2 = ""
        for rank in range(2):
            p = os.path.join(log_dir2, f"workerlog.{rank}")
            if os.path.exists(p):
                logs2 += open(p).read()
        assert r2.returncode == 0, logs2[-4000:]
        assert "RESTORED1 rank=0" in logs2 and "RESTORED1 rank=1" in logs2


# -- manager policies --------------------------------------------------------

class TestManagerPolicies:
    def test_retention_keeps_newest(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path / "root"), keep_last_n=2)
        for s in range(1, 6):
            mgr.save(s, _state(s))
        assert mgr.all_steps() == [4, 5]
        tgt = _template()
        assert mgr.restore_latest(tgt) == 5
        np.testing.assert_array_equal(tgt["w"].numpy(), W0 + 5)

    def test_gc_never_deletes_newest_even_with_keep_one(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path / "root"), keep_last_n=1)
        mgr.save(1, _state(1))
        mgr.save(2, _state(2))
        assert mgr.all_steps() == [2]

    def test_save_interval_policy(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path / "root"),
                                save_interval_steps=3, keep_last_n=10)
        saved = [s for s in range(1, 10) if mgr.save(s, _state(s))]
        assert saved == [3, 6, 9]
        assert mgr.all_steps() == [3, 6, 9]
        mgr.save(10, _state(10), force=True)
        assert mgr.latest_step() == 10

    def test_async_pipeline_roundtrip(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path / "root"), keep_last_n=2,
                                async_save=True)
        for s in range(1, 5):
            mgr.save(s, _state(s))
        mgr.wait()
        assert mgr.all_steps() == [3, 4]
        tgt = _template()
        assert mgr.restore_latest(tgt) == 4

    def test_restore_latest_falls_back_over_corruption(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path / "root"), keep_last_n=5)
        for s in (1, 2, 3):
            mgr.save(s, _state(s))
        # corrupt the newest, truncate the middle: restore must land on 1
        step3 = os.path.join(str(tmp_path / "root"), "step_3")
        shard = next(n for n in os.listdir(step3) if n.endswith(".distcp"))
        with open(os.path.join(step3, shard), "r+b") as f:
            f.seek(0)
            f.write(b"\x00" * 8)
        os.remove(os.path.join(str(tmp_path / "root"), "step_2", "COMMIT"))
        tgt = _template()
        assert mgr.restore_latest(tgt) == 1
        np.testing.assert_array_equal(tgt["w"].numpy(), W0 + 1)

    def test_overwrite_same_step_commit_failure_restores_previous(
            self, tmp_path):
        """Re-saving an existing committed step takes the move-aside
        branch; a raised failure after the move must put the old
        committed checkpoint back."""
        path = str(tmp_path / "ck")
        dckpt.save_state_dict(_state(1), path)
        with faults.injected("checkpoint.commit", action="raise"):
            with pytest.raises(faults.FaultInjected):
                dckpt.save_state_dict(_state(2), path)
        dckpt.verify_checkpoint(path)
        tgt = _template()
        dckpt.load_state_dict(tgt, path)
        np.testing.assert_array_equal(tgt["w"].numpy(), W0 + 1)
        assert [n for n in os.listdir(tmp_path) if ".old." in n] == []

    def test_manager_recovers_graveyard_from_kill_window(self, tmp_path):
        """Simulate a kill between the overwrite protocol's two renames:
        the committed checkpoint sits at step_1.old.<uid>, nothing (or
        an uncommitted half-rename) at step_1. A new manager must
        recover it, not garbage-collect it."""
        root = tmp_path / "root"
        mgr = CheckpointManager(str(root), keep_last_n=3)
        mgr.save(1, _state(1))
        os.rename(root / "step_1", root / "step_1.old.999.1")
        mgr2 = CheckpointManager(str(root), keep_last_n=3)
        assert mgr2.all_steps() == [1]
        tgt = _template()
        assert mgr2.restore_latest(tgt) == 1
        np.testing.assert_array_equal(tgt["w"].numpy(), W0 + 1)

    def test_restore_latest_none_on_fresh_root(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path / "root"))
        tgt = _template()
        assert mgr.restore_latest(tgt) is None
        np.testing.assert_array_equal(tgt["w"].numpy(), np.zeros((3, 4)))

    def test_checkpoint_metrics_recorded(self, tmp_path):
        from paddle_tpu import monitor
        monitor.reset()
        pt.set_flags({"FLAGS_enable_monitor": True})
        try:
            mgr = CheckpointManager(str(tmp_path / "root"), keep_last_n=1)
            mgr.save(1, _state(1))
            mgr.save(2, _state(2))
            with faults.injected("checkpoint.rename", action="raise"):
                with pytest.raises(faults.FaultInjected):
                    mgr.save(3, _state(3))
            snap = monitor.snapshot()
            c = snap["counters"]
            assert c["ckpt.saves"] == 2
            assert c["ckpt.commit.failures"] == 1
            assert c["ckpt.gc.deleted"] >= 1
            assert c["ckpt.save.bytes"] > 0
            assert snap["histograms"]["ckpt.save.duration_ms"]["count"] == 2
        finally:
            pt.set_flags({"FLAGS_enable_monitor": False})
            monitor.reset()


# -- elastic + hapi wiring ---------------------------------------------------

class TestElasticWiring:
    @pytest.fixture(autouse=True)
    def _clean_managers(self):
        # the elastic helpers install a SIGTERM hook per manager; the
        # pytest process must not keep it (or the manager refs) after
        # the test
        from paddle_tpu.distributed.fleet import elastic
        yield
        for mgr in elastic._MANAGERS.values():
            mgr.remove_preemption_hook()
        elastic._MANAGERS.clear()

    def test_save_load_state_roundtrip_with_retention(self, tmp_path,
                                                      monkeypatch):
        import jax.numpy as jnp
        from paddle_tpu.distributed.fleet import elastic
        monkeypatch.setenv("PADDLE_ELASTIC_CKPT_DIR", str(tmp_path / "ck"))
        monkeypatch.setenv("PADDLE_ELASTIC_KEEP_CKPTS", "2")
        elastic._MANAGERS.clear()
        pending = None
        for step in range(4):
            pending = elastic.save_state(
                step + 1, {"w": jnp.full((4,), float(step))},
                prev_handle=pending)
        assert elastic.finish_saves(pending)
        start, state = elastic.load_state({"w": jnp.zeros((4,))})
        assert start == 4
        np.testing.assert_array_equal(np.asarray(state["w"]),
                                      np.full((4,), 3.0))
        assert sorted(os.listdir(str(tmp_path / "ck"))) == ["step_3",
                                                            "step_4"]

    def test_load_state_legacy_v1_layout_fallback(self, tmp_path,
                                                  monkeypatch):
        """A checkpoint dir written before the commit protocol (step<N>
        dirs + rank-0 `latest` pointer) must still resume, not silently
        restart from step 0."""
        import jax.numpy as jnp
        from paddle_tpu.distributed.fleet import elastic
        root = tmp_path / "ck"
        monkeypatch.setenv("PADDLE_ELASTIC_CKPT_DIR", str(root))
        elastic._MANAGERS.clear()
        # fabricate the v1 layout: a markerless step9 dir + latest file
        dckpt.save_state_dict({"w": jnp.full((4,), 9.0)},
                              str(root / "step9"))
        for marker in ("COMMIT", "checkpoint.manifest"):
            os.remove(str(root / "step9" / marker))
        (root / "latest").write_text("9")
        start, state = elastic.load_state({"w": jnp.zeros((4,))})
        assert start == 9
        np.testing.assert_array_equal(np.asarray(state["w"]),
                                      np.full((4,), 9.0))

    def test_load_state_skips_uncommitted_newest(self, tmp_path,
                                                 monkeypatch):
        import jax.numpy as jnp
        from paddle_tpu.distributed.fleet import elastic
        monkeypatch.setenv("PADDLE_ELASTIC_CKPT_DIR", str(tmp_path / "ck"))
        monkeypatch.setenv("PADDLE_ELASTIC_KEEP_CKPTS", "3")
        elastic._MANAGERS.clear()
        for step in (1, 2):
            elastic.save_state(step, {"w": jnp.full((4,), float(step))},
                               blocking=True)
        # simulate a crash that left step_2 uncommitted
        os.remove(str(tmp_path / "ck" / "step_2" / "COMMIT"))
        start, state = elastic.load_state({"w": jnp.zeros((4,))})
        assert start == 1
        np.testing.assert_array_equal(np.asarray(state["w"]),
                                      np.full((4,), 1.0))


class TestHapiCallback:
    def _fit_once(self, save_dir, seed=3):
        pt.seed(seed)
        net = pt.nn.Linear(4, 2)
        model = pt.Model(net)
        model.prepare(
            optimizer=pt.optimizer.SGD(learning_rate=0.05,
                                       parameters=net.parameters()),
            loss=pt.nn.MSELoss())
        rng = np.random.default_rng(0)
        x = rng.normal(size=(16, 4)).astype("float32")
        y = rng.normal(size=(16, 2)).astype("float32")
        from paddle_tpu.io import TensorDataset
        ds = TensorDataset([pt.to_tensor(x), pt.to_tensor(y)])
        from paddle_tpu.hapi.callbacks import FaultTolerantCheckpoint
        cb = FaultTolerantCheckpoint(save_dir, keep_last_n=2,
                                     save_interval_steps=2,
                                     async_save=False,
                                     preemption_hook=False)
        model.fit(ds, batch_size=4, epochs=1, verbose=0, callbacks=[cb])
        return net

    def test_fit_checkpoints_and_resumes(self, tmp_path):
        save_dir = str(tmp_path / "hapi_ck")
        net = self._fit_once(save_dir)
        trained = {k: np.asarray(v.numpy())
                   for k, v in net.state_dict().items()}
        mgr = CheckpointManager(save_dir)
        assert mgr.latest_step() == 4          # 16 samples / bs 4
        assert len(mgr.all_steps()) <= 2       # retention

        # fresh model resumes the trained weights via on_train_begin
        pt.seed(99)
        net2 = pt.nn.Linear(4, 2)
        model2 = pt.Model(net2)
        model2.prepare()
        from paddle_tpu.hapi.callbacks import FaultTolerantCheckpoint
        cb2 = FaultTolerantCheckpoint(save_dir, preemption_hook=False)
        cb2.set_model(model2)
        cb2.on_train_begin()
        assert cb2.restored_step == 4
        for k, v in net2.state_dict().items():
            np.testing.assert_array_equal(np.asarray(v.numpy()),
                                          trained[k])
        cb2.on_train_end()

    def test_resume_restores_optimizer_state(self, tmp_path):
        """A freshly-built optimizer must get the checkpointed
        accumulators back (Momentum buffers are NOT live handles, so
        the callback has to re-apply them via set_state_dict)."""
        save_dir = str(tmp_path / "hapi_ck")
        def build():
            pt.seed(7)
            net = pt.nn.Linear(4, 2)
            model = pt.Model(net)
            model.prepare(
                optimizer=pt.optimizer.Momentum(
                    learning_rate=0.05, momentum=0.9,
                    parameters=net.parameters()),
                loss=pt.nn.MSELoss())
            return net, model
        net, model = build()
        rng = np.random.default_rng(1)
        x = rng.normal(size=(8, 4)).astype("float32")
        y = rng.normal(size=(8, 2)).astype("float32")
        from paddle_tpu.io import TensorDataset
        ds = TensorDataset([pt.to_tensor(x), pt.to_tensor(y)])
        from paddle_tpu.hapi.callbacks import FaultTolerantCheckpoint
        cb = FaultTolerantCheckpoint(save_dir, save_interval_steps=1,
                                     async_save=False,
                                     preemption_hook=False)
        model.fit(ds, batch_size=4, epochs=1, verbose=0, callbacks=[cb])
        want = {k: np.asarray(v.numpy()) if hasattr(v, "numpy") else v
                for k, v in model._optimizer.state_dict().items()}
        assert any(k.endswith(".velocity") for k in want), want.keys()

        net2, model2 = build()
        cb2 = FaultTolerantCheckpoint(save_dir, preemption_hook=False)
        cb2.set_model(model2)
        cb2.on_train_begin()
        got = model2._optimizer.state_dict()
        assert got["global_step"] == want["global_step"] != 0
        for k, v in want.items():
            if hasattr(got.get(k), "numpy"):
                np.testing.assert_array_equal(
                    np.asarray(got[k].numpy()), v, err_msg=k)
        cb2.on_train_end()
