"""PP / MoE / SP / ring-attention tests on the 8-virtual-device CPU mesh
(SURVEY.md §4's hardware-free distributed strategy). Each parallel form is
checked for *numeric parity with its single-device equivalent* — the same
assertion discipline as the reference's hybrid_parallel_* suites."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed import pipeline as pp_sched
from paddle_tpu.distributed.fleet import (LayerDesc, PipelineLayer,
                                          SegmentLayers)
from paddle_tpu.incubate.distributed.models.moe import (MoELayer,
                                                        top_k_gating)
from paddle_tpu.kernels.ring_attention import ring_attention
from paddle_tpu.nn.functional.attention import sdpa_reference

RNG = np.random.default_rng(11)


class TestPipelineSchedule:
    def _setup(self, S=4, M=8, mb=2, d=16):
        mesh = Mesh(np.array(jax.devices()[:S]), ("pp",))
        params = {
            "w": jnp.asarray(RNG.normal(size=(S, d, d)) * 0.3, jnp.float32),
            "b": jnp.asarray(RNG.normal(size=(S, d)) * 0.1, jnp.float32),
        }

        def stage_fn(p, x):
            return jax.nn.relu(x @ p["w"] + p["b"])

        x = jnp.asarray(RNG.normal(size=(M, mb, d)), jnp.float32)
        return mesh, params, stage_fn, x

    def test_pipeline_matches_sequential(self):
        mesh, params, stage_fn, x = self._setup()
        out = pp_sched.pipeline_spmd(
            stage_fn, pp_sched.shard_stage_params(params, mesh), x, mesh)
        ref = x
        for s in range(4):
            ref = jax.nn.relu(ref @ params["w"][s] + params["b"][s])
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.slow  # tier-1 budget (ISSUE 19 rebalance): convergence run; pipeline_matches_sequential
    # + grad parity pin the schedule math fast
    def test_pipeline_train_converges(self):
        mesh, params, stage_fn, x = self._setup()
        tparams = {
            "w": jnp.asarray(RNG.normal(size=(4, 16, 16)) * 0.3,
                             jnp.float32),
            "b": jnp.asarray(RNG.normal(size=(4, 16)) * 0.1, jnp.float32),
        }
        tgt = x.reshape(16, 16)
        for s in range(4):
            tgt = jax.nn.relu(tgt @ tparams["w"][s] + tparams["b"][s])
        step = pp_sched.make_pipeline_train_step(
            stage_fn, lambda y, t: jnp.mean((y - t) ** 2), mesh,
            num_micro=8, lr=0.2)
        p = pp_sched.shard_stage_params(params, mesh)
        batch = x.reshape(16, 16)
        losses = []
        for _ in range(60):
            p, loss = step(p, batch, tgt)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5, losses[::10]

    @pytest.mark.slow  # tier-1 budget (ISSUE 3): heavy; run in the slow lane
    def test_pipeline_grad_matches_sequential(self):
        """d(loss)/d(params) through the pipelined program equals the
        sequential gradient."""
        mesh, params, stage_fn, x = self._setup(M=4)
        tgt = jnp.asarray(RNG.normal(size=(8, 16)), jnp.float32)

        def pipe_loss(p):
            y = pp_sched.pipeline_spmd(stage_fn, p, x, mesh, remat=False)
            return jnp.mean((y.reshape(8, 16) - tgt) ** 2)

        def seq_loss(p):
            h = x.reshape(8, 16)
            for s in range(4):
                h = jax.nn.relu(h @ p["w"][s] + p["b"][s])
            return jnp.mean((h - tgt) ** 2)

        g1 = jax.grad(pipe_loss)(pp_sched.shard_stage_params(params, mesh))
        g2 = jax.grad(seq_loss)(params)
        for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)


class TestPipelineLayerAPI:
    def test_uniform_segmentation(self):
        seg = SegmentLayers([object()] * 10, num_parts=4)
        assert seg.do_segment() == [0, 3, 6, 8, 10]

    def test_pipeline_layer_eager_forward(self):
        model = PipelineLayer(
            layers=[LayerDesc(nn.Linear, 8, 8) for _ in range(4)],
            num_stages=2)
        assert model.get_num_stages() == 2
        assert len(model.get_stage_layers(0)) == 2
        x = paddle.to_tensor(RNG.normal(size=(2, 8)).astype("float32"))
        y = model(x)
        assert y.shape == [2, 8]
        # stage callables compose to the same forward
        z = model.stage_callable(1)(model.stage_callable(0)(x))
        np.testing.assert_allclose(y.numpy(), z.numpy(), rtol=1e-6)

    def test_parameter_segmentation(self):
        layers = [LayerDesc(nn.Linear, 4, 4), LayerDesc(nn.Linear, 64, 64),
                  LayerDesc(nn.Linear, 4, 4), LayerDesc(nn.Linear, 64, 64)]
        seg = SegmentLayers(layers, num_parts=2, method="parameter")
        bounds = seg.do_segment()
        assert bounds[0] == 0 and bounds[-1] == 4 and len(bounds) == 3


class TestMoE:
    def test_gating_invariants(self):
        logits = jnp.asarray(RNG.normal(size=(32, 8)), jnp.float32)
        d, c, aux = top_k_gating(logits, top_k=2, capacity=8)
        # each token dispatched at most top_k times
        assert float(d.sum(axis=(1, 2)).max()) <= 2.0
        # each (expert, slot) holds at most one token
        assert float(d.sum(axis=0).max()) <= 1.0 + 1e-6
        # combine weights vanish where dispatch is zero
        assert float(jnp.abs(c * (1 - d)).max()) < 1e-6
        assert np.isfinite(float(aux))

    def test_moe_layer_trains(self):
        paddle.seed(0)
        m = MoELayer(d_model=16, d_hidden=32, num_experts=4, gate="gshard")
        o = opt.AdamW(learning_rate=1e-2, parameters=m.parameters())
        x = paddle.to_tensor(RNG.normal(size=(32, 16)).astype("float32"))
        tgt = paddle.to_tensor(RNG.normal(size=(32, 16)).astype("float32"))
        first = last = None
        for _ in range(40):
            y = m(x)
            loss = F.mse_loss(y, tgt) + 0.01 * m.aux_loss
            loss.backward()
            o.step()
            o.clear_grad()
            first = first or float(loss)
            last = float(loss)
        assert last < first * 0.5, (first, last)

    def test_switch_gate_top1(self):
        m = MoELayer(d_model=8, d_hidden=16, num_experts=4, gate="switch")
        x = paddle.to_tensor(RNG.normal(size=(16, 8)).astype("float32"))
        y = m(x)
        assert y.shape == [16, 8]
        assert m.gate.top_k == 1

    def test_moe_3d_input(self):
        m = MoELayer(d_model=8, d_hidden=16, num_experts=2, gate="naive",
                     top_k=1)
        x = paddle.to_tensor(RNG.normal(size=(2, 5, 8)).astype("float32"))
        assert m(x).shape == [2, 5, 8]


class TestRingAttention:
    @pytest.mark.parametrize("B,S,H,KV,D,causal", [
        (2, 64, 4, 4, 32, True),
        (1, 128, 4, 2, 32, True),     # GQA
        (2, 64, 2, 2, 16, False),
    ])
    def test_matches_reference(self, B, S, H, KV, D, causal):
        mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
        q = jnp.asarray(RNG.normal(size=(B, S, H, D)), jnp.float32)
        k = jnp.asarray(RNG.normal(size=(B, S, KV, D)), jnp.float32)
        v = jnp.asarray(RNG.normal(size=(B, S, KV, D)), jnp.float32)
        ref = sdpa_reference(q, k, v, causal=causal)
        out = ring_attention(q, k, v, mesh, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.slow  # tier-1 budget (ISSUE 19 rebalance): ring-attention grads; the parametrized
    # forward parity sweep keeps the kernel seam fast
    def test_gradients_match(self):
        mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
        q = jnp.asarray(RNG.normal(size=(1, 64, 2, 16)), jnp.float32)
        g1 = jax.grad(lambda q: (ring_attention(
            q, q, q, mesh, causal=True) ** 2).sum())(q)
        g2 = jax.grad(lambda q: (sdpa_reference(
            q, q, q, causal=True) ** 2).sum())(q)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=1e-4, atol=5e-4)


class TestSequenceParallelUtils:
    def test_ops_identity_without_mesh(self):
        from paddle_tpu.distributed.fleet import sequence_parallel_utils as spu
        x = paddle.to_tensor(RNG.normal(size=(2, 8, 4)).astype("float32"))
        for op in (spu.ScatterOp, spu.GatherOp, spu.AllGatherOp,
                   spu.ReduceScatterOp):
            y = op.apply(x)
            np.testing.assert_allclose(y.numpy(), x.numpy())

    def test_sp_linears_compute_linear(self):
        from paddle_tpu.distributed.fleet import sequence_parallel_utils as spu
        col = spu.ColumnSequenceParallelLinear(8, 16, has_bias=True)
        row = spu.RowSequenceParallelLinear(16, 8, has_bias=True)
        x = paddle.to_tensor(RNG.normal(size=(2, 4, 8)).astype("float32"))
        y = row(col(x))
        assert y.shape == [2, 4, 8]

    def test_mark_parameter(self):
        from paddle_tpu.distributed.fleet import sequence_parallel_utils as spu
        lin = nn.Linear(4, 4)
        spu.mark_as_sequence_parallel_parameter(lin.weight)
        assert lin.weight.sequence_parallel


class TestSegmentationRegressions:
    def test_layer_method_cuts_at_named_layers(self):
        class Block(nn.Layer):
            def forward(self, x):
                return x

        class Norm(nn.Layer):
            def forward(self, x):
                return x

        layers = [LayerDesc(Block), LayerDesc(Norm),
                  LayerDesc(Block), LayerDesc(Norm)]
        seg = SegmentLayers(layers, num_parts=2, method="layer:Block")
        assert seg.do_segment() == [0, 2, 4]

    def test_parameter_method_never_empty_stage(self):
        layers = [LayerDesc(nn.Linear, 2, 2), LayerDesc(nn.Linear, 2, 2),
                  LayerDesc(nn.Linear, 2, 2), LayerDesc(nn.Linear, 64, 64)]
        seg = SegmentLayers(layers, num_parts=2, method="parameter")
        bounds = seg.do_segment()
        widths = [bounds[i + 1] - bounds[i] for i in range(2)]
        assert all(w >= 1 for w in widths), bounds


class TestSPAutogradUnderMesh:
    def test_sp_ops_keep_gradient_flow(self):
        """With a mesh ('dp','mp') set, the SP scatter/gather ops must stay
        on the autograd tape (regression: constraint severed the graph)."""
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.fleet import sequence_parallel_utils as spu
        mesh = dist.ProcessMesh(
            np.arange(8).reshape(2, 4), dim_names=["dp", "mp"])
        dist.set_mesh(mesh)
        try:
            lin = nn.Linear(8, 8)
            x = paddle.to_tensor(
                RNG.normal(size=(2, 4, 8)).astype("float32"))
            y = spu.ReduceScatterOp.apply(spu.AllGatherOp.apply(lin(x)))
            y.sum().backward()
            assert lin.weight.grad is not None
            assert float(np.abs(lin.weight.grad.numpy()).sum()) > 0
        finally:
            dist.set_mesh(None)
