"""Tests for the API-surface completion sweep: top-level misc ops,
framework compat surface, unpool/fractional pool, sequence losses
(CTC/RNN-T), hsigmoid, margin losses, beam search decode.

Torch (CPU) is used as the parity oracle where it implements the same
op; otherwise numpy references.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def t(x, **kw):
    return paddle.to_tensor(x, **kw)


class TestTopLevelMisc:
    def test_stacks(self):
        x = np.arange(4, dtype="float32")
        a, b = t(x), t(x + 4)
        assert paddle.hstack([a, b]).shape == [8]
        assert paddle.vstack([a, b]).shape == [2, 4]
        assert paddle.row_stack([a, b]).shape == [2, 4]
        assert paddle.column_stack([a, b]).shape == [4, 2]
        m = t(x.reshape(2, 2))
        assert paddle.dstack([m, m]).shape == [2, 2, 2]

    def test_combinations(self):
        x = t(np.arange(4, dtype="float32"))
        c = paddle.combinations(x)
        assert c.shape == [6, 2]
        want = [[0, 1], [0, 2], [0, 3], [1, 2], [1, 3], [2, 3]]
        np.testing.assert_array_equal(np.asarray(c.numpy()), want)
        cr = paddle.combinations(x, 2, with_replacement=True)
        assert cr.shape == [10, 2]

    def test_pdist(self):
        import scipy.spatial.distance as ssd

        a = np.random.default_rng(0).random((6, 3)).astype("float32")
        np.testing.assert_allclose(np.asarray(paddle.pdist(t(a)).numpy()),
                                   ssd.pdist(a), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(paddle.pdist(t(a), p=1.0).numpy()),
            ssd.pdist(a, "minkowski", p=1), rtol=1e-5)

    def test_random_ops(self):
        x = t(np.zeros((3, 4), "float32"))
        r = paddle.randint_like(x, 5)
        assert r.shape == [3, 4]
        arr = np.asarray(r.numpy())
        assert (arr >= 0).all() and (arr < 5).all()
        b = paddle.binomial(t(np.full(1000, 20.0, "float32")),
                            t(np.full(1000, 0.3, "float32")))
        m = float(np.asarray(b.numpy()).mean())
        assert 5.0 < m < 7.0          # E = 6
        g = paddle.standard_gamma(t(np.full(2000, 3.0, "float32")))
        gm = float(np.asarray(g.numpy()).mean())
        assert 2.5 < gm < 3.5         # E = alpha = 3

    def test_inplace_variants(self):
        x = t([1.0, -2.0])
        x.square_()
        np.testing.assert_allclose(x.numpy(), [1.0, 4.0])
        y = t([0.5])
        paddle.erf_(y)
        np.testing.assert_allclose(np.asarray(y.numpy()),
                                   [0.5204999], rtol=1e-5)
        z = t(np.zeros((3, 2), "float32"))
        z.index_add_(t(np.array([0, 2], "int64")), axis=0,
                     value=t(np.ones((2, 2), "float32")))
        np.testing.assert_allclose(np.asarray(z.numpy()),
                                   [[1, 1], [0, 0], [1, 1]])

    def test_dtype_info_and_places(self):
        assert paddle.finfo(paddle.float32).max > 1e38
        assert paddle.iinfo("int16").max == 32767
        assert paddle.CPUPlace() == paddle.CPUPlace()
        assert paddle.CPUPlace() != paddle.CUDAPlace(0)
        assert paddle.CUDAPlace(0).get_device_id() == 0
        paddle.set_printoptions(precision=4)
        paddle.disable_signal_handler()
        assert paddle.is_grad_enabled()
        assert paddle.bool is paddle.bool_

    def test_batch_reader(self):
        def reader():
            yield from range(7)

        batches = list(paddle.batch(reader, 3)())
        assert batches == [[0, 1, 2], [3, 4, 5], [6]]
        batches = list(paddle.batch(reader, 3, drop_last=True)())
        assert batches == [[0, 1, 2], [3, 4, 5]]

    def test_flops(self):
        net = nn.Linear(8, 4)
        assert paddle.flops(net, (2, 8)) == 2 * 2 * 8 * 4

    def test_check_shape(self):
        paddle.check_shape([2, -1, 3])
        with pytest.raises(ValueError):
            paddle.check_shape([-1, -1])

    def test_lazy_guard(self):
        with paddle.LazyGuard():
            lin = nn.Linear(3, 3)
        assert lin.weight.shape == [3, 3]

    def test_tolist(self):
        assert t([[1.0, 2.0]]).tolist() == [[1.0, 2.0]]


class TestPoolingExtras:
    def test_max_pool_mask_and_unpool_torch_parity(self):
        import torch
        import torch.nn.functional as TF

        x = np.random.default_rng(1).normal(size=(2, 3, 8, 8)) \
            .astype("float32")
        for k, s, p in [(2, 2, 0), (3, 2, 1)]:
            out, mask = F.max_pool2d(t(x), k, s, p, return_mask=True)
            tout, tmask = TF.max_pool2d(torch.tensor(x), k, s, p,
                                        return_indices=True)
            np.testing.assert_allclose(np.asarray(out.numpy()),
                                       tout.numpy(), rtol=1e-6)
            np.testing.assert_array_equal(np.asarray(mask.numpy()),
                                          tmask.numpy())
            osz = (8, 8) if p else None
            up = F.max_unpool2d(out, mask, k, s, p, output_size=osz)
            tup = TF.max_unpool2d(tout, tmask, k, s, p, output_size=osz)
            np.testing.assert_allclose(np.asarray(up.numpy()),
                                       tup.numpy(), rtol=1e-6)

    def test_max_pool1d_3d_mask(self):
        import torch
        import torch.nn.functional as TF

        x1 = np.random.default_rng(2).normal(size=(2, 3, 10)) \
            .astype("float32")
        o1, m1 = F.max_pool1d(t(x1), 2, 2, return_mask=True)
        to1, tm1 = TF.max_pool1d(torch.tensor(x1), 2, 2,
                                 return_indices=True)
        np.testing.assert_array_equal(np.asarray(m1.numpy()), tm1.numpy())
        up = F.max_unpool1d(o1, m1, 2, 2)
        np.testing.assert_allclose(np.asarray(up.numpy()),
                                   TF.max_unpool1d(to1, tm1, 2, 2).numpy())
        x3 = np.random.default_rng(3).normal(size=(1, 2, 6, 6, 6)) \
            .astype("float32")
        o3, m3 = F.max_pool3d(t(x3), 2, 2, return_mask=True)
        to3, tm3 = TF.max_pool3d(torch.tensor(x3), 2, 2,
                                 return_indices=True)
        np.testing.assert_array_equal(np.asarray(m3.numpy()), tm3.numpy())

    def test_unpool_layers(self):
        x = np.random.default_rng(4).normal(size=(1, 2, 6, 6)) \
            .astype("float32")
        out, mask = F.max_pool2d(t(x), 2, 2, return_mask=True)
        up = nn.MaxUnPool2D(2, 2)(out, mask)
        assert up.shape == [1, 2, 6, 6]
        # every pooled max lands back at its argmax position
        total = np.asarray(up.numpy()).sum()
        np.testing.assert_allclose(total, np.asarray(out.numpy()).sum(),
                                   rtol=1e-6)

    def test_fractional_max_pool(self):
        x = np.random.default_rng(5).normal(size=(2, 3, 9, 9)) \
            .astype("float32")
        out = F.fractional_max_pool2d(t(x), 3, random_u=0.3)
        assert out.shape == [2, 3, 3, 3]
        # every output is the max of SOME window, so must appear in input
        assert np.isin(np.asarray(out.numpy()),
                       np.asarray(x)).all()
        out, mask = F.fractional_max_pool2d(t(x), 3, random_u=0.3,
                                            return_mask=True)
        flat = np.asarray(x).reshape(2, 3, -1)
        gathered = np.take_along_axis(
            flat, np.asarray(mask.numpy()).reshape(2, 3, -1), axis=2)
        np.testing.assert_allclose(gathered.reshape(2, 3, 3, 3),
                                   np.asarray(out.numpy()), rtol=1e-6)
        o3 = F.fractional_max_pool3d(t(np.random.default_rng(6).normal(
            size=(1, 2, 8, 8, 8)).astype("float32")), 2, random_u=0.5)
        assert o3.shape == [1, 2, 2, 2, 2]
        # kernel_size-pinned variant + layer classes
        ok = F.fractional_max_pool2d(t(x), 3, kernel_size=2, random_u=0.4)
        assert ok.shape == [2, 3, 3, 3]
        assert nn.FractionalMaxPool2D(3, random_u=0.2)(t(x)).shape == \
            [2, 3, 3, 3]


class TestSequenceLosses:
    def test_ctc_torch_parity(self):
        import torch

        rng = np.random.default_rng(0)
        T, N, C, S = 12, 3, 6, 4
        logits = rng.normal(size=(T, N, C)).astype("float32")
        labels = rng.integers(1, C, (N, S)).astype("int32")
        ilen = np.array([12, 10, 8], "int32")
        llen = np.array([4, 3, 2], "int32")
        ours = F.ctc_loss(t(logits), t(labels), t(ilen), t(llen),
                          blank=0, reduction="none")
        tl = torch.nn.functional.ctc_loss(
            torch.log_softmax(torch.tensor(logits), -1),
            torch.tensor(labels.astype("int64")),
            torch.tensor(ilen.astype("int64")),
            torch.tensor(llen.astype("int64")), blank=0, reduction="none")
        np.testing.assert_allclose(np.asarray(ours.numpy()), tl.numpy(),
                                   rtol=1e-4)

    def test_ctc_grad_and_layer(self):
        rng = np.random.default_rng(1)
        logits = rng.normal(size=(6, 2, 5)).astype("float32")
        labels = rng.integers(1, 5, (2, 3)).astype("int32")
        lt = t(logits, stop_gradient=False)
        loss = nn.CTCLoss()(lt, t(labels), t(np.array([6, 5], "int32")),
                            t(np.array([3, 2], "int32")))
        loss.backward()
        g = np.asarray(lt.grad.numpy())
        assert np.isfinite(g).all() and np.abs(g).sum() > 0

    def test_rnnt_dp_parity(self):
        import scipy.special as sp

        rng = np.random.default_rng(1)
        B, T, U, V = 2, 5, 3, 4
        logits = rng.normal(size=(B, T, U + 1, V)).astype("float32")
        label = rng.integers(1, V, (B, U)).astype("int32")
        ilen = np.array([5, 4], "int32")
        llen = np.array([3, 2], "int32")

        def ref(lp, lab, Tb, Ub, blank=0):
            lp = sp.log_softmax(lp, axis=-1)
            alpha = np.full((Tb, Ub + 1), -np.inf)
            alpha[0, 0] = 0.0
            for ti in range(Tb):
                for u in range(Ub + 1):
                    if ti == 0 and u == 0:
                        continue
                    c = []
                    if ti > 0:
                        c.append(alpha[ti - 1, u] + lp[ti - 1, u, blank])
                    if u > 0:
                        c.append(alpha[ti, u - 1] + lp[ti, u - 1, lab[u - 1]])
                    alpha[ti, u] = sp.logsumexp(c)
            return -(alpha[Tb - 1, Ub] + lp[Tb - 1, Ub, blank])

        want = [ref(logits[b], label[b], ilen[b], llen[b]) for b in range(B)]
        ours = F.rnnt_loss(t(logits), t(label), t(ilen), t(llen),
                           blank=0, reduction="none")
        np.testing.assert_allclose(np.asarray(ours.numpy()), want,
                                   rtol=1e-4)
        lt = t(logits, stop_gradient=False)
        loss = nn.RNNTLoss()(lt, t(label), t(ilen), t(llen))
        loss.backward()
        assert np.isfinite(np.asarray(lt.grad.numpy())).all()


class TestMarginAndTreeLosses:
    def test_multi_margin_torch_parity(self):
        import torch

        rng = np.random.default_rng(2)
        x = rng.normal(size=(5, 7)).astype("float32")
        y = rng.integers(0, 7, (5,)).astype("int64")
        for p, margin in [(1, 1.0), (2, 0.5)]:
            ours = F.multi_margin_loss(t(x), t(y), p=p, margin=margin)
            tl = torch.nn.functional.multi_margin_loss(
                torch.tensor(x), torch.tensor(y), p=p, margin=margin)
            np.testing.assert_allclose(float(ours.numpy()), tl.item(),
                                       rtol=1e-5)
        assert nn.MultiMarginLoss()(t(x), t(y)).shape == []

    def test_triplet_with_distance_torch_parity(self):
        import torch

        rng = np.random.default_rng(3)
        a, pos, neg = (rng.normal(size=(4, 8)).astype("float32")
                       for _ in range(3))
        ours = F.triplet_margin_with_distance_loss(t(a), t(pos), t(neg),
                                                   margin=1.0)
        tl = torch.nn.functional.triplet_margin_with_distance_loss(
            torch.tensor(a), torch.tensor(pos), torch.tensor(neg),
            margin=1.0)
        np.testing.assert_allclose(float(ours.numpy()), tl.item(),
                                   rtol=1e-4)
        # custom distance fn keeps autograd
        at = t(a, stop_gradient=False)
        loss = F.triplet_margin_with_distance_loss(
            at, t(pos), t(neg),
            distance_function=lambda u, v: ((u - v) ** 2).sum(axis=-1))
        loss.backward()
        assert np.abs(np.asarray(at.grad.numpy())).sum() > 0
        assert nn.TripletMarginWithDistanceLoss(swap=True)(
            t(a), t(pos), t(neg)).shape == []

    def test_hsigmoid(self):
        rng = np.random.default_rng(4)
        x = rng.normal(size=(5, 6)).astype("float32")
        y = rng.integers(0, 8, (5,)).astype("int64")
        layer = nn.HSigmoidLoss(6, 8)
        loss = layer(t(x), t(y))
        assert loss.shape == [5, 1]
        assert (np.asarray(loss.numpy()) > 0).all()
        # custom path: two classes, single internal node
        pt = np.zeros((5, 1), "int64")
        pc = (y % 2).reshape(5, 1).astype("int64")
        w = rng.normal(size=(1, 6)).astype("float32")
        l2 = F.hsigmoid_loss(t(x), t(y), 2, t(w), path_table=t(pt),
                             path_code=t(pc))
        s = x @ w[0]
        want = np.log1p(np.exp(s)) - pc[:, 0] * s
        np.testing.assert_allclose(np.asarray(l2.numpy())[:, 0], want,
                                   rtol=1e-4)

    def test_margin_cross_entropy(self):
        rng = np.random.default_rng(5)
        feats = rng.normal(size=(6, 9)).astype("float32")
        cos = (feats / np.linalg.norm(feats, axis=1, keepdims=True)) @ \
            np.eye(9, 4, dtype="float32")
        label = rng.integers(0, 4, (6,)).astype("int64")
        loss, sm = F.margin_cross_entropy(t(cos), t(label),
                                          return_softmax=True,
                                          reduction=None)
        assert loss.shape == [6, 1] and sm.shape == [6, 4]
        # m1=1, m2=0, m3=0, scale=1 degenerates to plain softmax CE
        import scipy.special as sp

        plain, _ = F.margin_cross_entropy(
            t(cos), t(label), margin1=1.0, margin2=0.0, margin3=0.0,
            scale=1.0, return_softmax=True, reduction=None)
        want = -sp.log_softmax(cos, axis=1)[np.arange(6), label]
        np.testing.assert_allclose(np.asarray(plain.numpy())[:, 0], want,
                                   rtol=1e-4)

    def test_class_center_sample(self):
        label = t(np.array([1, 5, 5, 7], "int64"))
        remapped, sampled = F.class_center_sample(label, 20, 6)
        s = np.asarray(sampled.numpy())
        r = np.asarray(remapped.numpy())
        assert len(s) == 6
        assert {1, 5, 7} <= set(s.tolist())
        # remapped labels index into sampled
        np.testing.assert_array_equal(s[r], np.array([1, 5, 5, 7]))


class TestSequenceUtils:
    def test_sequence_mask(self):
        m = F.sequence_mask(t(np.array([2, 0, 3], "int64")), maxlen=4,
                            dtype="int32")
        np.testing.assert_array_equal(
            np.asarray(m.numpy()),
            [[1, 1, 0, 0], [0, 0, 0, 0], [1, 1, 1, 0]])

    def test_temporal_shift(self):
        x = np.arange(2 * 2 * 4 * 1 * 1, dtype="float32") \
            .reshape(4, 4, 1, 1)   # N=2, T=2, C=4
        out = F.temporal_shift(t(x), seg_num=2, shift_ratio=0.25)
        o = np.asarray(out.numpy()).reshape(2, 2, 4)
        xr = x.reshape(2, 2, 4)
        # channel 0: shifted backward (t gets t+1); last step zero
        np.testing.assert_allclose(o[:, 0, 0], xr[:, 1, 0])
        np.testing.assert_allclose(o[:, 1, 0], 0.0)
        # channel 1: shifted forward; first step zero
        np.testing.assert_allclose(o[:, 0, 1], 0.0)
        np.testing.assert_allclose(o[:, 1, 1], xr[:, 0, 1])
        # channels 2..: unchanged
        np.testing.assert_allclose(o[:, :, 2:], xr[:, :, 2:])

    def test_gather_tree(self):
        ids = t(np.array([[[2, 2]], [[3, 4]], [[5, 6]]], "int64"))
        parents = t(np.array([[[0, 0]], [[1, 0]], [[1, 0]]], "int64"))
        out = F.gather_tree(ids, parents)
        # beam 0 at final step came from parent chain 1 -> 0
        np.testing.assert_array_equal(
            np.asarray(out.numpy())[:, 0, 0], [2, 4, 5])

    def test_sparse_attention(self):
        rng = np.random.default_rng(6)
        B, H, S, D = 1, 2, 4, 8
        q, k, v = (rng.normal(size=(B, H, S, D)).astype("float32")
                   for _ in range(3))
        # full CSR = dense attention
        offset = np.tile(np.arange(S + 1, dtype="int32") * S, (B, H, 1))
        cols = np.tile(np.tile(np.arange(S, dtype="int32"), S), (B, H, 1))
        out = F.sparse_attention(t(q), t(k), t(v), t(offset), t(cols))
        logits = q @ k.transpose(0, 1, 3, 2) / np.sqrt(D)
        import scipy.special as sp

        want = sp.softmax(logits, axis=-1) @ v
        np.testing.assert_allclose(np.asarray(out.numpy()), want,
                                   rtol=1e-4)


class TestAttentionWrappers:
    def test_qkvpacked(self):
        rng = np.random.default_rng(7)
        qkv = rng.normal(size=(2, 6, 3, 2, 8)).astype("float32")
        out, _ = F.flash_attn_qkvpacked(t(qkv), causal=True)
        want, _ = F.flash_attention(t(qkv[:, :, 0]), t(qkv[:, :, 1]),
                                    t(qkv[:, :, 2]), causal=True)
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   np.asarray(want.numpy()), rtol=1e-5)

    def test_varlen_qkvpacked(self):
        rng = np.random.default_rng(8)
        qkv = rng.normal(size=(6, 3, 2, 8)).astype("float32")
        cu = np.array([0, 2, 6], "int32")
        out, _ = F.flash_attn_varlen_qkvpacked(t(qkv), t(cu), t(cu), 4, 4,
                                               None)
        assert out.shape == [6, 2, 8]

    def test_sparse_mask_flash(self):
        rng = np.random.default_rng(9)
        q, k, v = (rng.normal(size=(1, 4, 2, 8)).astype("float32")
                   for _ in range(3))
        starts = np.full((1, 2, 4), 4, "int32")   # nothing masked
        out, _ = F.flash_attention_with_sparse_mask(
            t(q), t(k), t(v), t(starts), is_causal=True)
        want, _ = F.flash_attention(t(q), t(k), t(v), causal=True)
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   np.asarray(want.numpy()), rtol=1e-5,
                                   atol=1e-6)


class TestInplaceActivations:
    def test_inplace_acts(self):
        x = t([-1.0, 2.0])
        F.relu_(x)
        np.testing.assert_allclose(np.asarray(x.numpy()), [0.0, 2.0])
        y = t([-1.0, 0.5])
        F.tanh_(y)
        np.testing.assert_allclose(np.asarray(y.numpy()),
                                   np.tanh([-1.0, 0.5]), rtol=1e-6)
        z = t([[1.0, 1.0]])
        F.softmax_(z)
        np.testing.assert_allclose(np.asarray(z.numpy()), [[0.5, 0.5]])


class TestBeamSearch:
    def test_beam_search_decode(self):
        """A deterministic 'cell' whose logits always prefer token 2, end
        token 3 — beam search must emit 2s then finish on 3."""
        vocab, beam = 5, 2

        class DummyCell:
            def __call__(self, inputs, states):
                # states: running count tensor [B*W, 1]
                cnt = states
                logits = np.full((cnt.shape[0], vocab), -5.0, "float32")
                n = np.asarray(cnt.numpy())[:, 0]
                logits[:, 2] = 2.0
                logits[n >= 2, 3] = 8.0      # after 2 steps, prefer EOS
                return paddle.to_tensor(logits), cnt + 1

        dec = nn.BeamSearchDecoder(DummyCell(), start_token=0, end_token=3,
                                   beam_size=beam)
        init = paddle.to_tensor(np.zeros((1, 1), "float32"))
        out, states = nn.dynamic_decode(dec, inits=init, max_step_num=8)
        ids = np.asarray(out.predicted_ids.numpy())   # [B, T, W]
        assert ids.shape[0] == 1 and ids.shape[2] == beam
        best = ids[0, :, 0]
        assert best[0] == 2 and 3 in best.tolist()

    def test_rnn_cell_base_exported(self):
        assert issubclass(nn.SimpleRNNCell, nn.RNNCellBase)
        assert issubclass(nn.LSTMCell, nn.RNNCellBase)


class TestOpRegistry:
    def test_registry_breadth(self):
        """VERDICT r2 ask: registered-op count >= 500 (primitives via
        @op_fn + composite surface via ops/composite.py)."""
        from paddle_tpu.ops._op import registered_ops

        reg = registered_ops()
        assert len(reg) >= 500, len(reg)
        # every entry is callable and its recorded name resolves in the
        # registry (aliases keep their first name: row_stack -> vstack)
        for name, fn in reg.items():
            assert callable(fn)
            assert getattr(fn, "op_name", name) in reg

    def test_composite_entries_dispatch(self):
        """Composite registry entries are the live API functions."""
        from paddle_tpu.ops._op import get_op

        out = get_op("hstack")([t(np.zeros(2, "float32")),
                                t(np.ones(2, "float32"))])
        assert out.shape == [4]
        assert get_op("allclose") is not None
        assert get_op("bmm") is not None


class TestAdviceR3Fixes:
    """Regressions for the round-3 advisor findings (ADVICE.md r3)."""

    def test_worker_seed_differs_across_epochs(self):
        # WorkerInfo.seed must be base_seed + wid with a fresh base per
        # epoch, not a constant equal to the worker id.
        from paddle_tpu.io import DataLoader

        seen = []

        class DS:
            def __getitem__(self, i):
                from paddle_tpu.io.dataloader import get_worker_info
                return np.float32(get_worker_info().seed)

            def __len__(self):
                return 4

        dl = DataLoader(DS(), batch_size=4, num_workers=1,
                        worker_mode="process")
        for _ in range(2):
            for batch in dl:
                seen.append(int(np.asarray(batch.numpy())[0]))
        assert seen[0] != 0 or seen[1] != 0
        assert seen[0] != seen[1]   # fresh base seed per epoch

    def test_affine_transform_preserves_dtype_and_broadcasts_shape(self):
        import jax.numpy as jnp

        from paddle_tpu.distribution import AffineTransform
        import paddle_tpu as paddle

        tr = AffineTransform(paddle.to_tensor([0.0, 1.0]),
                             paddle.to_tensor([1.0, 2.0]))
        y = tr.forward(paddle.to_tensor(np.ones((3, 2), np.float16)))
        assert y._data.dtype == jnp.float16
        assert tr.forward_shape((3, 1)) == (3, 2)
        assert tr.inverse_shape((2,)) == (2,)
        ld = tr.forward_log_det_jacobian(paddle.to_tensor(
            np.ones((3, 1), np.float32)))
        assert list(ld.shape) == [3, 2]

    def test_sequence_mask_traced_without_maxlen_raises(self):
        import jax
        import pytest

        from paddle_tpu.nn.functional import sequence_mask
        import paddle_tpu as paddle

        assert sequence_mask(paddle.to_tensor([2, 3]), maxlen=None) \
            .shape == [2, 3]

        def f(x):
            return sequence_mask(x, maxlen=None)._data

        with pytest.raises(ValueError, match="explicit maxlen"):
            jax.jit(f)(np.array([2, 3]))

    def test_binomial_entropy_traced_raises(self):
        import jax
        import pytest

        from paddle_tpu.distribution import Binomial
        import paddle_tpu as paddle

        def f(n):
            return Binomial(n, paddle.to_tensor(0.5)).entropy()._data

        with pytest.raises(ValueError, match="concrete total_count"):
            jax.jit(f)(np.array(4.0, np.float32))
