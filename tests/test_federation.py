"""Fleet SLO federation (ISSUE 15): per-replica telemetry frames,
federated burn-aware scaling, and the /fleet/serving surface.

Fast-lane pins: frame schema/versioning, clock-skew-free staleness
(stale/absent frames contribute NOTHING — never fabricated),
request-weighted federation math against synthetic frames (exact
ratios), flags-off byte-identical controller decisions on a recorded
signal trace, fast-burn-at-flat-demand scale-out + alerting-burn
scale-in refusal (both acceptance pins), the bounded legacy-signals
fallback (one frozen replica delays a tick by at most its bound),
heartbeat beat-file GC on stop/replace, the zero-device-sync pin via
the exectime ``_block_until_ready`` indirection, and the
/fleet/serving + exposition + flight surfaces. The 2-process
launch-CLI case (frames over the KV transport, rank-0 scrape) is
slow-marked.
"""
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import monitor
from paddle_tpu.monitor import federation as fed
from paddle_tpu.monitor import server
from paddle_tpu.distributed import heartbeat as hb
from paddle_tpu.distributed.fleet.elastic import (AdaptiveElasticManager,
                                                  _BoundedSignals)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def mon():
    monitor.reset()
    server.stop_server()
    pt.set_flags({"FLAGS_enable_monitor": True})
    yield monitor
    server.stop_server()
    pt.set_flags({"FLAGS_enable_monitor": False,
                  "FLAGS_enable_monitor_server": False})
    monitor.reset()


@pytest.fixture(autouse=True)
def _clean_federation():
    fed.reset()
    yield
    fed.reset()


class FakeKV:
    def __init__(self):
        self.d = {}

    def key_value_set(self, k, v, allow_overwrite=False):
        if not allow_overwrite and k in self.d:
            raise RuntimeError(f"key exists: {k}")
        self.d[k] = v

    def key_value_try_get(self, k):
        if k not in self.d:
            raise KeyError(k)
        return self.d[k]

    def key_value_delete(self, k):
        self.d.pop(k, None)


def _tiny_engine(num_slots=2):
    from paddle_tpu.inference import ServingEngine
    from paddle_tpu.models import llama as L
    cfg = L.llama_tiny()
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    return ServingEngine(L, params, cfg, num_slots=num_slots,
                         max_len=32, page_size=4, decode_chunk=3), cfg


def _requests(cfg, n, max_new=4, seed=0):
    from paddle_tpu.inference import Request
    rng = np.random.default_rng(seed)
    return [Request(rid=i,
                    prompt=rng.integers(0, cfg.vocab_size, (5,))
                    .astype(np.int32),
                    max_new_tokens=max_new) for i in range(n)]


def _mk_frame(name, seq=1, *, demand=0.5, burn_fast=None,
              compliance=None, samples=32, objective="ttft_p99_ms",
              draining=False, drain_safe=True, tenants=None,
              requests=None, version=fed.FRAME_VERSION):
    """A synthetic frame with one objective's slo row."""
    objectives = {}
    if burn_fast is not None or compliance is not None:
        objectives[objective] = {
            "compliance": compliance,
            "burn_fast": burn_fast,
            "burn_slow": burn_fast,
            "samples_slow": samples,
            "samples_fast": samples,
            "target_ratio": 0.99,
        }
    return {
        "kind": fed.FRAME_KIND, "version": version, "name": name,
        "seq": seq, "t": round(time.time(), 3),
        "autoscale": {"demand_estimate": demand,
                      "desired_capacity_hint": int(np.ceil(demand)),
                      "queue_depth": 0, "live_slots": 0,
                      "drain_safe": drain_safe},
        "slo": {"objectives": objectives,
                "alerting": [objective] if (burn_fast or 0) >= 14.4
                else []},
        "tenants": tenants or {},
        "requests": requests or {"completed": samples},
        "draining": draining, "drain_complete": drain_safe,
    }


class _StubEngine:
    """Engine-shaped stand-in for publisher/surface tests that don't
    need a real decode path (the real-engine pins — schema, the step
    hook, zero-sync — keep a real ServingEngine; everything else
    skips the compile cost)."""

    def __init__(self):
        from paddle_tpu.inference.engine import EngineStats
        self.stats = EngineStats()
        self.stats.admitted = self.stats.completed = 2
        self.draining = False
        self.drain_complete = True

    def autoscale_payload(self):
        return {"demand_estimate": 0.4, "desired_capacity_hint": 1,
                "queue_depth": 0, "live_slots": 0, "drain_safe": True}


class _FakeReplica:
    def __init__(self, demand=0.0, drain_safe=True):
        self.demand = demand
        self._drain_safe = drain_safe
        self.draining = False

    def autoscale_payload(self):
        return {"demand_estimate": self.demand,
                "desired_capacity_hint": int(np.ceil(self.demand)),
                "drain_safe": self._drain_safe}

    def begin_drain(self):
        self.draining = True


# ---------------------------------------------------------------------------
# frame schema + publisher
# ---------------------------------------------------------------------------

class TestFrameSchema:
    def test_build_frame_fields_and_version(self, mon):
        eng, cfg = _tiny_engine()
        eng.run(_requests(cfg, 2))
        frame = fed.build_frame(eng, name="r0", seq=3)
        assert frame["kind"] == fed.FRAME_KIND
        assert frame["version"] == fed.FRAME_VERSION == 1
        assert frame["name"] == "r0" and frame["seq"] == 3
        asc = frame["autoscale"]
        assert asc["drain_safe"] is True        # drained engine
        for obj in ("ttft_p99_ms", "availability"):
            assert obj in frame["slo"]["objectives"]
            row = frame["slo"]["objectives"][obj]
            assert set(row) == {"compliance", "burn_fast", "burn_slow",
                                "samples_slow", "samples_fast",
                                "target_ratio"}
        assert frame["requests"]["completed"] == 2
        assert frame["requests"]["admitted"] == 2
        assert frame["draining"] is False
        assert frame["drain_complete"] is True
        assert "default" in frame["tenants"]     # bounded table rides
        json.dumps(frame)                        # JSON-serializable

    def test_publisher_seq_rate_limit_and_force(self, mon):
        eng, cfg = _tiny_engine()
        clock = [0.0]
        pub = fed.FramePublisher("r0", min_interval_s=1.0,
                                 _time_fn=lambda: clock[0])
        assert pub.maybe_publish(eng)["seq"] == 1
        assert pub.maybe_publish(eng) is None          # rate-limited
        assert pub.maybe_publish(eng, force=True)["seq"] == 2
        clock[0] = 5.0
        assert pub.maybe_publish(eng)["seq"] == 3
        assert fed.local_frames()["r0"]["seq"] == 3

    def test_publish_file_kv_roundtrip_prefers_higher_seq(self,
                                                         tmp_path):
        kv = FakeKV()
        d = str(tmp_path)
        hb.publish_named("r0", _mk_frame("r0", seq=1), dir_path=d,
                         client=kv)
        assert hb.read_named("r0", dir_path=d,
                             client=kv)["seq"] == 1
        # KV ahead of the file (a relay lag): reader takes the max seq
        kv.key_value_set(f"{hb._NAMED_KV_PREFIX}/r0",
                         json.dumps(_mk_frame("r0", seq=7)),
                         allow_overwrite=True)
        assert hb.read_named("r0", dir_path=d,
                             client=kv)["seq"] == 7
        # file ahead: file wins
        hb.touch_named(d, "r0", _mk_frame("r0", seq=9))
        assert hb.read_named("r0", dir_path=d,
                             client=kv)["seq"] == 9

    def test_engine_hook_publishes_and_frame_is_the_beat(self, mon,
                                                         tmp_path):
        d = str(tmp_path)
        eng, cfg = _tiny_engine()
        eng.publish_frames("replica0", d, min_interval_s=0.0)
        eng.run(_requests(cfg, 2))
        frame = hb.read_named("replica0", dir_path=d)
        assert frame is not None and frame["seq"] >= 2
        assert frame["requests"]["completed"] == 2
        # the frame IS the liveness beat: stale_names sees it fresh
        assert hb.stale_names(d, ["replica0"], timeout=30.0) == {}
        # counted
        snap = monitor.snapshot()
        assert snap["counters"]["federation.frames.published"] >= 2

    def test_begin_drain_force_publishes(self, mon, tmp_path):
        d = str(tmp_path)
        eng, cfg = _tiny_engine()
        eng.publish_frames("replica0", d, min_interval_s=1e9)
        eng.begin_drain()
        frame = hb.read_named("replica0", dir_path=d)
        assert frame["draining"] is True

    def test_monitor_off_publishes_but_registers_nothing(self,
                                                         tmp_path):
        monitor.reset()
        pt.set_flags({"FLAGS_enable_monitor": False})
        d = str(tmp_path)
        pub = fed.FramePublisher("replica0", d, min_interval_s=0.0)
        assert pub.maybe_publish(_StubEngine()) is not None
        # the explicit opt-in still publishes (a controller needs the
        # demand signal regardless of the metrics plane)...
        assert hb.read_named("replica0", dir_path=d) is not None
        # ...and federating it writes no gauges either
        view = fed.FleetSLOView(d, staleness_s=60.0)
        view.fleet_report(["replica0"])
        # the metrics registry stays empty
        assert monitor.snapshot() == {}


# ---------------------------------------------------------------------------
# staleness (clock-skew-free) + version gating
# ---------------------------------------------------------------------------

class TestStaleness:
    def test_fresh_then_stale_contributes_nothing(self):
        clock = [0.0]
        view = fed.FleetSLOView(staleness_s=5.0,
                                _time_fn=lambda: clock[0])
        view.ingest("a", _mk_frame("a", seq=1, burn_fast=20.0,
                                   compliance=0.5, demand=0.8))
        fresh, stale = view.frames()
        assert "a" in fresh and not stale
        rep = view.fleet_report(poll=False)
        assert rep["objectives"]["ttft_p99_ms"]["burn_fast"] == 20.0
        assert rep["demand"]["demand_estimate_sum"] == 0.8
        clock[0] = 6.0                         # past the window
        fresh, stale = view.frames()
        assert not fresh and stale["a"] == 6.0
        rep = view.fleet_report(poll=False)
        # a stale frame contributes NOTHING — no objectives, no
        # demand, no fabricated zeros
        assert rep["objectives"] == {}
        assert rep["demand"]["demand_estimate_sum"] is None
        assert rep["demand"]["desired_capacity_hint"] is None
        assert rep["attribution"] == []
        assert rep["staleness"]["stale"] == {"a": 6.0}

    def test_same_seq_does_not_reset_age_new_seq_does(self):
        clock = [0.0]
        view = fed.FleetSLOView(staleness_s=5.0,
                                _time_fn=lambda: clock[0])
        view.ingest("a", _mk_frame("a", seq=1))
        clock[0] = 4.0
        view.ingest("a", _mk_frame("a", seq=1))    # republish, no new
        clock[0] = 6.0                             # 6s since seq change
        assert view.frames()[0] == {}
        view.ingest("a", _mk_frame("a", seq=2))    # a real new frame
        assert "a" in view.frames()[0]

    def test_absent_name_never_appears(self):
        view = fed.FleetSLOView(staleness_s=5.0)
        view.ingest("a", _mk_frame("a"))
        fresh, stale = view.frames(names=["b"])
        assert fresh == {} and stale == {}

    def test_newer_version_dropped(self):
        view = fed.FleetSLOView(staleness_s=5.0)
        assert not view.ingest("a", _mk_frame(
            "a", version=fed.FRAME_VERSION + 1))
        assert not view.ingest("a", {"kind": "something-else"})
        assert not view.ingest("a", _mk_frame("a", version="junk"))
        assert view.frames()[0] == {}

    def test_forget_drops_tracking(self):
        view = fed.FleetSLOView(staleness_s=60.0)
        view.ingest("a", _mk_frame("a"))
        view.forget("a")
        assert view.frames()[0] == {}

    def test_poll_reads_transport(self, tmp_path):
        d = str(tmp_path)
        hb.publish_named("a", _mk_frame("a", seq=4), dir_path=d)
        view = fed.FleetSLOView(d, staleness_s=60.0)
        assert view.poll(["a", "missing"]) == 1
        assert view.frames()[0]["a"]["seq"] == 4


# ---------------------------------------------------------------------------
# federation math (pure, exact)
# ---------------------------------------------------------------------------

class TestFederateMath:
    def test_request_weighted_burn_and_compliance(self):
        frames = {
            "a": _mk_frame("a", compliance=0.9, burn_fast=10.0,
                           samples=100),
            "b": _mk_frame("b", compliance=0.99, burn_fast=1.0,
                           samples=50),
        }
        rep = fed.federate(frames)
        obj = rep["objectives"]["ttft_p99_ms"]
        # (0.9*100 + 0.99*50) / 150
        assert obj["compliance"] == pytest.approx(0.93)
        # (10*100 + 1*50) / 150
        assert obj["burn_slow"] == pytest.approx(7.0)
        assert obj["burn_fast"] == pytest.approx(
            (10.0 * 100 + 1.0 * 50) / 150)
        assert obj["samples_slow"] == 150
        assert obj["replicas_reporting"] == 2

    def test_none_windows_never_fabricated(self):
        frames = {"a": _mk_frame("a"),        # no slo rows at all
                  "b": _mk_frame("b", compliance=None, burn_fast=None,
                                 samples=0)}
        rep = fed.federate(frames)
        obj = rep["objectives"].get("ttft_p99_ms")
        if obj is not None:
            assert obj["compliance"] is None
            assert obj["burn_fast"] is None
            assert obj["burn_slow"] is None
        assert rep["alerting"] == []

    def test_alerting_threshold_and_load_view(self):
        frames = {"a": _mk_frame("a", burn_fast=20.0, compliance=0.5,
                                 samples=64)}
        rep = fed.federate(frames)
        assert rep["alerting"] == ["ttft_p99_ms"]
        assert rep["alerting_load"] == ["ttft_p99_ms"]
        # availability burn alone does NOT arm the load view
        frames = {"a": _mk_frame("a", burn_fast=20.0, compliance=0.5,
                                 samples=64, objective="availability")}
        rep = fed.federate(frames)
        assert rep["alerting"] == ["availability"]
        assert rep["alerting_load"] == []

    def test_attribution_burning_replica_is_line_one(self):
        frames = {
            "healthy": _mk_frame("healthy", compliance=1.0,
                                 burn_fast=0.0, samples=64),
            "burning": _mk_frame("burning", compliance=0.5,
                                 burn_fast=50.0, samples=64),
            "quiet": _mk_frame("quiet"),     # no slo data: last
        }
        att = fed.federate(frames)["attribution"]
        assert [a["replica"] for a in att] == \
            ["burning", "healthy", "quiet"]
        assert att[0]["alerting"] is True
        assert att[0]["objective"] == "ttft_p99_ms"
        assert att[2]["burn_fast"] is None    # no data stays None

    def test_tenant_and_request_sums_and_demand_ceiling(self):
        frames = {
            "a": _mk_frame("a", demand=0.6,
                           tenants={"t1": {"requests": 3,
                                           "decode_tokens": 10}},
                           requests={"completed": 5, "shed": 1}),
            "b": _mk_frame("b", demand=0.7,
                           tenants={"t1": {"requests": 2},
                                    "t2": {"requests": 9}},
                           requests={"completed": 7, "expired": 2}),
        }
        rep = fed.federate(frames)
        assert rep["tenants"]["t1"] == {"requests": 5,
                                        "decode_tokens": 10}
        assert rep["tenants"]["t2"] == {"requests": 9}
        assert rep["requests"] == {"completed": 12, "shed": 1,
                                   "expired": 2}
        assert rep["demand"]["demand_estimate_sum"] == \
            pytest.approx(1.3)
        assert rep["demand"]["desired_capacity_hint"] == 2

    def test_empty_fleet(self):
        rep = fed.federate({})
        assert rep["replicas"] == []
        assert rep["objectives"] == {}
        assert rep["demand"]["demand_estimate_sum"] is None


# ---------------------------------------------------------------------------
# controller actuation (acceptance pins)
# ---------------------------------------------------------------------------

def _run_controller(mgr, spawn, stop, done, out, **kw):
    def run():
        out.update(mgr.run_serving(spawn, stop, stop_event=done, **kw))
    th = threading.Thread(target=run, daemon=True)
    th.start()
    return th


class TestControllerActuation:
    def test_fast_burn_flat_demand_scales_out(self):
        """Acceptance: a fleet latency fast-burn with FLAT demand
        provably scales out — and the pressure is stable (+1 over the
        demand target, not an escalation to max)."""
        view = fed.FleetSLOView(staleness_s=120.0)
        view.ingest("replica0", _mk_frame(
            "replica0", seq=1, demand=0.2, burn_fast=30.0,
            compliance=0.5, samples=64))
        replicas, stopped = {}, []

        def spawn(name):
            r = _FakeReplica(demand=0.0)
            replicas[name] = r
            return r

        mgr = AdaptiveElasticManager()
        done = threading.Event()
        out = {}
        th = _run_controller(
            mgr, spawn, lambda n, h: stopped.append(n), done, out,
            min_replicas=1, max_replicas=4, poll_interval=0.01,
            federation=view, fleet_burn_scaling=True, max_ticks=2000)
        deadline = time.monotonic() + 10
        while len(replicas) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(replicas) == 2, "burn pressure did not scale out"
        # pressure is stable: +1 over demand-desired (1) = 2, never 3
        time.sleep(0.3)
        assert len(replicas) == 2
        assert not stopped        # and never scaled in while burning
        done.set()
        th.join(timeout=5)
        reasons = [d.get("reason") for _, s, d in mgr.events]
        assert "burn-pressure" in reasons

    def test_alerting_burn_refuses_scale_in_until_clear(self):
        """Acceptance: surplus capacity is NOT drained while the fleet
        burn alerts; clearing the burn releases the scale-in."""
        view = fed.FleetSLOView(staleness_s=120.0)

        def ingest_all(seq, burn, demand0):
            for n in ("replica0", "replica1", "replica2"):
                view.ingest(n, _mk_frame(
                    n, seq=seq,
                    demand=demand0 if n == "replica0" else 0.2,
                    burn_fast=burn,
                    compliance=0.5 if burn else 1.0, samples=64))

        replicas, stopped = {}, []

        def spawn(name):
            r = _FakeReplica(demand=0.0)
            replicas[name] = r
            return r

        mgr = AdaptiveElasticManager()
        done = threading.Event()
        out = {}
        ingest_all(1, 0.0, demand0=2.5)         # healthy high demand
        th = _run_controller(
            mgr, spawn, lambda n, h: stopped.append(n), done, out,
            min_replicas=1, max_replicas=4, poll_interval=0.01,
            federation=view, fleet_burn_scaling=True, max_ticks=20000)
        deadline = time.monotonic() + 5
        while len(replicas) < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(replicas) == 3               # demand scaled to 3
        # demand collapses AND the fleet burns: desired = demand(1) +
        # pressure(1) = 2 < live 3 — scale-in is wanted but refused
        ingest_all(2, 30.0, demand0=0.2)
        deadline = time.monotonic() + 3
        while time.monotonic() < deadline and not any(
                d.get("reason") == "burn-scale-in-refused"
                for _, s, d in mgr.events):
            time.sleep(0.01)
        assert any(d.get("reason") == "burn-scale-in-refused"
                   for _, s, d in mgr.events)
        assert stopped == []                    # nothing drained
        assert not any(r.draining for r in replicas.values())
        ingest_all(3, 0.0, demand0=0.2)         # burn clears
        deadline = time.monotonic() + 10
        while not stopped and time.monotonic() < deadline:
            time.sleep(0.01)
        done.set()
        th.join(timeout=5)
        assert stopped and stopped[0] == "replica2"  # newest drained

    def test_flags_off_decisions_byte_identical_on_recorded_trace(self):
        """Acceptance: with FLAGS_serving_fleet_burn_scaling off (the
        default), the controller's decisions on a deterministic signal
        trace are byte-identical to the pre-federation controller —
        the exact event sequence of the demand-only policy."""
        assert not pt.get_flags(
            ["FLAGS_serving_fleet_burn_scaling"]
        )["FLAGS_serving_fleet_burn_scaling"]
        replicas, stopped = {}, []
        tick = [0]
        # recorded trace: 5 ticks at fleet demand 2.6 (scale 1->3),
        # then flat 0.2 (scale 3->1, newest first, one per tick)
        demand_by_tick = [2.6] * 5 + [0.2] * 200

        def spawn(name):
            r = _FakeReplica(demand=0.0, drain_safe=True)
            replicas[name] = r
            return r

        def signals(name, h):
            if name == "replica0":
                # replica0 is polled first each gather: it carries the
                # whole fleet's scripted demand and advances the tick
                t = min(tick[0], len(demand_by_tick) - 1)
                tick[0] += 1
                return {"demand_estimate": demand_by_tick[t],
                        "drain_safe": True}
            return {"demand_estimate": 0.0,
                    "drain_safe": True}

        mgr = AdaptiveElasticManager()
        out = mgr.run_serving(
            spawn, lambda n, h: stopped.append(n), signals=signals,
            min_replicas=1, max_replicas=4, poll_interval=0.001,
            drain_timeout=5.0, max_ticks=40)
        decisions = [(s, d.get("reason"), d.get("replica"))
                     for _, s, d in mgr.events]
        # the pre-PR controller's exact decision sequence, byte for
        # byte: initial spawn, two scale-outs on the first 2.6 tick,
        # then newest-first scale-ins once demand falls to 0.2
        assert decisions == [
            ("restart", "spawn", "replica0"),
            ("restart", "scale-out", "replica1"),
            ("restart", "scale-out", "replica2"),
            ("restart", "scale-in", "replica2"),
            ("restart", "scale-in", "replica1"),
            ("exit", "max_ticks", None),
        ], decisions
        assert stopped == ["replica2", "replica1"]
        assert out["replicas"] == ["replica0"]

    def test_frames_replace_signals_calls(self):
        """With a view holding fresh frames, the legacy callable is
        never consulted for those replicas — the tick is frame-fed."""
        view = fed.FleetSLOView(staleness_s=120.0)
        view.ingest("replica0", _mk_frame("replica0", seq=1,
                                          demand=2.4))
        calls = []

        def signals(name, h):
            calls.append(name)
            return {"demand_estimate": 0.0, "drain_safe": True}

        replicas = {}

        def spawn(name):
            r = _FakeReplica()
            replicas[name] = r
            return r

        mgr = AdaptiveElasticManager()
        done = threading.Event()
        th = _run_controller(
            mgr, spawn, lambda n, h: None, done, {},
            signals=signals, min_replicas=1, max_replicas=3,
            poll_interval=0.01, federation=view, max_ticks=2000)
        deadline = time.monotonic() + 10
        while (len(replicas) < 3 or "replica1" not in calls) \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        done.set()
        th.join(timeout=5)
        # frame demand 2.4 drove the scale-out to 3...
        assert len(replicas) == 3
        # ...and replica0 (fresh frame) was never signalled; the
        # frame-less replicas used the fallback
        assert "replica0" not in calls
        assert "replica1" in calls


# ---------------------------------------------------------------------------
# control-loop isolation: bounded legacy callable
# ---------------------------------------------------------------------------

class TestControlLoopIsolation:
    def test_bounded_signals_frozen_call_skipped_next_time(self):
        frozen = threading.Event()
        calls = []

        def signals(name, h):
            calls.append(name)
            if name == "stuck":
                frozen.wait()          # never set: wedged forever
            return {"demand_estimate": 1.0}

        b = _BoundedSignals(signals, timeout=0.2)
        t0 = time.monotonic()
        assert b("stuck", None) is None          # waited one bound
        first = time.monotonic() - t0
        assert 0.15 <= first < 2.0
        t0 = time.monotonic()
        assert b("stuck", None) is None          # skipped instantly
        assert time.monotonic() - t0 < 0.05
        assert b("ok", None) == {"demand_estimate": 1.0}
        assert calls.count("stuck") == 1         # no thread stacking
        frozen.set()

    def test_bounded_signals_passthrough_and_recovery(self):
        gate = threading.Event()

        def signals(name, h):
            gate.wait(0.4)
            return {"demand_estimate": 2.0}

        b = _BoundedSignals(signals, timeout=0.1)
        assert b("r", None) is None              # blew the bound
        gate.set()
        time.sleep(0.5)                          # worker finished late
        assert b("r", None) == {"demand_estimate": 2.0}  # late result
        # unbounded passthrough
        ub = _BoundedSignals(lambda n, h: {"x": 1}, timeout=None)
        assert ub("r", None) == {"x": 1}

    def test_bounded_signals_reuses_one_worker_and_retires(self):
        """The healthy common case (every replica, every tick) rides
        ONE persistent worker per name — no thread create/join per
        call; retire() shuts the worker down so a stopped replica's
        thread does not idle for the rest of the run."""
        idents = []

        def signals(name, h):
            idents.append(threading.get_ident())
            return {"demand_estimate": 1.0}

        b = _BoundedSignals(signals, timeout=1.0)
        for _ in range(5):
            assert b("r", None) == {"demand_estimate": 1.0}
        assert len(idents) == 5
        assert len(set(idents)) == 1             # one worker, reused
        assert idents[0] != threading.get_ident()
        th = b._workers["r"][0]
        b.retire("r")
        th.join(timeout=2)
        assert not th.is_alive()                 # worker shut down
        assert "r" not in b._workers

    def test_frozen_replica_does_not_stall_the_fleet(self):
        """The isolation pin: one replica whose signals callable hangs
        forever delays each tick by at most the bound — heartbeat
        checks and scale-out for the rest of the fleet keep running."""
        frozen = threading.Event()
        demand0 = [1.2]

        def signals(name, h):
            if name == "replica1":
                frozen.wait()                    # wedged forever
                return None
            return {"demand_estimate": demand0[0],
                    "drain_safe": True}

        replicas = {}

        def spawn(name):
            r = _FakeReplica()
            replicas[name] = r
            return r

        mgr = AdaptiveElasticManager()
        done = threading.Event()
        th = _run_controller(
            mgr, spawn, lambda n, h: None, done, {},
            signals=signals, min_replicas=2, max_replicas=3,
            poll_interval=0.01, signal_timeout=0.2, max_ticks=100000)
        time.sleep(0.5)                          # past the first bound
        demand0[0] = 2.5                         # demand rises
        deadline = time.monotonic() + 5
        while len(replicas) < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        done.set()
        th.join(timeout=5)
        frozen.set()
        # the frozen replica1 did not stall the loop: the demand rise
        # on replica0 still scaled the fleet out within the deadline
        assert len(replicas) == 3, mgr.events

    def test_default_inprocess_signals_not_thread_bounded(
            self, monkeypatch):
        """The built-in default signals (a direct in-process
        ``autoscale_payload()`` read) is pass-through — it cannot
        wedge on a transport, and bounding it would spawn a worker
        thread per replica per tick on the control loop. A
        user-passed callable keeps the bound."""
        import paddle_tpu.distributed.fleet.elastic as el
        timeouts = []
        real = el._BoundedSignals

        class Spy(real):
            def __init__(self, fn, timeout):
                timeouts.append(timeout)
                super().__init__(fn, timeout)

        monkeypatch.setattr(el, "_BoundedSignals", Spy)
        mgr = AdaptiveElasticManager()
        mgr.run_serving(lambda n: _FakeReplica(), lambda n, h: None,
                        min_replicas=1, max_replicas=1,
                        poll_interval=0.001, max_ticks=3)
        assert timeouts == [None]            # default: inline
        mgr2 = AdaptiveElasticManager()
        mgr2.run_serving(lambda n: _FakeReplica(), lambda n, h: None,
                         signals=lambda n, h: {"demand_estimate": 0.0,
                                               "drain_safe": True},
                         min_replicas=1, max_replicas=1,
                         poll_interval=0.001, max_ticks=3)
        assert timeouts == [None, 5.0]       # user callable: bounded


# ---------------------------------------------------------------------------
# heartbeat beat-file GC
# ---------------------------------------------------------------------------

class TestBeatFileGC:
    def test_remove_named_file_and_kv(self, tmp_path):
        d = str(tmp_path)
        kv = FakeKV()
        hb.publish_named("r0", _mk_frame("r0"), dir_path=d, client=kv)
        assert os.path.exists(os.path.join(d, "r0.alive"))
        hb.remove_named(d, "r0", client=kv)
        assert not os.path.exists(os.path.join(d, "r0.alive"))
        assert f"{hb._NAMED_KV_PREFIX}/r0" not in kv.d
        hb.remove_named(d, "r0", client=kv)      # idempotent

    def test_scale_in_sweeps_beat_file_no_stale_report(self, tmp_path):
        """The satellite pin: stop -> sweep -> no stale report, no
        accumulating beat files."""
        d = str(tmp_path)
        replicas, stopped = {}, []

        def spawn(name):
            r = _FakeReplica(demand=2.2 if name == "replica0" else 0.0)
            replicas[name] = r
            hb.touch_named(d, name)              # the replica beats
            return r

        mgr = AdaptiveElasticManager()
        done = threading.Event()
        th = _run_controller(
            mgr, spawn, lambda n, h: stopped.append(n), done, {},
            min_replicas=1, max_replicas=3, poll_interval=0.01,
            heartbeat_dir=d, heartbeat_timeout=30.0, max_ticks=100000)
        deadline = time.monotonic() + 5
        while len(replicas) < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        replicas["replica0"].demand = 0.2        # load falls off
        deadline = time.monotonic() + 10
        while len(stopped) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        done.set()
        th.join(timeout=5)
        assert stopped == ["replica2", "replica1"]
        # swept: the retired replicas' beat files are GONE...
        for name in stopped:
            assert not os.path.exists(os.path.join(d, f"{name}.alive"))
            # ...and a later scan over the name reports nothing (no
            # file, no started_at -> silent, not "stale forever")
            assert hb.stale_names(d, [name], timeout=0.001) == {}
        # the survivor's beat file remains
        assert os.path.exists(os.path.join(d, "replica0.alive"))

    def test_stale_replace_sweeps_beat_file(self, tmp_path):
        d = str(tmp_path)
        replicas, stopped, beat_stops = {}, [], []

        def spawn(name):
            r = _FakeReplica(demand=0.0)
            replicas[name] = r
            if name == "replica0":
                hb.touch_named(d, name)          # beats once, then dies
            else:
                # the replacement keeps beating via its own thread
                beat_stops.append(hb.start_named(d, name,
                                                 interval=0.05))
            return r

        mgr = AdaptiveElasticManager(max_restarts=3)
        done = threading.Event()
        th = _run_controller(
            mgr, spawn, lambda n, h: stopped.append(n), done, {},
            min_replicas=1, max_replicas=2, poll_interval=0.05,
            heartbeat_dir=d, heartbeat_timeout=0.3, max_ticks=100000)
        deadline = time.monotonic() + 10
        while "replica0" not in stopped and time.monotonic() < deadline:
            time.sleep(0.02)
        done.set()
        th.join(timeout=5)
        for ev in beat_stops:
            ev.set()
        assert "replica0" in stopped             # stale-replaced
        assert "replica1" in replicas
        assert not os.path.exists(os.path.join(d, "replica0.alive"))
        reasons = [x[2].get("reason") for x in mgr.events]
        assert "stale-replace" in reasons

    def test_scale_in_sweeps_view_transport_kv_only(self):
        """KV-only fleet (no shared filesystem, a view with its OWN
        client — the deployment read_named's KV leg exists for):
        scale-in retirement sweeps the retired name's pt_named key
        through the VIEW's transport, not just the global client."""
        kv = FakeKV()
        view = fed.FleetSLOView(None, client=kv, staleness_s=0.01)
        replicas, stopped = {}, []

        def spawn(name):
            r = _FakeReplica(demand=2.2 if name == "replica0" else 0.0)
            replicas[name] = r
            # the replica publishes one frame into the KV store; the
            # tiny staleness window hands demand control back to the
            # signals fallback right away
            hb.publish_named(name, _mk_frame(name, seq=1), client=kv)
            return r

        mgr = AdaptiveElasticManager()
        done = threading.Event()
        th = _run_controller(
            mgr, spawn, lambda n, h: stopped.append(n), done, {},
            min_replicas=1, max_replicas=3, poll_interval=0.01,
            federation=view, max_ticks=100000)
        deadline = time.monotonic() + 5
        while len(replicas) < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(replicas) == 3
        replicas["replica0"].demand = 0.2        # load falls off
        deadline = time.monotonic() + 10
        while len(stopped) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        done.set()
        th.join(timeout=5)
        assert stopped == ["replica2", "replica1"]
        for name in stopped:
            assert f"{hb._NAMED_KV_PREFIX}/{name}" not in kv.d
        # the survivor's frame is untouched
        assert f"{hb._NAMED_KV_PREFIX}/replica0" in kv.d

    def test_spawn_sweeps_prior_incarnation_frame(self, tmp_path):
        """A prior controller incarnation that exited with replicas
        live leaves a high-seq replica0 frame behind (file + KV); the
        next incarnation's spawn sweeps the name, so the dead frame
        is neither stamped fresh for a staleness window nor allowed
        to outrank the fresh replica's restart-at-1 publisher in
        ``read_named``'s seq tiebreak."""
        d = str(tmp_path)
        kv = FakeKV()
        hb.publish_named("replica0",
                         _mk_frame("replica0", seq=500, demand=3.9),
                         dir_path=d, client=kv)
        view = fed.FleetSLOView(d, client=kv, staleness_s=120.0)
        replicas = {}

        def spawn(name):
            r = _FakeReplica(demand=0.0)
            replicas[name] = r
            return r

        mgr = AdaptiveElasticManager()
        out = mgr.run_serving(
            spawn, lambda n, h: None, min_replicas=1, max_replicas=4,
            poll_interval=0.001, heartbeat_dir=d, federation=view,
            max_ticks=30)
        # swept at spawn: file + KV gone before the first poll
        assert not os.path.exists(os.path.join(d, "replica0.alive"))
        assert f"{hb._NAMED_KV_PREFIX}/replica0" not in kv.d
        # and the dead frame's demand (3.9 -> 4 replicas) never fed
        # the controller: the live replica's 0.0 demand held the fleet
        assert out["replicas"] == ["replica0"]
        assert not any(x[2].get("reason") == "scale-out"
                       for x in mgr.events)


# ---------------------------------------------------------------------------
# zero device synchronizations
# ---------------------------------------------------------------------------

class TestZeroSync:
    def test_frame_publication_adds_zero_syncs_at_any_rate(
            self, mon, tmp_path, monkeypatch):
        """Acceptance: publishing every scheduler step adds ZERO
        block_until_ready calls (the exectime indirection counts every
        added synchronization; the engine's own paths add none at
        sample rate 0)."""
        from paddle_tpu.monitor import exectime
        exectime.set_sample_rate(0)
        calls = []
        monkeypatch.setattr(exectime, "_block_until_ready",
                            lambda outputs: calls.append(1))
        try:
            eng, cfg = _tiny_engine()
            eng.publish_frames("r0", str(tmp_path), min_interval_s=0.0)
            eng.run(_requests(cfg, 3))
            assert eng.stats.completed == 3
            assert fed.local_frames()["r0"]["seq"] >= 3
            assert calls == []
        finally:
            exectime.set_sample_rate(None)


# ---------------------------------------------------------------------------
# surfaces: /fleet/serving, exposition, gauges, flight record
# ---------------------------------------------------------------------------

def _get(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


class TestSurfaces:
    def test_fleet_serving_route_local_mode(self, mon, tmp_path):
        pub = fed.FramePublisher("replica0", str(tmp_path),
                                 min_interval_s=0.0)
        pub.maybe_publish(_StubEngine())
        srv = server.start_server(port=0)
        code, body = _get(f"{srv.url}/fleet/serving")
        assert code == 200
        p = json.loads(body)
        assert p["kind"] == "paddle_tpu.fleet_serving"
        assert p["source"] == "local"
        assert "replica0" in p["frames"]
        assert p["report"]["attribution"][0]["replica"] == "replica0"
        # listed on the root index
        code, body = _get(f"{srv.url}/")
        assert "/fleet/serving" in json.loads(body)["routes"]

    def test_fleet_serving_route_controller_mode_names_burner(
            self, mon):
        view = fed.FleetSLOView(staleness_s=120.0)
        view.ingest("good", _mk_frame("good", burn_fast=0.5,
                                      compliance=1.0, samples=64))
        view.ingest("bad", _mk_frame("bad", burn_fast=40.0,
                                     compliance=0.4, samples=64))
        fed.set_active_view(view)
        srv = server.start_server(port=0)
        code, body = _get(f"{srv.url}/fleet/serving")
        assert code == 200
        p = json.loads(body)
        assert p["source"] == "controller"
        rep = p["report"]
        assert rep["attribution"][0]["replica"] == "bad"
        assert rep["attribution"][0]["alerting"] is True
        assert rep["alerting"] == ["ttft_p99_ms"]
        assert sorted(rep["staleness"]["fresh"]) == ["bad", "good"]

    def test_gauges_and_labeled_exposition(self, mon):
        view = fed.FleetSLOView(staleness_s=120.0)
        hostile = 'evil"\n\\replica'
        view.ingest(hostile, _mk_frame(hostile, burn_fast=20.5,
                                       compliance=0.5, samples=64,
                                       demand=0.7))
        view.fleet_report(poll=False)
        snap = monitor.snapshot()["gauges"]
        assert snap["slo.fleet.replicas_fresh"] == 1
        assert snap["slo.fleet.alerting"] == 1
        assert snap["slo.fleet.demand_estimate"] == \
            pytest.approx(0.7)
        assert snap["slo.fleet.desired_capacity_hint"] == 1
        assert snap["slo.fleet.ttft_p99_ms.burn_fast"] == \
            pytest.approx(20.5)
        text = monitor.expose_text()
        # per-replica attribution series with the PR 7 label escaping:
        # hostile replica names round-trip, never raw bytes
        assert ('slo_fleet_replica_burn_fast{replica='
                '"evil\\"\\n\\\\replica"} 20.5') in text, \
            [ln for ln in text.splitlines()
             if "slo_fleet_replica" in ln]
        assert 'slo_fleet_replica_alerting{replica=' in text

    def test_flight_record_federation_block(self, mon, tmp_path):
        pub = fed.FramePublisher("replica0", str(tmp_path),
                                 min_interval_s=0.0)
        pub.maybe_publish(_StubEngine())
        from paddle_tpu.monitor import trace
        payload = trace.flight_payload(reason="test")
        fd = payload["federation"]
        assert fd is not None
        assert "replica0" in fd["local_frames"]
        assert fd["local_frames"]["replica0"]["seq"] >= 1
        json.dumps(payload)            # crash-dump parseable

    def test_no_frames_no_block_no_exposition(self, mon):
        assert fed.flight_block() is None
        assert fed.exposition_text() == ""
        snap = fed.fleet_serving_snapshot()
        assert snap["frames"] == {} and snap["report"] is None


# ---------------------------------------------------------------------------
# review hardening pins (code-review findings, all applied)
# ---------------------------------------------------------------------------

class TestReviewHardening:
    def test_pre_drain_frame_never_authorizes_stop(self):
        """A fresh frame captured BEFORE begin_drain (draining=False,
        drain_safe=True — the replica was idle, then admitted work)
        must not let _drain_and_stop stop the replica; a frame that
        reflects the drain does."""
        view = fed.FleetSLOView(staleness_s=120.0)
        view.ingest("r", _mk_frame("r", seq=1, draining=False,
                                   drain_safe=True))
        stopped = []
        mgr = AdaptiveElasticManager()
        ok = mgr._drain_and_stop(
            "r", object(), signals=lambda n, h: None,
            drain=lambda n, h: None,
            stop=lambda n, h: stopped.append(n),
            drain_timeout=0.3, poll_interval=0.02, view=view)
        assert not ok and stopped == []       # pre-drain frame ignored
        view.ingest("r", _mk_frame("r", seq=2, draining=True,
                                   drain_safe=True))
        ok = mgr._drain_and_stop(
            "r", object(), signals=lambda n, h: None,
            drain=lambda n, h: None,
            stop=lambda n, h: stopped.append(n),
            drain_timeout=2.0, poll_interval=0.02, view=view)
        assert ok and stopped == ["r"]

    def test_drain_barrier_discards_late_pre_drain_signal(self):
        """A signals() call that wedged before the drain completing
        late must not serve its pre-drain idle payload inside the
        drain wait (the discard_pending barrier)."""
        gate = threading.Event()

        def signals(name, h):
            gate.wait(0.3)
            return {"demand_estimate": 0.0, "drain_safe": True}

        b = _BoundedSignals(signals, timeout=0.05)
        assert b("r", None) is None           # wedged pre-drain
        gate.set()
        time.sleep(0.4)                       # it finished late...
        b.discard_pending("r")                # ...but the drain began
        t0 = time.monotonic()
        got = b("r", None)                    # fresh post-drain call
        assert got == {"demand_estimate": 0.0, "drain_safe": True}
        assert time.monotonic() - t0 < 0.2    # (fresh, not cached)

    def test_publisher_retries_after_transport_failure(self, tmp_path):
        """A configured-but-failing transport must not wait out a long
        rate limit — but the retry sits behind a short backoff, never
        per-step: a dead disk must not turn every scheduler tick into
        transport I/O (the local registry keeps the frame either
        way)."""
        clock = [0.0]
        bad = os.path.join(str(tmp_path), "missing", "x")
        pub = fed.FramePublisher("r0", bad, min_interval_s=10.0,
                                 _time_fn=lambda: clock[0])
        eng = _StubEngine()
        # publish_named makedirs the missing dir, so break it harder:
        # a FILE where the dir should be
        open(os.path.join(str(tmp_path), "missing"), "w").close()
        assert pub.maybe_publish(eng) is not None
        assert fed.local_frames()["r0"]["seq"] == 1
        clock[0] = 0.1                  # inside the failure backoff
        assert pub.maybe_publish(eng) is None       # NOT per-step
        clock[0] = 0.3                  # backoff (0.25s) spent, far
        #                                 inside the 10s rate limit
        assert pub.maybe_publish(eng) is not None   # retried
        # a WORKING local-only publisher (no transport configured)
        # keeps its full rate limit
        ok_pub = fed.FramePublisher("r1", None, min_interval_s=10.0,
                                    _time_fn=lambda: clock[0])
        assert ok_pub.maybe_publish(eng) is not None
        clock[0] = 0.5
        assert ok_pub.maybe_publish(eng) is None

    def test_publisher_env_dir_fallback_failure_arms_retry(
            self, tmp_path, monkeypatch):
        """The fast-retry must key on the transport publish_named
        ACTUALLY uses: a publisher relying on the PADDLE_HEARTBEAT_DIR
        fallback (the launch-CLI worker pattern) whose env dir fails
        deserves the same short backoff as an explicit dir_path — not
        a full rate-limit window of frame gap."""
        broken = os.path.join(str(tmp_path), "asfile")
        open(broken, "w").close()       # a FILE where the dir should be
        monkeypatch.setenv("PADDLE_HEARTBEAT_DIR",
                           os.path.join(broken, "d"))
        clock = [0.0]
        pub = fed.FramePublisher("r0", None, min_interval_s=10.0,
                                 _time_fn=lambda: clock[0])
        eng = _StubEngine()
        assert pub.maybe_publish(eng) is not None   # local frame kept
        clock[0] = 0.1
        assert pub.maybe_publish(eng) is None       # backoff holds
        clock[0] = 0.3                  # backoff spent, far inside the
        #                                 10s rate limit
        assert pub.maybe_publish(eng) is not None   # retried
        # with NO transport anywhere (env cleared), the full rate
        # limit holds — no frame build every backoff for a publisher
        # with nowhere to write
        monkeypatch.delenv("PADDLE_HEARTBEAT_DIR")
        pub2 = fed.FramePublisher("r1", None, min_interval_s=10.0,
                                  _time_fn=lambda: clock[0])
        assert pub2.maybe_publish(eng) is not None
        clock[0] = 0.8
        assert pub2.maybe_publish(eng) is None

    def test_failing_frame_build_backs_off_and_counts(self, mon):
        """A frame build that raises (a raising slo_fn, a malformed
        report) gets the SAME short backoff as a failing transport —
        not a retry on every scheduler step of the decode hot path —
        and is counted, not silent (the frame is the liveness beat, so
        a silently never-publishing replica gets stale-killed with no
        diagnostic). seq is not burned on failed builds."""
        clock = [0.0]
        boom = [True]

        def slo_fn():
            if boom[0]:
                raise RuntimeError("injected")
            return {"objectives": {}, "alerting": []}

        pub = fed.FramePublisher("r0", None, min_interval_s=10.0,
                                 slo_fn=slo_fn,
                                 _time_fn=lambda: clock[0])
        eng = _StubEngine()
        assert pub.maybe_publish(eng) is None
        clock[0] = 0.1
        assert pub.maybe_publish(eng) is None       # backoff holds...
        clock[0] = 0.15
        assert pub.maybe_publish(eng) is None       # ...not per-step
        # exactly ONE build attempt was paid: the two held calls
        # never reached build_frame (that is the backoff working)
        counters = monitor.snapshot()["counters"]
        assert counters["federation.frames.build_errors"] == 1
        clock[0] = 0.3                              # backoff spent
        boom[0] = False                             # build recovers
        frame = pub.maybe_publish(eng)
        assert frame is not None and frame["seq"] == 1  # seq unburned
        assert "r0" in fed.local_frames()

    def test_kv_only_view_never_touches_env_dir(self, tmp_path,
                                                monkeypatch):
        """A KV-only view's file leg must not resolve through the
        PADDLE_HEARTBEAT_DIR fallback (the launcher exports it to
        every worker): sweep must not delete, and poll must not
        ingest, an unrelated fleet's generic replicaN files there."""
        env_dir = str(tmp_path)
        monkeypatch.setenv("PADDLE_HEARTBEAT_DIR", env_dir)
        # an unrelated fleet's beat file + frame in the env dir
        other = _mk_frame("replica0", seq=99, demand=7.0,
                          burn_fast=50.0, compliance=0.1, samples=64)
        hb.touch_named(env_dir, "replica0", other)
        kv = FakeKV()
        view = fed.FleetSLOView(None, client=kv, staleness_s=60.0)
        # poll: nothing on the view's own (KV) transport -> no ingest
        # of the env dir's foreign frame
        assert view.poll(["replica0"]) == 0
        assert view.fresh_frames() == {}
        # sweep: the foreign fleet's beat file survives
        view.sweep("replica0")
        assert os.path.exists(
            os.path.join(env_dir, "replica0.alive"))
        # the view's OWN transport still works both ways
        mine = _mk_frame("replica0", seq=1, demand=0.5,
                         burn_fast=0.0, compliance=1.0, samples=64)
        kv.key_value_set("pt_named/replica0", json.dumps(mine),
                         allow_overwrite=True)
        view._next_read.clear()
        assert view.poll(["replica0"]) == 1
        view.sweep("replica0")
        assert "pt_named/replica0" not in kv.d

    def test_burn_scaling_without_telemetry_records_event(self):
        """FLAGS_serving_fleet_burn_scaling on with NO federation view
        and NO heartbeat_dir to build one over cannot engage — the
        controller must record the misconfiguration once instead of
        silently degrading to demand-only scaling."""
        mgr = AdaptiveElasticManager()
        mgr.run_serving(lambda n: _FakeReplica(demand=0.0),
                        lambda n, h: None,
                        min_replicas=1, max_replicas=2,
                        poll_interval=0.01, fleet_burn_scaling=True,
                        max_ticks=3)
        reasons = [d.get("reason") for _, s, d in mgr.events]
        assert reasons.count("burn-scaling-no-telemetry") == 1
        # with a view passed, the event does not fire
        mgr2 = AdaptiveElasticManager()
        mgr2.run_serving(lambda n: _FakeReplica(demand=0.0),
                         lambda n, h: None,
                         min_replicas=1, max_replicas=2,
                         poll_interval=0.01, fleet_burn_scaling=True,
                         federation=fed.FleetSLOView(staleness_s=1.0),
                         max_ticks=3)
        assert not any(d.get("reason") == "burn-scaling-no-telemetry"
                       for _, s, d in mgr2.events)

    def test_fleet_gauge_names_bounded_to_known_objectives(self, mon):
        """Gauge NAMES are process-global and permanent: objective
        names inside frames are remote input, so slo.fleet.<obj>.*
        gauges are minted only for the slo plane's closed objective
        set — a buggy publisher varying objective names per publish
        must not grow the registry without bound (the tenant-label
        cardinality discipline, applied to metric names)."""
        view = fed.FleetSLOView(staleness_s=120.0)
        for i in range(5):
            view.ingest("r0", _mk_frame(
                "r0", seq=i + 1, burn_fast=20.0, compliance=0.8,
                samples=64, objective=f"req-{i}-ttft"))
            view.fleet_report(["r0"], poll=False)
        snap = monitor.snapshot()["gauges"]
        assert not any("req-" in k for k in snap), sorted(snap)
        # ...while the hostile objectives still ride the bounded
        # report JSON, and canonical objectives still gauge
        rep = fed.last_report()
        assert "req-4-ttft" in rep["objectives"]
        view.ingest("r0", _mk_frame("r0", seq=99, burn_fast=20.0,
                                    compliance=0.8, samples=64))
        view.fleet_report(["r0"], poll=False)
        snap = monitor.snapshot()["gauges"]
        assert "slo.fleet.ttft_p99_ms.burn_fast" in snap

    def test_warn_threshold_shared_with_slo_plane(self, monkeypatch):
        """One threshold governs both planes: federate() reads the
        slo plane's env/default, so a custom PADDLE_TPU_SLO_BURN_WARN
        moves the fleet verdict with the per-replica alerts."""
        frames = {"a": _mk_frame("a", burn_fast=5.0, compliance=0.9,
                                 samples=64)}
        assert fed.federate(frames)["alerting"] == []     # 5 < 14.4
        monkeypatch.setenv("PADDLE_TPU_SLO_BURN_WARN", "4.0")
        assert fed.federate(frames)["alerting"] == ["ttft_p99_ms"]

    def test_drain_retry_ticks_do_not_rearm_the_bound(self):
        """The drain barrier discards ONCE, at commit: a committed
        drain's per-tick retries must not re-spawn a bounded worker
        for a wedged callable and re-block the loop by the full bound
        every tick (the no-thread-stacking guarantee)."""
        frozen = threading.Event()
        calls = []

        def signals(name, h):
            calls.append(name)
            frozen.wait()                     # wedged forever

        b = _BoundedSignals(signals, timeout=0.2)
        mgr = AdaptiveElasticManager()
        kw = dict(signals=b, drain=lambda n, h: None,
                  stop=lambda n, h: None, drain_timeout=0.05,
                  poll_interval=0.01)
        # commit tick: the barrier discards + one bounded call
        assert not mgr._drain_and_stop("r", object(),
                                       discard_stale_signals=True,
                                       **kw)
        n_commit = len(calls)
        # retry ticks (the run_serving checkpoint=False discipline):
        # the pending wedge is honored — skipped instantly, no new
        # worker spawned, tick not re-blocked by the bound
        for _ in range(3):
            t0 = time.monotonic()
            assert not mgr._drain_and_stop(
                "r", object(), discard_stale_signals=False, **kw)
            assert time.monotonic() - t0 < 0.15
        assert len(calls) == n_commit         # no thread stacking
        frozen.set()

    def test_local_only_publisher_touches_no_transport(
            self, tmp_path, monkeypatch):
        """local_only frames must not fall back to a configured
        PADDLE_HEARTBEAT_DIR (the bench publisher's contract: no beat
        files nobody sweeps in a live heartbeat dir)."""
        d = str(tmp_path)
        monkeypatch.setenv("PADDLE_HEARTBEAT_DIR", d)
        pub = fed.FramePublisher("bench-r0", None, local_only=True,
                                 min_interval_s=0.0)
        assert pub.maybe_publish(_StubEngine()) is not None
        assert fed.local_frames()["bench-r0"]["seq"] == 1
        assert not os.path.exists(os.path.join(d, "bench-r0.alive"))
        # without local_only, dir_path=None DOES fall back to the env
        # dir — the heartbeat convention run_serving replicas rely on
        pub2 = fed.FramePublisher("real-r0", None, min_interval_s=0.0)
        assert pub2.maybe_publish(_StubEngine()) is not None
        assert os.path.exists(os.path.join(d, "real-r0.alive"))

    def test_concurrent_publish_serialized_monotonic_seq(
            self, tmp_path, monkeypatch):
        """The replica's step thread and the controller's begin_drain
        force-publish race on one publisher: the publish lock
        serializes whole frames in seq order — the transport never
        sees an out-of-order publish (a lower-seq pre-drain frame
        landing AFTER the forced draining frame would stall the
        drain gate), and the local registry holds the highest seq."""
        published = []

        def slow_publish(name, payload, *, dir_path=None, client=None):
            published.append(payload["seq"])
            time.sleep(0.002)               # widen the race window
            return True

        monkeypatch.setattr(hb, "publish_named", slow_publish)
        pub = fed.FramePublisher("r0", str(tmp_path),
                                 min_interval_s=0.0)
        eng = _StubEngine()

        def burst():
            for _ in range(5):
                pub.maybe_publish(eng, force=True)

        threads = [threading.Thread(target=burst) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(published) == 20
        assert published == sorted(published)    # strictly in order
        assert len(set(published)) == 20         # no duplicate seqs
        assert fed.local_frames()["r0"]["seq"] == max(published)

    def test_malformed_frame_degrades_never_crashes(self):
        """Frame fields are remote input: one publisher emitting
        non-numeric / NaN compliance, burn, samples, or demand must
        contribute nothing (never fabricated) — not crash federation
        (and 500 /fleet/serving) for the whole fleet."""
        good = _mk_frame("good", burn_fast=20.0, compliance=0.8,
                         samples=64, demand=1.5)
        bad = _mk_frame("bad", burn_fast=1.0, compliance=0.9,
                        samples=32, demand=2.0)
        row = bad["slo"]["objectives"]["ttft_p99_ms"]
        row["burn_fast"] = "n/a"
        row["burn_slow"] = float("nan")
        row["compliance"] = [0.9]
        row["samples_slow"] = "many"
        bad["autoscale"]["demand_estimate"] = float("nan")
        bad["requests"]["completed"] = float("inf")
        bad["tenants"] = {"t0": {"completed": "x"}}
        rep = fed.federate({"good": good, "bad": bad})
        obj = rep["objectives"]["ttft_p99_ms"]
        # every fleet value == the good replica alone
        assert obj["burn_fast"] == pytest.approx(20.0)
        assert obj["compliance"] == pytest.approx(0.8)
        assert obj["samples_slow"] == 64     # "many" dropped
        assert obj["replicas_reporting"] == 1
        assert rep["demand"]["demand_estimate_sum"] == \
            pytest.approx(1.5)               # NaN dropped, not summed
        assert rep["demand"]["desired_capacity_hint"] == 2
        assert rep["requests"]["completed"] == 64   # inf dropped
        assert rep["tenants"]["t0"] == {}    # non-numeric dropped
        # attribution: the malformed replica ranks LAST with no data
        assert [a["replica"] for a in rep["attribution"]] == \
            ["good", "bad"]
        assert rep["attribution"][1]["burn_fast"] is None
        assert rep["attribution"][1]["alerting"] is False

    def test_non_dict_sub_blocks_degrade_never_crash(self):
        """The _num leaf discipline extends to SUB-BLOCKS: a truthy
        non-dict slo/objectives/autoscale/requests/tenants block (or
        a string objective row) bypasses the `or {}` guards and must
        degrade like an absent block — never raise through the
        fold."""
        good = _mk_frame("good", burn_fast=20.0, compliance=0.8,
                         samples=64, demand=1.5)
        for block in ({"slo": "x"}, {"autoscale": "oops"},
                      {"requests": "x"}, {"tenants": "x"},
                      {"slo": {"objectives": "x", "alerting": []}},
                      {"slo": {"objectives": {"ttft_p99_ms": "row"},
                               "alerting": []}}):
            bad = _mk_frame("bad", seq=2, demand=0.0)
            bad.update(block)
            rep = fed.federate({"good": good, "bad": bad})
            obj = rep["objectives"]["ttft_p99_ms"]
            assert obj["burn_fast"] == pytest.approx(20.0), block
            assert rep["demand"]["desired_capacity_hint"] == 2, block

    def test_corrupt_kv_seq_and_unprovable_seq(self, tmp_path):
        """A corrupt KV copy carrying a non-numeric seq loses the
        read_named tiebreak (the valid file copy is served — no
        TypeError that would discard BOTH transports and get a
        healthy frame-is-the-beat replica stale-killed), and a frame
        whose seq cannot prove publication order is never ingested
        (a NaN seq would re-stamp freshness every poll)."""
        d = str(tmp_path)
        kv = FakeKV()
        hb.publish_named("r0", _mk_frame("r0", seq=3), dir_path=d)
        kv.d[f"{hb._NAMED_KV_PREFIX}/r0"] = json.dumps(
            {**_mk_frame("r0"), "seq": "5"})
        got = hb.read_named("r0", dir_path=d, client=kv)
        assert got["seq"] == 3               # file copy served
        view = fed.FleetSLOView(staleness_s=120.0)
        assert not view.ingest("r0", {**_mk_frame("r0"), "seq": "x"})
        assert not view.ingest("r0", {**_mk_frame("r0"),
                                      "seq": float("nan")})
        assert view.fresh_frames() == {}

    def test_non_dict_autoscale_in_controller_and_drain_gate(self):
        """A fresh frame whose autoscale block is a truthy non-dict
        contributes nothing to the controller tick (no crash), and a
        draining frame with one falls through to the signals callable
        at the drain gate instead of crashing run_serving."""
        view = fed.FleetSLOView(staleness_s=120.0)
        f = _mk_frame("replica0", seq=1)
        f["autoscale"] = "oops"
        view.ingest("replica0", f)
        mgr = AdaptiveElasticManager()
        out = mgr.run_serving(
            lambda n: _FakeReplica(), lambda n, h: None,
            min_replicas=1, max_replicas=4, poll_interval=0.001,
            federation=view, fleet_burn_scaling=True, max_ticks=20)
        assert out["replicas"] == ["replica0"]   # held steady
        view.ingest("r", _mk_frame("r", seq=1, draining=True))
        view.fresh_frames(["r"])["r"]["autoscale"] = "oops"
        stopped = []
        mgr2 = AdaptiveElasticManager()
        ok = mgr2._drain_and_stop(
            "r", object(), signals=lambda n, h: {"drain_safe": True},
            drain=lambda n, h: None,
            stop=lambda n, h: stopped.append(n),
            drain_timeout=2.0, poll_interval=0.02, view=view)
        assert ok and stopped == ["r"]

    def test_malformed_frame_demand_does_not_crash_controller(self):
        """The controller's own demand fold sits outside the view's
        try/except: a frame whose demand_estimate is a string (or
        NaN — math.ceil(NaN) raises) must contribute nothing, not
        crash run_serving."""
        view = fed.FleetSLOView(staleness_s=120.0)
        f = _mk_frame("replica0", seq=1, demand=1.0)
        f["autoscale"]["demand_estimate"] = "lots"
        view.ingest("replica0", f)
        mgr = AdaptiveElasticManager()
        out = mgr.run_serving(
            lambda n: _FakeReplica(), lambda n, h: None,
            min_replicas=1, max_replicas=4, poll_interval=0.001,
            federation=view, max_ticks=20)
        assert out["replicas"] == ["replica0"]   # held steady

    def test_unconfigured_view_sweep_touches_no_env_transport(
            self, tmp_path, monkeypatch):
        """A transportless (in-process seeded) view's sweep must not
        fall back to PADDLE_HEARTBEAT_DIR / the global KV client and
        delete an unrelated live fleet's generic replicaN beat files
        (the local_only publisher lesson, applied to the sweep)."""
        d = str(tmp_path)
        monkeypatch.setenv("PADDLE_HEARTBEAT_DIR", d)
        hb.touch_named(d, "replica0")        # an unrelated live fleet
        view = fed.FleetSLOView(staleness_s=120.0)
        view.sweep("replica0")
        assert os.path.exists(os.path.join(d, "replica0.alive"))
        # a view WITH its transport configured does sweep it
        view2 = fed.FleetSLOView(d, staleness_s=120.0)
        view2.sweep("replica0")
        assert not os.path.exists(os.path.join(d, "replica0.alive"))

    def test_poll_throttles_transport_reads(self, tmp_path,
                                            monkeypatch):
        """run_serving polls every tick (50ms), but on old jaxlib an
        ABSENT pt_named key costs a blocking ~10ms KV probe per name:
        per-name transport reads are capped at read_interval_s, and a
        name found on neither transport backs off absent_backoff_s —
        both far inside the staleness window, so freshness holds."""
        d = str(tmp_path)
        hb.publish_named("a", _mk_frame("a", seq=1), dir_path=d)
        clock = [0.0]
        reads = []
        real = hb.read_named

        def counting(name, **kw):
            reads.append(name)
            return real(name, **kw)

        monkeypatch.setattr(hb, "read_named", counting)
        view = fed.FleetSLOView(d, staleness_s=120.0,
                                _time_fn=lambda: clock[0])
        assert view.poll(["a", "b"]) == 1        # both read once
        assert reads == ["a", "b"]
        clock[0] = 0.1                           # inside both holds
        view.poll(["a", "b"])
        assert reads == ["a", "b"]               # no new reads
        clock[0] = 0.3                           # past read_interval
        view.poll(["a", "b"])
        assert reads == ["a", "b", "a"]          # absent b held back
        clock[0] = 1.4                           # past absent backoff
        view.poll(["a", "b"])
        assert reads.count("b") == 2
        # forget clears the throttle: a respawned name reads NOW
        view.forget("b")
        view.poll(["b"])
        assert reads.count("b") == 3

    def test_stale_replace_prunes_pending_signals_entry(
            self, tmp_path, monkeypatch):
        """A wedged signals call's pending entry is dropped when its
        replica is stale-replaced: the name is never asked again
        (numbering is monotonic), and the entry would otherwise pin
        the stopped replica's handle for the rest of the run."""
        import paddle_tpu.distributed.fleet.elastic as el
        instances = []
        real = el._BoundedSignals

        class Spy(real):
            def __init__(self, fn, timeout):
                instances.append(self)
                super().__init__(fn, timeout)

        monkeypatch.setattr(el, "_BoundedSignals", Spy)
        d = str(tmp_path)
        frozen = threading.Event()

        def signals(name, h):
            if name == "replica0":
                frozen.wait()                    # wedged forever
            return {"demand_estimate": 0.0, "drain_safe": True}

        replicas, stopped, beat_stops = {}, [], []

        def spawn(name):
            r = _FakeReplica()
            replicas[name] = r
            if name == "replica0":
                hb.touch_named(d, name)          # beats once, dies
            else:
                beat_stops.append(hb.start_named(d, name,
                                                 interval=0.05))
            return r

        mgr = AdaptiveElasticManager(max_restarts=3)
        done = threading.Event()
        th = _run_controller(
            mgr, spawn, lambda n, h: stopped.append(n), done, {},
            signals=signals, signal_timeout=0.1, min_replicas=1,
            max_replicas=2, poll_interval=0.05, heartbeat_dir=d,
            heartbeat_timeout=0.3, max_ticks=100000)
        deadline = time.monotonic() + 10
        while "replica0" not in stopped and time.monotonic() < deadline:
            time.sleep(0.02)
        done.set()
        th.join(timeout=5)
        for ev in beat_stops:
            ev.set()
        assert "replica0" in stopped             # stale-replaced
        assert instances and "replica0" not in instances[0]._pending
        frozen.set()

    def test_slo_report_ttl_cache_bounds_window_scans(self,
                                                      monkeypatch):
        """Frame publication must not push the slo window scan back
        onto the scheduler step at the frame rate (the PR 12
        pull-shaped hardening): the report is TTL-cached."""
        from paddle_tpu.monitor import slo as mon_slo
        calls = []
        real = mon_slo.compliance_report

        def counting():
            calls.append(1)
            return real()

        monkeypatch.setattr(mon_slo, "compliance_report", counting)
        clock = [0.0]
        pub = fed.FramePublisher("r0", None, min_interval_s=0.0,
                                 slo_cache_s=0.5,
                                 _time_fn=lambda: clock[0])
        eng = _StubEngine()
        for i in range(10):                   # 10 publishes inside TTL
            clock[0] = i * 0.01
            pub.maybe_publish(eng, force=True)
        assert len(calls) == 1                # one scan, not ten
        clock[0] = 1.0                        # TTL expired
        pub.maybe_publish(eng, force=True)
        assert len(calls) == 2


# ---------------------------------------------------------------------------
# 2-process launch-CLI federation (KV transport)
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestTwoProcessFederation:
    def test_frames_over_kv_rank0_scrape_names_both(self, tmp_path):
        """The PR 7/8 template: two launch-CLI ranks each publish
        frames over the coordination-service KV store; rank 0
        federates them and serves /fleet/serving — both replicas
        present, the injected burner is attribution line 1."""
        worker = os.path.join(REPO, "tests", "_federation_worker.py")
        log_dir = str(tmp_path / "logs")
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "2", "--log_dir", log_dir, worker],
            capture_output=True, text=True, timeout=420,
            env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO))
        logs = {}
        for rank in range(2):
            p = os.path.join(log_dir, f"workerlog.{rank}")
            logs[rank] = open(p).read() if os.path.exists(p) else ""
        blob = logs[0] + logs[1]
        assert r.returncode == 0, blob[-4000:]
        assert "PUBLISHED rank=0 name=replica0" in blob, blob[-4000:]
        assert "PUBLISHED rank=1 name=replica1" in blob, blob[-4000:]
        assert "FEDERATED rank=0 replicas=replica0,replica1" in blob, \
            blob[-4000:]
        assert "ATTRIBUTION rank=0 line1=replica1" in blob, blob[-4000:]
        assert "SCRAPE rank=0 ok=1 burner=replica1" in blob, \
            blob[-4000:]
