"""Worker for the rank-loss chaos pin (run via the launch CLI, NOT
collected by pytest). After a warm-up gather proves the world is live,
rank 1 kill -9s itself while rank 0 enters the next gather; rank 0 must
surface a typed PeerLostError NAMING rank 1 in wall time far under the
collective deadline (tombstone fast path), then exit through
coordinated_abort (PEER_FAILURE_RC) with the abort marker + flight
record on disk."""
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import paddle_tpu.distributed as dist
from paddle_tpu.distributed import collective as coll


def main():
    deadline_s = float(sys.argv[1]) if len(sys.argv) > 1 else 45.0
    dist.init_parallel_env()
    rank = dist.get_rank()
    out = []
    dist.all_gather_object(out, rank, tag="warm")
    assert out == [0, 1], out
    print(f"WARM_OK rank={rank}", flush=True)

    if rank == 1:
        os.kill(os.getpid(), 9)      # mid-job kill -9: no cleanup at all

    t0 = time.monotonic()
    try:
        dist.all_gather_object([], {"rank": rank}, tag="doomed",
                               timeout_s=deadline_s)
    except coll.PeerLostError as e:
        dt = time.monotonic() - t0
        print(f"PEER_LOST rank={rank} lost={e.lost_ranks} "
              f"dt={dt:.2f}s reasons={e.reasons}", flush=True)
        assert e.lost_ranks == [1], e.lost_ranks
        assert dt < deadline_s / 2, \
            f"tombstone fast path missed: waited {dt:.1f}s"
        coll.coordinated_abort(e)    # exits PEER_FAILURE_RC
    print(f"UNEXPECTED_SURVIVAL rank={rank}", flush=True)


if __name__ == "__main__":
    main()
