"""End-to-end training slice (SURVEY.md §7 build-order milestone 3):
an MLP classifier converging on synthetic data, and a 1-block Llama-style
decoder (embedding, RMSNorm, SDPA attention, SwiGLU, cross-entropy) training
eagerly. Mirrors the reference's model-level convergence tests
(test/legacy_test/test_imperative_mnist.py style: loss must drop)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as opt
from paddle_tpu.io import DataLoader, Dataset


def make_blobs(n=256, d=16, k=4, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(k, d)) * 3
    y = rng.integers(0, k, size=n)
    x = centers[y] + rng.normal(size=(n, d))
    return x.astype(np.float32), y.astype(np.int64)


class BlobDataset(Dataset):
    def __init__(self):
        self.x, self.y = make_blobs()

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


class TestMLPTraining:
    def test_mlp_converges(self):
        paddle.seed(0)
        model = nn.Sequential(
            nn.Linear(16, 64), nn.ReLU(),
            nn.Linear(64, 64), nn.ReLU(),
            nn.Linear(64, 4))
        ce = nn.CrossEntropyLoss()
        o = opt.Adam(learning_rate=1e-2, parameters=model.parameters())
        loader = DataLoader(BlobDataset(), batch_size=64, shuffle=True)

        first, last = None, None
        for epoch in range(5):
            for x, y in loader:
                logits = model(x)
                loss = ce(logits, y)
                loss.backward()
                o.step()
                o.clear_grad()
                if first is None:
                    first = float(loss)
                last = float(loss)
        assert last < first * 0.2, (first, last)

        # accuracy check
        x, y = make_blobs()
        pred = np.argmax(model(paddle.to_tensor(x)).numpy(), -1)
        assert (pred == y).mean() > 0.9


class TinyLlamaBlock(nn.Layer):
    """One Llama decoder block built from framework primitives:
    RMSNorm -> causal SDPA (with RoPE omitted here; full model in
    models/llama.py) -> residual -> RMSNorm -> SwiGLU -> residual."""

    def __init__(self, vocab=97, dim=32, heads=4, ffn=64):
        super().__init__()
        self.dim, self.heads = dim, heads
        self.head_dim = dim // heads
        self.embed = nn.Embedding(vocab, dim)
        self.ln1 = nn.RMSNorm(dim)
        self.wq = nn.Linear(dim, dim, bias_attr=False)
        self.wk = nn.Linear(dim, dim, bias_attr=False)
        self.wv = nn.Linear(dim, dim, bias_attr=False)
        self.wo = nn.Linear(dim, dim, bias_attr=False)
        self.ln2 = nn.RMSNorm(dim)
        self.gate = nn.Linear(dim, ffn, bias_attr=False)
        self.up = nn.Linear(dim, ffn, bias_attr=False)
        self.down = nn.Linear(ffn, dim, bias_attr=False)
        self.ln_f = nn.RMSNorm(dim)
        self.head = nn.Linear(dim, vocab, bias_attr=False)

    def forward(self, ids):
        from paddle_tpu import ops
        x = self.embed(ids)
        b, s = ids.shape[0], ids.shape[1]
        h = self.ln1(x)
        q = ops.reshape(self.wq(h), shape=[b, s, self.heads, self.head_dim])
        k = ops.reshape(self.wk(h), shape=[b, s, self.heads, self.head_dim])
        v = ops.reshape(self.wv(h), shape=[b, s, self.heads, self.head_dim])
        a = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        a = ops.reshape(a, shape=[b, s, self.dim])
        x = x + self.wo(a)
        h = self.ln2(x)
        x = x + self.down(F.silu(self.gate(h)) * self.up(h))
        return self.head(self.ln_f(x))


class TestLlamaBlockTraining:
    @pytest.mark.slow  # tier-1 budget (ISSUE 19 rebalance): convergence run; block_jit_step_matches_eager
    # keeps the block train-step seam fast
    def test_block_memorizes_sequence(self):
        paddle.seed(1)
        vocab = 97
        model = TinyLlamaBlock(vocab=vocab)
        o = opt.AdamW(learning_rate=3e-3,
                      parameters=model.parameters())
        rng = np.random.default_rng(0)
        data = rng.integers(0, vocab, size=(8, 17)).astype(np.int64)
        inp = paddle.to_tensor(data[:, :-1])
        tgt = paddle.to_tensor(data[:, 1:])

        first, last = None, None
        for step in range(60):
            logits = model(inp)
            loss = F.cross_entropy(
                logits.reshape([-1, vocab]), tgt.reshape([-1]))
            loss.backward()
            o.step()
            o.clear_grad()
            if first is None:
                first = float(loss)
            last = float(loss)
        assert last < first * 0.5, (first, last)

    def test_block_jit_step_matches_eager(self):
        """The same eager model code must trace under jax.jit (functional
        mode) — SURVEY.md §7: 'eager + jit step'."""
        import jax
        import jax.numpy as jnp
        paddle.seed(2)
        model = TinyLlamaBlock()
        ids = np.random.default_rng(1).integers(0, 97, size=(2, 9))

        eager_out = model(paddle.to_tensor(ids)).numpy()

        params = {n: p._data for n, p in model.named_parameters()}

        def forward(params, ids):
            for n, p in model.named_parameters():
                p._data = params[n]
            with paddle.no_grad():
                return model(paddle.to_tensor(ids))._data

        jit_out = jax.jit(forward)(params, jnp.asarray(ids))
        np.testing.assert_allclose(eager_out, np.asarray(jit_out),
                                   rtol=2e-4, atol=2e-5)
