"""Mosaic block-shape legality tests.

Interpret mode skips Mosaic's tiling checks, so a kernel can pass every
CPU numeric test and still fail to lower on TPU (BENCH_r02 recorded
exactly that: block (1, 128) over a (128, 2048) LSE array). These tests
pin the legality predicate to the empirically-verified TPU rules so the
dispatcher's `supported()` guard keeps illegal specs off the chip.
"""
import jax.numpy as jnp
import numpy as np

import importlib

fa = importlib.import_module("paddle_tpu.kernels.flash_attention")
rn = importlib.import_module("paddle_tpu.kernels.rms_norm")
from paddle_tpu.kernels.tiling import block_legal, flash_specs_legal


class TestBlockLegal:
    def test_bench_r02_lse_shape_rejected(self):
        # the exact spec that killed BENCH_r02: (1, block_q) over [BH, Sq]
        assert not block_legal((1, 128), (128, 2048), np.float32)

    def test_squeezed_dim_still_counts(self):
        # (None, bq) over [bh, sq] is checked as (1, bq): illegal
        # (verified on TPU v5e — the squeeze does NOT satisfy Mosaic)
        assert not block_legal((None, 128), (128, 2048), np.float32)

    def test_rms_partial_dw_rejected(self):
        # (1, d) over [grid, d] with grid > 1: sublane dim 1 fails
        assert not block_legal((1, 4096), (4, 4096), np.float32)
        # but legal when the block spans the whole array
        assert block_legal((1, 4096), (1, 4096), np.float32)

    def test_trailing_singleton_equal_arm(self):
        # (1, bq, 1) over [bh, sq, 1]: last dim equals array dim -> legal
        assert block_legal((1, 128, 1), (128, 2048, 1), np.float32)
        assert block_legal((128, 1), (1024, 1), np.float32)

    def test_divisible_arm(self):
        assert block_legal((1, 128, 128), (8, 512, 128), np.float32)
        assert block_legal((256, 1024), (2048, 1024), np.float32)

    def test_dtype_sublane(self):
        # bf16 tile is (16, 128): 8 rows not divisible, not equal
        assert not block_legal((8, 128), (64, 256), jnp.bfloat16)
        assert block_legal((16, 128), (64, 256), jnp.bfloat16)

    def test_rank_and_bounds(self):
        assert not block_legal((1, 128), (8, 128, 128))    # rank mismatch
        assert not block_legal((256, 128), (128, 128))     # block > array


class TestSupportedGuards:
    def test_flash_bench_shapes_supported(self):
        # the BENCH llama config must take the fast path
        q = jnp.zeros((4, 2048, 32, 128), jnp.bfloat16)
        kv = jnp.zeros((4, 2048, 8, 128), jnp.bfloat16)
        assert fa.supported(q, kv, kv)
        assert flash_specs_legal(4 * 32, 2048, 2048, 128, 128, 128,
                                 jnp.bfloat16)

    def test_flash_every_emitted_spec_is_legal(self):
        # mirror of the specs _fwd/_bwd construct, checked via block_legal
        bh, sq, sk, d, bq, bk = 128, 2048, 2048, 128, 128, 128
        dt = jnp.bfloat16
        assert block_legal((1, bq, d), (bh, sq, d), dt)       # q/o/do/dq
        assert block_legal((1, bk, d), (bh, sk, d), dt)       # k/v/dk/dv
        assert block_legal((1, bq, 1), (bh, sq, 1), np.float32)  # lse/delta

    def test_rms_block_rows_bounded(self):
        # v5e scoped-vmem OOMs at (256, 4096) blocks; picker must shrink
        br = rn._pick_block_rows(rn.DEFAULT_BLOCK_ROWS, 4096, 4096)
        assert br * 4096 <= rn._MAX_BLOCK_ELEMS
        assert 4096 % br == 0 and br % 8 == 0
        # small d keeps the full default
        assert rn._pick_block_rows(256, 1024, 512) == 256

    def test_rms_emitted_specs_legal(self):
        n, d = 4096, 4096
        br = rn._pick_block_rows(rn.DEFAULT_BLOCK_ROWS, n, d)
        assert block_legal((br, d), (n, d), jnp.bfloat16)
        assert block_legal((br, 1), (n, 1), np.float32)       # rstd
        assert block_legal((1, d), (1, d), np.float32)        # dw out
