"""Optimizer + LR scheduler tests (reference strategy:
test/legacy_test/test_adam_op.py family — compare against NumPy math)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.optimizer import lr as lr_mod


def quad_problem():
    """A single param with known gradient: loss = sum((w - 3)^2)."""
    w = paddle.nn.Linear(1, 1)  # placeholder; we use raw Parameter
    p = paddle.Parameter(paddle.to_tensor(np.zeros(4, np.float32))._data)
    return p


def step_once(optimizer, p):
    loss = paddle.sum((p - 3.0) ** 2)
    loss.backward()
    optimizer.step()
    optimizer.clear_grad()
    return float(loss)


class TestSGD:
    def test_converges(self):
        p = quad_problem()
        o = opt.SGD(learning_rate=0.1, parameters=[p])
        for _ in range(100):
            step_once(o, p)
        np.testing.assert_allclose(p.numpy(), 3 * np.ones(4), atol=1e-3)

    def test_single_step_math(self):
        p = paddle.Parameter(paddle.to_tensor(
            np.array([1.0], np.float32))._data)
        o = opt.SGD(learning_rate=0.5, parameters=[p])
        step_once(o, p)  # grad = 2*(1-3) = -4 -> p = 1 + 2 = 3
        np.testing.assert_allclose(p.numpy(), [3.0], rtol=1e-6)


class TestMomentum:
    def test_velocity_math(self):
        p = paddle.Parameter(paddle.to_tensor(
            np.array([0.0], np.float32))._data)
        o = opt.Momentum(learning_rate=0.1, momentum=0.9, parameters=[p])
        # grad is constant -6 at w=0: v1=-6, p1=0.6
        step_once(o, p)
        np.testing.assert_allclose(p.numpy(), [0.6], rtol=1e-5)
        # v2 = 0.9*(-6) + g2; g2 = 2*(0.6-3) = -4.8 ; v2 = -10.2; p2 = 0.6+1.02
        step_once(o, p)
        np.testing.assert_allclose(p.numpy(), [1.62], rtol=1e-5)


class TestAdam:
    def test_first_step_is_lr_sized(self):
        p = paddle.Parameter(paddle.to_tensor(
            np.array([0.0], np.float32))._data)
        o = opt.Adam(learning_rate=0.01, parameters=[p])
        step_once(o, p)
        # Adam's first step ≈ lr (bias-corrected)
        np.testing.assert_allclose(p.numpy(), [0.01], rtol=1e-3)

    def test_converges(self):
        p = quad_problem()
        o = opt.Adam(learning_rate=0.3, parameters=[p])
        for _ in range(200):
            step_once(o, p)
        np.testing.assert_allclose(p.numpy(), 3 * np.ones(4), atol=1e-2)

    def test_matches_reference_impl(self):
        """Full Adam recurrence vs NumPy for several steps."""
        w0 = np.array([0.5, -1.0], np.float32)
        p = paddle.Parameter(paddle.to_tensor(w0)._data)
        lr, b1, b2, eps = 0.1, 0.9, 0.999, 1e-8
        o = opt.Adam(learning_rate=lr, beta1=b1, beta2=b2, epsilon=eps,
                     parameters=[p])
        w = w0.copy().astype(np.float64)
        m = np.zeros(2)
        v = np.zeros(2)
        for step in range(1, 6):
            g = 2 * (w - 3)
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            mh = m / (1 - b1 ** step)
            vh = v / (1 - b2 ** step)
            w = w - lr * mh / (np.sqrt(vh) + eps)
            step_once(o, p)
        np.testing.assert_allclose(p.numpy(), w, rtol=1e-4)


class TestAdamW:
    def test_decoupled_decay(self):
        # with zero grad path impossible here; compare vs Adam: AdamW shrinks
        w0 = np.array([2.0], np.float32)
        p1 = paddle.Parameter(paddle.to_tensor(w0)._data)
        p2 = paddle.Parameter(paddle.to_tensor(w0)._data)
        a = opt.Adam(learning_rate=0.01, parameters=[p1], weight_decay=None)
        aw = opt.AdamW(learning_rate=0.01, parameters=[p2], weight_decay=0.1)
        step_once(a, p1)
        step_once(aw, p2)
        # AdamW result = Adam result - lr*wd*w
        np.testing.assert_allclose(
            p2.numpy(), p1.numpy() - 0.01 * 0.1 * w0, rtol=1e-5)

    def test_apply_decay_param_fun(self):
        p = paddle.Parameter(paddle.to_tensor(
            np.array([2.0], np.float32))._data, name="bias")
        aw = opt.AdamW(learning_rate=0.01, parameters=[p], weight_decay=0.5,
                       apply_decay_param_fun=lambda n: "bias" not in n)
        p_ref = paddle.Parameter(paddle.to_tensor(
            np.array([2.0], np.float32))._data)
        a = opt.Adam(learning_rate=0.01, parameters=[p_ref])
        step_once(aw, p)
        step_once(a, p_ref)
        np.testing.assert_allclose(p.numpy(), p_ref.numpy(), rtol=1e-6)


class TestOtherOptimizers:
    @pytest.mark.parametrize("cls,kwargs", [
        (opt.Adagrad, {"learning_rate": 0.5}),
        (opt.RMSProp, {"learning_rate": 0.05}),
        (opt.Adamax, {"learning_rate": 0.3}),
        (opt.Lamb, {"learning_rate": 0.1}),
    ])
    def test_converges(self, cls, kwargs):
        p = quad_problem()
        o = cls(parameters=[p], **kwargs)
        for _ in range(300):
            step_once(o, p)
        np.testing.assert_allclose(p.numpy(), 3 * np.ones(4), atol=0.15)


class TestGradClip:
    def test_clip_by_value(self):
        p = paddle.Parameter(paddle.to_tensor(
            np.array([0.0], np.float32))._data)
        o = opt.SGD(learning_rate=1.0, parameters=[p],
                    grad_clip=opt.ClipGradByValue(1.0))
        step_once(o, p)  # raw grad -6, clipped to -1
        np.testing.assert_allclose(p.numpy(), [1.0], rtol=1e-6)

    def test_clip_by_global_norm(self):
        p = paddle.Parameter(paddle.to_tensor(
            np.array([3.0, 4.0], np.float32))._data)
        o = opt.SGD(learning_rate=1.0, parameters=[p],
                    grad_clip=opt.ClipGradByGlobalNorm(1.0))
        loss = paddle.sum(p * paddle.to_tensor(np.array([3.0, 4.0],
                                                        np.float32)))
        loss.backward()
        o.step()
        # grad = [3,4], norm 5 -> scaled to [0.6, 0.8]
        np.testing.assert_allclose(p.numpy(), [3 - 0.6, 4 - 0.8], rtol=1e-5)


class TestLRSchedulers:
    def test_step_decay(self):
        s = lr_mod.StepDecay(learning_rate=1.0, step_size=2, gamma=0.1)
        lrs = []
        for _ in range(5):
            lrs.append(s())
            s.step()
        np.testing.assert_allclose(lrs, [1.0, 1.0, 0.1, 0.1, 0.01], rtol=1e-6)

    def test_multistep(self):
        s = lr_mod.MultiStepDecay(learning_rate=1.0, milestones=[2, 4],
                                  gamma=0.5)
        lrs = [s() for _ in range(1)]
        for _ in range(4):
            s.step()
            lrs.append(s())
        np.testing.assert_allclose(lrs, [1, 1, 0.5, 0.5, 0.25], rtol=1e-6)

    def test_cosine(self):
        s = lr_mod.CosineAnnealingDecay(learning_rate=1.0, T_max=10)
        assert abs(s() - 1.0) < 1e-6
        for _ in range(10):
            s.step()
        assert s() < 1e-6

    def test_warmup_then_constant(self):
        s = lr_mod.LinearWarmup(learning_rate=1.0, warmup_steps=4,
                                start_lr=0.0, end_lr=1.0)
        vals = []
        for _ in range(6):
            vals.append(s())
            s.step()
        np.testing.assert_allclose(vals[:4], [0.0, 0.25, 0.5, 0.75],
                                   rtol=1e-6)
        assert vals[4] == 1.0

    def test_scheduler_with_optimizer(self):
        p = quad_problem()
        sched = lr_mod.StepDecay(learning_rate=0.1, step_size=1, gamma=0.5)
        o = opt.SGD(learning_rate=sched, parameters=[p])
        assert o.get_lr() == 0.1
        sched.step()
        assert o.get_lr() == 0.05

    def test_reduce_on_plateau(self):
        s = lr_mod.ReduceOnPlateau(learning_rate=1.0, patience=1, factor=0.1)
        s.step(metrics=1.0)
        s.step(metrics=1.0)
        s.step(metrics=1.0)  # no improvement for > patience
        assert s() < 1.0

    def test_noam(self):
        s = lr_mod.NoamDecay(d_model=64, warmup_steps=10, learning_rate=1.0)
        vals = []
        for _ in range(20):
            vals.append(s())
            s.step()
        assert np.argmax(vals) in (9, 10)

    def test_state_dict_roundtrip(self):
        s = lr_mod.StepDecay(learning_rate=1.0, step_size=2, gamma=0.1)
        for _ in range(3):
            s.step()
        st = s.state_dict()
        s2 = lr_mod.StepDecay(learning_rate=1.0, step_size=2, gamma=0.1)
        s2.set_state_dict(st)
        assert s2() == s()


class TestOptimizerState:
    def test_state_dict_roundtrip(self):
        p = quad_problem()
        o = opt.Adam(learning_rate=0.1, parameters=[p])
        for _ in range(3):
            step_once(o, p)
        sd = o.state_dict()
        p2 = quad_problem()
        o2 = opt.Adam(learning_rate=0.1, parameters=[p2])
        o2.set_state_dict(sd)
        assert o2._global_step == 3

    def test_minimize(self):
        p = quad_problem()
        o = opt.SGD(learning_rate=0.1, parameters=[p])
        loss = paddle.sum((p - 3.0) ** 2)
        o.minimize(loss)
        assert p.grad is None  # cleared
        assert not np.allclose(p.numpy(), np.zeros(4))


class TestLRParityFixes:
    def test_onecycle_three_phase(self):
        s = lr_mod.OneCycleLR(max_learning_rate=0.1, total_steps=100,
                              three_phase=True, phase_pct=0.3)
        vals = []
        for _ in range(101):
            vals.append(s())
            s.step()
        assert abs(vals[30] - 0.1) < 1e-6          # peak after up phase
        assert abs(vals[60] - 0.1 / 25) < 1e-3     # back to initial_lr
        assert vals[100] <= 2e-4                   # annealed to end_lr

    def test_l1_decay_applies_sign(self):
        p = paddle.Parameter(paddle.to_tensor(
            np.array([2.0, -2.0], np.float32))._data)
        o = opt.SGD(learning_rate=1.0, parameters=[p],
                    weight_decay=opt.L1Decay(0.5))
        loss = paddle.sum(p * 0.0)
        loss.backward()
        o.step()
        np.testing.assert_allclose(p.numpy(), [1.5, -1.5], rtol=1e-6)


def test_linear_lr_warmup_accepts_int_and_rejects_junk():
    import pytest
    from paddle_tpu.optimizer import lr as lr_mod
    s = lr_mod.linear_lr_warmup(1, warmup_steps=4, start_lr=0.0, end_lr=0.5)
    for _ in range(5):
        s.step()
    assert abs(s.get_lr() - 1.0) < 1e-9   # post-warmup base is the int 1
    with pytest.raises(TypeError, match="linear_lr_warmup"):
        lr_mod.linear_lr_warmup(object(), 4, 0.0, 0.5)
