"""bench.py watchdog semantics: a deadline expiring in a LATE optional
stage (the MoE rung) must emit the already-measured headline number,
not zero the run; before any measurement it emits the failure record.
Importing bench must not arm the watchdog or print anything."""
import importlib
import json
import sys


def _fresh_bench(capsys):
    sys.modules.pop("bench", None)
    import bench
    importlib.reload(bench)
    assert capsys.readouterr().out == ""     # import is silent
    return bench


class TestWatchdogFire:
    def test_pre_measurement_fires_failure(self, capsys):
        b = _fresh_bench(capsys)
        b._STAGE["name"] = "init+compile"
        b._watchdog_fire()
        out = capsys.readouterr().out.strip().splitlines()
        assert len(out) == 1
        p = json.loads(out[0])
        assert p["value"] == 0.0
        assert "init+compile" in p["error"]

    def test_post_measurement_emits_partial(self, capsys):
        b = _fresh_bench(capsys)
        b._STAGE["name"] = "moe-rung"
        b._PARTIAL["payload"] = {
            "metric": b._METRIC, "value": 123.4, "unit": "tokens/s",
            "vs_baseline": 0.5, "extra": {"mfu": 0.2}}
        b._watchdog_fire()
        out = capsys.readouterr().out.strip().splitlines()
        assert len(out) == 1
        p = json.loads(out[0])
        assert p["value"] == 123.4                      # not zeroed
        assert "moe-rung" in p["extra"]["late_stage_timeout"]

    def test_emit_is_once_only(self, capsys):
        b = _fresh_bench(capsys)
        b._PARTIAL["payload"] = {"metric": b._METRIC, "value": 1.0,
                                 "unit": "tokens/s", "vs_baseline": 0.0}
        b._watchdog_fire()
        b._watchdog_fire()                              # second is a no-op
        out = capsys.readouterr().out.strip().splitlines()
        assert len(out) == 1
