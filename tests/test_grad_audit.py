"""Finite-difference gradient audit across the eager op surface.

Reference methodology: test/legacy_test/op_test.py:418 — every op's
analytic gradient is checked against a central-difference numerical
gradient on smooth inputs. Here one parametrized harness sweeps a broad
sample of differentiable ops: tape backward vs numerical d(sum(f(x)))/dx.
"""
import numpy as np
import pytest

import paddle_tpu as paddle


def _num_grad(f, x, eps=1e-3):
    """Central difference of sum(f(x)) w.r.t. x (float64 inputs)."""
    g = np.zeros_like(x, dtype=np.float64)
    flat = x.reshape(-1)
    gf = g.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + eps
        hi = float(np.asarray(f(x.astype("float32"))).sum())
        flat[i] = orig - eps
        lo = float(np.asarray(f(x.astype("float32"))).sum())
        flat[i] = orig
        gf[i] = (hi - lo) / (2 * eps)
    return g


def _tape_grad(fn, x_np):
    t = paddle.to_tensor(x_np.astype("float32"), stop_gradient=False)
    out = fn(t)
    out.sum().backward()
    return np.asarray(t.grad.numpy())


# (name, fn, input builder) — inputs chosen inside each op's smooth region
RNG = np.random.default_rng(7)
UNARY_CASES = [
    ("exp", lambda t: paddle.exp(t), lambda: RNG.uniform(-1, 1, (3, 4))),
    ("log", lambda t: paddle.log(t), lambda: RNG.uniform(0.5, 2, (3, 4))),
    ("sqrt", lambda t: paddle.sqrt(t), lambda: RNG.uniform(0.5, 2, (3, 4))),
    ("rsqrt", lambda t: paddle.rsqrt(t), lambda: RNG.uniform(0.5, 2, (3, 4))),
    ("sin", lambda t: paddle.sin(t), lambda: RNG.uniform(-1, 1, (3, 4))),
    ("cos", lambda t: paddle.cos(t), lambda: RNG.uniform(-1, 1, (3, 4))),
    ("tanh", lambda t: paddle.tanh(t), lambda: RNG.uniform(-1, 1, (3, 4))),
    ("sigmoid", lambda t: paddle.nn.functional.sigmoid(t),
     lambda: RNG.uniform(-2, 2, (3, 4))),
    ("erf", lambda t: paddle.erf(t), lambda: RNG.uniform(-1, 1, (3, 4))),
    ("square", lambda t: paddle.square(t),
     lambda: RNG.uniform(-2, 2, (3, 4))),
    ("softplus", lambda t: paddle.nn.functional.softplus(t),
     lambda: RNG.uniform(-2, 2, (3, 4))),
    ("gelu", lambda t: paddle.nn.functional.gelu(t),
     lambda: RNG.uniform(-2, 2, (3, 4))),
    ("silu", lambda t: paddle.nn.functional.silu(t),
     lambda: RNG.uniform(-2, 2, (3, 4))),
    ("elu", lambda t: paddle.nn.functional.elu(t),
     lambda: RNG.uniform(-2, 2, (3, 4))),
    ("mish", lambda t: paddle.nn.functional.mish(t),
     lambda: RNG.uniform(-2, 2, (3, 4))),
    ("softmax", lambda t: paddle.nn.functional.softmax(t),
     lambda: RNG.uniform(-2, 2, (3, 4))),
    ("log_softmax", lambda t: paddle.nn.functional.log_softmax(t),
     lambda: RNG.uniform(-2, 2, (3, 4))),
    ("logsumexp", lambda t: paddle.logsumexp(t, axis=-1),
     lambda: RNG.uniform(-2, 2, (3, 4))),
    ("expm1", lambda t: paddle.expm1(t), lambda: RNG.uniform(-1, 1, (3, 4))),
    ("log1p", lambda t: paddle.log1p(t), lambda: RNG.uniform(0, 2, (3, 4))),
    ("atan", lambda t: paddle.atan(t), lambda: RNG.uniform(-2, 2, (3, 4))),
    ("asinh", lambda t: paddle.asinh(t), lambda: RNG.uniform(-2, 2, (3, 4))),
    ("reciprocal", lambda t: paddle.reciprocal(t),
     lambda: RNG.uniform(0.5, 2, (3, 4))),
    ("logit", lambda t: paddle.logit(t),
     lambda: RNG.uniform(0.2, 0.8, (3, 4))),
    ("lgamma", lambda t: paddle.lgamma(t),
     lambda: RNG.uniform(1.5, 3, (3, 4))),
    ("digamma", lambda t: paddle.digamma(t),
     lambda: RNG.uniform(1.5, 3, (3, 4))),
    ("erfinv", lambda t: paddle.erfinv(t),
     lambda: RNG.uniform(-0.5, 0.5, (3, 4))),
    ("sinc", lambda t: paddle.sinc(t), lambda: RNG.uniform(0.2, 1, (3, 4))),
    ("i0", lambda t: paddle.i0(t), lambda: RNG.uniform(0.2, 2, (3, 4))),
    ("mean", lambda t: paddle.mean(t, axis=-1),
     lambda: RNG.uniform(-1, 1, (3, 4))),
    ("sum_sq", lambda t: (t * t).sum(axis=0),
     lambda: RNG.uniform(-1, 1, (3, 4))),
    ("prod", lambda t: paddle.prod(t, axis=-1),
     lambda: RNG.uniform(0.5, 1.5, (3, 4))),
    ("norm", lambda t: paddle.linalg.norm(t),
     lambda: RNG.uniform(0.5, 1.5, (3, 4))),
    ("cumsum", lambda t: paddle.cumsum(t, axis=1),
     lambda: RNG.uniform(-1, 1, (3, 4))),
    ("cumprod", lambda t: paddle.cumprod(t, dim=1),
     lambda: RNG.uniform(0.5, 1.5, (3, 4))),
    ("matmul_self", lambda t: paddle.matmul(t, t.transpose([1, 0])),
     lambda: RNG.uniform(-1, 1, (3, 4))),
    ("reshape_mul", lambda t: (t.reshape([4, 3]) * 2.0),
     lambda: RNG.uniform(-1, 1, (3, 4))),
    ("pad", lambda t: paddle.nn.functional.pad(t, [1, 1, 1, 1]),
     lambda: RNG.uniform(-1, 1, (3, 4))),
    ("clip_smooth", lambda t: paddle.clip(t, min=-0.5, max=0.5),
     lambda: RNG.uniform(-0.4, 0.4, (3, 4))),   # inside the linear region
    ("stanh", lambda t: paddle.stanh(t),
     lambda: RNG.uniform(-1, 1, (3, 4))),
    ("swish", lambda t: paddle.nn.functional.swish(t),
     lambda: RNG.uniform(-2, 2, (3, 4))),
    ("kron_self", lambda t: paddle.kron(t, t),
     lambda: RNG.uniform(-1, 1, (2, 2))),
]


@pytest.mark.parametrize("name,fn,mk", UNARY_CASES,
                         ids=[c[0] for c in UNARY_CASES])
def test_grad_matches_finite_difference(name, fn, mk):
    x = mk().astype(np.float64)
    analytic = _tape_grad(fn, x)

    def f(arr):
        return fn(paddle.to_tensor(arr)).numpy()

    numerical = _num_grad(f, x.copy())
    np.testing.assert_allclose(analytic, numerical, rtol=2e-2, atol=2e-3,
                               err_msg=f"gradient mismatch for {name}")


def test_binary_grads():
    rng = np.random.default_rng(11)
    a = rng.uniform(0.5, 1.5, (3, 4)).astype(np.float64)
    b = rng.uniform(0.5, 1.5, (3, 4)).astype(np.float64)
    cases = [
        ("divide", lambda x, y: paddle.divide(x, y)),
        ("pow", lambda x, y: paddle.pow(x, y)),
        ("maximum_sm", lambda x, y: paddle.maximum(x, y * 0.5)),
        ("atan2", lambda x, y: paddle.atan2(x, y)),
        ("logaddexp", lambda x, y: paddle.logaddexp(x, y)),
        ("hypot", lambda x, y: paddle.hypot(x, y)),
    ]
    for name, fn in cases:
        ta = paddle.to_tensor(a.astype("float32"), stop_gradient=False)
        tb = paddle.to_tensor(b.astype("float32"), stop_gradient=False)
        fn(ta, tb).sum().backward()
        ga = np.asarray(ta.grad.numpy())

        def f_a(arr):
            return fn(paddle.to_tensor(arr.astype("float32")),
                      paddle.to_tensor(b.astype("float32"))).numpy()

        num = _num_grad(lambda arr: f_a(arr), a.copy())
        np.testing.assert_allclose(ga, num, rtol=2e-2, atol=2e-3,
                                   err_msg=f"d/da mismatch for {name}")


def _fd_check(fn, x, rtol=3e-2, atol=3e-3, eps=1e-3):
    analytic = _tape_grad(fn, x.astype(np.float64))

    def f(arr):
        return fn(paddle.to_tensor(arr.astype("float32"))).numpy()

    numerical = _num_grad(f, x.astype(np.float64).copy(), eps=eps)
    np.testing.assert_allclose(analytic, numerical, rtol=rtol, atol=atol)


class TestComplexOpGrads:
    """Finite-difference checks for the structurally complex ops added in
    round 3 (scan-based losses, window gathers, samplers)."""

    @pytest.mark.slow  # tier-1 budget (ISSUE 3): heavy; run in the slow lane
    def test_ctc_loss_grad(self):
        import paddle_tpu.nn.functional as F

        rng = np.random.default_rng(0)
        labels = paddle.to_tensor(rng.integers(1, 4, (2, 2)).astype("int32"))
        il = paddle.to_tensor(np.array([5, 4], "int32"))
        ll = paddle.to_tensor(np.array([2, 1], "int32"))

        def fn(t):
            return F.ctc_loss(t, labels, il, ll, blank=0, reduction="sum")

        _fd_check(fn, rng.normal(size=(5, 2, 4)), rtol=5e-2, atol=5e-3)

    @pytest.mark.slow  # tier-1 budget (ISSUE 3): heavy; run in the slow lane
    def test_rnnt_loss_grad(self):
        import paddle_tpu.nn.functional as F

        rng = np.random.default_rng(1)
        label = paddle.to_tensor(rng.integers(1, 3, (1, 2)).astype("int32"))
        il = paddle.to_tensor(np.array([3], "int32"))
        ll = paddle.to_tensor(np.array([2], "int32"))

        def fn(t):
            return F.rnnt_loss(t, label, il, ll, blank=0, reduction="sum")

        _fd_check(fn, rng.normal(size=(1, 3, 3, 3)), rtol=5e-2, atol=5e-3)

    def test_hsigmoid_grad(self):
        import paddle_tpu.nn.functional as F

        rng = np.random.default_rng(2)
        label = paddle.to_tensor(rng.integers(0, 6, (3,)).astype("int64"))
        w = paddle.to_tensor(rng.normal(size=(5, 4)).astype("float32"))

        def fn(t):
            return F.hsigmoid_loss(t, label, 6, w).sum()

        _fd_check(fn, rng.normal(size=(3, 4)))

    def test_multi_margin_grad(self):
        import paddle_tpu.nn.functional as F

        rng = np.random.default_rng(3)
        label = paddle.to_tensor(rng.integers(0, 4, (3,)).astype("int64"))

        def fn(t):
            return F.multi_margin_loss(t, label, p=2, reduction="sum")

        _fd_check(fn, rng.normal(size=(3, 4)))

    def test_fractional_pool_grad(self):
        import paddle_tpu.nn.functional as F

        rng = np.random.default_rng(4)

        def fn(t):
            return F.fractional_max_pool2d(t, 2, random_u=0.4).sum()

        # distinct values so the argmax is fd-stable
        x = rng.permutation(36).reshape(1, 1, 6, 6).astype(np.float64)
        _fd_check(fn, x, eps=1e-2)

    def test_max_pool_mask_path_grad(self):
        import paddle_tpu.nn.functional as F

        rng = np.random.default_rng(5)

        def fn(t):
            out, _ = F.max_pool2d(t, 2, 2, return_mask=True)
            return out.sum()

        x = rng.permutation(16).reshape(1, 1, 4, 4).astype(np.float64)
        _fd_check(fn, x, eps=1e-2)

    def test_grid_sample_grad(self):
        import paddle_tpu.nn.functional as F

        rng = np.random.default_rng(6)
        grid = paddle.to_tensor(
            (rng.uniform(-0.8, 0.8, (1, 3, 3, 2))).astype("float32"))

        def fn(t):
            return F.grid_sample(t, grid).sum()

        _fd_check(fn, rng.normal(size=(1, 2, 4, 4)))

    def test_fused_mha_input_grad(self):
        from paddle_tpu.incubate.nn.functional import \
            fused_multi_head_attention

        rng = np.random.default_rng(7)
        qkvw = paddle.to_tensor(
            (rng.normal(size=(3, 2, 4, 8)) * 0.2).astype("float32"))
        lw = paddle.to_tensor(
            (rng.normal(size=(8, 8)) * 0.2).astype("float32"))
        lns = paddle.to_tensor(np.ones(8, "float32"))
        lnb = paddle.to_tensor(np.zeros(8, "float32"))

        def fn(t):
            return fused_multi_head_attention(
                t, qkvw, lw, ln_scale=lns, ln_bias=lnb,
                dropout_rate=0.0, attn_dropout_rate=0.0,
                training=False).sum()

        _fd_check(fn, rng.normal(size=(1, 3, 8)) * 0.5, rtol=5e-2,
                  atol=5e-3)
