"""Worker for the multi-process COMPILED-collective training test (run
via the launch CLI, not collected by pytest).

Reference pattern: test/legacy_test/test_collective_api_base.py:113 — the
core multi-rank check is a real train step whose gradient reduction
crosses process boundaries, compared against single-process math. Here:
each of W processes hosts 2 virtual CPU devices; a 2W-device ("dp",)
mesh spans all of them; one jitted SGD step on dp-sharded data makes XLA
emit a cross-process all-reduce for the gradient (SPMD over gloo, not
host-side object exchange). Every rank recomputes the same training
single-process and asserts parity.
"""
import os
import sys

# 2 local virtual CPU devices per process -> 2*world global devices
# across the cluster. Must be set before jax import; strip any inherited
# device-count flag (e.g. conftest's =8) rather than relying on
# last-occurrence-wins parsing.
_flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
          if "xla_force_host_platform_device_count" not in f]
_flags.append("--xla_force_host_platform_device_count=2")
os.environ["XLA_FLAGS"] = " ".join(_flags)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu.distributed as dist

STEPS = 5
LR = 0.1
N, D = 16, 4        # 16 rows: 4 per device across 4 devices


def _data():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(N, D)).astype(np.float32)
    w_true = rng.normal(size=(D,)).astype(np.float32)
    Y = X @ w_true
    return X, Y


def _step_fn(w, x, y):
    def loss_fn(w):
        return jnp.mean((x @ w - y) ** 2)

    loss, g = jax.value_and_grad(loss_fn)(w)
    return w - LR * g, loss


def main():
    dist.init_parallel_env()
    rank = dist.get_rank()
    world = dist.get_world_size()
    assert jax.process_count() == world, "jax.distributed did not initialize"
    devs = jax.devices()
    assert len(devs) == 2 * world, \
        f"expected {2 * world} global devices, got {len(devs)}"

    mesh = Mesh(np.array(devs), ("dp",))
    repl = NamedSharding(mesh, P())
    row = NamedSharding(mesh, P("dp"))

    X, Y = _data()
    # each process feeds only ITS rows; the global array spans all procs
    lo, hi = rank * (N // world), (rank + 1) * (N // world)
    gx = jax.make_array_from_process_local_data(row, X[lo:hi])
    gy = jax.make_array_from_process_local_data(row, Y[lo:hi])

    jitted = jax.jit(_step_fn, in_shardings=(repl, row, row),
                     out_shardings=(repl, repl))
    w = jax.device_put(jnp.zeros((D,), jnp.float32), repl)
    # AOT-compile ONCE; the loop reuses the same executable and the HLO
    # check reads its text (no second trace/compile)
    step = jitted.lower(w, gx, gy).compile()
    hlo = step.as_text()
    assert "all-reduce" in hlo or "reduce-scatter" in hlo, \
        "no cross-device reduction in the compiled train step"
    losses = []
    for _ in range(STEPS):
        w, loss = step(w, gx, gy)
        losses.append(float(loss))   # cross-process fetch = sync

    assert losses[-1] < losses[0] * 0.5, losses

    # single-process oracle: identical math on the full batch
    wref = jnp.zeros((D,), jnp.float32)
    for _ in range(STEPS):
        wref, _ = _step_fn(wref, jnp.asarray(X), jnp.asarray(Y))
    np.testing.assert_allclose(np.asarray(w), np.asarray(wref),
                               rtol=1e-5, atol=1e-6)

    dist.barrier()
    print(f"DIST_TRAIN_OK rank={rank} loss0={losses[0]:.4f} "
          f"lossN={losses[-1]:.4f}", flush=True)


if __name__ == "__main__":
    main()
